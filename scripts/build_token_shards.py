"""Tokenize a text corpus into a memmappable shard directory.

The offline half of the streaming data pipeline
(`shallowspeed_tpu/data/token_shards.py`): runs the tokenizer ONCE,
writes `shard_*.bin` + `index.json` (+ `val.bin` held-out tail,
+ `tokenizer.json` in BPE mode), and from then on training streams
windows off disk instead of re-reading/re-encoding `--text` whole into
RAM every run.

Usage:
    python scripts/build_token_shards.py --text corpus.txt --out shards/
        [--tokenizer bpe --vocab-size 8192] [--val-fraction 0.1]
        [--shard-mb 32]
Prints one JSON line describing what was written.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--text", required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--tokenizer", choices=["bytes", "bpe"],
                    default="bytes")
    ap.add_argument("--vocab-size", type=int, default=8192,
                    help="BPE target vocab (ignored for bytes)")
    ap.add_argument("--val-fraction", type=float, default=0.1)
    ap.add_argument("--shard-mb", type=int, default=32,
                    help="approximate shard size in MB of token ids")
    args = ap.parse_args()

    from shallowspeed_tpu.data.token_shards import build_shards

    raw = open(args.text, "rb").read()
    if args.tokenizer == "bpe":
        from shallowspeed_tpu.data.tokenizer import train_bpe

        # train the merges on the TRAIN portion only (the val tail must
        # not influence the vocabulary)
        n_val = int(len(raw) * args.val_fraction)
        tok = train_bpe(raw[:len(raw) - n_val or None], args.vocab_size)
        ids = tok.encode(raw)
        vocab = tok.vocab_size
        Path(args.out).mkdir(parents=True, exist_ok=True)
        tok.save(Path(args.out) / "tokenizer.json")
    else:
        ids = np.frombuffer(raw, np.uint8).astype(np.int32)
        vocab = 256
    itemsize = 2 if vocab <= (1 << 16) else 4
    shard_tokens = max(args.shard_mb * (1 << 20) // itemsize, 1024)
    out = build_shards(np.asarray(ids), args.out, vocab,
                       shard_tokens=shard_tokens,
                       val_fraction=args.val_fraction,
                       meta={"source": args.text,
                             "tokenizer": args.tokenizer})
    idx = json.loads((out / "index.json").read_text())
    print(json.dumps({
        "out": str(out), "vocab": vocab,
        "shards": len(idx["shard_tokens"]),
        "train_tokens": int(sum(idx["shard_tokens"])),
        "val_tokens": idx["val_tokens"],
        "tokenizer": args.tokenizer}))


if __name__ == "__main__":
    main()
