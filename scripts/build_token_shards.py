"""Tokenize a text corpus into a memmappable shard directory.

The offline half of the streaming data pipeline
(`shallowspeed_tpu/data/token_shards.py`): runs the tokenizer ONCE,
writes `shard_*.bin` + `index.json` (+ `val.bin` held-out tail,
+ `tokenizer.json` in BPE mode), and from then on training streams
windows off disk instead of re-reading/re-encoding `--text` whole into
RAM every run.

Usage:
    python scripts/build_token_shards.py --text corpus.txt --out shards/
        [--tokenizer bpe --vocab-size 8192] [--val-fraction 0.1]
        [--shard-mb 32]
Prints one JSON line describing what was written.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--text", required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--tokenizer", choices=["bytes", "bpe"],
                    default="bytes")
    ap.add_argument("--vocab-size", type=int, default=8192,
                    help="BPE target vocab (ignored for bytes)")
    ap.add_argument("--val-fraction", type=float, default=0.1)
    ap.add_argument("--shard-mb", type=int, default=32,
                    help="approximate shard size in MB of token ids")
    args = ap.parse_args()

    from shallowspeed_tpu.data.token_shards import build_shards

    raw = open(args.text, "rb").read()
    assert 0.0 <= args.val_fraction < 1.0, args.val_fraction
    if args.tokenizer == "bpe":
        from shallowspeed_tpu.data.tokenizer import train_bpe

        # split the BYTES once, then train the merges and encode each
        # side separately — the val tail never influences the
        # vocabulary, and the train/val boundary is exactly the byte
        # boundary (a token-fraction split after encoding can disagree
        # with the byte split when the tail compresses differently)
        n_val_bytes = int(len(raw) * args.val_fraction)
        head = raw[:len(raw) - n_val_bytes] if n_val_bytes else raw
        assert len(head) > 0, "val_fraction leaves no training bytes"
        tok = train_bpe(head, args.vocab_size)
        ids = tok.encode(head)
        val_ids = (tok.encode(raw[len(head):]) if n_val_bytes
                   else None)
        vocab = tok.vocab_size
        Path(args.out).mkdir(parents=True, exist_ok=True)
        tok.save(Path(args.out) / "tokenizer.json")
        itemsize = 2 if vocab <= (1 << 16) else 4
        out = build_shards(
            np.asarray(ids), args.out, vocab,
            shard_tokens=max(args.shard_mb * (1 << 20) // itemsize,
                             1024),
            val=val_ids,
            meta={"source": args.text, "tokenizer": args.tokenizer})
    else:
        ids = np.frombuffer(raw, np.uint8).astype(np.int32)
        vocab = 256
        out = build_shards(
            np.asarray(ids), args.out, vocab,
            shard_tokens=max(args.shard_mb * (1 << 20) // 2, 1024),
            val_fraction=args.val_fraction,
            meta={"source": args.text, "tokenizer": args.tokenizer})
    idx = json.loads((out / "index.json").read_text())
    print(json.dumps({
        "out": str(out), "vocab": vocab,
        "shards": len(idx["shard_tokens"]),
        "train_tokens": int(sum(idx["shard_tokens"])),
        "val_tokens": idx["val_tokens"],
        "tokenizer": args.tokenizer}))


if __name__ == "__main__":
    main()
