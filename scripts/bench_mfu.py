"""MFU benchmark: transformer-LM training at MXU-saturating scale.

The reference's perf story is wall-clock prints (`/root/reference/
train.py:131-137`); the TPU bar is fraction-of-peak. This script trains a
saturating config (d_model >= 1024, seq >= 2048, bf16 + flash attention)
for a fixed number of steady-state steps and reports achieved TFLOP/s and
MFU against the detected chip peak (`shallowspeed_tpu/flops.py`).

Usage: python scripts/bench_mfu.py [--d-model 1024 --n-layers 8 ...]
Prints one JSON line per config.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402


def run(args) -> dict:
    import jax
    from jax.sharding import Mesh

    from shallowspeed_tpu.flops import mfu, transformer_flops_per_token
    from shallowspeed_tpu.models.transformer import TransformerConfig
    from shallowspeed_tpu.optim import Adafactor, AdamW
    from shallowspeed_tpu.parallel.context import ContextParallelEngine

    cfg = TransformerConfig(
        vocab=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        n_layers=args.n_layers, max_seq=args.seq_len,
        dtype=np.float32, compute_dtype=np.dtype("bfloat16"),
        rope=True, norm="rmsnorm", ffn=args.ffn, remat=args.remat,
        remat_policy=args.remat_policy, xent_chunk=args.xent_chunk)
    opt = (Adafactor(3e-4) if args.optimizer == "adafactor"
           else AdamW(3e-4))
    devs = np.array(jax.devices()[:1])
    mesh = Mesh(devs.reshape(1, 1), ("dp", "sp"))
    eng = ContextParallelEngine(cfg, opt, mesh, seed=0,
                                attn=args.attn, accum=args.accum)

    rng = np.random.default_rng(0)
    toks = rng.integers(0, args.vocab,
                        (args.batch_size, args.seq_len)).astype(np.int32)
    tgts = np.roll(toks, -1, axis=1)

    # steady state: the whole S-step run is ONE XLA dispatch (train_run's
    # lax.scan), so per-dispatch tunnel latency cannot pollute the timing
    stack_t = np.broadcast_to(toks, (args.steps, *toks.shape)).copy()
    stack_g = np.broadcast_to(tgts, (args.steps, *tgts.shape)).copy()
    jax.device_get(eng.train_run(stack_t, stack_g))  # compile (excluded)
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        losses = eng.train_run(stack_t, stack_g)
        jax.device_get(losses)  # drain the tunneled async queue for real
        dt = time.perf_counter() - t0
        best = max(best, args.steps * args.batch_size * args.seq_len / dt)

    stats = mfu(best, cfg, args.seq_len, dtype="bf16")
    return {
        "metric": "transformer_train_mfu",
        "config": {
            "d_model": args.d_model, "n_layers": args.n_layers,
            "n_heads": args.n_heads, "seq_len": args.seq_len,
            "batch": args.batch_size, "vocab": args.vocab,
            "ffn": args.ffn, "attn": args.attn, "remat": args.remat,
            "remat_policy": args.remat_policy,
            "xent_chunk": args.xent_chunk, "accum": args.accum,
            "optimizer": args.optimizer,
            "params_m": round(sum(
                x.size for x in jax.tree_util.tree_leaves(eng.params))
                / 1e6, 1),
        },
        "tokens_per_sec": round(best, 0),
        "flops_per_token": round(
            transformer_flops_per_token(cfg, args.seq_len)),
        "tflops": round(stats["tflops"], 1),
        "peak_tflops": stats["peak_tflops"],
        "mfu": None if stats["mfu"] is None else round(stats["mfu"], 4),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--d-model", type=int, default=1024)
    ap.add_argument("--n-heads", type=int, default=16)
    ap.add_argument("--n-layers", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=2048)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--ffn", default="swiglu", choices=["gelu", "swiglu"])
    ap.add_argument("--attn", default="flash",
                    choices=["flash", "ring", "ring-flash", "ulysses",
                             "ulysses-flash"])
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--remat", action="store_true")
    ap.add_argument("--remat-policy", default="full",
                    choices=["full", "attn", "dots"])
    ap.add_argument("--xent-chunk", type=int, default=0)
    ap.add_argument("--accum", type=int, default=1)
    ap.add_argument("--optimizer", default="adamw",
                    choices=["adamw", "adafactor"])
    args = ap.parse_args()
    print(json.dumps(run(args)))


if __name__ == "__main__":
    main()
