"""Microbenchmark: Pallas flash attention vs XLA attention on the real chip.

Reproduces the BASELINE.md "flash-attention kernel vs XLA attention" table:
device-resident (B, T, H, D) inputs, forward and forward+backward timings,
best of `--reps` timed runs after a compile warmup, synced via device_get
(block_until_ready does not drain the tunneled backend's async queue).

    python scripts/bench_attention.py [--seqs 2048 8192] [--batch 2]
        [--heads 8] [--head-dim 64] [--dtype bf16|f32] [--reps 5]

Prints one line per (T, pass) with both times and the speedup.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # runnable from any cwd without install


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--seqs", type=int, nargs="+", default=[2048, 8192])
    p.add_argument("--batch", type=int, default=2)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--head-dim", type=int, default=64)
    p.add_argument("--dtype", default="bf16", choices=["f32", "bf16"],
                   help="bf16 is the BASELINE.md table's dtype (and the "
                        "realistic training dtype)")
    p.add_argument("--reps", type=int, default=5)
    return p.parse_args()


def main():
    args = parse_args()
    import jax
    import jax.numpy as jnp

    from shallowspeed_tpu.ops.attention import attention
    from shallowspeed_tpu.ops.flash_attention import flash_attention

    dt = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    rng = np.random.default_rng(0)

    def sync_cost() -> float:
        """One device_get round-trip through the tunnel (~tens of ms) —
        measured so it can be subtracted from the timed runs instead of
        being amortized into short-T per-call times."""
        z = jax.device_put(jnp.zeros(()))
        jax.device_get(z)
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            jax.device_get(z)
            best = min(best, time.perf_counter() - t0)
        return best

    sync_s = sync_cost()

    def timed(fn, *xs, iters=20) -> float:
        """Per-call seconds over `iters` queued dispatches per timed run,
        with the single end-of-run sync round-trip subtracted."""
        fn(*xs)  # compile warmup
        jax.device_get(jnp.zeros(()))
        best = float("inf")
        for _ in range(args.reps):
            t0 = time.perf_counter()
            out = None
            for _ in range(iters):
                out = fn(*xs)
            jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
            elapsed = max(time.perf_counter() - t0 - sync_s, 1e-9)
            best = min(best, elapsed / iters)
        return best

    for t in args.seqs:
        shape = (args.batch, t, args.heads, args.head_dim)
        q, k, v = (jnp.asarray(rng.normal(size=shape), dt) for _ in range(3))

        xla_fwd = jax.jit(lambda q, k, v: attention(q, k, v, causal=True))
        fla_fwd = jax.jit(
            lambda q, k, v: flash_attention(q, k, v, causal=True))

        def loss(fn):
            return lambda q, k, v: (
                fn(q, k, v).astype(jnp.float32) ** 2).sum()

        xla_bwd = jax.jit(jax.grad(loss(
            lambda q, k, v: attention(q, k, v, causal=True)),
            argnums=(0, 1, 2)))
        fla_bwd = jax.jit(jax.grad(loss(
            lambda q, k, v: flash_attention(q, k, v, causal=True)),
            argnums=(0, 1, 2)))

        for name, ref_fn, fl_fn in (("fwd", xla_fwd, fla_fwd),
                                    ("fwd+bwd", xla_bwd, fla_bwd)):
            tx = timed(ref_fn, q, k, v)
            tf = timed(fl_fn, q, k, v)
            print(f"T={t:6d} {name:8s} xla {tx * 1e3:8.2f} ms   "
                  f"flash {tf * 1e3:8.2f} ms   speedup {tx / tf:5.2f}x")


if __name__ == "__main__":
    main()
