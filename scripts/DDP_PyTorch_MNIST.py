"""Self-contained PyTorch DP baseline — the cross-framework reference check.

Role parity with `/root/reference/scripts/DDP_PyTorch_MNIST.py:23-167`: an
*independent* implementation (PyTorch, not this framework) of data-parallel
MNIST-MLP training whose result is compared against serial training by
absolute weight divergence — the strongest equivalence check in the reference
repo (`DDP_PyTorch_MNIST.py:159-167`).

Differences, by design:
- No mpi4py on this host: DP ranks are simulated in-process. Each rank holds
  a model replica and computes grads on its strided batch shard; grads are
  then all-reduce-averaged across ranks (the explicit equivalent of the
  reference's blocking per-param `Allreduce` + loss/comm.size rescale,
  `DDP_PyTorch_MNIST.py:113,119-122`) and every replica takes the same Adam
  step. Replicas staying bit-identical is asserted every epoch (the
  reference's end-of-run sync check).
- The dataset is the framework's prepared MNIST (synthetic fallback
  offline), so the numbers are comparable with `train.py` runs.

Usage: python scripts/DDP_PyTorch_MNIST.py [--ranks 4] [--epochs 5]
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

import numpy as np
import torch

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from shallowspeed_tpu.data.mnist import ensure_mnist  # noqa: E402

torch.set_num_threads(1)  # reference `DDP_PyTorch_MNIST.py:18`


class MLP(torch.nn.Module):
    """Reference topology 784→64→64→10 (`DDP_PyTorch_MNIST.py:23-33`)."""

    def __init__(self):
        super().__init__()
        torch.manual_seed(0)
        self.net = torch.nn.Sequential(
            torch.nn.Linear(784, 64), torch.nn.ReLU(),
            torch.nn.Linear(64, 64), torch.nn.ReLU(),
            torch.nn.Linear(64, 10),
        )

    def forward(self, x):
        return self.net(x)


def load_data(data_dir):
    x_tr = np.load(data_dir / "x_train.npy")
    y_tr = np.load(data_dir / "y_train.npy").argmax(1)
    x_va = np.load(data_dir / "x_val.npy")
    y_va = np.load(data_dir / "y_val.npy").argmax(1)
    return (torch.from_numpy(x_tr), torch.from_numpy(y_tr),
            torch.from_numpy(x_va), torch.from_numpy(y_va))


def accuracy(model, x, y):
    with torch.no_grad():
        return (model(x).argmax(1) == y).float().mean().item()


def train_serial(x, y, epochs, gbs, lr):
    model = MLP()
    opt = torch.optim.Adam(model.parameters(), lr=lr)
    loss_fn = torch.nn.CrossEntropyLoss()
    n = len(x) - len(x) % gbs
    for _ in range(epochs):
        for b in range(n // gbs):
            xb, yb = x[b * gbs:(b + 1) * gbs], y[b * gbs:(b + 1) * gbs]
            opt.zero_grad()
            loss_fn(model(xb), yb).backward()
            opt.step()
    return model


def train_ddp(x, y, epochs, gbs, lr, ranks):
    """R replicas, strided shards, grad all-reduce-mean each step."""
    replicas = [MLP() for _ in range(ranks)]
    # identical init (manual_seed in __init__) — assert anyway
    for r in replicas[1:]:
        for p0, pr in zip(replicas[0].parameters(), r.parameters()):
            assert torch.equal(p0, pr)
    opts = [torch.optim.Adam(m.parameters(), lr=lr) for m in replicas]
    loss_fn = torch.nn.CrossEntropyLoss()
    n = len(x) - len(x) % gbs
    local = gbs // ranks
    for _ in range(epochs):
        for b in range(n // gbs):
            xb, yb = x[b * gbs:(b + 1) * gbs], y[b * gbs:(b + 1) * gbs]
            for r, (m, o) in enumerate(zip(replicas, opts)):
                o.zero_grad()
                # strided shard, like the framework's Dataset (`dataset.py:54-58`)
                xs, ys = xb[r::ranks], yb[r::ranks]
                assert len(xs) == local
                # loss rescale by 1/ranks + SUM allreduce == mean of the
                # global batch (`DDP_PyTorch_MNIST.py:113`)
                (loss_fn(m(xs), ys) / ranks).backward()
            # blocking all-reduce (sum) across replicas (`:119-122`)
            for params in zip(*(m.parameters() for m in replicas)):
                total = sum(p.grad for p in params)
                for p in params:
                    p.grad = total.clone()
            for o in opts:
                o.step()
        for r in replicas[1:]:
            for p0, pr in zip(replicas[0].parameters(), r.parameters()):
                assert torch.equal(p0, pr), "DDP replicas diverged"
    return replicas[0]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ranks", type=int, default=4)
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--data-dir", default="data/mnist_784")
    args = ap.parse_args()

    data_dir = ensure_mnist(Path(args.data_dir))
    x_tr, y_tr, x_va, y_va = load_data(data_dir)

    t0 = time.time()
    serial = train_serial(x_tr, y_tr, args.epochs, args.batch_size, args.lr)
    t_serial = time.time() - t0
    print(f"serial: {t_serial:.2f}s, "
          f"test accuracy {accuracy(serial, x_va, y_va) * 100:.2f}%")

    t0 = time.time()
    ddp = train_ddp(x_tr, y_tr, args.epochs, args.batch_size, args.lr,
                    args.ranks)
    t_ddp = time.time() - t0
    print(f"ddp x{args.ranks}: {t_ddp:.2f}s, "
          f"test accuracy {accuracy(ddp, x_va, y_va) * 100:.2f}%")

    # abs weight divergence vs the serially trained model (`:159-167`)
    div = max((a - b).abs().max().item()
              for a, b in zip(serial.parameters(), ddp.parameters()))
    print(f"max abs weight divergence vs serial: {div:.3e}")


if __name__ == "__main__":
    main()
