"""Decode benchmark: prefill tok/s and steady-state decode tok/s.

Round 1 had no generation perf number at all (VERDICT item 6). The whole
generation — parallel prefill + a `lax.scan` decode loop — is ONE
compiled XLA program (`models/generate.py`), so per-dispatch tunnel
latency (~50 ms here) is paid once per measurement, not per token.

Method: time `generate(max_new=N1)` and `generate(max_new=N2)` (compiled,
best of 3 each); steady decode rate = (N2-N1) * B / (t2 - t1) — the
difference cancels the prefill and the fixed dispatch cost. Prefill tok/s
= B * Tp / t(max_new=1). GQA rows show the decode-bandwidth win of the
unrepeated-cache grouped attention (`generate._cached_attention`).

Usage: python scripts/bench_decode.py  — prints one JSON line per config.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402


def time_generate(params, prompt, cfg, max_new, reps=3, kv_quant=""):
    import jax

    from shallowspeed_tpu.models.generate import generate

    out = generate(params, prompt, cfg, max_new, temperature=0.0,
                   kv_quant=kv_quant)
    jax.device_get(out)  # compile + drain (excluded)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.device_get(generate(params, prompt, cfg, max_new,
                                temperature=0.0, kv_quant=kv_quant))
        best = min(best, time.perf_counter() - t0)
    return best


def run_config(batch, prompt_len, max_seq, kv_heads=0, d_model=1024,
               n_layers=8, n_heads=16, kv_quant=""):
    import jax

    from shallowspeed_tpu.models import transformer as T

    cfg = T.TransformerConfig(
        vocab=256, d_model=d_model, n_heads=n_heads, n_layers=n_layers,
        max_seq=max_seq, dtype=np.float32,
        compute_dtype=np.dtype("bfloat16"), rope=True, norm="rmsnorm",
        ffn="swiglu", n_kv_heads=kv_heads)
    params = jax.device_put(T.init(cfg, seed=0))
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab, (batch, prompt_len)).astype(np.int32)

    n1 = 32
    n2 = min(256, max_seq - prompt_len)
    t_pre = time_generate(params, prompt, cfg, 1, kv_quant=kv_quant)
    t1 = time_generate(params, prompt, cfg, n1, kv_quant=kv_quant)
    t2 = time_generate(params, prompt, cfg, n2, kv_quant=kv_quant)
    decode_tps = (n2 - n1) * batch / max(t2 - t1, 1e-9)
    return {
        "metric": "decode_throughput",
        "config": {"batch": batch, "prompt_len": prompt_len,
                   "max_seq": max_seq, "d_model": d_model,
                   "n_layers": n_layers, "n_heads": n_heads,
                   "kv_heads": kv_heads or n_heads,
                   "kv_quant": kv_quant or "bf16"},
        "prefill_tokens_per_sec": round(batch * prompt_len / t_pre, 0),
        "decode_tokens_per_sec": round(decode_tps, 1),
        "decode_ms_per_token": round(1000.0 / (decode_tps / batch), 3),
    }


def run_pp_config(pp, batch=4, prompt_len=64, max_seq=256, d_model=128,
                  n_layers=4, n_heads=4):
    """Pipelined decode on pp-sharded params vs replicated decode, on a
    virtual pp-device CPU mesh (a pp>1 mesh needs distinct devices, so
    absolute tok/s is not chip-representative — the RATIO is the cost
    of the per-token pp-phase latency chain; token-exactness is asserted
    in tests/test_generate.py)."""
    import jax
    from jax.sharding import Mesh

    from shallowspeed_tpu.models import transformer as T
    from shallowspeed_tpu.optim import SGD
    from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine

    cfg = T.TransformerConfig(
        vocab=256, d_model=d_model, n_heads=n_heads, n_layers=n_layers,
        max_seq=max_seq, dtype=np.float32, rope=True, norm="rmsnorm",
        ffn="swiglu")
    mesh = Mesh(np.array(jax.devices()[:pp]).reshape(1, pp),
                ("dp", "pp"))
    eng = PipelineLMEngine(cfg, SGD(0.1), mesh, n_mubatches=1, seed=0)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab,
                          (batch, prompt_len)).astype(np.int32)

    def timed(max_new, reps=3):
        eng.generate(prompt, max_new, temperature=0.0)  # compile
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            eng.generate(prompt, max_new, temperature=0.0)
            best = min(best, time.perf_counter() - t0)
        return best

    n1, n2 = 16, min(128, max_seq - prompt_len)
    t1, t2 = timed(n1), timed(n2)
    tps = (n2 - n1) * batch / max(t2 - t1, 1e-9)
    return {
        "metric": "pp_decode_throughput",
        "config": {"pp": pp, "batch": batch, "prompt_len": prompt_len,
                   "d_model": d_model, "n_layers": n_layers},
        "decode_tokens_per_sec": round(tps, 1),
        "decode_ms_per_token": round(1000.0 / (tps / batch), 3),
    }


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--pp", type=int, default=0,
                    help="benchmark pipelined decode over a virtual "
                         "pp-device CPU mesh instead of the single-chip "
                         "KV-cache decode")
    ap.add_argument("--long-context", action="store_true",
                    help="the cache-share-dominant regime (b8, ~8k "
                         "context): bf16 vs int8 KV cache head-to-head "
                         "(round 5 — the lever the round-4 roofline "
                         "named for when the cache dominates)")
    args = ap.parse_args()
    if args.long_context:
        for kv_quant in ("", "int8"):
            print(json.dumps(run_config(
                batch=8, prompt_len=7936, max_seq=8192,
                kv_quant=kv_quant)), flush=True)
            print(json.dumps(run_config(
                batch=8, prompt_len=7936, max_seq=8192, kv_heads=4,
                kv_quant=kv_quant)), flush=True)
        return
    if args.pp:
        import os

        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                f"{max(args.pp, 2)}").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
        for pp in sorted({1, 2, args.pp}):
            print(json.dumps(run_pp_config(pp)), flush=True)
        return
    for kwargs in (
        {"batch": 1, "prompt_len": 512, "max_seq": 2048},
        {"batch": 8, "prompt_len": 512, "max_seq": 2048},
        {"batch": 32, "prompt_len": 128, "max_seq": 1024},
        # GQA 4x fewer kv heads: the cache sweep shrinks 4x
        {"batch": 8, "prompt_len": 512, "max_seq": 2048, "kv_heads": 4},
        {"batch": 1, "prompt_len": 512, "max_seq": 2048, "kv_heads": 4},
    ):
        print(json.dumps(run_config(**kwargs)), flush=True)


if __name__ == "__main__":
    main()
