"""Narrow-matmul efficiency probe (VERDICT r2 item 5) — corrected.

Round 2 recorded "(16384,1024)@(1024,4096) at ~21 TFLOP/s vs 159-170 at
K>=2048" and BASELINE.md blamed a narrow-K tiling pathology. Re-measured
with a methodology that survives this tunnel (see below), the cliff is
real but half the story was measurement error:

- fetching any full matrix result crosses the ~10MB/s tunnel (seconds);
- consuming only out[0,0] lets XLA dead-code-narrow the matmul
  (apparent 1200+ "TFLOP/s");
- small per-dispatch chains sit on the 50-200ms dispatch-latency floor.

Correct method (here): a `lax.scan` chain of `iters` matmuls per
dispatch, the weight perturbed per step (defeats loop hoisting), the
full product consumed by a sum into the carry (defeats DCE), one scalar
fetched. Measured 2026-07-31 on the v5e:

    (16384,1024)@(1024,4096)  ~75 TFLOP/s   (not 21)
    (16384,1024)@(1024,8192) ~125 TFLOP/s   (wide N recovers the MXU)
    (16384,2048)@(2048,8192) ~133 TFLOP/s
    (16384, 512)@( 512,2048)  ~25 TFLOP/s   (genuinely starved)
    (16384,1024)@(1024,1024)  (proj-shaped) — see output

The surviving pathology is SMALL ops (K and N both ~<=1024), where
fixed per-pass costs can't amortize — which is why d_model<=1024 model
configs underuse the chip (their proj/down projections are exactly this
shape). A hand-tiled Pallas matmul (`shallowspeed_tpu/ops/matmul.py`)
does NOT beat Mosaic here (~65 vs ~75 TFLOP/s at K=1024) — kept as an
op + evidence, not wired into models. The model-level mitigation is
documented in BASELINE.md (larger batch*seq, or d_model >= 2048), and
`train_lm.py` warns when a config lands in the starved regime.

Usage: python scripts/bench_matmul.py [--m 16384] [--iters 100]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402


def bench_tflops(mm, m, k, n, iters=100, reps=3):
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(m, k)), jnp.bfloat16)
    y = jnp.asarray(rng.normal(size=(k, n)), jnp.bfloat16)

    @jax.jit
    def chain(x, y):
        def body(c, i):
            yy = y + i.astype(y.dtype) * jnp.bfloat16(1e-6)
            z = mm(x, yy)
            return c + z.astype(jnp.float32).sum(), None

        s, _ = jax.lax.scan(body, jnp.float32(0.0), jnp.arange(iters))
        return s

    jax.device_get(chain(x, y))  # compile + drain
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.device_get(chain(x, y))
        best = min(best, (time.perf_counter() - t0) / iters)
    return 2.0 * m * n * k / best / 1e12, best


def main():
    import jax

    from shallowspeed_tpu.ops.matmul import blocked_matmul

    ap = argparse.ArgumentParser()
    ap.add_argument("--m", type=int, default=16384)
    ap.add_argument("--iters", type=int, default=100)
    args = ap.parse_args()
    m = args.m

    shapes = [(1024, 4096), (1024, 8192), (2048, 8192), (512, 2048),
              (1024, 1024), (4096, 1024)]
    for k, n in shapes:
        for name, mm in (
            ("xla", lambda x, y: x @ y),
            ("pallas", lambda x, y: blocked_matmul(
                x, y, bm=512, bk=min(1024, x.shape[1]), bn=1024)),
        ):
            try:
                tf, dt = bench_tflops(mm, m, k, n, iters=args.iters)
                rec = {"tflops": round(tf, 1),
                       "ms": round(dt * 1e3, 3), "error": None}
            except Exception as e:
                rec = {"tflops": None, "ms": None,
                       "error": repr(e)[:120]}
            print(json.dumps({"metric": "matmul_tflops", "m": m, "k": k,
                              "n": n, "variant": name, **rec}),
                  flush=True)


if __name__ == "__main__":
    main()
