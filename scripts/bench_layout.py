"""Attention data-layout experiment: token-major vs head-major.

Round-4 gap accounting (BASELINE.md) measured 5.0% of d2048 step time in
"data formatting" — the (B,T,H,hd) <-> (B*H,T,hd) layout copies around
the flash kernel — and estimated a head-major layout (transposing the
projection weights instead of the activations) worth ~2 MFU points.
VERDICT r4 item 3: take the win or record a measured refutation.

This script measures exactly that sub-graph at the bench shapes, fwd +
bwd, as one fused scan per variant (the `bench_matmul.py` methodology:
weight-dependency chain across steps so XLA can neither hoist nor DCE):

- token_major: qkv dot -> reshape -> transpose-fold -> kernel ->
  transpose-unfold -> out-proj dot (the current model path).
- head_major: qkv einsum 'btd,dhxc->xbhtc' (projection weights carry the
  head split; the kernel's (B*H,T,hd) view is then a FREE reshape) ->
  kernel -> out einsum 'bhtc,hcd->btd'.

Identical math (same W layout bits, same kernel) — only the placement of
the layout permutation differs, so the delta is the data-formatting cost
XLA can or cannot fuse away.

Usage: python scripts/bench_layout.py [--steps 10 --batch 8 ...]
Prints one JSON line per variant plus the verdict.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402


def build(args):
    import jax
    import jax.numpy as jnp

    from shallowspeed_tpu.ops import flash_attention as fa

    B, T, H, hd = args.batch, args.seq_len, args.n_heads, args.head_dim
    D = H * hd
    bq = fa._pick_block(T, 512)
    bk = fa._pick_block(T, 512)
    kw = dict(causal=True, window=0, bq=bq, bk=bk, nqb_chunk=T // bq,
              interpret=False)

    # shared flash core on pre-folded (B*H, T, hd) operands, with the
    # hand VJP from the module (so both variants run the same kernels)
    @jax.custom_vjp
    def flash3(q3, k3, v3):
        o3, _ = fa._chunk_fwd(q3, k3, v3, 0, **kw)
        return o3

    def flash3_fwd(q3, k3, v3):
        o3, lse = fa._chunk_fwd(q3, k3, v3, 0, **kw)
        return o3, (q3, k3, v3, o3, lse)

    def flash3_bwd(res, do3):
        q3, k3, v3, o3, lse = res
        delta = fa._delta_of(do3, o3, lse)
        dq3 = fa._chunk_dq(q3, k3, v3, do3, lse, delta, 0, **kw)
        dk3, dv3 = fa._chunk_dkv(q3, k3, v3, do3, lse, delta, 0,
                                 groups=1, **kw)
        return (dq3.astype(q3.dtype), dk3.astype(k3.dtype),
                dv3.astype(v3.dtype))

    flash3.defvjp(flash3_fwd, flash3_bwd)
    cdt = jnp.bfloat16

    def token_major(x, Wqkv, Wo):
        # current model path: token-major dot, fold/unfold activations
        qkv = (x @ Wqkv.astype(cdt)).reshape(B, T, H, 3, hd)
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        o3 = flash3(fa._to_bhsd(q), fa._to_bhsd(k), fa._to_bhsd(v))
        o = fa._from_bhsd(o3, B, H).reshape(B, T, D)
        return x + o @ Wo.astype(cdt)

    def head_major(x, Wqkv, Wo):
        # head-major: the permutation rides the PROJECTION WEIGHTS; the
        # kernel view is a free reshape of the einsum output
        w = Wqkv.astype(cdt).reshape(D, H, 3, hd)
        qkv = jnp.einsum("btd,dhxc->xbhtc", x, w)
        q3, k3, v3 = (qkv[i].reshape(B * H, T, hd) for i in range(3))
        o3 = flash3(q3, k3, v3)
        o = o3.reshape(B, H, T, hd)
        return x + jnp.einsum("bhtc,hcd->btd", o,
                              Wo.astype(cdt).reshape(H, hd, D))

    rng = np.random.default_rng(0)
    x0 = jnp.asarray(rng.standard_normal((B, T, D)), cdt) * 0.02
    Wqkv0 = jnp.asarray(rng.standard_normal((D, 3 * D)), jnp.float32) * 0.02
    Wo0 = jnp.asarray(rng.standard_normal((D, D)), jnp.float32) * 0.02

    def stepper(block):
        def loss(Wqkv, Wo):
            return jnp.sum(block(x0, Wqkv, Wo).astype(jnp.float32))

        def step(carry, _):
            Wqkv, Wo = carry
            gq, go = jax.grad(loss, argnums=(0, 1))(Wqkv, Wo)
            # dependency chain: next step's weights depend on this
            # step's grads, so XLA cannot hoist or elide any step
            return (Wqkv + 1e-6 * gq, Wo + 1e-6 * go), gq[0, 0]

        @jax.jit
        def run():
            (_, _), probes = jax.lax.scan(step, (Wqkv0, Wo0), None,
                                          length=args.steps)
            return probes

        return run

    def hm_qkv_only(x, Wqkv, Wo):
        # head-major projections, token-major out-projection: isolates
        # the qkv-side fold cost from the out-side einsum cost
        w = Wqkv.astype(cdt).reshape(D, H, 3, hd)
        qkv = jnp.einsum("btd,dhxc->xbhtc", x, w)
        q3, k3, v3 = (qkv[i].reshape(B * H, T, hd) for i in range(3))
        o3 = flash3(q3, k3, v3)
        o = fa._from_bhsd(o3, B, H).reshape(B, T, D)
        return x + o @ Wo.astype(cdt)

    def hm_out_only(x, Wqkv, Wo):
        qkv = (x @ Wqkv.astype(cdt)).reshape(B, T, H, 3, hd)
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        o3 = flash3(fa._to_bhsd(q), fa._to_bhsd(k), fa._to_bhsd(v))
        o = o3.reshape(B, H, T, hd)
        return x + jnp.einsum("bhtc,hcd->btd", o,
                              Wo.astype(cdt).reshape(H, hd, D))

    def no_permute(x, Wqkv, Wo):
        # LOWER BOUND, deliberately wrong math: plain reshapes where the
        # transposes were (different token<->head association, same
        # shapes/FLOPs). The gap token_major - no_permute is the TOTAL
        # winnable data-formatting cost; if it is ~0 the copies are
        # already fused into adjacent ops and there is nothing to take.
        qkv = (x @ Wqkv.astype(cdt)).reshape(B, T, H, 3, hd)
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        o3 = flash3(q.reshape(B * H, T, hd), k.reshape(B * H, T, hd),
                    v.reshape(B * H, T, hd))
        return x + o3.reshape(B, T, D) @ Wo.astype(cdt)

    return {"token_major": stepper(token_major),
            "head_major": stepper(head_major),
            "hm_qkv_only": stepper(hm_qkv_only),
            "hm_out_only": stepper(hm_out_only),
            "no_permute_lower_bound": stepper(no_permute)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=2048)
    ap.add_argument("--n-heads", type=int, default=16)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--rounds", type=int, default=5)
    args = ap.parse_args()

    import jax

    runners = build(args)
    out = {}
    for name, run in runners.items():
        probes = jax.device_get(run())  # compile + correctness probe
        assert np.all(np.isfinite(probes)), name
        out[name] = float("inf")
    # interleave variants across rounds so slow host/tunnel drift hits
    # every variant equally; per-variant min over rounds
    for _ in range(args.rounds):
        for name, run in runners.items():
            t0 = time.perf_counter()
            jax.device_get(run())
            out[name] = min(out[name],
                            (time.perf_counter() - t0) / args.steps)
    for name in out:
        out[name] = round(out[name] * 1e3, 3)
        print(json.dumps({"variant": name, "ms_per_step": out[name]}))
    best = min(out, key=out.get)
    ratio = out["token_major"] / out[best]
    print(json.dumps({
        "metric": "attn_layout_speedup_best_vs_token_major",
        "best_variant": best,
        "value": round(ratio, 4),
        "verdict": (f"{best} wins" if best != "token_major"
                    and ratio > 1.01 else
                    "token_major holds (refutation measured)")}))


if __name__ == "__main__":
    main()
