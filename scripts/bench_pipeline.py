"""GPipe vs 1F1B (PipeDream-Flush) pipeline schedule cost.

VERDICT r1 item 9: the compiled 1F1B engine recomputes each microbatch's
forward per tick (`parallel/pipeline_lm.py` derives the backward with
per-tick `jax.vjp`), trading FLOPs for the bounded O(pp) stash; the cost
was asserted, never measured. A pp>1 mesh needs pp DISTINCT devices, so
on this 1-chip setup the benchmark runs both schedules on the virtual
8-device CPU mesh — absolute tok/s is not chip-representative, but the
1f1b/gpipe RATIO (the vjp-recompute overhead, the thing being decided)
is a compute-for-compute comparison on identical hardware.

Usage: python scripts/bench_pipeline.py [--pp 2 --n-mu 4 ...]
Prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np  # noqa: E402

def _setup_platform(n_devices: int) -> None:
    """Force a CPU device pool big enough for the requested mesh —
    BEFORE the first jax import (the bench_decode.py pattern:
    argparse first, then flags, then jax)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count="
            f"{n_devices}").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    assert len(jax.devices()) >= n_devices, (
        f"this mesh needs {n_devices} devices but the platform has "
        f"{len(jax.devices())} (XLA_FLAGS pinned a smaller pool?)")


def bench_engine(schedule, args, virtual_pp=1, sp=1):
    import jax
    from jax.sharding import Mesh

    from shallowspeed_tpu.models.transformer import TransformerConfig
    from shallowspeed_tpu.optim import AdamW
    from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine

    cfg = TransformerConfig(
        vocab=256, d_model=args.d_model, n_heads=args.n_heads,
        n_layers=args.n_layers, max_seq=args.seq_len, dtype=np.float32,
        compute_dtype=np.dtype("bfloat16"), rope=True, norm="rmsnorm",
        ffn="swiglu")
    if sp > 1:
        devs = np.array(jax.devices()[: args.pp * sp]).reshape(
            1, args.pp, sp)
        mesh = Mesh(devs, ("dp", "pp", "sp"))
        attn = "ring"
    else:
        devs = np.array(jax.devices()[: args.pp]).reshape(1, args.pp)
        mesh = Mesh(devs, ("dp", "pp"))
        attn = "flash"
    eng = PipelineLMEngine(cfg, AdamW(3e-4), mesh,
                           n_mubatches=args.n_mu, seed=0,
                           schedule=schedule, attn=attn,
                           virtual_pp=virtual_pp)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab,
                        (args.batch_size, args.seq_len)).astype(np.int32)
    tgts = np.roll(toks, -1, axis=1)
    eng.train_batch(toks, tgts)  # compile (excluded)
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        loss = None
        for _ in range(args.steps):
            loss = eng.train_batch_async(toks, tgts)
        jax.device_get(loss)
        dt = time.perf_counter() - t0
        best = max(best, args.steps * args.batch_size * args.seq_len / dt)
    return best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pp", type=int, default=2)
    ap.add_argument("--n-mu", type=int, default=4)
    ap.add_argument("--d-model", type=int, default=256)
    ap.add_argument("--n-heads", type=int, default=8)
    ap.add_argument("--n-layers", type=int, default=4)
    ap.add_argument("--seq-len", type=int, default=256)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--sp", type=int, default=0,
                    help="also benchmark gpipe vs 1f1b on a (dp, pp, sp) "
                         "mesh (ring attention; measures the 1F1B "
                         "uniform-execution cost; 0 = skip)")
    ap.add_argument("--virtual-pp", type=int, default=2,
                    help="also benchmark interleaved virtual stages at "
                         "this chunk count (0/1 = skip)")
    args = ap.parse_args()
    _setup_platform(max(8, args.pp * max(1, args.sp)))
    import jax  # noqa: F401  (platform configured above)

    gpipe = bench_engine("gpipe", args)
    f1b1 = bench_engine("1f1b", args)
    # ZB-H1 (round 5): hand-split B/W backward, no recompute — the win
    # over 1f1b combines the removed per-tick forward recompute and the
    # W-filled drain bubble (verify.simulate_zb proves the schedule
    # half; this measures the compiled whole)
    zb = bench_engine("zb", args)
    out = {
        "metric": "pipeline_schedule_throughput",
        "substrate": f"cpu-{args.pp}dev-virtual",
        "config": {"pp": args.pp, "n_mubatches": args.n_mu,
                   "d_model": args.d_model, "n_layers": args.n_layers,
                   "seq_len": args.seq_len, "batch": args.batch_size},
        "gpipe_tokens_per_sec": round(gpipe, 0),
        "1f1b_tokens_per_sec": round(f1b1, 0),
        "1f1b_over_gpipe": round(f1b1 / gpipe, 3),
        "zb_tokens_per_sec": round(zb, 0),
        "zb_over_1f1b": round(zb / f1b1, 3),
        "zb_over_gpipe": round(zb / gpipe, 3),
    }
    if args.virtual_pp > 1 and args.n_layers % (args.pp * args.virtual_pp) == 0:
        inter = bench_engine("gpipe", args, virtual_pp=args.virtual_pp)
        out["interleaved_tokens_per_sec"] = round(inter, 0)
        out["interleaved_over_gpipe"] = round(inter / gpipe, 3)
        interf = bench_engine("1f1b", args, virtual_pp=args.virtual_pp)
        out["interleaved_1f1b_tokens_per_sec"] = round(interf, 0)
        out["interleaved_1f1b_over_gpipe"] = round(interf / gpipe, 3)
    if args.sp > 1:
        # the 1F1B x sp uniform-execution cost (VERDICT r3 weak 4): with
        # an sp axis every 1F1B tick runs BOTH halves unmasked (the
        # cond-gated collective hazard), so its economics flip — this
        # row measures by how much, against gpipe on the SAME sp mesh
        gp_sp = bench_engine("gpipe", args, sp=args.sp)
        f1_sp = bench_engine("1f1b", args, sp=args.sp)
        out["sp"] = args.sp
        out["sp_gpipe_tokens_per_sec"] = round(gp_sp, 0)
        out["sp_1f1b_tokens_per_sec"] = round(f1_sp, 0)
        out["sp_1f1b_over_gpipe"] = round(f1_sp / gp_sp, 3)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
