"""Pipeline-schedule pebble graphs from the executable simulator.

The reference README illustrates its schedules with a static image
(`/root/reference/README.md:41`); here the picture is GENERATED from the
same simulation that proves the schedule correct
(`parallel/verify.py::simulate` — FIFO channel semantics, unit-cost
compute rounds), so the diagram can never drift from the code. Prints an
ASCII pebble graph per schedule (stages x rounds, F<mu>/B<mu> cells) with
makespan / bubble-fraction / peak-stash numbers, and optionally writes a
standalone SVG.

Usage:
    python scripts/plot_schedule.py [--pp 4] [--n-mu 8] [--svg out.svg]
"""

from __future__ import annotations

import argparse
import re
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from shallowspeed_tpu.parallel.schedules import (  # noqa: E402
    GPipeSchedule, InferenceSchedule, NaiveParallelSchedule,
    PipeDreamSchedule)
from shallowspeed_tpu.parallel.verify import simulate  # noqa: E402

SCHEDULES = [
    ("naive", NaiveParallelSchedule, True),
    ("gpipe", GPipeSchedule, True),
    ("1f1b (PipeDream-Flush)", PipeDreamSchedule, True),
    ("inference", InferenceSchedule, False),
]


def cells(report, pp):
    """(stage, round) -> label grid from the simulator's round maps."""
    grid = {}
    for (s, mu), r in report.fwd_rounds.items():
        grid[(s, r)] = f"F{mu}"
    for (s, mu), r in report.bwd_rounds.items():
        grid[(s, r)] = f"B{mu}"
    return grid


def ascii_graph(name, report, pp, n_mu, training) -> str:
    grid = cells(report, pp)
    span = report.makespan
    # bubble from the grid itself (generalizes to the interleaved/ZB
    # panels, whose per-stage op counts differ from 2*n_mu)
    work = max((sum(1 for (st, _) in grid if st == s)
                for s in range(pp)), default=0)
    bubble = 1.0 - work / span if span else 0.0
    out = [f"{name}  pp={pp}  n_mu={n_mu}  makespan={span} rounds  "
           f"bubble={bubble:.0%}  peak stash={report.peak_stash}"]
    for s in range(pp):
        row = "".join(f"{grid.get((s, r), '.'):>4}" for r in range(span))
        out.append(f"  stage {s} |{row}")
    return "\n".join(out)


def svg_graph(reports, pp, n_mu, path):
    """One SVG with all schedules stacked; fwd = blue family, bwd =
    orange family, shaded by microbatch."""
    cw, ch, pad, gap = 26, 18, 6, 34
    span_max = max(r.makespan for _, r, _ in reports)
    width = pad * 2 + 70 + span_max * cw
    height = pad + sum(gap + pp * ch + pad for _ in reports)
    parts = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
             f'height="{height}" font-family="monospace" font-size="11">']
    y = pad
    for name, rep, _training in reports:
        parts.append(f'<text x="{pad}" y="{y + 12}">{name}  '
                     f'(makespan {rep.makespan}, peak stash '
                     f'{rep.peak_stash})</text>')
        y += gap - 14
        grid = cells(rep, pp)
        for s in range(pp):
            for r in range(rep.makespan):
                lab = grid.get((s, r))
                x = pad + 70 + r * cw
                yy = y + s * ch
                if lab:
                    m_ = re.match(r"[FB](\d+)", lab)
                    mu = int(m_.group(1)) if m_ else 0
                    shade = 35 + int(45 * (mu / max(1, n_mu - 1)))
                    hue = 210 if lab[0] == "F" else 25
                    if lab.endswith("w"):
                        hue = 130  # ZB weight-grad fill: green family
                    fill = f"hsl({hue},70%,{shade}%)"
                    parts.append(
                        f'<rect x="{x}" y="{yy}" width="{cw - 2}" '
                        f'height="{ch - 2}" fill="{fill}"/>')
                    parts.append(
                        f'<text x="{x + 3}" y="{yy + 13}" '
                        f'fill="white">{lab}</text>')
                else:
                    parts.append(
                        f'<rect x="{x}" y="{yy}" width="{cw - 2}" '
                        f'height="{ch - 2}" fill="#eee"/>')
            parts.append(f'<text x="{pad}" y="{y + s * ch + 13}">'
                         f'stage {s}</text>')
        y += pp * ch + pad
    parts.append("</svg>")
    Path(path).write_text("\n".join(parts))


def interleaved_report(n_mu, pp, vpp):
    """Round-4 schedules rendered from the SAME artifacts the engine
    executes / the simulator proves: the interleaved-1F1B tables
    (verify.interleaved_tables — exactly what the compiled vpp x 1f1b
    engine follows) and the ZB-H1 list schedule (verify.simulate_zb).
    Both are shaped into SimReport-compatible grids: the interleaved
    grid labels chunk 0 'F<mu>'/'B<mu>' and chunk v >= 1 lowercase, so
    the pebble graph shows the chunk interleaving directly."""
    from shallowspeed_tpu.parallel.verify import interleaved_tables

    tb = interleaved_tables(n_mu, pp, vpp)

    class _Rep:
        makespan = tb.n_rounds
        peak_stash = [tb.n_stash_slots] * pp
        fwd_rounds = {}
        bwd_rounds = {}

    rep = _Rep()
    for r in range(tb.n_rounds):
        for d in range(pp):
            op, v, mu = tb.op[r, d], tb.chunk[r, d], tb.mu[r, d]
            if op == 0:
                continue
            # encode the chunk into the "mu" slot: v apostrophes mark
            # chunk v (distinct keys per chunk — vpp >= 3 must not
            # collapse ops onto one cell)
            target = rep.fwd_rounds if op == 1 else rep.bwd_rounds
            target[(d, f"{mu}" + "'" * int(v))] = r
    return rep


def zb_report(n_mu, pp):
    """Rendered from `verify.zb_tables` — the EXECUTED artifact (the
    compiled schedule="zb" engine scans these exact rows; round 5), not
    merely the simulation it was lowered from. Stash line = the colored
    peak across ALL three same-device pools (resb + resw residuals and
    the B->W tap cotangents) — the engine's real buffers."""
    from shallowspeed_tpu.parallel.verify import zb_tables

    tb = zb_tables(n_mu, pp)

    class _Rep:
        makespan = tb.n_rounds
        peak_stash = [tb.n_resb_slots + tb.n_resw_slots
                      + tb.n_tap_slots] * pp
        fwd_rounds = {}
        bwd_rounds = {}

    rep = _Rep()
    for r in range(tb.n_rounds):
        for d in range(pp):
            op, mu = int(tb.op[r, d]), int(tb.mu[r, d])
            if op == 1:
                rep.fwd_rounds[(d, f"{mu}")] = r
            elif op == 2:
                rep.bwd_rounds[(d, f"{mu}")] = r
            elif op == 3:  # W: weight-grad fill — cell renders as B<mu>w
                rep.bwd_rounds[(d, f"{mu}w")] = r
    return rep


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pp", type=int, default=4)
    ap.add_argument("--n-mu", type=int, default=8)
    ap.add_argument("--virtual-pp", type=int, default=2,
                    help="chunk count for the interleaved-1F1B panel "
                         "(0/1 = skip; needs n_mu, pp from above)")
    ap.add_argument("--svg", type=str, default="",
                    help="also write a stacked SVG to this path")
    args = ap.parse_args()

    reports = []
    for name, cls, training in SCHEDULES:
        rep = simulate(cls, args.n_mu, args.pp, training=training)
        reports.append((name, rep, training))
        print(ascii_graph(name, rep, args.pp, args.n_mu, training))
        print()
    if args.virtual_pp > 1:
        rep = interleaved_report(args.n_mu, args.pp, args.virtual_pp)
        name = (f"interleaved 1f1b (vpp={args.virtual_pp}; chunk>=1 "
                f"marked ')")
        reports.append((name, rep, True))
        print(ascii_graph(name, rep, args.pp, args.n_mu, True))
        print()
    repz = zb_report(args.n_mu, args.pp)
    reports.append(("ZB-H1 zero-bubble (W ops marked w)", repz, True))
    print(ascii_graph("ZB-H1 zero-bubble (W ops marked w)", repz,
                      args.pp, args.n_mu, True))
    print()
    if args.svg:
        svg_graph(reports, args.pp, args.n_mu, args.svg)
        print(f"wrote {args.svg}")


if __name__ == "__main__":
    main()
