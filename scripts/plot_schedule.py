"""Pipeline-schedule pebble graphs from the executable simulator.

The reference README illustrates its schedules with a static image
(`/root/reference/README.md:41`); here the picture is GENERATED from the
same simulation that proves the schedule correct
(`parallel/verify.py::simulate` — FIFO channel semantics, unit-cost
compute rounds), so the diagram can never drift from the code. Prints an
ASCII pebble graph per schedule (stages x rounds, F<mu>/B<mu> cells) with
makespan / bubble-fraction / peak-stash numbers, and optionally writes a
standalone SVG.

Usage:
    python scripts/plot_schedule.py [--pp 4] [--n-mu 8] [--svg out.svg]
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from shallowspeed_tpu.parallel.schedules import (  # noqa: E402
    GPipeSchedule, InferenceSchedule, NaiveParallelSchedule,
    PipeDreamSchedule)
from shallowspeed_tpu.parallel.verify import simulate  # noqa: E402

SCHEDULES = [
    ("naive", NaiveParallelSchedule, True),
    ("gpipe", GPipeSchedule, True),
    ("1f1b (PipeDream-Flush)", PipeDreamSchedule, True),
    ("inference", InferenceSchedule, False),
]


def cells(report, pp):
    """(stage, round) -> label grid from the simulator's round maps."""
    grid = {}
    for (s, mu), r in report.fwd_rounds.items():
        grid[(s, r)] = f"F{mu}"
    for (s, mu), r in report.bwd_rounds.items():
        grid[(s, r)] = f"B{mu}"
    return grid


def ascii_graph(name, report, pp, n_mu, training) -> str:
    grid = cells(report, pp)
    span = report.makespan
    work = (2 if training else 1) * n_mu
    bubble = 1.0 - work / span if span else 0.0
    out = [f"{name}  pp={pp}  n_mu={n_mu}  makespan={span} rounds  "
           f"bubble={bubble:.0%}  peak stash={report.peak_stash}"]
    for s in range(pp):
        row = "".join(f"{grid.get((s, r), '.'):>4}" for r in range(span))
        out.append(f"  stage {s} |{row}")
    return "\n".join(out)


def svg_graph(reports, pp, n_mu, path):
    """One SVG with all schedules stacked; fwd = blue family, bwd =
    orange family, shaded by microbatch."""
    cw, ch, pad, gap = 26, 18, 6, 34
    span_max = max(r.makespan for _, r, _ in reports)
    width = pad * 2 + 70 + span_max * cw
    height = pad + sum(gap + pp * ch + pad for _ in reports)
    parts = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
             f'height="{height}" font-family="monospace" font-size="11">']
    y = pad
    for name, rep, _training in reports:
        parts.append(f'<text x="{pad}" y="{y + 12}">{name}  '
                     f'(makespan {rep.makespan}, peak stash '
                     f'{rep.peak_stash})</text>')
        y += gap - 14
        grid = cells(rep, pp)
        for s in range(pp):
            for r in range(rep.makespan):
                lab = grid.get((s, r))
                x = pad + 70 + r * cw
                yy = y + s * ch
                if lab:
                    mu = int(lab[1:])
                    shade = 35 + int(45 * (mu / max(1, n_mu - 1)))
                    hue = 210 if lab[0] == "F" else 25
                    fill = f"hsl({hue},70%,{shade}%)"
                    parts.append(
                        f'<rect x="{x}" y="{yy}" width="{cw - 2}" '
                        f'height="{ch - 2}" fill="{fill}"/>')
                    parts.append(
                        f'<text x="{x + 3}" y="{yy + 13}" '
                        f'fill="white">{lab}</text>')
                else:
                    parts.append(
                        f'<rect x="{x}" y="{yy}" width="{cw - 2}" '
                        f'height="{ch - 2}" fill="#eee"/>')
            parts.append(f'<text x="{pad}" y="{y + s * ch + 13}">'
                         f'stage {s}</text>')
        y += pp * ch + pad
    parts.append("</svg>")
    Path(path).write_text("\n".join(parts))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pp", type=int, default=4)
    ap.add_argument("--n-mu", type=int, default=8)
    ap.add_argument("--svg", type=str, default="",
                    help="also write a stacked SVG to this path")
    args = ap.parse_args()

    reports = []
    for name, cls, training in SCHEDULES:
        rep = simulate(cls, args.n_mu, args.pp, training=training)
        reports.append((name, rep, training))
        print(ascii_graph(name, rep, args.pp, args.n_mu, training))
        print()
    if args.svg:
        svg_graph(reports, args.pp, args.n_mu, args.svg)
        print(f"wrote {args.svg}")


if __name__ == "__main__":
    main()
