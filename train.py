"""CLI training driver — L6.

Same surface as the reference (`/root/reference/train.py:62-155`):
`python train.py [--dp N] [--pp M] [--schedule naive|gpipe|pipedream]` — but
no `mpirun`: one controller process sees every TPU device through a
(dp, pp) `jax.sharding.Mesh` (`train.py:87-94`'s communicator splits become
mesh axes). Extra flags (epochs, batch size, engine, ...) replace the
reference's module-level constants (`train.py:56-59`) without changing the
defaults.

Engines:
- `fused` (pp=1 only): the whole batch step is one jitted XLA program
  (`shallowspeed_tpu/engine.py`).
- `vm`: the instruction-stream pipeline VM (`shallowspeed_tpu/parallel/
  worker.py`), required for pp>1.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

import numpy as np

EPOCHS = 20           # reference `train.py:56`
GLOBAL_BATCH_SIZE = 128  # reference `train.py:58`
N_MUBATCHES = 4       # reference `train.py:59`
LAYER_SIZES = [784, 128, 127, 126, 125, 124, 123, 10]  # reference `train.py:98`
LR = 0.006            # reference `train.py:107`


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--dp", type=int, default=1,
                   help="Degree of data parallelism (=number of full model replicas)")
    p.add_argument("--pp", type=int, default=1, help="Number of pipeline stages")
    p.add_argument("--schedule", type=str,
                   choices=["pipedream", "gpipe", "naive"], default="naive")
    p.add_argument("--engine", type=str,
                   choices=["auto", "vm", "fused", "spmd", "fp8"],
                   default="auto",
                   help="auto: fused for pp=1, spmd (compiled GPipe) for "
                        "pp>1 with --schedule gpipe, else the instruction "
                        "VM. fp8: the single-device fp8-e4m3 trainer "
                        "(shallowspeed_tpu.fp8) under the numerics "
                        "observatory — per-step numerics pack, shadow-"
                        "parity sampling, guard-driven bf16 fallback")
    p.add_argument("--epochs", type=int, default=EPOCHS)
    p.add_argument("--batch-size", type=int, default=GLOBAL_BATCH_SIZE)
    p.add_argument("--mubatches", type=int, default=N_MUBATCHES)
    p.add_argument("--lr", type=float, default=LR)
    p.add_argument("--optimizer", type=str, default="sgd",
                   choices=["sgd", "momentum", "adam", "adamw"])
    p.add_argument("--grad-clip", type=float, default=0.0,
                   help="global-norm gradient clipping (0 = off)")
    p.add_argument("--overlap", default="off", choices=["off", "on"],
                   help="comm/compute interleaving (shallowspeed_tpu."
                        "parallel.overlap): bucketed dp gradient "
                        "reduction issued inside the backward (fused "
                        "engine) and double-buffered stage hops + the "
                        "peeled bucketed reduction (spmd engine); the "
                        "default bulk reduction is the oracle")
    p.add_argument("--bucket-mb", type=float, default=4.0,
                   help="with --overlap on: target bytes per reduction "
                        "bucket (MiB); smaller = more, earlier "
                        "collectives")
    p.add_argument("--weight-decay", type=float, default=0.01,
                   help="decoupled weight decay (adamw only)")
    p.add_argument("--data-dir", type=str, default="data/mnist_784")
    p.add_argument("--max-batches", type=int, default=0,
                   help="limit batches per epoch (0 = all); for smoke tests")
    p.add_argument("--save-dir", type=str, default="",
                   help="checkpoint directory; saves after every epoch")
    p.add_argument("--resume", action="store_true",
                   help="resume from the latest checkpoint in --save-dir")
    p.add_argument("--auto-resume", action="store_true",
                   help="resume from the latest checkpoint if one exists, "
                        "start fresh otherwise (restart-safe; pairs with "
                        "the elastic supervisor, shallowspeed_tpu.elastic)")
    p.add_argument("--profile-dir", type=str, default="",
                   help="write a jax.profiler trace of the training epochs")
    p.add_argument("--heartbeat-file", type=str, default="",
                   help="touch this file at every epoch log point — the "
                        "elastic supervisor's liveness signal "
                        "(shallowspeed_tpu/elastic.py hang detection)")
    p.add_argument("--chaos", type=str, default="",
                   help="deterministic fault injection (shallowspeed_"
                        "tpu.chaos). On this driver kill/nan/freeze "
                        "faults fire per EPOCH, stall@N fires at "
                        "dataset BATCH id N (the Dataset.load_batch "
                        "hook — batch ids restart each epoch, so it "
                        "lands in the first epoch that loads batch N), "
                        "and save faults count checkpoint saves; "
                        "falls back to the supervisor-exported "
                        "SHALLOWSPEED_CHAOS env")
    p.add_argument("--chaos-state", type=str, default="",
                   help="fired-fault marker dir (default: "
                        "<save-dir>/.chaos); must survive restarts")
    p.add_argument("--chaos-seed", type=int, default=0)
    p.add_argument("--log-file", type=str, default="",
                   help="append per-epoch JSONL metrics here")
    p.add_argument("--telemetry", default="off",
                   choices=["off", "steps", "spans"],
                   help="runtime telemetry level (shallowspeed_tpu."
                        "telemetry): steps = host-clock spans + "
                        "HBM/collective/recompile fields per epoch "
                        "line; spans = device-fenced per-instruction "
                        "spans — on the VM engine this records the "
                        "executed schedule trace and reports the "
                        "measured pipeline bubble vs verify.py's "
                        "static prediction (serializes dispatch; a "
                        "measurement mode)")
    p.add_argument("--trace-dir", type=str, default="",
                   help="write spans.jsonl + trace.json (Chrome/"
                        "Perfetto) + telemetry.json here; implies "
                        "--telemetry steps when the level is off")
    p.add_argument("--monitor-port", type=int, default=None,
                   help="live telemetry plane (telemetry/monitor): "
                        "/status.json + /metrics on 127.0.0.1:PORT "
                        "while the run is live (0 = free port)")
    p.add_argument("--replica", type=str, default=None,
                   help="replica label for fleet views (telemetry/"
                        "fleet): stamped on run_start and served "
                        "from /status.json")
    p.add_argument("--slo", type=str, default="",
                   help="declarative SLOs over dual burn-rate windows "
                        "(telemetry/monitor DSL); 'alert' events land "
                        "in --log-file")
    p.add_argument("--flight-recorder", type=int, default=0,
                   help="ring of the last N metrics/span records, "
                        "dumped to flightrec_<step>.json on anomaly "
                        "verdicts, chaos faults, or SLO alerts "
                        "(0 = off)")
    p.add_argument("--profile", default="off",
                   choices=["off", "host", "host+device"],
                   help="continuous profiling plane (telemetry/"
                        "profiler): always-on host stack sampler "
                        "(schema-v12 'profile' events, span-tagged "
                        "phase buckets when --telemetry is on) + "
                        "burn/fault-triggered capture windows "
                        "(profcap_*.json); 'host+device' wraps each "
                        "capture in a bounded jax.profiler trace")
    p.add_argument("--profile-hz", type=float, default=None,
                   help="host sampler rate (default 67 Hz)")
    p.add_argument("--health", default="off",
                   choices=["off", "monitor", "guard"],
                   help="training-health observability (telemetry/"
                        "health.py): monitor = on-device grad/param "
                        "norms + nonfinite sentinel inside every "
                        "compiled step, anomaly verdicts per epoch "
                        "line; guard = monitor + skip any update with "
                        "non-finite gradients bit-identically. "
                        "Disables the fused whole-epoch dispatch (the "
                        "pack rides the per-batch step)")
    p.add_argument("--shadow-every", type=int, default=16,
                   help="--engine fp8: run the frozen master-precision "
                        "oracle step on the live batch every N training "
                        "steps (0 = off) and gate the loss/grad parity "
                        "against the numerics envelopes; the oracle "
                        "seconds are ledger-excluded as shadow_parity. "
                        "Step 0 is always skipped — the delayed amax "
                        "history has not warmed and its parity is "
                        "legitimately loose")
    p.add_argument("--log-every", type=int, default=10,
                   help="--engine fp8: step-line cadence (schema v13 "
                        "num_* fields ride each line)")
    p.add_argument("--platform", type=str, default=None,
                   choices=["cpu", "tpu"],
                   help="force a JAX platform (this environment pins "
                        "JAX_PLATFORMS at interpreter startup, so a flag — "
                        "not an env var — is needed to simulate meshes on CPU)")
    p.add_argument("--host-devices", type=int, default=0,
                   help="with --platform cpu: number of virtual host devices "
                        "for mesh simulation (XLA --xla_force_host_platform_"
                        "device_count)")
    return p.parse_args(argv)


def configure_platform(args):
    """Must run before the first JAX backend initialization."""
    import os

    if args.host_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{args.host_devices}").strip()
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    # multi-host: connect to the JAX distributed service when a coordinator
    # is configured (env vars / TPU pod metadata); single-process no-op
    from shallowspeed_tpu import distributed

    distributed.initialize()


def build(args):
    import jax

    from shallowspeed_tpu.data.dataset import Dataset
    from shallowspeed_tpu.data.mnist import ensure_mnist
    from shallowspeed_tpu.engine import FusedDPEngine
    from shallowspeed_tpu.models.mlp import MLPStage
    from shallowspeed_tpu.optim import OPTIMIZERS
    from shallowspeed_tpu.parallel.mesh import make_mesh
    from shallowspeed_tpu.parallel.worker import PipelineExecutor

    dp, pp = args.dp, args.pp
    assert dp >= 1 and pp >= 1
    assert args.batch_size % dp == 0, "Batch size must be divisible by DP"
    n_devices = len(jax.devices())
    if dp * pp > n_devices:
        raise SystemExit(
            f"requested dp*pp={dp * pp} devices but only {n_devices} present")

    mesh = make_mesh(dp, pp)
    opt_kw = {"grad_clip": args.grad_clip or None}
    if args.optimizer == "adamw":
        opt_kw["weight_decay"] = args.weight_decay
    optimizer = OPTIMIZERS[args.optimizer](lr=args.lr, **opt_kw)

    data_dir = ensure_mnist(Path(args.data_dir))
    local_bs = args.batch_size // dp
    assert local_bs % args.mubatches == 0, (
        f"local batch {local_bs} must be divisible by --mubatches "
        f"{args.mubatches}")
    mubatch_size = local_bs // args.mubatches
    train_ds = [Dataset(data_dir, args.batch_size, mubatch_size).load(r, dp)
                for r in range(dp)]
    # Validation: whole local batch as one microbatch (reference
    # `train.py:122-128` uses mubatch_size == global batch, 1 μbatch).
    val_ds = [Dataset(data_dir, args.batch_size, local_bs, validation=True)
              .load(r, dp) for r in range(dp)]

    from shallowspeed_tpu.parallel.spmd_pipeline import SPMDPipelineEngine

    engine_kind = args.engine
    if engine_kind == "auto":
        engine_kind = ("fused" if pp == 1
                       else "spmd" if args.schedule == "gpipe" else "vm")
    if engine_kind == "fused" and pp != 1:
        raise SystemExit("--engine fused requires --pp 1")
    if engine_kind == "spmd" and args.schedule != "gpipe":
        raise SystemExit("--engine spmd implements the gpipe schedule; use "
                         "--schedule gpipe (or --engine vm)")

    from shallowspeed_tpu.parallel.overlap import from_flags

    ov = from_flags(args.overlap, args.bucket_mb)
    if engine_kind == "fused":
        stage = MLPStage(LAYER_SIZES, 0, 1, batch_size=args.batch_size)
        engine = FusedDPEngine(stage, optimizer, mesh,
                               health=args.health, overlap=ov)
    elif engine_kind == "spmd":
        engine = SPMDPipelineEngine(LAYER_SIZES, optimizer, mesh,
                                    args.mubatches, mubatch_size,
                                    args.batch_size,
                                    health=args.health, overlap=ov)
    else:
        if ov is not None:
            raise SystemExit(
                "--overlap on needs a compiled engine (fused or spmd); "
                "the instruction VM already issues its collectives "
                "per-instruction")
        stages = [MLPStage(LAYER_SIZES, s, pp, batch_size=args.batch_size)
                  for s in range(pp)]
        engine = PipelineExecutor(mesh, stages, optimizer,
                                  health=args.health)
    return engine, train_ds, val_ds


def compute_accuracy(engine, val_ds) -> float:
    """Reference `compute_accuracy` (`train.py:21-47`): argmax of the
    last-stage output vs the one-hot target, streamed over val batches."""
    from shallowspeed_tpu.parallel.schedules import InferenceSchedule

    correct = total = 0
    for batch_id in range(val_ds[0].get_num_batches()):
        targets = np.concatenate(
            [ds.load_micro_batch_target(batch_id, 0) for ds in val_ds])
        if hasattr(engine, "infer"):  # fused / spmd engines
            x = np.concatenate(
                [ds.load_micro_batch_input(batch_id, 0) for ds in val_ds])
            out = np.asarray(engine.infer(x))
        else:  # pipeline VM
            out = np.asarray(
                engine.infer_batch(InferenceSchedule, 1, batch_id, val_ds))
        pred = out.argmax(axis=-1)
        correct += int((pred == targets.argmax(axis=-1)).sum())
        total += len(pred)
    return correct / total


def train_fp8(args) -> float:
    """The numerics-observatory driver (round 18): a STEP-based loop
    over the fp8-e4m3 trainer (`shallowspeed_tpu.fp8`) whose every
    line carries the runtime precision telemetry — the per-layer
    clamp/scale pack reduced by `telemetry.numerics.NumericsMonitor`,
    shadow-parity samples against the frozen f32 oracle every
    `--shadow-every` steps, and the guard escalation those verdicts
    drive (warn -> fallback_bf16 -> abort). Returns the final
    validation loss (the MSE head has no argmax accuracy story worth
    reporting next to the parity numbers)."""
    import jax  # noqa: F401  (backend init before any engine build)

    from shallowspeed_tpu import chaos
    from shallowspeed_tpu.data.dataset import Dataset
    from shallowspeed_tpu.data.mnist import ensure_mnist
    from shallowspeed_tpu.elastic import install_sigterm_exit
    from shallowspeed_tpu.fp8 import Fp8TrainEngine
    from shallowspeed_tpu.metrics import MetricsLogger, StepRates
    from shallowspeed_tpu.optim import OPTIMIZERS
    from shallowspeed_tpu.telemetry import profiler as profiler_mod
    from shallowspeed_tpu.telemetry.anomaly import GuardPolicy
    from shallowspeed_tpu.telemetry.goodput import GoodputLedger
    from shallowspeed_tpu.telemetry.health import HealthMonitor
    from shallowspeed_tpu.telemetry.monitor import close_monitor, from_args
    from shallowspeed_tpu.telemetry.numerics import NumericsMonitor
    from shallowspeed_tpu.utils import rprint

    for flag, val in (("--dp", args.dp != 1), ("--pp", args.pp != 1),
                      ("--save-dir", bool(args.save_dir)),
                      ("--telemetry", args.telemetry != "off"),
                      ("--overlap", args.overlap != "off")):
        if val:
            raise SystemExit(
                f"--engine fp8 is the single-device numerics trainer; "
                f"{flag} is not supported with it")
    install_sigterm_exit()
    chaos.setup(args.chaos, seed=args.chaos_seed,
                state_dir=args.chaos_state or None,
                log_file=args.log_file or None)
    t_proc0 = time.time()
    opt_kw = {"grad_clip": args.grad_clip or None}
    if args.optimizer == "adamw":
        opt_kw["weight_decay"] = args.weight_decay
    optimizer = OPTIMIZERS[args.optimizer](lr=args.lr, **opt_kw)
    engine = Fp8TrainEngine(LAYER_SIZES, optimizer)

    data_dir = ensure_mnist(Path(args.data_dir))
    train_ds = Dataset(data_dir, args.batch_size,
                       args.batch_size).load(0, 1)
    val_ds = Dataset(data_dir, args.batch_size, args.batch_size,
                     validation=True).load(0, 1)
    n_batches = train_ds.get_num_batches()
    if args.max_batches:
        n_batches = min(n_batches, args.max_batches)
    total_steps = n_batches * args.epochs

    metrics = MetricsLogger(
        args.log_file, engine=type(engine).__name__, dp=1, pp=1,
        schedule="fp8", batch_size=args.batch_size,
        **({"replica": args.replica} if args.replica else {}))
    ledger = GoodputLedger(metrics)
    live_mon, live_srv = from_args(args, metrics)
    if live_mon is not None:
        chaos.add_observer(live_mon.note_line)
    plane = profiler_mod.from_args(args, metrics)
    if plane is not None:
        chaos.add_observer(plane.on_fault)
        if live_mon is not None:
            live_mon.profiler = plane
            live_mon.alert_listeners.append(plane.on_alert)

    # the observatory's two host-side reducers: the numerics monitor
    # is ALWAYS on for this engine (it is the point of the driver);
    # grad-health verdicts join it under --health
    policy = GuardPolicy.for_mode(args.health) \
        if args.health != "off" else None
    num_mon = NumericsMonitor(policy=policy)
    monitor = HealthMonitor(policy=policy) \
        if args.health != "off" else None
    guarded = args.health == "guard"

    def val_loss() -> float:
        t0 = time.time()
        tot = 0.0
        nb = val_ds.get_num_batches()
        for b in range(nb):
            tot += engine.eval_loss(val_ds.load_micro_batch_input(b, 0),
                                    val_ds.load_micro_batch_target(b, 0))
        rates.pause(time.time() - t0, kind="val")
        return tot / max(nb, 1)

    rates = StepRates(args.batch_size, health=monitor, numerics=num_mon,
                      ledger=ledger, monitor=live_mon)
    ledger.note("init", seconds=time.time() - t_proc0)
    last_logged = -1
    loss = float("nan")
    try:
        for step in range(total_steps):
            # step faults (incl. scale_poison@N) fire per training
            # STEP on this driver — its cadence is the step, not the
            # epoch
            chaos.on_step(step, engine)
            batch_id = step % n_batches
            x = train_ds.load_micro_batch_input(batch_id, 0)
            y = train_ds.load_micro_batch_target(batch_id, 0)
            loss = engine.train_batch(x, y)
            # the pack fetch is one tiny host sync per step — this
            # engine's contract is observability, and the collapse
            # signature (a poisoned scale self-heals as fresh amaxes
            # roll in) is only visible AT the poisoned step
            verdicts = num_mon.observe(step, engine.health_snapshot())
            if (args.shadow_every and step
                    and step % args.shadow_every == 0):
                t_sh = time.time()
                parity = engine.shadow_parity(x, y)
                rates.pause(time.time() - t_sh, kind="shadow_parity")
                verdicts += num_mon.note_parity(step, parity)
            if monitor is not None:
                verdicts += monitor.observe(step, loss,
                                            engine.health_snapshot())
            fatal = []
            for v in verdicts:
                rprint(str(v))
                if v.action == "fallback_bf16" and guarded \
                        and engine.precision == "fp8":
                    engine.fallback_bf16()
                    num_mon.note_fallback()
                    ledger.note("fp8_fallback", count=1)
                    rprint(f"numerics guard: falling back to the bf16 "
                           f"master-precision step at step {step} "
                           f"({v.kind})")
                elif v.action == "abort" and guarded:
                    fatal.append(v)
            at_end = step == total_steps - 1
            if verdicts or at_end or step - last_logged >= args.log_every:
                r = rates.log_point(step - last_logged)
                last_logged = step
                metrics.log(event="step", step=step,
                            loss=round(float(loss), 6),
                            tokens_per_sec=round(r.pop(
                                "tokens_per_sec"), 1),
                            tokens_per_sec_cum=round(r.pop(
                                "tokens_per_sec_cum"), 1), **r)
                rprint(f"step {step:5d}  loss {loss:.5f}  "
                       f"precision {engine.precision}"
                       + (f"  parity "
                          f"{num_mon._last_parity['loss_rel']:.3g}"
                          if num_mon._last_parity else ""))
                if args.heartbeat_file and not chaos.heartbeat_frozen():
                    from shallowspeed_tpu.elastic import write_heartbeat

                    write_heartbeat(args.heartbeat_file,
                                    monitor.heartbeat_status()
                                    if monitor is not None else "ok")
            if fatal:
                if live_mon is not None:
                    live_mon.flight_dump(
                        "numerics:" + ",".join(v.kind for v in fatal),
                        step=step, trigger=[str(v) for v in fatal])
                raise SystemExit(
                    f"numerics policy abort at step {step}: "
                    + "; ".join(v.detail for v in fatal))
        final = val_loss()
        rprint(f"final val loss {final:.5f}  precision "
               f"{engine.precision}  shadow samples "
               f"{num_mon.shadow_total}")
        metrics.log(event="val", step=max(total_steps - 1, 0),
                    val_loss=round(final, 6))
        return final
    finally:
        if plane is not None:
            chaos.remove_observer(plane.on_fault)
            plane.close()
        if live_mon is not None:
            chaos.remove_observer(live_mon.note_line)
            close_monitor(live_mon, live_srv)
        plan = chaos.active()
        if plan is not None and plan.unfired():
            rprint(f"chaos: scheduled fault(s) never fired: "
                   f"{', '.join(plan.unfired())}")


def train(args) -> float:
    import jax

    from shallowspeed_tpu import chaos, checkpoint
    from shallowspeed_tpu.elastic import (EXIT_CORRUPT_CKPT,
                                          install_sigterm_exit)
    from shallowspeed_tpu.metrics import MetricsLogger
    from shallowspeed_tpu.parallel.schedules import (
        GPipeSchedule, NaiveParallelSchedule, PipeDreamSchedule)
    from shallowspeed_tpu.utils import assert_replicas_in_sync, get_model_hash, rprint

    if args.engine == "fp8":
        return train_fp8(args)

    schedule_cls = {
        "naive": NaiveParallelSchedule,
        "gpipe": GPipeSchedule,
        "pipedream": PipeDreamSchedule,
    }[args.schedule]

    # supervisor kill path: exit through finally blocks on SIGTERM so
    # the metrics tail flushes before the SIGKILL deadline
    install_sigterm_exit()
    chaos.setup(args.chaos, seed=args.chaos_seed,
                state_dir=args.chaos_state
                or (Path(args.save_dir) / ".chaos"
                    if args.save_dir else None),
                log_file=args.log_file or None)

    t_proc0 = time.time()  # goodput ledger: init = entry -> epoch loop
    engine, train_ds, val_ds = build(args)
    n_batches = train_ds[0].get_num_batches()
    if args.max_batches:
        n_batches = min(n_batches, args.max_batches)

    start_epoch = 0
    if args.auto_resume and not args.resume:
        # elastic restarts: resume iff a checkpoint EXISTS (cheap
        # probe; restore_latest verifies, quarantines, falls back)
        if not args.save_dir:
            raise SystemExit("--auto-resume requires --save-dir")
        if checkpoint.has_checkpoint(args.save_dir):
            args.resume = True
    if args.resume:
        if not args.save_dir:
            raise SystemExit("--resume requires --save-dir")
        start_epoch, ck, quarantined = checkpoint.restore_latest(
            engine, args.save_dir)
        if ck is None:
            if args.auto_resume:
                rprint(f"--auto-resume: no restorable checkpoint under "
                       f"{args.save_dir!r}; starting fresh")
            elif quarantined:
                print(f"--resume: every checkpoint under "
                      f"{args.save_dir!r} failed verification "
                      f"({len(quarantined)} quarantined)",
                      file=sys.stderr)
                raise SystemExit(EXIT_CORRUPT_CKPT)
            else:
                raise SystemExit(f"--resume: no checkpoint found under "
                                 f"{args.save_dir!r}")
        else:
            rprint(f"resumed from {ck} at epoch {start_epoch}")

    metrics = MetricsLogger(
        args.log_file, dp=args.dp, pp=args.pp, schedule=args.schedule,
        engine=type(engine).__name__, batch_size=args.batch_size,
        **({"replica": args.replica} if args.replica else {}))

    # goodput ledger (telemetry/goodput): init / val-eval / save time
    # stamped into the same JSONL so `--goodput` decomposes the run
    from shallowspeed_tpu.telemetry.goodput import GoodputLedger

    ledger = GoodputLedger(metrics)

    # ---- runtime telemetry (shallowspeed_tpu/telemetry)
    from shallowspeed_tpu import telemetry as tele

    if args.trace_dir and args.telemetry == "off":
        args.telemetry = "steps"  # --trace-dir implies tracing
    tracer = tele.configure(trace_dir=args.trace_dir or None,
                            level=args.telemetry)
    telem = (tele.RunTelemetry(engine, tracer, dtype="f32")
             if args.telemetry != "off" else None)
    if telem is not None:
        telem.ledger = ledger
        # memory observatory (round 20): register the long-lived trees
        # so step lines decompose live HBM per owner; resolvers, not
        # snapshots — the engine rotates/donates these every step
        from shallowspeed_tpu.telemetry import memory as memlib
        memlib.register_owner(
            "train.params", lambda: getattr(engine, "params", None))
        memlib.register_owner(
            "train.opt_state", lambda: getattr(engine, "opt_state", None))

    # ---- live telemetry plane (telemetry/monitor.py): endpoint +
    # SLO alerts + flight recorder, fed by every metrics line
    from shallowspeed_tpu.telemetry.monitor import (close_monitor,
                                                    from_args)

    live_mon, live_srv = from_args(args, metrics)
    if live_mon is not None:
        chaos.add_observer(live_mon.note_line)
        if args.telemetry != "off":
            tracer.subscribers.append(live_mon.record_span)
        if live_srv is not None:
            rprint(f"monitor: {live_srv.url('/status.json')} "
                   f"(+ /metrics)")
    # continuous profiling plane (round 17): host stack sampler into
    # the metrics JSONL + trigger-armed capture windows; tracer spans
    # feed the sampler's phase buckets via trace.PHASE_HOOKS, so
    # --telemetry steps/spans gives named host-time attribution
    from shallowspeed_tpu.telemetry import profiler as profiler_mod

    plane = profiler_mod.from_args(args, metrics)
    if plane is not None:
        chaos.add_observer(plane.on_fault)
        if live_mon is not None:
            live_mon.profiler = plane
            live_mon.alert_listeners.append(plane.on_alert)
    if telem is not None and args.pp > 1:
        telem.set_bubble(bubble_static=tele.static_bubble(
            args.schedule, args.mubatches,
            args.pp)["bubble_fraction"])

    # ---- training health: monitor fed at epoch log points (the pack
    # itself is computed on device every batch; guard skips are
    # enacted in-step regardless of the host cadence)
    monitor = None
    if args.health != "off":
        from shallowspeed_tpu.telemetry.anomaly import GuardPolicy
        from shallowspeed_tpu.telemetry.health import HealthMonitor

        monitor = HealthMonitor(policy=GuardPolicy.for_mode(args.health))

    # Fused engines: stage the epoch's batches on device once (HBM-resident)
    # and run each epoch as a single dispatch — unless health is on,
    # whose per-step pack rides the per-batch step program.
    staged = (engine.stage_epoch(train_ds, n_batches)
              if hasattr(engine, "train_epoch") and args.health == "off"
              else None)

    # the ONE jax.profiler entry point (telemetry/profiler): a falsy
    # dir is a no-op, and an active whole-run trace makes the capture
    # windows skip their own device half (xprof traces don't nest)
    from shallowspeed_tpu.telemetry.profiler import device_trace_ctx

    profile_ctx = device_trace_ctx(args.profile_dir)
    ledger.note("init", seconds=time.time() - t_proc0)
    start = time.time()
    accuracy = 0.0
    with profile_ctx:
        for epoch in range(start_epoch, args.epochs):
            # chaos step faults fire per EPOCH on this driver (its
            # checkpoint cadence is the epoch)
            chaos.on_step(epoch, engine)
            t_val = time.time()
            accuracy = compute_accuracy(engine, val_ds)
            ledger.note("val", seconds=time.time() - t_val)
            rprint(f"Epoch: {epoch}, Time Spent: {time.time() - start:.2f}s, "
                   f"Accuracy: {accuracy * 100:.2f}%")
            if args.heartbeat_file and not chaos.heartbeat_frozen():
                from shallowspeed_tpu.elastic import write_heartbeat

                write_heartbeat(args.heartbeat_file,
                                monitor.heartbeat_status()
                                if monitor is not None else "ok")
            t_epoch = time.time()
            trace_mark = 0
            if staged is not None:
                engine.train_epoch(staged)
            elif hasattr(engine, "train_epoch"):
                # fused/spmd engines under --health: per-batch stepping
                # (the health pack rides the batch step program)
                for batch_id in range(n_batches):
                    engine.train_batch(batch_id, train_ds)
            else:
                for batch_id in range(n_batches):
                    if batch_id == n_batches - 1:
                        # the bubble replay reads ONLY this batch's
                        # spans: batch ids repeat across epochs (and
                        # eval reuses them), so a bare batch filter
                        # would mix epochs into one replay
                        trace_mark = tracer.event_count
                    engine.train_batch(schedule_cls, args.mubatches, batch_id,
                                       train_ds)
            # JAX dispatch is async: wait for the params update to land so
            # the logged epoch time measures compute, not dispatch.
            jax.block_until_ready(engine.params)
            metrics.epoch(epoch, accuracy, n_batches * args.batch_size,
                          time.time() - t_epoch)
            if monitor is not None:
                # the last batch's pack + anomaly verdicts, once per
                # epoch (the MLP driver has no step lines)
                verdicts = monitor.observe(epoch, None,
                                           engine.health_snapshot())
                for v in verdicts:
                    rprint(str(v))
                metrics.log(event="health", step=epoch,
                            **monitor.step_fields())
            if telem is not None:
                # VM at the `spans` level: the per-instruction fenced
                # spans ARE the executed schedule trace — replay the
                # last batch's ops against the dataflow structure and
                # report the measured bubble vs the static prediction
                if (args.telemetry == "spans" and staged is None
                        and args.pp > 1):
                    from shallowspeed_tpu.telemetry import bubble as _b

                    ops = _b.span_replay_ops(
                        tracer.events_since(trace_mark),
                        batch=n_batches - 1)
                    if ops:
                        rep = _b.replay_trace(ops, args.pp)
                        telem.set_bubble(
                            bubble_measured=rep["bubble_fraction"])
                tf = telem.step_fields()
                metrics.log(event="telemetry", epoch=epoch, **tf)
                if "bubble_measured" in tf:
                    rprint(f"  telemetry: bubble measured "
                           f"{tf['bubble_measured']:.1%} vs static "
                           f"{tf.get('bubble_static', 0.0):.1%}  "
                           f"hbm {tf.get('hbm_live_mib', 0):,.0f} MiB")
            if args.save_dir:
                t_save = time.time()
                if monitor is not None and monitor.unhealthy():
                    # never checkpoint a poisoned iterate (see
                    # train_lm.py; found by the chaos NaN-storm drill)
                    rprint(f"epoch {epoch}: health is "
                           f"{monitor.heartbeat_status()!r} — "
                           f"skipping checkpoint save")
                    ledger.note("ckpt_save_skipped_unhealthy", count=1)
                else:
                    try:
                        checkpoint.save(args.save_dir, engine, epoch)
                    except (checkpoint.CheckpointError, OSError) as e:
                        if jax.process_count() > 1:
                            # peers already sit in the save barrier —
                            # swallowing on process 0 would wedge the
                            # gang; die and let the supervisor restart
                            raise
                        # atomic rename: latest() still points at the
                        # previous checkpoint — keep training
                        rprint(f"warning: checkpoint save failed "
                               f"({e}); the previous checkpoint "
                               f"remains the restore point")
                        ledger.note("ckpt_save_failed", count=1)
                ledger.note("ckpt_save", seconds=time.time() - t_save)

    accuracy = compute_accuracy(engine, val_ds)
    rprint(f"Epoch: {args.epochs}, Time Spent: {time.time() - start:.2f}s, "
           f"Accuracy: {accuracy * 100:.2f}%")
    metrics.final(accuracy, time.time() - start)
    if telem is not None:
        tracer.close()  # flush spans.jsonl, write trace.json
        if args.trace_dir:
            path = telem.write_summary(args.trace_dir)
            rprint(f"telemetry: {path} (+ spans.jsonl, trace.json)")
    if plane is not None:
        chaos.remove_observer(plane.on_fault)
        plane.close()
    if live_mon is not None:
        chaos.remove_observer(live_mon.note_line)
        close_monitor(live_mon, live_srv)

    plan = chaos.active()
    if plan is not None and plan.unfired():
        rprint(f"chaos: scheduled fault(s) never fired: "
               f"{', '.join(plan.unfired())}")
    # Sanity check: DP replicas hold bit-identical weights (reference
    # `train.py:154-155`, `utils.py:27-31`).
    params = engine.params
    assert_replicas_in_sync(params)
    rprint(f"model hash: {get_model_hash(params)}")
    return accuracy


if __name__ == "__main__":
    _args = parse_args()
    configure_platform(_args)
    train(_args)
