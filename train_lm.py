"""CLI driver for the long-context transformer LM family.

The MLP driver (`train.py`) keeps the reference's exact surface
(`/root/reference/train.py:62-155`); this driver exposes the capability the
reference never had: context-parallel training of a causal transformer with
ring attention over a (dp, sp) mesh (`shallowspeed_tpu/parallel/context.py`).

Data is a synthetic character-level copy-ahead corpus by default (this image
has zero egress), or any plain-text file via --text.

Example (virtual 8-device mesh, sequence sharded 4-way):

    python train_lm.py --platform cpu --host-devices 8 --dp 2 --sp 4 \
        --seq-len 256 --steps 200
"""

from __future__ import annotations

import argparse
import contextlib
import sys
import time
from pathlib import Path

import numpy as np


def parse_args(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--dp", type=int, default=1, help="data-parallel degree")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline-parallel degree: GPipe over transformer "
                        "blocks, backward schedule derived by autodiff "
                        "(needs n_layers %% pp == 0)")
    p.add_argument("--pp-schedule", choices=["gpipe", "1f1b", "zb"],
                   default="gpipe",
                   help="compiled pipeline schedule: gpipe (autodiff "
                        "backward), 1f1b (PipeDream-Flush: bounded "
                        "min(pp, n_mu) activation stash), or zb "
                        "(ZB-H1 zero-bubble: hand-split B/W backward, "
                        "deferred weight grads fill the drain bubble; "
                        "full residual stash, no recompute)")
    p.add_argument("--virtual-pp", type=int, default=1,
                   help="interleaved virtual pipeline stages per device "
                        "(Megatron-style; gpipe schedule, needs "
                        "n_layers %% (pp*virtual_pp) == 0)")
    p.add_argument("--n-mubatches", type=int, default=4,
                   help="microbatches per batch in the pipeline (--pp > 1)")
    p.add_argument("--sp", type=int, default=1,
                   help="sequence/context-parallel degree (ring attention)")
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel degree (Megatron placement); "
                        "composes with --sp on a (dp, sp, tp) mesh "
                        "(GSPMD) or with --pp on a (dp, pp, tp) mesh "
                        "(explicit psum inside the pipeline)")
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel degree (requires --experts > 0); "
                        "composes with --dp, and with --sp on a "
                        "(dp, sp, ep) mesh for long-context MoE")
    p.add_argument("--experts", type=int, default=0,
                   help="number of MoE experts per block (0 = dense FFN)")
    p.add_argument("--moe-top-k", type=int, default=2)
    p.add_argument("--moe-capacity-factor", type=float, default=2.0,
                   help="expert buffer slots = cf * top_k * tokens / E; "
                        "lower = faster steps, more dropped assignments "
                        "(drop fraction is logged per step)")
    p.add_argument("--moe-routing", default="sequence",
                   choices=["sequence", "priority"],
                   help="expert slot assignment: sequence order (GShard) "
                        "or batch-priority (V-MoE: overflow drops the "
                        "router's least-confident assignments)")
    p.add_argument("--moe-z-weight", type=float, default=0.0,
                   help="router z-loss weight (ST-MoE stabilizer; "
                        "1e-3 typical, 0 = off)")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=256)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--n-heads", type=int, default=4)
    p.add_argument("--kv-heads", type=int, default=0,
                   help="grouped-query attention: K/V head count "
                        "(0 = n-heads, plain MHA); the decode KV cache "
                        "shrinks by n-heads/kv-heads")
    p.add_argument("--n-layers", type=int, default=2)
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--optimizer", default="adam",
                   choices=["sgd", "momentum", "adam", "adamw",
                            "adafactor"])
    p.add_argument("--weight-decay", type=float, default=0.01,
                   help="decoupled weight decay (adamw/adafactor)")
    p.add_argument("--grad-clip", type=float, default=0.0,
                   help="global-norm gradient clipping (0 = off)")
    p.add_argument("--lr-schedule", default="constant",
                   choices=["constant", "linear", "cosine"],
                   help="lr schedule; linear/cosine warm up over "
                        "--warmup-steps then decay to --lr-end at "
                        "--steps")
    p.add_argument("--warmup-steps", type=int, default=0)
    p.add_argument("--lr-end", type=float, default=0.0,
                   help="final learning rate the linear/cosine schedules "
                        "decay to (default 0)")
    p.add_argument("--attn-window", type=int, default=0,
                   help="sliding-window attention: each position sees "
                        "only the last N positions (0 = full causal; "
                        "XLA-attention engines only)")
    p.add_argument("--logit-softcap", type=float, default=0.0,
                   help="final-logit soft-capping: cap*tanh(logits/cap) "
                        "(Gemma-2 style; 30.0 typical, 0 = off)")
    p.add_argument("--bf16", action="store_true",
                   help="mixed precision: bfloat16 compute (MXU-native), "
                        "float32 master weights/optimizer state")
    p.add_argument("--norm", default="layernorm",
                   choices=["layernorm", "rmsnorm"])
    p.add_argument("--ffn", default="gelu", choices=["gelu", "swiglu"],
                   help="dense FFN flavor (ignored with --experts)")
    p.add_argument("--rope", action="store_true",
                   help="rotary position embeddings (replaces the learned "
                        "absolute embedding; composes with every engine "
                        "and sequence sharding)")
    p.add_argument("--tie-embeddings", action="store_true",
                   help="weight tying: the output head reuses tok_emb^T "
                        "(no separate head matrix)")
    p.add_argument("--label-smoothing", type=float, default=0.0,
                   help="mix the one-hot target with the uniform "
                        "distribution in the loss")
    p.add_argument("--attn-dropout", type=float, default=0.0,
                   help="attention-PROBABILITY dropout (pre-AV-matmul "
                        "mask); plain XLA attention substrate only — "
                        "rejected with --pp, --sp>1, or a fused "
                        "substrate")
    p.add_argument("--dropout", type=float, default=0.0,
                   help="dropout rate on embeddings and attention/FFN "
                        "outputs (GPT-2 placement); active in training "
                        "steps only — eval and decode never drop")
    p.add_argument("--remat", action="store_true",
                   help="rematerialize each block's activations in the "
                        "backward (jax.checkpoint): ~1 extra forward of "
                        "FLOPs for O(layers)->O(1) activation memory")
    p.add_argument("--remat-policy", default="full",
                   choices=["full", "attn", "dots"],
                   help="what --remat SAVES per block: full = nothing "
                        "(max saving, +1 fwd of recompute), attn = the "
                        "attention output (never re-runs the attention "
                        "substrate), dots = every matmul output "
                        "(elementwise-only recompute; use when "
                        "microbatched activations fit)")
    p.add_argument("--xent-chunk", type=int, default=0,
                   help="chunked cross-entropy: compute the loss over "
                        "this many positions at a time (logits remat'd "
                        "per chunk) — never materializes the (B*T, vocab) "
                        "logits; 0 = whole-batch log-softmax")
    p.add_argument("--d-ff", type=int, default=0,
                   help="FFN hidden width (0 = 4*d_model)")
    p.add_argument("--fsdp", action="store_true",
                   help="ZeRO-3/FSDP: shard params, grads, AND optimizer "
                        "state over the dp axis (XLA derives the "
                        "just-in-time all-gather / reduce-scatter "
                        "schedule); stacks onto --sp/--tp via the 3-D "
                        "composite engine")
    p.add_argument("--zero1", action="store_true",
                   help="ZeRO-1: shard optimizer state over the dp axis "
                        "(1/dp per-device Adam moment footprint; GSPMD "
                        "derives the reduce/all-gather pattern)")
    p.add_argument("--zero2", action="store_true",
                   help="ZeRO-2: ZeRO-1 plus dp-sharded gradients — the "
                        "DP reduction becomes a reduce-scatter and the "
                        "persistent grad buffer is 1/dp per device")
    p.add_argument("--overlap", default="off", choices=["off", "on"],
                   help="comm/compute interleaving (shallowspeed_tpu."
                        "parallel.overlap): the dp gradient reduction "
                        "moves INSIDE the backward, one size-targeted "
                        "bucket at a time (with --accum the last "
                        "microbatch is peeled out of the accumulation "
                        "scan); --fsdp gains explicit per-leaf "
                        "all-gather prefetch + in-backward "
                        "reduce-scatter. Context engine (any --zero "
                        "level) and pure --fsdp; the bulk reduction "
                        "stays the oracle")
    p.add_argument("--bucket-mb", type=float, default=4.0,
                   help="with --overlap on: target bytes per reduction "
                        "bucket (MiB)")
    p.add_argument("--attn", default="ring",
                   choices=["ring", "ring-flash", "ulysses",
                            "ulysses-flash", "flash"],
                   help="attention substrate: ring (any --sp; XLA local "
                        "compute), ring-flash (any --sp; the fused "
                        "Pallas kernel as the ring's local compute — no "
                        "head-divisibility constraint), ulysses "
                        "(all-to-all; needs n_heads %% sp == 0), "
                        "ulysses-flash (all-to-all + fused Pallas kernel) "
                        "or the fused Pallas flash kernel (--sp 1 only; "
                        "also drops into each --pp stage, incl. --pp "
                        "--tp); with --tp/--fsdp alone the GSPMD engines "
                        "use XLA attention (K/V all-gather under --sp)")
    p.add_argument("--data-dir", type=str, default="",
                   help="memmapped token-shard corpus directory "
                        "(scripts/build_token_shards.py): streams "
                        "windows off disk — deterministic resumable "
                        "order, held-out val.bin split, no whole-file "
                        "RAM load. Replaces --text; vocab/tokenizer "
                        "come from the shard index")
    p.add_argument("--text", type=str, default="",
                   help="train on this UTF-8 text file (byte-level vocab, "
                        "or subword with --tokenizer bpe)")
    p.add_argument("--tokenizer", default="byte", choices=["byte", "bpe"],
                   help="text tokenization: raw bytes (vocab 256) or "
                        "byte-level BPE trained on --text to --vocab-size "
                        "(saved/restored with --save-dir)")
    p.add_argument("--vocab-size", type=int, default=512,
                   help="BPE target vocabulary (--tokenizer bpe)")
    p.add_argument("--generate", type=int, default=0,
                   help="after training, sample this many tokens from the "
                        "model (KV-cache decode) and print them")
    p.add_argument("--temperature", type=float, default=0.8)
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--top-p", type=float, default=0.0,
                   help="nucleus sampling: keep the smallest probability "
                        "mass >= p (0 = off; composes with --top-k)")
    p.add_argument("--kv-int8", action="store_true",
                   help="decode with an int8-quantized KV cache (halves "
                        "the cache sweep's HBM bytes — measured 1.18x "
                        "decode on bandwidth-bound GQA long-context, "
                        "BASELINE.md; streams are deterministic but not "
                        "bit-equal to the bf16 cache). Replicated decode "
                        "path only — the pipelined per-stage cache stays "
                        "bf16")
    p.add_argument("--prompt", type=str, default="",
                   help="UTF-8 prompt for --generate (byte-level; default: "
                        "a 16-token prefix from the data stream)")
    p.add_argument("--sample-only", action="store_true",
                   help="skip training: restore --save-dir's latest "
                        "checkpoint (implies --resume) and just --generate")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--log-every", type=int, default=20)
    p.add_argument("--ema-decay", type=float, default=0.0,
                   help="keep an exponential moving average of the "
                        "weights (e.g. 0.999); validation and sampling "
                        "use the averaged weights, checkpoints carry "
                        "them (0 = off)")
    p.add_argument("--accum", type=int, default=1,
                   help="gradient accumulation: split each batch into N "
                        "sequential microbatches per device (activation "
                        "memory of one microbatch, same gradient); plain "
                        "dp/sp engine only")
    p.add_argument("--prefetch", type=int, default=2,
                   help="input-pipeline depth: batches built + placed on "
                        "device this many steps ahead on a background "
                        "thread (0 = synchronous)")
    p.add_argument("--async-save", action="store_true",
                   help="write checkpoints on a background thread: the "
                        "device->host snapshot is synchronous (pins the "
                        "state), compression/IO never blocks training")
    p.add_argument("--keep-checkpoints", "--keep-last", type=int,
                   default=0, dest="keep_checkpoints",
                   help="checkpoint rotation: keep only the N newest "
                        "ckpt_* dirs (0 = keep all); a long elastic "
                        "run otherwise accumulates multi-GB "
                        "checkpoints without bound. The newest "
                        "VERIFIED checkpoint is never rotated away, "
                        "whatever its age — if everything newer is "
                        "corrupt, the one restorable state survives")
    p.add_argument("--save-every", type=int, default=100,
                   help="checkpoint every N steps when --save-dir is set")
    p.add_argument("--save-dir", type=str, default="")
    p.add_argument("--resume", action="store_true")
    p.add_argument("--auto-resume", action="store_true",
                   help="resume from the latest checkpoint if one exists, "
                        "start fresh otherwise — the restart-safe mode "
                        "the elastic supervisor (shallowspeed_tpu."
                        "elastic) relies on")
    p.add_argument("--heartbeat-file", type=str, default="",
                   help="touch this file at every log point; the elastic "
                        "supervisor watches its mtime for hang detection")
    p.add_argument("--log-file", type=str, default="")
    p.add_argument("--profile-dir", type=str, default="",
                   help="write a jax.profiler trace of the training loop")
    p.add_argument("--telemetry", default="off",
                   choices=["off", "steps", "spans"],
                   help="runtime telemetry level: steps = host-clock "
                        "spans + per-step-line HBM/collective/recompile "
                        "fields (async dispatch preserved); spans = "
                        "device-fenced phase spans + measured pipeline "
                        "bubble (accurate attributed time; serializes "
                        "dispatch — a measurement mode, not a "
                        "throughput mode)")
    p.add_argument("--health", default="off",
                   choices=["off", "monitor", "guard"],
                   help="training-health observability (shallowspeed_"
                        "tpu.telemetry.health): monitor = compute the "
                        "on-device health pack (grad/param norms, "
                        "update ratio, nonfinite sentinel) inside every "
                        "compiled step — zero extra executables — and "
                        "run the streaming anomaly detector (loss/grad "
                        "spikes, divergence, dead layers) over the "
                        "step lines; guard = monitor + gate the "
                        "optimizer update on the nonfinite sentinel "
                        "(a poisoned step is skipped bit-identically, "
                        "params and moments untouched). Health "
                        "verdicts ride --heartbeat-file, so the "
                        "elastic supervisor restarts a numerically "
                        "dead run from the last good checkpoint")
    p.add_argument("--trace-dir", type=str, default="",
                   help="write the telemetry trace here: spans.jsonl "
                        "(streamed), trace.json (Chrome/Perfetto), "
                        "telemetry.json (run summary). Implies "
                        "--telemetry steps when the level is off")
    p.add_argument("--monitor-port", type=int, default=None,
                   help="live telemetry plane (telemetry/monitor): "
                        "serve /status.json + /metrics (Prometheus "
                        "text) on 127.0.0.1:PORT — streaming sketch "
                        "quantiles over step time / tok/s, goodput so "
                        "far, health verdict, last fault — while the "
                        "run is live (0 = pick a free port)")
    p.add_argument("--replica", type=str, default=None,
                   help="replica label for fleet views (telemetry/"
                        "fleet): stamped on run_start and served from "
                        "/status.json so a FleetCollector names this "
                        "process in breakdowns and straggler events")
    p.add_argument("--slo", type=str, default="",
                   help="declarative SLOs over dual burn-rate "
                        "windows, e.g. 'step_p95_ms<250,"
                        "availability>0.99'; transitions land as "
                        "schema-v7 'alert' events in --log-file")
    p.add_argument("--flight-recorder", type=int, default=0,
                   help="anomaly flight recorder: ring of the last N "
                        "metrics/span records, dumped to flightrec_"
                        "<step>.json (next to --log-file) when an "
                        "anomaly verdict fires, a chaos fault stamps, "
                        "or an SLO alert trips (0 = off)")
    p.add_argument("--profile", default="off",
                   choices=["off", "host", "host+device"],
                   help="continuous profiling plane (telemetry/"
                        "profiler): always-on host stack sampler "
                        "streaming schema-v12 'profile' events into "
                        "--log-file (step spans tag the samples when "
                        "--telemetry is on, so host time decomposes "
                        "into named buckets) + burn/fault/anomaly-"
                        "triggered capture windows (profcap_*.json "
                        "next to flightrec_*); 'host+device' wraps "
                        "each capture in a bounded jax.profiler trace")
    p.add_argument("--profile-hz", type=float, default=None,
                   help="host sampler rate (default 67 Hz)")
    p.add_argument("--chaos", type=str, default="",
                   help="deterministic fault injection (shallowspeed_"
                        "tpu.chaos): a seeded plan like "
                        "'kill@9,corrupt@2,stall@5:0.5' (or a JSON "
                        "path) scheduling faults at named injection "
                        "points — process kill, SIGKILL inside the "
                        "checkpoint write window, NaN-poisoned "
                        "params, data-loader stall, heartbeat "
                        "freeze, ENOSPC on save, post-hoc checkpoint "
                        "corruption. Falls back to the supervisor-"
                        "exported SHALLOWSPEED_CHAOS env. Each fault "
                        "fires once and stamps a schema-v5 'fault' "
                        "event into --log-file")
    p.add_argument("--chaos-state", type=str, default="",
                   help="fired-fault marker directory (default: "
                        "<save-dir>/.chaos) — must survive restarts "
                        "so a restarted child replays fault windows "
                        "clean")
    p.add_argument("--chaos-seed", type=int, default=0)
    p.add_argument("--val-every", type=int, default=0,
                   help="every N steps evaluate held-out loss/perplexity "
                        "(--text: last 10%% of the file; synthetic: a "
                        "disjoint seed stream)")
    p.add_argument("--platform", type=str, default=None,
                   choices=["cpu", "tpu"])
    p.add_argument("--host-devices", type=int, default=0)
    return p.parse_args(argv)


def prepare_text(args):
    """(vocab, tokenizer, train ids, val ids) for the configured text
    pipeline. Byte mode: ids ARE the bytes (vocab 256, tokenizer None).
    BPE mode: train a ByteBPE on the training split (or load the one
    saved next to the checkpoints — --resume/--sample-only restore text
    fidelity with the model), then encode each split. Runs before the
    model config is built because the tokenizer defines the vocab."""
    from pathlib import Path

    tokenizer = None
    text_data = val_data = None
    train_bytes = val_bytes = None
    if args.data_dir:
        # streaming shard corpus: vocab + tokenizer come FROM the shard
        # directory (the builder bound them); --text would shadow it
        from shallowspeed_tpu.data.token_shards import (TokenShards,
                                                        ValSplit)

        if args.text:
            raise SystemExit("--data-dir replaces --text (the shard "
                             "index already fixes the token stream)")
        shards = TokenShards(args.data_dir, args.seq_len)
        tok_path = Path(args.data_dir) / "tokenizer.json"
        if tok_path.exists():
            from shallowspeed_tpu.data.tokenizer import ByteBPE

            tokenizer = ByteBPE.load(tok_path)
            assert tokenizer.vocab_size == shards.vocab, (
                tokenizer.vocab_size, shards.vocab)
        elif args.tokenizer == "bpe":
            # the shard index fixes the token stream; a bpe request
            # against byte-built shards would silently train a
            # different vocabulary than asked
            raise SystemExit(
                f"--tokenizer bpe but {args.data_dir} has no "
                f"tokenizer.json (it was built byte-level) — rebuild "
                f"with build_token_shards.py --tokenizer bpe")
        if args.val_every and not shards.has_val:
            raise SystemExit(
                f"--val-every needs a held-out split but {args.data_dir}"
                f" has no val.bin — rebuild with --val-fraction")
        if args.val_every and shards.has_val \
                and shards.val_tokens <= args.seq_len + 1:
            raise SystemExit(
                f"val.bin holds {shards.val_tokens} tokens — shorter "
                f"than seq_len+2; rebuild with a larger --val-fraction")
        val_data = ValSplit(shards) if shards.has_val else None
        return shards.vocab, tokenizer, shards, val_data
    if args.text:
        raw = open(args.text, "rb").read()
        assert len(raw) > args.seq_len + 1, "text too short for --seq-len"
        if args.val_every:
            split = max(int(len(raw) * 0.9), args.seq_len + 2)
            train_bytes, val_bytes = raw[:split], raw[split:]
            assert len(val_bytes) > args.seq_len + 1, (
                "text too short to hold out a 10% validation tail")
        else:
            train_bytes = raw

    if args.tokenizer == "bpe":
        from shallowspeed_tpu.data.tokenizer import ByteBPE, train_bpe

        tok_path = (Path(args.save_dir) / "tokenizer.json"
                    if args.save_dir else None)
        reuse = args.resume or args.sample_only
        if reuse and tok_path is not None and tok_path.exists():
            # resuming: the checkpointed weights are bound to the saved
            # merges — restore them and ignore --vocab-size. A FRESH run
            # always retrains (and overwrites), so a stale tokenizer.json
            # can never silently pin a new run's vocabulary.
            tokenizer = ByteBPE.load(tok_path)
        elif train_bytes is not None:
            tokenizer = train_bpe(train_bytes, args.vocab_size)
            if tok_path is not None:
                tok_path.parent.mkdir(parents=True, exist_ok=True)
                tokenizer.save(tok_path)
        else:
            raise SystemExit("--tokenizer bpe needs --text to train on "
                             "(or a tokenizer.json under --save-dir)")
        vocab = tokenizer.vocab_size
        if train_bytes is not None:
            text_data = tokenizer.encode(train_bytes)
            assert len(text_data) > args.seq_len + 1, (
                "tokenized text too short for --seq-len")
        if val_bytes is not None:
            val_data = tokenizer.encode(val_bytes)
            assert len(val_data) > args.seq_len + 1, (
                "tokenized validation tail too short for --seq-len")
    else:
        vocab = 256
        if train_bytes is not None:
            text_data = np.frombuffer(train_bytes, np.uint8).astype(
                np.int32)
        if val_bytes is not None:
            val_data = np.frombuffer(val_bytes, np.uint8).astype(np.int32)
    return vocab, tokenizer, text_data, val_data


def make_batch(args, vocab, step: int, text_data=None):
    """(tokens, targets) (B, T) int32 batch for `step` — random-access
    (seeded per step), so a resumed run continues the exact stream an
    uninterrupted run would have seen."""
    b, t = args.batch_size, args.seq_len
    if hasattr(text_data, "batch"):
        # shard-backed stream (TokenShards train view or ValSplit):
        # same purity contract, order delegated to the dataset
        return text_data.batch(step, b, seed=args.seed)
    rng = np.random.default_rng([args.seed, step])
    if text_data is not None:
        starts = rng.integers(0, len(text_data) - t - 1, b)
        tok = np.stack([text_data[s:s + t] for s in starts])
        tgt = np.stack([text_data[s + 1:s + t + 1] for s in starts])
        return tok, tgt
    # synthetic: repeat a random motif; next-token is learnable
    motif = rng.integers(0, vocab, (b, 16))
    tok = np.tile(motif, (1, t // 16 + 1))[:, :t].astype(np.int32)
    tgt = np.roll(tok, -1, axis=1).astype(np.int32)
    return tok, tgt


def train(args) -> float:
    t_proc0 = time.time()  # goodput ledger: init = entry -> step loop
    import jax

    # multi-host: connect to the JAX distributed service when a
    # coordinator is configured (env vars / pod metadata; the gang
    # supervisor injects them) — single-process no-op, like train.py
    from shallowspeed_tpu import distributed

    distributed.initialize()
    from jax.sharding import Mesh

    from shallowspeed_tpu import chaos, checkpoint
    from shallowspeed_tpu.elastic import (EXIT_CORRUPT_CKPT,
                                          install_sigterm_exit)
    from shallowspeed_tpu.metrics import MetricsLogger
    from shallowspeed_tpu.models.transformer import TransformerConfig
    from shallowspeed_tpu.optim import OPTIMIZERS
    from shallowspeed_tpu.parallel.context import ContextParallelEngine
    from shallowspeed_tpu.utils import rprint

    # a supervisor hang/health kill sends SIGTERM first (--term-grace):
    # exit through the finally blocks so the metrics/ledger tail the
    # goodput reducer reads is flushed, not truncated mid-write
    install_sigterm_exit()
    # deterministic fault injection (--chaos flag or the supervisor-
    # exported env); fired-fault markers default to living WITH the
    # checkpoints so they survive supervisor restarts
    chaos.setup(args.chaos, seed=args.chaos_seed,
                state_dir=args.chaos_state
                or (Path(args.save_dir) / ".chaos"
                    if args.save_dir else None),
                log_file=args.log_file or None)

    if ((args.resume or args.sample_only or args.auto_resume)
            and not args.save_dir):
        raise SystemExit(
            "--resume/--auto-resume/--sample-only require --save-dir")
    if (args.prompt or args.sample_only) and not args.generate:
        args.generate = 128  # --prompt/--sample-only imply sampling
    prompt_len = len(args.prompt.encode()) if args.prompt else 16
    if args.generate and args.generate + prompt_len > args.seq_len:
        raise SystemExit(f"--generate {args.generate} + the {prompt_len}-"
                         f"token prompt exceeds --seq-len {args.seq_len} "
                         f"(= max_seq)")
    composite = args.sp > 1 and args.tp > 1
    if args.pp > 1 and (args.zero1 or args.zero2 or args.fsdp) \
            and args.dp < 2:
        raise SystemExit("--pp with --zero1/--zero2/--fsdp shards over "
                         "dp; need --dp >= 2")
    if args.pp > 1 and (args.zero2 or args.fsdp) and args.ep > 1:
        raise SystemExit("--pp with --zero2/--fsdp takes a "
                         "('dp','pp'[,'tp'|'sp']) mesh (no --ep: "
                         "expert-leaf grads are ep-sharded, outside "
                         "the per-leaf ZeRO scatter rule)")
    if args.pp > 1 and sum(a > 1 for a in (args.tp, args.sp,
                                           args.ep)) > 1:
        raise SystemExit("--pp takes ONE extra model axis: --tp, --sp, "
                         "or --ep")
    if args.pp > 1 and args.virtual_pp > 1 and args.ep > 1:
        raise SystemExit("--virtual-pp needs collective-free chunk "
                         "bodies (no --ep all-to-all inside a "
                         "cond-gated chunk)")
    if args.pp > 1 and args.experts and args.tp > 1:
        raise SystemExit("--experts with --pp composes with --dp/--sp/"
                         "--ep (not --tp)")
    if args.pp > 1 and args.sp > 1 and args.attn not in (
            "ring", "ring-flash", "ulysses-flash"):
        raise SystemExit(f"--pp with --sp needs a sequence-parallel "
                         f"attention substrate (--attn ring, ring-flash "
                         f"or ulysses-flash), got {args.attn}")
    if args.pp > 1 and args.sp > 1 and args.pp_schedule == "1f1b":
        print("note: on an sp mesh the 1F1B ticks cannot skip (the F/B "
              "halves run unmasked so every device issues the same "
              "collective schedule) — measured ~0.5x GPipe's throughput "
              "(BASELINE.md '1F1B x sp'); --pp-schedule gpipe is the "
              "fast choice here", file=sys.stderr)
    if args.pp > 1 and args.sp == 1 and args.attn not in ("ring", "flash"):
        raise SystemExit(f"--attn {args.attn} is not available with --pp "
                         "(XLA attention by default, or the fused Pallas "
                         "kernel via --attn flash)")
    if args.pp > 1 and args.pp_schedule == "zb":
        # mirror of PipelineLMEngine's pinned zb carve-outs, with CLI
        # vocabulary (tests/test_pipeline_zb.py pins the mechanisms);
        # gated on pp > 1 like every sibling check — at pp=1 the
        # schedule flag is inert (no pipeline engine is built)
        if any(a > 1 for a in (args.tp, args.sp, args.ep)):
            raise SystemExit("--pp-schedule zb runs on a ('dp','pp') "
                             "mesh (no --tp/--sp/--ep: collectives "
                             "inside the per-round switch de-sync)")
        if args.virtual_pp > 1:
            raise SystemExit("--pp-schedule zb needs --virtual-pp 1 "
                             "(per-chunk B/W tables are not built)")
        if args.experts:
            raise SystemExit("--pp-schedule zb needs the dense block "
                             "family (no --experts)")
        if args.dropout > 0.0 or args.attn_dropout > 0.0:
            raise SystemExit("--pp-schedule zb trains without dropout "
                             "(the hand-split backward does not thread "
                             "mask keys F->B)")
        if args.remat:
            raise SystemExit("--pp-schedule zb IS the no-recompute "
                             "schedule (it stashes residuals F->B); "
                             "drop --remat")
    if args.ep > 1 and args.tp > 1:
        raise SystemExit("--ep composes with --dp/--sp (not --tp)")
    if args.keep_checkpoints < 0:
        raise SystemExit("--keep-checkpoints takes 0 (keep all) or a "
                         "positive count")
    if args.fsdp and (args.ep > 1 or args.experts or args.zero1
                      or args.zero2):
        raise SystemExit("--fsdp composes with --dp/--sp/--tp/--pp (and already "
                         "subsumes --zero1/--zero2; MoE uses --ep)")
    if args.zero1 and args.zero2:
        raise SystemExit("--zero2 subsumes --zero1; pick one")
    if args.overlap != "off" and (
            args.pp > 1 or args.tp > 1 or args.ep > 1 or args.experts
            or (args.fsdp and (args.sp > 1 or args.tp > 1))):
        raise SystemExit(
            "--overlap on supports the context engine (--dp/--sp, any "
            "--zero level, --accum) and pure --fsdp; the GSPMD tp/ep/"
            "composite engines schedule compiler-inserted collectives "
            "and the LM pipeline keeps its own hop schedule")
    # --attn-window composes with every substrate: the XLA/ring/ulysses
    # paths mask (ops/attention.py) and the flash kernel skips
    # out-of-window tiles (ops/flash_attention.py) — no guard needed.
    if not 0.0 <= args.ema_decay < 1.0:
        raise SystemExit(f"--ema-decay must be in [0, 1), got "
                         f"{args.ema_decay} (1.0 would freeze the average "
                         f"at the initial weights)")
    if args.accum > 1 and (args.tp > 1 or args.ep > 1 or args.experts
                           or args.fsdp or args.pp > 1):
        raise SystemExit("--accum composes with --dp/--sp (the context "
                         "engine) for now; the pipeline engine already "
                         "microbatches via --n-mubatches")
    if args.fsdp and (args.sp > 1 or args.tp > 1) and args.pp <= 1:
        # ZeRO-3 on top of the 3-D mesh; with --pp the pipeline engine
        # owns fsdp x sp (round 5) so this must not reroute it
        composite = True
    if (args.fsdp or args.tp > 1) and args.pp <= 1 and args.attn != "ring":
        raise SystemExit(f"--attn {args.attn} is not available with "
                         "--tp/--fsdp (the GSPMD engines use XLA attention; "
                         "under --sp the composite engine's context "
                         "parallelism is the K/V all-gather formulation)")
    if args.ep > 1 and args.experts == 0:
        raise SystemExit("--ep requires --experts > 0")
    if args.experts and args.tp > 1:
        raise SystemExit("--experts composes with --dp/--sp/--ep (not "
                         "--tp) for now")
    if args.experts and args.moe_top_k > args.experts:
        raise SystemExit(f"--moe-top-k {args.moe_top_k} cannot exceed "
                         f"--experts {args.experts}")
    if args.attn_dropout > 0.0 and (
            args.pp > 1 or args.sp > 1
            or args.attn not in ("ring",)):
        raise SystemExit("--attn-dropout needs the plain XLA attention "
                         "substrate (no --pp/--sp>1, --attn ring)")
    if args.experts and args.pp <= 1 and args.attn != "ring":
        raise SystemExit(f"--attn {args.attn} is not available with "
                         "--experts (the MoE engine uses XLA attention)")
    if composite:
        model_par = args.sp * args.tp
    elif args.pp > 1:
        model_par = args.pp * args.tp * args.sp * args.ep
    elif (args.ep > 1 or args.experts) and args.sp > 1:
        model_par = args.sp * args.ep  # long-context MoE: (dp, sp, ep)
    else:
        model_par = max(args.tp, args.sp, args.ep)
    n_dev = len(jax.devices())
    if args.dp * model_par > n_dev:
        raise SystemExit(f"requested dp*model_parallel="
                         f"{args.dp * model_par} devices but only "
                         f"{n_dev} present")
    assert args.batch_size % args.dp == 0
    assert args.seq_len % args.sp == 0

    vocab, tokenizer, text_data, val_data = prepare_text(args)
    import jax.numpy as jnp

    cfg = TransformerConfig(vocab=vocab, d_model=args.d_model,
                            n_heads=args.n_heads, n_layers=args.n_layers,
                            max_seq=args.seq_len, n_experts=args.experts,
                            moe_top_k=args.moe_top_k,
                            moe_capacity_factor=args.moe_capacity_factor,
                            moe_z_weight=args.moe_z_weight,
                            moe_routing=args.moe_routing,
                            compute_dtype=jnp.bfloat16 if args.bf16 else None,
                            remat=args.remat,
                            remat_policy=args.remat_policy,
                            xent_chunk=args.xent_chunk, d_ff=args.d_ff,
                            rope=args.rope,
                            norm=args.norm, ffn=args.ffn,
                            n_kv_heads=args.kv_heads,
                            dropout=args.dropout,
                            attn_dropout=args.attn_dropout,
                            tie_embeddings=args.tie_embeddings,
                            label_smoothing=args.label_smoothing,
                            logit_softcap=args.logit_softcap,
                            attn_window=args.attn_window)
    if jax.default_backend() == "tpu" and 256 < args.d_model <= 1024:
        # measured on this v5e (scripts/bench_matmul.py, BASELINE.md):
        # ops with K and N both <= 1024 run far below MXU peak (fixed
        # per-pass costs dominate), so d_model <= 1024 configs cap out
        # around 26-35% MFU while d_model >= 2048 reaches ~57%. Tiny
        # (demo-sized, <=256) models are exempt — nobody MFU-tunes those.
        from shallowspeed_tpu.utils import rprint as _rprint

        _rprint(f"note: d_model={args.d_model} puts the attention/FFN "
                f"projections in the MXU's starved small-matmul regime "
                f"on this chip (~26-35% MFU vs ~57% at d_model>=2048); "
                f"prefer fewer/wider layers or raise batch*seq "
                f"(BASELINE.md 'narrow-matmul' section)")
    from shallowspeed_tpu.optim import SCHEDULES

    if args.lr_schedule == "constant":
        lr = args.lr  # static float keeps SGD stateless (no step counter)
    else:
        lr = SCHEDULES[args.lr_schedule](
            peak=args.lr, warmup=args.warmup_steps, total=args.steps,
            end=args.lr_end)
    opt_kw = {"grad_clip": args.grad_clip or None}
    if args.optimizer in ("adamw", "adafactor"):
        opt_kw["weight_decay"] = args.weight_decay
    opt = OPTIMIZERS[args.optimizer](lr=lr, **opt_kw)
    devs = np.array(jax.devices()[: args.dp * model_par])
    if args.pp > 1:
        from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine

        if args.tp > 1:
            mesh = Mesh(devs.reshape(args.dp, args.pp, args.tp),
                        ("dp", "pp", "tp"))
            pp_attn = "flash" if args.attn == "flash" else "xla"
        elif args.sp > 1:
            mesh = Mesh(devs.reshape(args.dp, args.pp, args.sp),
                        ("dp", "pp", "sp"))
            pp_attn = args.attn  # ring / ring-flash / ulysses-flash
        elif args.ep > 1:
            # ep x pp: experts sharded over 'ep' inside each stage,
            # stage-local all-to-all dispatch; ep also multiplies the
            # data dimension (rows shard over dp x ep)
            mesh = Mesh(devs.reshape(args.dp, args.pp, args.ep),
                        ("dp", "pp", "ep"))
            pp_attn = "flash" if args.attn == "flash" else "xla"
        else:
            mesh = Mesh(devs.reshape(args.dp, args.pp), ("dp", "pp"))
            pp_attn = "flash" if args.attn == "flash" else "xla"
        engine = PipelineLMEngine(cfg, opt, mesh,
                                  n_mubatches=args.n_mubatches,
                                  seed=args.seed,
                                  schedule=args.pp_schedule,
                                  attn=pp_attn,
                                  virtual_pp=args.virtual_pp,
                                  zero1=args.zero1, zero2=args.zero2,
                                  fsdp=args.fsdp, health=args.health)
    elif composite:
        from shallowspeed_tpu.parallel.composite import Composite3DEngine

        mesh = Mesh(devs.reshape(args.dp, args.sp, args.tp),
                    ("dp", "sp", "tp"))
        engine = Composite3DEngine(cfg, opt, mesh, seed=args.seed,
                                   zero1=args.zero1, zero2=args.zero2,
                                   fsdp=args.fsdp, health=args.health)
    elif args.fsdp:
        from shallowspeed_tpu.parallel.fsdp import FSDPEngine
        from shallowspeed_tpu.parallel.overlap import from_flags

        mesh = Mesh(devs.reshape(args.dp), ("dp",))
        engine = FSDPEngine(cfg, opt, mesh, seed=args.seed,
                            health=args.health,
                            overlap=from_flags(args.overlap,
                                               args.bucket_mb))
    elif args.ep > 1 or args.experts:
        from shallowspeed_tpu.parallel.expert import ExpertParallelEngine

        if args.sp > 1:
            mesh = Mesh(devs.reshape(args.dp, args.sp, args.ep),
                        ("dp", "sp", "ep"))
        else:
            mesh = Mesh(devs.reshape(args.dp, args.ep), ("dp", "ep"))
        engine = ExpertParallelEngine(cfg, opt, mesh, seed=args.seed,
                                      zero1=args.zero1, zero2=args.zero2,
                                      health=args.health)
    elif args.tp > 1:
        from shallowspeed_tpu.parallel.tensor import TensorParallelEngine

        mesh = Mesh(devs.reshape(args.dp, args.tp), ("dp", "tp"))
        engine = TensorParallelEngine(cfg, opt, mesh, seed=args.seed,
                                      zero1=args.zero1, zero2=args.zero2,
                                      health=args.health)
    else:
        from shallowspeed_tpu.parallel.overlap import from_flags

        mesh = Mesh(devs.reshape(args.dp, args.sp), ("dp", "sp"))
        engine = ContextParallelEngine(cfg, opt, mesh, seed=args.seed,
                                       attn=args.attn, zero1=args.zero1,
                                       zero2=args.zero2, accum=args.accum,
                                       health=args.health,
                                       overlap=from_flags(
                                           args.overlap, args.bucket_mb))

    start_step = 0
    restored_ckpt = None
    if args.auto_resume and not args.resume:
        # elastic restarts: resume iff a checkpoint EXISTS (cheap
        # probe — restore_latest does the one verification pass,
        # quarantining corrupt dirs and falling back), else fresh
        if checkpoint.has_checkpoint(args.save_dir):
            args.resume = True
    restore_secs = 0.0
    if args.resume or args.sample_only:  # save-dir presence checked early
        t_restore = time.time()
        start_step, restored_ckpt, quarantined = \
            checkpoint.restore_latest(engine, args.save_dir)
        if restored_ckpt is None:
            if args.auto_resume and not args.sample_only:
                # the restart-safe mode falls back to a fresh start —
                # deterministic seeded data means the replayed
                # trajectory is the same one the lost checkpoints held
                rprint(f"--auto-resume: no restorable checkpoint under "
                       f"{args.save_dir!r}"
                       + (f" ({len(quarantined)} quarantined)"
                          if quarantined else "") + "; starting fresh")
                args.resume = False
            elif quarantined:
                # strict --resume with every checkpoint corrupt: a
                # distinct exit code so the supervisor classes this as
                # checkpoint corruption, not a generic crash
                print(f"--resume: every checkpoint under "
                      f"{args.save_dir!r} failed verification "
                      f"({len(quarantined)} quarantined)",
                      file=sys.stderr)
                raise SystemExit(EXIT_CORRUPT_CKPT)
            else:
                raise SystemExit(
                    f"--resume: no checkpoint under {args.save_dir!r}")
        else:
            restore_secs = time.time() - t_restore
            if quarantined:
                rprint(f"quarantined {len(quarantined)} corrupt "
                       f"checkpoint(s); fell back to {restored_ckpt}")
            rprint(f"resumed from {restored_ckpt} at step {start_step}")

    if not args.sample_only and start_step >= args.steps:
        raise SystemExit(
            f"checkpoint is already at step {start_step} >= --steps "
            f"{args.steps}; nothing to do")

    # run_start carries start_step so the goodput reducer can tell
    # replayed-from-checkpoint steps from fresh work after a restart
    metrics = MetricsLogger(args.log_file, dp=args.dp, sp=args.sp,
                            seq_len=args.seq_len, d_model=args.d_model,
                            n_layers=args.n_layers,
                            start_step=start_step,
                            **({"replica": args.replica}
                               if args.replica else {}))

    # ---- goodput ledger (telemetry/goodput): every non-step second is
    # stamped into the same JSONL the step lines live in — init,
    # restore, val/save pauses, data stalls, recompile/skip counts —
    # so `python -m shallowspeed_tpu.telemetry --goodput <log-file>`
    # can decompose the run's wall clock even across supervisor
    # restarts (elastic.py stamps the downtime between processes)
    from shallowspeed_tpu.telemetry.goodput import GoodputLedger

    ledger = GoodputLedger(metrics)
    if restore_secs:
        ledger.note("restore", seconds=restore_secs)

    # ---- runtime telemetry (shallowspeed_tpu/telemetry): span tracing,
    # HBM/collective/recompile step-line fields, bubble accounting
    from shallowspeed_tpu import telemetry as tele

    if args.trace_dir and args.telemetry == "off":
        args.telemetry = "steps"  # --trace-dir implies tracing
    tracer = tele.configure(trace_dir=args.trace_dir or None,
                            level=args.telemetry)
    telem = (tele.RunTelemetry(engine, tracer,
                               dtype="bf16" if args.bf16 else "f32")
             if args.telemetry != "off" else None)
    if telem is not None:
        telem.ledger = ledger  # loss totals ride telemetry.json too
        # memory observatory (round 20): register the long-lived trees
        # so step lines decompose live HBM per owner (hbm_owned_mib)
        # with the residual surfaced as hbm_untracked_mib — a growing
        # residual is the leak alarm. Resolvers, not snapshots: the
        # engine rotates/donates these trees every step.
        from shallowspeed_tpu.telemetry import memory as memlib
        memlib.register_owner(
            "train.params", lambda: getattr(engine, "params", None))
        memlib.register_owner(
            "train.opt_state", lambda: getattr(engine, "opt_state", None))
    # ---- training health (telemetry/health.py): the engines compute
    # the pack on device every step; the monitor fetches it at log
    # points, runs the anomaly detectors, and its fields ride the same
    # step lines. Heartbeats carry its verdict so the elastic
    # supervisor can restart a numerically-dead run from checkpoint.
    monitor = None
    if args.health != "off":
        from shallowspeed_tpu.telemetry.anomaly import GuardPolicy
        from shallowspeed_tpu.telemetry.health import HealthMonitor

        monitor = HealthMonitor(policy=GuardPolicy.for_mode(args.health))
    # ---- live telemetry plane (telemetry/monitor.py): streaming
    # sketches + /status.json + /metrics endpoint + SLO burn-rate
    # alerts + flight recorder, fed by every metrics line (the logger
    # forwards them), the exact StepRates window rates, chaos fault
    # stamps, and (at spans level) the tracer's phase spans
    from shallowspeed_tpu.telemetry.monitor import (close_monitor,
                                                    from_args)

    live_mon, live_srv = from_args(args, metrics)
    if live_mon is not None:
        chaos.add_observer(live_mon.note_line)
        if tracer is not None and args.telemetry != "off":
            tracer.subscribers.append(live_mon.record_span)
        if live_srv is not None:
            rprint(f"monitor: {live_srv.url('/status.json')} "
                   f"(+ /metrics)")
    # continuous profiling plane (round 17): host stack sampler into
    # the same metrics JSONL + trigger-armed capture windows; the
    # tracer's step/phase spans tag each sample via trace.PHASE_HOOKS,
    # so `--profile <log>` decomposes attrib_host_frac by name
    from shallowspeed_tpu.telemetry import profiler as profiler_mod

    plane = profiler_mod.from_args(args, metrics)
    if plane is not None:
        chaos.add_observer(plane.on_fault)
        if live_mon is not None:
            live_mon.profiler = plane
            live_mon.alert_listeners.append(plane.on_alert)
    if telem is not None and hasattr(engine, "schedule_info"):
        # pipeline engines: the verified schedule's static bubble rides
        # on every step line from the start; the measured fraction
        # (two-point calibration) joins at the first spans-level log
        si = engine.schedule_info()
        telem.set_bubble(bubble_static=tele.static_bubble(
            si["schedule"], si["n_mu"], si["pp"],
            si["vpp"])["bubble_fraction"])
    saver = checkpoint.AsyncSaver() if args.async_save else None

    def save_ckpt(ckpt_dir, step):
        extra = ({"ema": ema_canonical()} if ema is not None else None)
        keep = args.keep_checkpoints or None
        if saver is not None:
            saver.save(ckpt_dir, engine, step, extra=extra, keep=keep)
        else:
            checkpoint.save(ckpt_dir, engine, step, extra=extra,
                            keep=keep)

    def _warn_save_failed(err):
        # a failed save (ENOSPC, IO error) must not kill a healthy run:
        # the atomic-rename contract means latest() still points at the
        # previous checkpoint — keep training, name the loss in the
        # ledger so --goodput shows the widened restart exposure.
        # MULTI-PROCESS: swallowing is process-0-only state while the
        # peers already sit in the save barrier — carrying on here
        # would wedge the gang on the next mismatched collective, so
        # re-raise and let the gang supervisor restart everyone (the
        # async path's collective success-bit exchange is the
        # equivalent contract).
        if jax.process_count() > 1:
            raise err
        rprint(f"warning: checkpoint save failed ({err}); the previous "
               f"checkpoint remains the restore point")
        ledger.note("ckpt_save_failed", count=1)

    # ---- EMA of the weights: driver-owned, engine-agnostic (a pure
    # elementwise update on the engine's live params tree, whatever its
    # sharding); eval/sampling swap the averaged tree in temporarily
    from shallowspeed_tpu.optim import ema_init, ema_update

    ema = None
    ema_path = (Path(restored_ckpt) / "ema.npz"
                if restored_ckpt is not None else None)
    have_saved_ema = ema_path is not None and ema_path.exists()
    if args.ema_decay == 0.0 and have_saved_ema:
        if args.sample_only:
            # the checkpoint carries an average — sampling the raw
            # iterate instead would silently change output quality
            rprint("checkpoint has EMA weights; sampling the average "
                   "(pass --ema-decay 0 explicitly? it is the default — "
                   "delete ema.npz to sample the raw iterate)")
            args.ema_decay = -1.0  # sentinel: load + use, never update
        else:
            rprint("warning: checkpoint has ema.npz but --ema-decay is "
                   "unset; the running average will NOT be continued")
    if args.ema_decay != 0.0:
        if have_saved_ema:
            # ema.npz is stored in the CANONICAL layout (like params.npz)
            # so it survives topology changes; install it through the
            # engine's own canonical-import path, with the same structure
            # guard restore() applies to params
            host = checkpoint.load_pytree(ema_path)
            mismatch = checkpoint._structure_mismatch(
                host, engine.get_canonical_params())
            if mismatch is None:
                live = engine.params
                engine.set_canonical_params(host)
                ema = engine.params
                engine.params = live
            else:
                rprint(f"warning: ema.npz does not match this model "
                       f"({mismatch}); restarting the average from the "
                       f"restored weights")
                ema = ema_init(engine.params)
        else:
            ema = ema_init(engine.params)

    def ema_canonical():
        """The average in the engine-agnostic checkpoint layout."""
        with ema_weights():
            return engine.get_canonical_params()

    @contextlib.contextmanager
    def ema_weights():
        """Temporarily swap the averaged weights into the engine."""
        if ema is None:
            yield
            return
        live = engine.params
        engine.params = ema
        try:
            yield
        finally:
            engine.params = live

    def val_loss(step: int) -> float:
        """Held-out loss: --text tail, or a seed stream disjoint from
        training (steps are seeded [seed, step]; val uses [seed+1, ...]).
        Each call draws a FRESH batch of held-out windows — seeded by
        the TRAINING STEP (round 4: the old eval-counter seed made a
        resumed run draw different val windows than the uninterrupted
        run at the same step, so val curves were not comparable across
        restarts) — so the metric tracks the distribution, not a fixed
        handful of examples. With --ema-decay, evaluates the averaged
        weights (what you would ship), not the raw iterate."""
        val_args = args if val_data is not None else argparse.Namespace(
            **{**vars(args), "seed": args.seed + 1})
        tok, tgt = make_batch(val_args, vocab, 10**9 + step, val_data)
        with ema_weights():
            return float(engine.eval_loss(local_rows(tok),
                                          local_rows(tgt)))

    if args.sample_only:
        try:
            with ema_weights():
                sample_and_print(args, engine, cfg, vocab, text_data,
                                 tokenizer, metrics=metrics)
        finally:
            if plane is not None:
                chaos.remove_observer(plane.on_fault)
                plane.close()
            if live_mon is not None:
                chaos.remove_observer(live_mon.note_line)
                close_monitor(live_mon, live_srv)
        return float("nan")

    from shallowspeed_tpu.metrics import StepRates

    # window + cumulative tok/s with val/save time excluded from both;
    # the WINDOW rate is what step lines and step events report first
    # (the cumulative average buries the sustained rate under compile
    # time — round-4 endurance lesson). With telemetry on, every
    # log_point line additionally carries the telemetry fields.
    rates = StepRates(args.batch_size * args.seq_len, telemetry=telem,
                      health=monitor, ledger=ledger, monitor=live_mon)
    # everything before the step loop (imports, engine build, data
    # prep; restore is itemized separately) is init time
    ledger.note("init", seconds=max(0.0, time.time() - t_proc0
                                    - restore_secs))
    data_stall = 0.0  # next(placed) wait since the last log point
    last_logged = start_step - 1
    loss = float("nan")
    from shallowspeed_tpu.data.prefetch import prefetch_to_device, sync_every
    from shallowspeed_tpu.distributed import local_rows

    def batches():
        for step in range(start_step, args.steps):
            # chaos stall fault: injected HERE, in the producer, so a
            # prefetched pipeline may absorb it (that's the overlap
            # working) while --prefetch 0 must surface it as ledger
            # data_stall seconds
            chaos.on_data_load(step)
            tok, tgt = make_batch(args, vocab, step, text_data)
            # multi-host: every process builds the same seeded global batch
            # and feeds its own row-block (no-op single-process)
            yield local_rows(tok), local_rows(tgt)

    # batches are built + placed `--prefetch` steps ahead on a background
    # thread (H2D streams under the running step), and the loss stays a
    # lazy device scalar except at log points — the dispatch loop never
    # blocks on the host
    placed = prefetch_to_device(
        batches(), lambda b: (engine.place(b[0]), engine.place(b[1])),
        depth=args.prefetch)
    # the ONE jax.profiler entry point (telemetry/profiler): falsy dir
    # = no-op; an active whole-run trace makes the profiling plane's
    # capture windows skip their device half (xprof doesn't nest)
    from shallowspeed_tpu.telemetry.profiler import device_trace_ctx

    profile_ctx = device_trace_ctx(args.profile_dir)
    t_loop_done = None  # set at loop exit; teardown time is ledgered
    try:
        with profile_ctx:
            placed_it = iter(placed)
            for step in range(start_step, args.steps):
                # chaos step faults: kill / param poison / heartbeat
                # freeze, each at most once per plan (markers survive
                # supervisor restarts, so the replay runs clean)
                chaos.on_step(step, engine)
                # input-pipeline stall accounting: with prefetch ahead
                # this wait is ~0; a slow producer shows up as
                # data_stall seconds in the goodput ledger
                t_fetch = time.time()
                try:
                    tok, tgt = next(placed_it)
                except StopIteration:
                    break
                data_stall += time.time() - t_fetch
                loss_dev = engine.train_batch_async(tok, tgt)
                if ema is not None:
                    ema = ema_update(ema, engine.params, args.ema_decay)
                if sync_every(step, args.log_every, args.steps):
                    loss = float(loss_dev)
                    if monitor is not None:
                        # one device_get for the pack, then the
                        # streaming detectors; verdict fields ride the
                        # step line via StepRates(health=...)
                        verdicts = monitor.observe(
                            step, loss, engine.health_snapshot())
                        for v in verdicts:
                            rprint(str(v))
                        fatal = [v for v in verdicts
                                 if v.action == "abort"]
                        if fatal:
                            if live_mon is not None:
                                # the process exits before the next
                                # metrics line — dump the incident
                                # ring NOW, while it still exists
                                live_mon.flight_dump(
                                    "anomaly:" + ",".join(
                                        v.kind for v in fatal),
                                    step=step,
                                    trigger=[str(v) for v in fatal])
                            if args.save_dir:
                                save_ckpt(f"{args.save_dir}/diverged",
                                          step)
                                if saver is not None:
                                    saver.wait()
                            raise SystemExit(
                                f"health policy abort at step {step}: "
                                + "; ".join(v.detail for v in fatal))
                    if args.heartbeat_file \
                            and not chaos.heartbeat_frozen():
                        # liveness + health signal for the elastic
                        # supervisor: a stale mtime means a hung step
                        # loop; a 'dead ...' status means a numerically
                        # dead one (restart from the last good
                        # checkpoint either way). A chaos freeze fault
                        # suppresses the beat — the run keeps stepping
                        # and only the supervisor's staleness clock
                        # can catch it (the hang drill).
                        from shallowspeed_tpu.elastic import (
                            write_heartbeat)

                        write_heartbeat(
                            args.heartbeat_file,
                            monitor.heartbeat_status()
                            if monitor is not None else "ok")
                    if not np.isfinite(loss):
                        # failure detection: divergence gets a labeled exit
                        # (and the params snapshot when --save-dir is set)
                        # instead of silently training on NaNs
                        if live_mon is not None:
                            live_mon.flight_dump(
                                "divergence:nonfinite_loss", step=step,
                                trigger={"loss": str(loss)})
                        if args.save_dir:
                            # under diverged/ so checkpoint.latest() keeps
                            # resolving to the last GOOD checkpoint for
                            # --resume; this snapshot is forensic only
                            save_ckpt(f"{args.save_dir}/diverged", step)
                            if saver is not None:
                                saver.wait()
                            path = f"{args.save_dir}/diverged/ckpt_{step}"
                            rprint(f"diverged-state snapshot: {path}")
                        raise SystemExit(
                            f"loss became non-finite ({loss}) at step "
                            f"{step}; try --grad-clip, a lower --lr, or "
                            f"--lr-schedule with --warmup-steps")
                    r = rates.log_point(step - last_logged)
                    last_logged = step
                    if data_stall > 0.01:
                        ledger.note("data_stall", seconds=data_stall)
                    data_stall = 0.0
                    # achieved TFLOP/s + fraction-of-peak (exact matmul
                    # count per token; None off-TPU where no peak is
                    # known). Rates are GLOBAL — divide by the engine's
                    # mesh size, not one chip's peak.
                    from shallowspeed_tpu.flops import mfu as _mfu

                    n_dev = getattr(getattr(engine, "mesh", None),
                                    "devices", np.zeros(1)).size
                    kw = dict(dtype="bf16" if args.bf16 else "f32",
                              n_devices=n_dev)
                    perf = _mfu(r["tokens_per_sec"], cfg, args.seq_len,
                                **kw)
                    cum = _mfu(r["tokens_per_sec_cum"], cfg,
                               args.seq_len, **kw)
                    mfu_txt = ("" if perf["mfu"] is None else
                               f"  {perf['tflops']:.1f} TF/s "
                               f"({perf['mfu'] * 100:.1f}% MFU)")
                    rprint(f"step {step:5d}  loss {loss:.4f}  "
                           f"tok/s {r['tokens_per_sec']:,.0f}{mfu_txt}")
                    # telemetry fields ride the same step line (HBM,
                    # collective bytes/GB/s, recompiles, bubble)
                    tfields = {k: v for k, v in r.items()
                               if k not in ("tokens_per_sec",
                                            "tokens_per_sec_cum")}
                    metrics.log(event="step", step=step,
                                loss=round(loss, 6),
                                tokens_per_sec=round(
                                    r["tokens_per_sec"], 1),
                                tflops=round(perf["tflops"], 2),
                                mfu=(None if perf["mfu"] is None
                                     else round(perf["mfu"], 4)),
                                tokens_per_sec_cum=round(
                                    r["tokens_per_sec_cum"], 1),
                                tflops_cum=round(cum["tflops"], 2),
                                mfu_cum=(None if cum["mfu"] is None
                                         else round(cum["mfu"], 4)),
                                **tfields)
                    if telem is not None:
                        parts = []
                        if "bubble_measured" in tfields:
                            parts.append(
                                f"bubble {tfields['bubble_measured']:.1%}"
                                f" (static "
                                f"{tfields['bubble_static']:.1%})")
                        elif "bubble_static" in tfields:
                            parts.append(f"bubble static "
                                         f"{tfields['bubble_static']:.1%}")
                        if "coll_bytes_per_step" in tfields:
                            mib = tfields["coll_bytes_per_step"] / 2**20
                            parts.append(f"coll {mib:,.1f} MiB/step")
                        if "hbm_live_mib" in tfields:
                            parts.append(
                                f"hbm {tfields['hbm_live_mib']:,.0f}"
                                + (f"/{tfields['hbm_static_mib']:,.0f}"
                                   f" MiB" if "hbm_static_mib" in
                                   tfields else " MiB"))
                        if tfields.get("recompiles"):
                            parts.append(
                                f"RECOMPILES {tfields['recompiles']}")
                        if parts:
                            rprint("             " + "  ".join(parts))
                        if "attrib_unexplained_frac" in tfields:
                            wf = [f"compute "
                                  f"{tfields['attrib_compute_frac']:.0%}"]
                            if "attrib_comm_exposed_frac" in tfields:
                                wf.append(
                                    f"comm {tfields['attrib_comm_exposed_frac']:.0%}")
                            if "attrib_bubble_frac" in tfields:
                                wf.append(
                                    f"bubble {tfields['attrib_bubble_frac']:.0%}")
                            if "attrib_host_frac" in tfields:
                                wf.append(
                                    f"host {tfields['attrib_host_frac']:.0%}")
                            rprint(
                                "             waterfall "
                                + " + ".join(wf) + " -> unexplained "
                                + f"{tfields['attrib_unexplained_frac']:.0%}"
                                + f"  (t_step "
                                  f"{tfields['attrib_t_step_ms']:.0f} ms)")
                    if (telem is not None
                            and args.telemetry == "spans"
                            and args.pp > 1
                            and hasattr(engine, "schedule_info")
                            and "bubble_measured" not in telem.bubble):
                        # two-point bubble calibration: one extra
                        # engine compile, training state untouched;
                        # excluded from the throughput windows
                        from shallowspeed_tpu.telemetry import (
                            bubble as _bubble)

                        tc = time.time()
                        htok, htgt = make_batch(args, vocab, step,
                                                text_data)
                        cal = _bubble.calibrate_compiled(
                            engine, tracer, local_rows(htok),
                            local_rows(htgt))
                        rates.pause(time.time() - tc, kind="calibration")
                        if cal is not None:
                            telem.set_bubble(
                                bubble_static=cal["bubble_static"],
                                bubble_measured=cal["bubble_measured"])
                            metrics.log(event="bubble", step=step,
                                        **cal["bubble_detail"],
                                        bubble_static=cal[
                                            "bubble_static"],
                                        bubble_measured=cal[
                                            "bubble_measured"])
                            rprint(f"             bubble measured "
                                   f"{cal['bubble_measured']:.1%} vs "
                                   f"static {cal['bubble_static']:.1%} "
                                   f"({si['schedule']}, n_mu="
                                   f"{si['n_mu']}, pp={si['pp']})")
                    if args.experts and hasattr(engine, "router_stats"):
                        # routing observability: the capacity drop is
                        # silent in the loss (ops/moe.py), so surface it
                        rs = engine.router_stats(tok)
                        if rs is not None:
                            rprint(f"             moe drop "
                                   f"{rs['drop_fraction']:.1%}  load "
                                   f"{rs['expert_load']}")
                            metrics.log(event="moe_router", step=step,
                                        **rs)
                if args.val_every and ((step + 1) % args.val_every == 0
                                       or step == args.steps - 1):
                    # drain queued TRAIN work first, so its wall time isn't
                    # booked as val time (val points need not be log points)
                    jax.block_until_ready(loss_dev)
                    tv = time.time()
                    vl = val_loss(step)
                    rates.pause(time.time() - tv, kind="val")
                    rprint(f"step {step:5d}  val_loss {vl:.4f}  "
                           f"ppl {np.exp(min(vl, 20)):,.2f}")
                    metrics.log(event="val", step=step,
                                val_loss=round(vl, 6),
                                perplexity=round(float(np.exp(min(vl, 20))),
                                                 3))
                if args.save_dir and ((step + 1) % args.save_every == 0
                                      or step == args.steps - 1):
                    # save wall time (device->host fetch: minutes on big
                    # models over the tunnel) must not depress the next
                    # window's rate — round-4 endurance lesson
                    ts = time.time()
                    # never checkpoint a poisoned iterate: the restore
                    # point must not BE the state the supervisor is
                    # about to recover from (found by the chaos
                    # NaN-storm drill). Two signals: the monitor's
                    # last-observed pack, and THIS step's loss — a
                    # poison landing between a log point and a save
                    # would slip past the monitor alone. The float()
                    # sync is free here: the save fetches the whole
                    # state to host anyway.
                    cur_loss = float(loss_dev)
                    if (monitor is not None and monitor.unhealthy()) \
                            or not np.isfinite(cur_loss):
                        status = (monitor.heartbeat_status()
                                  if monitor is not None
                                  and monitor.unhealthy()
                                  else f"loss {cur_loss}")
                        rprint(f"step {step}: state is {status!r} — "
                               f"skipping checkpoint save")
                        ledger.note("ckpt_save_skipped_unhealthy",
                                    count=1)
                    else:
                        try:
                            save_ckpt(args.save_dir, step)
                        except (checkpoint.CheckpointError,
                                OSError) as e:
                            _warn_save_failed(e)
                        except RuntimeError as e:
                            # the async saver surfaces its worker's
                            # failure on the NEXT call, wrapped
                            if "checkpoint" not in str(e):
                                raise
                            _warn_save_failed(e)
                    rates.pause(time.time() - ts, kind="ckpt_save")
            t_loop_done = time.time()
    finally:
        # abandoning mid-stream must not leave placed batches pinned on
        # device by a blocked producer thread
        if hasattr(placed, "close"):
            placed.close()
        if telem is not None:
            tracer.close()  # flush spans.jsonl, write trace.json
            if args.trace_dir:
                path = telem.write_summary(args.trace_dir)
                rprint(f"telemetry: {path} (+ spans.jsonl, trace.json)")
        if plane is not None:
            # final profile snapshot + any in-flight capture land in
            # the outputs before the monitor's own final snapshot
            chaos.remove_observer(plane.on_fault)
            plane.close()
        if live_mon is not None:
            # final sketch snapshot into the JSONL (the offline
            # merge/parity path reads it), then stop the endpoint
            chaos.remove_observer(live_mon.note_line)
            close_monitor(live_mon, live_srv)
        if t_loop_done is not None:
            # loop exit -> here: profiler trace write, prefetch close,
            # tracer flush + summary — wall clock the ledger must name
            ledger.note("teardown",
                        seconds=max(0.0, time.time() - t_loop_done))
        if saver is not None:
            if sys.exc_info()[0] is None:
                # wait() is the COLLECTIVE failure-exchange point: if
                # process 0's background write failed, every process
                # raises here together instead of peers sailing into
                # sample_and_print's collectives against a dying rank
                saver.wait()
                saver.close()  # stop the worker; surface any IO error
            else:
                # an exception is already propagating (e.g. the divergence
                # SystemExit with its forensic-snapshot path) — don't let a
                # checkpoint-write error from close() replace it
                try:
                    saver.close()
                except Exception as ckpt_err:
                    print(f"[warn] async checkpoint save failed during "
                          f"teardown: {ckpt_err!r}", file=sys.stderr)

    plan = chaos.active()
    if plan is not None and plan.unfired():
        # a clean exit with scheduled-but-unfired faults means the
        # drill injected less than planned — say so, or a green run
        # overstates what it proved
        rprint(f"chaos: scheduled fault(s) never fired: "
               f"{', '.join(plan.unfired())}")
    if args.generate > 0:
        t_sample = time.time()
        with ema_weights():
            sample_and_print(args, engine, cfg, vocab, text_data,
                             tokenizer, metrics=metrics)
        # post-training sampling is wall-clock the goodput ledger must
        # name (decode compile alone can be seconds)
        ledger.note("sample", seconds=time.time() - t_sample)
    return loss


def sample_and_print(args, engine, cfg, vocab, text_data, tokenizer=None,
                     metrics=None):
    """KV-cache decode from the trained/restored model: --prompt (bytes,
    or BPE ids with --tokenizer bpe) or a 16-token data-stream prefix."""
    from shallowspeed_tpu.models.generate import generate
    from shallowspeed_tpu.utils import rprint

    # length already validated fail-fast at argument-checking time
    # (--prompt/--sample-only force args.generate to be set there;
    # byte count upper-bounds the BPE token count, so the check holds)
    if args.prompt:
        if tokenizer is not None:
            prompt = tokenizer.encode(args.prompt)[None, :]
        else:
            prompt = np.frombuffer(args.prompt.encode(), np.uint8).astype(
                np.int32)[None, :]
    else:
        prompt, _ = make_batch(args, vocab, 0, text_data)
        prompt = prompt[:1, :16]  # one row, short prefix
    if not args.kv_int8 and hasattr(engine, "generate") \
            and getattr(engine, "tp", 1) == 1 \
            and getattr(engine, "sp", 1) == 1 \
            and getattr(engine, "ep", 1) == 1 \
            and not getattr(engine, "fsdp", False):
        # vpp >= 1 both route here (round 5): the pipelined decode
        # walks pp*vpp logical phases, chunks in logical order
        # pipeline engine: decode ON the pp-sharded params (no re-gather
        # onto one device's memory); token-stream-identical to the
        # replicated path. --kv-int8 routes to the replicated path
        # (the quantized cache lives in models/generate only)
        t0 = time.time()
        out = engine.generate(prompt, args.generate,
                              temperature=args.temperature,
                              top_k=args.top_k, top_p=args.top_p,
                              seed=args.seed)
        out = np.asarray(out)  # drain the dispatch before timing stops
        dt = time.time() - t0
        rprint(f"decode: {prompt.shape[0] * args.generate / dt:,.0f} "
               f"tok/s (pp-sharded decode; includes prefill+compile)")
    else:
        if args.kv_int8 and hasattr(engine, "generate"):
            # the quantized cache lives in the replicated decode path
            # only — say so OUT LOUD, because this re-gathers the full
            # params onto one device (the memory cost the pipelined
            # decode exists to avoid)
            rprint("note: --kv-int8 decodes on the REPLICATED path "
                   "(full params re-gathered to one device); the "
                   "pipelined per-stage cache stays bf16 — drop "
                   "--kv-int8 to decode on the pp-sharded params")
        from shallowspeed_tpu.models.generate import (decode_report,
                                                      prompt_bucket_len)

        params = engine.get_canonical_params()
        kvq = "int8" if args.kv_int8 else ""
        # time the STEADY-STATE decode: the first call compiles and
        # prefills, so rate it over a second call's scan only when the
        # generation is long enough to care; otherwise report the
        # single-shot rate with compile included, and say so
        t0 = time.time()
        out = np.asarray(generate(
            params, prompt, cfg, args.generate,
            temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p, seed=args.seed, kv_quant=kvq))
        dt = time.time() - t0
        cache_len = prompt_bucket_len(prompt.shape[1], args.generate,
                                      cfg.max_seq) + args.generate
        rep = decode_report(params, cfg, prompt.shape[0], cache_len,
                            args.generate, dt, kv_quant=kvq)
        util = ("" if rep["hbm_util"] is None else
                f"  ({rep['hbm_util']:.0%} of the "
                f"{rep['hbm_peak_gbps']:,.0f} GB/s HBM roofline)")
        rprint(f"decode: {rep['tokens_per_sec']:,.0f} tok/s  "
               f"~{rep['bytes_per_token'] / 2**20:.1f} MiB/token sweep "
               f"-> {rep['hbm_gbps']:.1f} GB/s{util} "
               f"[includes prefill+compile]")
        if metrics is not None:
            metrics.log(event="generate", **rep)
    if tokenizer is not None:
        rprint(f"prompt: {tokenizer.decode_bytes(prompt[0])!r}")
        rprint(f"sample: {tokenizer.decode_bytes(out[0])!r}")
    else:
        rprint(f"prompt: {bytes(int(x) for x in prompt[0])!r}")
        rprint(f"sample: {bytes(int(x) for x in out[0])!r}")


if __name__ == "__main__":
    _args = parse_args()
    # same platform bootstrap as train.py (env vars alone are too late here)
    from train import configure_platform

    configure_platform(_args)
    train(_args)
