"""Telemetry subsystem gates (tier-1).

What must hold:
- span nesting + the export schema round-trip (spans.jsonl validates
  against `telemetry.schema`; trace.json is Chrome-trace shaped);
- bubble accounting: the executed-trace replay of a known 2-stage
  GPipe trace matches `verify.py`'s closed form, costed replays are
  F:B-ratio-invariant for gpipe, and the static fractions agree with
  the simulators per schedule;
- HBM live-vs-static cross-check within tolerance on a real engine;
- `--telemetry off` inserts NO fences and buffers nothing — the
  engines' async dispatch pipeline is untouched;
- the recompile counter: every VM stage executable compiles exactly
  once across batches (pins the zero_grad sharding fix this counter
  caught);
- collective traffic accounting multiplies scan trip counts.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shallowspeed_tpu.telemetry import bubble, schema
from shallowspeed_tpu.telemetry import trace as trace_mod
from shallowspeed_tpu.telemetry.report import RunTelemetry, compile_counts
from shallowspeed_tpu.telemetry.trace import Tracer, _NULL_SPAN


# ------------------------------------------------------------- spans


def test_span_nesting_and_depth():
    tr = Tracer(level="steps")
    with tr.span("step", step=3):
        with tr.span("fwd", mu=0):
            pass
        with tr.span("bwd", mu=0):
            pass
    evs = tr.events
    assert [e["name"] for e in evs] == ["fwd", "bwd", "step"]
    assert [e["depth"] for e in evs] == [1, 1, 0]
    assert evs[2]["args"] == {"step": 3}
    # children nest inside the parent's interval
    assert evs[0]["ts"] >= evs[2]["ts"]
    assert evs[0]["ts"] + evs[0]["dur"] <= evs[2]["ts"] + evs[2]["dur"]


def test_span_export_schema_roundtrip(tmp_path):
    tr = Tracer(trace_dir=tmp_path, level="steps")
    with tr.span("step", step=0):
        tr.event("marker", note="x")
        tr.counter("hbm_bytes", 123)
    tr.close()
    # streamed JSONL validates line-by-line against the schema
    assert schema.validate_file(tmp_path / "spans.jsonl") == []
    # Chrome trace: every X event has a dur, structure is loadable
    chrome = json.loads((tmp_path / "trace.json").read_text())
    phs = {e["ph"] for e in chrome["traceEvents"]}
    assert phs == {"X", "i", "C"}
    for e in chrome["traceEvents"]:
        assert ("dur" in e) == (e["ph"] == "X")


def test_schema_rejects_malformed_lines():
    assert schema.validate_line({"event": "nope"}) != []
    assert schema.validate_line({"event": "step", "step": 1}) != []
    assert schema.validate_line({"ph": "X", "name": "s", "ts": 1}) != []
    assert schema.validate_line({"what": 1}) != []
    ok_step = {"event": "step", "step": 1, "loss": 0.5,
               "tokens_per_sec": 10.0, "recompiles": 0}
    assert schema.validate_line(ok_step) == []
    assert schema.validate_line(
        {"event": "step", "step": 1, "loss": 0.5,
         "tokens_per_sec": 10.0, "recompiles": 0.5}) != []


def test_off_level_is_nullop_and_fenceless(monkeypatch):
    """`--telemetry off` must insert NO fences and buffer nothing: the
    span is the shared null object and block_until_ready is never
    reached (the engines' async dispatch stays async)."""
    def boom(*_a, **_k):  # any fence attempt explodes
        raise AssertionError("off-level telemetry fenced device work")

    monkeypatch.setattr(trace_mod, "_block", boom)
    tr = Tracer(level="off")
    sp = tr.span("step", step=0)
    assert sp is _NULL_SPAN
    with sp:
        sp.fence(object())
    tr.event("x")
    tr.counter("c", 1)
    assert tr.events == []
    # and at `steps` level fences are still skipped (dispatch preserved)
    tr2 = Tracer(level="steps")
    with tr2.span("step") as s:
        s.fence(object())
    assert len(tr2.events) == 1


def test_spans_level_fences_on_exit(monkeypatch):
    fenced = []
    monkeypatch.setattr(trace_mod, "_block",
                        lambda arrs: fenced.extend(arrs))
    tr = Tracer(level="spans")
    tok = object()
    with tr.span("step") as s:
        s.fence(tok)
    assert fenced == [tok]


# ------------------------------------------------------------- bubble


def test_gpipe_2stage_replay_matches_closed_form():
    """The satellite gate: a known 2-stage GPipe trace replayed at unit
    cost must land exactly on verify.py's closed form
    (pp-1)/(n_mu+pp-1)."""
    n_mu, pp = 4, 2
    ops = [(k, s, m, 1.0)
           for (k, s, m) in bubble._placement("gpipe", n_mu, pp)]
    rep = bubble.replay_trace(ops, pp)
    closed = (pp - 1) / (n_mu + pp - 1)
    assert rep["bubble_fraction"] == pytest.approx(closed, abs=1e-4)
    assert rep["makespan"] == 2 * (n_mu + pp - 1)
    st = bubble.static_bubble("gpipe", n_mu, pp)
    assert st["bubble_fraction"] == pytest.approx(closed, abs=1e-4)


def test_gpipe_costed_replay_is_ratio_invariant():
    """GPipe's fill and drain scale with (c_f + c_b) together, so the
    measured F:B ratio must NOT move the fraction — the property that
    makes measured-vs-static a structural check for gpipe."""
    a = bubble.costed_replay("gpipe", 8, 2, c_f=1.0, c_b=1.0)
    b = bubble.costed_replay("gpipe", 8, 2, c_f=1.0, c_b=2.7)
    assert a["bubble_fraction"] == pytest.approx(
        b["bubble_fraction"], abs=1e-3)


@pytest.mark.parametrize("schedule,n_mu,pp,vpp", [
    ("1f1b", 8, 4, 1), ("zb", 8, 4, 1), ("gpipe", 8, 2, 2)])
def test_unit_replay_matches_static(schedule, n_mu, pp, vpp):
    """Unit-cost replay of each schedule's verified placement agrees
    with the static fraction within a round of slack (the replay packs
    zero-cost waits the round model counts as whole rounds)."""
    st = bubble.static_bubble(schedule, n_mu, pp, vpp)
    rep = bubble.costed_replay(schedule, n_mu, pp, vpp)
    # same work, same placement: makespans within 10%
    assert rep["makespan"] <= st["makespan"] * 1.1 + 1
    assert rep["bubble_fraction"] == pytest.approx(
        st["bubble_fraction"], abs=0.05)


def test_replay_rejects_unsound_trace():
    ops = [("B", 0, 0, 1.0)]  # backward with no forward anywhere
    with pytest.raises(ValueError, match="dataflow"):
        bubble.replay_trace(ops, 1)


def test_trace_bubble_wall_clock():
    evs = [dict(stage=0, ts=0.0, dur=8.0), dict(stage=1, ts=1.0, dur=8.0)]
    rep = bubble.trace_bubble(evs)
    assert rep["bubble_fraction"] == pytest.approx(1 - 16 / 18, abs=1e-4)


def test_two_point_bubble_math():
    # t(n) = (n + pp - 1) * c: n=8, pp=2, c=1 -> t1=9; 2n -> t2=17
    r = bubble.two_point_bubble(9.0, 17.0)
    assert r["bubble_fraction"] == pytest.approx(1 / 9, abs=1e-6)
    assert r["t_ideal"] == pytest.approx(8.0)
    # noise pushing t2 past 2*t1 clamps at 0, never negative
    assert bubble.two_point_bubble(1.0, 2.3)["bubble_fraction"] == 0.0


def test_span_replay_ops_filtering():
    evs = [
        {"name": "Forward", "ph": "X", "ts": 0, "dur": 5,
         "args": {"stage": 0, "mu": 0, "batch": 7}},
        {"name": "BackwardGradAcc", "ph": "X", "ts": 5, "dur": 5,
         "args": {"stage": 0, "mu": 0, "batch": 7}},
        {"name": "Forward", "ph": "X", "ts": 0, "dur": 5,
         "args": {"stage": 0, "mu": 0, "batch": 8}},
        {"name": "step", "ph": "X", "ts": 0, "dur": 99, "args": {}},
    ]
    ops = bubble.span_replay_ops(evs, batch=7)
    assert ops == [("F", 0, 0, 5), ("B", 0, 0, 5)]


# -------------------------------------------------- engine integration


def _mlp_vm(pp=2, dp=1):
    from shallowspeed_tpu.models.mlp import MLPStage
    from shallowspeed_tpu.optim import SGD
    from shallowspeed_tpu.parallel.mesh import make_mesh
    from shallowspeed_tpu.parallel.worker import PipelineExecutor

    mesh = make_mesh(dp, pp)
    stages = [MLPStage([12, 14, 13, 10], s, pp, batch_size=16)
              for s in range(pp)]
    return PipelineExecutor(mesh, stages, SGD(0.1))


class _DS:
    def __init__(self, rank=0, rows=4):
        self.rank, self.rows = rank, rows

    def load_micro_batch_input(self, b, mu):
        rng = np.random.default_rng([b, mu, self.rank])
        return rng.standard_normal((self.rows, 12)).astype(np.float32)

    def load_micro_batch_target(self, b, mu):
        y = np.zeros((self.rows, 10), np.float32)
        y[:, 0] = 1.0
        return y


def test_vm_executables_compile_exactly_once():
    """The recompile counter's first catch, pinned: the zero-grad
    accumulator must be born with the steady-state sharding, or the
    second BackwardGradAcc of every batch recompiles each stage's
    backward (worker.StageRuntime._zeros_acc)."""
    from shallowspeed_tpu.parallel.schedules import GPipeSchedule

    eng = _mlp_vm()
    for b in range(3):
        eng.train_batch(GPipeSchedule, 4, b, [_DS()])
    counts = compile_counts(eng.telemetry_entrypoints())
    exercised = {k: v for k, v in counts.items() if v > 0}
    assert exercised, "VM published no exercised entrypoints"
    multi = {k: v for k, v in exercised.items() if v > 1}
    assert not multi, f"VM executables recompiled: {multi}"


def test_vm_spans_replay_to_bubble():
    """At the `spans` level the VM's fenced per-op spans ARE the
    executed schedule trace: the replay consumes them and yields a
    bubble fraction; op count matches the schedule's compute ops."""
    from shallowspeed_tpu.parallel.schedules import GPipeSchedule

    tr = trace_mod.configure(level="spans")
    try:
        eng = _mlp_vm()
        n_mu = 4
        eng.train_batch(GPipeSchedule, n_mu, 0, [_DS()])
        ops = bubble.span_replay_ops(tr.events, batch=0)
        # pp stages x n_mu forwards + n_mu backwards each
        assert len(ops) == 2 * 2 * n_mu
        rep = bubble.replay_trace(ops, 2)
        assert 0.0 <= rep["bubble_fraction"] < 1.0
        assert rep["n_stages"] == 2
        # measured comm accounting counted the stage hops
        traffic = eng.telemetry_traffic()
        assert traffic.get("pp_p2p", 0) > 0
        assert traffic.get("dp_psum", 0) > 0
    finally:
        trace_mod.configure(level="off")


def test_run_telemetry_hbm_cross_check_and_traffic():
    """A real engine end-to-end: static report exists after one step,
    live HBM stays within the static bound, collective bytes per axis
    are positive, recompiles stay 0 across steps."""
    from jax.sharding import Mesh

    from shallowspeed_tpu.models.transformer import TransformerConfig
    from shallowspeed_tpu.optim import Adam
    from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine

    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=4,
                            max_seq=32)
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(1, 2), ("dp", "pp"))
    eng = PipelineLMEngine(cfg, Adam(1e-3), mesh, n_mubatches=2)
    rt = RunTelemetry(eng)
    rng = np.random.default_rng(0)
    tok = rng.integers(0, 64, (4, 16)).astype(np.int32)
    tgt = np.roll(tok, -1, 1).astype(np.int32)
    # skeleton capture is gated on an active tracer (the off path must
    # pay nothing) — run the steps under a steps-level tracer
    trace_mod.configure(level="steps")
    try:
        for _ in range(3):
            eng.train_batch(tok, tgt)
    finally:
        trace_mod.configure(level="off")
    fields = rt.step_fields(window_secs=1.0, steps_in_window=3)
    assert fields["recompiles"] == 0
    assert fields["hbm_within_bound"], fields
    assert fields["hbm_live_mib"] > 0
    assert fields["coll_bytes_per_step"] > 0
    assert "pp" in fields["coll_bytes_by_axis"]
    assert fields["coll_gbps"] > 0
    # the step line validates as a metrics step event
    line = {"event": "step", "step": 2, "loss": 1.0,
            "tokens_per_sec": 1.0, **fields}
    line.pop("coll_bytes_by_axis")
    assert schema.validate_line(line) == []
    summary = rt.run_summary()
    assert summary["hbm_check"]["within_bound"]


def test_memory_cross_check_tolerance():
    from shallowspeed_tpu.telemetry import memory

    assert memory.cross_check(100, 100)["within_bound"]
    assert memory.cross_check(104, 100)["within_bound"]  # inside 1.05
    assert not memory.cross_check(120, 100)["within_bound"]


# -------------------------------------------------------- collectives


def test_collective_traffic_counts_scan_trips():
    from functools import partial

    from jax.sharding import Mesh, PartitionSpec as P

    from shallowspeed_tpu.telemetry.collectives import collective_traffic
    from shallowspeed_tpu.utils import shard_map

    mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    def fn(x):
        def body(c, xi):
            return c + jax.lax.psum(xi, "dp"), None

        c, _ = jax.lax.scan(body, jnp.zeros_like(x[0]), x)
        return c[None] + jax.lax.psum(x, "dp")

    x = jax.ShapeDtypeStruct((6, 8), np.float32)  # 3 rows/device
    rep = collective_traffic(fn, x)
    dp = rep["per_axis"]["dp"]
    # scan runs 3 iterations of an 8-float psum + one 3x8 psum outside
    assert dp["calls"] == 4
    assert dp["bytes"] == 3 * 8 * 4 + 3 * 8 * 4
    assert not rep["approximate"]


def test_steprates_merges_telemetry_fields():
    from shallowspeed_tpu.metrics import StepRates

    class FakeTelem:
        def step_fields(self, window_secs=None, steps_in_window=None):
            return {"recompiles": 0, "bubble_static": 0.2,
                    "win": window_secs, "n": steps_in_window}

    # 3 ticks: init, log_point's `now`, and the post-telemetry tick
    # that books telemetry's own cost as excluded pause time
    clock = iter([0.0, 10.0, 12.0, 20.0, 21.0]).__next__
    rates = StepRates(100.0, clock=clock, telemetry=FakeTelem())
    r = rates.log_point(5)
    assert r["tokens_per_sec"] == pytest.approx(50.0)
    assert r["bubble_static"] == 0.2
    assert r["n"] == 5 and r["win"] == pytest.approx(10.0)
    # the 2s the telemetry fields took is excluded from window 2
    r2 = rates.log_point(4)
    assert r2["tokens_per_sec"] == pytest.approx(400 / 8.0)


def test_replay_rejects_mixed_window_and_pads_partial_capture():
    # two epochs' worth of the same op in one window -> rejected
    ops = [("F", 0, 0, 1.0), ("F", 0, 0, 1.0)]
    with pytest.raises(ValueError, match="duplicate"):
        bubble.replay_trace(ops, 1)
    # a partial capture (stage 1's spans missing) counts the absent
    # processor as idle instead of reporting a 1-deep pipeline
    ops = [("F", 0, m, 1.0) for m in range(4)]
    rep = bubble.replay_trace(ops, 2)
    assert rep["n_stages"] == 2
    assert rep["bubble_fraction"] == pytest.approx(0.5, abs=1e-4)
    # and naming more processors than the pipeline has is mislabeling
    with pytest.raises(ValueError, match="mislabeled"):
        bubble.replay_trace([("F", 0, 0, 1.0), ("F", 1, 0, 1.0)], 1)


def test_tracer_event_windows_survive_buffer_eviction(monkeypatch):
    monkeypatch.setattr(trace_mod, "_BUF_CAP", 4)
    tr = Tracer(level="steps")
    tr._events = __import__("collections").deque(maxlen=4)
    for i in range(10):
        tr.event("e", i=i)
    assert tr.event_count == 10
    # a window starting inside the buffer returns exactly that suffix
    assert [e["args"]["i"] for e in tr.events_since(8)] == [8, 9]
    # a window starting before the eviction point returns what remains
    assert [e["args"]["i"] for e in tr.events_since(2)] == [6, 7, 8, 9]


def test_chrome_trace_sources_full_stream_from_jsonl(tmp_path,
                                                     monkeypatch):
    """trace.json must carry the COMPLETE stream even when the RAM
    buffer evicted early events (spans.jsonl is the source of truth)."""
    tr = Tracer(trace_dir=tmp_path, level="steps")
    tr._events = __import__("collections").deque(maxlen=2)
    for i in range(6):
        tr.event("e", i=i)
    tr.close()
    chrome = json.loads((tmp_path / "trace.json").read_text())
    assert len(chrome["traceEvents"]) == 6


def test_make_calibration_twin_trains_at_double_n_mu():
    """The on-chip two-point path: the twin must construct (pinning
    the 11-arg constructor call against signature drift), run a step
    on a row-doubled batch, and leave the live engine untouched."""
    from jax.sharding import Mesh

    from shallowspeed_tpu.models.transformer import TransformerConfig
    from shallowspeed_tpu.optim import SGD
    from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine

    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=4,
                            max_seq=32)
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(1, 2), ("dp", "pp"))
    eng = PipelineLMEngine(cfg, SGD(0.1), mesh, n_mubatches=2)
    twin = eng.make_calibration_twin()
    assert twin.n_mu == 2 * eng.n_mu
    assert (twin.schedule, twin.pp, twin.vpp) == (
        eng.schedule, eng.pp, eng.vpp)
    rng = np.random.default_rng(0)
    tok = rng.integers(0, 64, (4, 16)).astype(np.int32)
    tgt = np.roll(tok, -1, 1).astype(np.int32)
    before = eng._step_count
    tok2 = np.concatenate([tok, tok], axis=0)
    tgt2 = np.concatenate([tgt, tgt], axis=0)
    loss = twin.train_batch(tok2, tgt2)
    assert np.isfinite(loss)
    assert eng._step_count == before  # live trajectory untouched
