"""Compiled ZB-H1 zero-bubble schedule (`parallel/pipeline_lm.py`,
`schedule="zb"`; split backward in `parallel/zb.py`; tables in
`verify.zb_tables`).

Round-4's pinned decision said the compiled form loses while a dw-only
vjp must re-run the forward; round 5 hand-writes the per-block dW/dx
split, flipping the decision (see test_schedule_verify.py). Oracles:
the same gradient-sum equivalence every schedule is held to (gpipe /
plain-dp trajectory parity), plus a pure-python replay that the static
tables execute the exact schedule `simulate_zb` verified.
"""

from dataclasses import replace

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.optim import SGD, Adam
from shallowspeed_tpu.parallel.context import ContextParallelEngine
from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine
from shallowspeed_tpu.parallel.verify import simulate_zb, zb_tables

CFG = T.TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=4,
                          max_seq=32)


def pp_mesh(dp, pp):
    devs = np.array(jax.devices()[: dp * pp]).reshape(dp, pp)
    return Mesh(devs, ("dp", "pp"))


def batch(seed=0, b=8, t=32, vocab=64):
    rng = np.random.default_rng(seed)
    tok = rng.integers(0, vocab, (b, t)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1).astype(np.int32)


# ------------------------------------------------------- tables replay


@pytest.mark.parametrize("n_mu,pp", [(4, 2), (8, 4), (12, 3)])
def test_zb_tables_replay(n_mu, pp):
    """Pure-python execution of the static tables: every F/B/W runs
    exactly once, every read sees the matching write (act/grad messages
    and all three stash pools), and the round count IS the simulator's
    verified makespan."""
    tb = zb_tables(n_mu, pp)
    rep = simulate_zb(n_mu, pp)
    assert tb.n_rounds == rep.makespan

    act = [[None] * (tb.n_act_slots + 1) for _ in range(pp)]
    grad = [[None] * (tb.n_grad_slots + 1) for _ in range(pp)]
    resb = [[None] * (tb.n_resb_slots + 1) for _ in range(pp)]
    resw = [[None] * (tb.n_resw_slots + 1) for _ in range(pp)]
    tap = [[None] * (tb.n_tap_slots + 1) for _ in range(pp)]
    seen = {"F": set(), "B": set(), "W": set()}
    for r in range(tb.n_rounds):
        out_act = [None] * pp
        out_grad = [None] * pp
        for d in range(pp):
            op, m = tb.op[r, d], tb.mu[r, d]
            if op == 1:                                   # F
                if d > 0:
                    assert act[d][tb.act_read[r, d]] == ("act", d, m), \
                        (r, d, m)
                resb[d][tb.resb_write[r, d]] = ("resb", d, m)
                resw[d][tb.resw_write[r, d]] = ("resw", d, m)
                out_act[d] = ("act", d + 1, m)
                seen["F"].add((d, m))
            elif op == 2:                                 # B
                if d < pp - 1:
                    assert grad[d][tb.grad_read[r, d]] == \
                        ("grad", d, m), (r, d, m)
                assert resb[d][tb.resb_read[r, d]] == ("resb", d, m)
                assert resw[d][tb.resw_read_b[r, d]] == ("resw", d, m)
                tap[d][tb.tap_write[r, d]] = ("tap", d, m)
                out_grad[d] = ("grad", d - 1, m)
                seen["B"].add((d, m))
            elif op == 3:                                 # W
                assert resw[d][tb.resw_read[r, d]] == ("resw", d, m)
                assert tap[d][tb.tap_read[r, d]] == ("tap", d, m)
                seen["W"].add((d, m))
        for d in range(pp):                               # the hops
            src = out_act[(d - 1) % pp]
            act[d][tb.act_write[r, d]] = src
            srcg = out_grad[(d + 1) % pp]
            grad[d][tb.grad_write[r, d]] = srcg
    full = {(d, m) for d in range(pp) for m in range(n_mu)}
    assert seen["F"] == full and seen["B"] == full and seen["W"] == full


def test_zb_beats_1f1b_makespan_at_flagship_size():
    """The VERDICT's bar: a makespan/bubble win at pp=4, n_mu >= 8 —
    now from the COMPILED tables (what executes), not just the sim."""
    tb = zb_tables(8, 4)
    rep = simulate_zb(8, 4)
    assert tb.n_rounds < rep.f1b1_makespan
    assert rep.bubble < rep.f1b1_bubble


# ---------------------------------------------------------- equivalence


@pytest.mark.parametrize("dp,pp,n_mu", [(1, 4, 8), (2, 2, 4), (1, 2, 6),
                                        (2, 4, 2)])
def test_zb_matches_plain_dp(dp, pp, n_mu):
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("dp", "sp"))
    ref = ContextParallelEngine(CFG, SGD(0.1), mesh, seed=0)
    eng = PipelineLMEngine(CFG, SGD(0.1), pp_mesh(dp, pp),
                           n_mubatches=n_mu, seed=0, schedule="zb")
    for step in range(4):
        tok, tgt = batch(step, b=8 if n_mu != 6 else 24)
        lr_ = ref.train_batch(tok, tgt)
        lz = eng.train_batch(tok, tgt)
        assert lz == pytest.approx(lr_, rel=3e-4), (step, dp, pp, n_mu)
    for a, b in zip(
            jax.tree_util.tree_leaves(eng.get_canonical_params()),
            jax.tree_util.tree_leaves(ref.get_canonical_params())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-5)


@pytest.mark.parametrize("kw,attn", [
    (dict(), "xla"),
    (dict(norm="rmsnorm", ffn="swiglu", rope=True), "xla"),
    (dict(n_kv_heads=2, rope=True), "flash"),
    (dict(attn_window=16), "flash"),
    (dict(tie_embeddings=True, xent_chunk=64), "xla"),
])
def test_zb_matches_gpipe_exactly(kw, attn):
    """Same engine, same data placement, two schedules: agreement to
    float reassociation tolerance across the block-option matrix the
    split backward supports."""
    cfg = replace(CFG, **kw)
    g = PipelineLMEngine(cfg, SGD(0.1), pp_mesh(1, 4), n_mubatches=8,
                         seed=0, schedule="gpipe", attn=attn)
    z = PipelineLMEngine(cfg, SGD(0.1), pp_mesh(1, 4), n_mubatches=8,
                         seed=0, schedule="zb", attn=attn)
    for step in range(3):
        tok, tgt = batch(step)
        assert z.train_batch(tok, tgt) == pytest.approx(
            g.train_batch(tok, tgt), rel=1e-5), (step, kw, attn)


def test_zb_zero1_matches_dense():
    """ZeRO-1 composes: the update program shards moments over dp while
    the zb gradient program is unchanged."""
    g = PipelineLMEngine(CFG, Adam(1e-2), pp_mesh(2, 2), n_mubatches=4,
                         seed=0, schedule="zb")
    z = PipelineLMEngine(CFG, Adam(1e-2), pp_mesh(2, 2), n_mubatches=4,
                         seed=0, schedule="zb", zero1=True)
    for step in range(3):
        tok, tgt = batch(step)
        assert z.train_batch(tok, tgt) == pytest.approx(
            g.train_batch(tok, tgt), rel=1e-5), step


@pytest.mark.parametrize("flavor", ["zero2", "fsdp"])
def test_zb_zero_family_matches_dense(flavor):
    """ZeRO-2 / FSDP x zb (round 5): the zb scan hands raw per-device
    partials to the same grad_reduce substitution the 1F1B scan takes,
    so the dp reduce-scatter (and fsdp's transient param gather) drop
    in unchanged — trajectories bit-match the dense zb run."""
    dense = PipelineLMEngine(CFG, Adam(1e-2), pp_mesh(2, 2),
                             n_mubatches=4, seed=0, schedule="zb")
    z = PipelineLMEngine(CFG, Adam(1e-2), pp_mesh(2, 2), n_mubatches=4,
                         seed=0, schedule="zb",
                         zero2=flavor == "zero2", fsdp=flavor == "fsdp")
    for step in range(3):
        tok, tgt = batch(step)
        assert z.train_batch(tok, tgt) == pytest.approx(
            dense.train_batch(tok, tgt), rel=1e-5), (flavor, step)


def test_zb_bf16_trains():
    cfg = replace(CFG, dtype=np.float32,
                  compute_dtype=np.dtype("bfloat16"))
    eng = PipelineLMEngine(cfg, SGD(0.1), pp_mesh(1, 2), n_mubatches=4,
                           seed=0, schedule="zb")
    tok, tgt = batch(0)
    first = eng.train_batch(tok, tgt)
    for step in range(1, 4):
        tok, tgt = batch(0)
        last = eng.train_batch(tok, tgt)
    assert np.isfinite(first) and last < first


# ------------------------------------------------- pinned carve-outs


@pytest.mark.parametrize("build", [
    lambda: PipelineLMEngine(
        CFG, SGD(0.1),
        Mesh(np.array(jax.devices()[:4]).reshape(1, 2, 2),
             ("dp", "pp", "tp")), n_mubatches=2, schedule="zb"),
    lambda: PipelineLMEngine(
        CFG, SGD(0.1),
        Mesh(np.array(jax.devices()[:4]).reshape(1, 2, 2),
             ("dp", "pp", "sp")), n_mubatches=2, schedule="zb",
        attn="ring"),
    lambda: PipelineLMEngine(CFG, SGD(0.1), pp_mesh(1, 2),
                             n_mubatches=2, schedule="zb",
                             virtual_pp=2),
    lambda: PipelineLMEngine(replace(CFG, n_experts=4), SGD(0.1),
                             pp_mesh(1, 2), n_mubatches=2,
                             schedule="zb"),
    lambda: PipelineLMEngine(replace(CFG, dropout=0.1), SGD(0.1),
                             pp_mesh(1, 2), n_mubatches=2,
                             schedule="zb"),
    lambda: PipelineLMEngine(replace(CFG, remat=True), SGD(0.1),
                             pp_mesh(1, 2), n_mubatches=2,
                             schedule="zb"),
])
def test_zb_carveouts_are_pinned(build):
    """Every constructor exclusion fails fast with its mechanism named
    (the executable-negative-decision style the ZB lineage set)."""
    with pytest.raises(AssertionError, match="zb"):
        build()
