"""Transformer pipeline parallelism (`parallel/pipeline_lm.py`).

Oracle: GPipe over ('dp', 'pp') computes the SAME global-mean NLL and
gradient as non-pipelined training (the microbatch split is exact for
mean-of-equal-means), so every (dp, pp, n_mu) layout must match the
plain data-parallel context engine step for step.
"""

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.optim import SGD, Adam
from shallowspeed_tpu.parallel.context import ContextParallelEngine
from shallowspeed_tpu.parallel.pipeline_lm import (
    PipelineLMEngine, stack_blocks, unstack_blocks)

CFG = T.TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=4,
                          max_seq=32)


def pp_mesh(dp, pp):
    devs = np.array(jax.devices()[: dp * pp]).reshape(dp, pp)
    return Mesh(devs, ("dp", "pp"))


def batch(seed=0, b=8, t=32, vocab=64):
    rng = np.random.default_rng(seed)
    tok = rng.integers(0, vocab, (b, t)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1).astype(np.int32)


def ref_engine(opt):
    """Plain DP oracle: context engine with sp=1 (no sequence sharding)."""
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("dp", "sp"))
    return ContextParallelEngine(CFG, opt, mesh, seed=0)


# ------------------------------------------------------------ structure


def test_stack_unstack_roundtrip():
    params = T.init(CFG, seed=1)
    rt = unstack_blocks(stack_blocks(params), CFG.n_layers)
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(rt)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_blocks_sharded_over_pp():
    eng = PipelineLMEngine(CFG, Adam(1e-3), pp_mesh(2, 4))
    blk = eng.params["blocks"]["qkv"]["W"]          # (L, d, 3d)
    assert "pp" in blk.sharding.spec
    assert blk.addressable_shards[0].data.shape[0] == CFG.n_layers // 4
    assert eng.params["tok_emb"].sharding.spec == ()  # replicated
    # Adam moments follow the placement
    assert (eng.opt_state["m"]["blocks"]["qkv"]["W"].sharding
            == blk.sharding)


def test_moe_tp_rejected():
    """MoE composes with dp/pp/sp in this engine — tp is the one axis it
    does not take."""
    devs = np.array(jax.devices()[:4]).reshape(1, 2, 2)
    with pytest.raises(AssertionError, match="MoE x tp"):
        PipelineLMEngine(replace(CFG, n_experts=4), Adam(1e-3),
                         Mesh(devs, ("dp", "pp", "tp")))


def test_indivisible_layers_rejected():
    with pytest.raises(AssertionError, match="divisible by pp"):
        PipelineLMEngine(replace(CFG, n_layers=3), Adam(1e-3),
                         pp_mesh(1, 4))


# ---------------------------------------------------------- equivalence


@pytest.mark.parametrize("dp,pp,n_mu", [(1, 4, 4), (2, 4, 2), (4, 2, 2),
                                        (2, 2, 1)])
def test_pipeline_matches_plain_dp(dp, pp, n_mu):
    ref = ref_engine(SGD(0.1))
    eng = PipelineLMEngine(CFG, SGD(0.1), pp_mesh(dp, pp),
                           n_mubatches=n_mu, seed=0)
    for step in range(4):
        tok, tgt = batch(step)
        lr_ = ref.train_batch(tok, tgt)
        lp = eng.train_batch(tok, tgt)
        assert lp == pytest.approx(lr_, rel=3e-4), (step, dp, pp, n_mu)
    ref_p = ref.get_canonical_params()
    pipe_p = eng.get_canonical_params()
    for a, b in zip(jax.tree_util.tree_leaves(pipe_p),
                    jax.tree_util.tree_leaves(ref_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-5)


def test_pipeline_with_adam_and_clip():
    ref = ref_engine(Adam(1e-2, grad_clip=0.5))
    eng = PipelineLMEngine(CFG, Adam(1e-2, grad_clip=0.5), pp_mesh(2, 4),
                           n_mubatches=2, seed=0)
    for step in range(4):
        tok, tgt = batch(step)
        assert eng.train_batch(tok, tgt) == pytest.approx(
            ref.train_batch(tok, tgt), rel=3e-4), step


def test_eval_loss_matches():
    ref = ref_engine(SGD(0.1))
    eng = PipelineLMEngine(CFG, SGD(0.1), pp_mesh(2, 4), n_mubatches=2,
                           seed=0)
    tok, tgt = batch(11)
    assert eng.eval_loss(tok, tgt) == pytest.approx(
        ref.eval_loss(tok, tgt), rel=3e-4)


# ----------------------------------------------------- compose features


def test_pipeline_bf16_remat_trains():
    cfg = replace(CFG, compute_dtype=jnp.bfloat16, remat=True)
    eng = PipelineLMEngine(cfg, Adam(5e-3), pp_mesh(2, 4), n_mubatches=2,
                           seed=0)
    tok, tgt = batch(7)
    losses = [eng.train_batch(tok, tgt) for _ in range(20)]
    assert losses[-1] < losses[0] - 0.15, losses[::5]
    for leaf in jax.tree_util.tree_leaves(eng.params):
        assert leaf.dtype == jnp.float32


def test_pipeline_checkpoint_roundtrip(tmp_path):
    from shallowspeed_tpu import checkpoint

    eng = PipelineLMEngine(CFG, Adam(1e-2), pp_mesh(2, 4), n_mubatches=2,
                           seed=0)
    tok, tgt = batch(3)
    for _ in range(2):
        eng.train_batch(tok, tgt)
    checkpoint.save(str(tmp_path), eng, 2)
    # restore into a DIFFERENT topology: canonical format is engine-agnostic
    eng2 = PipelineLMEngine(CFG, Adam(1e-2), pp_mesh(1, 2), n_mubatches=4,
                            seed=1)
    assert checkpoint.restore(eng2, checkpoint.latest(str(tmp_path))) == 3
    l1 = eng.train_batch(tok, tgt)
    l2 = eng2.train_batch(tok, tgt)
    assert l1 == pytest.approx(l2, rel=1e-3)


# -------------------------------------------------- pp x tp composition


def pp_tp_mesh(dp, pp, tp):
    devs = np.array(jax.devices()[: dp * pp * tp]).reshape(dp, pp, tp)
    return Mesh(devs, ("dp", "pp", "tp"))


@pytest.mark.parametrize("dp,pp,tp,n_mu", [(1, 2, 2, 2), (2, 2, 2, 1),
                                           (1, 2, 4, 2)])
def test_pp_tp_matches_plain_dp(dp, pp, tp, n_mu):
    """dp x pp x tp on one mesh must reproduce the serial trajectory:
    Megatron column/row placement inside each pipeline stage, explicit
    psum over 'tp'."""
    ref = ref_engine(SGD(0.1))
    eng = PipelineLMEngine(CFG, SGD(0.1), pp_tp_mesh(dp, pp, tp),
                           n_mubatches=n_mu, seed=0)
    for step in range(4):
        tok, tgt = batch(step)
        lr_ = ref.train_batch(tok, tgt)
        lp = eng.train_batch(tok, tgt)
        assert lp == pytest.approx(lr_, rel=3e-4), (step, dp, pp, tp)
    for a, b in zip(jax.tree_util.tree_leaves(eng.get_canonical_params()),
                    jax.tree_util.tree_leaves(ref.get_canonical_params())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-5)


def test_pp_tp_blocks_sharded_both_axes():
    eng = PipelineLMEngine(CFG, Adam(1e-3), pp_tp_mesh(1, 2, 2),
                           n_mubatches=2)
    qkv = eng.params["blocks"]["qkv"]["W"]          # (L, d, 3d)
    assert set(a for a in qkv.sharding.spec if a) == {"pp", "tp"}
    shard = qkv.addressable_shards[0].data
    assert shard.shape == (CFG.n_layers // 2, CFG.d_model,
                           3 * CFG.d_model // 2)
    proj = eng.params["blocks"]["proj"]["W"].sharding.spec
    assert proj == ("pp", "tp", None) or tuple(proj) == ("pp", "tp")


def test_pp_tp_with_clip_matches_serial():
    """Mixed-variance clipping: block grads vary over (pp, tp), embed/head
    grads are replicated — the VMA-aware norm must agree with serial."""
    ref = ref_engine(Adam(1e-2, grad_clip=0.5))
    eng = PipelineLMEngine(CFG, Adam(1e-2, grad_clip=0.5),
                           pp_tp_mesh(2, 2, 2), n_mubatches=2, seed=0)
    for step in range(3):
        tok, tgt = batch(step)
        assert eng.train_batch(tok, tgt) == pytest.approx(
            ref.train_batch(tok, tgt), rel=3e-4), step


def test_pp_tp_bf16_remat_trains():
    cfg = replace(CFG, compute_dtype=jnp.bfloat16, remat=True)
    eng = PipelineLMEngine(cfg, Adam(5e-3), pp_tp_mesh(2, 2, 2),
                           n_mubatches=2, seed=0)
    tok, tgt = batch(7)
    losses = [eng.train_batch(tok, tgt) for _ in range(20)]
    assert losses[-1] < losses[0] - 0.15, losses[::5]


# ------------------------------------------------- flash attention in --pp


@pytest.mark.parametrize("sched", ["gpipe", "1f1b"])
def test_pipeline_flash_matches_plain_dp(sched):
    """The fused Pallas kernel inside each pipeline stage (interpret mode
    on CPU — the same code path Mosaic compiles on TPU) must reproduce
    the XLA-attention oracle under BOTH backward derivations."""
    ref = ref_engine(SGD(0.1))
    eng = PipelineLMEngine(CFG, SGD(0.1), pp_mesh(1, 4), n_mubatches=2,
                           seed=0, schedule=sched, attn="flash")
    for step in range(2):
        tok, tgt = batch(step)
        assert eng.train_batch(tok, tgt) == pytest.approx(
            ref.train_batch(tok, tgt), rel=5e-4), (sched, step)


def test_pipeline_flash_with_tp_trains():
    import jax.numpy as _jnp

    cfg = replace(CFG, compute_dtype=_jnp.bfloat16)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                ("dp", "pp", "tp"))
    eng = PipelineLMEngine(cfg, Adam(5e-3), mesh, n_mubatches=2, seed=0,
                           attn="flash")
    tok, tgt = batch(7)
    losses = [eng.train_batch(tok, tgt) for _ in range(10)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses[::3]


# ------------------------------------------- round 3: pp x sp and pp x MoE


def pp_sp_mesh(dp, pp, sp):
    devs = np.array(jax.devices()[: dp * pp * sp]).reshape(dp, pp, sp)
    return Mesh(devs, ("dp", "pp", "sp"))


@pytest.mark.parametrize("dp,pp,sp,n_mu,sched", [
    (1, 2, 2, 2, "gpipe"), (1, 2, 2, 2, "1f1b"), (2, 2, 2, 1, "gpipe"),
    (1, 2, 4, 2, "1f1b"),
])
def test_pp_sp_matches_plain_dp(dp, pp, sp, n_mu, sched):
    """Sequence sharding INSIDE pipeline stages (ring attention over
    'sp', global positions per tile) must reproduce the serial
    trajectory under both schedules — the composability the round-2
    verdict flagged as missing (long context and pp were mutually
    exclusive)."""
    ref = ref_engine(SGD(0.1))
    eng = PipelineLMEngine(CFG, SGD(0.1), pp_sp_mesh(dp, pp, sp),
                           n_mubatches=n_mu, seed=0, schedule=sched,
                           attn="ring")
    for step in range(3):
        tok, tgt = batch(step)
        lr_ = ref.train_batch(tok, tgt)
        lp = eng.train_batch(tok, tgt)
        assert lp == pytest.approx(lr_, rel=3e-4), (step, dp, pp, sp)
    for a, b in zip(jax.tree_util.tree_leaves(eng.get_canonical_params()),
                    jax.tree_util.tree_leaves(ref.get_canonical_params())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-5)


def test_pp_sp_rope_positions_global():
    """RoPE under sp sharding uses GLOBAL positions: parity vs serial
    with rope on would fail if each sp tile restarted at position 0."""
    cfg = replace(CFG, rope=True, norm="rmsnorm")
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("dp", "sp"))
    ref = ContextParallelEngine(cfg, SGD(0.1), mesh, seed=0)
    eng = PipelineLMEngine(cfg, SGD(0.1), pp_sp_mesh(1, 2, 2),
                           n_mubatches=2, seed=0, attn="ring")
    for step in range(2):
        tok, tgt = batch(step)
        assert eng.train_batch(tok, tgt) == pytest.approx(
            ref.train_batch(tok, tgt), rel=3e-4), step


def test_pp_sp_ring_flash_trains():
    """The Pallas ring-flash kernel as the stage substrate (interpret
    mode on CPU) composes with the pipeline: finite + decreasing."""
    import jax.numpy as _jnp

    cfg = replace(CFG, compute_dtype=_jnp.bfloat16)
    eng = PipelineLMEngine(cfg, Adam(5e-3), pp_sp_mesh(1, 2, 2),
                           n_mubatches=2, seed=0, attn="ring-flash")
    tok, tgt = batch(7)
    losses = [eng.train_batch(tok, tgt) for _ in range(8)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses[::2]


MOE_CFG = replace(CFG, n_experts=4, moe_top_k=2, moe_capacity_factor=2.0,
                  n_layers=2)


def moe_ref_engine(opt, cfg):
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("dp", "sp"))
    return ContextParallelEngine(cfg, opt, mesh, seed=0)


@pytest.mark.parametrize("sched", ["gpipe", "1f1b"])
def test_pp_moe_matches_plain_n_mu1(sched):
    """MoE x pp with ONE microbatch is exactly the non-pipelined MoE
    step (same routing set, same balance/z aux, every stage's aux
    collected) — the lifted round-2 assert, both schedules."""
    ref = moe_ref_engine(SGD(0.1), MOE_CFG)
    eng = PipelineLMEngine(MOE_CFG, SGD(0.1), pp_mesh(1, 2),
                           n_mubatches=1, seed=0, schedule=sched)
    for step in range(3):
        tok, tgt = batch(step)
        lr_ = ref.train_batch(tok, tgt)
        lp = eng.train_batch(tok, tgt)
        assert lp == pytest.approx(lr_, rel=3e-4), (sched, step)
    for a, b in zip(jax.tree_util.tree_leaves(eng.get_canonical_params()),
                    jax.tree_util.tree_leaves(ref.get_canonical_params())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-5)


def test_pp_moe_microbatched_trains():
    """n_mu > 1: per-microbatch routing/aux (documented divergence from
    the full-batch aux — the balance loss is nonlinear in batch
    composition), so assert training works rather than exact parity."""
    eng = PipelineLMEngine(MOE_CFG, Adam(5e-3), pp_mesh(2, 2),
                           n_mubatches=2, seed=0)
    tok, tgt = batch(5)
    losses = [eng.train_batch(tok, tgt) for _ in range(10)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses[::3]


def test_pp_moe_sp_composes():
    """All three: experts in the stage FFN, sequence sharded over 'sp',
    stages over 'pp'. Oracle: the context engine at the SAME sp tiling
    (routing is per-tile in both, so n_mu=1 parity is exact)."""
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(1, 2), ("dp", "sp"))
    ref = ContextParallelEngine(MOE_CFG, SGD(0.1), mesh, seed=0)
    eng = PipelineLMEngine(MOE_CFG, SGD(0.1), pp_sp_mesh(1, 2, 2),
                           n_mubatches=1, seed=0, attn="ring")
    for step in range(2):
        tok, tgt = batch(step)
        assert eng.train_batch(tok, tgt) == pytest.approx(
            ref.train_batch(tok, tgt), rel=3e-4), step


def test_pp_moe_eval_includes_aux():
    ref = moe_ref_engine(SGD(0.1), MOE_CFG)
    eng = PipelineLMEngine(MOE_CFG, SGD(0.1), pp_mesh(1, 2),
                           n_mubatches=1, seed=0)
    tok, tgt = batch(9)
    assert eng.eval_loss(tok, tgt) == pytest.approx(
        ref.eval_loss(tok, tgt), rel=3e-4)


def test_pp_chunked_xent_and_remat_policy_match():
    """cfg.xent_chunk (chunked CE on the last stage, inside the tick
    scan / 1F1B vjp) and cfg.remat_policy (policied per-stage
    checkpoint) must not change the pipeline trajectory."""
    cfgc = replace(CFG, xent_chunk=13, remat=True, remat_policy="dots")
    for sched in ("gpipe", "1f1b"):
        ref = ref_engine(SGD(0.1))
        eng = PipelineLMEngine(cfgc, SGD(0.1), pp_mesh(1, 2),
                               n_mubatches=2, seed=0, schedule=sched)
        for step in range(2):
            tok, tgt = batch(step + 20)
            assert eng.train_batch(tok, tgt) == pytest.approx(
                ref.train_batch(tok, tgt), rel=3e-4), (sched, step)


def test_pp_sp_chunked_xent_matches():
    """Chunked CE on sp-sharded last-stage tiles: the per-tile chunk
    scan + /(n_mu*sp) normalization must equal the plain path."""
    cfgc = replace(CFG, xent_chunk=16)
    a = PipelineLMEngine(CFG, SGD(0.1), pp_sp_mesh(1, 2, 2),
                         n_mubatches=2, seed=0, attn="ring")
    b = PipelineLMEngine(cfgc, SGD(0.1), pp_sp_mesh(1, 2, 2),
                         n_mubatches=2, seed=0, attn="ring",
                         schedule="1f1b")
    tok, tgt = batch(31)
    assert a.train_batch(tok, tgt) == pytest.approx(
        b.train_batch(tok, tgt), rel=3e-4)


# ------------------------------------ interleaved virtual stages (round 3)


@pytest.mark.parametrize("dp,pp,vpp,n_mu", [(1, 2, 2, 4), (2, 2, 2, 2),
                                            (1, 2, 2, 8)])
def test_virtual_pp_matches_plain_dp(dp, pp, vpp, n_mu):
    """Interleaved GPipe (virtual chunks, ring hops with the device-0
    chunk shift) must reproduce the serial trajectory exactly like
    plain GPipe — placement permutation included (canonical params
    round-trip through the interleaved layout)."""
    ref = ref_engine(SGD(0.1))
    eng = PipelineLMEngine(CFG, SGD(0.1), pp_mesh(dp, pp),
                           n_mubatches=n_mu, seed=0, virtual_pp=vpp)
    for step in range(3):
        tok, tgt = batch(step)
        lr_ = ref.train_batch(tok, tgt)
        lp = eng.train_batch(tok, tgt)
        assert lp == pytest.approx(lr_, rel=3e-4), (step, dp, pp, vpp)
    for a, b in zip(jax.tree_util.tree_leaves(eng.get_canonical_params()),
                    jax.tree_util.tree_leaves(ref.get_canonical_params())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-5)


def test_virtual_pp_checkpoint_roundtrip(tmp_path):
    """The interleaved layer permutation must be invisible in the
    canonical checkpoint: save interleaved, restore plain (and the
    eval losses agree)."""
    from shallowspeed_tpu import checkpoint

    eng = PipelineLMEngine(CFG, Adam(1e-2), pp_mesh(1, 2),
                           n_mubatches=2, seed=0, virtual_pp=2)
    tok, tgt = batch(3)
    eng.train_batch(tok, tgt)
    checkpoint.save(str(tmp_path), eng, 1)
    eng2 = PipelineLMEngine(CFG, Adam(1e-2), pp_mesh(1, 4),
                            n_mubatches=2, seed=1)
    checkpoint.restore(eng2, checkpoint.latest(str(tmp_path)))
    assert eng.eval_loss(tok, tgt) == pytest.approx(
        eng2.eval_loss(tok, tgt), rel=1e-4)


def test_virtual_pp_guards():
    with pytest.raises(AssertionError, match="divide over"):
        PipelineLMEngine(replace(CFG, n_layers=4), SGD(0.1),
                         pp_mesh(1, 2), virtual_pp=3)


# ------------------------------ interleaved 1F1B (vpp x 1f1b, round 4)


@pytest.mark.parametrize("dp,pp,vpp,n_mu", [(1, 2, 2, 4), (2, 2, 2, 2),
                                            (1, 2, 2, 8), (1, 4, 2, 4)])
def test_virtual_1f1b_matches_plain_dp(dp, pp, vpp, n_mu):
    """Compiled interleaved PipeDream-Flush (table-driven rounds from
    verify.interleaved_tables) must reproduce the serial trajectory —
    the same oracle every other schedule answers to."""
    cfg = replace(CFG, n_layers=pp * vpp)
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("dp", "sp"))
    ref = ContextParallelEngine(cfg, SGD(0.1), mesh, seed=0)
    eng = PipelineLMEngine(cfg, SGD(0.1), pp_mesh(dp, pp),
                           n_mubatches=n_mu, seed=0, schedule="1f1b",
                           virtual_pp=vpp)
    for step in range(3):
        tok, tgt = batch(step)
        lr_ = ref.train_batch(tok, tgt)
        lp = eng.train_batch(tok, tgt)
        assert lp == pytest.approx(lr_, rel=3e-4), (step, dp, pp, vpp)
    for a, b in zip(jax.tree_util.tree_leaves(eng.get_canonical_params()),
                    jax.tree_util.tree_leaves(ref.get_canonical_params())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-5)


def test_virtual_1f1b_matches_virtual_gpipe_with_dropout():
    """The two interleaved schedules must draw BIT-IDENTICAL dropout
    masks (same mu_key + chunk fold), so their loss trajectories agree
    to reassociation tolerance even with dropout on."""
    cfg = replace(CFG, dropout=0.1)
    a = PipelineLMEngine(cfg, SGD(0.1), pp_mesh(1, 2), n_mubatches=4,
                         seed=0, schedule="gpipe", virtual_pp=2)
    b = PipelineLMEngine(cfg, SGD(0.1), pp_mesh(1, 2), n_mubatches=4,
                         seed=0, schedule="1f1b", virtual_pp=2)
    for step in range(2):
        tok, tgt = batch(step)
        la = a.train_batch(tok, tgt)
        lb = b.train_batch(tok, tgt)
        assert la == pytest.approx(lb, rel=3e-4), step


def test_virtual_1f1b_moe():
    """MoE x interleaved 1F1B: every chunk's balance/z aux rides the
    per-round vjp seed (the GPipe-virtual path is the oracle)."""
    cfg = replace(CFG, n_experts=2, moe_top_k=1, moe_aux_weight=1e-2)
    a = PipelineLMEngine(cfg, SGD(0.1), pp_mesh(1, 2), n_mubatches=2,
                         seed=0, schedule="gpipe", virtual_pp=2)
    b = PipelineLMEngine(cfg, SGD(0.1), pp_mesh(1, 2), n_mubatches=2,
                         seed=0, schedule="1f1b", virtual_pp=2)
    for step in range(2):
        tok, tgt = batch(step)
        assert a.train_batch(tok, tgt) == pytest.approx(
            b.train_batch(tok, tgt), rel=3e-4), step


def test_virtual_1f1b_checkpoint_roundtrip(tmp_path):
    """Interleave permutation invisible in the canonical checkpoint,
    1F1B flavor: save interleaved-1f1b, restore plain gpipe."""
    from shallowspeed_tpu import checkpoint

    eng = PipelineLMEngine(CFG, Adam(1e-2), pp_mesh(1, 2),
                           n_mubatches=2, seed=0, schedule="1f1b",
                           virtual_pp=2)
    tok, tgt = batch(3)
    eng.train_batch(tok, tgt)
    checkpoint.save(str(tmp_path), eng, 1)
    eng2 = PipelineLMEngine(CFG, Adam(1e-2), pp_mesh(1, 4),
                            n_mubatches=2, seed=1)
    checkpoint.restore(eng2, checkpoint.latest(str(tmp_path)))
    assert eng.eval_loss(tok, tgt) == pytest.approx(
        eng2.eval_loss(tok, tgt), rel=1e-4)


# --------------------------------------------- ZeRO-1 x pp (round 3)


def test_pp_zero1_matches_dense_pipeline():
    """ZeRO-1 on the pipeline engine: dp-sharded moments + split-step
    GSPMD update must reproduce the dense pipeline trajectory; moment
    leaves carry BOTH 'pp' (stage placement) and 'dp' (ZeRO shard)."""
    dense = PipelineLMEngine(CFG, Adam(1e-2), pp_mesh(2, 2),
                             n_mubatches=2, seed=0)
    z1 = PipelineLMEngine(CFG, Adam(1e-2), pp_mesh(2, 2),
                          n_mubatches=2, seed=0, zero1=True)
    m = z1.opt_state["m"]["blocks"]["qkv"]["W"]
    axes = set(a for a in m.sharding.spec if a)
    assert axes == {"pp", "dp"}, m.sharding.spec
    for step in range(3):
        tok, tgt = batch(step)
        assert z1.train_batch(tok, tgt) == pytest.approx(
            dense.train_batch(tok, tgt), rel=3e-4), step
    for a, b in zip(jax.tree_util.tree_leaves(z1.get_canonical_params()),
                    jax.tree_util.tree_leaves(
                        dense.get_canonical_params())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_pp_zero1_with_clip_and_checkpoint(tmp_path):
    from shallowspeed_tpu import checkpoint

    ref = ref_engine(Adam(1e-2, grad_clip=0.5))
    eng = PipelineLMEngine(CFG, Adam(1e-2, grad_clip=0.5), pp_mesh(2, 2),
                           n_mubatches=2, seed=0, zero1=True)
    tok, tgt = batch(5)
    for step in range(2):
        assert eng.train_batch(tok, tgt) == pytest.approx(
            ref.train_batch(tok, tgt), rel=3e-4), step
    checkpoint.save(str(tmp_path), eng, 2)
    # restore into a dense pipeline at a different topology
    eng2 = PipelineLMEngine(CFG, Adam(1e-2, grad_clip=0.5),
                            pp_mesh(1, 4), n_mubatches=2, seed=1)
    checkpoint.restore(eng2, checkpoint.latest(str(tmp_path)))
    l1 = eng.train_batch(tok, tgt)
    l2 = eng2.train_batch(tok, tgt)
    assert l1 == pytest.approx(l2, rel=1e-3)


@pytest.mark.parametrize("sched", ["gpipe", "1f1b"])
def test_pp_zero2_matches_dense_pipeline(sched):
    """ZeRO-2 x pp: grads leave the shard_map dp-SHARDED (reduce-
    scatter), aligned with the ZeRO-1-placed moments; trajectory equals
    the dense pipeline under BOTH schedules.

    Params are compared under SGD: the k-bias slice of the fused qkv
    bias has a TRUE gradient of ~0 (softmax shift-invariance), so
    Adam normalizes reduction-order fp noise into O(lr) drift there —
    loss-invisible (the Adam loss check below is bit-tight) but it
    would fail a naive param comparison."""
    dense = PipelineLMEngine(CFG, SGD(0.1), pp_mesh(2, 2),
                             n_mubatches=2, seed=0, schedule=sched)
    z2 = PipelineLMEngine(CFG, SGD(0.1), pp_mesh(2, 2),
                          n_mubatches=2, seed=0, schedule=sched,
                          zero2=True)
    m = z2.opt_state
    for step in range(3):
        tok, tgt = batch(step)
        assert z2.train_batch(tok, tgt) == pytest.approx(
            dense.train_batch(tok, tgt), rel=3e-4), (sched, step)
    for a, b in zip(jax.tree_util.tree_leaves(z2.get_canonical_params()),
                    jax.tree_util.tree_leaves(
                        dense.get_canonical_params())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)
    # Adam: moments carry BOTH 'pp' and 'dp'; loss trajectory stays
    # tight even where the zero-gradient noise drifts params
    da = PipelineLMEngine(CFG, Adam(1e-2), pp_mesh(2, 2),
                          n_mubatches=2, seed=0, schedule=sched)
    za = PipelineLMEngine(CFG, Adam(1e-2), pp_mesh(2, 2),
                          n_mubatches=2, seed=0, schedule=sched,
                          zero2=True)
    mm = za.opt_state["m"]["blocks"]["qkv"]["W"]
    assert set(a for a in mm.sharding.spec if a) == {"pp", "dp"}
    for step in range(3):
        tok, tgt = batch(step + 10)
        assert za.train_batch(tok, tgt) == pytest.approx(
            da.train_batch(tok, tgt), rel=3e-4), (sched, step)


def test_pp_zero2_guards():
    with pytest.raises(AssertionError, match="pick ONE"):
        PipelineLMEngine(CFG, Adam(1e-2), pp_mesh(2, 2), zero1=True,
                         zero2=True)
    # round 5: tp AND sp compose with zero2/fsdp x pp; only ep stays
    # excluded (expert-leaf grads are ep-sharded — the mechanism lives
    # in test_zero2.test_zero_family_pp_ep_pinned)
    devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
    with pytest.raises(AssertionError, match="ep-sharded"):
        PipelineLMEngine(replace(CFG, n_experts=2), Adam(1e-2),
                         Mesh(devs, ("dp", "pp", "ep")), zero2=True)


def test_pp_fsdp_matches_dense_pipeline():
    """FSDP/ZeRO-3 x pp: params REST dp-sharded on top of the stage
    placement (1/dp master+moment memory per device), each step
    all-gathers the stage's params transiently and reduce-scatters the
    grads back; trajectory equals the dense pipeline."""
    dense = PipelineLMEngine(CFG, SGD(0.1), pp_mesh(2, 2),
                             n_mubatches=2, seed=0)
    f = PipelineLMEngine(CFG, SGD(0.1), pp_mesh(2, 2), n_mubatches=2,
                         seed=0, fsdp=True)
    w = f.params["blocks"]["qkv"]["W"]
    assert set(a for a in w.sharding.spec if a) == {"pp", "dp"}
    # stateful optimizers: moments inherit the dp-sharded placement
    fa = PipelineLMEngine(CFG, Adam(1e-2), pp_mesh(2, 2), n_mubatches=2,
                          seed=0, fsdp=True)
    mm = fa.opt_state["m"]["blocks"]["qkv"]["W"]
    assert set(a for a in mm.sharding.spec if a) == {"pp", "dp"}
    tok, tgt = batch(9)
    assert np.isfinite(fa.train_batch(tok, tgt))
    for step in range(3):
        tok, tgt = batch(step)
        assert f.train_batch(tok, tgt) == pytest.approx(
            dense.train_batch(tok, tgt), rel=3e-4), step
    for a, b in zip(jax.tree_util.tree_leaves(f.get_canonical_params()),
                    jax.tree_util.tree_leaves(
                        dense.get_canonical_params())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)
    assert f.eval_loss(tok, tgt) == pytest.approx(
        dense.eval_loss(tok, tgt), rel=3e-4)


def test_pp_fsdp_checkpoint_roundtrip(tmp_path):
    from shallowspeed_tpu import checkpoint
    from shallowspeed_tpu.optim import SGD as _SGD

    eng = PipelineLMEngine(CFG, _SGD(0.1), pp_mesh(2, 2), n_mubatches=2,
                           seed=0, fsdp=True)
    tok, tgt = batch(3)
    eng.train_batch(tok, tgt)
    checkpoint.save(str(tmp_path), eng, 1)
    eng2 = PipelineLMEngine(CFG, _SGD(0.1), pp_mesh(1, 2), n_mubatches=2,
                            seed=1)
    checkpoint.restore(eng2, checkpoint.latest(str(tmp_path)))
    assert eng.eval_loss(tok, tgt) == pytest.approx(
        eng2.eval_loss(tok, tgt), rel=1e-4)


# ----------------------------------------- ep x pp (round 4)


MOE_CFG = replace(CFG, n_experts=4, moe_top_k=2, moe_aux_weight=1e-2)


def ep_mesh(dp, pp, ep):
    devs = np.array(jax.devices()[: dp * pp * ep]).reshape(dp, pp, ep)
    return Mesh(devs, ("dp", "pp", "ep"))


@pytest.mark.parametrize("sched", ["gpipe", "1f1b"])
def test_ep_pp_matches_dp_local_moe(sched):
    """Expert parallelism INSIDE pipeline stages: experts shard over
    'ep' with stage-local all-to-all dispatch (moe_ffn_ep); rows shard
    over dp x ep. Capacity competition is per ROW (each row is its own
    routing group), so dp=4 and dp=2 x ep=2 are the same math — the
    trajectories must match bit-for-bit-ish."""
    ref = PipelineLMEngine(MOE_CFG, SGD(0.1), pp_mesh(4, 2),
                          n_mubatches=2, seed=0, schedule=sched)
    eng = PipelineLMEngine(MOE_CFG, SGD(0.1), ep_mesh(2, 2, 2),
                          n_mubatches=2, seed=0, schedule=sched)
    for step in range(3):
        tok, tgt = batch(step)
        assert eng.train_batch(tok, tgt) == pytest.approx(
            ref.train_batch(tok, tgt), rel=3e-4), step
    for a, b in zip(jax.tree_util.tree_leaves(eng.get_canonical_params()),
                    jax.tree_util.tree_leaves(ref.get_canonical_params())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-5)


def test_ep_pp_expert_grads_differ_across_shards():
    """The ep shards hold DIFFERENT experts (not replicas): after a
    step, expert weights must carry 'ep' in their sharding spec and the
    canonical expert stack must differ across the expert axis."""
    eng = PipelineLMEngine(MOE_CFG, SGD(0.1), ep_mesh(2, 2, 2),
                          n_mubatches=2, seed=0)
    assert "ep" in str(eng.params["blocks"]["moe"]["wi"].sharding.spec)
    tok, tgt = batch(1)
    eng.train_batch(tok, tgt)
    wi = np.asarray(jax.device_get(eng.params["blocks"]["moe"]["wi"]))
    assert not np.allclose(wi[:, 0], wi[:, 1])  # experts diverge


def test_ep_pp_zero1():
    """ZeRO-1 stacks on ep x pp: moments shard over 'dp' on top of the
    ('pp', 'ep') placement; trajectory equals the dense ep x pp run."""
    from shallowspeed_tpu.optim import Adam

    dense = PipelineLMEngine(MOE_CFG, Adam(1e-2), ep_mesh(2, 2, 2),
                             n_mubatches=2, seed=0)
    z1 = PipelineLMEngine(MOE_CFG, Adam(1e-2), ep_mesh(2, 2, 2),
                          n_mubatches=2, seed=0, zero1=True)
    for step in range(3):
        tok, tgt = batch(step)
        assert z1.train_batch(tok, tgt) == pytest.approx(
            dense.train_batch(tok, tgt), rel=3e-4), step


def test_ep_pp_checkpoint_roundtrip(tmp_path):
    """Canonical checkpoint is layout-free: save from ep x pp, restore
    into a dp-only MoE pipeline."""
    from shallowspeed_tpu import checkpoint
    from shallowspeed_tpu.optim import Adam

    eng = PipelineLMEngine(MOE_CFG, Adam(1e-2), ep_mesh(2, 2, 2),
                          n_mubatches=2, seed=0)
    tok, tgt = batch(3)
    eng.train_batch(tok, tgt)
    checkpoint.save(str(tmp_path), eng, 1)
    eng2 = PipelineLMEngine(MOE_CFG, Adam(1e-2), pp_mesh(2, 2),
                            n_mubatches=2, seed=1)
    checkpoint.restore(eng2, checkpoint.latest(str(tmp_path)))
    assert eng.eval_loss(tok, tgt) == pytest.approx(
        eng2.eval_loss(tok, tgt), rel=1e-4)


def test_ep_pp_guards():
    with pytest.raises(AssertionError, match="n_experts > 0"):
        PipelineLMEngine(CFG, SGD(0.1), ep_mesh(2, 2, 2))
    with pytest.raises(AssertionError, match="divide over"):
        PipelineLMEngine(replace(MOE_CFG, n_experts=3), SGD(0.1),
                         ep_mesh(2, 2, 2))
    with pytest.raises(AssertionError, match="cond-gated"):
        PipelineLMEngine(MOE_CFG, SGD(0.1), ep_mesh(2, 2, 2),
                         virtual_pp=2)


# ------------------------------------------ vpp x tp composes (round 5)


@pytest.mark.parametrize("sched", ["gpipe", "1f1b"])
def test_virtual_pp_tp_matches_plain_vpp(sched):
    """Interleaved virtual stages x Megatron tp: the chunk-gating
    predicate depends only on (tick, pp coordinate), so tp peers take
    the same branch and the in-chunk psums stay schedule-identical —
    the round-4 exclusion was conservative and is lifted. Trajectory
    equals the tp-less vpp run."""
    devs = np.array(jax.devices()[:4]).reshape(1, 2, 2)
    ref = PipelineLMEngine(CFG, SGD(0.1), pp_mesh(1, 2),
                           n_mubatches=2, seed=0, schedule=sched,
                           virtual_pp=2)
    eng = PipelineLMEngine(CFG, SGD(0.1),
                           Mesh(devs, ("dp", "pp", "tp")),
                           n_mubatches=2, seed=0, schedule=sched,
                           virtual_pp=2)
    for step in range(3):
        tok, tgt = batch(step)
        assert eng.train_batch(tok, tgt) == pytest.approx(
            ref.train_batch(tok, tgt), rel=3e-4), (sched, step)


# --------------------- pinned constructor carve-outs (VERDICT r4 item 7)


def _mesh3(axes, shape=(1, 2, 2), n=4):
    return Mesh(np.array(jax.devices()[:n]).reshape(shape), axes)


@pytest.mark.parametrize("build,match", [
    # a non-pipeline mesh is refused by name, not mis-executed
    (lambda: PipelineLMEngine(CFG, SGD(0.1), _mesh3(("dp", "sp", "tp"))),
     "expects a"),
    # sp>1 without a sequence-parallel substrate
    (lambda: PipelineLMEngine(CFG, SGD(0.1), _mesh3(("dp", "pp", "sp")),
                              attn="flash"),
     "sequence-parallel attention substrate"),
    # a sequence-parallel substrate without an sp axis to collect over
    (lambda: PipelineLMEngine(CFG, SGD(0.1), pp_mesh(1, 2),
                              attn="ring"),
     "collects over an 'sp'"),
    # ulysses all-to-all needs head counts divisible by sp
    (lambda: PipelineLMEngine(replace(CFG, n_heads=3, d_model=48),
                              SGD(0.1), _mesh3(("dp", "pp", "sp")),
                              attn="ulysses-flash"),
     "divisible by sp"),
    # Megatron column split needs heads divisible by tp
    (lambda: PipelineLMEngine(replace(CFG, n_heads=3, d_model=48),
                              SGD(0.1), _mesh3(("dp", "pp", "tp"))),
     "divisible by tp"),
    # GQA kv heads must divide over tp too
    (lambda: PipelineLMEngine(replace(CFG, n_kv_heads=1), SGD(0.1),
                              _mesh3(("dp", "pp", "tp"))),
     "divisible by tp"),
    # ZeRO flavors shard over dp — dp=1 has nothing to shard
    (lambda: PipelineLMEngine(CFG, SGD(0.1), pp_mesh(1, 2),
                              zero1=True),
     "need dp > 1"),
    # vpp keeps sp out (ring members span the gated axis)
    (lambda: PipelineLMEngine(CFG, SGD(0.1), _mesh3(("dp", "pp", "sp")),
                              attn="ring", virtual_pp=2),
     "sp/ep-collective-free"),
])
def test_constructor_carveouts_are_pinned(build, match):
    """Every remaining constructor exclusion fails fast with its
    mechanism named (the ZB-style executable-negative-decision bar:
    carve-outs must not silently rot)."""
    with pytest.raises(AssertionError, match=match):
        build()
