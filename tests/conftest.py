"""Test configuration: run everything on a virtual 8-device CPU platform.

The reference tests multi-stage logic without processes by keeping schedules
pure data (`/root/reference/tests/test_schedules.py`). We keep that idea and go
further: with `--xla_force_host_platform_device_count=8` a single pytest
process hosts a real 8-device `jax.sharding.Mesh`, so DP×PP SPMD paths run
end-to-end with real XLA collectives — no MPI, no TPU pod needed.

Notes:
- This environment pre-imports jax config at interpreter startup (PYTHONPATH
  site hook) with JAX_PLATFORMS=axon, so env vars alone are too late; we must
  use `jax.config.update` to pin the CPU platform.
- XLA_FLAGS is read lazily at first backend initialization, which has not
  happened yet at conftest import time, so forcing the host device count here
  still works.
- Numerics tests assume true-f32 matmuls (the reference's NumPy/BLAS
  semantics); TPU MXU defaults to bf16 passes, so pin highest precision for
  the test suite.
"""

import os
from pathlib import Path

import pytest

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_cpu_multi_thread_eigen" not in flags:
    # On oversubscribed hosts (1-core CI), intra-op Eigen threads
    # preempt XLA CPU's in-process collective rendezvous and
    # collective-permute-heavy programs (pp x sp pipelines) abort in
    # rendezvous.h ("id >= num_threads") — every collective shares
    # channel_id=1, so the one rendezvous key is reused hundreds of
    # times per step and the reuse race needs an un-thrashed pool.
    flags = (flags + " --xla_cpu_multi_thread_eigen=false").strip()
if "xla_cpu_enable_concurrency_optimized_scheduler" not in flags:
    # ...and the concurrency-optimized thunk scheduler runs INDEPENDENT
    # collectives of one program concurrently (e.g. a ring VJP's dq and
    # dk/dv hop chains) — two in-flight instances of the shared channel
    # from the same device blow the same rendezvous up. Serialize.
    flags = (flags
             + " --xla_cpu_enable_concurrency_optimized_scheduler=false"
             ).strip()
os.environ["XLA_FLAGS"] = flags

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")


# Test tiers: nodeids listed in slow_tests.txt (measured compile-heavy
# cross-engine matrices) get the `slow` marker; pyproject's addopts
# excludes them by default. Full run: pytest -m "slow or not slow".
# Tier budget (re-measured round 5, 2026-07-31): the default tier is
# ~474 tests in ~7:30 and the FULL suite is 759 tests in ~1:13h on
# this host UNDER LOAD (the same tiers measured ~4:01 / ~30 min on an
# idle host — wall times here swing ~2x with host load; the tier
# SPLIT, not the absolute budget, is the stable contract). Regenerate
# by running the full suite with --durations=0 and moving the heaviest
# compile-bound matrices (keeping one canary per feature in the
# default tier) into slow_tests.txt. Round-18 squeeze: eleven heavy
# matrix members (health engine-matrix siblings, the int8 serving
# stream twin, the big_cfg attribution analog, two pipeline_lm
# analysis targets the pre-commit --target-all hook re-runs anyway)
# moved to slow; default tier measured ~800 s / 834P on this host.
_SLOW = set((Path(__file__).parent / "slow_tests.txt").read_text().split())


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.nodeid in _SLOW:
            item.add_marker(pytest.mark.slow)
