"""Static analysis (`shallowspeed_tpu.analysis`) tests.

Two layers:

- **Per-rule toy fixtures**: one intentionally-bad jitted program per
  rule (accidental f32 promotion, missing donation, foreign-mesh
  collective, multi-cycle pp ppermute, unstable jit cache, over-budget
  memory, stacked fp8 roundings, bf16 scan-carry grad sums, narrow
  grad psums, forgotten/VJP-side quantization scales, provable range
  overflows) asserting the rule FIRES, plus a clean twin asserting it
  stays quiet — the rules are tested like any other pure function.
- **The tier-1 gate**: every shipped compiled train-step family
  (pipeline_lm GPipe/1F1B/interleaved/ZB-H1, gspmd, spmd_pipeline,
  engine, serving decode, fp8_train) must analyze to ZERO unsuppressed
  high-severity findings; plus the CLI contract (JSON format, baseline
  diff mode, usage-error exit codes) and the stale-suppression audit.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from shallowspeed_tpu import analysis
from shallowspeed_tpu.analysis import (EntryPoint, Severity, TargetProbe,
                                       gate_count, run_rules)
from shallowspeed_tpu.analysis.findings import (clear_suppressions,
                                                registered_suppressions,
                                                suppress)
from shallowspeed_tpu.analysis.targets import TARGET_BUILDERS
from shallowspeed_tpu.utils import shard_map


def toy_probe(fn, args, donate=(), mesh=None, compute_dtype=None,
              calls=0, budget=16 << 30, name="toy", ranges=None):
    probe = TargetProbe(name, mesh, compute_dtype, hbm_budget=budget)
    probe.entrypoints = [EntryPoint(
        "fn", fn, tuple(args), tuple(f"arg{i}" for i in range(len(args))),
        donate=tuple(donate), calls=calls, ranges=ranges)]
    return probe.seal()


def sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


def highs(findings):
    return [f for f in findings
            if f.severity == Severity.HIGH and not f.suppressed]


# ------------------------------------------------------- dtype promotion


def test_dtype_rule_fires_on_weak_promotion():
    @jax.jit
    def bad(x):  # bf16 activations against a forgotten-f32 constant
        return x @ jnp.ones((8, 8), jnp.float32)

    probe = toy_probe(bad, [sds((4, 8), jnp.bfloat16)],
                      compute_dtype=jnp.bfloat16)
    assert highs(run_rules(probe, only=("dtype-promotion",)))


def test_dtype_rule_fires_on_upcast_matmul():
    @jax.jit
    def bad(x, w):  # bf16 data upcast, then an all-f32 matmul
        return x.astype(jnp.float32) @ w

    probe = toy_probe(
        bad, [sds((4, 8), jnp.bfloat16), sds((8, 8), jnp.float32)],
        compute_dtype=jnp.bfloat16)
    assert highs(run_rules(probe, only=("dtype-promotion",)))


def test_dtype_rule_quiet_on_f32_accumulation():
    @jax.jit
    def clean(q, k, v):  # the documented score-path pattern
        s = jnp.einsum("qd,kd->qk", q, k,
                       preferred_element_type=jnp.float32)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("qk,kd->qd", p.astype(v.dtype), v,
                          preferred_element_type=jnp.float32)

    args = [sds((4, 8), jnp.bfloat16)] * 3
    probe = toy_probe(clean, args, compute_dtype=jnp.bfloat16)
    assert not highs(run_rules(probe, only=("dtype-promotion",)))


def test_dtype_rule_flags_round_trip_convert():
    @jax.jit
    def smelly(x):
        return x.astype(jnp.bfloat16).astype(jnp.float32) + 1.0

    probe = toy_probe(smelly, [sds((16,), jnp.float32)])
    fs = run_rules(probe, only=("dtype-promotion",))
    assert any("round-trip" in f.message for f in fs)
    assert not highs(fs)  # MEDIUM: a smell, not a gate


# --------------------------------------------------------------- donation


def test_donation_rule_fires_on_undonated_step():
    @jax.jit
    def step(params, opt, x):
        return params + x.sum(), opt + 1.0

    args = [sds((8,), jnp.float32), sds((), jnp.float32),
            sds((4,), jnp.float32)]
    probe = toy_probe(step, args, donate=(0, 1))
    found = highs(run_rules(probe, only=("donation",)))
    assert len(found) == 2  # params AND opt-state un-donated


def test_donation_rule_quiet_when_donated():
    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt, x):
        return params + x.sum(), opt + 1.0

    args = [sds((8,), jnp.float32), sds((), jnp.float32),
            sds((4,), jnp.float32)]
    probe = toy_probe(step, args, donate=(0, 1))
    assert not run_rules(probe, only=("donation",))


# ------------------------------------------------------------- collective


def mesh2x2(names=("dp", "pp")):
    return Mesh(np.array(jax.devices()[:4]).reshape(2, 2), names)


def test_collective_rule_fires_on_foreign_mesh():
    foreign = Mesh(np.array(jax.devices()[:2]), ("foo",))

    @jax.jit
    @partial(shard_map, mesh=foreign, in_specs=P("foo"), out_specs=P())
    def prog(x):
        return jax.lax.psum(x, "foo")

    probe = toy_probe(prog, [sds((4,), jnp.float32)], mesh=mesh2x2())
    found = highs(run_rules(probe, only=("collective",)))
    assert found and "foo" in found[0].message


def test_collective_rule_fires_on_multi_cycle_pp_ppermute():
    @jax.jit
    @partial(shard_map, mesh=mesh2x2(), in_specs=P("dp", "pp"),
             out_specs=P("dp", "pp"))
    def prog(x):  # two self-loops: stages never exchange
        return jax.lax.ppermute(x, "pp", [(0, 0), (1, 1)])

    probe = toy_probe(prog, [sds((4, 4), jnp.float32)], mesh=mesh2x2())
    found = highs(run_rules(probe, only=("collective",)))
    assert found and "single" in found[0].message


def test_collective_rule_quiet_on_ring():
    @jax.jit
    @partial(shard_map, mesh=mesh2x2(), in_specs=P("dp", "pp"),
             out_specs=P("dp", "pp"))
    def prog(x):
        x = jax.lax.ppermute(x, "pp", [(0, 1), (1, 0)])
        return jax.lax.psum(x, "dp") * 0.5

    probe = toy_probe(prog, [sds((4, 4), jnp.float32)], mesh=mesh2x2())
    assert not highs(run_rules(probe, only=("collective",)))


# ---------------------------------------------------------------- retrace


def test_retrace_rule_fires_on_unstable_cache():
    @jax.jit
    def f(x):
        return x * 2

    f(jnp.ones((4,)))
    f(jnp.ones((8,)))  # a second executable
    probe = toy_probe(f, [sds((4,), jnp.float32)], calls=2)
    assert highs(run_rules(probe, only=("retrace",)))


def test_retrace_rule_quiet_on_stable_cache():
    @jax.jit
    def f(x):
        return x * 3

    f(jnp.ones((4,)))
    f(jnp.ones((4,)) + 1)
    probe = toy_probe(f, [sds((4,), jnp.float32)], calls=2)
    assert not run_rules(probe, only=("retrace",))


# ------------------------------------------------------- memory highwater


def test_memory_rule_fires_over_budget():
    @jax.jit
    def big(x):
        y = jnp.outer(x, x)          # (2048, 2048) f32 = 16 MiB live
        return y.sum()

    probe = toy_probe(big, [sds((2048,), jnp.float32)],
                      budget=1 << 20)  # 1 MiB
    assert highs(run_rules(probe, only=("memory-highwater",)))


def test_memory_rule_quiet_within_budget():
    @jax.jit
    def small(x):
        return (x * 2).sum()

    probe = toy_probe(small, [sds((64,), jnp.float32)])
    fs = run_rules(probe, only=("memory-highwater",))
    assert fs and not highs(fs)  # informational LOW only


# ------------------------------------------------------------ suppression


def test_suppression_marks_and_ungates():
    @jax.jit
    def step(params, x):
        return params + x.sum()

    snapshot = registered_suppressions()
    try:
        suppress("donation", target="toy-sup", match="not donated",
                 reason="toy fixture: documents the mechanism")
        probe = toy_probe(step, [sds((8,), jnp.float32),
                                 sds((4,), jnp.float32)],
                          donate=(0,), name="toy-sup")
        fs = run_rules(probe, only=("donation",))
        assert fs and all(f.suppressed for f in fs)
        assert gate_count(fs) == 0
        assert "toy fixture" in fs[0].format()
    finally:
        clear_suppressions(snapshot)


def test_suppression_requires_reason():
    with pytest.raises(AssertionError):
        suppress("donation", reason="   ")


# ---------------------------------------------------------- overlap-bucket


def dp_mesh2():
    return Mesh(np.array(jax.devices()[:2]), ("dp",))


def _grad_psum_program(extra_stray=False):
    """Toy overlapped-style grad program: two dot 'layers', per-bucket
    psums interleaved so each has independent compute. With
    `extra_stray`, a third grad-sized dp psum is emitted that no
    bucket registers."""
    mesh = dp_mesh2()

    @jax.jit
    @partial(shard_map, mesh=mesh,
             in_specs=(P(), P(), P("dp")), out_specs=(P(), P(), P()))
    def step(w1, w2, x):
        h = x @ w1
        g2 = jax.lax.psum((h.T @ h,), "dp")[0]       # bucket: layer 2
        g1 = jax.lax.psum((x.T @ (h @ w2),), "dp")[0]  # bucket: layer 1
        stray = (jax.lax.psum(x.T @ x, "dp")
                 if extra_stray else jnp.zeros_like(g1))
        return g1, g2, stray

    return step


def _ov_probe(fn, register_buckets):
    from shallowspeed_tpu.parallel.overlap import (bucket_signature,
                                                   register_program)

    register_program(
        fn, "dp",
        [bucket_signature([np.zeros((64, 64), np.float32)])
         for _ in range(register_buckets)], engine="toy")
    args = [sds((64, 64), jnp.float32), sds((64, 64), jnp.float32),
            sds((8, 64), jnp.float32)]
    return toy_probe(fn, args, mesh=dp_mesh2())


def test_overlap_rule_fires_on_unregistered_dp_psum():
    probe = _ov_probe(_grad_psum_program(extra_stray=True),
                      register_buckets=2)
    found = highs(run_rules(probe, only=("overlap-bucket",)))
    assert found and "not a registered" in found[0].message


def test_overlap_rule_quiet_on_registered_buckets():
    probe = _ov_probe(_grad_psum_program(), register_buckets=2)
    assert not run_rules(probe, only=("overlap-bucket",))


def test_overlap_rule_fires_when_nothing_can_overlap():
    mesh = dp_mesh2()

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=(P(), P("dp")),
             out_specs=P())
    def barrier(w, x):
        h = x @ w
        return jax.lax.psum((h.T @ h,), "dp")[0]  # every dot feeds it

    from shallowspeed_tpu.parallel.overlap import (bucket_signature,
                                                   register_program)

    register_program(barrier, "dp",
                     [bucket_signature([np.zeros((64, 64),
                                                 np.float32)])])
    probe = toy_probe(barrier, [sds((64, 64), jnp.float32),
                                sds((8, 64), jnp.float32)],
                      mesh=dp_mesh2())
    found = highs(run_rules(probe, only=("overlap-bucket",)))
    assert found and "independent compute" in found[0].message


def test_overlap_rule_flags_missing_registered_bucket():
    probe = _ov_probe(_grad_psum_program(), register_buckets=3)
    found = run_rules(probe, only=("overlap-bucket",))
    assert any("never appeared" in f.message
               and f.severity == Severity.MEDIUM for f in found)


def test_overlap_rule_skips_unregistered_programs():
    mesh = dp_mesh2()

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=(P(), P("dp")),
             out_specs=P())
    def bulk(w, x):  # the documented bulk oracle — not a defect
        h = x @ w
        return jax.lax.psum(h.T @ h, "dp")

    probe = toy_probe(bulk, [sds((64, 64), jnp.float32),
                             sds((8, 64), jnp.float32)],
                      mesh=dp_mesh2())
    assert not run_rules(probe, only=("overlap-bucket",))


# --------------------------------------------------------- dequant fusion


def test_dequant_rule_fires_on_materialized_dequant():
    """The classic way to lose quantized storage: scale the upcast
    weight BEFORE the dot — a full (K, N) dequantized copy."""
    @jax.jit
    def bad(x, wq, ws):
        return x @ (wq.astype(jnp.float32) * ws)

    probe = toy_probe(bad, [sds((4, 8), jnp.float32),
                            sds((8, 16), jnp.int8),
                            sds((16,), jnp.float32)])
    found = highs(run_rules(probe, only=("dequant-fusion",)))
    assert found and "dequantized copy" in found[0].message


def test_dequant_rule_fires_on_bf16_dequant_copy():
    """A bf16 dequant copy is still a copy (ml_dtypes floats must
    class as floating for the size check)."""
    @jax.jit
    def bad(x, wq, ws):
        return x @ (wq.astype(jnp.bfloat16) * ws.astype(jnp.bfloat16))

    probe = toy_probe(bad, [sds((4, 8), jnp.float32),
                            sds((8, 16), jnp.int8),
                            sds((16,), jnp.float32)])
    assert highs(run_rules(probe, only=("dequant-fusion",)))


def test_dequant_rule_fires_on_fp8_weights():
    @jax.jit
    def bad(x, wq, ws):
        return x @ (wq.astype(jnp.float32) * ws)

    fp8 = getattr(jnp, "float8_e4m3fn", None)
    if fp8 is None:
        pytest.skip("no float8_e4m3fn in this jax build")
    probe = toy_probe(bad, [sds((4, 8), jnp.float32),
                            sds((8, 16), fp8),
                            sds((16,), jnp.float32)])
    assert highs(run_rules(probe, only=("dequant-fusion",)))


def test_dequant_rule_quiet_on_fused_form():
    """`dequant_matmul` is the clean fixture: the value upcast feeds
    the dot directly (folded into the operand load), the scale lands
    on the f32 accumulator."""
    from shallowspeed_tpu.ops.matmul import dequant_matmul

    @jax.jit
    def clean(x, wq, ws):
        return dequant_matmul(x, wq, ws)

    probe = toy_probe(clean, [sds((4, 8), jnp.float32),
                              sds((8, 16), jnp.int8),
                              sds((16,), jnp.float32)])
    assert not run_rules(probe, only=("dequant-fusion",))


def test_dequant_rule_exempts_gathered_int8_kv_views():
    """int8 KV reads go through a GATHER before their upcast (the
    paged read path); the gather breaks the weight-view chain, so the
    reference attention's gathered-view casts are not weight dequants
    and must not fire."""
    @jax.jit
    def kv_read(q, pool, bt):
        g = pool[bt]                       # (rows, W, H, bs, hd) int8
        g = jnp.swapaxes(g, 1, 2).reshape(2, 2, 16, 4)
        return jnp.einsum("rhd,rhkd->rhk", q, g.astype(jnp.float32))

    probe = toy_probe(kv_read, [sds((2, 2, 4), jnp.float32),
                                sds((8, 2, 8, 4), jnp.int8),
                                sds((2, 2), jnp.int32)])
    assert not run_rules(probe, only=("dequant-fusion",))


def test_dequant_rule_clean_on_quantized_decode_tick():
    """The live target: the serving decode tick at full quantization
    (int8 weights + int8 KV + the paged flash kernel) never
    materializes a dequantized weight copy. (Also exercised by the
    parametrized clean gate below via the 'serving' target.)"""
    results = analysis.analyze("serving", only=("dequant-fusion",))
    assert all(not fs for fs in results.values()), results


# ----------------------------------------------- fp8 double rounding

FP8 = getattr(jnp, "float8_e4m3fn", None)
fp8_only = pytest.mark.skipif(FP8 is None,
                              reason="no float8_e4m3fn in this build")


@fp8_only
def test_double_rounding_fires_on_stacked_narrowing():
    @jax.jit
    def bad(x):  # f32 -> bf16 -> e4m3: two roundings, no rescale
        return x.astype(jnp.bfloat16).astype(FP8)

    probe = toy_probe(bad, [sds((8, 8), jnp.float32)])
    found = highs(run_rules(probe, only=("fp8-double-rounding",)))
    assert found and "rounded again" in found[0].message


@fp8_only
def test_double_rounding_quiet_after_rescale():
    @jax.jit
    def clean(x, s):  # requantization done right: rescale FIRST
        h = x.astype(jnp.bfloat16)
        return (h.astype(jnp.float32) / s).astype(FP8)

    probe = toy_probe(clean, [sds((8, 8), jnp.float32),
                              sds((), jnp.float32)])
    assert not run_rules(probe, only=("fp8-double-rounding",))


def test_double_rounding_exempts_same_width_reround():
    @jax.jit
    def clean(x, b):  # the standard mixed-precision layernorm shape
        h = x.astype(jnp.float32) + b.astype(jnp.float32)
        return h.astype(jnp.bfloat16)

    probe = toy_probe(clean, [sds((8, 8), jnp.bfloat16),
                              sds((8,), jnp.bfloat16)])
    assert not run_rules(probe, only=("fp8-double-rounding",))


# ----------------------------------------------- accumulation dtype


def test_accumulation_rule_fires_on_bf16_scan_carry():
    @jax.jit
    def bad(xs):  # the peeled-microbatch grad sum, done wrong
        def tick(acc, x):
            return acc + x, None

        acc, _ = jax.lax.scan(tick, jnp.zeros((64,), jnp.bfloat16), xs)
        return acc

    probe = toy_probe(bad, [sds((4, 64), jnp.bfloat16)])
    found = highs(run_rules(probe, only=("accumulation-dtype",)))
    assert found and "carried accumulator" in found[0].message


def test_accumulation_rule_quiet_on_f32_scan_carry():
    @jax.jit
    def clean(xs):  # the hand schedules' `a + g.astype(f32)` idiom
        def tick(acc, x):
            return acc + x.astype(jnp.float32), None

        acc, _ = jax.lax.scan(tick, jnp.zeros((64,), jnp.float32), xs)
        return acc.astype(jnp.bfloat16)

    probe = toy_probe(clean, [sds((4, 64), jnp.bfloat16)])
    assert not highs(run_rules(probe, only=("accumulation-dtype",)))


def test_accumulation_rule_quiet_on_bf16_residual_stream():
    @jax.jit
    def clean(xs, w):  # h + f(h): f depends on the carry — NOT a sum
        def tick(h, _):
            return h + (h @ w).astype(h.dtype), None

        h, _ = jax.lax.scan(tick, xs, None, length=3)
        return h

    probe = toy_probe(clean, [sds((8, 64), jnp.bfloat16),
                              sds((64, 64), jnp.bfloat16)])
    assert not highs(run_rules(probe, only=("accumulation-dtype",)))


def test_accumulation_rule_fires_on_narrow_quant_dot():
    @jax.jit
    def bad(x, w):  # int8 weights, bf16 accumulator: K rounded away
        return x @ w["Wq"].astype(jnp.bfloat16) * w["Ws"]

    probe = toy_probe(bad, [sds((4, 32), jnp.bfloat16),
                            {"Wq": sds((32, 16), jnp.int8),
                             "Ws": sds((16,), jnp.bfloat16)}])
    found = highs(run_rules(probe, only=("accumulation-dtype",)))
    assert found and "quantized-storage" in found[0].message


def test_accumulation_rule_quiet_on_f32_quant_dot():
    @jax.jit
    def clean(x, w):
        acc = jax.lax.dot_general(
            x, w["Wq"].astype(jnp.bfloat16), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc * w["Ws"]

    probe = toy_probe(clean, [sds((4, 32), jnp.bfloat16),
                              {"Wq": sds((32, 16), jnp.int8),
                               "Ws": sds((16,), jnp.float32)}])
    assert not highs(run_rules(probe, only=("accumulation-dtype",)))


# --------------------------------------------- reduction precision


def test_reduction_rule_fires_on_bf16_grad_psum():
    mesh = dp_mesh2()

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P())
    def bad(g):
        return jax.lax.psum(g, "dp")

    probe = toy_probe(bad, [sds((4, 64, 64), jnp.bfloat16)], mesh=mesh)
    found = highs(run_rules(probe, only=("reduction-precision",)))
    assert found and "re-rounds" in found[0].message


def test_reduction_rule_quiet_on_f32_and_subkib():
    mesh = dp_mesh2()

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=(P("dp"), P("dp")),
             out_specs=(P(), P()))
    def clean(g, stat):
        return (jax.lax.psum(g.astype(jnp.float32), "dp"),
                jax.lax.psum(stat, "dp"))  # sub-KiB statistic: exempt

    probe = toy_probe(clean, [sds((4, 64, 64), jnp.bfloat16),
                              sds((4,), jnp.bfloat16)], mesh=mesh)
    assert not run_rules(probe, only=("reduction-precision",))


# ----------------------------------------------- scale consistency


def test_scale_rule_fires_on_forgotten_scale():
    @jax.jit
    def bad(x, w):  # Wq consumed, Ws never applied
        return x @ w["Wq"].astype(jnp.float32)

    probe = toy_probe(bad, [sds((4, 32), jnp.float32),
                            {"Wq": sds((32, 16), jnp.int8),
                             "Ws": sds((16,), jnp.float32)}])
    found = highs(run_rules(probe, only=("scale-consistency",)))
    assert found and "never applied" in found[0].message


def test_scale_rule_quiet_on_accumulator_scale():
    @jax.jit
    def clean(x, w):
        return (x @ w["Wq"].astype(jnp.float32)) * w["Ws"]

    probe = toy_probe(clean, [sds((4, 32), jnp.float32),
                              {"Wq": sds((32, 16), jnp.int8),
                               "Ws": sds((16,), jnp.float32)}])
    assert not run_rules(probe, only=("scale-consistency",))


def test_scale_rule_resolves_vjp_cotangent_scaling():
    """The transpose side: d/dx of a scaled quant matmul consumes Wq
    in the backward dot with the scale riding the COTANGENT (g * Ws)
    — a resolved pairing, not a forgotten scale."""
    def f(x, w):
        return ((x @ w["Wq"].astype(jnp.float32)) * w["Ws"]).sum()

    g = jax.jit(jax.grad(f, argnums=0))
    probe = toy_probe(g, [sds((4, 32), jnp.float32),
                          {"Wq": sds((32, 16), jnp.int8),
                           "Ws": sds((16,), jnp.float32)}])
    assert not highs(run_rules(probe, only=("scale-consistency",)))


def test_scale_rule_certifies_fp8_train_both_sides():
    """The live target: in-program e4m3 quantization in fp8_dense must
    pair every quantized operand to its delayed/JIT scale on the
    forward AND the hand-VJP dots."""
    results = analysis.analyze("fp8_train", only=("scale-consistency",))
    assert all(not fs for fs in results.values()), results


# --------------------------------------------------- range safety


@fp8_only
def test_range_rule_fires_on_unclamped_fp8_cast():
    @jax.jit
    def bad(x):
        return x.astype(FP8)

    probe = toy_probe(bad, [sds((8, 8), jnp.float32)],
                      ranges={"arg0": (-1000.0, 1000.0)})
    found = highs(run_rules(probe, only=("range-safety",)))
    assert found and "overflows" in found[0].message


@fp8_only
def test_range_rule_quiet_on_saturating_clamp():
    @jax.jit
    def clean(x):
        return jnp.clip(x, -448.0, 448.0).astype(FP8)

    probe = toy_probe(clean, [sds((8, 8), jnp.float32)],
                      ranges={"arg0": (-1000.0, 1000.0)})
    assert not run_rules(probe, only=("range-safety",))


def test_range_rule_fires_on_provable_exp_overflow():
    @jax.jit
    def bad(x):
        return jnp.exp(x)

    probe = toy_probe(bad, [sds((8,), jnp.float32)],
                      ranges={"arg0": (120.0, 200.0)})
    assert highs(run_rules(probe, only=("range-safety",)))


def test_range_rule_quiet_on_shifted_softmax():
    @jax.jit
    def clean(x):  # x - max(x) <= 0: exp provably in range
        return jax.nn.softmax(x, axis=-1)

    probe = toy_probe(clean, [sds((4, 8), jnp.float32)],
                      ranges={"arg0": (-500.0, 500.0)})
    assert not run_rules(probe, only=("range-safety",))


# ------------------------------- serialization, stale audit, baseline


def test_finding_to_dict_and_key():
    f = analysis.Finding("r", Severity.HIGH, "t", "s", ("pjit",),
                         "boom (x3)")
    d = f.to_dict()
    assert d["severity"] == "HIGH" and d["path"] == ["pjit"]
    assert d["key"] == "r|t|s|pjit|boom"  # dedup count stripped
    assert f.key == d["key"]


def test_stale_suppression_audit():
    from shallowspeed_tpu.analysis.findings import stale_suppressions

    snapshot = registered_suppressions()
    try:
        clear_suppressions()
        s_used = suppress("donation", target="probe-a", reason="live")
        suppress("donation", target="probe-a", match="nope",
                 reason="documents a deviation that no longer exists")
        suppress("donation", target="probe-b",
                 reason="covers a probe that did not run")
        hit = analysis.Finding("donation", Severity.HIGH, "probe-a",
                               "fn", (), "x", suppressed="live",
                               suppressed_by=s_used)
        stale = stale_suppressions({"probe-a": [hit]},
                                   ran_rules=("donation",))
        assert len(stale) == 1  # only the matched-nothing registration
        assert stale[0].severity == Severity.MEDIUM
        assert stale[0].rule == "stale-suppression"
        assert "matched no finding" in stale[0].message
        # rule didn't run -> nothing can be proven stale
        assert not stale_suppressions({"probe-a": [hit]},
                                      ran_rules=("retrace",))
    finally:
        clear_suppressions(snapshot)


def test_cli_json_and_baseline_roundtrip(tmp_path, capsys):
    import json

    from shallowspeed_tpu.analysis.__main__ import SCHEMA, main

    base = tmp_path / "baseline.json"
    assert main(["--target", "engine", "--write-baseline",
                 str(base)]) == 0
    capsys.readouterr()
    doc = json.loads(base.read_text())
    assert doc["schema"] == SCHEMA and doc["keys"] == []  # clean target

    assert main(["--target", "engine", "--baseline", str(base),
                 "--format", "json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["schema"] == SCHEMA
    assert out["gate"] == 0 and "engine" in out["targets"]
    fs = out["targets"]["engine"]["findings"]
    assert fs and all(
        set(f) >= {"rule", "severity", "target", "site", "path",
                   "message", "suppressed", "key"} for f in fs)


def test_cli_usage_errors_exit_two(tmp_path):
    from shallowspeed_tpu.analysis.__main__ import main

    with pytest.raises(SystemExit) as e:
        main(["--target", "bogus"])
    assert e.value.code == 2
    with pytest.raises(SystemExit) as e:
        main(["--rules", "bogus"])
    assert e.value.code == 2
    with pytest.raises(SystemExit) as e:
        main(["--baseline", str(tmp_path / "missing.json")])
    assert e.value.code == 2


# ----------------------------------------------- the tier-1 clean gate


@pytest.mark.parametrize("target", sorted(TARGET_BUILDERS))
def test_shipped_train_steps_are_tpu_clean(target):
    """THE acceptance gate: every compiled train-step family ships with
    zero unsuppressed high-severity findings."""
    results = analysis.analyze(target)
    gating = [f for fs in results.values() for f in fs
              if f.severity == Severity.HIGH and not f.suppressed]
    assert not gating, "\n".join(f.format() for f in gating)


def test_cli_exits_zero_on_clean_target():
    from shallowspeed_tpu.analysis.__main__ import main

    assert main(["--target", "engine", "-q"]) == 0
