"""Fault-tolerant fleet serving (round 15): the SLO-aware router —
`shallowspeed_tpu/serving/router.py` + the `router.py` driver.

The load-bearing invariants:

- **Failover stream parity.** A request whose replica dies mid-decode
  re-dispatches (seeded, idempotent: prompt + tokens-so-far re-prefill
  on another replica) and its completed stream is TOKEN-IDENTICAL to
  the solo `generate()` oracle — the engine's evict-newest
  continuation crossing a process boundary. The in-process canary here
  is default-tier; the cross-process fleet chaos drill (real serve.py
  subprocesses, SIGKILL mid-decode + stall + heartbeat freeze) rides
  the slow tier.
- **Circuit breakers.** Consecutive-failure trip, jittered doubling
  cooldown, half-open single-probe recovery; replica death force-opens;
  transitions stamped as schema-v10 ledger lines.
- **Fleet-edge backpressure.** Typed `FleetOverloaded` + retry-after
  when every breaker is open or the queue exceeds budget — never
  silent queue growth.
- **Burn-driven autoscaling.** Sustained critical ttft burn (the
  Monitor's dual-window rule over the router's own observations)
  spawns a replica; sustained idle drains one gracefully with
  deregistration and zero dropped requests.
- **Schema v10 + goodput.** route/failover/scale events validate; the
  goodput reducer's fleet block reports per-replica MTTR and fleet
  availability from a router log alone.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np
import pytest

from shallowspeed_tpu.serving.router import (CircuitBreaker,
                                             FleetOverloaded,
                                             InProcessReplica,
                                             RequestGateway, Router)
from shallowspeed_tpu.telemetry.schema import (SCHEMA_VERSION,
                                               validate_file,
                                               validate_line)

ROOT = Path(__file__).resolve().parents[1]


@pytest.fixture(scope="module")
def serving_fixture():
    import jax

    from shallowspeed_tpu.models import transformer as T

    cfg = T.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                              n_layers=2, max_seq=128)
    params = jax.device_put(T.init(cfg, seed=1))
    return params, cfg


def toks(seed=0, t=12, vocab=64):
    rng = np.random.default_rng(seed)
    return rng.integers(0, vocab, (t,)).astype(np.int32)


def solo(params, cfg, prompt, max_new, **kw):
    from shallowspeed_tpu.models.generate import generate

    return np.asarray(generate(params, prompt[None, :], cfg, max_new,
                               **kw))[0]


def make_spawn(params, cfg, clock=None, **engine_kw):
    from shallowspeed_tpu.serving import ServingEngine

    kw = dict(n_blocks=32, block_size=8, max_slots=4,
              prefill_chunk=16)
    kw.update(engine_kw)

    def factory(name):
        return ServingEngine(params, cfg, **kw)

    def spawn(name):
        return (InProcessReplica(name, factory)
                if clock is None
                else InProcessReplica(name, factory, clock=clock))

    return spawn


# ------------------------------------------------------ circuit breaker


def test_circuit_breaker_trip_halfopen_recover():
    clock = [100.0]
    transitions = []
    br = CircuitBreaker(threshold=3, cooldown=2.0, cooldown_max=10.0,
                        jitter=0.5, seed=7,
                        on_transition=lambda st, t: transitions.append(
                            (st, t)))
    now = lambda: clock[0]  # noqa: E731
    assert br.allow(now()) and br.state == "closed"
    br.note_failure(now())
    br.note_failure(now())
    assert br.state == "closed"          # below threshold
    br.note_success(now())
    br.note_failure(now())
    br.note_failure(now())
    assert br.state == "closed"          # success reset the streak
    br.note_failure(now())
    br.note_failure(now())               # 3 consecutive -> trip
    assert br.state == "open" and br.trips == 1
    # jittered cooldown: within [cooldown, cooldown*(1+jitter)]
    reopen = br.retry_after(now())
    assert 2.0 <= reopen <= 3.0
    assert not br.allow(now())           # still open
    clock[0] += reopen + 0.01
    assert br.allow(now())               # -> half-open, one probe
    assert br.state == "half_open"
    assert not br.allow(now())           # second probe denied
    br.note_failure(now())               # probe failed -> reopen
    assert br.state == "open"
    # cooldown doubled (2.0 -> 4.0 base, still jitter-bounded)
    assert 4.0 <= br.retry_after(now()) <= 6.0
    clock[0] += br.retry_after(now()) + 0.01
    assert br.allow(now())
    br.note_success(now())               # probe succeeded -> closed
    assert br.state == "closed"
    # cooldown reset: a fresh trip starts from the base again
    for _ in range(3):
        br.note_failure(now())
    assert 2.0 <= br.retry_after(now()) <= 3.0
    assert [s for s, _ in transitions] == [
        "open", "half_open", "open", "half_open", "closed", "open"]


def test_circuit_breaker_force_open_on_death():
    br = CircuitBreaker(threshold=5, cooldown=1.0, jitter=0.0)
    br.force_open(10.0)
    assert br.state == "open" and br.trips == 1
    assert not br.allow(10.5)
    assert br.allow(11.01) and br.state == "half_open"


# ------------------------------------------------------ request gateway


def test_gateway_submit_poll_drain_typed_rejections(serving_fixture):
    from shallowspeed_tpu.serving import ServingEngine

    params, cfg = serving_fixture
    eng = ServingEngine(params, cfg, n_blocks=32, block_size=8,
                        max_slots=4, prefill_chunk=16)
    gw = RequestGateway(max_queue=2)
    ok = gw.submit_request({"id": "a",
                            "prompt": [int(t) for t in toks(0, t=8)],
                            "max_new": 4})
    assert ok == {"ok": True, "id": "a"}
    # duplicate rejected before it ever reaches the engine
    assert "duplicate" in gw.submit_request(
        {"id": "a", "prompt": [1], "max_new": 2})["error"]
    gw.submit_request({"id": "b", "prompt": [1, 2], "max_new": 2})
    over = gw.submit_request({"id": "c", "prompt": [1], "max_new": 2})
    assert over["error"] == "EngineOverloaded"
    assert over["retry_after"] > 0
    assert gw.pump(eng) == 2
    while eng.pending():
        eng.step()
        gw.publish(eng)
    snap = gw.poll_requests()["requests"]
    assert snap["a"]["status"] == "done"
    assert len(snap["a"]["tokens"]) == 4
    # drain: typed rejection at the gateway edge, no engine involved
    assert gw.drain_request()["draining"] is True
    rej = gw.submit_request({"id": "d", "prompt": [1], "max_new": 2})
    assert rej["error"] == "EngineDraining" and rej["retry_after"] > 0
    assert gw.idle()
    # a malformed request publishes as rejected instead of crashing
    gw2 = RequestGateway()
    gw2.submit_request({"id": "bad", "prompt": [999999],
                        "max_new": -1})
    gw2.pump(eng)
    assert gw2.poll_requests()["requests"]["bad"]["status"] \
        == "rejected"


# ------------------------------------------------- dispatch + balance


def test_router_routes_to_least_loaded(serving_fixture):
    params, cfg = serving_fixture
    router = Router(make_spawn(params, cfg), n_replicas=2,
                    request_timeout=None)
    # 4 requests dispatched in one step: the score (router in-flight
    # + replica queue pressure) must spread them over BOTH replicas
    # rather than pile onto the first name
    for i in range(4):
        router.submit(toks(i, t=8), 4, rid=f"pre{i}")
    router.step()
    by_replica = {}
    for r in router.inflight.values():
        by_replica.setdefault(r.replica, []).append(r.rid)
    assert set(by_replica) == {"r0", "r1"}, by_replica
    assert {len(v) for v in by_replica.values()} == {2}, by_replica
    router.run(max_wall=120)
    assert len(router.results) == 4
    routes = [e for e in router.events if e["event"] == "route"]
    assert {e["replica"] for e in routes} == {"r0", "r1"}
    for e in routes:
        assert validate_line(e) == []


def test_router_backpressure_typed_reject(serving_fixture):
    params, cfg = serving_fixture
    router = Router(make_spawn(params, cfg), n_replicas=1,
                    queue_budget=2, request_timeout=None)
    router.submit(toks(0, t=8), 4, rid="a")
    router.submit(toks(1, t=8), 4, rid="b")
    with pytest.raises(FleetOverloaded) as ei:
        router.submit(toks(2, t=8), 4, rid="c")
    assert ei.value.retry_after > 0
    assert router.counters["rejected"] == 1
    # every replica down -> the other reject shape, with the breaker /
    # respawn wait as the retry hint
    router.run(max_wall=120)
    router._replicas["r0"]["handle"].kill()
    router.step()
    with pytest.raises(FleetOverloaded):
        router.submit(toks(3, t=8), 4, rid="d")
    with pytest.raises(ValueError, match="duplicate"):
        router.submit(toks(4, t=8), 4, rid="a")


# ----------------------------------- THE canary: failover mid-decode


def test_router_failover_midstream_token_identical(serving_fixture,
                                                   tmp_path):
    """The in-process half of the pinned fleet chaos drill: kill a
    replica while requests are mid-decode on it — every stream still
    completes token-identical to its solo oracle (seeded idempotent
    re-dispatch re-prefills prompt + prefix elsewhere), a failover
    event is recorded per re-dispatched request, the breaker trips
    and recovers, and the restart_downtime stamp carries replica +
    fail_class. Greedy AND sampled requests (the key-schedule proof)."""
    import time

    from shallowspeed_tpu.metrics import MetricsLogger

    params, cfg = serving_fixture
    log = tmp_path / "router.jsonl"
    router = Router(make_spawn(params, cfg), n_replicas=2,
                    metrics=MetricsLogger(log, kind="router"),
                    request_timeout=None,
                    breaker_kw=dict(cooldown=0.05, jitter=0.0),
                    policy_kw=dict(backoff=0.01, jitter=0.0))
    reqs = {"g": (toks(20, t=10), 8, 0.0, 0),
            "s": (toks(21, t=13), 8, 1.0, 7),
            "t": (toks(22, t=9), 8, 0.7, 3)}
    oracle = {k: solo(params, cfg, p, mn, temperature=tmp, seed=s)
              for k, (p, mn, tmp, s) in reqs.items()}
    for k, (p, mn, tmp, s) in reqs.items():
        router.submit(p, mn, temperature=tmp, seed=s, rid=k)
    # step until at least one request is mid-stream on r0
    for _ in range(500):
        router.step()
        if any(r.replica == "r0" and 1 <= len(r.tokens) < r.max_new
               for r in router.inflight.values()):
            break
    assert any(r.replica == "r0" for r in router.inflight.values())
    router._replicas["r0"]["handle"].kill()          # SIGKILL analog
    res = router.run(max_wall=120)
    for k, ref in oracle.items():
        np.testing.assert_array_equal(res[k], ref, err_msg=k)
    assert router.counters["failovers"] >= 1
    fos = [e for e in router.events if e["event"] == "failover"]
    assert fos and all(validate_line(e) == [] for e in fos)
    assert all(e["from"] == "r0" and e["replica"] != "r0"
               for e in fos)
    # respawn + breaker recovery (the probe is the progress poll)
    t0 = time.monotonic()
    while time.monotonic() - t0 < 10.0:
        router.step()
        if router.counters["respawns"] >= 1 \
                and router._breakers["r0"].state == "closed":
            break
        time.sleep(0.01)
    assert router.counters["respawns"] == 1
    assert router._breakers["r0"].state == "closed"
    led = [e for e in router.events if e["event"] == "ledger"]
    states = [e["state"] for e in led if e.get("kind") == "breaker"]
    assert states == ["open", "half_open", "closed"]
    rd = [e for e in led if e.get("kind") == "restart_downtime"]
    assert rd and rd[0]["replica"] == "r0" \
        and rd[0]["fail_class"] == "crash" and rd[0]["seconds"] > 0
    # the recovered replica serves again
    router.submit(toks(23, t=8), 4, rid="post")
    router.run(max_wall=120)
    assert "post" in router.results
    # the router log validates as schema v10 end to end
    assert SCHEMA_VERSION >= 10
    assert validate_file(log) == []


def test_router_timeout_failover_reattaches_when_alone(
        serving_fixture):
    """A progress-timeout failover with nowhere else to go (single
    replica, still alive) re-attaches to the original replica instead
    of re-submitting a duplicate id — the work is still running
    there."""
    params, cfg = serving_fixture
    clock = [0.0]
    router = Router(make_spawn(params, cfg), n_replicas=1,
                    clock=lambda: clock[0], request_timeout=5.0,
                    breaker_kw=dict(threshold=99))
    router.submit(toks(30, t=8), 6, rid="x")
    router.step()
    assert router.inflight["x"].replica == "r0"
    clock[0] += 10.0                       # no progress for 10 "s"
    router.step()
    assert router.inflight["x"].replica == "r0"    # re-attached
    assert router.counters["failovers"] == 0
    res = router.run(max_wall=120)
    np.testing.assert_array_equal(
        res["x"], solo(params, cfg, toks(30, t=8), 6,
                       temperature=0.0))


def test_router_deadline_exceeded_is_typed(serving_fixture):
    params, cfg = serving_fixture
    clock = [0.0]
    router = Router(make_spawn(params, cfg), n_replicas=1,
                    clock=lambda: clock[0], request_timeout=None)
    router.submit(toks(31, t=8), 6, rid="dl", deadline_s=2.0)
    clock[0] += 5.0
    router.step()
    assert "dl" not in router.results
    rec = next(r for r in router.records if r["id"] == "dl")
    assert rec["status"] == "deadline_exceeded"
    assert router.counters["failed"] == 1
    assert router.unfinished() == 0


# --------------------------------------------- autoscale (end to end)


def test_router_autoscale_burn_up_then_idle_drain(serving_fixture,
                                                  tmp_path):
    """Acceptance: a sustained ttft burn (every completion violates a
    deliberately-impossible 1 ms SLO under a fake clock) fires the
    dual-window critical alert, the router spawns a replica, the burn
    clears (alert resolves); then sustained idle drains one replica
    via graceful drain + collector deregistration — with zero dropped
    requests."""
    from shallowspeed_tpu.metrics import MetricsLogger
    from shallowspeed_tpu.telemetry.fleet import FleetCollector

    params, cfg = serving_fixture
    clock = [1000.0]
    collector = FleetCollector()
    log = tmp_path / "scale.jsonl"
    base_spawn = make_spawn(params, cfg, clock=lambda: clock[0],
                            max_slots=2)

    def spawn(name):
        p = tmp_path / f"{name}.jsonl"
        p.write_text("")
        collector.add_file(p, label=name)
        return base_spawn(name)

    router = Router(spawn, n_replicas=1, collector=collector,
                    metrics=MetricsLogger(log, kind="router"),
                    clock=lambda: clock[0],
                    slos="ttft_p95_ms<1",
                    slo_kw=dict(fast_s=5, slow_s=20, min_count=3),
                    request_timeout=None, autoscale=True,
                    min_replicas=1, max_replicas=2,
                    scale_hold_s=1.0, idle_drain_s=2.0,
                    scale_cooldown_s=0.5)
    for i in range(6):
        router.submit(toks(40 + i, t=8), 4, rid=f"x{i}")
    for _ in range(600):
        clock[0] += 0.1
        router.step()
        if router.counters["scale_ups"] and not router.unfinished():
            break
    assert router.counters["scale_ups"] == 1
    assert router.replica_names() == ["r0", "r1"]
    assert len(router.results) == 6            # zero dropped
    alerts = [e for e in router.events if e["event"] == "alert"]
    assert alerts[0]["state"] == "firing" \
        and alerts[0]["severity"] == "critical"
    # idle: the burn ages out (alert resolves) and a replica drains
    for _ in range(400):
        clock[0] += 0.1
        router.step()
        if router.counters["scale_downs"]:
            break
    assert router.counters["scale_downs"] == 1
    assert router.replica_names() == ["r0"]
    assert all(r.state is None for r in router.rules)   # burn cleared
    alerts = [e for e in router.events if e["event"] == "alert"]
    assert [e["state"] for e in alerts
            if e["slo"] == "ttft_p95_ms<1"][-1] == "resolved"
    # deregistration: the drained replica left the collector
    assert [rep.name for rep in collector.replicas] == ["r0"]
    scale = [e for e in router.events if e["event"] == "scale"]
    assert [e["action"] for e in scale] == ["up", "drain", "down"]
    assert all(validate_line(e) == [] for e in scale)
    assert scale[0]["reason"] == "burn" and scale[0]["burn"] > 1
    assert validate_file(log) == []


# ------------------------------------------------- schema + goodput


def test_schema_v10_route_failover_scale_validation():
    assert SCHEMA_VERSION >= 10
    good = [
        {"event": "route", "id": "a", "replica": "r0",
         "queue_depth": 2, "score": 1.5},
        {"event": "failover", "id": "a", "replica": "r1",
         "reason": "death", "from": "r0", "tokens_done": 3,
         "attempt": 1},
        {"event": "scale", "action": "up", "replica": "r2",
         "reason": "burn", "burn": 12.0, "n_replicas": 3},
        {"event": "ledger", "kind": "breaker", "replica": "r0",
         "state": "open"},
        {"event": "ledger", "kind": "restart_downtime",
         "seconds": 0.5, "fail_class": "hang", "replica": "r0"},
        {"event": "request", "id": "a", "ttft_ms": 5.0,
         "tokens_in": 4, "tokens_out": 8, "replica": "r1",
         "failovers": 1},
        {"event": "lifecycle", "id": "a", "phase": "submit",
         "resumed": 3},
    ]
    for rec in good:
        assert validate_line(rec) == [], rec
    bad = [
        {"event": "route", "id": "a"},                 # no replica
        {"event": "failover", "id": "a", "replica": "r1"},  # no reason
        {"event": "scale"},                            # no action
        {"event": "route", "id": "a", "replica": "r0",
         "score": "high"},
        {"event": "ledger", "kind": "breaker", "replica": 3},
        {"event": "request", "id": "a", "ttft_ms": 1.0,
         "tokens_in": 1, "tokens_out": 1, "failovers": "two"},
    ]
    for rec in bad:
        assert validate_line(rec) != [], rec


def test_goodput_fleet_block_per_replica_mttr(tmp_path):
    """A synthetic router log reduces to the fleet block: per-replica
    MTTR from replica-stamped restart_downtime lines, breaker trips,
    failover/scale tallies, and fleet availability — and the
    formatted report prints them."""
    from shallowspeed_tpu.telemetry.goodput import (format_report,
                                                    run_goodput)

    log = tmp_path / "router.jsonl"
    wall0 = 1000.0
    lines = [{"event": "run_start", "schema_version": 10,
              "kind": "router", "wall": wall0, "t": 0.0}]
    for i, rep in enumerate(["r0", "r1", "r0"]):
        lines.append({"event": "route", "id": f"q{i}", "replica": rep,
                      "wall": wall0 + 1 + i, "t": 1.0 + i})
    lines += [
        {"event": "ledger", "kind": "breaker", "replica": "r0",
         "state": "open", "wall": wall0 + 5, "t": 5.0},
        {"event": "failover", "id": "q0", "replica": "r1",
         "reason": "death", "from": "r0", "tokens_done": 2,
         "wall": wall0 + 5.1, "t": 5.1},
        {"event": "ledger", "kind": "restart_downtime", "seconds": 2.0,
         "fail_class": "crash", "replica": "r0", "wall": wall0 + 7,
         "t": 7.0},
        {"event": "ledger", "kind": "restart_downtime", "seconds": 1.0,
         "fail_class": "hang", "replica": "r0", "wall": wall0 + 9,
         "t": 9.0},
        {"event": "scale", "action": "up", "replica": "r2",
         "reason": "burn", "wall": wall0 + 10, "t": 10.0},
        {"event": "request", "id": "q0", "ttft_ms": 50.0,
         "tokens_in": 4, "tokens_out": 8, "replica": "r1",
         "failovers": 1, "wall": wall0 + 20, "t": 20.0},
    ]
    log.write_text("".join(json.dumps(r) + "\n" for r in lines))
    assert validate_file(log) == []
    rep = run_goodput(log)
    fl = rep["fleet"]
    assert fl["routes"] == 3 and fl["failovers"] == 1
    assert fl["breaker_trips"] == 1
    assert fl["scale"] == {"up": 1}
    assert set(fl["replicas"]) == {"r0", "r1", "r2"}
    assert fl["mttr"]["r0"]["count"] == 2
    assert fl["mttr"]["r0"]["mttr_s"] == pytest.approx(1.5)
    # wall span = 20s; r0 down 3s of it -> 0.85; others 1.0
    assert fl["availability"]["r0"] == pytest.approx(0.85)
    assert fl["availability"]["r1"] == 1.0
    assert fl["fleet_availability"] == pytest.approx((0.85 + 2) / 3)
    # per-class MTTR (the training-era block) still reduces alongside
    assert rep["mttr"]["crash"]["count"] == 1
    out = format_report(rep)
    assert "fleet [r0, r1, r2]" in out and "mttr[r0" in out
    assert "fleet availability" in out
    # a training log (no routing events) has no fleet block
    assert run_goodput(ROOT / "docs_runs"
                       / "chaos_r06_metrics.jsonl")["fleet"] is None


# ------------------------------- cross-process fleet chaos drill (slow)


def _oracle_params_cfg():
    import jax

    from shallowspeed_tpu.models import transformer as T

    cfg = T.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                              n_layers=2, max_seq=128)
    return jax.device_put(T.init(cfg, seed=0)), cfg


def test_fleet_chaos_drill_cross_process(tmp_path):
    """THE pinned drill (slow tier): a real router over three
    `serve.py --serve` subprocess replicas under a seeded fleet chaos
    plan — r0 SIGKILLed mid-decode (kill@3 on its engine ticks), r1
    stalled (stall@2:0.75), r2's heartbeat frozen (freeze@1, so the
    router's hang detector kills it). Every submitted request still
    completes with a stream token-identical to its solo oracle, ≥1
    failover event and ≥1 breaker trip/recover cycle are recorded,
    and `--goodput` over the router log reports per-replica MTTR with
    fleet availability."""
    import sys

    from shallowspeed_tpu.metrics import MetricsLogger
    from shallowspeed_tpu.serving.router import ReplicaProc
    from shallowspeed_tpu.telemetry.fleet import FleetCollector
    from shallowspeed_tpu.telemetry.goodput import (format_report,
                                                    run_goodput)
    from shallowspeed_tpu.telemetry.monitor import StatusServer

    params, cfg = _oracle_params_cfg()
    chaos_map = {"r0": "kill@3", "r1": "stall@2:0.75",
                 "r2": "freeze@1"}
    collector = FleetCollector()
    srv = StatusServer(collector, port=0)
    fleet_url = f"http://{srv.host}:{srv.port}"
    serve_py = str(ROOT / "serve.py")

    def spawn(name):
        hb = str(tmp_path / f"hb_{name}")
        argv = [sys.executable, serve_py, "--serve",
                "--monitor-port", "0", "--fleet-register", fleet_url,
                "--replica", name, "--platform", "cpu",
                "--log-file", str(tmp_path / f"rep_{name}.jsonl"),
                "--heartbeat-file", hb,
                "--vocab", "64", "--d-model", "32", "--n-heads", "4",
                "--n-layers", "2", "--max-seq", "128",
                "--n-blocks", "32", "--block-size", "8",
                "--slots", "4", "--prefill-chunk", "16",
                "--chaos", chaos_map[name],
                "--chaos-state", str(tmp_path / f"chaos_{name}"),
                "--chaos-seed", "0"]
        # hang_timeout must clear the engine's worst compile pause (a
        # fresh replica's first tick blocks the serve loop for seconds
        # on a loaded CPU host) — 20 s kills only a genuinely frozen
        # heartbeat, which is exactly r2's chaos fault
        return ReplicaProc(
            name, argv, collector, heartbeat_file=hb,
            hang_timeout=20.0, term_grace=3.0,
            stdout_path=str(tmp_path / f"rep_{name}.out"))

    log = tmp_path / "router.jsonl"
    router = Router(spawn, n_replicas=3, collector=collector,
                    metrics=MetricsLogger(log, kind="router"),
                    request_timeout=45.0, progress_interval=0.1,
                    breaker_kw=dict(cooldown=0.5, jitter=0.2),
                    policy_kw=dict(backoff=0.2, jitter=0.1))
    collector.start(poll=0.3)
    try:
        # wait for EVERY replica to register before offering load, so
        # dispatch spreads 2/2/2 and each replica's engine ticks reach
        # its scheduled fault — the drill must be deterministic, not a
        # race on whose jax import wins
        import time

        t0 = time.monotonic()
        while time.monotonic() - t0 < 120.0:
            router.step()
            if not any(e["warming"]
                       for e in router._replicas.values()):
                break
            time.sleep(0.1)
        assert not any(e["warming"] for e in router._replicas.values())
        reqs = {f"q{i}": (toks(60 + i, t=8 + 2 * (i % 3)), 6,
                          0.7 if i % 2 else 0.0, i)
                for i in range(6)}
        oracle = {k: solo(params, cfg, p, mn, temperature=tmp, seed=s)
                  for k, (p, mn, tmp, s) in reqs.items()}
        for k, (p, mn, tmp, s) in reqs.items():
            router.submit(p, mn, temperature=tmp, seed=s, rid=k)
        res = router.run(max_wall=300.0, poll=0.05)
        for k, ref in oracle.items():
            np.testing.assert_array_equal(res[k], ref, err_msg=k)
        assert router.counters["failovers"] >= 1
        fos = [e for e in router.events if e["event"] == "failover"]
        assert fos and all(validate_line(e) == [] for e in fos)
        # the kill fault actually fired on r0 (forensic stamp in its
        # metrics JSONL), and the stall on r1
        r0recs = [json.loads(line) for line in
                  (tmp_path / "rep_r0.jsonl").read_text().splitlines()]
        assert any(r.get("event") == "fault"
                   and r.get("kind") == "kill" for r in r0recs)
        r1recs = [json.loads(line) for line in
                  (tmp_path / "rep_r1.jsonl").read_text().splitlines()]
        assert any(r.get("event") == "fault"
                   and r.get("kind") == "stall" for r in r1recs)
        # breaker tripped (death force-open at least); keep stepping
        # until (a) a tripped breaker recovered via the half-open
        # probe against its respawned replica and (b) r2's frozen
        # heartbeat was detected as a HANG (20 s staleness) and
        # stamped with its class
        assert router.counters["breaker_trips"] >= 1

        def hang_stamped():
            return any(e.get("kind") == "restart_downtime"
                       and e.get("fail_class") == "hang"
                       for e in router.events
                       if e["event"] == "ledger")

        t0 = time.monotonic()
        while time.monotonic() - t0 < 120.0:
            router.step()
            if hang_stamped() and any(
                    br.state == "closed" and br.trips
                    for br in router._breakers.values()):
                break
            time.sleep(0.05)
        recovered = [n for n, br in router._breakers.items()
                     if br.trips and br.state == "closed"]
        assert recovered, {n: br.state
                           for n, br in router._breakers.items()}
        assert hang_stamped(), [e for e in router.events
                                if e["event"] == "ledger"]
        assert router.counters["respawns"] >= 1
    finally:
        router.shutdown()
        collector.stop()
        srv.close()
    # --goodput over the router log: per-replica MTTR + availability
    assert validate_file(log) == []
    rep = run_goodput(log)
    fl = rep["fleet"]
    assert fl["failovers"] >= 1 and fl["breaker_trips"] >= 1
    assert fl["mttr"], fl
    for m in fl["mttr"].values():
        assert m["count"] >= 1 and m["mttr_s"] > 0
    assert fl["fleet_availability"] is not None
    assert fl["fleet_availability"] >= 0.5
    out = format_report(rep)
    assert "fleet availability" in out
    # round 16: the drill's logs stitch into ONE skew-corrected trace
    # — a failed-over request's spans from the router and BOTH
    # replicas on a single ordered timeline, its waterfall closing
    # within 5% of the measured e2e (telemetry/tracing.py; the full
    # acceptance canary is tests/test_tracing.py)
    from shallowspeed_tpu.telemetry import tracing
    from shallowspeed_tpu.telemetry.report import request_waterfall

    replica_logs = [tmp_path / f"rep_{n}.jsonl"
                    for n in ("r0", "r1", "r2")]
    st = tracing.stitch([log] + replica_logs)
    fos = [e for e in router.events if e["event"] == "failover"]
    spanning = [st["journeys"][e["trace"]] for e in fos
                if len(st["journeys"][e["trace"]]["sources"]) >= 3]
    assert spanning, [st["journeys"][e["trace"]]["sources"]
                      for e in fos]
    for jn in spanning:
        t_att = {att: [t for t, _p, _r in evs]
                 for att, evs in jn["attempts"].items()}
        atts = sorted(t_att)
        for a, b in zip(atts, atts[1:]):
            assert max(t_att[a]) <= min(t_att[b]) + 1e-6
        wf = request_waterfall(jn)
        assert wf is not None
        assert abs(wf["rq_unexplained_frac"]) <= 0.05, (jn["rid"], wf)
        assert wf["rq_failover_gap_ms"] > 0.0


# --------------------------- sticky prefix affinity (round 19)


def test_router_sticky_prefix_affinity(serving_fixture):
    """Sticky routing homes a shared-prefix family on the replica
    that already served it (decisively — the 1.5-capped bonus beats
    one unit of queue pressure plus telemetry noise), load still
    overrides locality once the home's backlog exceeds the cap, the
    route events carry the schema-v14 affinity field, and every
    stream stays token-identical to its solo oracle."""
    params, cfg = serving_fixture
    router = Router(make_spawn(params, cfg, prefix_cache=True,
                               prefill_chunk=8),
                    n_replicas=2, request_timeout=None,
                    sticky=True, sticky_block=8)
    fam = toks(50, t=32)                 # 4 fingerprint chunks of 8
    oracle = solo(params, cfg, fam, 4, temperature=0.0)
    router.submit(fam, 4, rid="cold")
    router.run(max_wall=120)
    routes = {e["id"]: e for e in router.events
              if e["event"] == "route"}
    home = routes["cold"]["replica"]
    assert routes["cold"]["affinity"] == 0.0     # nothing seen yet
    # a decoy occupies whichever replica the load tie-break prefers;
    # the family's sharer must still go HOME (bonus 1.5 > load 1)
    router.submit(toks(51, t=32), 4, rid="decoy")
    router.submit(fam, 4, rid="warm", temperature=0.8, seed=7)
    router.run(max_wall=120)
    routes = {e["id"]: e for e in router.events
              if e["event"] == "route"}
    assert routes["warm"]["replica"] == home
    assert routes["warm"]["affinity"] >= 1.0
    np.testing.assert_array_equal(
        router.results["warm"],
        solo(params, cfg, fam, 4, temperature=0.8, seed=7))
    np.testing.assert_array_equal(router.results["cold"], oracle)
    # bounded: a burst of sharers overflows once home's backlog
    # exceeds the cap — the 3rd concurrent family request spills to
    # the other replica instead of queueing behind locality
    for i in range(3):
        router.submit(fam, 4, rid=f"burst{i}", temperature=0.0)
    router.step()
    placed = {r.rid: r.replica for r in router.inflight.values()}
    assert placed["burst0"] == home and placed["burst1"] == home
    assert placed["burst2"] != home, (
        "sticky bonus outranked load — the cap is not bounding")
    router.run(max_wall=120)
    for i in range(3):
        np.testing.assert_array_equal(router.results[f"burst{i}"],
                                      oracle, err_msg=f"burst{i}")
    for e in router.events:
        if e["event"] == "route":
            assert validate_line(e) == []
            assert isinstance(e["affinity"], float)
    # a dead replica's affinity history dies with it — the respawned
    # successor starts cold instead of attracting stale traffic
    assert home in router._affinity
    router._on_replica_down(home, "crash", now=0.0)
    assert home not in router._affinity


def test_router_sticky_off_emits_no_affinity(serving_fixture):
    """sticky=False keeps the route schema at its load-only shape: no
    affinity field, no fingerprinting work on submit."""
    params, cfg = serving_fixture
    router = Router(make_spawn(params, cfg), n_replicas=2,
                    request_timeout=None, sticky=False)
    router.submit(toks(52, t=16), 4, rid="q")
    router.run(max_wall=120)
    route = next(e for e in router.events if e["event"] == "route")
    assert "affinity" not in route
    assert validate_line(route) == []


def test_router_headroom_penalty_deprioritizes_near_oom(serving_fixture):
    """The v15 capacity plane's placement pin: a replica whose
    admission headroom is NEGATIVE (accepted max-token budgets already
    overcommit its block pool) is deprioritized — placing work there
    buys evictions, not throughput — while positive headroom costs
    nothing, and the penalty is capped so a deeply-overcommitted
    replica still ranks when it is the only one alive."""
    params, cfg = serving_fixture
    router = Router(make_spawn(params, cfg), n_replicas=2,
                    request_timeout=None, sticky=False)
    base = {"queue_depth": 0, "active_slots": 0, "free_blocks": 10}
    h0 = router._replicas["r0"]["handle"]
    h1 = router._replicas["r1"]["handle"]
    h0.telemetry = lambda: dict(base, headroom_blocks=12)
    h1.telemetry = lambda: dict(base, headroom_blocks=-6)
    now = router.clock()
    # each overcommitted block is one full score unit — decisive
    # against telemetry noise, unlike the 0.001/free-block nudge
    assert router._score("r1", now) - router._score("r0", now) \
        == pytest.approx(6.0)
    # capped at 20: a catastrophically-overcommitted replica is
    # deprioritized, not unroutable
    h1.telemetry = lambda: dict(base, headroom_blocks=-10_000)
    assert router._score("r1", now) - router._score("r0", now) \
        == pytest.approx(20.0)
    # placement: the submitted request lands on the healthy replica
    h1.telemetry = lambda: dict(base, headroom_blocks=-6)
    router.submit(toks(53, t=8), 4, rid="x")
    router.step()
    assert router.inflight["x"].replica == "r0"
    router.run(max_wall=120)
    assert "x" in router.results
    # positive headroom itself is never a tiebreak bonus beyond the
    # free-blocks nudge: two healthy replicas score identically
    h1.telemetry = lambda: dict(base, headroom_blocks=2)
    h0.telemetry = lambda: dict(base, headroom_blocks=900)
    assert router._score("r0", router.clock()) \
        == pytest.approx(router._score("r1", router.clock()))
