"""3-D composite parallelism (`parallel/composite.py`): dp x sp x tp on one
mesh, optionally + ZeRO-3 parameter sharding.

Correctness oracle: every axis is a placement decision over the SAME
jitted program, so any (dp, sp, tp) layout must reproduce the serial
(1, 1, 1) trajectory up to float reassociation.
"""

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.optim import Adam, SGD
from shallowspeed_tpu.parallel.composite import Composite3DEngine
from shallowspeed_tpu.parallel.fsdp import add_dp as _add_dp

CFG = T.TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                          max_seq=32)


def mesh3(dp, sp, tp):
    devs = np.array(jax.devices()[: dp * sp * tp]).reshape(dp, sp, tp)
    return Mesh(devs, ("dp", "sp", "tp"))


def batch(seed=0, b=8, t=32, vocab=64):
    rng = np.random.default_rng(seed)
    tok = rng.integers(0, vocab, (b, t)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1).astype(np.int32)


# ------------------------------------------------------------- placement


def test_add_dp_respects_existing_axes():
    assert _add_dp(P(None, "tp"), (64, 32), 2) == P("dp", "tp")
    assert _add_dp(P("tp", None), (32, 128), 2) == P("tp", "dp")
    assert _add_dp(P(), (7,), 2) == P()          # nothing divisible
    assert _add_dp(P("tp"), (32,), 2) == P("tp")  # fully sharded already


def test_param_placement_tp_and_fsdp():
    eng = Composite3DEngine(CFG, Adam(1e-3), mesh3(2, 2, 2), fsdp=True)
    qkv = eng.params["blocks"][0]["qkv"]["W"]
    assert set(qkv.sharding.spec) == {"dp", "tp"}
    # embeddings: replicated under plain TP, dp-sharded with fsdp
    assert "dp" in eng.params["tok_emb"].sharding.spec
    # moments inherit
    assert eng.opt_state["m"]["blocks"][0]["qkv"]["W"].sharding == qkv.sharding


def test_moe_config_rejected():
    with pytest.raises(AssertionError, match="dense FFN"):
        Composite3DEngine(replace(CFG, n_experts=4), Adam(1e-3),
                          mesh3(2, 2, 2))


def test_fsdp_zero1_conflict():
    with pytest.raises(ValueError, match="drop zero1"):
        Composite3DEngine(CFG, Adam(1e-3), mesh3(2, 2, 2),
                          zero1=True, fsdp=True)


# ----------------------------------------------------------- equivalence


def serial_engine(opt):
    return Composite3DEngine(CFG, opt, mesh3(1, 1, 1), seed=0)


@pytest.mark.parametrize("dp,sp,tp", [(2, 2, 2), (1, 4, 2), (2, 1, 4),
                                      (4, 2, 1)])
def test_composite_matches_serial(dp, sp, tp):
    ser = serial_engine(SGD(0.1))
    eng = Composite3DEngine(CFG, SGD(0.1), mesh3(dp, sp, tp), seed=0)
    for step in range(4):
        tok, tgt = batch(step)
        ls = ser.train_batch(tok, tgt)
        lc = eng.train_batch(tok, tgt)
        assert lc == pytest.approx(ls, rel=3e-4), (step, dp, sp, tp)
    for a, b in zip(jax.tree_util.tree_leaves(eng.params),
                    jax.tree_util.tree_leaves(ser.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-5)


@pytest.mark.parametrize("kw", [{"fsdp": True}, {"zero1": True}])
def test_composite_sharded_state_matches_serial(kw):
    ser = serial_engine(Adam(1e-2))
    eng = Composite3DEngine(CFG, Adam(1e-2), mesh3(2, 2, 2), seed=0, **kw)
    for step in range(4):
        tok, tgt = batch(step)
        ls = ser.train_batch(tok, tgt)
        lc = eng.train_batch(tok, tgt)
        assert lc == pytest.approx(ls, rel=3e-4), (step, kw)


# -------------------------------------------------------------- training


def test_composite_trains_bf16():
    cfg16 = replace(CFG, compute_dtype=jnp.bfloat16)
    eng = Composite3DEngine(cfg16, Adam(5e-3), mesh3(2, 2, 2), seed=0,
                            fsdp=True)
    tok, tgt = batch(7)
    losses = [eng.train_batch(tok, tgt) for _ in range(25)]
    assert losses[-1] < losses[0] - 0.2, losses[::6]


def test_composite_checkpoint_roundtrip(tmp_path):
    from shallowspeed_tpu import checkpoint

    eng = Composite3DEngine(CFG, Adam(1e-2), mesh3(2, 2, 2), seed=0,
                            fsdp=True)
    tok, tgt = batch(3)
    for _ in range(2):
        eng.train_batch(tok, tgt)
    checkpoint.save(str(tmp_path), eng, 2)
    eng2 = Composite3DEngine(CFG, Adam(1e-2), mesh3(2, 2, 2), seed=1,
                             fsdp=True)
    assert checkpoint.restore(eng2, checkpoint.latest(str(tmp_path))) == 3
    l1 = eng.train_batch(tok, tgt)
    l2 = eng2.train_batch(tok, tgt)
    assert l1 == pytest.approx(l2, rel=1e-5)
