"""Fused SPMD pipeline vs fused sequential engine — exact-capability checks.

The SPMD GPipe step must produce the same trained weights as sequential
training (same grads modulo float reassociation), across dp x pp layouts on
the virtual 8-device mesh, with padding provably inert.
"""

import numpy as np
import pytest

import jax

from shallowspeed_tpu.data.dataset import Dataset
from shallowspeed_tpu.data.mnist import prepare_mnist
from shallowspeed_tpu.engine import FusedDPEngine
from shallowspeed_tpu.models.mlp import MLPStage
from shallowspeed_tpu.optim import SGD, Adam, MomentumSGD
from shallowspeed_tpu.parallel.mesh import make_mesh
from shallowspeed_tpu.parallel.spmd_pipeline import SPMDPipelineEngine, StageStack

SIZES = [784, 32, 31, 30, 29, 28, 27, 10]
GBS = 64
N_MU = 4
LR = 0.5


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("mnist_spmd")
    prepare_mnist(d, synthetic=True, n_samples=1024)
    return d


def make_datasets(data_dir, dp, val=False):
    local = GBS // dp
    mubs = local if val else local // N_MU
    return [Dataset(data_dir, GBS, mubs, validation=val).load(r, dp)
            for r in range(dp)]


def train_spmd(data_dir, dp, pp, n_batches=3, opt=None, epoch_mode=False):
    mesh = make_mesh(dp, pp)
    eng = SPMDPipelineEngine(SIZES, opt or SGD(LR), mesh, N_MU,
                             (GBS // dp) // N_MU, GBS)
    ds = make_datasets(data_dir, dp)
    if epoch_mode:
        staged = eng.stage_epoch(ds, n_batches)
        eng.train_epoch(staged)
    else:
        for b in range(n_batches):
            eng.train_batch(b, ds)
    return eng

def train_fused(data_dir, n_batches=3, opt=None):
    stage = MLPStage(SIZES, 0, 1, batch_size=GBS)
    eng = FusedDPEngine(stage, opt or SGD(LR), make_mesh(1, 1))
    ds = make_datasets(data_dir, 1)
    for b in range(n_batches):
        eng.train_batch(b, ds)
    return eng


def assert_matches_fused(spmd_eng, fused_eng, rtol=3e-4, atol=3e-6):
    flat_spmd = [np.asarray(l)
                 for stage_p in spmd_eng.unstacked_params
                 for layer in stage_p
                 for l in (layer["W"], layer["b"])]
    flat_fused = [np.asarray(l)
                  for layer in fused_eng.params
                  for l in (layer["W"], layer["b"])]
    assert len(flat_spmd) == len(flat_fused)
    for a, b in zip(flat_spmd, flat_fused):
        np.testing.assert_allclose(a, b, rtol=rtol, atol=atol)


# ------------------------------------------------------------------ tests


def test_stage_stack_padding_structure():
    st = StageStack(SIZES, 4)
    params, meta = st.init()
    assert params["W"].shape == (4, 2, 784, 784)
    assert meta["valid"].tolist() == [[1, 1], [1, 1], [1, 1], [1, 0]]
    assert meta["relu"][3].tolist() == [0.0, 0.0]  # last stage: no-act linear
    assert meta["relu"][0].tolist() == [1.0, 1.0]
    # padded regions are zero
    assert params["W"][0, 0, 128:, :].sum() == 0
    assert params["W"][0, 0, :, 784:].sum() == 0


@pytest.mark.parametrize("dp,pp", [(1, 2), (1, 4), (2, 2), (2, 4), (4, 2)])
def test_spmd_matches_sequential(data_dir, dp, pp):
    fused = train_fused(data_dir)
    spmd = train_spmd(data_dir, dp, pp)
    assert_matches_fused(spmd, fused)


def test_spmd_pp1(data_dir):
    """pp=1 degenerate pipeline must also match."""
    fused = train_fused(data_dir)
    spmd = train_spmd(data_dir, 1, 1)
    assert_matches_fused(spmd, fused)


def test_spmd_epoch_mode_matches_batch_mode(data_dir):
    a = train_spmd(data_dir, 2, 4, n_batches=3, epoch_mode=False)
    b = train_spmd(data_dir, 2, 4, n_batches=3, epoch_mode=True)
    for la, lb in zip(jax.tree_util.tree_leaves(a.params),
                      jax.tree_util.tree_leaves(b.params)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=1e-6, atol=1e-7)


def test_spmd_padding_stays_zero(data_dir):
    spmd = train_spmd(data_dir, 2, 4, n_batches=5)
    W = np.asarray(jax.device_get(spmd.params["W"]))
    st = spmd.stack
    from shallowspeed_tpu.models.mlp import stage_layer_sizes

    for s in range(st.pp):
        local = stage_layer_sizes(SIZES, s, st.pp)
        for i in range(st.L):
            if i < len(local) - 1:
                out_d, in_d = local[i + 1], local[i]
                assert np.abs(W[s, i, out_d:, :]).sum() == 0
                assert np.abs(W[s, i, :, in_d:]).sum() == 0
            else:
                assert np.abs(W[s, i]).sum() == 0  # whole layer is padding


def test_spmd_infer_matches_fused(data_dir):
    fused = train_fused(data_dir, n_batches=2)
    spmd = train_spmd(data_dir, 1, 4, n_batches=2)
    val = make_datasets(data_dir, 1, val=True)
    x = val[0].load_micro_batch_input(0, 0)
    np.testing.assert_allclose(
        np.asarray(spmd.infer(x)), np.asarray(fused.infer(x)),
        rtol=3e-4, atol=1e-6)


def test_spmd_with_momentum(data_dir):
    """Optimizer state shards over the stage axis like params."""
    fused = train_fused(data_dir, opt=MomentumSGD(0.05))
    spmd = train_spmd(data_dir, 2, 2, opt=MomentumSGD(0.05))
    assert_matches_fused(spmd, fused, rtol=1e-3, atol=1e-4)


def test_spmd_with_adam(data_dir):
    """Adam's normalized update m/(sqrt(v)+eps) is scale-free: the ~1e-6
    relative float-reassociation difference in the summed grads (reversed
    GPipe order + dp psum vs serial accumulation) turns into ~1e-2 relative
    update differences on near-zero-gradient entries, compounding per step.
    The check is therefore coarse; the real invariant (state shards like
    params and training stays in lockstep) is covered by the momentum test
    plus the magnitude bound here."""
    fused = train_fused(data_dir, opt=Adam(0.05))
    spmd = train_spmd(data_dir, 2, 2, opt=Adam(0.05))
    assert_matches_fused(spmd, fused, rtol=5e-2, atol=5e-3)


def test_spmd_grad_clip_uses_cross_stage_norm(data_dir):
    """Global-norm clipping must psum the squared norm over 'pp'
    (`optim.py clip_axes`): each device holds only its stage's gradient
    slice inside the shard_map step. A tight threshold makes clipping
    active every step, so a per-shard (wrong) norm would scale each
    stage's update differently and diverge from the serial run."""
    opt = lambda: SGD(LR, grad_clip=0.05)  # noqa: E731
    fused = train_fused(data_dir, opt=opt())
    spmd = train_spmd(data_dir, 2, 4, opt=opt())
    assert_matches_fused(spmd, fused, rtol=1e-3, atol=1e-5)
