"""Failure detection / elastic recovery (`shallowspeed_tpu/elastic.py`).

The reference has none of this (SURVEY §5: a rank failure kills the
mpirun job). Coverage: the restart policy's budget/backoff/refill
arithmetic (pure), the supervisor loop against real child processes
(crash-then-succeed, budget exhaustion, hang detection via heartbeat
staleness), and the driver-level contract (`--auto-resume` starts fresh
without a checkpoint and resumes with one — the property every restart
relies on).
"""

import json
import subprocess
import sys
import textwrap
import time
from pathlib import Path

import pytest

from shallowspeed_tpu.elastic import RestartPolicy, Supervisor


# ------------------------------------------------------------- policy


def test_policy_budget_and_backoff_doubling():
    p = RestartPolicy(max_restarts=3, backoff=1.0, backoff_max=3.0,
                      healthy_after=60.0)
    assert p.next_restart() == 1.0
    assert p.next_restart() == 2.0
    assert p.next_restart() == 3.0  # capped at backoff_max
    assert p.next_restart() is None  # budget exhausted


def test_policy_healthy_run_refills_budget():
    p = RestartPolicy(max_restarts=1, backoff=1.0, healthy_after=10.0)
    assert p.next_restart() == 1.0
    assert p.next_restart() is None
    p.record_run(11.0)  # child stayed up past the healthy window
    assert p.next_restart() == 1.0  # budget and backoff reset


def test_policy_short_run_does_not_refill():
    p = RestartPolicy(max_restarts=1, backoff=1.0, healthy_after=10.0)
    assert p.next_restart() == 1.0
    p.record_run(2.0)  # crash loop: stayed up 2s only
    assert p.next_restart() is None


# --------------------------------------------------------- supervisor


def _script(tmp_path, body) -> list:
    f = tmp_path / "child.py"
    f.write_text(textwrap.dedent(body))
    return [sys.executable, str(f)]


def test_supervisor_restarts_until_success(tmp_path):
    """Child crashes twice, then succeeds: the supervisor must retry
    through the failures and return 0."""
    marker = tmp_path / "attempts"
    cmd = _script(tmp_path, f"""
        from pathlib import Path
        m = Path({str(marker)!r})
        n = int(m.read_text()) if m.exists() else 0
        m.write_text(str(n + 1))
        raise SystemExit(0 if n >= 2 else 1)
    """)
    sup = Supervisor(cmd, RestartPolicy(max_restarts=5, backoff=0.01),
                     log=lambda *_: None)
    assert sup.run() == 0
    assert marker.read_text() == "3"  # 2 failures + 1 success


def test_supervisor_gives_up_when_budget_exhausted(tmp_path):
    cmd = _script(tmp_path, "raise SystemExit(7)")
    sup = Supervisor(cmd, RestartPolicy(max_restarts=2, backoff=0.01),
                     log=lambda *_: None)
    assert sup.run() == 7  # the child's failing code, after 1+2 runs


def test_supervisor_kills_hung_child(tmp_path):
    """A child that never touches its heartbeat is killed after
    hang_timeout and the restart policy takes over; a second attempt
    that finishes quickly rescues the run."""
    marker = tmp_path / "attempts"
    hb = tmp_path / "hb"
    cmd = _script(tmp_path, f"""
        import sys, time
        from pathlib import Path
        m = Path({str(marker)!r})
        n = int(m.read_text()) if m.exists() else 0
        m.write_text(str(n + 1))
        if n == 0:
            time.sleep(60)  # never heartbeats -> must be killed
        raise SystemExit(0)
    """) + ["--heartbeat-file", str(hb)]
    t0 = time.monotonic()
    # hang_timeout must exceed worst-case interpreter startup on a
    # loaded host (the healthy retry must not be killed mid-import)
    sup = Supervisor(cmd, RestartPolicy(max_restarts=2, backoff=0.01),
                     hang_timeout=15.0, poll_interval=0.2,
                     log=lambda *_: None)
    assert sup.run() == 0
    assert time.monotonic() - t0 < 55  # killed at ~15s, not waited out
    assert marker.read_text() == "2"


def test_hang_detection_survives_deleted_heartbeat(tmp_path):
    """Deleting the heartbeat file mid-run must NOT disable hang
    detection (ADVICE r2: getmtime OSError used to reset staleness to
    zero forever): the child deletes its own heartbeat then sleeps —
    the supervisor still kills it, measuring staleness from the last
    known beat."""
    marker = tmp_path / "attempts"
    hb = tmp_path / "hb"
    cmd = _script(tmp_path, f"""
        import os, sys, time
        from pathlib import Path
        m = Path({str(marker)!r})
        n = int(m.read_text()) if m.exists() else 0
        m.write_text(str(n + 1))
        if n == 0:
            os.unlink({str(hb)!r})  # vanish the liveness signal...
            time.sleep(60)          # ...and hang
        raise SystemExit(0)
    """) + ["--heartbeat-file", str(hb)]
    t0 = time.monotonic()
    sup = Supervisor(cmd, RestartPolicy(max_restarts=2, backoff=0.01),
                     hang_timeout=15.0, poll_interval=0.2,
                     log=lambda *_: None)
    assert sup.run() == 0
    assert time.monotonic() - t0 < 55
    assert marker.read_text() == "2"


def test_cli_requires_command():
    from shallowspeed_tpu.elastic import main

    with pytest.raises(SystemExit):
        main(["--max-restarts", "1"])


# -------------------------------------------------- driver integration


def test_auto_resume_fresh_then_resume(tmp_path):
    """The contract every supervised restart relies on: --auto-resume
    starts fresh when no checkpoint exists and resumes when one does."""
    base = [sys.executable, "train_lm.py", "--platform", "cpu",
            "--host-devices", "2", "--dp", "2", "--seq-len", "32",
            "--d-model", "32", "--n-layers", "1", "--log-every", "2",
            "--save-dir", str(tmp_path / "ck"), "--save-every", "4",
            "--auto-resume"]
    repo = Path(__file__).parent.parent
    r1 = subprocess.run(base + ["--steps", "4"], capture_output=True,
                        text=True, cwd=repo, timeout=300)
    assert r1.returncode == 0, r1.stdout + r1.stderr
    assert "resumed" not in r1.stdout  # fresh start
    r2 = subprocess.run(base + ["--steps", "8"], capture_output=True,
                        text=True, cwd=repo, timeout=300)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "resumed from" in r2.stdout  # picked up ckpt_3


def test_auto_resume_mlp_driver(tmp_path):
    """The reference-parity MLP driver honors --auto-resume the same way
    (fresh without a checkpoint, resumed with one)."""
    base = [sys.executable, "train.py", "--platform", "cpu",
            "--host-devices", "2", "--dp", "2", "--max-batches", "4",
            "--lr", "0.5", "--save-dir", str(tmp_path / "ck"),
            "--auto-resume"]
    repo = Path(__file__).parent.parent
    r1 = subprocess.run(base + ["--epochs", "1"], capture_output=True,
                        text=True, cwd=repo, timeout=300)
    assert r1.returncode == 0, r1.stdout + r1.stderr
    assert "resumed" not in r1.stdout
    r2 = subprocess.run(base + ["--epochs", "2"], capture_output=True,
                        text=True, cwd=repo, timeout=300)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "resumed from" in r2.stdout


# ----------------------------------------------- gang mode (round 4)


def _gang_child(tmp_path, body):
    """A stub gang member: asserts the injected env, then runs `body`."""
    script = tmp_path / "child.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys, time
        assert os.environ["JAX_COORDINATOR_ADDRESS"]
        n = int(os.environ["JAX_NUM_PROCESSES"])
        pid = int(os.environ["JAX_PROCESS_ID"])
        {body}
    """))
    return [sys.executable, str(script)]


def test_gang_env_injection_and_clean_finish(tmp_path):
    from shallowspeed_tpu.elastic import GangSupervisor

    cmd = _gang_child(tmp_path, """
        (open(os.path.join(r'%s', f'saw_{pid}'), 'w')).write('1')
        assert n == 2
    """ % tmp_path)
    sup = GangSupervisor(cmd, 2, RestartPolicy(max_restarts=0),
                         poll_interval=0.05)
    assert sup.run() == 0
    assert (tmp_path / "saw_0").exists() and (tmp_path / "saw_1").exists()


def test_gang_member_failure_restarts_whole_gang(tmp_path):
    """Any member's nonzero exit kills the gang; the restart relaunches
    ALL members (a JAX multi-controller job cannot continue with a
    missing peer — the compiled collectives bake the topology)."""
    from shallowspeed_tpu.elastic import GangSupervisor

    marker = tmp_path / "crashed_once"
    cmd = _gang_child(tmp_path, """
        import pathlib
        runs = pathlib.Path(r'%s') / f'runs_{pid}'
        runs.write_text(str(int(runs.read_text()) + 1
                            if runs.exists() else 1))
        if pid == 1 and not pathlib.Path(r'%s').exists():
            pathlib.Path(r'%s').write_text('x')
            sys.exit(3)     # member 1 dies on the first attempt
        time.sleep(0.3)     # member 0 would outlive member 1's crash
    """ % (tmp_path, marker, marker))
    sup = GangSupervisor(cmd, 2,
                         RestartPolicy(max_restarts=2, backoff=0.01),
                         poll_interval=0.05)
    assert sup.run() == 0
    # BOTH members ran twice: the healthy member was killed and
    # relaunched along with the crashed one
    assert (tmp_path / "runs_0").read_text() == "2"
    assert (tmp_path / "runs_1").read_text() == "2"


def test_gang_hang_kills_and_restarts(tmp_path):
    """A single stale heartbeat (one wedged member) takes the whole
    gang down; the restart succeeds."""
    from shallowspeed_tpu.elastic import GangSupervisor

    marker = tmp_path / "hung_once"
    cmd = _gang_child(tmp_path, """
        import pathlib
        hb = sys.argv[sys.argv.index('--heartbeat-file') + 1]
        if pid == 0 and not pathlib.Path(r'%s').exists():
            pathlib.Path(r'%s').write_text('x')
            time.sleep(120)  # wedged: never beats
        for _ in range(8):
            pathlib.Path(hb).touch(); time.sleep(0.2)
    """ % (marker, marker))
    sup = GangSupervisor(cmd, 2,
                         RestartPolicy(max_restarts=2, backoff=0.01),
                         hang_timeout=6.0, poll_interval=0.1)
    t0 = time.time()
    assert sup.run() == 0
    assert time.time() - t0 < 60


def test_gang_supervises_real_multicontroller_training(tmp_path):
    """END-TO-END gang elasticity (round 4): a REAL 2-process
    multi-controller train_lm run (dp=4 across 2 procs x 2 devices,
    gradient psums crossing the boundary) under GangSupervisor; one
    member is SIGKILLed after the first checkpoint lands; the WHOLE
    gang restarts and BOTH processes resume from the checkpoint
    (multi-controller restore) and finish cleanly."""
    import os
    import signal

    ck = tmp_path / "ck"
    log = tmp_path / "gang.log"
    cmd = [sys.executable, "-m", "shallowspeed_tpu.elastic", "--procs",
           "2", "--max-restarts", "2", "--backoff", "1", "--",
           sys.executable, "train_lm.py", "--platform", "cpu",
           "--host-devices", "2", "--dp", "4", "--seq-len", "32",
           "--d-model", "32", "--steps", "260", "--log-every", "50",
           "--save-dir", str(ck), "--save-every", "40",
           "--auto-resume"]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    with open(log, "w") as logf:
        sup = subprocess.Popen(cmd, stdout=logf,
                               stderr=subprocess.STDOUT,
                               cwd=str(Path(__file__).parent.parent),
                               env=env)
    members = []
    try:
        for _ in range(180):          # wait for the first checkpoint
            time.sleep(1)
            if ck.exists() and any(
                    not p.name.endswith(".tmp")
                    for p in ck.glob("ckpt_*")):
                break
        else:
            raise AssertionError(
                f"no checkpoint appeared:\n{log.read_text()[-2000:]}")
        out = subprocess.run(["ps", "-eo", "pid,ppid"],
                             capture_output=True, text=True).stdout
        members = [int(l.split()[0]) for l in out.splitlines()[1:]
                   if l.split()[1] == str(sup.pid)]
        assert members, "no gang members found"
        os.kill(members[0], signal.SIGKILL)
        rc = sup.wait(timeout=400)
    finally:
        if sup.poll() is None:
            sup.kill()
        # the supervisor forwards nothing on SIGKILL: reap any gang
        # members it left behind so a timed-out test cannot leave two
        # training processes burning CPU under the rest of the suite
        out = subprocess.run(["ps", "-eo", "pid,ppid"],
                             capture_output=True, text=True).stdout
        stray = [int(l.split()[0]) for l in out.splitlines()[1:]
                 if l.split()[1] == str(sup.pid)] + [
                m for m in members if os.path.exists(f"/proc/{m}")]
        for pid in set(stray):
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
    text = log.read_text()
    assert rc == 0, text[-2000:]
    assert "killing the gang" in text, text[-2000:]
    assert "resumed from" in text, text[-2000:]
    assert "[elastic] attempt 2" in text, text[-2000:]

# ------------------------------------------------- heartbeat hygiene


def test_supervisor_cleans_up_owned_heartbeat_file(tmp_path):
    """ADVICE r4: a supervisor that mkstemp'd its own heartbeat file
    must unlink it when run() returns."""
    cmd = _script(tmp_path, "raise SystemExit(0)")
    sup = Supervisor(cmd, RestartPolicy(max_restarts=1, backoff=0.01),
                     hang_timeout=30.0, poll_interval=0.1,
                     log=lambda *_: None)
    hb = Path(sup.heartbeat_file)
    assert hb.exists()
    assert sup.run() == 0
    assert not hb.exists()


def test_supervisor_leaves_caller_owned_heartbeat_file(tmp_path):
    """A heartbeat file the CALLER passed is not ours to delete."""
    hb = tmp_path / "hb"
    hb.touch()
    cmd = _script(tmp_path, "raise SystemExit(0)") + [
        "--heartbeat-file", str(hb)]
    sup = Supervisor(cmd, RestartPolicy(max_restarts=1, backoff=0.01),
                     hang_timeout=30.0, poll_interval=0.1,
                     log=lambda *_: None)
    assert sup.run() == 0
    assert hb.exists()


# ------------------------------- failure-class supervision (round 10)


def test_policy_per_class_backoff_is_independent():
    """Each failure class doubles on its own stream: two crashes then a
    hang — the hang starts from the base backoff, not the crash
    stream's doubled value."""
    p = RestartPolicy(max_restarts=10, backoff=1.0, backoff_max=64.0)
    assert p.next_restart("crash") == 1.0
    assert p.next_restart("crash") == 2.0
    assert p.next_restart("hang") == 1.0   # own stream
    assert p.next_restart("crash") == 4.0
    assert p.next_restart("hang") == 2.0
    p.record_run(1e9)  # healthy run resets every stream and the budget
    assert p.next_restart("crash") == 1.0
    assert p.next_restart("hang") == 1.0


def test_policy_jitter_is_seeded_and_bounded():
    delays = [RestartPolicy(max_restarts=4, backoff=2.0, jitter=0.5,
                            seed=7) for _ in range(2)]
    seq = [[pol.next_restart("crash") for _ in range(3)]
           for pol in delays]
    assert seq[0] == seq[1]  # same seed -> same jitter stream
    for base, got in zip([2.0, 4.0, 8.0], seq[0]):
        assert base <= got <= base * 1.5  # stretch, never shrink
    other = RestartPolicy(max_restarts=4, backoff=2.0, jitter=0.5,
                          seed=8)
    assert [other.next_restart("crash")
            for _ in range(3)] != seq[0]  # the seed matters


def _ledger_stamps(path, kind):
    return [json.loads(l) for l in Path(path).read_text().splitlines()
            if json.loads(l).get("kind") == kind]


def test_supervisor_stamps_fail_class_crash_and_corrupt(tmp_path):
    """Exit-code classification rides the restart stamps: a generic
    nonzero exit is 'crash'; EXIT_CORRUPT_CKPT is 'corrupt_ckpt'."""
    from shallowspeed_tpu.elastic import EXIT_CORRUPT_CKPT

    log = tmp_path / "m.jsonl"
    log.write_text("")
    for code, expect in ((3, "crash"),
                        (EXIT_CORRUPT_CKPT, "corrupt_ckpt")):
        marker = tmp_path / f"ran_{code}"
        cmd = _script(tmp_path, f"""
            from pathlib import Path
            m = Path({str(marker)!r})
            if m.exists():
                raise SystemExit(0)
            m.write_text('x')
            raise SystemExit({code})
        """)
        sup = Supervisor(cmd,
                         RestartPolicy(max_restarts=2, backoff=0.01),
                         ledger_file=str(log), log=lambda *_: None)
        assert sup.run() == 0
    classes = [r["fail_class"] for r in
               _ledger_stamps(log, "restart_downtime")]
    assert classes == ["crash", "corrupt_ckpt"]


def test_supervisor_numeric_class_via_dead_heartbeat(tmp_path):
    """A beating-but-dead child (heartbeat status 'dead ...') is
    killed and classed 'numeric'."""
    log = tmp_path / "m.jsonl"
    log.write_text("")
    hb = tmp_path / "hb"
    marker = tmp_path / "died_once"
    cmd = _script(tmp_path, f"""
        import time
        from pathlib import Path
        m = Path({str(marker)!r})
        if m.exists():
            raise SystemExit(0)
        m.write_text('x')
        Path({str(hb)!r}).write_text('dead nonfinite gradients')
        time.sleep(60)   # still 'alive' — only the status says dead
    """) + ["--heartbeat-file", str(hb)]
    sup = Supervisor(cmd, RestartPolicy(max_restarts=2, backoff=0.01),
                     hang_timeout=30.0, poll_interval=0.1,
                     term_grace=2.0, ledger_file=str(log),
                     log=lambda *_: None)
    t0 = time.monotonic()
    assert sup.run() == 0
    assert time.monotonic() - t0 < 40  # killed on status, not timeout
    stamps = _ledger_stamps(log, "restart_downtime")
    assert [r["fail_class"] for r in stamps] == ["numeric"]


def test_supervisor_poison_step_aborts_with_forensics(tmp_path):
    """The same step failing twice in a row is a poison step: labeled
    abort + forensic snapshot after TWO attempts, not a crash loop
    that burns the whole budget."""
    log = tmp_path / "m.jsonl"
    attempts = tmp_path / "attempts"
    cmd = _script(tmp_path, f"""
        import json
        from pathlib import Path
        a = Path({str(attempts)!r})
        n = int(a.read_text()) if a.exists() else 0
        a.write_text(str(n + 1))
        with open({str(log)!r}, 'a') as f:
            f.write(json.dumps({{"event": "step", "step": 7,
                                 "loss": 1.0, "tokens_per_sec": 1.0,
                                 "t": 0.1}}) + chr(10))
        raise SystemExit(9)   # always dies right after step 7
    """)
    sup = Supervisor(cmd, RestartPolicy(max_restarts=10, backoff=0.01),
                     ledger_file=str(log), log=lambda *_: None)
    assert sup.run() == 9
    assert attempts.read_text() == "2"  # aborted at the second strike
    aborts = _ledger_stamps(log, "poison_step_abort")
    assert len(aborts) == 1 and aborts[0]["step"] == 7
    snap = json.loads(
        Path(f"{log}.poison_step_7.json").read_text())
    assert snap["poison_step"] == 7 and snap["fail_class"] == "crash"
    assert snap["metrics_tail"]


def test_term_grace_lets_child_flush_before_sigkill(tmp_path):
    """The satellite contract: a hang-kill sends SIGTERM first, and a
    child whose handler flushes state gets `term_grace` to do it —
    the goodput-ledger tail survives the kill."""
    flushed = tmp_path / "flushed"
    marker = tmp_path / "hung_once"
    hb = tmp_path / "hb"
    cmd = _script(tmp_path, f"""
        import signal, sys, time
        from pathlib import Path
        m = Path({str(marker)!r})
        if m.exists():
            raise SystemExit(0)
        m.write_text('x')
        def flush(signum, frame):
            Path({str(flushed)!r}).write_text('ledger tail')
            sys.exit(143)
        signal.signal(signal.SIGTERM, flush)
        time.sleep(120)   # hung: never beats
    """) + ["--heartbeat-file", str(hb)]
    sup = Supervisor(cmd, RestartPolicy(max_restarts=2, backoff=0.01),
                     hang_timeout=8.0, poll_interval=0.2,
                     term_grace=10.0, log=lambda *_: None)
    assert sup.run() == 0
    assert flushed.read_text() == "ledger tail"


def test_gang_supervisor_cleans_up_heartbeat_files(tmp_path):
    """ADVICE r4: gang mode injects N tmpfiles; all N must be unlinked
    when run() returns (long-lived hosts run many gangs)."""
    from shallowspeed_tpu.elastic import GangSupervisor

    cmd = _script(tmp_path, "raise SystemExit(0)")
    sup = GangSupervisor(cmd, n_procs=2,
                         policy=RestartPolicy(max_restarts=1,
                                              backoff=0.01),
                         hang_timeout=30.0, poll_interval=0.1,
                         log=lambda *_: None)
    paths = [Path(p) for p in sup.heartbeat_files]
    assert len(paths) == 2 and all(p.exists() for p in paths)
    assert sup.run() == 0
    assert not any(p.exists() for p in paths)
