"""Adafactor (`optim.py`) — factored second moments, sublinear state.

The reference's only optimizer is stateless SGD; its PyTorch baseline
uses Adam (2x params of state). Adafactor is the TPU-era answer: row +
column statistics per matrix. Contracts: it optimizes (loss falls on the
real LM), its state is a small fraction of Adam's, it composes with the
engines, ZeRO sharding, and checkpoints like any other optimizer.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from shallowspeed_tpu.models.transformer import TransformerConfig
from shallowspeed_tpu.optim import Adafactor, Adam
from shallowspeed_tpu.parallel.context import ContextParallelEngine

CFG = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                        max_seq=32)


def mesh2(dp, sp=1):
    devs = np.array(jax.devices()[: dp * sp]).reshape(dp, sp)
    return Mesh(devs, ("dp", "sp"))


def batch(step, b=8, t=32, vocab=64):
    rng = np.random.default_rng([3, step])
    tok = rng.integers(0, vocab, (b, t)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1).astype(np.int32)


def n_state_floats(state):
    return sum(int(np.prod(l.shape))
               for l in jax.tree_util.tree_leaves(state))


def test_quadratic_convergence():
    """Minimize ||W x - y||^2: the factored moments must still drive a
    plain quadratic to (near) zero."""
    rng = np.random.default_rng(0)
    w_true = rng.normal(size=(8, 4)).astype(np.float32)
    x = rng.normal(size=(4, 64)).astype(np.float32)
    y = w_true @ x
    params = {"W": jnp.zeros((8, 4)), "b": jnp.zeros((8,))}
    # scale_parameter off: from a zero init the relative step would start
    # at eps_scale and take many steps to wind up; the absolute step is
    # the right tool for a cold-start quadratic
    opt = Adafactor(lr=0.1, scale_parameter=False)
    state = opt.init(params)

    def loss_fn(p):
        return jnp.mean((p["W"] @ x + p["b"][:, None] - y) ** 2)

    @jax.jit
    def step(p, s):
        l, g = jax.value_and_grad(loss_fn)(p)
        p, s = opt.step(p, g, s)
        return p, s, l

    losses = []
    for _ in range(300):
        params, state, l = step(params, state)
        losses.append(float(l))
    assert losses[-1] < 1e-2 * losses[0], losses[::60]


def test_state_is_sublinear():
    eng_a = ContextParallelEngine(CFG, Adam(1e-2), mesh2(1))
    eng_f = ContextParallelEngine(CFG, Adafactor(1e-2), mesh2(1))
    n_params = n_state_floats(eng_a.params)
    adam_state = n_state_floats(eng_a.opt_state)
    fac_state = n_state_floats(eng_f.opt_state)
    assert adam_state >= 2 * n_params * 0.99
    # factored: row+col vectors per matrix — far under half of one param
    # copy for this config, and an order of magnitude under Adam
    assert fac_state < 0.2 * n_params, (fac_state, n_params)
    assert fac_state < 0.1 * adam_state


def test_lm_trains():
    eng = ContextParallelEngine(CFG, Adafactor(3e-2), mesh2(2, 2), seed=0)
    losses = [eng.train_batch(*batch(s % 4)) for s in range(40)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] - 0.3, losses[::10]


def test_momentum_variant_and_decay():
    eng = ContextParallelEngine(
        CFG, Adafactor(3e-2, beta1=0.9, weight_decay=0.01), mesh2(1),
        seed=0)
    losses = [eng.train_batch(*batch(s % 4)) for s in range(30)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] - 0.2, losses[::10]


def test_zero1_composes():
    from jax.sharding import NamedSharding

    dense = ContextParallelEngine(CFG, Adafactor(1e-2), mesh2(4), seed=0)
    zero = ContextParallelEngine(CFG, Adafactor(1e-2), mesh2(4), seed=0,
                                 zero1=True)
    sharded = [l for l in jax.tree_util.tree_leaves(zero.opt_state)
               if hasattr(l, "sharding")
               and isinstance(l.sharding, NamedSharding)
               and "dp" in str(l.sharding.spec)]
    assert len(sharded) > 0  # the factored vectors shard over dp too
    for s in range(3):
        tok, tgt = batch(s)
        np.testing.assert_allclose(dense.train_batch(tok, tgt),
                                   zero.train_batch(tok, tgt),
                                   rtol=1e-5, atol=1e-6)


def test_checkpoint_roundtrip(tmp_path):
    from shallowspeed_tpu import checkpoint

    eng = ContextParallelEngine(CFG, Adafactor(1e-2), mesh2(2, 1), seed=0)
    for s in range(2):
        eng.train_batch(*batch(s))
    checkpoint.save(tmp_path, eng, 2)
    eng2 = ContextParallelEngine(CFG, Adafactor(1e-2), mesh2(2, 1), seed=1)
    assert checkpoint.restore(eng2, checkpoint.latest(tmp_path)) == 3
    for s in range(2, 4):
        tok, tgt = batch(s)
        np.testing.assert_allclose(eng.train_batch(tok, tgt),
                                   eng2.train_batch(tok, tgt),
                                   rtol=1e-6, atol=1e-7)


def test_pipeline_engine_composes():
    """The factored slots must inherit the pp-stacked block sharding
    (zeros derived by reduction, not fresh) or the shard_map step cannot
    even trace; then the step must train."""
    from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "pp"))
    eng = PipelineLMEngine(CFG, Adafactor(3e-2), mesh, n_mubatches=2,
                           seed=0)
    losses = [eng.train_batch(*batch(s % 4)) for s in range(30)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] - 0.2, losses[::10]


def test_grad_clip_composes():
    eng = ContextParallelEngine(CFG, Adafactor(3e-2, grad_clip=1.0),
                                mesh2(1), seed=0)
    losses = [eng.train_batch(*batch(s % 4)) for s in range(20)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses[::5]
