"""ZeRO-2 gradient + optimizer-state sharding (`parallel/zero.py`).

Correctness contract: identical to ZeRO-1's — the training algorithm is
unchanged, only placement moves (grads leave the grad program dp-sharded
via reduce-scatter instead of replicated via all-reduce) — so params must
match the dense engine step for step. Plus placement asserts: the grad
leaves handed across the program boundary actually carry 'dp'.
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from shallowspeed_tpu.models.transformer import TransformerConfig
from shallowspeed_tpu.optim import SGD, Adam, MomentumSGD
from shallowspeed_tpu.parallel.context import ContextParallelEngine
from shallowspeed_tpu.parallel.tensor import TensorParallelEngine
from shallowspeed_tpu.parallel.zero import zero2_grad_dim, zero2_grad_specs

CFG = TransformerConfig(vocab=32, d_model=16, n_heads=2, n_layers=2,
                        max_seq=32)


def mesh2(dp, m, name):
    devs = np.array(jax.devices()[: dp * m]).reshape(dp, m)
    return Mesh(devs, ("dp", name))


def batch(step, b=8, t=32, vocab=32):
    rng = np.random.default_rng([7, step])
    tok = rng.integers(0, vocab, (b, t)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1).astype(np.int32)


def leaves_with_dp(tree):
    return [l for l in jax.tree_util.tree_leaves(tree)
            if hasattr(l, "sharding")
            and isinstance(l.sharding, NamedSharding)
            and "dp" in str(l.sharding.spec)]


def assert_same_training(dense, zero, n_steps=4):
    for s in range(n_steps):
        tok, tgt = batch(s)
        ld = dense.train_batch(tok, tgt)
        lz = zero.train_batch(tok, tgt)
        assert np.isfinite(ld) and np.isfinite(lz)
        np.testing.assert_allclose(ld, lz, rtol=1e-5, atol=1e-6)
    for a, b_ in zip(jax.tree_util.tree_leaves(dense.params),
                     jax.tree_util.tree_leaves(zero.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-5, atol=1e-6)


def test_zero2_grad_dim_arithmetic():
    assert zero2_grad_dim(P(), (8, 3), 4) == 0
    assert zero2_grad_dim(P(), (3, 8), 4) == 1
    assert zero2_grad_dim(P("tp"), (8, 12), 4) == 1
    assert zero2_grad_dim(P(), (3, 5), 4) is None
    assert zero2_grad_dim(P("dp"), (8, 8), 4) is None


def test_zero1_zero2_mutually_exclusive():
    with pytest.raises(AssertionError):
        ContextParallelEngine(CFG, Adam(1e-2), mesh2(4, 2, "sp"),
                              zero1=True, zero2=True)


def test_context_zero2_matches_dense():
    # MomentumSGD: linear in the gradients, so the dense and zero2
    # programs stay bit-close despite the reduce-scatter's different
    # summation order; Adam's rsqrt amplifies that reassociation noise on
    # near-zero bias gradients (same story as test_zero1's tensor test)
    # and is covered by the loss-trajectory test below.
    opt = lambda: MomentumSGD(0.1, momentum=0.9)  # noqa: E731
    dense = ContextParallelEngine(CFG, opt(), mesh2(4, 2, "sp"))
    zero = ContextParallelEngine(CFG, opt(), mesh2(4, 2, "sp"),
                                 zero2=True)
    assert len(leaves_with_dp(zero.opt_state)) > 0
    assert_same_training(dense, zero)


def test_context_zero2_adam_loss_trajectory():
    dense = ContextParallelEngine(CFG, Adam(1e-2), mesh2(4, 2, "sp"))
    zero = ContextParallelEngine(CFG, Adam(1e-2), mesh2(4, 2, "sp"),
                                 zero2=True)
    for s in range(6):
        tok, tgt = batch(s)
        np.testing.assert_allclose(dense.train_batch(tok, tgt),
                                   zero.train_batch(tok, tgt),
                                   rtol=1e-4)


def test_context_zero2_grads_are_dp_sharded():
    eng = ContextParallelEngine(CFG, Adam(1e-2), mesh2(8, 1, "sp"),
                                zero2=True)
    tok, tgt = batch(0)
    loss, grads = eng._loss_grads_fn(eng.params, eng.place(tok),
                                     eng.place(tgt), np.uint32(0))
    assert np.isfinite(float(loss))
    sharded = leaves_with_dp(grads)
    assert len(sharded) > 0
    # the big matrices must all be sharded; each leaf's local bytes 1/dp
    for leaf in sharded:
        full = np.prod(leaf.shape)
        local = np.prod(leaf.addressable_shards[0].data.shape)
        assert local * 8 == full, (leaf.shape, local)


def test_context_zero2_sp_composes():
    dense = ContextParallelEngine(CFG, MomentumSGD(0.1, momentum=0.9),
                                  mesh2(2, 4, "sp"))
    zero = ContextParallelEngine(CFG, MomentumSGD(0.1, momentum=0.9),
                                 mesh2(2, 4, "sp"), zero2=True)
    assert_same_training(dense, zero)


def test_tensor_zero2_matches_dense():
    opt = lambda: MomentumSGD(0.1, momentum=0.9)  # noqa: E731
    dense = TensorParallelEngine(CFG, opt(), mesh2(4, 2, "tp"))
    zero = TensorParallelEngine(CFG, opt(), mesh2(4, 2, "tp"), zero2=True)
    assert_same_training(dense, zero)


def test_zero2_checkpoint_roundtrip(tmp_path):
    from shallowspeed_tpu import checkpoint

    eng = ContextParallelEngine(CFG, Adam(1e-2), mesh2(4, 2, "sp"),
                                zero2=True)
    for s in range(2):
        eng.train_batch(*batch(s))
    checkpoint.save(tmp_path, eng, 2)
    eng2 = ContextParallelEngine(CFG, Adam(1e-2), mesh2(4, 2, "sp"),
                                 zero2=True)
    assert checkpoint.restore(eng2, checkpoint.latest(tmp_path)) == 3
    for s in range(2, 4):
        tok, tgt = batch(s)
        np.testing.assert_allclose(eng.train_batch(tok, tgt),
                                   eng2.train_batch(tok, tgt),
                                   rtol=1e-6, atol=1e-7)


def test_zero2_grad_specs_inherit_model_sharding():
    m = mesh2(4, 2, "tp")
    eng = TensorParallelEngine(CFG, SGD(0.1), m)
    specs = jax.tree_util.tree_leaves(
        zero2_grad_specs(eng.params, m),
        is_leaf=lambda x: isinstance(x, P))
    # at least one leaf carries BOTH the model axis and the new dp axis
    assert any("tp" in str(s) and "dp" in str(s) for s in specs), specs


# --------------------------------------- zero2/fsdp x pp x tp (round 4)


@pytest.mark.parametrize("flavor", ["zero2", "fsdp"])
def test_zero_family_pp_tp_matches_dense(flavor):
    """ZeRO-2 / FSDP on a ('dp','pp','tp') mesh: the dp reduce-scatter /
    transient all-gather act on each leaf's ZeRO dim while the Megatron
    tp placement keeps its variance-typed reductions — trajectories
    must equal the dense pp x tp pipeline."""
    from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine

    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4,
                            n_layers=4, max_seq=32)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                ("dp", "pp", "tp"))
    sched = "1f1b" if flavor == "fsdp" else "gpipe"  # cover both
    dense = PipelineLMEngine(cfg, Adam(1e-2), mesh, n_mubatches=2,
                             seed=0, schedule=sched)
    z = PipelineLMEngine(cfg, Adam(1e-2), mesh, n_mubatches=2, seed=0,
                         schedule=sched,
                         zero2=flavor == "zero2", fsdp=flavor == "fsdp")
    rng = np.random.default_rng(0)
    for step in range(3):
        tok = rng.integers(0, cfg.vocab, (8, 32)).astype(np.int32)
        tgt = np.roll(tok, -1, axis=1).astype(np.int32)
        assert z.train_batch(tok, tgt) == pytest.approx(
            dense.train_batch(tok, tgt), rel=3e-4), (flavor, step)
    # state leaves carry BOTH the tp placement and the dp ZeRO shard
    if flavor == "fsdp":
        spec = str(z.params["blocks"]["qkv"]["W"].sharding.spec)
        assert "dp" in spec and "tp" in spec and "pp" in spec


# ---------------------------------- zero2/fsdp x pp x sp, x vpp (round 5)


@pytest.mark.parametrize("flavor,sched", [
    ("zero2", "gpipe"), ("zero2", "1f1b"),
    ("fsdp", "gpipe"), ("fsdp", "1f1b"),
])
def test_zero_family_pp_sp_matches_dense(flavor, sched):
    """ZeRO-2 / FSDP on a ('dp','pp','sp') mesh — the long-context
    flagship's composition (sequence-sharded activations AND dp-sharded
    grads/params on one mesh): the uniform-execution 1F1B partials and
    the GPipe cotangents both reduce over 'sp' per leaf before the dp
    reduce-scatter. Trajectories must equal the dense pp x sp run."""
    from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine

    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4,
                            n_layers=4, max_seq=32)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                ("dp", "pp", "sp"))
    dense = PipelineLMEngine(cfg, Adam(1e-2), mesh, n_mubatches=2,
                             seed=0, schedule=sched, attn="ring")
    z = PipelineLMEngine(cfg, Adam(1e-2), mesh, n_mubatches=2, seed=0,
                         schedule=sched, attn="ring",
                         zero2=flavor == "zero2", fsdp=flavor == "fsdp")
    rng = np.random.default_rng(0)
    for step in range(3):
        tok = rng.integers(0, cfg.vocab, (8, 32)).astype(np.int32)
        tgt = np.roll(tok, -1, axis=1).astype(np.int32)
        assert z.train_batch(tok, tgt) == pytest.approx(
            dense.train_batch(tok, tgt), rel=3e-4), (flavor, sched,
                                                     step)
    if flavor == "fsdp":
        spec = str(z.params["blocks"]["qkv"]["W"].sharding.spec)
        assert "dp" in spec and "pp" in spec


@pytest.mark.parametrize("flavor,sched", [
    ("zero2", "gpipe"), ("zero2", "1f1b"),
    ("fsdp", "gpipe"), ("fsdp", "1f1b"),
])
def test_zero_family_virtual_pp_matches_dense(flavor, sched):
    """ZeRO-2 / FSDP under interleaved virtual stages: the vpp scan
    takes the same grad_reduce substitution (round 5 lifted the
    carve-out)."""
    from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine

    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4,
                            n_layers=4, max_seq=32)
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                ("dp", "pp"))
    dense = PipelineLMEngine(cfg, Adam(1e-2), mesh, n_mubatches=4,
                             seed=0, schedule=sched, virtual_pp=2)
    z = PipelineLMEngine(cfg, Adam(1e-2), mesh, n_mubatches=4, seed=0,
                         schedule=sched, virtual_pp=2,
                         zero2=flavor == "zero2", fsdp=flavor == "fsdp")
    rng = np.random.default_rng(0)
    for step in range(3):
        tok = rng.integers(0, cfg.vocab, (8, 32)).astype(np.int32)
        tgt = np.roll(tok, -1, axis=1).astype(np.int32)
        assert z.train_batch(tok, tgt) == pytest.approx(
            dense.train_batch(tok, tgt), rel=3e-4), (flavor, sched,
                                                     step)


def test_zero_family_pp_ep_pinned():
    """The kept exclusion, pinned with its mechanism: expert-leaf grads
    are ep-SHARDED (each device owns its experts' grads outright), so
    the per-leaf ZeRO dim/scatter rule — which assumes dp-PARTIAL
    replicated grads — does not describe them."""
    from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine

    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4,
                            n_layers=4, max_seq=32, n_experts=2)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                ("dp", "pp", "ep"))
    with pytest.raises(AssertionError, match="ep-sharded"):
        PipelineLMEngine(cfg, Adam(1e-2), mesh, n_mubatches=2, seed=0,
                         zero2=True)
