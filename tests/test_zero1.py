"""ZeRO-1 optimizer-state sharding (`parallel/zero.py`).

Correctness contract: a zero1 run is numerically the SAME training algorithm
as the dense run — only the placement of the optimizer moments changes — so
params must match the dense engine's step for step. Plus placement asserts:
moment leaves actually carry the 'dp' axis in their sharding spec.
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from shallowspeed_tpu.models.transformer import TransformerConfig
from shallowspeed_tpu.optim import SGD, Adam, MomentumSGD
from shallowspeed_tpu.parallel.context import ContextParallelEngine
from shallowspeed_tpu.parallel.tensor import TensorParallelEngine
from shallowspeed_tpu.parallel.zero import _with_axis, shard_state_zero1

CFG = TransformerConfig(vocab=32, d_model=16, n_heads=2, n_layers=2,
                        max_seq=32)


def mesh2(dp, m, name):
    devs = np.array(jax.devices()[: dp * m]).reshape(dp, m)
    return Mesh(devs, ("dp", name))


def batch(step, b=8, t=32, vocab=32):
    rng = np.random.default_rng([7, step])
    tok = rng.integers(0, vocab, (b, t)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1).astype(np.int32)


def leaves_with_dp(state):
    return [l for l in jax.tree_util.tree_leaves(state)
            if hasattr(l, "sharding")
            and isinstance(l.sharding, NamedSharding)
            and "dp" in str(l.sharding.spec)]


def assert_same_training(dense, zero, n_steps=4):
    for s in range(n_steps):
        tok, tgt = batch(s)
        ld = dense.train_batch(tok, tgt)
        lz = zero.train_batch(tok, tgt)
        assert np.isfinite(ld) and np.isfinite(lz)
        np.testing.assert_allclose(ld, lz, rtol=1e-5, atol=1e-6)
    for a, b_ in zip(jax.tree_util.tree_leaves(dense.params),
                     jax.tree_util.tree_leaves(zero.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-5, atol=1e-6)


def test_with_axis_spec_arithmetic():
    assert _with_axis(P(), (8, 3), 4, "dp") == P("dp", None)
    assert _with_axis(P(), (3, 8), 4, "dp") == P(None, "dp")
    assert _with_axis(P(None, "tp"), (8, 6), 4, "dp") == P("dp", "tp")
    # first dim taken by tp, second divisible -> dp lands there
    assert _with_axis(P("tp"), (8, 12), 4, "dp") == P("tp", "dp")
    # nothing divisible -> unchanged
    assert _with_axis(P(), (3, 5), 4, "dp") == P()
    # axis already used -> unchanged
    assert _with_axis(P("dp"), (8, 8), 4, "dp") == P("dp")


def test_context_zero1_matches_dense():
    m = mesh2(4, 2, "sp")
    dense = ContextParallelEngine(CFG, Adam(1e-2), m)
    zero = ContextParallelEngine(CFG, Adam(1e-2), mesh2(4, 2, "sp"),
                                 zero1=True)
    assert len(leaves_with_dp(zero.opt_state)) > 0
    assert len(leaves_with_dp(dense.opt_state)) == 0
    assert_same_training(dense, zero)


def test_tensor_zero1_matches_dense():
    # MomentumSGD: linear in the gradients, so the dense and zero1 programs
    # (two separate XLA compilations) stay bit-close; Adam's rsqrt amplifies
    # compile-order noise on near-zero gradients and is covered by the
    # context test + the single-step grad equivalence below.
    opt = lambda: MomentumSGD(0.1, momentum=0.9)  # noqa: E731
    dense = TensorParallelEngine(CFG, opt(), mesh2(4, 2, "tp"))
    zero = TensorParallelEngine(CFG, opt(), mesh2(4, 2, "tp"), zero1=True)
    # moments both dp-sharded and (where inherited from params) tp-sharded
    specs = [str(l.sharding.spec) for l in leaves_with_dp(zero.opt_state)]
    assert any("tp" in s for s in specs), specs
    assert_same_training(dense, zero)


def test_zero1_stateless_sgd_is_harmless():
    zero = ContextParallelEngine(CFG, SGD(0.1), mesh2(8, 1, "sp"),
                                 zero1=True)
    tok, tgt = batch(0)
    l0 = zero.train_batch(tok, tgt)
    l1 = zero.train_batch(tok, tgt)
    assert np.isfinite(l1) and l1 < l0


def test_zero1_checkpoint_roundtrip_preserves_sharding(tmp_path):
    from shallowspeed_tpu import checkpoint

    eng = ContextParallelEngine(CFG, Adam(1e-2), mesh2(4, 2, "sp"),
                                zero1=True)
    for s in range(2):
        eng.train_batch(*batch(s))
    checkpoint.save(tmp_path, eng, 2)

    eng2 = ContextParallelEngine(CFG, Adam(1e-2), mesh2(4, 2, "sp"),
                                 zero1=True)
    nxt = checkpoint.restore(eng2, checkpoint.latest(tmp_path))
    assert nxt == 3  # restore returns the next epoch/step to run
    assert len(leaves_with_dp(eng2.opt_state)) > 0
    # both continue identically
    for s in range(2, 4):
        tok, tgt = batch(s)
        np.testing.assert_allclose(eng.train_batch(tok, tgt),
                                   eng2.train_batch(tok, tgt),
                                   rtol=1e-6, atol=1e-7)


def test_shard_state_zero1_scalar_and_odd_leaves():
    m = mesh2(8, 1, "sp")
    state = {"m": jax.numpy.zeros((16, 3)), "t": jax.numpy.zeros(()),
             "odd": jax.numpy.zeros((5,))}
    placed = shard_state_zero1(state, m)
    assert "dp" in str(placed["m"].sharding.spec)
    assert placed["t"].sharding.spec == P()
    assert placed["odd"].sharding.spec == P()
