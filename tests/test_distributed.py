"""Multi-host runtime helpers (`shallowspeed_tpu/distributed.py`).

Two layers of coverage:

- single-process contracts: every helper's promised no-op / plain-JAX
  behavior, plus the mesh-construction logic (pure topology arithmetic);
- a REAL 2-process `jax.distributed` run
  (`test_two_process_training_agrees`): two spawned OS processes with a
  local coordinator train a dp=4 model whose gradient psum crosses the
  process boundary — the multi-controller counterpart of the reference's
  `mpirun -n N` runs (`/root/reference/train.py:87-94`), which round 1
  never actually exercised.
"""

import os
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from shallowspeed_tpu import distributed as D


def test_initialize_noop_without_coordinator(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    assert D.initialize() is False
    assert jax.process_count() == 1  # still single-process


def test_process_zero_single_process():
    assert D.process_zero() is True


def test_barrier_noop_single_process():
    D.barrier("test")  # must not raise or block


def test_hybrid_mesh_single_slice_fallback():
    mesh = D.hybrid_mesh(("dp", "sp", "tp"), (2, 2, 2))
    assert mesh.axis_names == ("dp", "sp", "tp")
    assert mesh.devices.shape == (2, 2, 2)
    # row-major: same layout the engines' plain reshape would produce
    assert (mesh.devices.ravel().tolist()
            == list(jax.devices()[:8]))


def test_hybrid_mesh_rejects_oversubscription():
    with pytest.raises(AssertionError, match="needs 16 devices"):
        D.hybrid_mesh(("dp", "tp"), (8, 2))


class _FakeDev:
    """Minimal stand-in carrying `slice_index` — enough to drive
    hybrid_mesh's multi-slice validation (the real
    create_hybrid_device_mesh needs genuine devices and real slices)."""

    def __init__(self, i, slice_index):
        self.id = i
        self.slice_index = slice_index


def test_hybrid_mesh_dcn_axis_must_match_slice_count():
    devs = [_FakeDev(i, slice_index=i // 4) for i in range(8)]  # 2 slices
    with pytest.raises(ValueError, match="fleet has 2 slices"):
        # leftmost (DCN) axis sized 4 over a 2-slice fleet
        D.hybrid_mesh(("dp", "tp"), (4, 2), devices=devs)


def test_hybrid_mesh_rejects_short_slices():
    # 8 devices total, but lopsided: slice 1 has only 3 of the 4 the
    # ICI axes need per slice
    devs = ([_FakeDev(i, 0) for i in range(5)]
            + [_FakeDev(5 + i, 1) for i in range(3)])
    with pytest.raises(ValueError, match="slices \\[1\\] have only"):
        D.hybrid_mesh(("dp", "tp"), (2, 4), devices=devs)


def test_place_global_single_process_is_device_put():
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "sp"))
    sh = NamedSharding(mesh, P("dp", "sp"))
    arr = np.arange(32, dtype=np.float32).reshape(4, 8)
    out = D.place_global(arr, sh)
    assert out.sharding == sh
    np.testing.assert_array_equal(np.asarray(out), arr)


def test_engines_train_through_place_global():
    """The GSPMD/context engines route batches through place_global; a
    single-process run must behave exactly as before."""
    from shallowspeed_tpu.models.transformer import TransformerConfig
    from shallowspeed_tpu.optim import SGD
    from shallowspeed_tpu.parallel.context import ContextParallelEngine

    cfg = TransformerConfig(vocab=32, d_model=16, n_heads=2, n_layers=1,
                            max_seq=16)
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "sp"))
    eng = ContextParallelEngine(cfg, SGD(0.05), mesh, seed=0)
    rng = np.random.default_rng(0)
    tok = rng.integers(0, 32, (4, 16)).astype(np.int32)
    loss = eng.train_batch(tok, np.roll(tok, -1, axis=1))
    assert np.isfinite(loss)


def test_local_rows_single_process_noop():
    arr = np.arange(12).reshape(4, 3)
    assert D.local_rows(arr) is arr


def test_two_process_training_agrees():
    """Spawn 2 processes (2 virtual CPU devices each) under a local JAX
    coordinator and train dp=4 across the process boundary: the gradient
    reduction is a REAL cross-process collective. Both processes must see
    identical losses at every step and identical final weights (the
    reference's `assert_sync`, `utils.py:27-31`, as a spawned test)."""
    import socket

    with socket.socket() as s:  # free port for the coordinator
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    worker = Path(__file__).parent / "_mp_worker.py"
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    # neutralize the axon site hook: it registers a PJRT plugin at
    # interpreter start, which counts as backend init and forbids a later
    # jax.distributed.initialize — workers are CPU-only anyway
    env.pop("PALLAS_AXON_POOL_IPS", None)
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(pid), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=str(worker.parent.parent)) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"DONE {pid}" in out, out

    def parse(out, tag):
        return [ln.split()[2:] for ln in out.splitlines()
                if ln.startswith(tag)]

    l0, l1 = (parse(out, "LOSS") for out in outs)
    assert len(l0) == 3 and l0 == l1, (l0, l1)  # identical loss stream
    (h0,), (h1,) = (parse(out, "HASH") for out in outs)
    assert h0 == h1, "replica weights diverged across processes"


def test_local_rows_multiprocess_slicing(monkeypatch):
    """Simulate P=4 processes: each must get its contiguous row-block."""
    arr = np.arange(8 * 2).reshape(8, 2)
    monkeypatch.setattr(jax, "process_count", lambda: 4)
    for pid in range(4):
        monkeypatch.setattr(jax, "process_index", lambda p=pid: p)
        out = D.local_rows(arr)
        np.testing.assert_array_equal(out, arr[pid * 2:(pid + 1) * 2])
    # indivisible batch rejected with a labeled error
    monkeypatch.setattr(jax, "process_count", lambda: 3)
    with pytest.raises(AssertionError, match="divide over 3"):
        D.local_rows(arr)


def _spawn_workers(mode, timeout=420, extra_env=None):
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    worker = Path(__file__).parent / "_mp_worker.py"
    env = {**os.environ, "JAX_PLATFORMS": "cpu", **(extra_env or {})}
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    procs = [subprocess.Popen(
        [sys.executable, str(worker), str(pid), str(port), mode],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=str(worker.parent.parent)) for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"DONE {pid}" in out, out
    return outs


def _parse(out, tag):
    return [ln.split()[2:] for ln in out.splitlines()
            if ln.startswith(tag)]


def _reference_pipeline_losses(schedule, attn="xla", three_axis=False,
                               zero1=False):
    """The SAME config/batches on a single-process mesh — multi-process
    runs must reproduce this trajectory (identical math, different
    transport)."""
    from shallowspeed_tpu.models.transformer import TransformerConfig
    from shallowspeed_tpu.optim import SGD, Adam
    from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine

    cfg = TransformerConfig(vocab=32, d_model=16, n_heads=2, n_layers=2,
                            max_seq=16)
    if three_axis:
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(1, 2, 2),
                    ("dp", "pp", "sp"))
    else:
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                    ("dp", "pp"))
    opt = Adam(1e-2) if zero1 else SGD(0.1)
    eng = PipelineLMEngine(cfg, opt, mesh, n_mubatches=2, seed=0,
                           schedule=schedule, attn=attn, zero1=zero1)
    losses = []
    for step in range(3):
        rng = np.random.default_rng([11, step])
        tok = rng.integers(0, cfg.vocab, (8, 16)).astype(np.int32)
        losses.append(eng.train_batch(tok, np.roll(tok, -1, axis=1)))
    return losses


def test_two_process_pipeline_ppermute_crosses_boundary(tmp_path):
    """(dp=2, pp=2) with the PP axis spanning two OS processes: every
    inter-stage ppermute hop (activations right, 1F1B cotangents left)
    is a REAL cross-process collective — the analogue of the reference's
    inter-rank Send/Recv (`pipe.py:367-381`). Both schedules plus the
    ZeRO-1 variant must reproduce the single-process trajectory and
    keep replicas in sync; the 2-process multi-controller CHECKPOINT
    (collective fetch, process-0 write) must restore into a 1-process
    engine — save-at-process-count-A / restore-at-B (round 4)."""
    outs = _spawn_workers("pp", extra_env={"MP_CKPT_DIR": str(tmp_path)})
    l0, l1 = (_parse(out, "LOSS") for out in outs)
    assert len(l0) == 9 and l0 == l1, (l0, l1)
    h0, h1 = (_parse(out, "HASH") for out in outs)
    assert h0 == h1, "weights diverged across processes"
    got = {tag_step: float(v) for (tag_step, v) in l0}
    for sched, z1 in (("gpipe", False), ("1f1b", False), ("z1", True)):
        ref = _reference_pipeline_losses("gpipe" if z1 else sched,
                                         zero1=z1)
        for step, r in enumerate(ref):
            assert got[f"{sched}:{step}"] == pytest.approx(r, rel=1e-4), (
                sched, step)

    # restore the 2-process checkpoint at process count 1 (and a
    # different layout: dp=1, pp=2, no zero1) — canonical format +
    # canonical Adam moment record make it exact
    from shallowspeed_tpu import checkpoint
    from shallowspeed_tpu.models.transformer import TransformerConfig
    from shallowspeed_tpu.optim import Adam
    from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine

    (ev0,), (ev1,) = (_parse(out, "EVAL") for out in outs)
    assert ev0 == ev1
    cfg = TransformerConfig(vocab=32, d_model=16, n_heads=2, n_layers=2,
                            max_seq=16)
    eng = PipelineLMEngine(cfg, Adam(1e-2),
                           Mesh(np.array(jax.devices()[:2]).reshape(1, 2),
                                ("dp", "pp")), n_mubatches=2, seed=5)
    assert checkpoint.restore(eng, checkpoint.latest(str(tmp_path))) == 8
    rng = np.random.default_rng([11, 0])
    tok = rng.integers(0, cfg.vocab, (8, 16)).astype(np.int32)
    ev = eng.eval_loss(tok, np.roll(tok, -1, axis=1))
    assert ev == pytest.approx(float(ev0[0]), rel=1e-4)


def test_two_process_ring_attention_crosses_boundary():
    """('dp','pp','sp') with the SP axis spanning the processes: the
    ring-attention K/V rotation crosses the OS boundary on every layer,
    and the sp-sharded batch is stitched by place_global from
    per-process host-local columns."""
    outs = _spawn_workers("ppsp")
    l0, l1 = (_parse(out, "LOSS") for out in outs)
    assert len(l0) == 3 and l0 == l1, (l0, l1)
    got = {tag_step: float(v) for (tag_step, v) in l0}
    ref = _reference_pipeline_losses("gpipe", attn="ring",
                                     three_axis=True)
    for step, r in enumerate(ref):
        assert got[f"gpipe:{step}"] == pytest.approx(r, rel=1e-4), step

@pytest.mark.skipif(
    tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5),
    reason="distributed.all_ok goes through multihost_utils."
           "process_allgather, a jit-compiled cross-process collective "
           "— jax 0.4.x's CPU backend rejects it outright "
           "('Multiprocess computations aren't implemented on the CPU "
           "backend'), so the all_ok exchange is untestable on this "
           "image's CPU mesh; the sibling two-process tests pass "
           "because ppermute/psum inside shard_map use the in-process "
           "XLA collective path, not the cross-process client. "
           "Re-runs automatically once the image's jax reaches 0.5.")
def test_two_process_async_save_failure_raises_on_all():
    """all_ok's multi-process exchange + AsyncSaver._raise_collectively
    across a REAL process boundary: a (simulated) failed background
    write on process 0 must make wait() raise on BOTH processes."""
    outs = _spawn_workers("allok")
    w0, w1 = (_parse(out, "WAITRAISED") for out in outs)
    assert w0 == [["yes"]] and w1 == [["yes"]], (w0, w1)
