"""Worker process for the 2-process `jax.distributed` test
(`test_distributed.py::test_two_process_training_agrees`).

Each of the two OS processes hosts 2 virtual CPU devices and connects to
the local coordinator — a real multi-controller runtime (the thing the
reference gets from `mpirun -n N`, `/root/reference/train.py:87-94`),
with the gradient psum crossing the process boundary over the JAX
distributed service. Run: python _mp_worker.py <process_id> <port>.
"""

import os
import sys

import re

# FORCE 2 local devices, replacing any inherited count (pytest's conftest
# exports 8 into XLA_FLAGS; each worker must present exactly 2)
flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
               os.environ.get("XLA_FLAGS", ""))
os.environ["XLA_FLAGS"] = (
    flags + " --xla_force_host_platform_device_count=2").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")


def main() -> None:
    pid, port = int(sys.argv[1]), sys.argv[2]
    mode = sys.argv[3] if len(sys.argv) > 3 else "dp"
    sys.path.insert(0, str(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))))

    import numpy as np

    from shallowspeed_tpu.distributed import (barrier, hybrid_mesh,
                                              initialize, local_rows,
                                              process_zero)
    from shallowspeed_tpu.models.transformer import TransformerConfig
    from shallowspeed_tpu.optim import SGD
    from shallowspeed_tpu.parallel.context import ContextParallelEngine

    assert initialize(f"localhost:{port}", num_processes=2, process_id=pid)
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 4, jax.device_count()  # 2 local x 2 procs
    assert len(jax.local_devices()) == 2
    assert process_zero() == (pid == 0)

    if mode in ("pp", "ppsp"):
        _pipeline_mode(pid, mode)
        return
    if mode == "allok":
        _allok_mode(pid)
        return

    # dp=4 spans BOTH processes: the gradient pmean/psum crosses the
    # process boundary; place_global stitches each process's local row
    # block into the globally-sharded batch.
    mesh = hybrid_mesh(("dp", "sp"), (4, 1))
    cfg = TransformerConfig(vocab=32, d_model=16, n_heads=2, n_layers=1,
                            max_seq=16)
    eng = ContextParallelEngine(cfg, SGD(0.1), mesh, seed=0)

    for step in range(3):
        rng = np.random.default_rng([7, step])  # same batch on every proc
        tok = rng.integers(0, cfg.vocab, (8, 16)).astype(np.int32)
        tgt = np.roll(tok, -1, axis=1).astype(np.int32)
        loss = eng.train_batch(local_rows(tok), local_rows(tgt))
        print(f"LOSS {pid} {step} {loss!r}", flush=True)

    # post-training replica sync check across the process boundary (the
    # reference's assert_sync, `utils.py:27-31`); sha1, not hash() —
    # Python's hash is salted per process
    import hashlib

    w = np.asarray(jax.device_get(eng.params["tok_emb"]))
    print(f"HASH {pid} {hashlib.sha1(w.tobytes()).hexdigest()}", flush=True)
    barrier("done")
    print(f"DONE {pid}", flush=True)


def _allok_mode(pid: int) -> None:
    """The collective success-bit exchange (`distributed.all_ok`) and the
    AsyncSaver failure contract ACROSS a real process boundary: when
    process 0's background checkpoint write failed, `wait()` must raise
    on EVERY process (the exchange is what stops peers trusting
    `latest()` and wedging the gang in the next collective — ADVICE r4)."""
    from shallowspeed_tpu.checkpoint import AsyncSaver
    from shallowspeed_tpu.distributed import all_ok, barrier

    assert all_ok(True) is True
    assert all_ok(pid != 0) is False  # any one process failing
    assert all_ok(pid == 0) is False  # ... regardless of which
    assert all_ok(False) is False

    saver = AsyncSaver()
    if pid == 0:  # simulate a failed background write on the writer
        saver._err = RuntimeError("simulated disk-full write failure")
    try:
        saver.wait()
        raised = "no"
    except RuntimeError:
        raised = "yes"
    print(f"WAITRAISED {pid} {raised}", flush=True)
    barrier("done")
    print(f"DONE {pid}", flush=True)


def _pipeline_mode(pid: int, mode: str) -> None:
    """Pipeline / context parallelism ACROSS the OS-process boundary —
    the analogue of the reference's inter-rank blocking Send/Recv
    (`/root/reference/shallowspeed/pipe.py:367-381`), which round 2's
    dp-only 2-process run never exercised:

    - mode "pp": a ('dp','pp') mesh with the PP axis spanning the two
      processes — every inter-stage `ppermute` activation/cotangent hop
      crosses the boundary, under BOTH compiled schedules.
    - mode "ppsp": a ('dp','pp','sp') mesh with the SP axis spanning
      the processes — the ring-attention K/V rotation crosses the
      boundary every layer.

    jax.devices() orders devices process-major ([p0d0, p0d1, p1d0,
    p1d1]); transposing puts the chosen axis across processes. Batches
    route through `place_global` (PipelineLMEngine._split_mu), so the
    multi-controller data path runs for real here too."""
    import hashlib

    import numpy as np

    from shallowspeed_tpu.distributed import barrier
    from shallowspeed_tpu.models.transformer import TransformerConfig
    from shallowspeed_tpu.optim import SGD
    from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine
    from jax.sharding import Mesh

    cfg = TransformerConfig(vocab=32, d_model=16, n_heads=2, n_layers=2,
                            max_seq=16)
    by_proc = np.array(jax.devices()).reshape(2, 2)  # [process, local]
    if mode == "pp":
        # dp = local device index, pp = process index -> pp hops cross
        mesh = Mesh(by_proc.T, ("dp", "pp"))
        engines = [
            ("gpipe", PipelineLMEngine(cfg, SGD(0.1), mesh,
                                       n_mubatches=2, seed=0,
                                       schedule="gpipe")),
            ("1f1b", PipelineLMEngine(cfg, SGD(0.1), mesh,
                                      n_mubatches=2, seed=0,
                                      schedule="1f1b")),
        ]
    else:  # ppsp: sp = process index -> ring K/V hops cross
        mesh = Mesh(by_proc.T[None], ("dp", "pp", "sp"))
        engines = [
            ("gpipe", PipelineLMEngine(cfg, SGD(0.1), mesh,
                                       n_mubatches=2, seed=0,
                                       schedule="gpipe", attn="ring")),
        ]

    if mode == "pp":
        # ZeRO-1 x pp across the boundary (round 4): Adam moments
        # sharded over 'dp' (local devices) ON pp-sharded stages that
        # SPAN the processes; the split GSPMD update program's
        # all-gather crosses the boundary every step
        from shallowspeed_tpu.optim import Adam

        engines.append(("z1", PipelineLMEngine(cfg, Adam(1e-2), mesh,
                                               n_mubatches=2, seed=0,
                                               zero1=True)))

    for tag, eng in engines:
        for step in range(3):
            rng = np.random.default_rng([11, step])  # same on every proc
            tok = rng.integers(0, cfg.vocab, (8, 16)).astype(np.int32)
            tgt = np.roll(tok, -1, axis=1).astype(np.int32)
            loss = eng.train_batch(tok, tgt)
            print(f"LOSS {pid} {tag}:{step} {loss!r}", flush=True)
        w = np.asarray(jax.device_get(eng.params["tok_emb"]))
        print(f"HASH {pid} {tag}:{hashlib.sha1(w.tobytes()).hexdigest()}",
              flush=True)

    ckpt_dir = os.environ.get("MP_CKPT_DIR")
    if ckpt_dir and mode == "pp":
        # multi-controller checkpoint (round 4): the canonical fetch is
        # collective (fetch_global replicates the pp-spanning leaves),
        # only process 0 writes, the barrier releases the rest. The
        # PARENT test then restores this 2-process checkpoint into a
        # 1-process engine (save-at-A / restore-at-B).
        from shallowspeed_tpu import checkpoint

        z1 = engines[-1][1]
        checkpoint.save(ckpt_dir, z1, 7)
        rng = np.random.default_rng([11, 0])
        tok = rng.integers(0, cfg.vocab, (8, 16)).astype(np.int32)
        ev = z1.eval_loss(tok, np.roll(tok, -1, axis=1))
        print(f"EVAL {pid} {ev!r}", flush=True)
    barrier("done")
    print(f"DONE {pid}", flush=True)


if __name__ == "__main__":
    main()
