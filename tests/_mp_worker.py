"""Worker process for the 2-process `jax.distributed` test
(`test_distributed.py::test_two_process_training_agrees`).

Each of the two OS processes hosts 2 virtual CPU devices and connects to
the local coordinator — a real multi-controller runtime (the thing the
reference gets from `mpirun -n N`, `/root/reference/train.py:87-94`),
with the gradient psum crossing the process boundary over the JAX
distributed service. Run: python _mp_worker.py <process_id> <port>.
"""

import os
import sys

import re

# FORCE 2 local devices, replacing any inherited count (pytest's conftest
# exports 8 into XLA_FLAGS; each worker must present exactly 2)
flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
               os.environ.get("XLA_FLAGS", ""))
os.environ["XLA_FLAGS"] = (
    flags + " --xla_force_host_platform_device_count=2").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")


def main() -> None:
    pid, port = int(sys.argv[1]), sys.argv[2]
    sys.path.insert(0, str(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))))

    import numpy as np

    from shallowspeed_tpu.distributed import (barrier, hybrid_mesh,
                                              initialize, local_rows,
                                              process_zero)
    from shallowspeed_tpu.models.transformer import TransformerConfig
    from shallowspeed_tpu.optim import SGD
    from shallowspeed_tpu.parallel.context import ContextParallelEngine

    assert initialize(f"localhost:{port}", num_processes=2, process_id=pid)
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 4, jax.device_count()  # 2 local x 2 procs
    assert len(jax.local_devices()) == 2
    assert process_zero() == (pid == 0)

    # dp=4 spans BOTH processes: the gradient pmean/psum crosses the
    # process boundary; place_global stitches each process's local row
    # block into the globally-sharded batch.
    mesh = hybrid_mesh(("dp", "sp"), (4, 1))
    cfg = TransformerConfig(vocab=32, d_model=16, n_heads=2, n_layers=1,
                            max_seq=16)
    eng = ContextParallelEngine(cfg, SGD(0.1), mesh, seed=0)

    for step in range(3):
        rng = np.random.default_rng([7, step])  # same batch on every proc
        tok = rng.integers(0, cfg.vocab, (8, 16)).astype(np.int32)
        tgt = np.roll(tok, -1, axis=1).astype(np.int32)
        loss = eng.train_batch(local_rows(tok), local_rows(tgt))
        print(f"LOSS {pid} {step} {loss!r}", flush=True)

    # post-training replica sync check across the process boundary (the
    # reference's assert_sync, `utils.py:27-31`); sha1, not hash() —
    # Python's hash is salted per process
    import hashlib

    w = np.asarray(jax.device_get(eng.params["tok_emb"]))
    print(f"HASH {pid} {hashlib.sha1(w.tobytes()).hexdigest()}", flush=True)
    barrier("done")
    print(f"DONE {pid}", flush=True)


if __name__ == "__main__":
    main()
