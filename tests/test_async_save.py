"""Async checkpointing (`checkpoint.AsyncSaver`, `--async-save`).

Contract: identical on-disk artifacts to the synchronous path (the
snapshot is taken on the caller's thread at the save point, so later
training steps cannot leak into the checkpoint), ordered completion,
and errors surfaced on wait/close instead of swallowed.
"""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from shallowspeed_tpu import checkpoint
from shallowspeed_tpu.models.transformer import TransformerConfig
from shallowspeed_tpu.optim import Adam
from shallowspeed_tpu.parallel.context import ContextParallelEngine

CFG = TransformerConfig(vocab=32, d_model=16, n_heads=2, n_layers=2,
                        max_seq=32)


def engine():
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("dp", "sp"))
    return ContextParallelEngine(CFG, Adam(1e-2), mesh, seed=0)


def batch(step):
    rng = np.random.default_rng([11, step])
    tok = rng.integers(0, 32, (4, 32)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1).astype(np.int32)


def test_async_matches_sync_snapshot(tmp_path):
    """The async save must capture the state AT the save point even if
    training continues while the write is queued."""
    eng = engine()
    eng.train_batch(*batch(0))
    saver = checkpoint.AsyncSaver()
    saver.save(tmp_path / "a", eng, 1)
    eng.train_batch(*batch(1))          # mutate AFTER the async save
    checkpoint.save(tmp_path / "b", eng, 1)
    saver.save(tmp_path / "a2", eng, 1)
    saver.close()

    sync_after = checkpoint.load_pytree(tmp_path / "b/ckpt_1/params.npz")
    async_at = checkpoint.load_pytree(tmp_path / "a/ckpt_1/params.npz")
    async_after = checkpoint.load_pytree(tmp_path / "a2/ckpt_1/params.npz")
    la = jax.tree_util.tree_leaves(async_at)
    lb = jax.tree_util.tree_leaves(sync_after)
    lc = jax.tree_util.tree_leaves(async_after)
    # the queued-then-trained save differs from post-training state...
    assert any(not np.array_equal(x, y) for x, y in zip(la, lb))
    # ...and the post-training async save equals the sync one exactly
    for x, y in zip(lc, lb):
        np.testing.assert_array_equal(x, y)


def test_async_restore_roundtrip(tmp_path):
    eng = engine()
    for s in range(2):
        eng.train_batch(*batch(s))
    saver = checkpoint.AsyncSaver()
    saver.save(tmp_path, eng, 2)
    saver.wait()
    eng2 = engine()
    assert checkpoint.restore(eng2, checkpoint.latest(tmp_path)) == 3
    tok, tgt = batch(5)
    np.testing.assert_allclose(eng.train_batch(tok, tgt),
                               eng2.train_batch(tok, tgt), rtol=1e-6)
    saver.close()


def test_async_error_surfaces(tmp_path):
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("file, not dir")
    eng = engine()
    saver = checkpoint.AsyncSaver()
    saver.save(blocker / "sub", eng, 0)   # mkdir under a file fails
    with pytest.raises(RuntimeError, match="async checkpoint"):
        saver.wait()
    # the saver stays usable after a surfaced error
    saver.save(tmp_path / "ok", eng, 1)
    saver.close()
    assert (tmp_path / "ok" / "ckpt_1" / "params.npz").exists()


def test_driver_async_save_resume(tmp_path):
    import train_lm

    common = ["--platform", "cpu", "--host-devices", "1", "--seq-len",
              "32", "--d-model", "32", "--batch-size", "4",
              "--log-every", "4", "--prefetch", "0",
              "--save-dir", str(tmp_path / "ck"), "--save-every", "4"]
    train_lm.train(train_lm.parse_args(
        common + ["--steps", "8", "--async-save"]))
    assert checkpoint.latest(tmp_path / "ck") is not None
    # resume continues bit-exactly from the async-written checkpoint
    train_lm.train(train_lm.parse_args(
        common + ["--steps", "12", "--resume", "--async-save"]))
    assert (tmp_path / "ck" / "ckpt_11").exists()
