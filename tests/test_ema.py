"""EMA of the weights (`optim.ema_update`, `--ema-decay`).

Driver-owned and engine-agnostic: a pure elementwise pytree update on
whatever the engine's live params are. Contracts: the math is the
textbook recursion, shardings are preserved, and the driver wires it
into validation/sampling/checkpoints.
"""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from shallowspeed_tpu.optim import ema_init, ema_update


def test_ema_math():
    p0 = {"w": jax.numpy.ones((4,)) * 2.0}
    ema = ema_init(p0)
    np.testing.assert_allclose(np.asarray(ema["w"]), 2.0)
    p1 = {"w": jax.numpy.ones((4,)) * 4.0}
    ema = ema_update(ema, p1, 0.9)
    np.testing.assert_allclose(np.asarray(ema["w"]),
                               0.9 * 2.0 + 0.1 * 4.0, rtol=1e-6)


def test_ema_preserves_sharding():
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("dp",))
    x = jax.device_put(np.zeros((8, 4), np.float32),
                       NamedSharding(mesh, P("dp")))
    ema = ema_init({"x": x})
    assert ema["x"].sharding == x.sharding
    ema = ema_update(ema, {"x": x + 1}, 0.5)
    assert ema["x"].sharding == x.sharding
    np.testing.assert_allclose(np.asarray(ema["x"]), 0.5)


def test_ema_survives_topology_change(tmp_path):
    """ema.npz is stored canonically (like params.npz): a pipeline run's
    average restores into a plain-dp sample-only run."""
    import train_lm

    train_lm.train(train_lm.parse_args(
        ["--platform", "cpu", "--host-devices", "2", "--dp", "1",
         "--pp", "2", "--ema-decay", "0.9", "--seq-len", "32",
         "--d-model", "32", "--n-layers", "2", "--batch-size", "4",
         "--steps", "4", "--save-every", "4", "--log-every", "2",
         "--prefetch", "0", "--save-dir", str(tmp_path / "ck")]))
    assert (tmp_path / "ck" / "ckpt_3" / "ema.npz").exists()
    # sample-only WITHOUT --pp and WITHOUT --ema-decay: auto-uses the
    # saved average through the canonical import path
    out = train_lm.train(train_lm.parse_args(
        ["--platform", "cpu", "--host-devices", "2", "--seq-len", "32",
         "--d-model", "32", "--n-layers", "2", "--sample-only",
         "--generate", "4", "--prefetch", "0",
         "--save-dir", str(tmp_path / "ck")]))
    assert np.isnan(out)


def test_driver_ema_resume_continues_average(tmp_path):
    """Save/resume must restore the running average, not restart it."""
    import train_lm

    common = ["--platform", "cpu", "--host-devices", "1",
              "--ema-decay", "0.9", "--seq-len", "32", "--d-model", "32",
              "--batch-size", "4", "--log-every", "5", "--prefetch", "0",
              "--save-dir", str(tmp_path / "ck"), "--save-every", "4"]
    train_lm.train(train_lm.parse_args(common + ["--steps", "8"]))
    straight_dir = tmp_path / "straight"
    train_lm.train(train_lm.parse_args(
        [*common[:-4], "--save-dir", str(straight_dir),
         "--save-every", "8", "--steps", "16"]))
    # resumed run: 8 more steps on top of the checkpoint
    train_lm.train(train_lm.parse_args(
        common + ["--steps", "16", "--resume"]))
    from shallowspeed_tpu import checkpoint

    ema_resumed = checkpoint.load_pytree(
        tmp_path / "ck" / "ckpt_15" / "ema.npz")
    ema_straight = checkpoint.load_pytree(
        straight_dir / "ckpt_15" / "ema.npz")
    for a, b in zip(jax.tree_util.tree_leaves(ema_resumed),
                    jax.tree_util.tree_leaves(ema_straight)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)
