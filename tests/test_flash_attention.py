"""Flash-attention kernel correctness vs the reference `ops.attention`:
forward and full custom-VJP backward, causal and bidirectional, over
uneven block/sequence combinations. Runs the actual Pallas kernels in
interpret mode on CPU — the same code path Mosaic compiles on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shallowspeed_tpu.ops.attention import attention
from shallowspeed_tpu.ops.flash_attention import flash_attention


def qkv(b=2, t=128, h=2, d=32, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(rng.normal(size=(b, t, h, d)).astype(np.float32)
                 for _ in range(3))


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("t,bq,bk", [(128, 64, 64), (128, 128, 32),
                                     (96, 32, 32)])
def test_forward_matches_reference(causal, t, bq, bk):
    q, k, v = qkv(t=t)
    want = np.asarray(attention(q, k, v, causal=causal))
    got = np.asarray(flash_attention(q, k, v, causal, block_q=bq,
                                     block_k=bk, interpret=True))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_gradients_match_reference(causal):
    q, k, v = qkv(t=64, d=16)

    def ref_loss(q, k, v):
        return (attention(q, k, v, causal=causal) ** 2).sum()

    def flash_loss(q, k, v):
        return (flash_attention(q, k, v, causal, block_q=32, block_k=32,
                                interpret=True) ** 2).sum()

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_ref, g_fl):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-4, atol=5e-5, err_msg=f"d{name}")


def test_block_autoshrink_odd_sequence():
    """T=40 not divisible by 128: blocks shrink to a divisor automatically."""
    q, k, v = qkv(t=40, d=16)
    want = np.asarray(attention(q, k, v, causal=True))
    got = np.asarray(flash_attention(q, k, v, True, interpret=True))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_transformer_with_flash_attention():
    """The LM family runs end-to-end with the kernel as its attn_fn."""
    from functools import partial

    from shallowspeed_tpu.models import transformer as T

    cfg = T.TransformerConfig(vocab=32, d_model=32, n_heads=2, n_layers=1,
                              max_seq=64)
    params = T.init(cfg, seed=0)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 32, (2, 64)).astype(np.int32)
    targets = np.roll(tokens, -1, axis=1).astype(np.int32)

    attn = partial(flash_attention, causal=True, block_q=32, block_k=32,
                   interpret=True)
    l_flash, g_flash = jax.value_and_grad(
        lambda p: T.loss(p, tokens, targets, cfg, attn_fn=attn))(params)
    l_ref, g_ref = jax.value_and_grad(
        lambda p: T.loss(p, tokens, targets, cfg))(params)
    assert abs(float(l_flash) - float(l_ref)) < 1e-5
    for a, b in zip(jax.tree_util.tree_leaves(g_ref),
                    jax.tree_util.tree_leaves(g_flash)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-4, atol=1e-5)


@pytest.mark.parametrize("window", [1, 16, 48, 64, 1000])
@pytest.mark.parametrize("causal", [True, False])
def test_window_matches_reference(window, causal):
    """Sliding-window flash == masked `ops.attention` (the semantics
    oracle, `attention(..., window=w)`), fwd and VJP, including tile
    boundary cases (window smaller / larger than a block; window 1 =
    self-only; window >= T = no-op)."""
    q, k, v = qkv(t=64, d=16)
    want = np.asarray(attention(q, k, v, causal=causal, window=window))
    got = np.asarray(flash_attention(q, k, v, causal, window,
                                     block_q=16, block_k=16,
                                     interpret=True))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    g_ref = jax.grad(lambda *a: (
        attention(*a, causal=causal, window=window) ** 2).sum(),
        argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(lambda *a: (
        flash_attention(*a, causal, window, block_q=16, block_k=16,
                        interpret=True) ** 2).sum(),
        argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_ref, g_fl):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-4, atol=5e-5, err_msg=f"d{name}")


def test_window_streaming_matches_resident(monkeypatch):
    """The streaming (3-D grid) kernels honor windows identically."""
    import shallowspeed_tpu.ops.flash_attention as fa

    q, k, v = qkv(t=128, d=16)
    want = np.asarray(attention(q, k, v, causal=True, window=40))
    monkeypatch.setattr(fa, "_RESIDENT_BYTES", 0)
    got = np.asarray(fa.flash_attention(q, k, v, True, 40, block_q=32,
                                        block_k=32, interpret=True))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
    g_ref = jax.grad(lambda *a: (
        attention(*a, causal=True, window=40) ** 2).sum(),
        argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(lambda *a: (
        fa.flash_attention(*a, True, 40, block_q=32, block_k=32,
                           interpret=True) ** 2).sum(),
        argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_ref, g_fl):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-4, atol=5e-5, err_msg=f"d{name}")


@pytest.mark.parametrize("kvh,window", [(1, 0), (2, 0), (2, 24), (4, 0)])
def test_gqa_native_matches_repeated(kvh, window):
    """GQA q-row group folding == attention over jnp.repeat'ed K/V: the
    kernel must produce identical outputs AND identical (k, v) grads —
    the repeated formulation's dk/dv sum over group members."""
    h = 4
    rng = np.random.default_rng(3)
    q = rng.normal(size=(2, 64, h, 16)).astype(np.float32)
    k = rng.normal(size=(2, 64, kvh, 16)).astype(np.float32)
    v = rng.normal(size=(2, 64, kvh, 16)).astype(np.float32)
    g = h // kvh
    k_rep = np.repeat(k, g, axis=2)
    v_rep = np.repeat(v, g, axis=2)

    want = np.asarray(attention(q, k_rep, v_rep, causal=True,
                                window=window))
    got = np.asarray(flash_attention(q, k, v, True, window, block_q=16,
                                     block_k=16, interpret=True))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    g_ref = jax.grad(lambda q, k, v: (attention(
        q, jnp.repeat(k, g, axis=2), jnp.repeat(v, g, axis=2),
        causal=True, window=window) ** 2).sum(),
        argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(lambda q, k, v: (flash_attention(
        q, k, v, True, window, block_q=16, block_k=16,
        interpret=True) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_ref, g_fl):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-4, atol=5e-5, err_msg=f"d{name}")


def test_gqa_streaming_matches_resident(monkeypatch):
    """GQA group folding on the streaming (3-D grid) kernels too."""
    import shallowspeed_tpu.ops.flash_attention as fa

    rng = np.random.default_rng(5)
    q = rng.normal(size=(1, 128, 4, 16)).astype(np.float32)
    k = rng.normal(size=(1, 128, 2, 16)).astype(np.float32)
    v = rng.normal(size=(1, 128, 2, 16)).astype(np.float32)
    want = np.asarray(fa.flash_attention(q, k, v, causal=True,
                                         interpret=True))
    monkeypatch.setattr(fa, "_RESIDENT_BYTES", 0)
    got = np.asarray(fa.flash_attention(q, k, v, causal=True,
                                        interpret=True))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def loss(fn):
        return lambda *a: (fn(*a, True) ** 2).sum()

    g_stream = jax.grad(loss(fa.flash_attention),
                        argnums=(0, 1, 2))(q, k, v)
    monkeypatch.undo()
    g_res = jax.grad(loss(fa.flash_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_stream, g_res):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_streaming_fwd_matches_resident(monkeypatch):
    """Force the streaming (3-D grid + scratch) forward and check it equals
    the resident fast path — the CPU suite's small shapes otherwise only
    exercise the resident branch."""
    import shallowspeed_tpu.ops.flash_attention as fa

    rng = np.random.default_rng(11)
    q, k, v = (jnp.asarray(rng.normal(size=(2, 256, 2, 16)), jnp.float32)
               for _ in range(3))
    want = np.asarray(fa.flash_attention(q, k, v, causal=True))
    monkeypatch.setattr(fa, "_RESIDENT_BYTES", 0)
    got = np.asarray(fa.flash_attention(q, k, v, causal=True))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def loss(fn):
        return lambda *a: (fn(*a, True) ** 2).sum()

    g_stream = jax.grad(loss(fa.flash_attention), argnums=(0, 1, 2))(q, k, v)
    monkeypatch.undo()
    g_res = jax.grad(loss(fa.flash_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_stream, g_res):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


# ------------------------------------------------------------ ring flash


def _shmap_ring(fn, sp, axis="sp"):
    from functools import partial

    from jax.sharding import Mesh, PartitionSpec as P

    # compat wrapper (utils.py): pre-VMA jax's replication rewriter has
    # no rule for pallas_call — the engines use this same wrapper
    from shallowspeed_tpu.utils import shard_map

    mesh = Mesh(np.array(jax.devices()[:sp]).reshape(sp), (axis,))
    return jax.jit(partial(
        shard_map(lambda q, k, v: fn(q, k, v),
                  mesh=mesh, in_specs=(P(None, axis), P(None, axis),
                                       P(None, axis)),
                  out_specs=P(None, axis))))


@pytest.mark.parametrize("sp", [1, 2, 4])
@pytest.mark.parametrize("kvh,window", [(4, 0), (2, 0), (2, 24)])
def test_ring_flash_matches_oracle(sp, kvh, window):
    """ring_flash_attention over a sequence-sharded axis == full
    `attention` on the gathered sequence — fwd AND grads (the
    hand-written ring VJP with traveling dk/dv accumulators), across
    sp widths, GQA group factors, and sliding windows."""
    from shallowspeed_tpu.ops.flash_attention import ring_flash_attention

    h, t, d = 4, 64, 16
    rng = np.random.default_rng(7)
    q = rng.normal(size=(2, t, h, d)).astype(np.float32)
    k = rng.normal(size=(2, t, kvh, d)).astype(np.float32)
    v = rng.normal(size=(2, t, kvh, d)).astype(np.float32)
    g = h // kvh
    want = np.asarray(attention(q, np.repeat(k, g, axis=2),
                                np.repeat(v, g, axis=2), causal=True,
                                window=window))

    ring = _shmap_ring(
        lambda a, b_, c: ring_flash_attention(a, b_, c, "sp", True,
                                              window), sp)
    got = np.asarray(ring(q, k, v))
    np.testing.assert_allclose(got, want, rtol=3e-5, atol=3e-5)

    # grads: ring VJP vs autodiff through the repeated-KV oracle
    def ref_loss(q, k, v):
        return (attention(q, jnp.repeat(k, g, axis=2),
                          jnp.repeat(v, g, axis=2), causal=True,
                          window=window) ** 2).sum()

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)

    ring_grad = _shmap_ring(
        lambda a, b_, c: jax.grad(
            lambda x, y, z: (ring_flash_attention(
                x, y, z, "sp", True, window) ** 2).sum(),
            argnums=(0, 1, 2))(a, b_, c), sp)

    # out_specs for grads: a 3-tuple sharded like the inputs
    from functools import partial

    from jax.sharding import Mesh, PartitionSpec as P

    from shallowspeed_tpu.utils import shard_map

    mesh = Mesh(np.array(jax.devices()[:sp]).reshape(sp), ("sp",))
    spec = P(None, "sp")
    # differentiate each device's LOCAL partial of the loss (no psum in
    # the differentiated function): run SPMD, every device seeds its own
    # partial with 1 and the ring VJP's reverse hops deliver the
    # cross-device cotangents, so the per-device grad outputs ARE the
    # global-loss grads. Differentiating THROUGH a psum is only correct
    # under VMA variance typing, which the check_rep=False compat
    # shard_map (pre-VMA jax) does not have.
    ring_grad = jax.jit(partial(shard_map(
        lambda a, b_, c: jax.grad(
            lambda x, y, z: (ring_flash_attention(
                x, y, z, "sp", True, window) ** 2).sum(),
            argnums=(0, 1, 2))(a, b_, c),
        mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=(spec, spec, spec))))
    g_got = ring_grad(q, k, v)
    for name, a, b_ in zip("qkv", g_ref, g_got):
        np.testing.assert_allclose(np.asarray(b_), np.asarray(a),
                                   rtol=1e-3, atol=1e-4,
                                   err_msg=f"d{name} sp={sp}")


def test_ring_flash_noncausal():
    from shallowspeed_tpu.ops.flash_attention import ring_flash_attention

    rng = np.random.default_rng(9)
    q, k, v = (rng.normal(size=(1, 32, 2, 8)).astype(np.float32)
               for _ in range(3))
    want = np.asarray(attention(q, k, v, causal=False))
    ring = _shmap_ring(
        lambda a, b_, c: ring_flash_attention(a, b_, c, "sp", False), 4)
    np.testing.assert_allclose(np.asarray(ring(q, k, v)), want,
                               rtol=3e-5, atol=3e-5)


def test_ring_flash_streaming_chunks(monkeypatch):
    """Force the streaming (3-D grid) chunk kernels inside the ring and
    check fwd + grads against the oracle — long-context rings stream."""
    import shallowspeed_tpu.ops.flash_attention as fa

    monkeypatch.setattr(fa, "_RESIDENT_BYTES", 0)
    rng = np.random.default_rng(13)
    q, k, v = (rng.normal(size=(1, 64, 2, 16)).astype(np.float32)
               for _ in range(3))
    want = np.asarray(attention(q, k, v, causal=True))
    ring = _shmap_ring(
        lambda a, b_, c: fa.ring_flash_attention(a, b_, c, "sp", True), 4)
    np.testing.assert_allclose(np.asarray(ring(q, k, v)), want,
                               rtol=3e-5, atol=3e-5)

    from functools import partial

    from jax.sharding import Mesh, PartitionSpec as P

    from shallowspeed_tpu.utils import shard_map

    g_ref = jax.grad(lambda *a: (attention(*a, causal=True) ** 2).sum(),
                     argnums=(0, 1, 2))(q, k, v)
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("sp",))
    spec = P(None, "sp")
    # grad of the LOCAL loss partial — see test_ring_flash_matches_oracle
    # for why the differentiated function must not contain the psum
    ring_grad = jax.jit(partial(shard_map(
        lambda a, b_, c: jax.grad(
            lambda x, y, z: (fa.ring_flash_attention(
                x, y, z, "sp", True) ** 2).sum(),
            argnums=(0, 1, 2))(a, b_, c),
        mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=(spec, spec, spec))))
    for name, a, b_ in zip("qkv", g_ref, ring_grad(q, k, v)):
        np.testing.assert_allclose(np.asarray(b_), np.asarray(a),
                                   rtol=1e-3, atol=1e-4,
                                   err_msg=f"d{name}")


# ------------------------------------------------ ring-chunk envelope


def _ring_pair_err(out_dtype):
    """Relative error of the two-chunk ring composition (`_chunk_fwd` +
    `_merge_chunks`, second-half queries over an earlier block at
    rel=t/2 and the own block at rel=0 — exactly what
    `ring_flash_attention` composes) against the f32 XLA oracle, at
    bf16 inputs with the given chunk-output dtype. Returns
    (flash_err, xla_bf16_floor)."""
    import shallowspeed_tpu.ops.flash_attention as fa

    rng = np.random.default_rng(7)
    q, k, v = (jnp.asarray(rng.normal(size=(2, 256, 4, 32)) * 0.5,
                           jnp.bfloat16) for _ in range(3))
    t2 = 128
    qh = q[:, t2:]
    (_, _, _, _, kvh, _, bq, bk, nqb) = fa._ring_geometry(qh, k[:, :t2])
    kw = dict(causal=True, window=0, bq=bq, bk=bk, nqb_chunk=nqb,
              interpret=True, out_dtype=out_dtype)
    q3 = fa._fold_q(qh, kvh)
    o0, l0 = fa._chunk_fwd(q3, fa._to_bhsd(k[:, :t2]),
                           fa._to_bhsd(v[:, :t2]), t2, **kw)
    o1, l1 = fa._chunk_fwd(q3, fa._to_bhsd(k[:, t2:]),
                           fa._to_bhsd(v[:, t2:]), 0, **kw)
    o, _ = fa._merge_chunks(o0.astype(jnp.float32), l0, o1, l1)
    got = fa._unfold_q(o.astype(q3.dtype), 2, 4)

    def rel_err(a, b):
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        return float(np.abs(a - b).max()) / max(1e-6,
                                                float(np.abs(b).max()))

    f32 = jnp.float32
    oracle = attention(q.astype(f32), k.astype(f32), v.astype(f32),
                       causal=True)[:, t2:]
    floor = rel_err(attention(q, k, v, causal=True)[:, t2:], oracle)
    return rel_err(got, oracle), floor


def test_ring_chunk_numerics_envelope():
    """Pin the ring-chunk merge's numerics envelope (VERDICT r5 weak
    #2): with the f32 chunk carry the two-chunk composition must sit
    AT the XLA-bf16 rounding floor (<= 1.25x, headroom for interpret-
    vs-Mosaic drift), where the old bf16 chunk output measured 2.3x
    above it on-chip (BENCH_r05). The bf16-chunk variant is measured
    alongside to prove the carry — not some unrelated drift — is what
    closes the gap. BASELINE.md 'ring-chunk numerics envelope'
    documents the mechanism; bench.py certifies the same bound on the
    compiled kernels every bench round."""
    err_f32, floor = _ring_pair_err(jnp.float32)
    err_bf16, _ = _ring_pair_err(None)  # old behavior: chunk o in bf16
    assert err_f32 <= 1.25 * floor, (
        f"f32-carry ring chunk error {err_f32} above the bf16 floor "
        f"{floor} — the merge lost its f32 carry")
    assert err_f32 < err_bf16, (
        f"f32 carry ({err_f32}) should beat the bf16 chunk output "
        f"({err_bf16}) — the envelope mechanism changed")


# ------------------------------------------------ paged flash decode


@pytest.mark.parametrize("kvh,quant,window", [
    (0, False, 0),       # MHA, full-precision pools
    (2, False, 0),       # GQA
    (0, True, 0),        # int8 pools + f32 scale planes
    (2, True, 0),        # GQA + int8
    (0, False, 6),       # sliding window
    (2, True, 5),        # everything at once
], ids=["mha", "gqa", "int8", "gqa-int8", "window", "gqa-int8-window"])
def test_paged_flash_decode_matches_gather_reference(kvh, quant,
                                                     window):
    """THE fast-decode kernel pin: `paged_flash_decode` (grid over the
    block table via scalar-prefetch index maps, online softmax across
    a row's blocks, int8 KV + scales read natively) matches the XLA
    reference — `serving/cache.gather_table` + `masked_attention` —
    to <= 1e-4 in interpret mode, across causal/GQA/int8-KV/window
    configs. `gather_table` deliberately stays in the tree as this
    reference; bench.py records the same envelope Mosaic-compiled."""
    from shallowspeed_tpu.models import transformer as T
    from shallowspeed_tpu.models.kv_cache import masked_attention
    from shallowspeed_tpu.ops.flash_attention import paged_flash_decode
    from shallowspeed_tpu.serving.cache import (gather_table,
                                                init_block_pool,
                                                write_rows)

    cfg = T.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                              n_kv_heads=kvh, n_layers=1, max_seq=128,
                              attn_window=window)
    rng = np.random.default_rng(kvh + 10 * quant + window)
    bs, n, s, w = 8, 16, 4, 3
    pool = init_block_pool(cfg, n, bs, "int8" if quant else "")[0]
    bt = rng.integers(1, n, (s, w)).astype(np.int32)
    pos = np.asarray([bs * w - 1, 13, 20, 0], np.int32)
    for row in range(s):
        for p in range(pos[row] + 1):
            k = jnp.asarray(rng.normal(
                size=(1, cfg.kv_heads, cfg.head_dim)), jnp.float32)
            v = jnp.asarray(rng.normal(
                size=(1, cfg.kv_heads, cfg.head_dim)), jnp.float32)
            pool = write_rows(pool, k, v,
                              jnp.asarray([bt[row, p // bs]]),
                              jnp.asarray([p % bs]), quant)
    q = jnp.asarray(rng.normal(size=(s, cfg.n_heads, cfg.head_dim)),
                    jnp.float32)
    got = paged_flash_decode(q, pool, jnp.asarray(bt),
                             jnp.asarray(pos), window=window)
    span = jnp.arange(w * bs)
    valid = span[None, :] <= pos[:, None]
    if window > 0:
        valid = valid & (span[None, :] > pos[:, None] - window)
    ref = masked_attention(q[:, None], gather_table(pool,
                                                    jnp.asarray(bt)),
                           valid[:, None, None, None, :], cfg)[:, 0]
    err = float(jnp.abs(got - ref).max())
    scale = max(1e-6, float(jnp.abs(ref).max()))
    assert err / scale <= 1e-4, (err, scale)
    assert got.shape == (s, cfg.n_heads, cfg.head_dim)


def test_paged_flash_decode_scratch_rows_are_harmless():
    """Inactive slots (pos=0, table all scratch) run through the
    kernel like any other row — no NaNs, no reads outside block 0 —
    matching the engine's occupancy-is-data contract."""
    from shallowspeed_tpu.models import transformer as T
    from shallowspeed_tpu.ops.flash_attention import paged_flash_decode
    from shallowspeed_tpu.serving.cache import init_block_pool

    cfg = T.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                              n_layers=1, max_seq=64)
    pool = init_block_pool(cfg, 4, 8)[0]
    q = jnp.ones((2, cfg.n_heads, cfg.head_dim), jnp.float32)
    bt = jnp.zeros((2, 2), jnp.int32)        # all scratch
    out = paged_flash_decode(q, pool, bt, jnp.zeros((2,), jnp.int32))
    assert bool(jnp.isfinite(out).all())
