"""Distributed request tracing (round 16): cross-process trace
propagation + the stitched fleet timeline + the per-request latency
waterfall — `shallowspeed_tpu/telemetry/tracing.py`.

The load-bearing invariants:

- **Trace identity.** `Router.submit` mints one trace id per request;
  the dispatch payload propagates it (with a fresh dispatch span and
  the 0-based cross-engine `attempt` counter) into
  `ServingEngine.submit`, including the ``generated=`` failover
  re-dispatch — so one rid's lifecycle/route/failover/request events
  are joinable across the router log and N replica logs.
- **Stitching + skew correction.** `stitch()` fits one clock offset
  per process stanza from the router's dispatch/ack pairs; a replica
  whose WALL clock is wrong still lands on the router's timeline
  (pinned by the injected-skew test). The failed-over request's spans
  from the router and BOTH replicas lie on a single ordered timeline.
- **Waterfall closure.** `report.request_waterfall` components sum to
  the router-measured e2e by construction; the drill pins
  |rq_unexplained| <= 5% of e2e and the failover gap >= the recorded
  detection -> ready interval.
- **(rid, attempt) reduction.** `report.request_timeline` keys on the
  attempt counter so a failover-resumed rid's two seq streams never
  interleave and no cross-process wall delta lands in a phase.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np
import pytest

from shallowspeed_tpu.telemetry import tracing
from shallowspeed_tpu.telemetry.report import (request_timeline,
                                               request_waterfall)
from shallowspeed_tpu.telemetry.schema import (SCHEMA_VERSION,
                                               validate_file,
                                               validate_line)

ROOT = Path(__file__).resolve().parents[1]


def _closes(wf, rel=0.05, floor_ms=2.5) -> bool:
    """Waterfall closure bound: |rq_unexplained| <= max(rel * e2e,
    floor_ms). The relative 5% is the acceptance bound for the
    failed-over drill request (e2e >= the breaker cooldown, tens to
    hundreds of ms); a millisecond-scale journey needs the absolute
    floor — the router's e2e and the stitched segment endpoints come
    from different clock reads, and the per-stanza offset fit carries
    sub-ms asymmetry, so ~1 ms of residual on a 10 ms request is
    measurement noise, not a stitching defect."""
    return abs(wf["rq_unexplained_ms"]) <= max(
        rel * wf["e2e_ms"], floor_ms)


# ------------------------------------------------------------ id units


def test_trace_ids_are_unique_hex():
    ids = {tracing.new_trace_id() for _ in range(64)}
    assert len(ids) == 64
    assert all(len(i) == 32 and int(i, 16) >= 0 for i in ids)
    spans = {tracing.new_span_id() for _ in range(64)}
    assert len(spans) == 64 and all(len(s) == 16 for s in spans)


# ----------------------------------------------- synthetic reductions


def test_request_waterfall_sums_by_construction():
    jn = {"e2e_ms": 100.0, "segments": [
        {"component": "rq_queue", "ms": 10.0},
        {"component": "rq_prefill", "ms": 25.0},
        {"component": "rq_decode", "ms": 40.0},
        {"component": "rq_failover_gap", "ms": 20.0},
    ]}
    wf = request_waterfall(jn)
    assert wf["rq_unexplained_ms"] == pytest.approx(5.0)
    total = sum(wf[f"{c}_ms"]
                for c in tracing.COMPONENTS) + wf["rq_unexplained_ms"]
    assert total == pytest.approx(wf["e2e_ms"])
    fracs = sum(wf[f"{c}_frac"] for c in tracing.COMPONENTS) \
        + wf["rq_unexplained_frac"]
    assert fracs == pytest.approx(1.0, abs=1e-3)
    assert request_waterfall({"e2e_ms": None, "segments": []}) is None


def test_request_timeline_keyed_on_rid_attempt():
    """Two attempts of one rid (a failover continuation) from two
    PROCESSES: both seq counters start at 0 and the walls differ by
    ~1000 s of clock skew. A rid-only reduction interleaves the seq
    streams and books the cross-process wall delta into a phase; the
    (rid, attempt) reduction must not."""
    a0, a1 = 1000.0, 2000.0      # two engines' unrelated wall epochs
    recs = [
        {"event": "lifecycle", "id": "q", "phase": "submit", "seq": 0,
         "attempt": 0, "wall": a0, "trace": "t" * 32},
        {"event": "lifecycle", "id": "q", "phase": "queued", "seq": 1,
         "attempt": 0, "wall": a0 + 0.001, "prev": "submit",
         "ms_in_prev": 1.0},
        {"event": "lifecycle", "id": "q", "phase": "decoding",
         "seq": 2, "attempt": 0, "wall": a0 + 0.011, "prev": "queued",
         "ms_in_prev": 10.0},
        # attempt 1, on another engine whose clock is 1000 s off;
        # seq restarts at 0 and the submit carries the resumed marker
        {"event": "lifecycle", "id": "q", "phase": "submit", "seq": 0,
         "attempt": 1, "resumed": 3, "wall": a1, "trace": "t" * 32},
        {"event": "lifecycle", "id": "q", "phase": "queued", "seq": 1,
         "attempt": 1, "wall": a1 + 0.002, "prev": "submit",
         "ms_in_prev": 2.0},
        {"event": "lifecycle", "id": "q", "phase": "finished",
         "seq": 2, "attempt": 1, "wall": a1 + 0.022, "prev": "queued",
         "ms_in_prev": 20.0},
    ]
    tl = request_timeline(recs)["q"]
    assert tl["attempts"] == 2
    assert [p["phase"] for p in tl["phases"]] == [
        "submit", "queued", "decoding", "submit", "queued", "finished"]
    # no phase swallowed the ~1000 s cross-attempt clock gap
    assert sum(tl["by_phase_ms"].values()) < 100.0
    assert tl["by_phase_ms"]["submit"] == pytest.approx(3.0)
    assert tl["by_phase_ms"]["queued"] == pytest.approx(30.0)
    assert tl["complete"]
    # e2e across two processes' clocks is not a real number — the
    # stitcher owns it
    assert tl["e2e_ms"] is None
    # pre-v11 fallback: no attempt fields — the resumed submit marker
    # still splits the attempts
    old = [dict(r) for r in recs]
    for r in old:
        r.pop("attempt", None)
    tl2 = request_timeline(old)["q"]
    assert tl2["attempts"] == 2
    assert sum(tl2["by_phase_ms"].values()) < 100.0


def test_stitch_router_log_appended_across_runs(tmp_path):
    """One router log APPENDED across two runs: each run_start restarts
    the monotonic epoch, so the second router stanza must be
    wall-aligned onto the first (and its dispatch/ack marks corrected
    by that offset) — at offset 0 the two epochs would share one mark
    set and poison every fit and the global timeline."""
    t2 = "b" * 32
    router = [
        # run 1: mono epoch 5000 @ wall 1000 (delta +4000)
        {"event": "run_start", "kind": "router", "schema_version": 11,
         "wall": 1000.0, "mono": 5000.0},
        {"event": "route", "id": "a", "trace": "a" * 32, "span": "s1",
         "parent": "p1", "replica": "r1", "wall": 1000.1,
         "mono": 5000.1, "dispatch_wall": 1000.09,
         "dispatch_mono": 5000.09, "wait_ms": 100.0},
        {"event": "request", "id": "a", "trace": "a" * 32, "span": "p1",
         "tokens_in": 4, "tokens_out": 4, "e2e_ms": 500.0,
         "ttft_ms": 250.0, "wall": 1000.6, "mono": 5000.6},
        # run 2 (same file): fresh mono epoch 100 @ wall 2000
        {"event": "run_start", "kind": "router", "schema_version": 11,
         "wall": 2000.0, "mono": 100.0},
        {"event": "route", "id": "b", "trace": t2, "span": "s2",
         "parent": "p2", "replica": "r1", "wall": 2000.1,
         "mono": 100.1, "dispatch_wall": 2000.09,
         "dispatch_mono": 100.09, "wait_ms": 100.0},
        {"event": "request", "id": "b", "trace": t2, "span": "p2",
         "tokens_in": 4, "tokens_out": 4, "e2e_ms": 700.0,
         "ttft_ms": 300.0, "wall": 2000.7, "mono": 100.7},
    ]
    replica = [
        {"event": "run_start", "replica": "r1", "schema_version": 11,
         "wall": 2000.0, "mono": 30.0},
        {"event": "lifecycle", "id": "b", "trace": t2, "span": "e1",
         "attempt": 0, "phase": "submit", "seq": 0, "wall": 2000.095,
         "mono": 30.095},
        {"event": "lifecycle", "id": "b", "trace": t2, "span": "e1",
         "attempt": 0, "phase": "decoding", "seq": 1, "prev": "submit",
         "ms_in_prev": 205.0, "wall": 2000.3, "mono": 30.3},
        {"event": "lifecycle", "id": "b", "trace": t2, "span": "e1",
         "attempt": 0, "phase": "finished", "seq": 2,
         "prev": "decoding", "ms_in_prev": 300.0, "wall": 2000.6,
         "mono": 30.6},
    ]
    pr = tmp_path / "router.jsonl"
    pe = tmp_path / "replica_r1.jsonl"
    pr.write_text("".join(json.dumps(r) + "\n" for r in router))
    pe.write_text("".join(json.dumps(r) + "\n" for r in replica))
    st = tracing.stitch([pr, pe])
    offs = {(p["name"], p["stanza"]): p["offset_s"]
            for p in st["processes"]}
    # stanza 1's epoch (mono 100 @ wall 2000) lands +5900 s after
    # stanza 0's (mono 5000 @ wall 1000): delta 4000 - delta -1900
    assert offs[("router", 0)] == 0.0
    assert offs[("router", 1)] == pytest.approx(5900.0, abs=1e-6)
    # the replica fit lands on run 2's corrected marks (its true
    # offset onto the reference epoch), not raw epoch-0 values
    assert offs[("r1", 0)] == pytest.approx(5970.0, abs=0.01)
    wf = request_waterfall(st["journeys"][t2])
    assert wf["rq_unexplained_ms"] == pytest.approx(0.0, abs=1.0)
    # the global timeline orders run 1 strictly before run 2
    j1 = st["journeys"]["a" * 32]
    assert max(t for t, _p, _r in j1["events"]) \
        < min(t for t, _p, _r in st["journeys"][t2]["events"])


def test_stitch_abandoned_attempt_truncated(tmp_path):
    """A TIMEOUT failover abandons live work: the old replica survives
    and keeps stamping — even a late 'finished' AFTER the router
    already finalized via the new attempt. The stitcher must (a) not
    pair the abandoned attempt's finished with the router's request
    record (an invalid ack bound would drag the whole stanza's clock
    early) and (b) truncate the abandoned attempt's booked phases at
    the resumed attempt's start (the post-abandon tail is work the
    user never saw — booking it double-counts against the real
    attempt and swallows the closure)."""
    tr = "c" * 32
    router = [
        {"event": "run_start", "kind": "router", "schema_version": 11,
         "wall": 100.0, "mono": 100.0},
        {"event": "route", "id": "q", "trace": tr, "span": "s0",
         "parent": "p0", "replica": "rA", "wall": 100.1, "mono": 100.1,
         "dispatch_wall": 100.09, "dispatch_mono": 100.09,
         "wait_ms": 100.0},
        {"event": "failover", "id": "q", "trace": tr, "span": "s1",
         "parent": "p0", "replica": "rB", "attempt": 1,
         "reason": "timeout", "from": "rA", "tokens_done": 1,
         "wall": 102.0, "mono": 102.0, "dispatch_wall": 101.99,
         "dispatch_mono": 101.99},
        {"event": "request", "id": "q", "trace": tr, "span": "p0",
         "tokens_in": 4, "tokens_out": 4, "e2e_ms": 3000.0,
         "ttft_ms": 500.0, "wall": 103.0, "mono": 103.0},
    ]
    rep_a = [
        {"event": "run_start", "replica": "rA", "schema_version": 11,
         "wall": 100.0, "mono": 100.0},
        {"event": "lifecycle", "id": "q", "trace": tr, "attempt": 0,
         "phase": "submit", "seq": 0, "wall": 100.095,
         "mono": 100.095},
        {"event": "lifecycle", "id": "q", "trace": tr, "attempt": 0,
         "phase": "decoding", "seq": 1, "prev": "submit",
         "ms_in_prev": 205.0, "wall": 100.3, "mono": 100.3},
        # the stalled replica finishes LATE — after the router's
        # request record above
        {"event": "lifecycle", "id": "q", "trace": tr, "attempt": 0,
         "phase": "finished", "seq": 2, "prev": "decoding",
         "ms_in_prev": 3200.0, "wall": 103.5, "mono": 103.5},
    ]
    rep_b = [
        {"event": "run_start", "replica": "rB", "schema_version": 11,
         "wall": 100.0, "mono": 100.0},
        {"event": "lifecycle", "id": "q", "trace": tr, "attempt": 1,
         "phase": "submit", "seq": 0, "resumed": 1, "wall": 101.995,
         "mono": 101.995},
        {"event": "lifecycle", "id": "q", "trace": tr, "attempt": 1,
         "phase": "decoding", "seq": 1, "prev": "submit",
         "ms_in_prev": 205.0, "wall": 102.2, "mono": 102.2},
        {"event": "lifecycle", "id": "q", "trace": tr, "attempt": 1,
         "phase": "finished", "seq": 2, "prev": "decoding",
         "ms_in_prev": 700.0, "wall": 102.9, "mono": 102.9},
    ]
    paths = []
    for name, recs in (("router", router), ("rep_a", rep_a),
                       ("rep_b", rep_b)):
        p = tmp_path / f"{name}.jsonl"
        p.write_text("".join(json.dumps(r) + "\n" for r in recs))
        paths.append(p)
    st = tracing.stitch(paths)
    procs = {p["name"]: p for p in st["processes"]}
    # (a) the abandoned finished contributed NO ack bound: rA's fit
    # rests on its dispatch pair alone and its clock stays put (a
    # paired late finish would have dragged it ~0.5 s early)
    assert procs["rA"]["pairs"]["ack"] == 0
    assert procs["rB"]["pairs"]["ack"] == 1
    assert abs(procs["rA"]["offset_s"]) < 0.01
    # (b) the truncated waterfall closes exactly: rA's post-abandon
    # tail (1.5 s past rB's start) is not booked, so components sum to
    # the router-measured e2e instead of overshooting it
    wf = request_waterfall(st["journeys"][tr])
    assert wf["rq_unexplained_ms"] == pytest.approx(0.0, abs=1.0)
    assert wf["rq_decode_ms"] == pytest.approx(2395.0, abs=2.0)


# --------------------------------------------- the in-process canary


def _toks(seed=0, t=12, vocab=64):
    rng = np.random.default_rng(seed)
    return rng.integers(0, vocab, (t,)).astype(np.int32)


@pytest.fixture(scope="module")
def drill(tmp_path_factory):
    """ONE in-process fleet chaos drill shared by the stitch tests:
    two replicas with per-replica metrics JSONLs, a router log, one
    replica killed mid-decode, every stream completing
    token-identical to its solo oracle."""
    import jax

    from shallowspeed_tpu.metrics import MetricsLogger
    from shallowspeed_tpu.models import transformer as T
    from shallowspeed_tpu.models.generate import generate
    from shallowspeed_tpu.serving import ServingEngine
    from shallowspeed_tpu.serving.router import InProcessReplica, Router

    tmp = tmp_path_factory.mktemp("trace_drill")
    cfg = T.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                              n_layers=2, max_seq=128)
    params = jax.device_put(T.init(cfg, seed=1))

    def spawn(name):
        path = tmp / f"replica_{name}.jsonl"

        def factory(_n):
            return ServingEngine(
                params, cfg, n_blocks=32, block_size=8, max_slots=4,
                prefill_chunk=16,
                metrics=MetricsLogger(path, kind="serve",
                                      replica=name))

        return InProcessReplica(name, factory)

    log = tmp / "router.jsonl"
    router = Router(spawn, n_replicas=2,
                    metrics=MetricsLogger(log, kind="router"),
                    request_timeout=None,
                    breaker_kw=dict(cooldown=0.05, jitter=0.0),
                    policy_kw=dict(backoff=0.01, jitter=0.0))
    reqs = {"g": (_toks(20, t=10), 6, 0.0, 0),
            "s": (_toks(21, t=13), 6, 1.0, 7),
            "t": (_toks(22, t=9), 6, 0.7, 3)}
    oracle = {k: np.asarray(generate(params, p[None, :], cfg, mn,
                                     temperature=tmp_, seed=s))[0]
              for k, (p, mn, tmp_, s) in reqs.items()}
    for k, (p, mn, tmp_, s) in reqs.items():
        router.submit(p, mn, temperature=tmp_, seed=s, rid=k)
    for _ in range(500):
        router.step()
        if any(r.replica == "r0" and 1 <= len(r.tokens) < r.max_new
               for r in router.inflight.values()):
            break
    assert any(r.replica == "r0" for r in router.inflight.values())
    router._replicas["r0"]["handle"].kill()
    res = router.run(max_wall=120)
    for k, ref in oracle.items():
        np.testing.assert_array_equal(res[k], ref, err_msg=k)
    assert router.counters["failovers"] >= 1
    paths = [log, tmp / "replica_r0.jsonl", tmp / "replica_r1.jsonl"]
    return {"paths": paths, "router": router, "tmp": tmp}


def test_trace_context_propagates_across_failover(drill):
    """Every route/failover/lifecycle/request event of one rid shares
    ONE trace id across the router and both replica logs; the
    failover re-dispatch increments `attempt`; everything validates
    as schema v11."""
    assert SCHEMA_VERSION >= 11
    for p in drill["paths"]:
        assert validate_file(p) == []
    router = drill["router"]
    # pick a failover that carried tokens (a mid-decode death): its
    # re-submit must show the resumed marker
    fo = next(e for e in router.events if e["event"] == "failover"
              and e.get("tokens_done", 0) >= 1)
    trace = fo["trace"]
    assert isinstance(trace, str) and len(trace) == 32
    rid = fo["id"]
    route = next(e for e in router.events if e["event"] == "route"
                 and e["id"] == rid)
    assert route["trace"] == trace and route["parent"] == fo["parent"]
    assert isinstance(route.get("wait_ms"), float)
    # the pre-POST clock pair (the skew fit's lower bound) rides both
    # dispatch events, and it precedes the event's own stamp
    for ev in (route, fo):
        assert isinstance(ev.get("dispatch_wall"), float)
        assert isinstance(ev.get("dispatch_mono"), float)
    # lifecycle events for this trace live in BOTH replica logs with
    # distinct attempt numbers and the resumed marker on the re-submit
    by_attempt = {}
    for p in drill["paths"][1:]:
        for line in p.read_text().splitlines():
            rec = json.loads(line)
            if rec.get("event") == "lifecycle" \
                    and rec.get("trace") == trace:
                by_attempt.setdefault(rec["attempt"], []).append(rec)
    assert set(by_attempt) >= {0, 1}
    resumed = [r for r in by_attempt[1] if r["phase"] == "submit"]
    assert resumed and resumed[0]["resumed"] >= 1
    assert resumed[0]["parent"] == fo["span"]
    # every line with a trace stamps the (wall, mono) clock pair
    for p in drill["paths"]:
        for line in p.read_text().splitlines():
            rec = json.loads(line)
            assert isinstance(rec.get("mono"), float), rec


def test_stitch_single_timeline_and_waterfall(drill):
    """THE acceptance canary: the failed-over request's spans from
    the router and BOTH replicas lie on a single skew-corrected
    timeline; its waterfall components sum to the measured e2e within
    5%; the failover gap >= the recorded detection -> ready
    interval."""
    st = tracing.stitch(drill["paths"])
    router = drill["router"]
    fo = next(e for e in router.events if e["event"] == "failover")
    jn = st["journeys"][fo["trace"]]
    assert set(jn["sources"]) >= {"router", "r0", "r1"}
    # one ordered timeline: every corrected event of attempt 0
    # precedes every corrected event of attempt 1
    t_att = {att: [t for t, _p, _r in evs]
             for att, evs in jn["attempts"].items()}
    assert max(t_att[0]) <= min(t_att[1]) + 1e-6
    # waterfall closure: 5% of e2e with the ms-scale absolute floor —
    # this in-process drill's journeys are tens of ms end to end, so
    # sub-ms stamp/poll granularity is a material fraction; the
    # strict seconds-scale 5% bound stays pinned by the committed
    # artifact + the cross-process drill
    wf = request_waterfall(jn)
    assert _closes(wf), wf
    named = sum(wf[f"{c}_ms"] for c in tracing.COMPONENTS)
    assert abs(named - wf["e2e_ms"]) <= max(
        0.05 * wf["e2e_ms"], 2.5), wf
    # failover gap >= detection -> ready: from the router's breaker
    # force-open stamp (detection, router clock) to the resumed
    # attempt's first corrected lifecycle stamp (re-prefill ready)
    led = [e for e in router.events if e["event"] == "ledger"
           and e.get("kind") == "breaker" and e.get("state") == "open"
           and e.get("replica") == fo["from"]]
    # the metrics line carries the wall stamp; find it in the log
    t_detect = None
    for line in drill["paths"][0].read_text().splitlines():
        rec = json.loads(line)
        if rec.get("event") == "ledger" \
                and rec.get("kind") == "breaker" \
                and rec.get("state") == "open" \
                and rec.get("replica") == fo["from"]:
            t_detect = rec["mono"]
            break
    assert led and t_detect is not None
    t_ready = min(t_att[1])
    gap_ms = wf["rq_failover_gap_ms"] + wf["rq_breaker_wait_ms"]
    assert gap_ms >= (t_ready - t_detect) * 1e3 - 1.0
    assert gap_ms > 0.0
    # non-failover journeys close too (absolute floor: these are
    # millisecond-scale requests)
    for trace, other in st["journeys"].items():
        owf = request_waterfall(other)
        assert owf is not None
        assert _closes(owf), (trace, owf)
    # the Chrome trace is loadable and carries both track families
    ev = st["chrome"]["traceEvents"]
    names = {e["name"] for e in ev}
    assert {"process_name", "thread_name"} <= names
    assert any(e["name"] == "rq_failover_gap" for e in ev)
    assert any(e["name"] == "decoding" and e["ph"] == "X" for e in ev)


def test_stitch_corrects_injected_wall_skew(drill):
    """Skew correction is real: shift one replica's ENTIRE clock pair
    (wall AND mono) 7.3 s into the future — the wall-aligned baseline
    alone would now misplace its spans — and the dispatch/ack pair
    fit must pull them back onto the router timeline: offsets differ
    by ~7.3 s, waterfalls match the unskewed stitch."""
    skew = 7.3
    st0 = tracing.stitch(drill["paths"])
    skewed = drill["tmp"] / "replica_r1_skewed.jsonl"
    lines = []
    for line in drill["paths"][2].read_text().splitlines():
        rec = json.loads(line)
        for k in ("wall", "mono"):
            if isinstance(rec.get(k), (int, float)):
                rec[k] = rec[k] + skew
        lines.append(json.dumps(rec))
    skewed.write_text("\n".join(lines) + "\n")
    st1 = tracing.stitch([drill["paths"][0], drill["paths"][1],
                          skewed])
    off0 = {(p["name"], p["stanza"]): p["offset_s"]
            for p in st0["processes"]}
    off1 = {(p["name"], p["stanza"]): p["offset_s"]
            for p in st1["processes"]}
    assert off1[("r1", 0)] - off0[("r1", 0)] == pytest.approx(
        -skew, abs=0.05)
    for trace, jn1 in st1["journeys"].items():
        wf0 = request_waterfall(st0["journeys"][trace])
        wf1 = request_waterfall(jn1)
        assert _closes(wf1), (trace, wf1)
        for c in tracing.COMPONENTS:
            assert wf1[f"{c}_ms"] == pytest.approx(
                wf0[f"{c}_ms"], abs=5.0), (trace, c)


def test_goodput_tracing_block_over_drill(drill):
    """--goodput over the router log + replica logs grows the fleet
    tracing block: per-component p50/p95, worst-unexplained
    exemplars; the formatted report prints it."""
    from shallowspeed_tpu.telemetry.goodput import (format_report,
                                                    run_goodput)

    rep = run_goodput(drill["paths"][0],
                      extra_paths=drill["paths"][1:])
    tr = rep["tracing"]
    assert tr is not None and tr["requests"] == 3
    assert "rq_decode" in tr["components"]
    assert tr["components"]["rq_decode"]["p50_ms"] > 0
    assert len(tr["worst_unexplained"]) == 3
    assert all(isinstance(w["trace"], str)
               for w in tr["worst_unexplained"])
    out = format_report(rep)
    assert "tracing (3 request(s)" in out
    # a training log has no tracing block
    assert run_goodput(ROOT / "docs_runs"
                       / "chaos_r06_metrics.jsonl")["tracing"] is None


def test_trace_stitch_cli(drill, tmp_path, capsys):
    from shallowspeed_tpu.telemetry.__main__ import main

    out = tmp_path / "stitched.json"
    rc = main(["--trace-stitch"] + [str(p) for p in drill["paths"]]
              + ["--out", str(out)])
    assert rc == 0
    cap = capsys.readouterr().out
    assert "router" in cap and "traced request(s)" in cap
    trace = json.loads(out.read_text())
    assert trace["traceEvents"]
    assert main(["--trace-stitch", str(tmp_path / "nope.jsonl")]) == 1


# ------------------------------------------- monitor / fleet surfaces


def test_monitor_rq_component_sketches_and_slowest_request():
    from shallowspeed_tpu.telemetry.monitor import Monitor

    mon = Monitor(snapshot_every=0, flight=0)
    for rid, decode_ms in (("a", 50.0), ("b", 400.0)):
        mon.note_line({"event": "lifecycle", "id": rid,
                       "phase": "submit", "attempt": 0,
                       "trace": "t" * 32, "wall": 1.0})
        mon.note_line({"event": "lifecycle", "id": rid,
                       "phase": "decoding", "prev": "queued",
                       "ms_in_prev": 10.0, "wall": 1.01})
        mon.note_line({"event": "lifecycle", "id": rid,
                       "phase": "finished", "prev": "decoding",
                       "ms_in_prev": decode_ms, "wall": 1.5})
    st = mon.status()
    assert st["sketches"]["rq_decode_ms"]["count"] == 2
    assert st["sketches"]["rq_queue_ms"]["count"] == 2
    sr = st["slowest_request"]
    assert sr["id"] == "b" and sr["trace"] == "t" * 32
    assert sr["by_component_ms"]["rq_decode"] == pytest.approx(400.0)
    assert sr["e2e_ms"] == pytest.approx(410.0)


def test_fleet_status_serves_slowest_request_decomposition(tmp_path):
    from shallowspeed_tpu.telemetry.fleet import FleetCollector

    paths = []
    for name, decode_ms in (("r0", 30.0), ("r1", 900.0)):
        p = tmp_path / f"{name}.jsonl"
        recs = [
            {"event": "run_start", "replica": name, "wall": 1.0},
            {"event": "lifecycle", "id": f"q-{name}",
             "phase": "submit", "attempt": 0, "trace": "u" * 32,
             "wall": 1.0},
            {"event": "lifecycle", "id": f"q-{name}",
             "phase": "finished", "prev": "decoding",
             "ms_in_prev": decode_ms, "wall": 2.0},
        ]
        p.write_text("".join(json.dumps(r) + "\n" for r in recs))
        paths.append(p)
    fc = FleetCollector(paths=paths)
    st = fc.refresh()
    sr = st["slowest_request"]
    assert sr["replica"] == "r1" and sr["id"] == "q-r1"
    assert sr["by_component_ms"]["rq_decode"] == pytest.approx(900.0)


# ------------------------------- committed cross-process artifact pin


ARTIFACT = sorted((ROOT / "docs_runs").glob("trace_r14_*.jsonl"))


@pytest.mark.skipif(not ARTIFACT,
                    reason="trace_r14 artifact not committed yet")
def test_stitch_committed_cross_process_artifact():
    """The committed cross-process drill artifact (router + replica
    logs from a real `router.py --chaos-fleet` run) stitches into ONE
    timeline in which a failed-over request spans the router and both
    replicas, with its waterfall closing within 5%."""
    router_log = next(p for p in ARTIFACT if "router" in p.name)
    replicas = [p for p in ARTIFACT if "replica" in p.name]
    assert len(replicas) >= 2
    st = tracing.stitch([router_log] + replicas)
    failover = [jn for jn in st["journeys"].values()
                if len(jn["attempts"]) >= 2]
    assert failover, "artifact must contain a failed-over request"
    spanning = [jn for jn in failover if len(jn["sources"]) >= 3]
    assert spanning, [jn["sources"] for jn in failover]
    for jn in spanning:
        wf = request_waterfall(jn)
        assert wf is not None
        assert abs(wf["rq_unexplained_frac"]) <= 0.05, (jn["rid"], wf)
        assert wf["rq_failover_gap_ms"] > 0.0
        atts = sorted(jn["attempts"])
        t_att = {att: [t for t, _p, _r in evs]
                 for att, evs in jn["attempts"].items()}
        for a, b in zip(atts, atts[1:]):
            assert max(t_att[a]) <= min(t_att[b]) + 1e-6


# ------------------------------------ cross-process drill (slow tier)


def test_trace_stitch_cross_process_drill(tmp_path):
    """Slow tier: a REAL router over two `serve.py --serve`
    subprocess replicas, r0 SIGKILLed mid-decode by its chaos plan —
    the stitched trace puts the failed-over request's spans from the
    router and both replicas on one skew-corrected timeline, the
    waterfall closes within 5%, and the failover gap >= the recorded
    detection -> ready interval."""
    import sys
    import time

    import jax

    from shallowspeed_tpu.metrics import MetricsLogger
    from shallowspeed_tpu.models import transformer as T
    from shallowspeed_tpu.models.generate import generate
    from shallowspeed_tpu.serving.router import ReplicaProc, Router
    from shallowspeed_tpu.telemetry.fleet import FleetCollector
    from shallowspeed_tpu.telemetry.monitor import StatusServer

    cfg = T.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                              n_layers=2, max_seq=128)
    params = jax.device_put(T.init(cfg, seed=0))
    collector = FleetCollector()
    srv = StatusServer(collector, port=0)
    fleet_url = f"http://{srv.host}:{srv.port}"
    serve_py = str(ROOT / "serve.py")
    chaos_map = {"r0": "kill@3", "r1": ""}

    def spawn(name):
        hb = str(tmp_path / f"hb_{name}")
        argv = [sys.executable, serve_py, "--serve",
                "--monitor-port", "0", "--fleet-register", fleet_url,
                "--replica", name, "--platform", "cpu",
                "--log-file", str(tmp_path / f"rep_{name}.jsonl"),
                "--heartbeat-file", hb,
                "--vocab", "64", "--d-model", "32", "--n-heads", "4",
                "--n-layers", "2", "--max-seq", "128",
                "--n-blocks", "32", "--block-size", "8",
                "--slots", "4", "--prefill-chunk", "16"]
        if chaos_map[name]:
            argv += ["--chaos", chaos_map[name],
                     "--chaos-state", str(tmp_path / f"chaos_{name}"),
                     "--chaos-seed", "0"]
        return ReplicaProc(
            name, argv, collector, heartbeat_file=hb,
            hang_timeout=20.0, term_grace=3.0,
            stdout_path=str(tmp_path / f"rep_{name}.out"))

    log = tmp_path / "router.jsonl"
    router = Router(spawn, n_replicas=2, collector=collector,
                    metrics=MetricsLogger(log, kind="router"),
                    request_timeout=45.0, progress_interval=0.1,
                    breaker_kw=dict(cooldown=0.5, jitter=0.2),
                    policy_kw=dict(backoff=0.2, jitter=0.1))
    collector.start(poll=0.3)
    try:
        t0 = time.monotonic()
        while time.monotonic() - t0 < 120.0:
            router.step()
            if not any(e["warming"]
                       for e in router._replicas.values()):
                break
            time.sleep(0.1)
        assert not any(e["warming"]
                       for e in router._replicas.values())
        reqs = {f"q{i}": (_toks(80 + i, t=8 + 2 * (i % 2)), 6,
                          0.7 if i % 2 else 0.0, i)
                for i in range(4)}
        oracle = {k: np.asarray(generate(params, p[None, :], cfg, mn,
                                         temperature=tmp_, seed=s))[0]
                  for k, (p, mn, tmp_, s) in reqs.items()}
        for k, (p, mn, tmp_, s) in reqs.items():
            router.submit(p, mn, temperature=tmp_, seed=s, rid=k)
        res = router.run(max_wall=300.0, poll=0.05)
        for k, ref in oracle.items():
            np.testing.assert_array_equal(res[k], ref, err_msg=k)
        assert router.counters["failovers"] >= 1
        fo = next(e for e in router.events
                  if e["event"] == "failover")
    finally:
        router.shutdown()
        collector.stop()
        srv.close()
    paths = [log, tmp_path / "rep_r0.jsonl",
             tmp_path / "rep_r1.jsonl"]
    for p in paths:
        assert validate_file(p) == []
    st = tracing.stitch(paths)
    jn = st["journeys"][fo["trace"]]
    assert set(jn["sources"]) >= {"router", "r0", "r1"}
    t_att = {att: [t for t, _p, _r in evs]
             for att, evs in jn["attempts"].items()}
    atts = sorted(t_att)
    for a, b in zip(atts, atts[1:]):
        assert max(t_att[a]) <= min(t_att[b]) + 1e-6
    wf = request_waterfall(jn)
    assert abs(wf["rq_unexplained_frac"]) <= 0.05, wf
    # detection -> ready from the router log's breaker open stamp
    t_detect = None
    for line in log.read_text().splitlines():
        rec = json.loads(line)
        if rec.get("event") == "ledger" \
                and rec.get("kind") == "breaker" \
                and rec.get("state") == "open" \
                and rec.get("replica") == fo["from"]:
            t_detect = rec["mono"]
            break
    assert t_detect is not None
    t_ready = min(t_att[atts[-1]])
    gap_ms = wf["rq_failover_gap_ms"] + wf["rq_breaker_wait_ms"]
    assert gap_ms >= (t_ready - t_detect) * 1e3 - 1.0
    # route/failover lines validate with the v11 fields
    for e in (fo,):
        assert validate_line(e) == []


# --------------------------- prefill_cached phase (round 19)


def test_prefill_cached_phase_component_and_timeline():
    """Satellite: the v14 `prefill_cached` lifecycle phase (stamped
    when the prefix cache maps shared blocks in at admission) books
    into rq_prefill — the waterfall keeps closing with cache hits in
    the stream — and `request_timeline` carries the stamp's
    blocks/tokens payload plus a per-request skipped_tokens total."""
    assert tracing.PHASE_COMPONENT["prefill_cached"] == "rq_prefill"
    recs = [
        {"event": "lifecycle", "id": "q", "phase": "submit", "seq": 0,
         "attempt": 0, "wall": 50.0},
        {"event": "lifecycle", "id": "q", "phase": "admitted", "seq": 1,
         "attempt": 0, "wall": 50.001, "prev": "submit",
         "ms_in_prev": 1.0},
        {"event": "lifecycle", "id": "q", "phase": "prefill_cached",
         "seq": 2, "attempt": 0, "wall": 50.0012, "prev": "admitted",
         "ms_in_prev": 0.2, "blocks": 3, "tokens": 48},
        {"event": "lifecycle", "id": "q", "phase": "decoding", "seq": 3,
         "attempt": 0, "wall": 50.003, "prev": "prefill_cached",
         "ms_in_prev": 1.8},
        {"event": "lifecycle", "id": "q", "phase": "finished", "seq": 4,
         "attempt": 0, "wall": 50.013, "prev": "decoding",
         "ms_in_prev": 10.0},
    ]
    for r in recs:
        assert validate_line(r) == [], r
    tl = request_timeline(recs)["q"]
    assert tl["complete"] and tl["attempts"] == 1
    cached = next(p for p in tl["phases"]
                  if p["phase"] == "prefill_cached")
    assert cached["blocks"] == 3 and cached["tokens"] == 48
    assert tl["skipped_tokens"] == 48
    # time spent IN the cached-admission phase books to prefill
    assert tl["by_phase_ms"]["prefill_cached"] == pytest.approx(1.8)
    # a timeline with no cache hit reports zero skipped, not a miss
    plain = [r for r in recs if r["phase"] != "prefill_cached"]
    plain[2]["prev"] = "admitted"
    assert request_timeline(plain)["q"]["skipped_tokens"] == 0
