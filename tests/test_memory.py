"""Memory observatory (`telemetry/memory.py`, round 20): live HBM
accounting, the per-owner ownership registry, leak/drift detection,
and the serving OOM forensics path.

The load-bearing invariants:

- **Accounting never invents bytes.** Every live array is claimed at
  most once (first registered owner wins), so tracked <= live and the
  `untracked` residual is >= 0 by construction; stale resolver leaves
  (donated-away buffers) cost 0.
- **The OOM drill recovers AND explains itself.** A seeded
  block-exhaustion run completes every stream, stamps a typed `oom`
  ledger line that validates at schema v15, and hands its forensics
  listeners a payload whose allocator snapshot satisfies
  n_free + n_live + n_cold == n_usable with the top owner named.
- **Detection is two-sided.** `MemoryWatch` catches step changes by
  robust z-spike (mem_drift) and slow leaks by monotone-growth run
  (mem_leak) — each blind to the other's failure mode.
"""

import json
from pathlib import Path

import jax
import numpy as np
import pytest

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.serving import (BlockAllocator, OutOfBlocks,
                                      ServingEngine, blocks_for)
from shallowspeed_tpu.telemetry import memory

CFG = T.TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                          max_seq=128)


@pytest.fixture(scope="module")
def params():
    return jax.device_put(T.init(CFG, seed=1))


@pytest.fixture(autouse=True)
def _clean_registry():
    """The registry is module-global observability state; tests must
    not leak owners (or resolvers closing over test arrays) into each
    other."""
    memory.clear_owners()
    yield
    memory.clear_owners()


def toks(seed=0, t=12, vocab=64):
    rng = np.random.default_rng(seed)
    return rng.integers(0, vocab, (t,)).astype(np.int32)


# ------------------------------------------------- sampling primitives


def test_live_hbm_high_water_counts_resident_shards():
    a = jax.device_put(np.ones((64, 64), np.float32))   # 16 KiB
    hw = memory.live_hbm_high_water()
    assert hw["n_arrays"] >= 1
    assert hw["max_device_bytes"] >= a.nbytes
    assert sum(hw["per_device"].values()) >= a.nbytes
    # per-device sums are what max_device_bytes reduces over
    assert hw["max_device_bytes"] == max(hw["per_device"].values())
    del a


def test_static_peak_bytes_matches_walker():
    from shallowspeed_tpu.analysis.walker import peak_bytes

    x = jax.ShapeDtypeStruct((128, 128), np.float32)
    fn = lambda v: (v @ v) + 1.0                       # noqa: E731
    got = memory.static_peak_bytes(fn, x)
    assert got == peak_bytes(jax.make_jaxpr(fn)(x).jaxpr)
    assert got >= 2 * 128 * 128 * 4    # input + matmul result live


def test_cross_check_bound_semantics():
    ok = memory.cross_check(100, 100)
    assert ok["within_bound"] and ok["ratio"] == 1.0
    assert memory.cross_check(104, 100)["within_bound"]   # inside 1.05
    bad = memory.cross_check(120, 100)
    assert not bad["within_bound"] and bad["ratio"] == 1.2
    # zero static prediction never divides by zero
    assert memory.cross_check(0, 0)["within_bound"]


def test_device_memory_stats_empty_on_cpu():
    stats = memory.device_memory_stats()
    if jax.devices()[0].platform == "cpu":
        assert stats == {}
    else:  # pragma: no cover — TPU/GPU CI
        for st in stats.values():
            assert all(isinstance(v, int) for v in st.values())


def test_host_rss_bytes_positive_and_plausible():
    rss = memory.host_rss_bytes()
    assert rss > 1 << 20        # a python + jax process holds > 1 MiB
    assert rss < 1 << 44


# ------------------------------------------------- ownership registry


def test_registry_accounting_first_owner_wins():
    a = jax.device_put(np.ones((32, 32), np.float32))
    b = jax.device_put(np.ones((16, 16), np.float32))
    memory.register_owner("first", lambda: {"w": a})
    memory.register_owner("second", lambda: [a, b])   # a already claimed
    assert memory.registered_owners() == ("first", "second")
    acct = memory.per_owner_accounting()
    assert acct["owners"]["first"] == a.nbytes
    assert acct["owners"]["second"] == b.nbytes       # a not re-counted
    assert acct["tracked_bytes"] == sum(acct["owners"].values())
    assert acct["untracked_bytes"] >= 0
    assert acct["tracked_bytes"] + acct["untracked_bytes"] \
        == acct["live_bytes"]
    del a, b


def test_registry_stale_and_broken_resolvers_cost_zero():
    gone = jax.device_put(np.ones((8, 8), np.float32))
    nb = gone.nbytes
    memory.register_owner("stale", lambda g=gone: g)
    live0 = memory.per_owner_accounting()
    assert live0["owners"]["stale"] == nb
    gone.delete()    # donated-away / deleted: resolver is now stale
    acct = memory.per_owner_accounting()
    assert acct["owners"]["stale"] == 0
    memory.register_owner("none", lambda: None)
    memory.register_owner("boom", lambda: 1 / 0)
    acct = memory.per_owner_accounting()
    assert acct["owners"]["none"] == 0 and acct["owners"]["boom"] == 0
    assert acct["untracked_bytes"] >= 0
    with pytest.raises(TypeError, match="callable"):
        memory.register_owner("bad", "not-a-resolver")
    memory.unregister_owner("stale")
    assert "stale" not in memory.registered_owners()


def test_top_live_arrays_names_owners():
    big = jax.device_put(np.ones((256, 256), np.float32))   # 256 KiB
    memory.register_owner("test.big", lambda: big)
    top = memory.top_live_arrays(3)
    assert 1 <= len(top) <= 3
    assert top[0]["nbytes"] >= top[-1]["nbytes"]    # sorted descending
    mine = [r for r in top if r["owner"] == "test.big"]
    assert mine and mine[0]["shape"] == [256, 256]
    assert mine[0]["dtype"] == "float32"
    assert memory.top_live_arrays(0) == []
    del big


def test_forensics_payload_shape():
    x = jax.device_put(np.ones((64, 64), np.float32))
    memory.register_owner("test.x", lambda: x)
    f = memory.forensics(top_k=2)
    assert sorted(f) == ["accounting", "device_stats", "host_rss_bytes",
                         "top_arrays"]
    assert f["accounting"]["owners"]["test.x"] == x.nbytes
    assert len(f["top_arrays"]) == 2
    assert f["host_rss_bytes"] > 0
    json.dumps(f)    # flight-dump payload must be JSON-serializable
    del x


# ------------------------------------------------- leak/drift detector


def test_memory_watch_leak_fires_once_on_sustained_growth():
    w = memory.MemoryWatch(spike_z=1e9, patience=4, growth_frac=0.01)
    verdicts = []
    x = 1e6
    for step in range(10):
        x *= 1.05                          # 5% growth every window
        verdicts += w.observe(step, device_bytes=x)
    leaks = [v for v in verdicts if v.kind == "mem_leak"]
    assert len(leaks) == 1                 # reported once, not per step
    assert leaks[0].severity == "error"
    assert leaks[0].step == 4              # patience-th growth window
    # plateau resets the run; renewed growth can re-report
    for step in range(10, 14):
        assert w.observe(step, device_bytes=x) == []
    again = []
    for step in range(14, 25):
        x *= 1.05
        again += w.observe(step, device_bytes=x)
    assert [v.kind for v in again].count("mem_leak") == 1


def test_memory_watch_drift_spikes_on_step_change():
    w = memory.MemoryWatch(spike_z=6.0, patience=1000, warmup=4)
    out = []
    for step in range(20):
        out += w.observe(step, device_bytes=1e6)   # flat steady state
    assert out == []
    spiked = w.observe(20, device_bytes=2e6)   # residency doubled
    assert [v.kind for v in spiked] == ["mem_drift"]
    assert "robust sigmas" in spiked[0].detail


def test_memory_watch_series_are_independent():
    w = memory.MemoryWatch(spike_z=1e9, patience=3, growth_frac=0.01)
    rss, dev = 1e6, 1e6
    hits = []
    for step in range(8):
        rss *= 1.1                         # host leaks, device flat
        hits += w.observe(step, device_bytes=dev, rss_bytes=rss)
    assert [v.kind for v in hits] == ["mem_leak"]
    assert "host_rss" in hits[0].detail
    # rss_bytes=0 (unavailable) is skipped, not treated as a crash to 0
    assert w.observe(99, rss_bytes=0) == []


def test_guard_policy_covers_memory_kinds():
    from shallowspeed_tpu.telemetry.anomaly import GuardPolicy

    for mode in ("monitor", "guard"):
        pol = GuardPolicy.for_mode(mode)
        assert pol.action("mem_leak") == "warn"
        assert pol.action("mem_drift") == "warn"


# ------------------------------------------- typed OutOfBlocks payload


def test_out_of_blocks_typed_payload_and_snapshot():
    al = BlockAllocator(8)
    ids = al.alloc(3, rid="warm")
    snap = al.snapshot()
    assert snap["n_usable"] == 7 and snap["n_live"] == 3
    assert snap["peak_live"] == 3 and snap["consistent"]
    with pytest.raises(OutOfBlocks) as ei:
        al.alloc(9, rid="req-7")
    e = ei.value
    assert (e.requested, e.n_free, e.n_cold, e.n_live) == (9, 4, 0, 3)
    assert e.rid == "req-7"
    # historical message shape preserved (pre-typed callers matched it)
    assert "need 9 blocks, 4 free + 0 cold" in str(e)
    assert "'req-7'" in str(e)
    # all-or-nothing: the failed alloc changed nothing
    assert al.snapshot() == snap
    al.free(ids)
    done = al.snapshot()
    assert done["n_free"] == done["n_usable"]
    assert done["peak_live"] == 3          # high-water survives drain
    # rid is optional; the payload still carries the counts
    plain = OutOfBlocks(2, n_free=1)
    assert plain.rid is None and "request" not in str(plain)


# --------------------------------------------- engine capacity plane


def test_engine_headroom_deficit_model(params):
    eng = ServingEngine(params, CFG, n_blocks=14, block_size=8,
                        max_slots=4, prefill_chunk=16)
    hr0 = eng.headroom()
    assert hr0 == {"live_blocks": 0, "blocks_needed": 0,
                   "headroom_blocks": 13}
    # one queued request's deficit = its full final footprint
    eng.submit(toks(0, t=24), 16, rid="a")
    need_a = blocks_for(24 + 16 - 1, 8)
    assert eng.headroom()["blocks_needed"] == need_a
    assert eng.headroom()["headroom_blocks"] == 13 - need_a
    # overcommit: accepted max-token budgets exceed the pool
    eng.submit(toks(1, t=24), 16, rid="b")
    eng.submit(toks(2, t=24), 16, rid="c")
    assert eng.headroom()["headroom_blocks"] < 0
    eng.run()
    end = eng.headroom()
    assert end["blocks_needed"] == 0 and end["live_blocks"] == 0


def test_oom_drill_recovers_with_forensics(params, tmp_path):
    """THE pinned OOM drill: seeded block exhaustion must recover via
    the evict path (every stream completes), stamp typed `oom` ledger
    lines that validate at schema v15, and hand the forensics listener
    a payload that names the top owner and self-checks the allocator
    invariant."""
    from shallowspeed_tpu.metrics import MetricsLogger
    from shallowspeed_tpu.telemetry import schema

    assert schema.SCHEMA_VERSION >= 15
    path = tmp_path / "oomdrill.jsonl"
    # 13 usable blocks * 8 = 104 positions < 3 * (24 + 16) = 120
    eng = ServingEngine(params, CFG, n_blocks=14, block_size=8,
                        max_slots=4, prefill_chunk=16,
                        metrics=MetricsLogger(path, kind="serve"),
                        log_every=2)
    dumps = []
    eng.oom_listeners.append(
        lambda en, exc: dumps.append(en.oom_forensics(exc)))
    for i, k in enumerate("abc"):
        eng.submit(toks(50 + i, t=24), 16, rid=k)
    res = eng.run()

    # recovery: every stream completed despite exhaustion
    assert set(res) == set("abc")
    assert all(len(r) == 16 for r in res.values())   # full max_new each
    assert eng.counters["oom_events"] >= 1
    assert eng.counters["preempted"] >= 1
    assert eng.alloc.n_free == eng.alloc.n_usable

    # forensics: listener got the rich payload at exhaustion time
    d = dumps[0]
    snap = d["allocator"]
    assert snap["consistent"]
    assert snap["n_free"] + snap["n_live"] + snap["n_cold"] \
        == snap["n_usable"]
    assert snap["n_live"] > 0              # exhaustion, not a leak
    acct = d["accounting"]
    assert acct["owners"]["serving.params"] > 0
    assert acct["owners"]["serving.kv_pools"] > 0
    assert acct["untracked_bytes"] >= 0
    top_owner = max(acct["owners"], key=acct["owners"].get)
    assert top_owner in ("serving.params", "serving.kv_pools")
    assert d["oom"]["requested"] >= 1
    assert d["headroom"]["headroom_blocks"] < 0    # overcommitted
    assert d["in_flight"] and d["block_tables"]
    assert all(w >= 1 for w in d["block_tables"].values())
    json.dumps(d)                          # flight-dump serializable

    # the metrics log validates and carries the v15 surface
    assert schema.validate_file(path) == []
    recs = [json.loads(ln) for ln in path.read_text().splitlines()]
    ooms = [r for r in recs
            if r.get("event") == "ledger" and r.get("kind") == "oom"]
    assert ooms
    for r in ooms:
        assert r["requested"] >= 1
        assert r["free"] + r["cold"] < r["requested"]
        assert "live" in r and "tick" in r
    gens = [r for r in recs if r.get("event") == "generate"]
    assert gens
    for g in gens:
        assert "headroom_blocks" in g and "live_blocks" in g
        assert "blocks_needed" in g
    # one ledger stamp per pressure episode (tick), not per retry
    assert len(ooms) == len({r["tick"] for r in ooms})

    # goodput reduces the same log into the memory block
    from shallowspeed_tpu.telemetry.goodput import (format_report,
                                                    run_goodput)

    rep = run_goodput(path)
    mem = rep["memory"]
    assert mem["oom_events"] == len(ooms)
    assert mem["worst_headroom_blocks"] < 0
    assert mem["worst_oom"]["requested"] >= 1
    assert mem["final_live_blocks"] == 0
    text = format_report(rep)
    assert "memory:" in text and "recovered OOM" in text


def test_goodput_memory_block_absent_without_memory_lines(tmp_path):
    from shallowspeed_tpu.telemetry.goodput import run_goodput

    path = tmp_path / "plain.jsonl"
    path.write_text(json.dumps(
        {"event": "step", "step": 1, "loss": 1.0, "wall": 1.0,
         "tokens_per_sec": 10.0}) + "\n")
    assert run_goodput(path)["memory"] is None


# --------------------------------------------- monitor + fleet surface


def test_monitor_memory_surface_and_oom_flight_dump(tmp_path):
    from shallowspeed_tpu.telemetry.monitor import Monitor

    mon = Monitor(flight=16, flight_dir=tmp_path, snapshot_every=0)
    mon.note_line({"event": "step", "step": 4, "loss": 1.0, "wall": 1.0,
                   "hbm_live_mib": 12.5,
                   "hbm_owned_mib": {"train.params": 8.0},
                   "hbm_untracked_mib": 4.5, "host_rss_mib": 900.0,
                   "hbm_within_bound": True})
    st = mon.status()
    assert st["memory"]["hbm_owned_mib"] == {"train.params": 8.0}
    assert st["memory"]["host_rss_mib"] == 900.0
    prom = mon.prometheus()
    assert "shallowspeed_hbm_live_mib 12.5" in prom
    assert "shallowspeed_host_rss_mib 900" in prom
    # a tailer-mode oom ledger line keeps the stamp AND dumps flight
    mon.note_line({"event": "ledger", "kind": "oom", "tick": 9,
                   "requested": 3, "free": 1, "cold": 0, "live": 12,
                   "wall": 2.0})
    assert mon.memory["last_oom"]["requested"] == 3
    assert mon.counters["flight_dumps"] == 1
    dump = json.loads(Path(mon.flight.dumps[0]).read_text())
    assert dump["reason"] == "oom" and dump["step"] == 9
    # the live-mode path: the engine listener's rich payload wins the
    # (reason, step) dedup when it arrives FIRST
    mon2 = Monitor(flight=16, flight_dir=tmp_path / "live",
                   snapshot_every=0)
    mon2.memory_flight_dump({"accounting": {"owners": {}}}, step=3)
    mon2.note_line({"event": "ledger", "kind": "oom", "tick": 3,
                    "requested": 2, "free": 0, "cold": 0, "live": 5,
                    "wall": 1.0})
    assert mon2.counters["flight_dumps"] == 1      # deduped
    rich = json.loads(Path(mon2.flight.dumps[0]).read_text())
    assert rich["trigger"] == {"accounting": {"owners": {}}}
    assert mon2.memory["oom_forensics"] == {"accounting": {"owners": {}}}
    # a mem_verdicts step line trips the incident path + health warn
    mon2.note_line({"event": "step", "step": 8, "loss": 1.0,
                    "wall": 3.0,
                    "mem_verdicts": ["[health] mem_leak at step 8: x"]})
    assert mon2.health.startswith("warn:")
    assert mon2.counters["flight_dumps"] == 2


def test_step_line_memory_fields_validate_v15():
    from shallowspeed_tpu.telemetry import schema

    line = {"event": "step", "step": 3, "loss": 2.0,
            "tokens_per_sec": 5.0, "hbm_owned_mib": {"a": 1.0},
            "hbm_untracked_mib": 0.5, "host_rss_mib": 100.0,
            "mem_verdicts": ["[health] mem_drift at step 3: y"]}
    assert schema.validate_line(line) == []
    assert schema.validate_line(
        {**line, "hbm_untracked_mib": "lots"}) != []
    assert schema.validate_line({**line, "hbm_owned_mib": 3}) != []


def test_fleet_memory_rollup_and_digest():
    from shallowspeed_tpu.telemetry.fleet import (FleetCollector,
                                                  format_fleet_status)

    fc = FleetCollector()
    r0 = fc.add_url("http://127.0.0.1:1/status.json", "r0")
    r1 = fc.add_url("http://127.0.0.1:2/status.json", "r1")
    # inject polled payloads directly (what refresh() would store)
    r0._status = {"serving": {"headroom_blocks": 11, "queue_depth": 0},
                  "memory": {"hbm_live_mib": 10.0}}
    r1._status = {"serving": {"headroom_blocks": -4, "queue_depth": 2},
                  "memory": {"hbm_live_mib": 30.0,
                             "last_oom": {"requested": 2, "tick": 7}}}
    st = fc.status()
    mem = st["memory"]
    assert mem["headroom_blocks"] == {"r0": 11, "r1": -4}
    assert mem["worst_headroom"] == {"replica": "r1", "value": -4}
    assert mem["oom_recovered"] == ["r1"]
    assert mem["replicas"]["r1"]["hbm_live_mib"] == 30.0
    text = format_fleet_status(st)
    assert "worst headroom -4 blocks @ r1" in text
    assert "OOM recovered: r1" in text
