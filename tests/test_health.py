"""Training-health observability (`telemetry/health.py` + `anomaly.py`).

The acceptance gates, pinned:
- every engine family's compiled step reports finite grad-norm /
  update-ratio / nonfinite fields with EXACTLY one executable per
  entrypoint (the health pack adds outputs, never entrypoints — the
  same counter the analysis retrace rule reads);
- dp / fsdp / pipeline health reductions match the single-device
  oracle to fp tolerance;
- an injected NaN fires the sentinel, and under health="guard" the
  update is skipped BIT-identically (params and optimizer state
  byte-equal to before the step) while the skip counter increments;
- the anomaly detectors (robust-EWMA spikes, divergence, dead layer)
  and the elastic dead-heartbeat restart behave as documented.
"""

import json

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.models.mlp import MLPStage
from shallowspeed_tpu.optim import SGD, Adam
from shallowspeed_tpu.telemetry import anomaly, health

CFG = T.TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                          max_seq=32)
SIZES = [784, 32, 31, 10]


@pytest.fixture(scope="module", autouse=True)
def _drop_compiled_caches_after_module():
    """This module compiles many short-lived engines (6 LM families x
    modes, 3 MLP engines); their executables' baked-in constants stay
    live in the pjit cache until collected and would otherwise tip
    test_telemetry's live-vs-static HBM cross-check (a 1.05x bound on
    CUMULATIVE process-wide live arrays) later in the same suite run."""
    yield
    import gc

    jax.clear_caches()
    gc.collect()

tree_leaves = jax.tree_util.tree_leaves


def lm_batch(seed=0, b=8, t=32, vocab=64):
    rng = np.random.default_rng(seed)
    tok = rng.integers(0, vocab, (b, t)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1).astype(np.int32)


def mesh2(dp, other, name):
    devs = np.array(jax.devices()[: dp * other]).reshape(dp, other)
    return Mesh(devs, ("dp", name))


def oracle_engine(opt=None, health_mode="monitor"):
    from shallowspeed_tpu.parallel.context import ContextParallelEngine

    return ContextParallelEngine(
        CFG, opt or Adam(1e-3), mesh2(1, 1, "sp"), seed=0,
        health=health_mode)


class _DS:
    """Minimal per-rank Dataset stand-in for the MLP engines."""

    def __init__(self, seed=0, rows=16, n_mu=4, poison=False):
        rng = np.random.default_rng(seed)
        self.rows, self.n_mu = rows, n_mu
        self.x = rng.standard_normal((rows, 784)).astype(np.float32)
        self.y = np.eye(10, dtype=np.float32)[
            rng.integers(0, 10, rows)]
        if poison:  # one nonfinite in ONE microbatch's input
            self.x[self.rows // self.n_mu + 1, 3] = np.nan

    def load_mubatch_stack(self, b):
        m = self.rows // self.n_mu
        return (self.x.reshape(self.n_mu, m, 784),
                self.y.reshape(self.n_mu, m, 10))

    def load_micro_batch_input(self, b, m):
        mb = self.rows // self.n_mu
        return self.x[m * mb:(m + 1) * mb]

    def load_micro_batch_target(self, b, m):
        mb = self.rows // self.n_mu
        return self.y[m * mb:(m + 1) * mb]

    def get_num_batches(self):
        return 1


def state_bytes(engine):
    return ([np.asarray(l).tobytes() for l in tree_leaves(engine.params)],
            [np.asarray(l).tobytes()
             for l in tree_leaves(engine.opt_state)])


def poison_params(engine):
    """Inject one NaN into the params (token engines' batches are int,
    so the gradient poison goes in through a weight). Note the skipped
    step's update_ratio then reads NaN, not 0 — ||old - old|| over a
    NaN-bearing tree NaN-propagates; the bit-identity assertion is the
    skip contract, and the float-input engines (test_fused_guard...)
    pin the clean ratio-0 behavior with finite params."""
    host = jax.device_get(engine.get_canonical_params())
    host = jax.tree_util.tree_map(lambda a: np.array(a), host)
    tree_leaves(host)[0].ravel()[0] = np.nan
    engine.set_canonical_params(host)


# ------------------------------------------------- pack correctness


def test_dp_sp_health_matches_single_device_oracle():
    from shallowspeed_tpu.parallel.context import ContextParallelEngine

    tok, tgt = lm_batch(0)
    o = oracle_engine()
    o.train_batch(tok, tgt)
    ref = o.health_snapshot()
    eng = ContextParallelEngine(CFG, Adam(1e-3), mesh2(2, 2, "sp"),
                                seed=0, health="monitor")
    eng.train_batch(tok, tgt)
    got = eng.health_snapshot()
    assert got["nonfinite"] == 0
    for k in ("grad_norm", "param_norm", "update_ratio"):
        assert got[k] == pytest.approx(ref[k], rel=1e-4), k
    for g, r in zip(got["groups"].values(), ref["groups"].values()):
        assert g == pytest.approx(r, rel=1e-4)


def test_fsdp_health_matches_single_device_oracle():
    from shallowspeed_tpu.parallel.fsdp import FSDPEngine

    tok, tgt = lm_batch(0)
    o = oracle_engine()
    o.train_batch(tok, tgt)
    ref = o.health_snapshot()
    eng = FSDPEngine(CFG, Adam(1e-3),
                     Mesh(np.array(jax.devices()[:4]), ("dp",)),
                     seed=0, health="monitor")
    eng.train_batch(tok, tgt)
    got = eng.health_snapshot()
    for k in ("grad_norm", "param_norm", "update_ratio"):
        assert got[k] == pytest.approx(ref[k], rel=1e-4), k


def test_pipeline_tp_health_matches_oracle():
    """pp x tp: block stats psum over BOTH sharded axes in-program.
    (This parity is what caught the pre-VMA pp x tp gradient corruption
    — round 7; keep it tight.)"""
    from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine

    tok, tgt = lm_batch(0)
    o = oracle_engine()
    o.train_batch(tok, tgt)
    ref = o.health_snapshot()
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(1, 2, 2),
                ("dp", "pp", "tp"))
    eng = PipelineLMEngine(CFG, Adam(1e-3), mesh, n_mubatches=2,
                           seed=0, health="monitor")
    eng.train_batch(tok, tgt)
    got = eng.health_snapshot()
    for k in ("grad_norm", "param_norm", "update_ratio"):
        assert got[k] == pytest.approx(ref[k], rel=1e-4), k


# ------------------- every family: finite fields, one executable each


def _exercised_cache_sizes(engine, fns):
    out = {}
    for name, fn in fns:
        size = getattr(fn, "_cache_size", None)
        if size is not None:
            out[name] = int(size())
    return out


def _lm_engines():
    from shallowspeed_tpu.parallel.context import ContextParallelEngine
    from shallowspeed_tpu.parallel.fsdp import FSDPEngine
    from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine
    from shallowspeed_tpu.parallel.tensor import TensorParallelEngine

    def ctx(h):
        return ContextParallelEngine(CFG, Adam(1e-3), mesh2(2, 2, "sp"),
                                     seed=0, health=h)

    def ctx_z1(h):
        return ContextParallelEngine(CFG, Adam(1e-3), mesh2(2, 1, "sp"),
                                     seed=0, zero1=True, health=h)

    def pp(h):
        return PipelineLMEngine(CFG, Adam(1e-3), mesh2(1, 2, "pp"),
                                n_mubatches=2, seed=0, health=h)

    def zb(h):
        return PipelineLMEngine(CFG, SGD(0.05), mesh2(1, 2, "pp"),
                                n_mubatches=2, seed=0, schedule="zb",
                                health=h)

    def fsdp(h):
        return FSDPEngine(CFG, Adam(1e-3),
                          Mesh(np.array(jax.devices()[:2]), ("dp",)),
                          seed=0, health=h)

    def tp(h):
        return TensorParallelEngine(CFG, Adam(1e-3), mesh2(1, 2, "tp"),
                                    seed=0, health=h)

    return {"context": ctx, "context-zero1": ctx_z1, "pipeline": pp,
            "pipeline-zb": zb, "fsdp": fsdp, "tensor": tp}


@pytest.mark.parametrize("family", ["context", "context-zero1",
                                    "pipeline", "pipeline-zb", "fsdp",
                                    "tensor"])
def test_lm_family_health_finite_and_one_executable(family):
    eng = _lm_engines()[family]("monitor")
    for step in range(3):
        eng.train_batch(*lm_batch(step))
    snap = eng.health_snapshot()
    assert snap["nonfinite"] == 0
    for k in ("grad_norm", "param_norm", "update_ratio"):
        assert np.isfinite(snap[k]) and snap[k] > 0, (family, k, snap)
    # exactly one executable per compiled entrypoint after 3 steps —
    # the pack added outputs, not entrypoints, and caused no retraces
    fns = [("step", getattr(eng, "_step_fn", None)),
           ("grads", getattr(eng, "_loss_grads_fn", None)
            or getattr(eng, "_grads_fn", None)),
           ("update", getattr(eng, "_update_fn", None))]
    counts = _exercised_cache_sizes(eng, [(n, f) for n, f in fns
                                          if f is not None])
    exercised = {k: v for k, v in counts.items() if v > 0}
    assert exercised, family
    assert all(v == 1 for v in exercised.values()), (family, counts)


def test_mlp_families_health_finite_and_one_executable():
    from shallowspeed_tpu.engine import FusedDPEngine
    from shallowspeed_tpu.parallel.mesh import make_mesh
    from shallowspeed_tpu.parallel.schedules import GPipeSchedule
    from shallowspeed_tpu.parallel.spmd_pipeline import SPMDPipelineEngine
    from shallowspeed_tpu.parallel.worker import PipelineExecutor
    from shallowspeed_tpu.telemetry.report import compile_counts

    fused = FusedDPEngine(MLPStage(SIZES, 0, 1, batch_size=32),
                          SGD(0.1), make_mesh(2, 1), health="monitor")
    for b in range(3):
        fused.train_batch(0, [_DS(0), _DS(1)])
    snap = fused.health_snapshot()
    assert snap["nonfinite"] == 0 and np.isfinite(snap["grad_norm"])
    assert set(snap["groups"]) == {"layer0", "layer1", "layer2"}
    assert int(fused._step._cache_size()) == 1

    spmd = SPMDPipelineEngine(
        SIZES, SGD(0.1),
        Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "pp")),
        4, 4, 32, health="monitor")
    for b in range(3):
        spmd.train_batch(0, [_DS(0), _DS(1)])
    ssnap = spmd.health_snapshot()
    assert ssnap["nonfinite"] == 0
    # same data, same semantics: fused and the compiled pipeline agree
    assert ssnap["grad_norm"] == pytest.approx(snap["grad_norm"],
                                               rel=1e-4)
    assert int(spmd._step_fn._cache_size()) == 1

    vm = PipelineExecutor(
        Mesh(np.array(jax.devices()[:2]).reshape(1, 2), ("dp", "pp")),
        [MLPStage(SIZES, s, 2, batch_size=32) for s in range(2)],
        SGD(0.1), health="monitor")
    for b in range(3):
        vm.train_batch(GPipeSchedule, 4, b, [_DS(0, rows=32)])
    vsnap = vm.health_snapshot()
    assert vsnap["nonfinite"] == 0
    assert np.isfinite(vsnap["grad_norm"]) and vsnap["grad_norm"] > 0
    assert np.isfinite(vsnap["update_ratio"])
    # per-stage packs merged over pp with stage-prefixed groups
    assert any(k.startswith("s0.") for k in vsnap["groups"])
    counts = compile_counts(vm.telemetry_entrypoints())
    exercised = {k: v for k, v in counts.items() if v > 0}
    assert exercised and all(v == 1 for v in exercised.values()), counts


# --------------------------------------- NaN injection + guarded skip


@pytest.mark.parametrize("family", ["context", "context-zero1",
                                    "pipeline", "fsdp"])
def test_lm_guard_skips_bit_identically(family):
    eng = _lm_engines()[family]("guard")
    tok, tgt = lm_batch(0)
    eng.train_batch(tok, tgt)         # healthy step updates
    poison_params(eng)
    p0, s0 = state_bytes(eng)
    eng.train_batch(*lm_batch(1))     # poisoned grads -> skip
    snap = eng.health_snapshot()
    assert snap["nonfinite"] > 0, family
    assert snap["skipped"] == 1, family
    assert not snap["update_ratio"] > 0, family  # 0, or NaN-poisoned
    p1, s1 = state_bytes(eng)
    assert p0 == p1 and s0 == s1, (
        f"{family}: a guarded skip must leave params AND optimizer "
        f"state bit-identical")


def test_lm_monitor_reports_but_does_not_skip():
    eng = _lm_engines()["context"]("monitor")
    eng.train_batch(*lm_batch(0))
    poison_params(eng)
    p0, _ = state_bytes(eng)
    eng.train_batch(*lm_batch(1))
    snap = eng.health_snapshot()
    assert snap["nonfinite"] > 0 and snap.get("skipped", 0) == 0
    p1, _ = state_bytes(eng)
    assert p0 != p1  # monitor observes; it does not guard


def test_fused_guard_skips_bit_identically_on_input_nan():
    from shallowspeed_tpu.engine import FusedDPEngine
    from shallowspeed_tpu.parallel.mesh import make_mesh

    eng = FusedDPEngine(MLPStage(SIZES, 0, 1, batch_size=16),
                        SGD(0.1), make_mesh(1, 1), health="guard")
    eng.train_batch(0, [_DS(0)])
    assert eng.health_snapshot()["skipped"] == 0
    p0, s0 = state_bytes(eng)
    eng.train_batch(0, [_DS(1, poison=True)])
    snap = eng.health_snapshot()
    assert snap["nonfinite"] > 0 and snap["skipped"] == 1
    p1, s1 = state_bytes(eng)
    assert p0 == p1 and s0 == s1
    # recovery: the next healthy batch trains again
    eng.train_batch(0, [_DS(2)])
    assert eng.health_snapshot()["skipped"] == 0
    assert state_bytes(eng)[0] != p0


def test_vm_guard_skips_all_stages_in_lockstep():
    from shallowspeed_tpu.parallel.schedules import GPipeSchedule
    from shallowspeed_tpu.parallel.worker import PipelineExecutor

    vm = PipelineExecutor(
        Mesh(np.array(jax.devices()[:2]).reshape(1, 2), ("dp", "pp")),
        [MLPStage(SIZES, s, 2, batch_size=32) for s in range(2)],
        SGD(0.1), health="guard")
    p0, s0 = state_bytes(vm)
    vm.train_batch(GPipeSchedule, 4, 0, [_DS(0, rows=32, poison=True)])
    snap = vm.health_snapshot()
    assert snap["nonfinite"] > 0 and snap["skipped"] == 1
    assert vm.health_skipped == 1
    p1, s1 = state_bytes(vm)
    assert p0 == p1 and s0 == s1
    vm.train_batch(GPipeSchedule, 4, 1, [_DS(1, rows=32)])
    assert vm.health_skipped == 1  # healthy batch trained
    assert state_bytes(vm)[0] != p0


def test_skip_counter_rides_step_fields():
    """The step-line counter increments across guarded skips
    (HealthMonitor -> StepRates merge)."""
    eng = _lm_engines()["context"]("guard")
    mon = health.HealthMonitor()
    eng.train_batch(*lm_batch(0))
    mon.observe(0, 2.0, eng.health_snapshot())
    poison_params(eng)
    eng.train_batch(*lm_batch(1))
    mon.observe(1, 2.0, eng.health_snapshot())
    eng.train_batch(*lm_batch(2))
    mon.observe(2, 2.0, eng.health_snapshot())
    fields = mon.step_fields()
    assert fields["health_skipped_total"] == 2
    assert fields["health_nonfinite"] > 0
    assert "nonfinite" in fields["health_verdicts"]


def test_transient_skip_between_log_points_is_counted():
    """A skip mid-window must reach the next snapshot even though
    last_health is overwritten every step (the device-side CUMULATIVE
    counters, health.note_step): poison exactly one step, recover, and
    only THEN observe."""
    from shallowspeed_tpu.engine import FusedDPEngine
    from shallowspeed_tpu.parallel.mesh import make_mesh

    eng = FusedDPEngine(MLPStage(SIZES, 0, 1, batch_size=16),
                        SGD(0.1), make_mesh(1, 1), health="guard")
    eng.train_batch(0, [_DS(0)])
    eng.train_batch(0, [_DS(1, poison=True)])   # skipped, not observed
    eng.train_batch(0, [_DS(2)])                # clean again
    snap = eng.health_snapshot()
    assert snap["nonfinite"] == 0               # the LAST step is clean
    assert snap["skipped_total"] == 1           # ...the skip still counted
    assert snap["nonfinite_steps_total"] == 1
    # and the monitor surfaces it on the next log point
    mon = health.HealthMonitor()
    verdicts = mon.observe(2, 2.0, snap)
    assert any(v.kind == "nonfinite" for v in verdicts)
    assert mon.step_fields()["health_skipped_total"] == 1


# ---------------------------------------------------- host-side units


def test_merge_packs_recovers_global_norms():
    import math

    a = {"grad_norm": 3.0, "param_norm": 4.0, "nonfinite": 1,
         "groups": {"layer0": 3.0}, "update_ratio": 0.5}
    b = {"grad_norm": 4.0, "param_norm": 3.0, "nonfinite": 2,
         "groups": {"layer0": 4.0}, "update_ratio": 1.0}
    m = health.merge_packs([a, b])
    assert m["grad_norm"] == pytest.approx(5.0)
    assert m["param_norm"] == pytest.approx(5.0)
    assert m["nonfinite"] == 3
    assert set(m["groups"]) == {"s0.layer0", "s1.layer0"}
    # sqrt((0.5*4)^2 + (1.0*3)^2) / 5
    assert m["update_ratio"] == pytest.approx(
        math.sqrt(2.0 ** 2 + 3.0 ** 2) / 5.0, rel=1e-6)
    assert health.merge_packs([]) is None


def test_robust_ewma_flags_outlier_not_baseline():
    ew = anomaly.RobustEWMA(alpha=0.1, warmup=5)
    rng = np.random.default_rng(0)
    zs = [ew.update(5.0 + 0.1 * rng.standard_normal())
          for _ in range(30)]
    assert all(abs(z) < 6 for z in zs if z is not None)
    z = ew.update(50.0)
    assert z is not None and z > 6


def test_detector_loss_spike_and_divergence():
    det = anomaly.AnomalyDetector(spike_z=6.0, div_factor=0.2,
                                  patience=3, warmup=4)
    for i in range(10):
        assert det.observe(i, loss=4.0 - 0.01 * i) == []
    v = det.observe(10, loss=40.0)
    assert [x.kind for x in v] == ["loss_spike"]
    kinds = []
    for i in range(11, 30):
        kinds += [x.kind for x in det.observe(i, loss=40.0)]
    assert "divergence" in kinds
    # a nonfinite loss is divergence immediately
    det2 = anomaly.AnomalyDetector()
    v = det2.observe(0, loss=float("nan"))
    assert [x.kind for x in v] == ["divergence"]


def test_detector_dead_layer_needs_patience_and_live_global():
    det = anomaly.AnomalyDetector(patience=3)
    pack = {"grad_norm": 1.0, "nonfinite": 0,
            "groups": {"head": 0.0, "blocks": 1.0}}
    assert det.observe(0, pack=pack) == []
    assert det.observe(1, pack=pack) == []
    v = det.observe(2, pack=pack)
    assert [x.kind for x in v] == ["dead_layer"]
    assert "head" in v[0].detail
    # reported once, not every observation after
    assert det.observe(3, pack=pack) == []


def test_guard_policy_modes_and_verdict_actions():
    p = anomaly.GuardPolicy.for_mode("guard")
    assert p.action("nonfinite") == "skip_step"
    assert p.action("divergence") == "warn"
    mon = health.HealthMonitor(policy=p)
    v = mon.observe(0, 2.0, {"grad_norm": 1.0, "param_norm": 1.0,
                             "nonfinite": 3, "groups": {}})
    assert v[0].kind == "nonfinite" and v[0].action == "skip_step"


def test_monitor_declares_dead_after_sustained_nonfinite():
    mon = health.HealthMonitor(dead_after=3)
    bad = {"grad_norm": float("nan"), "param_norm": 1.0,
           "nonfinite": 5, "groups": {}}
    assert mon.heartbeat_status() == "ok"
    for step in range(3):
        mon.observe(step, 2.0, bad)
    assert mon.heartbeat_status().startswith("dead")
    # recovery clears nothing retroactively but new healthy steps
    # keep the run counted; the status is sticky by design (the
    # supervisor restart is the way back)
    assert mon.nonfinite_steps == 3


# ----------------------------------------------- schema + elastic


def test_schema_accepts_v1_and_v2_lines():
    from shallowspeed_tpu.telemetry import schema

    # PR-2 dialect: no schema_version, no health fields
    assert schema.validate_line(
        {"event": "run_start", "dp": 2}) == []
    assert schema.validate_line(
        {"event": "step", "step": 1, "loss": 2.0,
         "tokens_per_sec": 10.0}) == []
    # health-extended dialect
    assert schema.validate_line(
        {"event": "run_start", "schema_version": schema.SCHEMA_VERSION
         }) == []
    assert schema.validate_line(
        {"event": "step", "step": 1, "loss": 2.0,
         "tokens_per_sec": 10.0, "health_grad_norm": 1.5,
         "health_nonfinite": 0, "health_skipped_total": 2,
         "health_verdicts": ["loss_spike"]}) == []
    assert schema.validate_line(
        {"event": "health", "step": 3, "health_grad_norm": 1.0}) == []
    # and still rejects malformed lines
    assert schema.validate_line(
        {"event": "step", "step": 1, "loss": 2.0,
         "tokens_per_sec": 10.0, "health_nonfinite": "three"})
    assert schema.validate_line(
        {"event": "run_start", "schema_version": "two"})


def test_metrics_logger_stamps_schema_version(tmp_path):
    from shallowspeed_tpu.metrics import MetricsLogger
    from shallowspeed_tpu.telemetry import schema

    path = tmp_path / "m.jsonl"
    MetricsLogger(path, dp=1)
    rec = json.loads(path.read_text().splitlines()[0])
    assert rec["schema_version"] == schema.SCHEMA_VERSION
    assert schema.validate_file(path) == []


def test_elastic_kills_numerically_dead_child(tmp_path):
    """A child that beats its heartbeat but reports 'dead ...' is
    killed for a checkpoint restart — the hang timeout alone would
    never fire on a beating loop."""
    import sys

    from shallowspeed_tpu.elastic import Supervisor

    hb = tmp_path / "hb"
    hb.write_text("ok")
    child = (
        "import time, sys\n"
        f"open({str(hb)!r}, 'w').write('dead nonfinite gradients')\n"
        "time.sleep(60)\n")
    sup = Supervisor([sys.executable, "-c", child],
                     hang_timeout=30.0, heartbeat_file=str(hb),
                     poll_interval=0.1)
    code, secs, fail_class = sup._run_once()
    assert code == -9
    assert secs < 20  # killed on the verdict, not the hang timeout
    assert fail_class == "numeric"  # round 10: classed for MTTR


def test_elastic_dead_kill_works_without_hang_timeout(tmp_path):
    """The health-verdict kill needs only a heartbeat file — a
    supervisor built without a hang timeout must still escalate."""
    import sys

    from shallowspeed_tpu.elastic import Supervisor

    hb = tmp_path / "hb"
    hb.write_text("ok")
    child = (
        "import time\n"
        f"open({str(hb)!r}, 'w').write('dead divergence')\n"
        "time.sleep(60)\n")
    sup = Supervisor([sys.executable, "-c", child],
                     hang_timeout=None, heartbeat_file=str(hb),
                     poll_interval=0.1)
    code, secs, fail_class = sup._run_once()
    assert code == -9 and secs < 20
    assert fail_class == "numeric"


def test_elastic_restart_clears_stale_dead_status(tmp_path):
    """A leftover 'dead ...' from the previous child must NOT kill the
    restarted child: _run_once resets the status to 'ok' at spawn."""
    import sys

    from shallowspeed_tpu.elastic import Supervisor

    hb = tmp_path / "hb"
    hb.write_text("dead nonfinite gradients")  # previous child's verdict
    sup = Supervisor([sys.executable, "-c", "import time; time.sleep(2)"],
                     hang_timeout=30.0, heartbeat_file=str(hb),
                     poll_interval=0.1)
    code, secs, fail_class = sup._run_once()
    assert code == 0, "fresh child was killed on the STALE dead status"
    assert fail_class is None


def test_heartbeat_status_roundtrip(tmp_path):
    from shallowspeed_tpu import elastic

    hb = tmp_path / "hb"
    elastic.write_heartbeat(hb, "ok")
    assert elastic.read_heartbeat_status(hb) == "ok"
    elastic.write_heartbeat(hb, "dead loss divergence")
    assert elastic.read_heartbeat_status(hb).startswith("dead")
    hb.write_text("")  # a plain touch stays a valid beat
    assert elastic.read_heartbeat_status(hb) == "ok"
    assert elastic.read_heartbeat_status(tmp_path / "absent") == "ok"
