"""L5 schedule tests — schedules are pure data, tested with zero devices.

Ports the reference's assertions (`/root/reference/tests/test_schedules.py`:
ZeroGrad first, OptimizerStep last, AllReduce exactly on the final backward)
and implements the upgrade its header comment wished for
(`test_schedules.py:4-10`): a happens-before check, here realised as a full
multi-stage FIFO-channel simulation that verifies deadlock-freedom, send/recv
pairing, and per-stage stash bounds for every schedule.
"""

import pytest

from shallowspeed_tpu.parallel.instructions import (
    BackwardGradAcc,
    BackwardGradAllReduce,
    Forward,
    LoadMuBatchInput,
    LoadMuBatchTarget,
    OptimizerStep,
    RecvActivations,
    RecvOutputGrad,
    SendActivations,
    SendInputGrad,
    ZeroGrad,
)
from shallowspeed_tpu.parallel.schedules import (
    GPipeSchedule,
    InferenceSchedule,
    NaiveParallelSchedule,
    PipeDreamSchedule,
)

TRAIN_SCHEDULES = [NaiveParallelSchedule, GPipeSchedule, PipeDreamSchedule]


def flatten(schedule):
    return [cmd for step in schedule.steps() for cmd in step]


# ------------------------------------------------------------ structure


@pytest.mark.parametrize("cls", TRAIN_SCHEDULES)
@pytest.mark.parametrize("n_stages,stage_id", [(1, 0), (4, 0), (4, 2), (4, 3)])
def test_zero_first_opt_last(cls, n_stages, stage_id):
    cmds = flatten(cls(num_micro_batches=4, num_stages=n_stages, stage_id=stage_id))
    assert isinstance(cmds[0], ZeroGrad)
    assert isinstance(cmds[-1], OptimizerStep)
    assert sum(isinstance(c, ZeroGrad) for c in cmds) == 1
    assert sum(isinstance(c, OptimizerStep) for c in cmds) == 1


@pytest.mark.parametrize("cls", TRAIN_SCHEDULES)
@pytest.mark.parametrize("n_stages,stage_id", [(1, 0), (4, 1), (4, 3)])
def test_one_fwd_one_bwd_per_mubatch(cls, n_stages, stage_id):
    n_mu = 4
    cmds = flatten(cls(n_mu, n_stages, stage_id))
    fwd_ids = [c.mubatch_id for c in cmds if isinstance(c, Forward)]
    bwd_ids = [c.mubatch_id for c in cmds
               if isinstance(c, (BackwardGradAcc, BackwardGradAllReduce))]
    assert sorted(fwd_ids) == list(range(n_mu))
    assert sorted(bwd_ids) == list(range(n_mu))


@pytest.mark.parametrize("cls", TRAIN_SCHEDULES)
@pytest.mark.parametrize("stage_id", [0, 1, 3])
def test_allreduce_exactly_on_final_bwd(cls, stage_id):
    """Exactly one BackwardGradAllReduce, and it is the last backward
    (reference `test_schedules.py` core assertion)."""
    cmds = flatten(cls(4, 4, stage_id))
    bwds = [c for c in cmds if isinstance(c, (BackwardGradAcc, BackwardGradAllReduce))]
    ars = [c for c in bwds if isinstance(c, BackwardGradAllReduce)]
    assert len(ars) == 1
    assert isinstance(bwds[-1], BackwardGradAllReduce)


def test_first_stage_loads_last_stage_targets():
    for cls in TRAIN_SCHEDULES:
        first = flatten(cls(4, 4, 0))
        last = flatten(cls(4, 4, 3))
        assert any(isinstance(c, LoadMuBatchInput) for c in first)
        assert not any(isinstance(c, RecvActivations) for c in first)
        assert any(isinstance(c, LoadMuBatchTarget) for c in last)
        assert not any(isinstance(c, (SendActivations, RecvOutputGrad)) for c in last)


def test_inference_schedule_fwd_only():
    cmds = flatten(InferenceSchedule(2, 4, 1))
    kinds = {type(c) for c in cmds}
    assert kinds <= {RecvActivations, Forward, SendActivations}
    assert sum(isinstance(c, Forward) for c in cmds) == 2


def test_gpipe_bwd_reversed_pipedream_fifo():
    def bwd_order(cls):
        cmds = flatten(cls(4, 2, 1))
        return [c.mubatch_id for c in cmds
                if isinstance(c, (BackwardGradAcc, BackwardGradAllReduce))]

    assert bwd_order(GPipeSchedule) == [3, 2, 1, 0]
    assert bwd_order(PipeDreamSchedule) == [0, 1, 2, 3]


# ------------------------------------------------- channel simulation


def simulate(cls, n_stages, n_mu):
    """Execute all stages' instruction streams against FIFO channels.

    Returns per-stage peak stash occupancy. Raises on deadlock or on a recv
    with nothing pairable in flight at completion.
    """
    progs = [flatten(cls(n_mu, n_stages, s)) for s in range(n_stages)]
    pcs = [0] * n_stages
    # channels[(src, dst)] = count of in-flight messages
    from collections import defaultdict

    channels = defaultdict(int)
    stash = [0] * n_stages
    peak = [0] * n_stages

    def blocked(s):
        c = progs[s][pcs[s]]
        if isinstance(c, RecvActivations):
            return channels[(s - 1, s)] == 0
        if isinstance(c, RecvOutputGrad):
            return channels[(s + 1, s)] == 0
        return False

    total = sum(len(p) for p in progs)
    executed = 0
    while executed < total:
        progress = False
        for s in range(n_stages):
            while pcs[s] < len(progs[s]) and not blocked(s):
                c = progs[s][pcs[s]]
                if isinstance(c, RecvActivations):
                    channels[(s - 1, s)] -= 1
                elif isinstance(c, RecvOutputGrad):
                    channels[(s + 1, s)] -= 1
                elif isinstance(c, SendActivations):
                    channels[(s, s + 1)] += 1
                elif isinstance(c, SendInputGrad):
                    channels[(s, s - 1)] += 1
                elif isinstance(c, Forward):
                    stash[s] += 1
                    peak[s] = max(peak[s], stash[s])
                elif isinstance(c, (BackwardGradAcc, BackwardGradAllReduce)):
                    stash[s] -= 1
                pcs[s] += 1
                executed += 1
                progress = True
        if not progress:
            raise AssertionError(f"deadlock: pcs={pcs}")
    assert all(v == 0 for v in channels.values()), "unconsumed messages"
    assert all(v == 0 for v in stash), "unconsumed stashes"
    return peak


@pytest.mark.parametrize("cls", TRAIN_SCHEDULES)
@pytest.mark.parametrize("n_stages,n_mu", [(1, 1), (1, 4), (2, 4), (4, 4), (4, 8), (8, 2)])
def test_schedules_deadlock_free(cls, n_stages, n_mu):
    simulate(cls, n_stages, n_mu)


def test_inference_every_stage_forwards_every_mubatch():
    n_stages, n_mu = 4, 2
    progs = [flatten(InferenceSchedule(n_mu, n_stages, s)) for s in range(n_stages)]
    for p in progs:
        assert sum(isinstance(c, Forward) for c in p) == n_mu


def test_pipedream_stash_bound():
    """1F1B's whole point: peak in-flight stashes per stage is bounded by
    pipeline depth remaining, not by n_mu (GPipe's bound)."""
    n_stages, n_mu = 4, 8
    peak_1f1b = simulate(PipeDreamSchedule, n_stages, n_mu)
    peak_gpipe = simulate(GPipeSchedule, n_stages, n_mu)
    for s in range(n_stages):
        expected = min(n_stages - s, n_mu)
        assert peak_1f1b[s] <= expected, (s, peak_1f1b)
        sched = PipeDreamSchedule(n_mu, n_stages, s)
        assert sched.max_stashed_mubatches() == expected
    assert peak_gpipe[0] == n_mu  # GPipe stage 0 holds all microbatches
    assert peak_1f1b[0] == n_stages  # 1F1B holds only pipeline depth
