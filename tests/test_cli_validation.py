"""train_lm.py argument-validation matrix — every rejected combination
must fail fast with a labeled SystemExit in train()'s validation block,
BEFORE any engine is built or parameters are placed on devices.
"""

import pytest

from train_lm import parse_args, train


def expect_exit(argv, match):
    with pytest.raises(SystemExit, match=match):
        train(parse_args(argv))


def test_pp_ep_composes_and_guards_zero_dp():
    # round 4: --ep now composes with --pp too (every model axis does);
    # the remaining guards are the generic ones
    expect_exit(["--pp", "2", "--ep", "2"], "--ep requires --experts")
    expect_exit(["--pp", "2", "--ep", "2", "--experts", "2", "--tp", "2"],
                "ONE extra model axis")
    expect_exit(["--pp", "2", "--ep", "2", "--experts", "2",
                 "--virtual-pp", "2"], "collective-free chunk")
    for z in ("--zero1", "--zero2", "--fsdp"):
        expect_exit(["--pp", "2", z],  # dp=1 has nothing to shard
                    "shards over\\s+dp")
    for z in ("--zero2", "--fsdp"):
        # round 5: --sp now composes with --pp + zero2/fsdp; only the
        # ep exclusion remains (expert grads are ep-sharded)
        expect_exit(["--dp", "2", "--pp", "2", z, "--ep", "2",
                     "--experts", "2"], "no --ep")


def test_pp_sp_guards():
    # one extra model axis only, and sp needs a sequence-parallel substrate
    expect_exit(["--pp", "2", "--sp", "2", "--tp", "2"],
                "ONE extra model axis")
    expect_exit(["--pp", "2", "--sp", "2", "--attn", "flash"],
                "sequence-parallel attention substrate")


def test_ep_requires_experts():
    expect_exit(["--ep", "2"], "--ep requires --experts")


def test_ep_excludes_tp():
    # --ep + --sp is the supported long-context MoE path; only tp conflicts
    expect_exit(["--ep", "2", "--experts", "2", "--tp", "2"],
                "--ep composes with --dp/--sp")


def test_fsdp_excludes_ep_and_zero1():
    expect_exit(["--fsdp", "--zero1"], "--fsdp composes with")
    expect_exit(["--fsdp", "--ep", "2", "--experts", "2"],
                "--fsdp composes with")


def test_attn_guards():
    expect_exit(["--tp", "2", "--attn", "flash"], "not available with")
    expect_exit(["--fsdp", "--attn", "ulysses"], "not available with")
    # --pp takes XLA attention or the fused Pallas kernel; the
    # sequence-resharding substrates stay rejected
    expect_exit(["--pp", "2", "--attn", "ulysses"],
                "not available with --pp")
    expect_exit(["--pp", "2", "--attn", "ulysses-flash"],
                "not available with --pp")


def test_generate_overflow_fails_at_parse_time():
    expect_exit(["--generate", "120", "--seq-len", "128"],
                "exceeds --seq-len")
    # --prompt implies generation (default 128) and counts its own bytes
    expect_exit(["--prompt", "x" * 40, "--seq-len", "128"],
                "40-token prompt exceeds")


def test_sample_only_requires_save_dir():
    expect_exit(["--sample-only", "--seq-len", "512"],
                "require --save-dir")


def test_resume_requires_save_dir():
    expect_exit(["--resume"], "require --save-dir")


# --attn-window now composes with every substrate (flash skips
# out-of-window tiles, ring/ulysses mask by global position) — the old
# rejection tests are gone; composition is covered by
# tests/test_attention.py / test_flash_attention.py window parity.


def test_zb_schedule_guards():
    """--pp-schedule zb (round 5): every carve-out exits labeled, in CLI
    vocabulary, mirroring PipelineLMEngine's pinned asserts."""
    base = ["--pp", "2", "--pp-schedule", "zb"]
    expect_exit(base + ["--tp", "2"], "'dp','pp'")
    expect_exit(base + ["--sp", "2", "--attn", "ring"], "'dp','pp'")
    expect_exit(base + ["--ep", "2", "--experts", "2"], "'dp','pp'")
    expect_exit(base + ["--virtual-pp", "2"], "--virtual-pp 1")
    expect_exit(base + ["--experts", "2"], "dense block family")
    expect_exit(base + ["--dropout", "0.1"], "without dropout")
    expect_exit(base + ["--remat"], "no-recompute")
    # --zero1/--zero2/--fsdp all compose with zb (round 5) — no rows
