"""Checkpoint/resume tests: exact round-trips, cross-engine interchange
(payoff of the canonical flat-layer format), and resume-equals-straight-run.
"""

import numpy as np
import pytest

from shallowspeed_tpu import checkpoint
from shallowspeed_tpu.data.dataset import Dataset
from shallowspeed_tpu.data.mnist import prepare_mnist
from shallowspeed_tpu.engine import FusedDPEngine
from shallowspeed_tpu.models.mlp import MLPStage
from shallowspeed_tpu.optim import SGD, Adam
from shallowspeed_tpu.parallel.mesh import make_mesh
from shallowspeed_tpu.parallel.schedules import GPipeSchedule
from shallowspeed_tpu.parallel.spmd_pipeline import SPMDPipelineEngine
from shallowspeed_tpu.parallel.worker import PipelineExecutor

SIZES = [784, 32, 31, 30, 29, 28, 27, 10]
GBS = 64
N_MU = 4


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("mnist_ckpt")
    prepare_mnist(d, synthetic=True, n_samples=512)
    return d


def make_ds(data_dir, dp=1):
    local = GBS // dp
    return [Dataset(data_dir, GBS, local // N_MU).load(r, dp)
            for r in range(dp)]


def fused_engine(opt=None, dp=1):
    stage = MLPStage(SIZES, 0, 1, batch_size=GBS)
    return FusedDPEngine(stage, opt or SGD(0.5), make_mesh(dp, 1))


def canon_equal(a, b, rtol=0, atol=0):
    la, lb = a.get_canonical_params(), b.get_canonical_params()
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        for k in ("W", "b"):
            if rtol or atol:
                np.testing.assert_allclose(x[k], y[k], rtol=rtol, atol=atol)
            else:
                np.testing.assert_array_equal(x[k], y[k])


def test_pytree_roundtrip(tmp_path):
    tree = {"a": [np.arange(6).reshape(2, 3), np.float32(1.5)],
            "b": {"c": np.ones((4,), np.int32)}}
    checkpoint.save_pytree(tmp_path / "t.npz", tree)
    got = checkpoint.load_pytree(tmp_path / "t.npz")
    np.testing.assert_array_equal(got["a"][0], tree["a"][0])
    np.testing.assert_array_equal(got["b"]["c"], tree["b"]["c"])


def test_fused_roundtrip_exact(tmp_path, data_dir):
    eng = fused_engine(opt=Adam(0.01))
    ds = make_ds(data_dir)
    for b in range(2):
        eng.train_batch(b, ds)
    checkpoint.save(tmp_path, eng, epoch=0)

    eng2 = fused_engine(opt=Adam(0.01))
    next_epoch = checkpoint.restore(eng2, checkpoint.latest(tmp_path))
    assert next_epoch == 1
    canon_equal(eng, eng2)
    # Adam state round-trips bit-exactly -> continued training is identical
    for b in range(2, 4):
        eng.train_batch(b, ds)
        eng2.train_batch(b, ds)
    canon_equal(eng, eng2)


def test_resume_equals_straight_run(tmp_path, data_dir):
    ds = make_ds(data_dir)
    straight = fused_engine()
    for b in range(4):
        straight.train_batch(b, ds)

    first = fused_engine()
    for b in range(2):
        first.train_batch(b, ds)
    checkpoint.save(tmp_path, first, epoch=0)

    second = fused_engine()
    checkpoint.restore(second, checkpoint.latest(tmp_path))
    for b in range(2, 4):
        second.train_batch(b, ds)
    canon_equal(straight, second)


def test_cross_engine_fused_to_spmd(tmp_path, data_dir):
    eng = fused_engine()
    ds = make_ds(data_dir)
    for b in range(2):
        eng.train_batch(b, ds)
    checkpoint.save(tmp_path, eng, epoch=3)

    spmd = SPMDPipelineEngine(SIZES, SGD(0.5), make_mesh(2, 4), N_MU,
                              (GBS // 2) // N_MU, GBS)
    assert checkpoint.restore(spmd, checkpoint.latest(tmp_path)) == 4
    canon_equal(eng, spmd)
    x = ds[0].load_micro_batch_input(0, 0)
    np.testing.assert_allclose(np.asarray(spmd.infer(x)),
                               np.asarray(eng.infer(x)),
                               rtol=3e-4, atol=1e-6)


def test_cross_engine_spmd_to_vm(tmp_path, data_dir):
    spmd = SPMDPipelineEngine(SIZES, SGD(0.5), make_mesh(1, 4), N_MU,
                              GBS // N_MU, GBS)
    ds = make_ds(data_dir)
    for b in range(2):
        spmd.train_batch(b, ds)
    checkpoint.save(tmp_path, spmd, epoch=0)

    stages = [MLPStage(SIZES, s, 4, batch_size=GBS) for s in range(4)]
    vm = PipelineExecutor(make_mesh(1, 4), stages, SGD(0.5))
    checkpoint.restore(vm, checkpoint.latest(tmp_path))
    canon_equal(spmd, vm)


def test_cross_engine_adam_moments_restore_into_spmd(tmp_path, data_dir):
    """Round 2: the canonical optimizer record crosses the MLP family's
    engine boundary too — a fused-DP Adam checkpoint restores its
    moments into the padded stage-stacked SPMD engine EXACTLY (padding
    is zeros-in, zeros-out), and both engines then train identically."""
    eng = fused_engine(opt=Adam(0.01))
    ds = make_ds(data_dir)
    eng.train_batch(0, ds)
    checkpoint.save(tmp_path, eng, epoch=0)
    spmd = SPMDPipelineEngine(SIZES, Adam(0.01), make_mesh(1, 2), N_MU,
                              GBS // N_MU, GBS)
    checkpoint.restore(spmd, checkpoint.latest(tmp_path))  # no warning
    # moments made it across: the restored m tree is nonzero and equals
    # the source's canonical m layer-for-layer
    import jax

    src_m = jax.device_get(eng.opt_state["m"])
    got_m = spmd.canon_export_tree(spmd.opt_state["m"])
    for a, b in zip(src_m, got_m):
        np.testing.assert_allclose(b["W"], np.asarray(a["W"]),
                                   rtol=1e-6, atol=1e-8)
    eng.train_batch(1, ds)
    spmd.train_batch(1, ds)
    # identical moments -> next steps agree up to float reassociation
    # (fused vs pipelined summation order)
    canon_equal(eng, spmd, rtol=2e-4, atol=1e-6)


def test_same_class_different_topology_restores_via_canonical(
        tmp_path, data_dir):
    """Same VM engine class, different pp: per-stage states re-split
    through the canonical record (concat/split by stage layer counts) —
    no re-init, and training continues in lockstep with the source."""
    stages4 = [MLPStage(SIZES, s, 4, batch_size=GBS) for s in range(4)]
    vm4 = PipelineExecutor(make_mesh(1, 4), stages4, Adam(0.01))
    ds = make_ds(data_dir)
    vm4.train_batch(GPipeSchedule, N_MU, 0, ds)
    checkpoint.save(tmp_path, vm4, epoch=0)

    stages2 = [MLPStage(SIZES, s, 2, batch_size=GBS) for s in range(2)]
    vm2 = PipelineExecutor(make_mesh(1, 2), stages2, Adam(0.01))
    checkpoint.restore(vm2, checkpoint.latest(tmp_path))  # no warning
    canon_equal(vm4, vm2)
    vm4.train_batch(GPipeSchedule, N_MU, 1, ds)
    vm2.train_batch(GPipeSchedule, N_MU, 1, ds)
    # identical moments -> next steps agree up to float reassociation
    canon_equal(vm4, vm2, rtol=2e-4, atol=1e-6)


def test_latest_picks_highest_epoch(tmp_path, data_dir):
    eng = fused_engine()
    for e in (0, 2, 10):
        checkpoint.save(tmp_path, eng, epoch=e)
    assert checkpoint.latest(tmp_path).name == "ckpt_10"
    assert checkpoint.latest(tmp_path / "nope") is None


def test_latest_ignores_partial_and_foreign_entries(tmp_path, data_dir):
    """A crash mid-save (simulated: missing opt.npz), a leftover .tmp dir,
    and a stray non-numeric ckpt_* name must not break or win latest()."""
    eng = fused_engine()
    checkpoint.save(tmp_path, eng, epoch=1)
    (tmp_path / "ckpt_99").mkdir()  # partial: no npz files at all
    partial = tmp_path / "ckpt_50"
    partial.mkdir()
    checkpoint.save_pytree(partial / "params.npz", [])  # missing opt.npz
    (tmp_path / "ckpt_7.tmp").mkdir()
    (tmp_path / "ckpt_backup").mkdir()
    assert checkpoint.latest(tmp_path).name == "ckpt_1"


def test_restore_rejects_config_mismatch(tmp_path, data_dir):
    """Restoring a checkpoint from a different model config must raise, not
    silently install wrong weights (same layer COUNT, different widths)."""
    eng = fused_engine()
    checkpoint.save(tmp_path, eng, epoch=0)
    other_sizes = [784, 64, 63, 62, 61, 60, 59, 10]
    other = FusedDPEngine(MLPStage(other_sizes, 0, 1, batch_size=GBS),
                          SGD(0.5), make_mesh(1, 1))
    with pytest.raises(ValueError, match="model config"):
        checkpoint.restore(other, checkpoint.latest(tmp_path))
    spmd = SPMDPipelineEngine(other_sizes, SGD(0.5), make_mesh(1, 4), N_MU,
                              GBS // N_MU, GBS)
    with pytest.raises(ValueError, match="model config"):
        checkpoint.restore(spmd, checkpoint.latest(tmp_path))


def test_save_overwrites_same_epoch(tmp_path, data_dir):
    eng = fused_engine()
    ds = make_ds(data_dir)
    checkpoint.save(tmp_path, eng, epoch=0)
    eng.train_batch(0, ds)
    checkpoint.save(tmp_path, eng, epoch=0)  # rename over existing dir
    eng2 = fused_engine()
    checkpoint.restore(eng2, checkpoint.latest(tmp_path))
    canon_equal(eng, eng2)


def test_no_pickle_in_checkpoint_files(tmp_path, data_dir):
    """The on-disk format must load with allow_pickle=False (no code
    execution on untrusted checkpoints)."""
    eng = fused_engine(opt=Adam(0.01))
    checkpoint.save(tmp_path, eng, epoch=0)
    for f in ("params.npz", "opt.npz"):
        with np.load(tmp_path / "ckpt_0" / f, allow_pickle=False) as z:
            assert "spec" in z.files


def test_prune_keeps_newest(tmp_path):
    """Checkpoint rotation (round 4): save with keep=2 retains only the
    two newest complete checkpoints; .tmp leftovers and foreign names
    are untouched; latest() still points at the newest."""
    from shallowspeed_tpu import checkpoint

    eng = fused_engine()
    (tmp_path / "ckpt_9.tmp").mkdir()          # crash leftover
    (tmp_path / "ckpt_foreign").mkdir()        # not ours
    for epoch in (1, 2, 3, 4):
        checkpoint.save(str(tmp_path), eng, epoch, keep=2)
    names = sorted(p.name for p in tmp_path.iterdir())
    assert "ckpt_3" in names and "ckpt_4" in names
    assert "ckpt_1" not in names and "ckpt_2" not in names
    assert "ckpt_9.tmp" in names and "ckpt_foreign" in names
    assert checkpoint.latest(str(tmp_path)).name == "ckpt_4"
