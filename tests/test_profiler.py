"""Continuous profiling plane (round 17): always-on host sampler,
burn/fault-armed capture windows, host-time attribution.

Acceptance pins:
- burn-triggered capture drill (default tier): a seeded `stall` chaos
  fault under a tight --slo on a real serve.py subprocess produces
  EXACTLY ONE profcap_*.json (the cooldown folds the fault and the
  SLO burn it causes into one window) whose dominant tagged phase
  names the stalled scheduler phase (`data-load`)
  (`test_serving_stall_drill_arms_one_capture`);
- sampler safety: a profiled serving run compiles ZERO new jit
  executables vs the unprofiled warmup (`executable_counts()`
  unchanged — which also pins zero recompiles) and the sampler's
  worst inter-sample gap stays bounded
  (`test_sampler_safety_zero_new_executables`);
- attribution cross-check: on a synthetic run with real tracer
  `step` spans, the sampler's out-of-step sample fraction matches
  the waterfall's `attrib_host_frac` prediction h/(1+h) within 0.10
  absolute (`test_host_frac_cross_check_against_step_spans`);
- snapshots are exact: top-K folded counts + `other` always sum to
  `samples`, through compaction, merge, and the flame-tree reduction.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from shallowspeed_tpu.telemetry import profiler
from shallowspeed_tpu.telemetry.profiler import (CaptureWindow,
                                                 SamplingProfiler,
                                                 device_trace_ctx,
                                                 flame_tree,
                                                 merge_profiles,
                                                 profile_main, tag)
from shallowspeed_tpu.telemetry.schema import validate_file

ROOT = Path(__file__).resolve().parents[1]


# ------------------------------------------------------------- tagging


def test_tag_is_shared_noop_when_no_profiler_runs():
    t = tag("data-load")
    assert t is profiler._NULL_TAG
    with t:
        assert not profiler._TAGS
    # and the engine may nest them unconditionally at zero cost
    assert tag("decode-tick") is t


def test_sample_once_labels_innermost_phase_and_step_membership():
    """Deterministic, clock-free: hooks installed by hand, one
    helper-thread sample per state (the sampler skips its own thread,
    so the main thread must be the samplee)."""
    prof = SamplingProfiler()   # never started: no background samples
    profiler._install_hooks()
    try:
        def one():
            th = threading.Thread(target=prof.sample_once)
            th.start()
            th.join()

        with tag("step"):
            with tag("sampling"):
                one()           # innermost wins; step anywhere counts
        with tag("decode-tick"):
            one()
        one()                   # untagged
    finally:
        profiler._uninstall_hooks()
    assert prof.samples == 3
    assert prof.phases == {"sampling": 1, "decode-tick": 1,
                           profiler.UNTAGGED: 1}
    assert prof.step_samples == 1
    # folded stacks are root->leaf module:function strings
    assert all(";" in k and ":" in k for k in prof.folded)
    # stop() after start(); tag() reverts to the no-op and the
    # cross-thread registry is cleared
    prof2 = SamplingProfiler(hz=200).start()
    assert tag("x") is not profiler._NULL_TAG
    prof2.stop()
    assert tag("x") is profiler._NULL_TAG and profiler._TAGS == {}


def test_tracer_spans_feed_phase_registry_while_profiler_runs():
    from shallowspeed_tpu.telemetry.trace import Tracer

    tr = Tracer(level="steps")
    prof = SamplingProfiler()
    profiler._install_hooks()
    try:
        ident = threading.get_ident()
        with tr.span("step"):
            assert profiler._TAGS[ident] == ["step"]
            with tr.span("grads"):
                assert profiler._TAGS[ident] == ["step", "grads"]
        assert profiler._TAGS[ident] == []
    finally:
        profiler._uninstall_hooks()
    from shallowspeed_tpu.telemetry import trace

    assert trace.PHASE_HOOKS is None
    del prof


# ------------------------------------------------------------ snapshot


def test_snapshot_topk_plus_other_sums_to_samples():
    prof = SamplingProfiler(top_k=2)
    prof.folded.update({"a;b": 5, "a;c": 3, "d": 2})
    prof.phases.update({profiler.UNTAGGED: 10})
    prof.samples = 10
    snap = prof.snapshot()
    assert set(snap["folded"]) == {"a;b", "a;c"}
    assert sum(snap["folded"].values()) + snap["other"] == snap["samples"]
    assert snap["other"] == 2


def test_compaction_keeps_exact_counts_for_survivors():
    prof = SamplingProfiler(top_k=2)
    prof._compact_at = 3
    prof.folded.update({f"s{i}": i + 1 for i in range(8)})  # 36 samples
    prof.samples = 36
    with prof._lock:
        prof._compact_locked()
    assert len(prof.folded) == 3            # back to _compact_at uniques
    assert prof.folded["s7"] == 8           # survivors keep exact counts
    snap = prof.snapshot()
    assert sum(snap["folded"].values()) + snap["other"] == 36


def test_merge_profiles_prefixes_replicas_and_flame_tree_sums():
    snaps = {
        "r0": {"samples": 10, "step_samples": 6, "other": 2,
               "folded": {"m:f;m:g": 5, "m:f;m:h": 3},
               "phases": {"step": 6, profiler.UNTAGGED: 4}},
        "r1": {"samples": 4, "step_samples": 0, "other": 0,
               "folded": {"m:f;m:g": 4},
               "phases": {profiler.UNTAGGED: 4}},
    }
    merged = merge_profiles(snaps)
    assert merged["samples"] == 14 and merged["step_samples"] == 6
    assert merged["folded"]["r0;m:f;m:g"] == 5
    assert merged["folded"]["r1;m:f;m:g"] == 4
    # the exact remainder survives the merge as a per-replica leaf
    assert merged["folded"][f"r0;{profiler.OTHER_KEY}"] == 2
    assert merged["phases"] == {"step": 6, profiler.UNTAGGED: 8}
    assert merged["replicas"] == ["r0", "r1"]

    tree = flame_tree(merged["folded"])
    assert tree["value"] == 14
    top = {c["name"]: c["value"] for c in tree["children"]}
    assert top == {"r0": 10, "r1": 4}       # replica-labelled first level

    def _check(node):
        for c in node.get("children", ()):
            _check(c)
        if node.get("children"):
            assert node["value"] >= max(c["value"]
                                        for c in node["children"])

    _check(tree)


# ----------------------------------------------------- capture windows


def test_capture_window_dedup_cooldown_cap_and_dominant_phase(tmp_path):
    t = [0.0]
    cw = CaptureWindow(out_dir=tmp_path, duration_s=0.1, hz=400,
                       max_captures=3, cooldown_s=30.0,
                       clock=lambda: t[0])
    profiler._install_hooks()   # so tag() is live for the capture
    try:
        with tag("data-load"):
            assert cw.arm("fault:stall", step=6, trigger={"kind": "stall"})
            time.sleep(0.12)    # the window samples the main thread here
        assert not cw.arm("fault:stall", step=6)    # (reason, step) dedup
        assert not cw.arm("slo:tpot_p95_ms", step=7)     # cooldown folds
        t[0] = 31.0
        assert cw.arm("slo:tpot_p95_ms", step=7)
        t[0] = 62.0
        assert cw.arm("anomaly", step=9)
        t[0] = 93.0
        assert not cw.arm("late", step=11)          # max_captures cap
        cw.wait()
    finally:
        profiler._uninstall_hooks()
    caps = sorted(tmp_path.glob("profcap_*.json"))
    assert len(caps) == 3, caps
    pay = json.loads((tmp_path / "profcap_6.json").read_text())
    assert pay["reason"] == "fault:stall" and pay["step"] == 6
    assert pay["samples"] > 0
    assert pay["dominant_phase"] == "data-load"
    assert pay["trigger"] == {"kind": "stall"}
    assert sum(pay["phases"].values()) == pay["samples"]


def test_capture_skips_device_trace_inside_live_xprof_session(tmp_path):
    cw = CaptureWindow(out_dir=tmp_path, duration_s=0.02,
                       device_trace=True)
    profiler._DEVICE_TRACE_DEPTH += 1   # a whole-run --profile-dir trace
    try:
        assert cw.arm("fault:stall", step=1)
        cw.wait()
    finally:
        profiler._DEVICE_TRACE_DEPTH -= 1
    pay = json.loads((tmp_path / "profcap_1.json").read_text())
    assert "device_trace" not in pay    # xprof sessions do not nest
    assert not list(tmp_path.glob("profcap_dev_*"))


def test_device_trace_ctx_falsy_dir_is_noop():
    assert not profiler._device_trace_active()
    with device_trace_ctx(None):
        assert not profiler._device_trace_active()
    with device_trace_ctx(""):
        pass
    assert profiler._DEVICE_TRACE_DEPTH == 0


# ----------------------------------------------------------- reduction


def test_profile_main_reduces_last_event_per_stanza(tmp_path, capsys):
    log = tmp_path / "m.jsonl"
    with open(log, "w") as f:
        f.write(json.dumps({"event": "run_start", "schema_version": 12,
                            "replica": "east", "wall": 1.0}) + "\n")
        f.write(json.dumps({"event": "profile", "samples": 5,
                            "folded": {"a:f": 5}, "other": 0,
                            "phases": {"step": 5}, "wall": 2.0}) + "\n")
        # cumulative: only this LAST snapshot of the stanza counts
        f.write(json.dumps({"event": "profile", "samples": 9,
                            "step_samples": 6,
                            "folded": {"a:f": 7, "a:g": 2}, "other": 0,
                            "phases": {"step": 6, "(untagged)": 3},
                            "wall": 3.0}) + "\n")
        f.write(json.dumps({"event": "run_start", "schema_version": 12,
                            "replica": "west", "wall": 4.0}) + "\n")
        f.write(json.dumps({"event": "profile", "samples": 3,
                            "folded": {"b:h": 2}, "other": 1,
                            "phases": {"(untagged)": 3},
                            "wall": 5.0}) + "\n")
    assert validate_file(log) == []
    out = tmp_path / "flame.json"
    assert profile_main([log], out=out) == 0
    tree = json.loads(out.read_text())
    assert tree["value"] == 12              # 9 + 3, not 5 + 9 + 3
    assert {c["name"] for c in tree["children"]} == {"east", "west"}
    printed = capsys.readouterr().out
    assert "phase step" in printed and "50.0%" in printed

    empty = tmp_path / "empty.jsonl"
    empty.write_text(json.dumps({"event": "run_start",
                                 "schema_version": 12}) + "\n")
    assert profile_main([empty]) == 1       # lost events fail the smoke


# ----------------------------------------------- fleet + goodput merge


def test_fleet_merges_replica_profiles_and_status_block(tmp_path):
    from shallowspeed_tpu.telemetry.fleet import FleetCollector

    def _replica(path, name, folded, phases):
        n = sum(folded.values())
        with open(path, "w") as f:
            f.write(json.dumps({"event": "run_start",
                                "schema_version": 12, "replica": name,
                                "wall": 100.0}) + "\n")
            f.write(json.dumps({"event": "request", "id": f"{name}-q0",
                                "ttft_ms": 10.0, "tokens_in": 2,
                                "tokens_out": 2, "wall": 101.0}) + "\n")
            f.write(json.dumps({"event": "profile", "samples": n,
                                "step_samples": 0, "folded": folded,
                                "other": 0, "phases": phases,
                                "wall": 102.0}) + "\n")
        assert validate_file(path) == []
        return path

    a = _replica(tmp_path / "a.jsonl", "alpha",
                 {"serve:main;engine:step": 25,
                  "serve:main;engine:_maybe_log": 5},
                 {"decode-tick": 25, "logging": 5})
    b = _replica(tmp_path / "b.jsonl", "beta",
                 {"serve:main;engine:step": 4},
                 {"prefill-chunk": 4})
    fc = FleetCollector(paths=[a, b])
    st = fc.refresh()
    prof = fc.profile_payload()
    assert prof["enabled"] and prof["samples"] == 34
    assert prof["folded"]["alpha;serve:main;engine:step"] == 25
    assert prof["folded"]["beta;serve:main;engine:step"] == 4
    # the fleet status grows a per-replica profiling block naming the
    # top phase and the hottest LEAF frame
    blk = st["profiling"]["replicas"]
    assert blk["alpha"]["top_phase"] == "decode-tick"
    assert blk["alpha"]["top_frame"] == "engine:step"
    assert blk["beta"]["samples"] == 4

    # replicas without profile events -> no block, payload disabled
    c = tmp_path / "c.jsonl"
    c.write_text(json.dumps({"event": "run_start", "schema_version": 12,
                             "replica": "gamma", "wall": 100.0}) + "\n")
    fc2 = FleetCollector(paths=[c])
    st2 = fc2.refresh()
    assert "profiling" not in st2
    assert fc2.profile_payload() == {"enabled": False}


def test_goodput_report_carries_profiling_block(tmp_path):
    from shallowspeed_tpu.telemetry.goodput import (format_report,
                                                    run_goodput)

    log = tmp_path / "m.jsonl"
    with open(log, "w") as f:
        f.write(json.dumps({"event": "run_start", "schema_version": 12,
                            "wall": 1.0}) + "\n")
        f.write(json.dumps({"event": "profile", "samples": 20,
                            "step_samples": 15,
                            "folded": {"train:main;lm:train_step": 15,
                                       "train:main;loader:next": 5},
                            "other": 0,
                            "phases": {"step": 15, "data-load": 5},
                            "wall": 9.0}) + "\n")
    rep = run_goodput(log)
    prof = rep["profiling"]
    assert prof["samples"] == 20 and prof["snapshots"] == 1
    assert prof["phases"] == {"step": 15, "data-load": 5}
    assert prof["top_frames"][0] == {"frame": "lm:train_step",
                                     "samples": 15}
    text = format_report(rep)
    assert "profiling (20 host sample(s), 1 snapshot(s))" in text
    assert "hottest frame: lm:train_step (75%)" in text


# ----------------------------------------------- attribution crosscheck


def test_host_frac_cross_check_against_step_spans():
    """The sampler's own in-step estimate must agree with the
    waterfall: with real tracer `step` spans of ~12 ms separated by
    ~4 ms of host gap, `attrib_host_frac` predicts an out-of-step
    sample fraction of h/(1+h); the tagged sampler must land within
    0.10 absolute (the documented cross-check bound)."""
    from shallowspeed_tpu.telemetry import attribution as attr
    from shallowspeed_tpu.telemetry.report import percentile
    from shallowspeed_tpu.telemetry.trace import Tracer

    tr = Tracer(level="steps")
    prof = SamplingProfiler(hz=250).start()
    try:
        t0 = time.perf_counter()
        for _ in range(40):
            with tr.span("step"):
                time.sleep(0.012)
            time.sleep(0.004)
        window = time.perf_counter() - t0
    finally:
        prof.stop()
    snap = prof.snapshot()
    assert snap["samples"] > 50, snap

    durs = attr.window_step_spans(tr.events)
    assert len(durs) == 40
    # report.py's host-gap attribution, verbatim
    host_gap = max(0.0, window - sum(durs)) / len(durs)
    t_step = percentile(durs, 25)
    h = host_gap / t_step                   # == attrib_host_frac
    predicted = h / (1.0 + h)
    measured = 1.0 - snap["step_samples"] / snap["samples"]
    assert abs(measured - predicted) <= 0.10, (
        f"measured out-of-step {measured:.3f} vs waterfall "
        f"prediction {predicted:.3f} (h={h:.3f}, {snap['samples']} "
        f"samples)")


# -------------------------------------------------------- sampler safety


@pytest.fixture(scope="module")
def tiny_engine():
    import jax

    from shallowspeed_tpu.models import transformer as T
    from shallowspeed_tpu.serving import ServingEngine

    cfg = T.TransformerConfig(vocab=48, d_model=24, n_heads=2,
                              n_layers=2, max_seq=96)
    params = jax.device_put(T.init(cfg, seed=1))
    eng = ServingEngine(params, cfg, n_blocks=48, block_size=8,
                        max_slots=2, prefill_chunk=16)
    return eng, cfg


def _offer(eng, cfg, n=6, seed=0, prefix=""):
    rng = np.random.default_rng(seed)
    for i in range(n):
        eng.submit(rng.integers(0, cfg.vocab, 6 + 2 * i)
                   .astype(np.int32), 4 + i, rid=f"{prefix}q{i}")


def test_sampler_safety_zero_new_executables(tiny_engine):
    """The safety contract: the sampler never touches jax, so the
    profiled run reuses the warmup's executables exactly — zero new
    jit entry points AND zero recompiles (cache sizes unchanged) —
    and the worst inter-sample gap stays bounded."""
    eng, cfg = tiny_engine
    _offer(eng, cfg, seed=3, prefix="warm-")
    eng.run()
    base = eng.executable_counts()
    assert base and sum(base.values()) > 0

    prof = SamplingProfiler(hz=250).start()
    try:
        _offer(eng, cfg, seed=3, prefix="prof-")   # same shapes as warmup
        out = eng.run()
        # the warmed rerun drains in milliseconds — keep the window
        # open a beat so the liveness bound measures real gaps
        time.sleep(0.25)
    finally:
        prof.stop()
    assert sum(1 for rid in out if rid.startswith("prof-")) == 6
    assert eng.executable_counts() == base
    assert prof.samples > 10
    # liveness: the sampler kept its beat through the serving loop
    # (generous bound — a 1-core CI host under GIL contention)
    assert 0.0 < prof.max_gap_ms < 2000.0, prof.max_gap_ms


def test_sampler_safety_train_driver_steps_monotone(tmp_path):
    """Satellite: a profiled `--telemetry spans` training run logs
    MONOTONE step lines with zero recompiles and stable compile
    counters (the sampler is invisible to jax), its profile events
    carry a bounded max sample gap, and the tracer's step spans land
    in the tagged phase buckets."""
    log = tmp_path / "m.jsonl"
    r = subprocess.run(
        [sys.executable, "train_lm.py", "--platform", "cpu",
         "--steps", "12", "--log-every", "2", "--batch-size", "2",
         "--seq-len", "16", "--d-model", "16", "--n-layers", "1",
         "--n-heads", "2", "--vocab", "32", "--prefetch", "0",
         "--telemetry", "spans", "--profile", "host",
         "--profile-hz", "200", "--log-file", str(log)],
        capture_output=True, text=True, cwd=ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert validate_file(log) == []
    recs = [json.loads(l) for l in log.read_text().splitlines()]
    steps = [rec for rec in recs if rec["event"] == "step"]
    assert steps
    nums = [rec["step"] for rec in steps]
    assert nums == sorted(nums) and len(set(nums)) == len(nums)
    assert steps[-1]["recompiles"] == 0
    assert steps[-1]["compiles"] == steps[0]["compiles"]
    profs = [rec for rec in recs if rec["event"] == "profile"]
    assert profs and profs[-1]["samples"] > 0
    # the sampler never wedged the run: worst inter-sample gap stays
    # bounded (generous — a 1-core host paying XLA compile under GIL)
    assert profs[-1]["max_gap_ms"] < 10_000
    assert profs[-1]["phases"].get("step", 0) > 0


# --------------------------------------- acceptance drill (default tier)


def test_serving_stall_drill_arms_one_capture(tmp_path):
    """ISSUE-17 acceptance: a seeded `stall` chaos fault under a
    deliberately-impossible tpot SLO arms EXACTLY ONE capture window
    — the fault fires first, the SLO burn it causes lands inside the
    cooldown — and the profcap names the stalled phase (`data-load`:
    chaos stamps observers before the stall sleep, inside the
    engine's data-load bracket)."""
    reqs = tmp_path / "reqs.jsonl"
    with open(reqs, "w") as f:
        for i in range(4):
            f.write(json.dumps({"id": f"r{i}", "prompt_len": 32,
                                "prompt_seed": i + 1,
                                "max_new": 48}) + "\n")
    log = tmp_path / "metrics.jsonl"
    r = subprocess.run(
        [sys.executable, "serve.py", "--platform", "cpu",
         "--vocab", "64", "--d-model", "32", "--n-heads", "2",
         "--n-layers", "1", "--max-seq", "256",
         "--requests", str(reqs), "--log-file", str(log),
         "--profile", "host", "--profile-hz", "200",
         "--chaos", "stall@6:0.75", "--chaos-seed", "3",
         "--slo", "tpot_p95_ms<0.01",
         "--n-blocks", "64", "--slots", "2"],
        capture_output=True, text=True, cwd=ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr

    caps = sorted(tmp_path.glob("profcap_*.json"))
    assert len(caps) == 1, [c.name for c in caps]
    pay = json.loads(caps[0].read_text())
    assert pay["reason"] == "fault:stall" and pay["step"] == 6
    assert pay["samples"] > 0
    assert pay["dominant_phase"] == "data-load", pay["phases"]

    # the metrics log validates schema v12 and its cumulative profile
    # events are monotone in sample count
    assert validate_file(log) == []
    recs = [json.loads(l) for l in log.read_text().splitlines()]
    profs = [rec["samples"] for rec in recs
             if rec["event"] == "profile"]
    assert profs and profs == sorted(profs) and profs[-1] > 0
    assert sum(1 for rec in recs if rec["event"] == "fault") == 1
    assert sum(1 for rec in recs if rec["event"] == "generate") >= 1
