"""Hand-split block backward (`parallel/zb.py`) vs autodiff.

The ZB engine's correctness reduces to: B+W of the hand split ==
jax.grad of the model family's OWN block forward (`T._block` with the
public attention substrates — the same math every other engine runs).
These tests pin that equivalence per configuration axis (norm, ffn,
rope, GQA, window, attention core) in f32, where the comparison is
near-exact.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import shallowspeed_tpu.models.transformer as T
import shallowspeed_tpu.parallel.zb as ZB
from shallowspeed_tpu.ops.attention import attention
from shallowspeed_tpu.ops.flash_attention import flash_attention
from shallowspeed_tpu.parallel.pipeline_lm import stack_blocks


def _cfg(**kw):
    base = dict(vocab=32, d_model=32, n_heads=4, n_layers=2, max_seq=16)
    return T.TransformerConfig(**{**base, **kw})


def _stack(cfg, seed=0):
    return jax.tree_util.tree_map(
        jnp.asarray, stack_blocks(T.init(cfg, seed))["blocks"])


def _ref_fwd(blocks, x, pos, cfg, attn):
    """The autodiff oracle: the model family's `T._block` scan with the
    PUBLIC substrate entries (custom-vjp flash / plain attention) —
    exactly what the gpipe/1f1b engines execute at tp=1."""
    w = cfg.attn_window
    if attn == "flash":
        def attn_fn(q, k, v):
            return flash_attention(q, k, v, causal=True, window=w)
    else:
        def attn_fn(q, k, v):
            return attention(q, k, v, causal=True, window=w)

    def body(h, blk):
        h2, _aux = T._block(blk, h, cfg, attn_fn=attn_fn, pos=pos)
        return h2, None

    y, _ = jax.lax.scan(body, x, blocks)
    return y


def _split_grads(blocks, x, pos, cfg, attn, dy):
    attn_fwd, attn_bwd = ZB.make_attn_core(attn, cfg.attn_window)
    y, resb, resw = ZB.stack_fwd(blocks, x, pos, cfg, attn_fwd)
    dx, taps, dnorm = ZB.stack_bwd_x(blocks, resb, resw, dy, pos, cfg,
                                     attn_bwd)
    dense = ZB.stack_bwd_w(resw, taps, cfg)
    return y, dx, {**dense, **dnorm}


def _max_diff(a, b):
    return max(float(jnp.max(jnp.abs(x - y)))
               for x, y in zip(jax.tree_util.tree_leaves(a),
                               jax.tree_util.tree_leaves(b)))


CASES = [
    dict(),                                             # layernorm+gelu
    dict(norm="rmsnorm", ffn="swiglu"),
    dict(rope=True),
    dict(norm="rmsnorm", ffn="swiglu", rope=True),
    dict(n_kv_heads=2),                                 # GQA
    dict(attn_window=8),
]


@pytest.mark.parametrize("kw", CASES)
@pytest.mark.parametrize("attn", ["xla", "flash"])
def test_split_backward_matches_autodiff(kw, attn):
    cfg = _cfg(**kw)
    blocks = _stack(cfg)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((2, 16, cfg.d_model)),
                    jnp.float32)
    dy = jnp.asarray(rng.standard_normal(x.shape), jnp.float32)
    pos = jnp.arange(16)

    # forward parity: the split forward == the model family's forward
    y_split, dx, dblk = _split_grads(blocks, x, pos, cfg, attn, dy)
    y_ref = _ref_fwd(blocks, x, pos, cfg, attn)
    assert float(jnp.max(jnp.abs(y_split - y_ref))) < 1e-5

    # gradient parity vs autodiff of the family forward
    def loss(blocks_, x_):
        return jnp.vdot(_ref_fwd(blocks_, x_, pos, cfg, attn), dy)

    g_ref, dx_ref = jax.grad(loss, argnums=(0, 1))(blocks, x)
    assert float(jnp.max(jnp.abs(dx - dx_ref))) < 1e-4, "dx mismatch"
    assert set(dblk) == set(g_ref), (set(dblk), set(g_ref))
    diff = _max_diff(dblk, g_ref)
    assert diff < 1e-4, f"weight-grad mismatch {diff}"
