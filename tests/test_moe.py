"""MoE + expert parallelism tests.

The reference has no MoE (SURVEY §2: EP absent) — these tests define the
new family's correctness: routing conservation/capacity invariants, the
dense-FFN degenerate case, and the engine-level guarantee shared with
TP/SP (`test_tensor_parallel.py`): expert sharding must be invisible to
the math while the expert weights are actually distributed.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.ops.moe import (expert_capacity, moe_ffn,
                                      topk_capacity_routing)
from shallowspeed_tpu.optim import SGD, Adam
from shallowspeed_tpu.parallel.expert import ExpertParallelEngine

MOE_CFG = T.TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                              max_seq=64, n_experts=4, moe_top_k=2,
                              moe_capacity_factor=2.0)


def ep_mesh(dp, ep):
    devs = np.array(jax.devices()[: dp * ep]).reshape(dp, ep)
    return Mesh(devs, ("dp", "ep"))


def toy_batch(b=4, t=32, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, MOE_CFG.vocab, (b, t)).astype(np.int32)
    return tokens, np.roll(tokens, -1, axis=1).astype(np.int32)


# ------------------------------------------------------------ routing


def test_routing_conservation_with_ample_capacity():
    """With capacity >= seq_len no token is dropped: per-token combine
    weights sum to 1 (top-k gates are renormalized)."""
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(2, 16, 4)), jnp.float32)
    combine, dispatch, aux, _st = topk_capacity_routing(
        logits, capacity=16, top_k=2)
    np.testing.assert_allclose(np.asarray(combine.sum(axis=(2, 3))),
                               np.ones((2, 16)), rtol=1e-5)
    assert bool((np.asarray(dispatch) == (np.asarray(combine) > 0)).all())
    assert np.isfinite(float(aux))


def test_routing_respects_capacity():
    """Each expert slot holds at most one token, and dropped tokens carry
    zero combine weight."""
    rng = np.random.default_rng(1)
    g, s, e, cap = 2, 32, 4, 3
    logits = jnp.asarray(rng.normal(size=(g, s, e)), jnp.float32)
    combine, dispatch, _, _st = topk_capacity_routing(
        logits, capacity=cap, top_k=2)
    # one token per (expert, slot) position
    per_slot = np.asarray(dispatch).sum(axis=1)          # (g, e, cap)
    assert per_slot.max() <= 1
    # per-expert token count <= capacity
    per_expert = np.asarray(dispatch).sum(axis=(1, 3))   # (g, e)
    assert per_expert.max() <= cap
    # combine weight never exceeds 1 per token (some tokens dropped -> < 1)
    tok_mass = np.asarray(combine.sum(axis=(2, 3)))
    assert tok_mass.max() <= 1.0 + 1e-5


def test_top1_routing_sends_full_weight():
    rng = np.random.default_rng(2)
    logits = jnp.asarray(rng.normal(size=(1, 8, 4)), jnp.float32)
    combine, _, _, _st = topk_capacity_routing(logits, capacity=8,
                                          top_k=1)
    np.testing.assert_allclose(np.asarray(combine.sum(axis=(2, 3))),
                               np.ones((1, 8)), rtol=1e-6)


def test_capacity_formula():
    assert expert_capacity(64, 8, 2, 1.0) == 16
    assert expert_capacity(4, 64, 1, 1.0) == 1   # floor at 1 slot


# ------------------------------------------------------------ moe layer


def test_single_expert_equals_dense_ffn():
    """E=1, top-1, ample capacity: the MoE layer must reduce to the plain
    GELU MLP with the same weights (routing sends every token to the one
    expert with gate weight exactly 1)."""
    rng = np.random.default_rng(3)
    d, ff, s = 16, 64, 12
    x = jnp.asarray(rng.normal(size=(2, s, d)), jnp.float32)
    wi = rng.normal(size=(1, d, ff)).astype(np.float32)
    wo = rng.normal(size=(1, ff, d)).astype(np.float32)
    p = {"gate": np.zeros((d, 1), np.float32),
         "wi": wi, "bi": np.zeros((1, ff), np.float32),
         "wo": wo, "bo": np.zeros((1, d), np.float32)}
    y, aux, _z, _st = moe_ffn(p, x, top_k=1, capacity_factor=float(s))
    dense = jax.nn.gelu(x @ wi[0]) @ wo[0]
    np.testing.assert_allclose(np.asarray(y), np.asarray(dense),
                               rtol=1e-4, atol=1e-5)


def test_moe_grads_reach_all_experts():
    """Top-2 routing over random inputs should touch every expert; the
    gradient must flow to every expert's weights (einsum dispatch keeps the
    whole layer differentiable)."""
    cfg = MOE_CFG
    params = T.init(cfg, seed=0)
    tokens, targets = toy_batch()

    g = jax.grad(lambda p: T.loss(p, jnp.asarray(tokens),
                                  jnp.asarray(targets), cfg))(params)
    for blk in g["blocks"]:
        wi_g = np.asarray(blk["moe"]["wi"])
        per_expert = np.abs(wi_g).sum(axis=(1, 2))
        assert (per_expert > 0).all(), per_expert
        assert np.abs(np.asarray(blk["moe"]["gate"])).sum() > 0


# ------------------------------------------------------------ engine


@pytest.mark.parametrize("dp,ep", [(1, 2), (1, 4), (2, 2), (4, 2)])
def test_ep_step_matches_serial(dp, ep):
    serial = ExpertParallelEngine(MOE_CFG, SGD(0.1), ep_mesh(1, 1), seed=3)
    eng = ExpertParallelEngine(MOE_CFG, SGD(0.1), ep_mesh(dp, ep), seed=3)
    for b in range(2):
        tok, tgt = toy_batch(seed=b)
        l0 = serial.train_batch(tok, tgt)
        l1 = eng.train_batch(tok, tgt)
        assert abs(l0 - l1) < 1e-5, (l0, l1)
    for a, b_ in zip(jax.tree_util.tree_leaves(serial.params),
                     jax.tree_util.tree_leaves(eng.params)):
        np.testing.assert_allclose(np.asarray(b_), np.asarray(a),
                                   rtol=1e-4, atol=1e-5)


def test_experts_actually_sharded():
    eng = ExpertParallelEngine(MOE_CFG, SGD(0.1), ep_mesh(1, 4), seed=0)
    e, d, ff = MOE_CFG.n_experts, MOE_CFG.d_model, 4 * MOE_CFG.d_model
    moe = eng.params["blocks"][0]["moe"]
    assert moe["wi"].addressable_shards[0].data.shape == (e // 4, d, ff)
    assert moe["wo"].addressable_shards[0].data.shape == (e // 4, ff, d)
    # router + attention stay replicated
    assert eng.params["blocks"][0]["qkv"]["W"].addressable_shards[0] \
        .data.shape == (d, 3 * d)


def test_moe_training_learns():
    """Loss must decrease on a fixed batch (Adam, a few steps) — the routed
    layer trains end to end, aux loss included."""
    eng = ExpertParallelEngine(MOE_CFG, Adam(1e-2), ep_mesh(2, 4), seed=0)
    tok, tgt = toy_batch(seed=7)
    losses = [eng.train_batch(tok, tgt) for _ in range(8)]
    assert losses[-1] < losses[0] * 0.7, losses
    assert all(np.isfinite(l) for l in losses)


def test_moe_with_sequence_sharding():
    """Long-context MoE: a ('dp','sp','ep') mesh must reproduce the
    ('dp','ep') trajectory — sequence sharding is purely the batch
    annotation; GSPMD reshards tokens<->expert buffers either way."""
    ref = ExpertParallelEngine(MOE_CFG, SGD(0.1), ep_mesh(1, 4), seed=0)
    devs = np.array(jax.devices()[:8]).reshape(2, 2, 2)
    eng = ExpertParallelEngine(MOE_CFG, SGD(0.1),
                               Mesh(devs, ("dp", "sp", "ep")), seed=0)
    rng = np.random.default_rng(3)
    for step in range(3):
        tok = rng.integers(0, MOE_CFG.vocab, (4, 16)).astype(np.int32)
        tgt = np.roll(tok, -1, axis=1).astype(np.int32)
        assert eng.train_batch(tok, tgt) == pytest.approx(
            ref.train_batch(tok, tgt), rel=3e-4), step


# ---------------------------------------------------------- routing stats


def test_routing_stats_no_drop_with_ample_capacity():
    rng = np.random.default_rng(4)
    logits = jnp.asarray(rng.normal(size=(2, 16, 4)), jnp.float32)
    _, _, _, st = topk_capacity_routing(logits, capacity=16, top_k=2)
    assert float(st["drop_fraction"]) == pytest.approx(0.0, abs=1e-6)
    np.testing.assert_allclose(float(st["load"].sum()), 1.0, rtol=1e-5)


def test_routing_stats_count_capacity_drops():
    """Uniform logits to ONE expert with capacity 1: per group, s tokens
    route top-1 to the same expert, 1 survives — the drop fraction and
    load vector must say exactly that."""
    g, s, e = 2, 8, 4
    logits = jnp.zeros((g, s, e), jnp.float32).at[..., 0].set(10.0)
    _, _, _, st = topk_capacity_routing(logits, capacity=1, top_k=1)
    assert float(st["drop_fraction"]) == pytest.approx((s - 1) / s)
    np.testing.assert_allclose(np.asarray(st["load"]),
                               [1.0, 0.0, 0.0, 0.0], atol=1e-6)


def test_engine_router_stats_surface():
    """Both MoE-capable engines expose the accounting; a dense config
    returns None (no silent pretend-stats)."""
    from shallowspeed_tpu.parallel.context import ContextParallelEngine

    tok, tgt = toy_batch()
    eng = ExpertParallelEngine(MOE_CFG, SGD(0.1), ep_mesh(2, 2), seed=0)
    rs = eng.router_stats(tok)
    assert set(rs) == {"expert_load", "drop_fraction"}
    assert len(rs["expert_load"]) == MOE_CFG.n_experts
    assert 0.0 <= rs["drop_fraction"] <= 1.0
    assert abs(sum(rs["expert_load"]) - 1.0) < 1e-3

    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2, 1), ("dp", "sp"))
    ctx = ContextParallelEngine(MOE_CFG, SGD(0.1), mesh, seed=0)
    rs2 = ctx.router_stats(tok)
    assert set(rs2) == {"expert_load", "drop_fraction"}
    # same params, same batch -> the two engines must agree on routing
    np.testing.assert_allclose(rs2["expert_load"], rs["expert_load"],
                               atol=2e-3)
    assert rs2["drop_fraction"] == pytest.approx(rs["drop_fraction"],
                                                 abs=2e-3)

    dense_cfg = T.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                    n_layers=1, max_seq=64)
    dense = ContextParallelEngine(dense_cfg, SGD(0.1), mesh, seed=0)
    assert dense.router_stats(tok) is None


# ------------------------------------------------------------ router z-loss


def test_router_z_loss_math():
    from shallowspeed_tpu.ops.moe import router_z_loss

    logits = jnp.asarray([[[1.0, 1.0], [3.0, -1.0]]], jnp.float32)
    z = np.log(np.exp([1.0, 1.0]).sum()), np.log(np.exp([3.0, -1.0]).sum())
    want = np.mean(np.square(z))
    np.testing.assert_allclose(float(router_z_loss(logits)), want,
                               rtol=1e-6)
    # shifting logits up increases the penalty, as intended
    assert float(router_z_loss(logits + 5.0)) > float(router_z_loss(logits))


def test_z_weight_scales_linearly_and_decouples_from_balance():
    from dataclasses import replace

    cfg0 = MOE_CFG
    params = jax.device_put(T.init(cfg0, seed=0))
    tok = np.random.default_rng(0).integers(
        0, cfg0.vocab, (4, 16)).astype(np.int32)
    tgt = np.roll(tok, -1, axis=1).astype(np.int32)

    def loss_at(**kw):
        return float(T.loss(params, tok, tgt, replace(cfg0, **kw)))

    l0 = loss_at()
    l1 = loss_at(moe_z_weight=1e-2)
    l2 = loss_at(moe_z_weight=2e-2)
    assert l1 > l0  # the z penalty is nonnegative and generically > 0
    # the z term is exactly linear in its weight
    np.testing.assert_allclose(l2 - l0, 2 * (l1 - l0), rtol=1e-4)
    # and independent of the balance weight: z-loss-only configs work
    lz_only = loss_at(moe_aux_weight=0.0, moe_z_weight=1e-2)
    lbal_only = loss_at(moe_aux_weight=0.0)
    np.testing.assert_allclose(lz_only - lbal_only, l1 - l0, rtol=1e-4)


# ------------------------------------------------ batch-priority routing


def test_priority_routing_keeps_highest_gates():
    """capacity 1, two tokens fighting for one expert: sequence order
    keeps the EARLIER token; priority keeps the HIGHER-gate one."""
    from shallowspeed_tpu.ops.moe import topk_capacity_routing

    # token 0 weakly prefers expert 0, token 1 strongly prefers expert 0
    logits = jnp.array([[[1.0, 0.9], [5.0, 0.0]]], jnp.float32)
    for priority, kept_token in ((False, 0), (True, 1)):
        combine, dispatch, _aux, stats = topk_capacity_routing(
            logits, capacity=1, top_k=1, priority=priority)
        kept = np.asarray(dispatch[0, :, 0, 0])  # expert 0, slot 0
        assert kept[kept_token] and not kept[1 - kept_token], (
            priority, kept)
        assert float(stats["drop_fraction"]) == pytest.approx(0.5)


def test_priority_routing_preserves_more_gate_mass():
    """Random logits, tight capacity: the kept combine mass under
    priority routing must be >= sequence routing's (it keeps the
    heaviest assignments by construction); drop COUNT is identical."""
    from shallowspeed_tpu.ops.moe import topk_capacity_routing

    rng = np.random.default_rng(3)
    logits = jnp.asarray(rng.normal(size=(2, 64, 8)), jnp.float32)
    out = {}
    for priority in (False, True):
        combine, _d, _a, stats = topk_capacity_routing(
            logits, capacity=8, top_k=2, priority=priority)
        out[priority] = (float(combine.sum()),
                         float(stats["drop_fraction"]))
    assert out[True][1] == pytest.approx(out[False][1])  # same drop count
    assert out[True][0] > out[False][0]  # more gate mass survives


def test_priority_routing_no_capacity_pressure_identical():
    """With capacity >= every expert's demand the two orders keep the
    same assignments — outputs must match exactly."""
    from shallowspeed_tpu.ops.moe import moe_ffn

    rng = np.random.default_rng(5)
    d, e, ff = 16, 4, 32
    p = {"gate": rng.normal(0, 0.1, (d, e)).astype(np.float32),
         "wi": rng.normal(0, 0.1, (e, d, ff)).astype(np.float32),
         "bi": np.zeros((e, ff), np.float32),
         "wo": rng.normal(0, 0.1, (e, ff, d)).astype(np.float32),
         "bo": np.zeros((e, d), np.float32)}
    x = jnp.asarray(rng.normal(size=(2, 16, d)), jnp.float32)
    y_seq, *_ = moe_ffn(p, x, 2, float(e), priority=False)
    y_pri, *_ = moe_ffn(p, x, 2, float(e), priority=True)
    np.testing.assert_allclose(np.asarray(y_pri), np.asarray(y_seq),
                               rtol=1e-5, atol=1e-6)


def test_priority_routing_trains_end_to_end():
    from dataclasses import replace as _replace

    from shallowspeed_tpu.parallel.context import ContextParallelEngine
    from shallowspeed_tpu.optim import Adam

    cfg = _replace(MOE_CFG, moe_routing="priority", moe_capacity_factor=1.0)
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("dp", "sp"))
    eng = ContextParallelEngine(cfg, Adam(5e-3), mesh, seed=0)
    rng = np.random.default_rng(0)
    tok = rng.integers(0, cfg.vocab, (4, 32)).astype(np.int32)
    tgt = np.roll(tok, -1, axis=1).astype(np.int32)
    losses = [eng.train_batch(tok, tgt) for _ in range(10)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
