"""Executable happens-before verification of every schedule
(`parallel/verify.py`) — the upgrade the reference's own test header
wishes for (`/root/reference/tests/test_schedules.py:4-10`).

The simulator executes all stages against FIFO channel semantics, so
these tests PROVE deadlock-freedom, per-microbatch data correctness,
reduction placement, and the 1F1B memory bound for every (stages, n_mu)
in the grid — with zero devices, inherited from schedules-as-data.
"""

import pytest

from shallowspeed_tpu.parallel.schedules import (
    GPipeSchedule,
    InferenceSchedule,
    NaiveParallelSchedule,
    PipeDreamSchedule,
)
from shallowspeed_tpu.parallel.verify import ScheduleError, simulate

GRID = [(stages, n_mu) for stages in (1, 2, 3, 4, 5) for n_mu in (1, 2, 4, 6)]


@pytest.mark.parametrize("stages,n_mu", GRID)
@pytest.mark.parametrize("sched", [NaiveParallelSchedule, GPipeSchedule,
                                   PipeDreamSchedule])
def test_training_schedules_verify(sched, stages, n_mu):
    simulate(sched, n_mu, stages)


@pytest.mark.parametrize("stages,n_mu", GRID)
def test_inference_schedule_verifies(stages, n_mu):
    simulate(InferenceSchedule, n_mu, stages, training=False)


@pytest.mark.parametrize("stages,n_mu", [(2, 4), (4, 4), (4, 8), (5, 3)])
def test_1f1b_stash_bound_measured(stages, n_mu):
    """The PipeDream memory claim, MEASURED: peak in-flight forwards on
    stage s is exactly min(stages - s, n_mu) — bounded by depth, while
    GPipe's peak is n_mu on every stage."""
    r = simulate(PipeDreamSchedule, n_mu, stages)
    for s in range(stages):
        assert r.peak_stash[s] == min(stages - s, n_mu), (s, r.peak_stash)
        sched = PipeDreamSchedule(n_mu, stages, s)
        assert r.peak_stash[s] == sched.max_stashed_mubatches()
    g = simulate(GPipeSchedule, n_mu, stages)
    assert g.peak_stash == [n_mu] * stages


@pytest.mark.parametrize("stages,n_mu", [(2, 4), (4, 4), (4, 8)])
def test_makespan_ranking(stages, n_mu):
    """Quantitative bubble comparison under the unit-cost model: Naive
    (one stage active at a time) is strictly worse than the interleaved
    schedules; 1F1B never loses to GPipe by more than the drain tail."""
    naive = simulate(NaiveParallelSchedule, n_mu, stages).makespan
    gpipe = simulate(GPipeSchedule, n_mu, stages).makespan
    pd = simulate(PipeDreamSchedule, n_mu, stages).makespan
    assert gpipe < naive, (gpipe, naive)
    assert pd < naive, (pd, naive)
    # 1F1B trades a slightly longer unit-cost makespan (late warmups)
    # for its bounded stash; it stays within the drain tail of GPipe
    assert pd <= gpipe + n_mu, (pd, gpipe)


def test_broken_schedule_is_caught():
    """Dropping one send must be detected as a deadlock, not pass."""

    class DroppedSend(GPipeSchedule):
        def steps_FWD_mubatch(self, mubatch_id):
            cmds = super().steps_FWD_mubatch(mubatch_id)
            if mubatch_id == 1 and self.stage_id == 0:
                cmds = [c for c in cmds
                        if type(c).__name__ != "SendActivations"]
            return cmds

    # caught even earlier than deadlock: the NEXT microbatch's forward
    # consumes the wrong activation (tag mismatch)
    with pytest.raises(ScheduleError,
                       match="consumed the activation|deadlock"):
        simulate(DroppedSend, 4, 3)


def test_reordered_sends_are_caught():
    """Swapping two microbatches' forwards breaks tag matching."""

    class Swapped(GPipeSchedule):
        def steps(self):
            steps = list(super().steps())
            if self.stage_id == 0:  # producer only: consumers still
                # expect microbatch order 0, 1, ...
                steps[1], steps[2] = steps[2], steps[1]
            yield from steps

    with pytest.raises(ScheduleError, match="consumed the activation"):
        simulate(Swapped, 4, 2)


def test_premature_optimizer_step_is_caught():
    class EarlyOpt(GPipeSchedule):
        def steps(self):
            from shallowspeed_tpu.parallel.instructions import OptimizerStep

            steps = list(super().steps())
            yield from [steps[0], [OptimizerStep()], *steps[1:]]

    with pytest.raises(ScheduleError, match="OptimizerStep after only"):
        simulate(EarlyOpt, 2, 2)


def test_pebble_graph_renders_all_schedules(tmp_path):
    """The pebble-graph generator (scripts/plot_schedule.py) renders
    every schedule from the simulator's round maps — the diagram is
    derived from the same simulation that proves correctness, so this
    smoke test pins the contract: every (stage, mu) compute lands in
    exactly one round cell, and the SVG writer emits a parseable file."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).parent.parent / "scripts"))
    import plot_schedule as P

    reports = []
    for name, cls, training in P.SCHEDULES:
        rep = P.simulate(cls, 4, 4, training=training)
        txt = P.ascii_graph(name, rep, 4, 4, training)
        assert "F0" in txt and ("B0" in txt) == training
        # every stage row appears
        for s in range(4):
            assert f"stage {s}" in txt
        reports.append((name, rep, training))
    svg = tmp_path / "sched.svg"
    P.svg_graph(reports, 4, 4, svg)
    body = svg.read_text()
    assert body.startswith("<svg") and body.rstrip().endswith("</svg>")
    assert body.count("<rect") > 100  # all four grids drawn


# ------------------------------------------- interleaved 1F1B (virtual)


@pytest.mark.parametrize("n_mu,pp,vpp", [(8, 4, 2), (8, 2, 2), (16, 4, 2),
                                         (8, 4, 4), (4, 4, 2)])
def test_interleaved_beats_plain_1f1b(n_mu, pp, vpp):
    """Virtual stages shrink the bubble: device-level makespan (chunk
    units) must beat plain 1F1B at the same pp with vpp-x-bigger
    stages; the logical depth-pp*vpp pipeline is channel-verified as
    part of the simulation."""
    from shallowspeed_tpu.parallel.verify import simulate_interleaved

    rep = simulate_interleaved(n_mu, pp, vpp)
    assert rep.makespan < rep.plain_makespan, (
        rep.makespan, rep.plain_makespan)
    # logical proof ran (depth pp*vpp, all stages drained)
    assert len(rep.logical.peak_stash) == pp * vpp


def test_interleaved_stash_bounded():
    """Each device's aggregate in-flight stash stays near the logical
    1F1B bound summed over its chunks (never GPipe's O(n_mu) blowup)."""
    from shallowspeed_tpu.parallel.verify import simulate_interleaved

    n_mu, pp, vpp = 16, 4, 2
    rep = simulate_interleaved(n_mu, pp, vpp)
    depth = pp * vpp
    for d in range(pp):
        logical_bound = sum(min(depth - ls, n_mu)
                            for ls in range(d, depth, pp))
        assert rep.peak_stash[d] <= logical_bound, (d, rep.peak_stash)
        assert rep.peak_stash[d] < n_mu * vpp  # not GPipe


# ------------------------- interleaved 1F1B execution tables (round 4)


@pytest.mark.parametrize("n_mu,pp,vpp", [(2, 2, 2), (4, 2, 2), (8, 2, 2),
                                         (4, 4, 2), (8, 4, 2), (8, 2, 4),
                                         (6, 3, 2), (1, 2, 2), (3, 2, 3)])
def test_interleaved_tables_replay_exact(n_mu, pp, vpp):
    """The static per-round tables the COMPILED vpp x 1f1b engine
    follows (verify.interleaved_tables) are replayed here against pure
    channel semantics: every F consumes exactly its predecessor logical
    stage's activation for ITS microbatch, every B consumes its own
    stashed input and the successor's cotangent, slot coloring never
    clobbers a live value, and the round count equals the verified
    greedy makespan. This is the bridge from `simulate_interleaved`'s
    proof to what the engine executes."""
    from shallowspeed_tpu.parallel.verify import (interleaved_tables,
                                                  simulate_interleaved)

    tb = interleaved_tables(n_mu, pp, vpp)
    depth = pp * vpp
    act = [[None] * (tb.n_act_slots + 1) for _ in range(pp)]
    grad = [[None] * (tb.n_grad_slots + 1) for _ in range(pp)]
    stash = [[None] * (tb.n_stash_slots + 1) for _ in range(pp)]
    f_seen, b_seen = set(), set()
    for r in range(tb.n_rounds):
        out_act = [None] * pp
        out_grad = [None] * pp
        for d in range(pp):
            op, v, m = tb.op[r, d], tb.chunk[r, d], tb.mu[r, d]
            l = v * pp + d
            if op == 1:
                x = ("emb", m) if l == 0 else act[d][tb.act_read[r, d]]
                if l > 0:
                    assert x == ("act", l - 1, m), (r, d, l, m, x)
                stash[d][tb.stash_write[r, d]] = ("stash", l, m)
                f_seen.add((l, m))
                if l < depth - 1:
                    out_act[d] = ("act", l, m)
            elif op == 2:
                st = stash[d][tb.stash_read[r, d]]
                assert st == ("stash", l, m), (r, d, l, m, st)
                if l < depth - 1:
                    g = grad[d][tb.grad_read[r, d]]
                    assert g == ("grad", l + 1, m), (r, d, l, m, g)
                b_seen.add((l, m))
                stash[d][tb.stash_read[r, d]] = None
                if l > 0:
                    out_grad[d] = ("grad", l, m)
        for d in range(pp):
            if tb.act_write[r, d] != tb.n_act_slots:
                a = out_act[(d - 1) % pp]
                assert a is not None, (r, d)
                act[d][tb.act_write[r, d]] = a
            if tb.grad_write[r, d] != tb.n_grad_slots:
                g = out_grad[(d + 1) % pp]
                assert g is not None, (r, d)
                grad[d][tb.grad_write[r, d]] = g
    full = {(l, m) for l in range(depth) for m in range(n_mu)}
    assert f_seen == full and b_seen == full
    assert tb.n_rounds == simulate_interleaved(n_mu, pp, vpp).makespan


# ----------------------------------------- zero-bubble ZB-H1 (round 4)


@pytest.mark.parametrize("n_mu,pp", [(4, 2), (8, 2), (8, 4), (16, 4),
                                     (12, 3), (16, 8)])
def test_zb_h1_beats_1f1b_at_equal_cost(n_mu, pp):
    """Zero-bubble H1 at the schedule level: splitting the backward
    into B (critical-path cotangent) + W (deferrable weight grads) and
    filling bubbles with W beats 1F1B cost-for-cost (F=1, B+W=2 =
    1F1B's fused backward), with the W placement bounded so the stash
    stays near 1F1B's level."""
    from shallowspeed_tpu.parallel.verify import simulate_zb

    r = simulate_zb(n_mu, pp)
    assert r.makespan < r.f1b1_makespan, (r.makespan, r.f1b1_makespan)
    assert r.bubble < r.f1b1_bubble
    # memory contract: bounded W placement keeps the peak stash within
    # ~2x the 1F1B bound (act stash + pending-W cotangent stash)
    assert max(r.peak_stash) <= 2 * min(pp, n_mu), r.peak_stash


def test_zb_h1_compile_decision_flipped():
    """Round 4 pinned the compile decision NEGATIVE: a JAX-expressible
    dw-only vjp re-runs the forward (F=1, B=2, W=2 vs 1F1B's fused 3),
    which loses at practical microbatch counts — and named its flip
    condition: a hand-written per-block dW path with no recompute in
    either half. Round 5 built exactly that (`parallel/zb.py`: B walks
    stashed residuals, W is batched outer products), so the decision
    FLIPS and this test pins both sides:

    1. the recompute-cost form still loses (the round-4 experiment
       stays executable — if JAX someday makes dw-only vjp free, this
       half fails and the hand-split can be retired);
    2. the hand-split's F=1, B=1, W=1 form wins and is what the engine
       compiles (`PipelineLMEngine(schedule="zb")` executes
       `zb_tables`' lowering of this exact simulation —
       tests/test_pipeline_zb.py holds the replay + parity oracles)."""
    import inspect

    import shallowspeed_tpu.parallel.verify as V

    code = inspect.getsource(V.simulate_zb).replace(
        'cost = {"F": 1, "B": 3, "W": 0}', "__nope__").replace(
        'cost = {"F": 1, "B": 2, "W": 0}\n        if split_bw:\n'
        '            cost = {"F": 1, "B": 1, "W": 1}',
        'cost = {"F": 1, "B": 3, "W": 0}\n        if split_bw:\n'
        '            cost = {"F": 1, "B": 2, "W": 2}')
    assert '"B": 2, "W": 2' in code, (
        "source patch did not apply — simulate_zb's cost block moved; "
        "update this test's replace targets")
    ns = {}
    exec(compile(code, "<zb-jax>", "exec"), vars(V), ns)
    for n_mu, pp in ((16, 4), (32, 8), (8, 2)):
        r = ns["simulate_zb"](n_mu, pp)
        assert r.makespan >= r.f1b1_makespan, (
            "the +1-forward ZB form now WINS at practical sizes — "
            "the hand-split may be retirable", n_mu, pp)
        # the no-recompute split (what parallel/zb.py implements) wins
        # at the same sizes, and its lowering is what compiles
        real = V.simulate_zb(n_mu, pp)
        assert real.makespan < real.f1b1_makespan, (n_mu, pp)
        assert V.zb_tables(n_mu, pp).n_rounds == real.makespan
