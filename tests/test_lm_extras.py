"""Weight tying (`cfg.tie_embeddings`) and label smoothing
(`cfg.label_smoothing`) — LM-completeness options.

Tying removes the "head" entry from the params pytree entirely, so every
engine's structural placement/checkpoint logic follows automatically;
smoothing lives in the ONE token_loss every engine calls.
"""

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.optim import SGD, Adam
from shallowspeed_tpu.parallel.context import ContextParallelEngine
from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine
from shallowspeed_tpu.parallel.tensor import TensorParallelEngine

CFG = T.TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                          max_seq=32)
TIED = replace(CFG, tie_embeddings=True)


def mesh2(dp, m=1, name="sp"):
    devs = np.array(jax.devices()[: dp * m]).reshape(dp, m)
    return Mesh(devs, ("dp", name))


def batch(step, b=8, t=32, vocab=64):
    rng = np.random.default_rng([9, step])
    tok = rng.integers(0, vocab, (b, t)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1).astype(np.int32)


# ------------------------------------------------------------ weight tying


def test_tied_params_have_no_head():
    params = T.init(TIED, seed=0)
    assert "head" not in params
    n_tied = sum(np.prod(l.shape)
                 for l in jax.tree_util.tree_leaves(params))
    n_untied = sum(np.prod(l.shape)
                   for l in jax.tree_util.tree_leaves(T.init(CFG, seed=0)))
    assert n_untied - n_tied == CFG.vocab * CFG.d_model + CFG.vocab


def test_tied_logits_use_embedding():
    params = T.init(TIED, seed=0)
    tok, _ = batch(0, b=2)
    logits = T.forward(params, tok, TIED)
    x = np.asarray(logits)
    assert x.shape == (2, 32, 64)
    # gradient flows into tok_emb from BOTH the input and output sides
    g = jax.grad(lambda p: T.loss(p, tok, np.roll(tok, -1, 1), TIED))(
        params)
    assert np.abs(np.asarray(g["tok_emb"])).sum() > 0


def test_tied_trains_and_generates():
    eng = ContextParallelEngine(TIED, Adam(5e-3), mesh2(2), seed=0)
    losses = [eng.train_batch(*batch(s % 4)) for s in range(30)]
    assert losses[-1] < losses[0] - 0.3, losses[::10]
    from shallowspeed_tpu.models.generate import generate

    out = generate(eng.get_canonical_params(),
                   np.array([[1, 2, 3]], np.int32), TIED, max_new=8,
                   seed=0)
    assert np.asarray(out).shape == (1, 8)


@pytest.mark.parametrize("sched", ["gpipe", "1f1b"])
def test_tied_pipeline_matches_plain_dp(sched):
    cfg = replace(TIED, n_layers=4)
    ref = ContextParallelEngine(cfg, SGD(0.1), mesh2(1), seed=0)
    eng = PipelineLMEngine(
        cfg, SGD(0.1),
        Mesh(np.array(jax.devices()[:4]).reshape(1, 4), ("dp", "pp")),
        n_mubatches=2, seed=0, schedule=sched)
    for s in range(3):
        tok, tgt = batch(s)
        assert eng.train_batch(tok, tgt) == pytest.approx(
            ref.train_batch(tok, tgt), rel=3e-4), (sched, s)


def test_tied_tensor_engine_trains():
    eng = TensorParallelEngine(TIED, Adam(5e-3), mesh2(2, 2, "tp"), seed=0)
    losses = [eng.train_batch(*batch(s % 4)) for s in range(20)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses[::5]


def test_tied_checkpoint_roundtrip(tmp_path):
    from shallowspeed_tpu import checkpoint

    eng = ContextParallelEngine(TIED, Adam(1e-2), mesh2(2), seed=0)
    for s in range(2):
        eng.train_batch(*batch(s))
    checkpoint.save(tmp_path, eng, 2)
    eng2 = ContextParallelEngine(TIED, Adam(1e-2), mesh2(2), seed=1)
    assert checkpoint.restore(eng2, checkpoint.latest(tmp_path)) == 3
    tok, tgt = batch(5)
    np.testing.assert_allclose(eng.train_batch(tok, tgt),
                               eng2.train_batch(tok, tgt), rtol=1e-6)


def test_untied_checkpoint_refuses_tied_engine(tmp_path):
    from shallowspeed_tpu import checkpoint

    eng = ContextParallelEngine(CFG, SGD(0.1), mesh2(1), seed=0)
    checkpoint.save(tmp_path, eng, 1)
    eng2 = ContextParallelEngine(TIED, SGD(0.1), mesh2(1), seed=0)
    with pytest.raises(ValueError, match="does not match"):
        checkpoint.restore(eng2, checkpoint.latest(tmp_path))


# --------------------------------------------------------- label smoothing


def test_smoothing_formula():
    cfg_ls = replace(CFG, label_smoothing=0.2)
    logits = jnp.asarray(np.random.default_rng(0).normal(size=(2, 4, 64)),
                         jnp.float32)
    tgt = np.random.default_rng(1).integers(0, 64, (2, 4)).astype(np.int32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -np.take_along_axis(np.asarray(logp), tgt[..., None], -1)[..., 0]
    uni = -np.asarray(logp).mean(-1)
    want = (0.8 * nll + 0.2 * uni).mean()
    got = float(T.token_loss(logits, jnp.asarray(tgt), cfg_ls))
    np.testing.assert_allclose(got, want, rtol=1e-6)
    # ls=0 is the plain NLL
    np.testing.assert_allclose(float(T.token_loss(logits, jnp.asarray(tgt),
                                                  CFG)),
                               nll.mean(), rtol=1e-6)


def test_smoothing_is_train_only():
    """Eval loss/perplexity must be the plain NLL — comparable across
    runs regardless of --label-smoothing (like dropout, smoothing is a
    training-only regularizer)."""
    cfg_ls = replace(CFG, label_smoothing=0.2)
    plain = ContextParallelEngine(CFG, SGD(0.1), mesh2(1), seed=0)
    smooth = ContextParallelEngine(cfg_ls, SGD(0.1), mesh2(1), seed=0)
    tok, tgt = batch(3)
    assert smooth.eval_loss(tok, tgt) == pytest.approx(
        plain.eval_loss(tok, tgt), rel=1e-6)
    # but the training objective differs
    assert smooth.train_batch(tok, tgt) != pytest.approx(
        plain.train_batch(tok, tgt), rel=1e-6)


def test_smoothing_trains_and_is_shared_by_pipeline():
    """The pipeline engines call the same token_loss: with smoothing on,
    the pipeline trajectory still matches the plain DP engine."""
    cfg = replace(CFG, n_layers=4, label_smoothing=0.1)
    ref = ContextParallelEngine(cfg, SGD(0.1), mesh2(1), seed=0)
    eng = PipelineLMEngine(
        cfg, SGD(0.1),
        Mesh(np.array(jax.devices()[:4]).reshape(1, 4), ("dp", "pp")),
        n_mubatches=2, seed=0, schedule="1f1b")
    for s in range(3):
        tok, tgt = batch(s)
        assert eng.train_batch(tok, tgt) == pytest.approx(
            ref.train_batch(tok, tgt), rel=3e-4), s


# -------------------------------------------------------- logit softcap


def test_softcap_bounds_logits_and_trains():
    cfg = replace(CFG, logit_softcap=5.0)
    params = jax.device_put(T.init(cfg, seed=0))
    tok, tgt = batch(0, b=2)
    logits = T.forward(params, tok, cfg)
    assert float(jnp.abs(logits).max()) < 5.0
    # cap off: identical to the plain head
    plain = T.forward(params, tok, CFG)
    assert not np.allclose(np.asarray(logits), np.asarray(plain))
    eng = ContextParallelEngine(cfg, Adam(5e-3), mesh2(2), seed=0)
    losses = [eng.train_batch(*batch(s % 4)) for s in range(20)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses[::5]


def test_softcap_reaches_decode():
    """Sampling must see the trained (capped) distribution."""
    from shallowspeed_tpu.models.generate import prefill, init_kv_cache

    cfg = replace(CFG, logit_softcap=5.0)
    params = jax.device_put(T.init(cfg, seed=0))
    prompt = np.array([[1, 2, 3, 4]], np.int32)
    logits, _ = prefill(params, prompt, cfg, init_kv_cache(cfg, 1))
    assert float(jnp.abs(logits).max()) < 5.0


def test_lr_end_floor():
    from shallowspeed_tpu.optim import SCHEDULES

    sched = SCHEDULES["cosine"](peak=1.0, warmup=10, total=100, end=0.1)
    assert float(sched(100)) == pytest.approx(0.1, rel=1e-6)
    assert float(sched(10**6)) == pytest.approx(0.1, rel=1e-6)
