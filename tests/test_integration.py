"""End-to-end parallelism-equivalence tests on a virtual 8-device CPU mesh.

The reference verifies distributed correctness at runtime (model-hash sync
assert, `utils.py:27-31`) and via its PyTorch script's weight-divergence
check against serial training (`scripts/DDP_PyTorch_MNIST.py:159-167`). Here
every (engine, schedule, dp, pp) combination is trained in-process and
compared against sequential training directly — strictly stronger, with zero
processes and zero real chips (SURVEY §4 closing note).

Float tolerance note: DP psum and reversed-order GPipe accumulation reorder
float32 sums vs the serial run, so comparisons are tolerance-based
(SURVEY §7 hard part 3), except where the op order is provably identical.
"""

import numpy as np
import pytest

import jax

from shallowspeed_tpu.data.dataset import Dataset
from shallowspeed_tpu.data.mnist import prepare_mnist
from shallowspeed_tpu.engine import FusedDPEngine
from shallowspeed_tpu.models.mlp import MLPStage
from shallowspeed_tpu.optim import SGD
from shallowspeed_tpu.parallel.mesh import make_mesh
from shallowspeed_tpu.parallel.schedules import (
    GPipeSchedule,
    InferenceSchedule,
    NaiveParallelSchedule,
    PipeDreamSchedule,
)
from shallowspeed_tpu.parallel.worker import PipelineExecutor
from shallowspeed_tpu.utils import assert_replicas_in_sync, get_model_hash

SIZES = [784, 32, 31, 30, 29, 28, 27, 10]
GBS = 64
N_MU = 4
LR = 0.5  # MSE-on-softmax gradients are tiny; big steps for fast test signal


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("mnist_it")
    prepare_mnist(d, synthetic=True, n_samples=1024)
    return d


def make_datasets(data_dir, dp, n_mu=N_MU, val=False):
    local = GBS // dp
    mubs = local if val else local // n_mu
    return [Dataset(data_dir, GBS, mubs, validation=val).load(r, dp)
            for r in range(dp)]


def train_fused(data_dir, dp, n_batches=3):
    mesh = make_mesh(dp, 1)
    stage = MLPStage(SIZES, 0, 1, batch_size=GBS)
    eng = FusedDPEngine(stage, SGD(LR), mesh)
    ds = make_datasets(data_dir, dp)
    for b in range(n_batches):
        eng.train_batch(b, ds)
    return eng


def train_vm(data_dir, dp, pp, schedule_cls, n_batches=3):
    mesh = make_mesh(dp, pp)
    stages = [MLPStage(SIZES, s, pp, batch_size=GBS) for s in range(pp)]
    eng = PipelineExecutor(mesh, stages, SGD(LR))
    ds = make_datasets(data_dir, dp)
    for b in range(n_batches):
        eng.train_batch(schedule_cls, N_MU, b, ds)
    return eng


def flat_params(obj):
    leaves = jax.tree_util.tree_leaves(
        obj.params if not isinstance(obj, list) else obj)
    return [np.asarray(l) for l in leaves]


def assert_params_close(a, b, rtol=2e-4, atol=2e-6):
    la, lb = flat_params(a), flat_params(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(x, y, rtol=rtol, atol=atol)


# ------------------------------------------------------------------ tests


def test_fused_sequential_learns(data_dir):
    """Accuracy improves on the synthetic task after a few batches."""
    mesh = make_mesh(1, 1)
    stage = MLPStage(SIZES, 0, 1, batch_size=GBS)
    eng = FusedDPEngine(stage, SGD(LR), mesh)
    ds = make_datasets(data_dir, 1)
    val = make_datasets(data_dir, 1, val=True)

    def acc():
        correct = total = 0
        for b in range(val[0].get_num_batches()):
            x = val[0].load_micro_batch_input(b, 0)
            t = val[0].load_micro_batch_target(b, 0)
            out = np.asarray(eng.infer(x))
            correct += int((out.argmax(-1) == t.argmax(-1)).sum())
            total += len(out)
        return correct / total

    before = acc()
    for epoch in range(12):
        for b in range(ds[0].get_num_batches()):
            eng.train_batch(b, ds)
    after = acc()
    assert after > before + 0.1, (before, after)
    # converged accuracy on this synthetic task lands just above or just
    # below 0.5 depending on XLA CPU fp-reassociation (the test suite
    # forces --xla_cpu_multi_thread_eigen=false, which lands at ~0.43;
    # threaded eigen lands ~0.52) — the LEARNING claim is the +0.1
    # improvement above; the absolute bar just guards against collapse
    assert after > 0.35, (before, after)


def test_fused_epoch_matches_batch_sequence(data_dir):
    """train_epoch (scan over batches, one dispatch) must equal the same
    batches trained via train_batch — the default `python train.py` path."""
    a = train_fused(data_dir, dp=2, n_batches=4)
    mesh = make_mesh(2, 1)
    stage = MLPStage(SIZES, 0, 1, batch_size=GBS)
    b = FusedDPEngine(stage, SGD(LR), mesh)
    ds = make_datasets(data_dir, 2)
    b.train_epoch(b.stage_epoch(ds, 4))
    for la, lb in zip(flat_params(a), flat_params(b)):
        np.testing.assert_allclose(la, lb, rtol=1e-6, atol=1e-7)


def test_fused_run_matches_epoch_loop(data_dir):
    """train_run (one dispatch for N epochs) must equal N train_epoch
    dispatches over the same staged data."""
    mesh = make_mesh(2, 1)
    a = FusedDPEngine(MLPStage(SIZES, 0, 1, batch_size=GBS), SGD(LR), mesh)
    ds = make_datasets(data_dir, 2)
    staged = a.stage_epoch(ds, 4)
    for _ in range(3):
        a.train_epoch(staged)

    b = FusedDPEngine(MLPStage(SIZES, 0, 1, batch_size=GBS),
                      SGD(LR), make_mesh(2, 1))
    b.train_run(b.stage_epoch(ds, 4), 3)
    for la, lb in zip(flat_params(a), flat_params(b)):
        np.testing.assert_allclose(la, lb, rtol=1e-6, atol=1e-7)


def test_vm_pp1_matches_fused(data_dir):
    fused = train_fused(data_dir, dp=1)
    vm = train_vm(data_dir, dp=1, pp=1, schedule_cls=NaiveParallelSchedule)
    assert_params_close(fused, vm)


def test_dp2_matches_serial(data_dir):
    serial = train_fused(data_dir, dp=1)
    dp2 = train_fused(data_dir, dp=2)
    assert_params_close(serial, dp2)
    assert_replicas_in_sync(dp2.params)


def test_dp4_vm_matches_serial(data_dir):
    serial = train_fused(data_dir, dp=1)
    dp4 = train_vm(data_dir, dp=4, pp=1, schedule_cls=GPipeSchedule)
    assert_params_close(serial, dp4)
    assert_replicas_in_sync(dp4.params)


@pytest.mark.parametrize("schedule_cls", [
    NaiveParallelSchedule, GPipeSchedule, PipeDreamSchedule])
def test_pp4_matches_serial(data_dir, schedule_cls):
    serial = train_fused(data_dir, dp=1)
    pp4 = train_vm(data_dir, dp=1, pp=4, schedule_cls=schedule_cls)
    assert_params_close(serial, pp4)


def test_dp2_pp2_2d_matches_serial(data_dir):
    serial = train_fused(data_dir, dp=1)
    grid = train_vm(data_dir, dp=2, pp=2, schedule_cls=GPipeSchedule)
    assert_params_close(serial, grid)
    assert_replicas_in_sync(grid.params)


def test_dp2_pp4_full_mesh(data_dir):
    """Uses all 8 virtual devices: 2-D DP x PP with 1F1B."""
    serial = train_fused(data_dir, dp=1)
    grid = train_vm(data_dir, dp=2, pp=4, schedule_cls=PipeDreamSchedule)
    assert_params_close(serial, grid)
    assert_replicas_in_sync(grid.params)


def test_vm_inference_matches_fused_infer(data_dir):
    fused = train_fused(data_dir, dp=1, n_batches=2)
    vm = train_vm(data_dir, dp=1, pp=4, schedule_cls=GPipeSchedule, n_batches=2)
    val = make_datasets(data_dir, 1, val=True)
    x = val[0].load_micro_batch_input(0, 0)
    out_f = np.asarray(fused.infer(x))
    out_vm = np.asarray(vm.infer_batch(InferenceSchedule, 1, 0, val))
    np.testing.assert_allclose(out_f, out_vm, rtol=2e-4, atol=1e-6)


def test_vm_inference_multiple_mubatches(data_dir):
    """infer_batch must return ALL microbatches' outputs, not just the last
    (regression: outputs were overwritten in buffer 0)."""
    vm = train_vm(data_dir, dp=1, pp=2, schedule_cls=GPipeSchedule, n_batches=1)
    ds = make_datasets(data_dir, 1)  # n_mu microbatches per batch
    out = np.asarray(vm.infer_batch(InferenceSchedule, N_MU, 0, ds))
    assert out.shape == (GBS, 10)
    # rows must match per-microbatch single inference
    x0 = ds[0].load_micro_batch_input(0, 0)
    val_like = [Dataset(data_dir, GBS // N_MU, GBS // N_MU).load(0, 1)]
    np.testing.assert_allclose(
        out[: GBS // N_MU],
        np.asarray(vm.infer_batch(InferenceSchedule, 1, 0, val_like)),
        rtol=1e-5, atol=1e-6)


def test_model_hash_stable(data_dir):
    a = train_fused(data_dir, dp=1, n_batches=1)
    b = train_fused(data_dir, dp=1, n_batches=1)
    assert get_model_hash(a.params) == get_model_hash(b.params)
