"""Live telemetry plane (round 12): streaming sketches, /status.json +
/metrics endpoints, SLO burn-rate alerts, flight recorder.

Acceptance pins:
- live-vs-offline parity: sketch quantiles served from /status.json
  during a scripted serving run match the post-hoc --goodput p50/p95
  ttft/tpot within the sketch's documented relative-error bound
  (`test_serving_live_status_matches_offline_goodput` — the default-
  tier canary; the subprocess end-to-ends ride the slow tier);
- a seeded chaos NaN-poison run leaves a flightrec_*.json whose last
  ring entry is the poisoned step
  (`test_chaos_nan_poison_leaves_flightrec` — slow tier, like PR 6's
  full chaos suite; `test_monitor_fault_line_triggers_flight_dump`
  pins the same ring/dump logic in-process in the default tier);
- `report.percentile` is round-half-up nearest-rank (banker's-rounding
  regression fixture) and is the ONE quantile definition step-time and
  request-latency reductions share.
"""

from __future__ import annotations

import json
import os
import random
import signal
import subprocess
import sys
import time
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from shallowspeed_tpu.telemetry.monitor import (FileTailer, FlightRecorder,
                                                Monitor, SloRule,
                                                StatusServer, live_main,
                                                parse_slos)
from shallowspeed_tpu.telemetry.report import percentile
from shallowspeed_tpu.telemetry.sketch import LogHistogram, MetricSketches

ROOT = Path(__file__).resolve().parents[1]


# ------------------------------------------------------------- sketch


def test_sketch_quantiles_within_documented_rel_err():
    rng = random.Random(7)
    vals = [rng.lognormvariate(2.0, 1.5) for _ in range(4000)]
    sk = LogHistogram(rel_err=0.01)
    for v in vals:
        sk.add(v)
    assert sk.n == len(vals)
    for q in (1, 25, 50, 90, 95, 99):
        exact = percentile(vals, q)
        est = sk.quantile(q)
        assert abs(est - exact) <= 0.01 * exact + 1e-12, (q, est, exact)
    assert abs(sk.mean() - sum(vals) / len(vals)) < 1e-9
    assert sk.vmin == min(vals) and sk.vmax == max(vals)


def test_sketch_merge_equals_union_and_roundtrips():
    rng = random.Random(3)
    vals = [rng.expovariate(0.1) for _ in range(1000)]
    whole = LogHistogram(0.02)
    a, b = LogHistogram(0.02), LogHistogram(0.02)
    for i, v in enumerate(vals):
        whole.add(v)
        (a if i % 2 else b).add(v)
    a.merge(b)
    for q in (50, 95, 99):
        assert a.quantile(q) == whole.quantile(q)
    # JSON round-trip (the schema-v7 "monitor" payload)
    back = LogHistogram.from_dict(json.loads(json.dumps(a.to_dict())))
    assert back.n == whole.n
    assert back.quantile(95) == whole.quantile(95)
    with pytest.raises(ValueError):
        a.merge(LogHistogram(0.01))


def test_sketch_zero_negative_and_empty():
    sk = LogHistogram(0.01)
    assert sk.quantile(50) is None
    sk.add(0.0, count=3)
    sk.add(-2.0)
    sk.add(10.0)
    assert sk.n == 5
    assert sk.quantile(50) <= 0.0      # rank 2 is in the zero bucket
    assert sk.quantile(99) <= 10.0 * 1.01
    sk.add(float("nan"))               # ignored, not poisoned
    assert sk.n == 5


def test_sketch_merge_with_empty_preserves_quantiles():
    """Satellite: merging an empty sketch (either direction) is the
    identity — quantile parity preserved."""
    full = LogHistogram(0.01)
    vals = [1.5, 7.0, 42.0, 42.0, 999.0]
    for v in vals:
        full.add(v)
    before = [full.quantile(q) for q in (50, 95, 99)]
    full.merge(LogHistogram(0.01))            # empty right operand
    assert [full.quantile(q) for q in (50, 95, 99)] == before
    assert full.n == len(vals)
    empty = LogHistogram(0.01)
    empty.merge(full)                         # empty left operand
    assert [empty.quantile(q) for q in (50, 95, 99)] == before
    assert empty.vmin == full.vmin and empty.vmax == full.vmax
    assert empty.total == full.total


def test_sketch_zero_negative_merge_and_counts():
    """Satellite: zero/negative observations live in the shared zero
    bucket and merge exactly; count_above answers at bucket
    resolution."""
    a, b = LogHistogram(0.01), LogHistogram(0.01)
    a.add(0.0, count=2)
    a.add(-5.0)
    a.add(100.0)
    b.add(-1.0)
    b.add(200.0, count=3)
    a.merge(b)
    assert a.n == 8 and a.n_zero == 4
    assert a.quantile(25) <= 0.0              # ranks 0..3 are <= 0
    assert a.quantile(99) <= 200.0 * 1.01
    assert a.vmin == -5.0 and a.vmax == 200.0
    assert a.count_above(150.0) == 3
    assert a.count_above(50.0) == 4
    assert a.count_above(-10.0) == 8          # zero bucket included


def test_sketch_from_dict_with_unseen_buckets_keeps_parity():
    """Satellite: from_dict round-trip carrying buckets the receiver
    never observed (a replica whose value range is disjoint) merges
    with full quantile parity against the pooled stream."""
    lo, hi, whole = (LogHistogram(0.01), LogHistogram(0.01),
                     LogHistogram(0.01))
    lo_vals = [0.001 * (i + 1) for i in range(50)]      # tiny values
    hi_vals = [1e6 + 1e4 * i for i in range(50)]        # huge values
    for v in lo_vals:
        lo.add(v)
        whole.add(v)
    for v in hi_vals:
        hi.add(v)
        whole.add(v)
    # serialize hi and fold into lo: every hi bucket index is unseen
    back = LogHistogram.from_dict(json.loads(json.dumps(hi.to_dict())))
    assert not set(back.buckets) & set(lo.buckets)
    lo.merge(back)
    assert lo.n == whole.n
    for q in (10, 50, 90, 99):
        assert lo.quantile(q) == whole.quantile(q)


def test_metric_sketches_merge_dict():
    a, b = MetricSketches(0.01), MetricSketches(0.01)
    for i in range(50):
        a.observe("ttft_ms", 10 + i)
        b.observe("ttft_ms", 200 + i)
        b.observe("tok_s", 5 * i + 1)
    a.merge_dict(b.to_dict())
    assert a.sketches["ttft_ms"].n == 100
    assert "tok_s" in a.sketches
    assert a.quantile("ttft_ms", 95) > 200


# --------------------------------------------- percentile (satellite)


def test_percentile_round_half_up_not_bankers():
    # rank = 0.5 * 17 = 8.5: round() would give 8 (half-to-even);
    # floor(+0.5) must give 9
    assert percentile(list(range(18)), 50) == 9
    # even-rank p95 fixture: 0.95 * 30 = 28.5 -> banker's 28, ours 29
    assert percentile(list(range(31)), 95) == 29
    assert percentile([], 50) is None
    assert percentile([3.0], 99) == 3.0
    assert percentile([1.0, 2.0], 0) == 1.0
    assert percentile([1.0, 2.0], 100) == 2.0


def test_percentile_is_shared_with_sketch_rank_rule():
    # same nearest-rank rule: on well-separated values the sketch must
    # pick the SAME sample (bucket error <<< gaps)
    vals = [1.0, 10.0, 100.0, 1000.0, 10000.0, 100000.0]
    sk = LogHistogram(0.001)
    for v in vals:
        sk.add(v)
    for q in (0, 10, 50, 75, 95, 100):
        exact = percentile(vals, q)
        assert abs(sk.quantile(q) - exact) <= 0.001 * exact


# ---------------------------------------------------------------- SLO


def test_slo_parsing_good_and_bad():
    rules = parse_slos("ttft_p95_ms<500, availability>0.99,"
                       "step_p99_ms<250,tok_s_p50>100")
    assert [r.sketch for r in rules] == ["ttft_ms", None, "step_ms",
                                         "tok_s"]
    assert rules[0].q == 95 and rules[0].budget == pytest.approx(0.05)
    assert rules[1].budget == pytest.approx(0.01)
    assert parse_slos("") == [] and parse_slos("  ") == []
    for bad in ("ttft_ms<500", "availability<0.99", "availability>2",
                "p95<1", "ttft_p95_ms=500", "nope"):
        with pytest.raises(ValueError):
            parse_slos(bad)


def test_burn_rate_dual_window_blip_vs_sustained():
    rule = SloRule("ttft_p95_ms<100", fast_s=10, slow_s=100,
                   warn_burn=2.0, critical_burn=10.0, min_count=1)
    t = 1000.0
    # 95 good observations over 95s of history
    for i in range(95):
        rule.record(50.0, t + i)
    t += 95
    # a 5-observation bad BLIP: fast window burns hot, the slow
    # window's bad fraction is 5/100 = exactly budget -> burn 1 < 2,
    # so the dual-window rule does NOT page
    for i in range(5):
        rule.record(500.0, t + i)
    t += 5
    assert rule.burn(rule.fast_s, t) >= 10.0
    assert rule.burn(rule.slow_s, t) <= 1.1
    assert rule.evaluate(t) is None and rule.state is None
    # SUSTAINED badness: both windows burn -> critical fire, then a
    # recovery resolves
    for i in range(60):
        rule.record(500.0, t + i)
    t += 60
    alert = rule.evaluate(t)
    assert alert is not None and alert["state"] == "firing"
    assert alert["severity"] == "critical"
    assert rule.evaluate(t) is None        # no re-fire while steady
    for i in range(200):
        rule.record(50.0, t + i)
    t += 200
    resolved = rule.evaluate(t)
    assert resolved is not None and resolved["state"] == "resolved"


def test_availability_slo_burns_on_downtime():
    rule = SloRule("availability>0.9", fast_s=10, slow_s=100,
                   warn_burn=2.0, critical_burn=50.0)
    t = 500.0
    rule.record_down(30.0, t)
    # fast: 30/(10*0.1)=30, slow: 30/(100*0.1)=3 -> warn fires
    alert = rule.evaluate(t)
    assert alert is not None and alert["severity"] == "warn"
    # the downtime ages out of both windows -> resolve
    resolved = rule.evaluate(t + 200)
    assert resolved is not None and resolved["state"] == "resolved"


# ------------------------------------------------------------ monitor


def _mk_monitor(**kw):
    kw.setdefault("slo_kw", dict(fast_s=10, slow_s=60, min_count=3))
    return Monitor(**kw)


def test_monitor_ingests_serving_lines_and_serves_status(tmp_path):
    clock = [2000.0]
    mon = _mk_monitor(slos="ttft_p95_ms<100", flight=16,
                      flight_dir=tmp_path, clock=lambda: clock[0])
    fired = []
    mon.alert_listeners.append(fired.append)
    for i in range(20):
        clock[0] += 1
        mon.note_line({"event": "request", "id": f"r{i}",
                       "ttft_ms": 250.0, "tpot_ms": 3.0,
                       "tokens_in": 4, "tokens_out": 4,
                       "queue_depth": 2, "wall": clock[0]})
    mon.note_line({"event": "generate", "tokens_per_sec": 120.0,
                   "queue_depth": 1, "free_blocks": 7,
                   "active_slots": 3, "wall": clock[0]})
    st = mon.status()
    assert st["sketches"]["ttft_ms"]["count"] == 20
    assert st["sketches"]["tpot_ms"]["p95"] == pytest.approx(3.0,
                                                             rel=0.02)
    assert st["sketches"]["free_blocks"]["count"] == 1
    assert st["serving"]["active_slots"] == 3
    assert st["counters"]["requests"] == 20
    # the sustained 250ms ttft fires the SLO; the trip also dumps the
    # flight ring
    assert fired and fired[0]["state"] == "firing"
    assert st["alerts"] and st["alerts"][0]["slo"] == "ttft_p95_ms<100"
    assert mon.flight.dumps
    dump = json.loads(Path(mon.flight.dumps[0]).read_text())
    assert dump["ring"][-1]["event"] in ("request", "generate")
    prom = mon.prometheus()
    assert "shallowspeed_ttft_ms{quantile=\"0.95\"}" in prom
    assert "shallowspeed_alerts_firing 1" in prom
    assert "shallowspeed_requests_total 20" in prom


def test_monitor_goodput_and_availability_from_ledger_lines():
    mon = _mk_monitor()
    mon.note_line({"event": "run_start", "wall": 100.0})
    mon.note_line({"event": "ledger", "kind": "init", "seconds": 5.0,
                   "wall": 105.0})
    mon.note_line({"event": "ledger", "kind": "restart_downtime",
                   "seconds": 10.0, "wall": 150.0})
    mon.note_line({"event": "step", "step": 5, "loss": 1.0,
                   "tokens_per_sec": 10.0, "wall": 200.0})
    assert mon.goodput_so_far() == pytest.approx(1 - 15.0 / 100.0)
    assert mon.availability() == pytest.approx(1 - 10.0 / 100.0)
    assert mon.counters["restarts"] == 1


def test_monitor_fault_line_triggers_flight_dump(tmp_path):
    mon = _mk_monitor(flight=8, flight_dir=tmp_path)
    mon.note_line({"event": "step", "step": 4, "loss": 1.0,
                   "tokens_per_sec": 5.0, "wall": 10.0})
    mon.note_line({"event": "fault", "kind": "nan", "step": 5,
                   "wall": 11.0})
    assert len(mon.flight.dumps) == 1
    dump = json.loads(Path(mon.flight.dumps[0]).read_text())
    assert dump["reason"] == "fault:nan" and dump["step"] == 5
    assert dump["ring"][-1]["event"] == "fault"
    assert dump["ring"][-1]["step"] == 5
    # same (reason, step) never dumps twice
    mon.note_line({"event": "fault", "kind": "nan", "step": 5,
                   "wall": 12.0})
    assert len(mon.flight.dumps) == 1


def test_flight_recorder_ring_capacity_and_dump_cap(tmp_path):
    fr = FlightRecorder(capacity=4, out_dir=tmp_path, max_dumps=2)
    for i in range(10):
        fr.record({"i": i})
    assert [r["i"] for r in fr.ring] == [6, 7, 8, 9]
    assert fr.dump("a", step=1) and fr.dump("b", step=2)
    assert fr.dump("c", step=3) is None          # max_dumps
    assert len(fr.dumps) == 2


def test_monitor_snapshot_emits_and_merges(tmp_path):
    lines = []
    emit = lambda **kw: lines.append(kw)  # noqa: E731
    a = Monitor(emit=emit, snapshot_every=0)
    b = Monitor(snapshot_every=0)
    for i in range(40):
        a.observe("ttft_ms", 10.0 + i)
        b.observe("ttft_ms", 500.0 + i)
    a.snapshot()
    assert lines and lines[0]["event"] == "monitor"
    assert "ttft_ms" in lines[0]["sketches"]
    b.merge_snapshot(lines[0])
    assert b.sketches.sketches["ttft_ms"].n == 80
    # schema-v7 validation of the emitted line
    from shallowspeed_tpu.telemetry import schema

    rec = {k: v for k, v in lines[0].items()}
    assert schema.validate_line(rec) == []
    assert schema.validate_line({"event": "monitor"}) != []
    assert schema.validate_line(
        {"event": "alert", "slo": "x<1", "state": "firing",
         "burn_fast": 3.0, "severity": "warn"}) == []
    assert schema.validate_line({"event": "alert", "slo": "x<1"}) != []


def test_goodput_monitor_block_tolerates_mixed_rel_err(tmp_path):
    """Snapshots from mixed-precision producers must reduce (largest
    same-rel_err group + a skipped count), not crash the reducer."""
    from shallowspeed_tpu.telemetry.goodput import run_goodput

    def snap(rel, lo):
        sk = MetricSketches(rel_err=rel)
        for i in range(20):
            sk.observe("ttft_ms", lo + i)
        return {"event": "monitor", "sketches": sk.to_dict(),
                "rel_err": rel}

    path = tmp_path / "m.jsonl"
    with open(path, "w") as f:
        for rec in ({"event": "run_start", "wall": 0.0}, snap(0.01, 10),
                    {"event": "run_start", "wall": 5.0}, snap(0.01, 50),
                    {"event": "run_start", "wall": 9.0}, snap(0.02, 90)):
            f.write(json.dumps(rec) + "\n")
    rep = run_goodput(path)
    mon = rep["monitor"]
    assert mon is not None
    assert mon["snapshots"] == 2 and mon["rel_err"] == 0.01
    assert mon["skipped_mixed_rel_err"] == 1
    assert mon["quantiles"]["ttft_ms"]["count"] == 40


def test_metrics_logger_feeds_monitor_without_file():
    from shallowspeed_tpu.metrics import MetricsLogger

    mon = _mk_monitor()
    logger = MetricsLogger(None, monitor=mon)
    logger.log(event="request", id="a", ttft_ms=12.0, tokens_in=1,
               tokens_out=2)
    assert mon.counters["requests"] == 1
    assert mon.sketches.sketches["ttft_ms"].n == 1


def test_steprates_feeds_exact_window_rates():
    from shallowspeed_tpu.metrics import StepRates

    clock = [0.0]
    mon = _mk_monitor(clock=lambda: clock[0])
    rates = StepRates(100.0, clock=lambda: clock[0], monitor=mon)
    clock[0] += 10.0
    rates.pause(5.0, kind="val")    # excluded: 5 steps over 5 busy secs
    rates.log_point(5)
    sk = mon.sketches.sketches["step_ms"]
    assert sk.n == 5                 # weighted by the window's steps
    assert sk.quantile(50) == pytest.approx(1000.0, rel=0.02)
    assert mon.sketches.sketches["tok_s"].n == 1
    assert mon.sketches.quantile("tok_s", 50) == pytest.approx(
        100.0, rel=0.02)


def test_tailer_derives_steps_and_ignores_monitor_events(tmp_path):
    path = tmp_path / "m.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps({"event": "run_start", "wall": 0.0}) + "\n")
        for s in range(0, 8, 2):
            f.write(json.dumps({"event": "step", "step": s,
                                "loss": 1.0, "tokens_per_sec": 50.0,
                                "wall": float(s)}) + "\n")
        # a monitor snapshot in the file must NOT be re-ingested
        f.write(json.dumps({"event": "monitor", "sketches": {
            "ttft_ms": {"rel_err": 0.01, "n": 99, "zero": 0,
                        "buckets": {"1": 99}}}}) + "\n")
    mon = Monitor(derive_steps=True, snapshot_every=0)
    tailer = FileTailer(path, mon)
    tailer.drain()
    assert mon.sketches.sketches["step_ms"].n == 6      # steps 0->6
    assert mon.sketches.quantile("step_ms", 50) == pytest.approx(
        1000.0, rel=0.02)
    assert mon.sketches.sketches["tok_s"].n == 4
    assert "ttft_ms" not in mon.sketches.sketches
    # incremental: appended lines arrive on the next drain
    with open(path, "a") as f:
        f.write(json.dumps({"event": "step", "step": 8, "loss": 1.0,
                            "tokens_per_sec": 50.0,
                            "wall": 8.0}) + "\n")
    tailer.drain()
    assert mon.sketches.sketches["step_ms"].n == 8


def test_status_server_serves_both_endpoints():
    mon = _mk_monitor()
    mon.observe("step_ms", 12.0)
    srv = StatusServer(mon, port=0)
    try:
        st = json.loads(urllib.request.urlopen(
            srv.url("/status.json"), timeout=10).read())
        assert st["sketches"]["step_ms"]["count"] == 1
        prom = urllib.request.urlopen(srv.url("/metrics"),
                                      timeout=10).read().decode()
        assert prom.startswith("# TYPE shallowspeed_up gauge")
        # unknown paths 404 with a JSON body (round 17): scripted
        # pollers get a parseable error naming the path, not the
        # default HTML error page
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(srv.url("/nope"), timeout=10)
        assert exc.value.code == 404
        body = json.loads(exc.value.read())
        assert body["error"] == "not found" and body["path"] == "/nope"
        assert exc.value.headers["Content-Type"].startswith(
            "application/json")
        # /profile.json without a profiling plane: enabled=False, not
        # a 404 — the fleet poller treats it as "profiler off"
        prof = json.loads(urllib.request.urlopen(
            srv.url("/profile.json"), timeout=10).read())
        assert prof == {"enabled": False}
    finally:
        srv.close()


def test_status_server_sketches_endpoint_is_mergeable():
    """/sketches.json serves the SERIALIZED sketches (what a fleet
    poller merges), not just quantile summaries."""
    mon = Monitor(label="west-3", flight=0)
    for i in range(30):
        mon.note_line({"event": "request", "id": f"r{i}",
                       "ttft_ms": 10.0 + i, "tokens_in": 1,
                       "tokens_out": 2, "wall": 100.0 + i})
    srv = StatusServer(mon, port=0)
    try:
        payload = json.loads(urllib.request.urlopen(
            srv.url("/sketches.json"), timeout=10).read())
    finally:
        srv.close()
    assert payload["label"] == "west-3"
    other = MetricSketches(rel_err=payload["rel_err"])
    other.merge_dict(payload["sketches"])
    assert other.sketches["ttft_ms"].n == 30
    # worst-K exemplars ride along: the ids behind the tail quantile
    worst = payload["exemplars"]["ttft_ms"]
    assert worst[0] == {"value": 39.0, "id": "r29"}
    assert len(worst) <= 5


def test_status_server_busy_port_raises_typed_error():
    """Satellite: a busy --monitor-port fails with a typed error
    naming the port, not a bare OSError traceback."""
    from shallowspeed_tpu.telemetry.monitor import PortInUseError

    mon = _mk_monitor()
    srv = StatusServer(mon, port=0)
    try:
        with pytest.raises(PortInUseError, match=str(srv.port)):
            StatusServer(mon, port=srv.port)
        assert issubclass(PortInUseError, OSError)  # callers' except
    finally:
        srv.close()


def test_prometheus_label_values_are_escaped():
    """Satellite: replica names are operator input — quotes,
    backslashes and newlines must not break the exposition parse."""
    from shallowspeed_tpu.telemetry.fleet import FleetCollector
    from shallowspeed_tpu.telemetry.monitor import prom_escape

    assert prom_escape('a"b') == 'a\\"b'
    assert prom_escape("a\\b") == "a\\\\b"
    assert prom_escape("a\nb") == "a\\nb"
    fc = FleetCollector()
    rep = fc.add_file("/nonexistent.jsonl", label='evil"name\nx')
    rep.alive = True
    prom = fc.prometheus()
    assert '{replica="evil\\"name\\nx"} 1' in prom
    # a raw newline in the label would have split the sample line
    assert not any(line.startswith('x"}') for line in prom.splitlines())


def test_tailer_restarts_after_truncation(tmp_path):
    """Satellite: when the tailed file SHRINKS (truncation/rotation),
    the tailer restarts from byte 0 instead of silently reading
    nothing forever."""
    from shallowspeed_tpu.telemetry.monitor import iter_jsonl

    path = tmp_path / "m.jsonl"
    lines = [{"event": "request", "id": f"a{i}", "ttft_ms": 10.0,
              "tokens_in": 1, "tokens_out": 1, "wall": float(i)}
             for i in range(20)]
    path.write_text("".join(json.dumps(r) + "\n" for r in lines))
    mon = Monitor(flight=0, snapshot_every=0)
    tailer = FileTailer(path, mon)
    assert tailer.drain() == 20
    # rotate: the writer replaces the file with a SHORTER one
    rotated = [{"event": "request", "id": f"b{i}", "ttft_ms": 99.0,
                "tokens_in": 1, "tokens_out": 1, "wall": 100.0 + i}
               for i in range(3)]
    path.write_text("".join(json.dumps(r) + "\n" for r in rotated))
    assert tailer.drain() == 3           # NOT zero: restarted at 0
    assert mon.sketches.sketches["ttft_ms"].n == 23
    # and keeps following the rotated file
    with open(path, "a") as f:
        f.write(json.dumps({"event": "request", "id": "b3",
                            "ttft_ms": 99.0, "tokens_in": 1,
                            "tokens_out": 1, "wall": 104.0}) + "\n")
    assert tailer.drain() == 1
    # rotation to an EQUAL-OR-LARGER file (size check can't see it):
    # the inode changed, so the tailer restarts from byte 0
    bigger = tmp_path / "m.jsonl.new"
    bigger.write_text("".join(
        json.dumps({"event": "request", "id": f"c{i}", "ttft_ms": 7.0,
                    "tokens_in": 1, "tokens_out": 1,
                    "wall": 200.0 + i}) + "\n" for i in range(30)))
    import os

    os.replace(bigger, path)
    assert tailer.drain() == 30
    # iter_jsonl unit: pos beyond EOF resets to 0
    recs, pos = iter_jsonl(path, pos=10_000_000)
    assert len(recs) == 30 and pos > 0


def test_tailer_torn_trailing_line_reread_whole(tmp_path):
    """Satellite (round 16): a record torn mid-write — the tail of
    the file ends WITHOUT a newline — must never be ingested as a
    truncated JSON parse (which would silently skip the record); the
    tailer leaves it unconsumed and re-reads it WHOLE once the writer
    completes it."""
    from shallowspeed_tpu.telemetry.monitor import iter_jsonl

    path = tmp_path / "m.jsonl"
    whole = json.dumps({"event": "request", "id": "a0",
                        "ttft_ms": 10.0, "tokens_in": 1,
                        "tokens_out": 1, "wall": 1.0}) + "\n"
    torn = json.dumps({"event": "request", "id": "a1",
                       "ttft_ms": 20.0, "tokens_in": 1,
                       "tokens_out": 1, "wall": 2.0})
    path.write_text(whole + torn[:len(torn) // 2])   # mid-record cut
    mon = Monitor(flight=0, snapshot_every=0)
    tailer = FileTailer(path, mon)
    assert tailer.drain() == 1          # only the complete line
    assert mon.sketches.sketches["ttft_ms"].n == 1
    # repeated polls while the writer is stalled: still nothing —
    # the torn fragment is NOT consumed as a failed parse
    assert tailer.drain() == 0
    # the writer completes the record: it arrives whole, once
    with open(path, "a") as f:
        f.write(torn[len(torn) // 2:] + "\n")
    assert tailer.drain() == 1
    sk = mon.sketches.sketches["ttft_ms"]
    assert sk.n == 2 and sk.vmax == 20.0


def test_tailer_rotation_mid_record(tmp_path):
    """Satellite (round 16): a log ROTATED mid-record — the new file
    (fresh inode) itself ends in a torn line. The inode check restarts
    the tailer at byte 0; the new file's torn tail must behave exactly
    like any torn tail: skipped while incomplete, ingested whole when
    completed — never a truncated-parse record skip."""
    import os

    path = tmp_path / "m.jsonl"
    path.write_text(json.dumps(
        {"event": "request", "id": "old", "ttft_ms": 5.0,
         "tokens_in": 1, "tokens_out": 1, "wall": 1.0}) + "\n")
    mon = Monitor(flight=0, snapshot_every=0)
    tailer = FileTailer(path, mon)
    assert tailer.drain() == 1
    # rotate to a LONGER file whose last record is torn mid-write
    torn = json.dumps({"event": "request", "id": "n2",
                       "ttft_ms": 30.0, "tokens_in": 1,
                       "tokens_out": 1, "wall": 12.0})
    rotated = tmp_path / "m.jsonl.new"
    rotated.write_text("".join(
        json.dumps({"event": "request", "id": f"n{i}", "ttft_ms": 20.0,
                    "tokens_in": 1, "tokens_out": 1,
                    "wall": 10.0 + i}) + "\n" for i in range(2))
        + torn[:10])
    os.replace(rotated, path)
    assert tailer.drain() == 2          # complete lines only
    assert tailer.drain() == 0          # torn tail never mis-parsed
    with open(path, "a") as f:
        f.write(torn[10:] + "\n")
    assert tailer.drain() == 1          # ... re-read whole
    assert mon.sketches.sketches["ttft_ms"].n == 4
    assert mon.sketches.sketches["ttft_ms"].vmax == 30.0


# --------------------------------------- native prometheus histograms


def test_log_histogram_count_le_and_prom_buckets():
    """Satellite (round 16): `count_le` is the cumulative counter
    behind the native histogram export — monotone over the fixed le
    ladder, +Inf == n, and bucket counts SUM across merged sketches
    (the property that makes fleet histogram_quantile correct)."""
    from shallowspeed_tpu.telemetry.monitor import (
        HIST_LE, prom_histogram_lines)
    from shallowspeed_tpu.telemetry.sketch import LogHistogram

    a, b = LogHistogram(), LogHistogram()
    for v in (0.0, 3.0, 40.0, 500.0, 500.0):
        a.add(v)
    for v in (7.0, 7.0, 9000.0):
        b.add(v)
    assert a.count_le(0.0) == 1          # the zero sample
    assert a.count_le(1e9) == a.n
    cums = [a.count_le(le) for le in HIST_LE]
    assert cums == sorted(cums)          # monotone
    # merged bucket counts == sum of parts at EVERY boundary
    parts = {le: a.count_le(le) + b.count_le(le) for le in HIST_LE}
    a.merge(b)
    assert {le: a.count_le(le) for le in HIST_LE} == parts
    lines = prom_histogram_lines("x_ms", a)
    assert lines[0] == "# TYPE x_ms_hist histogram"
    assert f'x_ms_hist_bucket{{le="+Inf"}} {a.n}' in lines
    assert f"x_ms_hist_count {a.n}" in lines
    assert any(line.startswith("x_ms_hist_sum ") for line in lines)
    # labelled form (the fleet export): label spliced before le, one
    # TYPE line suppressible for series after the first
    lab = prom_histogram_lines("x_ms", a, label='replica="r0",',
                               type_line=False)
    assert all(not line.startswith("# TYPE") for line in lab)
    assert any('x_ms_hist_bucket{replica="r0",le="+Inf"}' in line
               for line in lab)


def test_monitor_metrics_exports_native_histogram():
    mon = _mk_monitor()
    for i in range(10):
        mon.note_line({"event": "request", "id": f"r{i}",
                       "ttft_ms": 40.0 + i, "tokens_in": 1,
                       "tokens_out": 1, "wall": float(i)})
    prom = mon.prometheus()
    # summary with quantile labels STILL there...
    assert 'shallowspeed_ttft_ms{quantile="0.95"}' in prom
    # ... and the native histogram alongside
    assert "# TYPE shallowspeed_ttft_ms_hist histogram" in prom
    assert 'shallowspeed_ttft_ms_hist_bucket{le="+Inf"} 10' in prom
    assert 'shallowspeed_ttft_ms_hist_bucket{le="25"} 0' in prom
    assert 'shallowspeed_ttft_ms_hist_bucket{le="50"} ' in prom
    assert "shallowspeed_ttft_ms_hist_count 10" in prom


def test_fleet_metrics_histograms_aggregate_across_replicas(tmp_path):
    """Two replicas' native histograms share the fixed le ladder with
    replica labels, so a Prometheus sum() over them equals the pooled
    distribution — the aggregation summaries cannot provide."""
    from shallowspeed_tpu.telemetry.fleet import FleetCollector

    paths = []
    for name, ttft in (("ra", 40.0), ("rb", 400.0)):
        p = tmp_path / f"{name}.jsonl"
        p.write_text("".join(
            json.dumps({"event": "run_start", "replica": name,
                        "wall": 1.0}) + "\n"
            + json.dumps({"event": "request", "id": f"{name}-{i}",
                          "ttft_ms": ttft, "tokens_in": 1,
                          "tokens_out": 1, "wall": 2.0 + i}) + "\n"
            for i in range(4)))
        paths.append(p)
    fc = FleetCollector(paths=paths)
    fc.refresh()
    prom = fc.prometheus()
    assert prom.count("# TYPE shallowspeed_ttft_ms_hist histogram") \
        == 1
    assert 'shallowspeed_ttft_ms_hist_bucket{replica="ra",' \
           'le="+Inf"} 4' in prom
    assert 'shallowspeed_ttft_ms_hist_bucket{replica="rb",' \
           'le="+Inf"} 4' in prom
    # the per-le sums across replicas ARE the pooled cumulative
    # counts: at le=50 only ra's 4 samples, at le=500 all 8
    assert 'shallowspeed_ttft_ms_hist_bucket{replica="ra",le="50"} 4' \
        in prom
    assert 'shallowspeed_ttft_ms_hist_bucket{replica="rb",le="50"} 0' \
        in prom
    assert 'shallowspeed_ttft_ms_hist_bucket{replica="rb",le="500"} 4' \
        in prom


def test_schema_v8_straggler_and_lifecycle_lines():
    from shallowspeed_tpu.telemetry import schema

    assert schema.validate_line(
        {"event": "straggler", "replica": "r1", "metric": "step_ms",
         "state": "firing", "ratio": 2.4, "z": 7.1, "replica_q": 120.0,
         "fleet_q": 50.0, "q": 50, "rounds": 3}) == []
    assert schema.validate_line(
        {"event": "straggler", "metric": "step_ms",
         "state": "firing"}) != []              # replica required
    assert schema.validate_line(
        {"event": "straggler", "replica": "r1", "metric": "step_ms",
         "state": "firing", "ratio": "fast"}) != []
    assert schema.validate_line(
        {"event": "lifecycle", "id": "r0", "phase": "prefill",
         "seq": 3, "chunk": 1, "tokens": 16, "prev": "admitted",
         "ms_in_prev": 0.52, "tick": 9, "slot": 2}) == []
    assert schema.validate_line(
        {"event": "lifecycle", "phase": "prefill"}) != []
    assert schema.validate_line(
        {"event": "lifecycle", "id": "r0", "phase": "prefill",
         "chunk": 1.5}) != []
    # ph "M" (named trace tracks) is span-dialect-legal
    assert schema.validate_line(
        {"name": "thread_name", "ph": "M", "ts": 0.0,
         "args": {"name": "request r0"}}) == []


def test_live_main_once_renders_committed_artifact(capsys):
    rc = live_main(str(ROOT / "docs_runs" / "serving_r07_metrics.jsonl"),
                   once=True)
    assert rc == 0
    out = capsys.readouterr().out
    assert "ttft_ms" in out and "uptime" in out
    assert live_main("/nonexistent.jsonl", once=True) == 1


# ------------------------------------------- engine load-shed (hook)


def test_engine_on_alert_sheds_and_resumes():
    import jax

    from shallowspeed_tpu.models import transformer as T
    from shallowspeed_tpu.serving import ServingEngine

    cfg = T.TransformerConfig(vocab=32, d_model=16, n_heads=2,
                              n_layers=1, max_seq=64)
    params = jax.device_put(T.init(cfg, seed=0))
    eng = ServingEngine(params, cfg, n_blocks=24, block_size=8,
                        max_slots=2, prefill_chunk=8)
    crit = {"state": "firing", "severity": "critical", "slo": "x<1"}
    warm = {"state": "firing", "severity": "warn", "slo": "x<1"}
    done = {"state": "resolved", "severity": "critical", "slo": "x<1"}
    rng = np.random.default_rng(0)
    eng.submit(rng.integers(0, 32, 6).astype(np.int32), 4, rid="a")
    eng.on_alert(crit)
    assert eng.admission_paused
    # all-slots-empty carve-out: the scheduler stays live even shed
    assert eng.step()
    assert eng.slots[0] is not None
    # with work in flight, new submissions wait in the queue
    eng.submit(rng.integers(0, 32, 6).astype(np.int32), 4, rid="b")
    eng.step()
    assert any(r.rid == "b" for r in eng.queue)
    # de-escalation to warn releases the shed (only a CRITICAL burn
    # keeps admission paused); resolve releases it too
    eng.on_alert(warm)
    assert not eng.admission_paused
    eng.on_alert(crit)
    eng.on_alert(done)
    assert not eng.admission_paused
    # PER-RULE tracking: another SLO's warn/resolve must NOT release
    # a still-critical rule's shed; only ITS resolve does
    crit_b = {"state": "firing", "severity": "critical", "slo": "y<2"}
    done_b = {"state": "resolved", "severity": "critical", "slo": "y<2"}
    eng.on_alert(crit)
    eng.on_alert(crit_b)
    eng.on_alert({"state": "resolved", "severity": "warn", "slo": "z>3"})
    assert eng.admission_paused
    eng.on_alert(done)
    assert eng.admission_paused          # y<2 still burns critical
    eng.on_alert(done_b)
    assert not eng.admission_paused
    eng.run()
    assert set(eng.results) == {"a", "b"}
    assert eng.alloc.n_free == eng.alloc.n_usable


# ------------------------- acceptance: live-vs-offline parity canary


def test_serving_live_status_matches_offline_goodput(tmp_path):
    """The round-12 acceptance pin: /status.json quantiles DURING a
    scripted serving run match the post-hoc --goodput percentiles
    within the sketch's documented rel_err."""
    import jax

    from shallowspeed_tpu.metrics import MetricsLogger
    from shallowspeed_tpu.models import transformer as T
    from shallowspeed_tpu.serving import ServingEngine
    from shallowspeed_tpu.telemetry import schema
    from shallowspeed_tpu.telemetry.goodput import run_goodput

    cfg = T.TransformerConfig(vocab=48, d_model=24, n_heads=2,
                              n_layers=2, max_seq=96)
    params = jax.device_put(T.init(cfg, seed=1))
    path = tmp_path / "serve.jsonl"
    metrics = MetricsLogger(path, kind="serve")
    mon = Monitor(slos="", flight=0, emit=metrics.log,
                  snapshot_every=16)
    metrics.monitor = mon
    srv = StatusServer(mon, port=0)
    try:
        eng = ServingEngine(params, cfg, n_blocks=48, block_size=8,
                            max_slots=3, prefill_chunk=16,
                            metrics=metrics, log_every=4)
        rng = np.random.default_rng(5)
        for i in range(7):
            eng.submit(rng.integers(0, 48, 6 + 3 * i).astype(np.int32),
                       5 + i, temperature=0.7 if i % 2 else 0.0,
                       seed=i, rid=f"r{i}")
        polled = None
        for _ in range(400):
            if not eng.pending():
                break
            eng.step()
            # hit the LIVE endpoint mid-run (lock + thread sanity)
            polled = json.loads(urllib.request.urlopen(
                srv.url("/status.json"), timeout=10).read())
        assert not eng.pending()
        st = json.loads(urllib.request.urlopen(
            srv.url("/status.json"), timeout=10).read())
        assert polled is not None and polled["counters"]["lines"] > 0
    finally:
        srv.close()
        mon.close()

    rep = run_goodput(path)
    off = rep["requests"]
    assert off["n_requests"] == 7
    rel = st["rel_err"]
    for name in ("ttft_ms", "tpot_ms"):
        for q in (50, 95):
            live = st["sketches"][name][f"p{q}"]
            exact = off[f"{name}_p{q}"]
            # + 1e-3: both sides round to ms decimals for the report
            assert abs(live - exact) <= rel * abs(exact) + 1e-3, (
                name, q, live, exact)
    # the reducer's own merged-sketch cross-check agrees
    assert rep["monitor"] is not None
    assert rep["monitor"]["parity"], rep["monitor"]
    assert all(v["within_bound"]
               for v in rep["monitor"]["parity"].values())
    # the file (request + generate + monitor events) validates v7
    assert schema.validate_file(path) == []


# ------------------------------- committed artifacts (satellite gate)


@pytest.mark.parametrize(
    "artifact",
    sorted(p.name for p in (ROOT / "docs_runs").glob("*.jsonl")))
def test_committed_artifact_validates_current_schema(artifact):
    """EVERY committed docs_runs JSONL must validate against the
    current schema — one parametrized gate instead of each PR
    hand-listing its own artifact (v1-v7 dialects all accepted)."""
    from shallowspeed_tpu.telemetry.schema import validate_file

    assert validate_file(ROOT / "docs_runs" / artifact) == []


# --------------------------------------------- subprocess end-to-ends


def _run(cmd, cwd, timeout=240, **kw):
    return subprocess.run([sys.executable, *cmd], cwd=cwd,
                          capture_output=True, text=True,
                          timeout=timeout, **kw)


def _lm_args(tmp_path, steps=12):
    return ["train_lm.py", "--platform", "cpu", "--steps", str(steps),
            "--log-every", "2", "--batch-size", "2", "--seq-len", "16",
            "--d-model", "16", "--n-layers", "1", "--n-heads", "2",
            "--vocab", "32", "--log-file",
            str(tmp_path / "metrics.jsonl")]


def test_chaos_nan_poison_leaves_flightrec(tmp_path):
    """Acceptance: a seeded chaos NaN-poison run leaves a
    flightrec_*.json whose last ring entry is the poisoned step."""
    r = _run(_lm_args(tmp_path) + [
        "--chaos", "nan@6", "--chaos-state", str(tmp_path / "cs"),
        "--flight-recorder", "32", "--health", "monitor"], ROOT)
    # the NaN loss exits through the labeled divergence path
    assert r.returncode != 0
    assert "non-finite" in r.stdout + r.stderr
    recs = [json.loads(l) for l in
            (tmp_path / "metrics.jsonl").read_text().splitlines()]
    assert any(rec.get("event") == "fault" and rec.get("step") == 6
               for rec in recs)
    dumps = sorted(tmp_path.glob("flightrec_*.json"))
    assert dumps, list(tmp_path.iterdir())
    fr = json.loads(dumps[0].read_text())
    assert fr["step"] == 6
    last = fr["ring"][-1]
    assert last["event"] == "fault" and last["step"] == 6


def test_train_lm_monitor_endpoint_live(tmp_path):
    """--monitor-port 0 on the LM driver: the printed URL serves
    /status.json with step sketches while the run is alive, and the
    JSONL carries validating schema-v7 monitor snapshots."""
    proc = subprocess.Popen(
        [sys.executable] + _lm_args(tmp_path, steps=60)
        + ["--monitor-port", "0", "--slo", "step_p95_ms<10000000"],
        cwd=ROOT, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, env={**os.environ, "PYTHONUNBUFFERED": "1"})
    try:
        url = None
        t0 = time.time()
        while time.time() - t0 < 120:
            line = proc.stdout.readline()
            if "monitor: http" in line:
                url = line.split("monitor: ")[1].split(" ")[0]
                break
        assert url, "driver never printed the monitor URL"
        st = None
        while time.time() - t0 < 180 and proc.poll() is None:
            try:
                st = json.loads(urllib.request.urlopen(
                    url, timeout=5).read())
                if st["sketches"].get("step_ms", {}).get("count", 0) > 0:
                    break
            except Exception:
                pass
            time.sleep(0.5)
        assert st is not None and st["sketches"]["step_ms"]["count"] > 0
        assert st["slo"] and st["slo"][0]["state"] == "ok"
    finally:
        proc.terminate()
        proc.wait(timeout=60)
    from shallowspeed_tpu.telemetry.schema import validate_file

    assert validate_file(tmp_path / "metrics.jsonl") == []


def test_serve_sigterm_flushes_summary_and_snapshot(tmp_path):
    """Satellite: serve.py converts SIGTERM to SystemExit like the
    train drivers, so a supervisor kill flushes the request/ledger
    tail and a final summary line."""
    reqs = tmp_path / "reqs.jsonl"
    with open(reqs, "w") as f:
        for i in range(30):
            f.write(json.dumps({"id": f"r{i}", "prompt_len": 12,
                                "max_new": 40,
                                "at": 0.2 * i}) + "\n")
    log = tmp_path / "serve.jsonl"
    proc = subprocess.Popen(
        [sys.executable, "serve.py", "--platform", "cpu", "--vocab",
         "32", "--d-model", "16", "--n-heads", "2", "--n-layers", "1",
         "--max-seq", "128", "--n-blocks", "48", "--block-size", "8",
         "--slots", "2", "--prefill-chunk", "16", "--requests",
         str(reqs), "--log-file", str(log), "--log-every", "2"],
        cwd=ROOT, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    # wait for real work (first generate/request line), then SIGTERM
    t0 = time.time()
    while time.time() - t0 < 180:
        if log.exists() and any(
                json.loads(l).get("event") in ("generate", "request")
                for l in log.read_text().splitlines() if l.strip()):
            break
        time.sleep(0.5)
        assert proc.poll() is None, proc.communicate()
    proc.send_signal(signal.SIGTERM)
    out, err = proc.communicate(timeout=60)
    assert proc.returncode == 143, (proc.returncode, err[-500:])
    lines = [json.loads(l) for l in out.splitlines() if l.strip()]
    summaries = [l for l in lines if l.get("event") == "summary"]
    assert summaries, out[-800:]
    assert summaries[-1]["ticks"] > 0
    # the kill left a coherent, validating metrics file behind
    from shallowspeed_tpu.telemetry.schema import validate_file

    assert validate_file(log) == []


def test_supervisor_monitor_endpoint_aggregates(tmp_path):
    """elastic.py --monitor-port: the supervisor tails the child's
    metrics file and serves aggregated /status.json + /metrics."""
    import threading

    from shallowspeed_tpu.elastic import RestartPolicy, Supervisor

    sup = Supervisor(
        [sys.executable, str(ROOT / "train_lm.py")]
        + _lm_args(tmp_path, steps=40)[1:],
        RestartPolicy(max_restarts=1), monitor_port=0)
    hole = {}
    orig = sup._start_monitor

    def start():
        mon, srv, tailer = orig()
        hole["url"] = srv.url("/metrics")
        hole["status"] = srv.url("/status.json")
        return mon, srv, tailer

    sup._start_monitor = start
    rc = {}
    th = threading.Thread(target=lambda: rc.setdefault("c", sup.run()))
    th.start()
    got = None
    t0 = time.time()
    while time.time() - t0 < 180 and th.is_alive():
        try:
            st = json.loads(urllib.request.urlopen(
                hole["status"], timeout=5).read())
            if st["sketches"].get("step_ms", {}).get("count", 0) > 0:
                got = st
                prom = urllib.request.urlopen(
                    hole["url"], timeout=5).read().decode()
                break
        except Exception:
            pass
        time.sleep(0.5)
    th.join(timeout=180)
    assert rc.get("c") == 0
    assert got is not None, "endpoint never served step sketches"
    assert "shallowspeed_step_ms" in prom
