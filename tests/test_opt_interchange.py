"""Canonical optimizer-state interchange (`checkpoint.py` opt_canon.npz).

Round-1 verdict gap: moments were engine-shaped only, so a cross-engine
resume silently re-initialized Adam state. Now moments travel in the same
canonical per-layer layout params always had:

- a dp Adam checkpoint resumes EXACTLY into a dp x pp pipeline (and
  back) — post-resume losses match the never-interrupted run;
- identity-layout engines interchange Adafactor's factored state too;
- genuinely non-portable pairs (Adafactor through the stacking pipeline)
  still fall back to re-init with a warning, never silent corruption.
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from shallowspeed_tpu import checkpoint
from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.optim import Adafactor, Adam
from shallowspeed_tpu.parallel.context import ContextParallelEngine
from shallowspeed_tpu.parallel.fsdp import FSDPEngine
from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine

CFG = T.TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=4,
                          max_seq=32)


def batch(step, b=8, t=32):
    rng = np.random.default_rng([29, step])
    tok = rng.integers(0, CFG.vocab, (b, t)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1).astype(np.int32)


def ctx_mesh(dp):
    return Mesh(np.array(jax.devices()[:dp]).reshape(dp, 1), ("dp", "sp"))


def pipe_mesh(dp, pp):
    return Mesh(np.array(jax.devices()[: dp * pp]).reshape(dp, pp),
                ("dp", "pp"))


def test_adam_moments_cross_engine_exact(tmp_path):
    """dp=4 Adam -> save -> resume into dp=2 x pp=4: the continued losses
    must match the never-interrupted dp run step for step (the canonical
    moment record makes the resume exact, not approximately warm)."""
    eng = ContextParallelEngine(CFG, Adam(1e-2), ctx_mesh(4), seed=0)
    for s in range(4):
        eng.train_batch(*batch(s))
    checkpoint.save(tmp_path, eng, epoch=3)
    straight = [eng.train_batch(*batch(s)) for s in range(4, 8)]

    pipe = PipelineLMEngine(CFG, Adam(1e-2), pipe_mesh(2, 4),
                            n_mubatches=2, seed=1)
    assert checkpoint.restore(pipe, checkpoint.latest(tmp_path)) == 4
    resumed = [pipe.train_batch(*batch(s)) for s in range(4, 8)]
    np.testing.assert_allclose(resumed, straight, rtol=3e-4)


def test_adam_moments_pipeline_to_context_exact(tmp_path):
    """The reverse direction: the pipeline's stacked moments unstack into
    the canonical record and restore exactly into the context engine."""
    pipe = PipelineLMEngine(CFG, Adam(1e-2), pipe_mesh(1, 4),
                            n_mubatches=2, seed=0)
    for s in range(4):
        pipe.train_batch(*batch(s))
    checkpoint.save(tmp_path, pipe, epoch=3)
    straight = [pipe.train_batch(*batch(s)) for s in range(4, 8)]

    eng = ContextParallelEngine(CFG, Adam(1e-2), ctx_mesh(2), seed=1)
    assert checkpoint.restore(eng, checkpoint.latest(tmp_path)) == 4
    resumed = [eng.train_batch(*batch(s)) for s in range(4, 8)]
    np.testing.assert_allclose(resumed, straight, rtol=3e-4)


def test_adafactor_cross_dp_resume_exact(tmp_path):
    """Adafactor's factored state resumes exactly across a dp-width
    change (the post-hardware-change scenario): same replicated factoring
    on both sides, so the moments install, not re-init."""
    eng = ContextParallelEngine(CFG, Adafactor(3e-2), ctx_mesh(2), seed=0)
    for s in range(3):
        eng.train_batch(*batch(s))
    checkpoint.save(tmp_path, eng, epoch=2)
    straight = [eng.train_batch(*batch(s)) for s in range(3, 6)]

    wide = ContextParallelEngine(CFG, Adafactor(3e-2), ctx_mesh(4), seed=1)
    assert checkpoint.restore(wide, checkpoint.latest(tmp_path)) == 3
    resumed = [wide.train_batch(*batch(s)) for s in range(3, 6)]
    np.testing.assert_allclose(resumed, straight, rtol=3e-4)


def test_adafactor_mismatched_factoring_warns(tmp_path):
    """FSDP shards every matrix's trailing dims, so its Adafactor slots
    are UNfactored — a factored context checkpoint must warn + re-init
    (different information content, no silent install)."""
    eng = ContextParallelEngine(CFG, Adafactor(3e-2), ctx_mesh(1), seed=0)
    eng.train_batch(*batch(0))
    checkpoint.save(tmp_path, eng, epoch=0)
    fsdp = FSDPEngine(CFG, Adafactor(3e-2),
                      Mesh(np.array(jax.devices()[:4]).reshape(4), ("dp",)),
                      seed=1)
    with pytest.warns(UserWarning, match="re-initializing"):
        checkpoint.restore(fsdp, checkpoint.latest(tmp_path))
    fsdp.train_batch(*batch(1))  # must still train


def test_adafactor_to_pipeline_warns_and_reinits(tmp_path):
    """Adafactor's factored vectors cannot be re-stacked for the pipeline
    layout: the fallback is a WARNED re-init, never silent corruption."""
    eng = ContextParallelEngine(CFG, Adafactor(3e-2), ctx_mesh(1), seed=0)
    eng.train_batch(*batch(0))
    checkpoint.save(tmp_path, eng, epoch=0)
    pipe = PipelineLMEngine(CFG, Adafactor(3e-2), pipe_mesh(1, 4),
                            n_mubatches=2, seed=1)
    with pytest.warns(UserWarning, match="re-initializing"):
        checkpoint.restore(pipe, checkpoint.latest(tmp_path))
    pipe.train_batch(*batch(1))  # must still train


def test_optimizer_kind_mismatch_warns(tmp_path):
    """An Adam canonical record must not install into an Adafactor
    engine (and vice versa) — kind is checked, then warned."""
    eng = ContextParallelEngine(CFG, Adam(1e-2), ctx_mesh(1), seed=0)
    eng.train_batch(*batch(0))
    checkpoint.save(tmp_path, eng, epoch=0)
    pipe = PipelineLMEngine(CFG, Adafactor(3e-2), pipe_mesh(1, 4),
                            n_mubatches=2, seed=1)
    with pytest.warns(UserWarning):
        checkpoint.restore(pipe, checkpoint.latest(tmp_path))
