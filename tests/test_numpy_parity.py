"""Reference-math parity: the compiled TPU engine vs the pure-NumPy step.

The reference cannot execute in this image (mpi4py/mpirun absent, and its
OpenML fetch needs egress), so the strongest available parity check is
the one its own DDP script uses — absolute weight divergence against an
independently-executed implementation of the same math
(`/root/reference/scripts/DDP_PyTorch_MNIST.py:159-167`). `bench.py`'s
NumPy baseline step IS the reference's math (same forward, hand-written
backward, microbatch grad accumulation over the GLOBAL-batch-scaled MSE
grad, SGD; `functional.py`, `layers.py`, `optimizer.py`); here we train
both it and the jitted `FusedDPEngine` from the SAME seeded init on the
same batches and require the weights to stay together.
"""

import numpy as np

from bench import GBS, LAYER_SIZES, LR, N_MU, numpy_baseline_step_fn

from shallowspeed_tpu.engine import FusedDPEngine
from shallowspeed_tpu.models.mlp import MLPStage
from shallowspeed_tpu.optim import SGD
from shallowspeed_tpu.parallel.mesh import make_mesh


def make_data(seed, n_batches):
    rng = np.random.default_rng(seed)
    xs = rng.normal(size=(n_batches, N_MU, GBS // N_MU, 784)).astype(
        np.float32)
    labels = rng.integers(0, 10, (n_batches, GBS))
    ys = np.zeros((n_batches, GBS, 10), np.float32)
    for b in range(n_batches):
        ys[b, np.arange(GBS), labels[b]] = 1.0
    return xs, ys.reshape(n_batches, N_MU, GBS // N_MU, 10)


def test_fused_engine_matches_numpy_reference_math():
    n_batches = 12
    xs, ys = make_data(0, n_batches)

    np_step = numpy_baseline_step_fn()

    class _DS:
        def get_num_batches(self):
            return n_batches

        def load_mubatch_stack(self, batch_id):
            return xs[batch_id], ys[batch_id]

    eng = FusedDPEngine(MLPStage(LAYER_SIZES, 0, 1, batch_size=GBS),
                        SGD(LR), make_mesh(1, 1))
    ds = _DS()

    # identical seeded init before any step
    for i, (np_p, j_p) in enumerate(zip(np_step.params, eng.params)):
        np.testing.assert_array_equal(np_p["W"], np.asarray(j_p["W"]),
                                      err_msg=f"init layer {i}")

    for b in range(n_batches):
        np_step(xs[b], ys[b])
        eng.train_batch(b, [ds])

    # the reference's own parity criterion: small absolute weight
    # divergence after training (float reassociation only)
    for i, (np_p, j_p) in enumerate(zip(np_step.params, eng.params)):
        np.testing.assert_allclose(
            np.asarray(j_p["W"]), np_p["W"], rtol=5e-4, atol=1e-5,
            err_msg=f"layer {i} W diverged from the reference math")
        np.testing.assert_allclose(
            np.asarray(j_p["b"]).ravel(), np_p["b"].ravel(),
            rtol=5e-4, atol=1e-5,
            err_msg=f"layer {i} b diverged from the reference math")
