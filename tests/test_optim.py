"""Optimizer/schedule/clipping numerics (`shallowspeed_tpu/optim.py`).

The reference has one stateless SGD (`/root/reference/shallowspeed/
optimizer.py:4-13`) and trains its DDP baseline with torch Adam
(`scripts/DDP_PyTorch_MNIST.py`). We validate our pure-pytree optimizers
against hand-computed updates and — for Adam/AdamW — against the torch
implementations step by step (torch is CPU-only in this image, which is all
a numerics oracle needs).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shallowspeed_tpu.optim import (
    SCHEDULES, SGD, Adam, AdamW, MomentumSGD, clip_by_global_norm,
    constant, global_norm, warmup_cosine, warmup_linear)


def tree_np(tree):
    return jax.tree_util.tree_map(np.asarray, tree)


def rand_params(seed=0):
    rng = np.random.default_rng(seed)
    return {"W": jnp.asarray(rng.normal(size=(4, 3)), jnp.float32),
            "b": jnp.asarray(rng.normal(size=(3,)), jnp.float32)}


def rand_grads(seed):
    rng = np.random.default_rng(seed)
    return {"W": jnp.asarray(rng.normal(size=(4, 3)), jnp.float32),
            "b": jnp.asarray(rng.normal(size=(3,)), jnp.float32)}


# ----------------------------------------------------------------- sgd


def test_sgd_matches_reference_rule():
    p = rand_params()
    g = rand_grads(1)
    opt = SGD(0.1)
    new, state = opt.step(p, g, opt.init(p))
    np.testing.assert_allclose(new["W"], p["W"] - 0.1 * g["W"], rtol=1e-6)
    assert state == ()


def test_momentum_matches_hand_rolled():
    p = rand_params()
    opt = MomentumSGD(0.1, momentum=0.9)
    state = opt.init(p)
    v = np.zeros_like(np.asarray(p["W"]))
    w = np.asarray(p["W"]).copy()
    for s in range(3):
        g = rand_grads(s)
        p, state = opt.step(p, g, state)
        v = 0.9 * v + np.asarray(g["W"])
        w = w - 0.1 * v
    np.testing.assert_allclose(p["W"], w, rtol=1e-5)


# ---------------------------------------------------------- adam/adamw


def _torch_run(torch_cls, steps, lr=1e-2, **kw):
    """Run torch optimizer on the same params/grads stream; return final W."""
    torch = pytest.importorskip("torch")  # oracle only for the parity tests
    p0 = rand_params()
    tw = torch.tensor(np.asarray(p0["W"]), requires_grad=True)
    tb = torch.tensor(np.asarray(p0["b"]), requires_grad=True)
    topt = torch_cls([tw, tb], lr=lr, **kw)
    for s in range(steps):
        g = rand_grads(s)
        tw.grad = torch.tensor(np.asarray(g["W"]))
        tb.grad = torch.tensor(np.asarray(g["b"]))
        topt.step()
    return tw.detach().numpy(), tb.detach().numpy()


def _ours_run(opt, steps):
    p = rand_params()
    state = opt.init(p)
    for s in range(steps):
        p, state = opt.step(p, rand_grads(s), state)
    return np.asarray(p["W"]), np.asarray(p["b"])


def test_adam_matches_torch():
    torch = pytest.importorskip("torch")
    w, b = _ours_run(Adam(1e-2), steps=5)
    tw, tb = _torch_run(torch.optim.Adam, steps=5, lr=1e-2)
    np.testing.assert_allclose(w, tw, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(b, tb, rtol=1e-5, atol=1e-6)


def test_adamw_matches_torch():
    torch = pytest.importorskip("torch")
    w, b = _ours_run(AdamW(1e-2, weight_decay=0.1), steps=5)
    tw, tb = _torch_run(torch.optim.AdamW, steps=5, lr=1e-2,
                        weight_decay=0.1)
    np.testing.assert_allclose(w, tw, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(b, tb, rtol=1e-5, atol=1e-6)


def test_adamw_decay_shrinks_vs_adam():
    """Decoupled decay must pull weights toward zero relative to Adam."""
    wa, _ = _ours_run(Adam(1e-2), steps=10)
    ww, _ = _ours_run(AdamW(1e-2, weight_decay=0.5), steps=10)
    assert np.abs(ww).sum() < np.abs(wa).sum()


# ------------------------------------------------------------ clipping


def test_clip_noop_below_threshold():
    g = {"a": jnp.ones((2, 2)) * 0.01}
    out = clip_by_global_norm(g, 10.0)
    np.testing.assert_allclose(out["a"], g["a"], rtol=1e-6)


def test_clip_scales_to_max_norm():
    g = {"a": jnp.ones((3,)) * 100.0, "b": jnp.ones((4,)) * -50.0}
    out = clip_by_global_norm(g, 1.0)
    np.testing.assert_allclose(float(global_norm(out)), 1.0, rtol=1e-4)
    # direction preserved
    ratio = np.asarray(out["a"])[0] / np.asarray(out["b"])[0]
    assert ratio == pytest.approx(-2.0, rel=1e-5)


def test_grad_clip_inside_optimizer():
    p = rand_params()
    big = {"W": jnp.ones((4, 3)) * 1e4, "b": jnp.ones((3,)) * 1e4}
    clipped = SGD(1.0, grad_clip=1.0)
    new, _ = clipped.step(p, big, clipped.init(p))
    delta = np.sqrt(((np.asarray(new["W"]) - np.asarray(p["W"])) ** 2).sum()
                    + ((np.asarray(new["b"]) - np.asarray(p["b"])) ** 2).sum())
    assert delta == pytest.approx(1.0, rel=1e-4)


# ----------------------------------------------------------- schedules


def test_warmup_linear_shape():
    s = warmup_linear(peak=1.0, warmup=10, total=110, end=0.0)
    assert float(s(0)) == 0.0
    assert float(s(10)) == pytest.approx(1.0)
    assert float(s(5)) == pytest.approx(0.5)
    assert float(s(60)) == pytest.approx(0.5)
    assert float(s(110)) == pytest.approx(0.0)
    assert float(s(1000)) == pytest.approx(0.0)  # clamped after total


def test_warmup_cosine_shape():
    s = warmup_cosine(peak=2.0, warmup=4, total=104, end=0.2)
    assert float(s(0)) == 0.0
    assert float(s(4)) == pytest.approx(2.0)
    assert float(s(54)) == pytest.approx((2.0 + 0.2) / 2, rel=1e-5)
    assert float(s(104)) == pytest.approx(0.2, abs=1e-6)
    assert float(s(9999)) == pytest.approx(0.2, abs=1e-6)


def test_constant_schedule():
    s = constant(0.3)
    assert float(s(0)) == pytest.approx(0.3)
    assert float(s(777)) == pytest.approx(0.3)
    assert set(SCHEDULES) == {"constant", "linear", "cosine"}


def test_scheduled_sgd_tracks_step_counter():
    sched = warmup_linear(peak=1.0, warmup=2, total=4, end=0.0)
    opt = SGD(sched)
    p = {"w": jnp.zeros((1,))}
    g = {"w": jnp.ones((1,))}
    state = opt.init(p)
    assert "t" in state
    deltas = []
    for _ in range(4):
        new, state = opt.step(p, g, state)
        deltas.append(float(p["w"][0] - new["w"][0]))
        p = p  # params held fixed: delta == lr * 1
    np.testing.assert_allclose(deltas, [0.0, 0.5, 1.0, 0.5], atol=1e-6)


def test_scheduled_adam_uses_schedule():
    """Adam with a zero-lr schedule must not move params."""
    sched = lambda t: jnp.asarray(0.0)  # noqa: E731
    opt = Adam(sched)
    p = rand_params()
    new, _ = opt.step(p, rand_grads(0), opt.init(p))
    np.testing.assert_allclose(new["W"], p["W"], atol=0)


def test_scheduled_optimizer_jits():
    """Schedule + clip trace into one compiled step (no host callbacks)."""
    opt = AdamW(warmup_cosine(1e-2, 2, 10), weight_decay=0.01, grad_clip=1.0)
    p = rand_params()
    state = opt.init(p)
    step = jax.jit(opt.step)
    p2, state = step(p, rand_grads(0), state)
    p3, state = step(p2, rand_grads(1), state)
    assert np.isfinite(np.asarray(p3["W"])).all()
    assert int(state["t"]) == 2


def test_optimizers_preserve_param_dtype():
    """A strong-f32 lr scalar must not promote non-f32 params/moments: each
    optimizer casts its update back to the leaf's own dtype."""
    p = {"w": jnp.ones((4,), jnp.bfloat16)}
    g = {"w": jnp.ones((4,), jnp.float32)}  # f32 master-dtype grads
    for opt in (SGD(0.1), MomentumSGD(0.1), Adam(0.1),
                AdamW(0.1, weight_decay=0.1)):
        state = opt.init(p)
        new, state = opt.step(p, g, state)
        assert new["w"].dtype == jnp.bfloat16, type(opt).__name__
        for leaf in jax.tree_util.tree_leaves(state):
            if hasattr(leaf, "dtype") and leaf.dtype != jnp.int32:
                assert leaf.dtype == jnp.bfloat16, type(opt).__name__
