"""Dropout (`models/transformer.py` `cfg.dropout`, `--dropout`).

The reference has no regularization at all; this is the modern-framework
staple, done the functional way: train/eval is a property of the CALL
(key vs no key), never of mutable model state, and keys derive
deterministically from (step, microbatch, layer, mesh position) — which
makes masks reproducible under remat recompute and under the 1F1B
schedule's per-tick vjp recompute (asserted below).
"""

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.optim import SGD, Adam
from shallowspeed_tpu.parallel.context import ContextParallelEngine
from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine
from shallowspeed_tpu.parallel.tensor import TensorParallelEngine

CFG = T.TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                          max_seq=32, dropout=0.2)
CFG0 = replace(CFG, dropout=0.0)


def mesh2(dp, m, name):
    devs = np.array(jax.devices()[: dp * m]).reshape(dp, m)
    return Mesh(devs, ("dp", name))


def batch(seed=0, b=8, t=32, vocab=64):
    rng = np.random.default_rng(seed)
    tok = rng.integers(0, vocab, (b, t)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1).astype(np.int32)


# ------------------------------------------------------------- model level


def test_no_key_means_no_dropout():
    """Without a key the forward is the exact dropout=0 program."""
    params = T.init(CFG, seed=0)
    tok, _ = batch()
    a = T.forward(params, tok, CFG)                       # no key
    b_ = T.forward(params, tok, CFG0)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))


def test_key_changes_output_and_is_deterministic():
    params = T.init(CFG, seed=0)
    tok, _ = batch()
    k1, k2 = jax.random.PRNGKey(1), jax.random.PRNGKey(2)
    y1 = T.forward(params, tok, CFG, dropout_key=k1)
    y1b = T.forward(params, tok, CFG, dropout_key=k1)
    y2 = T.forward(params, tok, CFG, dropout_key=k2)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y1b))
    assert not np.allclose(np.asarray(y1), np.asarray(y2))
    assert not np.allclose(np.asarray(y1),
                           np.asarray(T.forward(params, tok, CFG)))


def test_dropout_zero_key_is_inert():
    """dropout=0 with a key passed is still the deterministic program."""
    params = T.init(CFG0, seed=0)
    tok, _ = batch()
    y = T.forward(params, tok, CFG0, dropout_key=jax.random.PRNGKey(3))
    np.testing.assert_array_equal(np.asarray(y),
                                  np.asarray(T.forward(params, tok, CFG0)))


def test_remat_reproduces_masks():
    """jax.checkpoint recompute must regenerate identical masks: the
    remat and non-remat programs compute the same loss AND gradient."""
    cfg_r = replace(CFG, remat=True)
    params = jax.device_put(T.init(CFG, seed=0))
    tok, tgt = batch()
    key = jax.random.PRNGKey(5)

    def loss_fn(cfg):
        return jax.value_and_grad(
            lambda p: T.loss(p, tok, tgt, cfg, dropout_key=key))(params)

    l0, g0 = loss_fn(CFG)
    l1, g1 = loss_fn(cfg_r)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b_ in zip(jax.tree_util.tree_leaves(g0),
                     jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-5, atol=1e-7)


# ------------------------------------------------------------ engine level


def test_context_engine_dropout_trains_and_eval_is_clean():
    eng = ContextParallelEngine(CFG, Adam(5e-3), mesh2(2, 2, "sp"), seed=0)
    ref = ContextParallelEngine(CFG0, Adam(5e-3), mesh2(1, 1, "sp"), seed=0)
    tok, tgt = batch(7)
    # eval never drops: identical params => identical eval loss, and a
    # training step with dropout differs from the dropout-free one
    assert eng.eval_loss(tok, tgt) == pytest.approx(
        ref.eval_loss(tok, tgt), rel=1e-5)
    l_drop = eng.train_batch(tok, tgt)
    l_ref = ref.train_batch(tok, tgt)
    assert l_drop != pytest.approx(l_ref, rel=1e-6)
    losses = [eng.train_batch(tok, tgt) for _ in range(30)]
    assert losses[-1] < losses[0], losses[::10]


def test_context_engine_dropout_deterministic_across_runs():
    a = ContextParallelEngine(CFG, SGD(0.1), mesh2(2, 2, "sp"), seed=3)
    b_ = ContextParallelEngine(CFG, SGD(0.1), mesh2(2, 2, "sp"), seed=3)
    for s in range(3):
        tok, tgt = batch(s)
        assert a.train_batch(tok, tgt) == pytest.approx(
            b_.train_batch(tok, tgt), rel=1e-7), s


def test_steps_draw_different_masks():
    """The per-step fold_in must vary the masks: two consecutive steps on
    IDENTICAL data with SGD lr=0 give different losses iff masks moved."""
    eng = ContextParallelEngine(CFG, SGD(0.0), mesh2(1, 1, "sp"), seed=0)
    tok, tgt = batch(1)
    l0 = eng.train_batch(tok, tgt)
    l1 = eng.train_batch(tok, tgt)   # same params (lr=0), new step key
    assert l0 != pytest.approx(l1, rel=1e-7)


def test_tensor_engine_dropout_trains():
    eng = TensorParallelEngine(CFG, Adam(5e-3), mesh2(2, 2, "tp"), seed=0)
    tok, tgt = batch(9)
    losses = [eng.train_batch(tok, tgt) for _ in range(30)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses[::10]


def test_zero2_dropout_matches_dense_engine():
    """Same mesh + same step keys: ZeRO-2 placement must not change the
    dropout math (keys fold mesh coordinates, not placement)."""
    dense = ContextParallelEngine(CFG, Adam(1e-2), mesh2(4, 1, "sp"),
                                  seed=0)
    z2 = ContextParallelEngine(CFG, Adam(1e-2), mesh2(4, 1, "sp"),
                               seed=0, zero2=True)
    for s in range(3):
        tok, tgt = batch(s)
        np.testing.assert_allclose(dense.train_batch(tok, tgt),
                                   z2.train_batch(tok, tgt),
                                   rtol=1e-4, atol=1e-6)


# --------------------------------------------------------------- pipeline


def test_pipeline_gpipe_1f1b_identical_masks():
    """The flagship recompute test: GPipe (autodiff backward over saved
    residuals) and 1F1B (per-tick vjp recompute from the stash) derive
    dropout keys the same way, so with the same seed they must produce
    the SAME losses and parameters — proving the 1F1B backward
    regenerates bit-identical masks."""
    g = PipelineLMEngine(replace(CFG, n_layers=4), SGD(0.1),
                         Mesh(np.array(jax.devices()[:4]).reshape(1, 4),
                              ("dp", "pp")),
                         n_mubatches=4, seed=0, schedule="gpipe")
    f = PipelineLMEngine(replace(CFG, n_layers=4), SGD(0.1),
                         Mesh(np.array(jax.devices()[:4]).reshape(1, 4),
                              ("dp", "pp")),
                         n_mubatches=4, seed=0, schedule="1f1b")
    for s in range(3):
        tok, tgt = batch(s)
        assert f.train_batch(tok, tgt) == pytest.approx(
            g.train_batch(tok, tgt), rel=1e-5), s
    for a, b_ in zip(jax.tree_util.tree_leaves(f.get_canonical_params()),
                     jax.tree_util.tree_leaves(g.get_canonical_params())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-6)


def test_pipeline_dropout_trains_with_tp():
    cfg = replace(CFG, n_layers=2)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 2, 2),
                ("dp", "pp", "tp"))
    eng = PipelineLMEngine(cfg, Adam(5e-3), mesh, n_mubatches=2, seed=0)
    tok, tgt = batch(11)
    losses = [eng.train_batch(tok, tgt) for _ in range(20)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses[::5]


def test_resume_continues_mask_stream(tmp_path):
    """checkpoint.restore resumes the dropout step counter: a save/
    restore/continue run must equal the uninterrupted run exactly."""
    from shallowspeed_tpu import checkpoint

    straight = ContextParallelEngine(CFG, Adam(1e-2), mesh2(2, 1, "sp"),
                                     seed=0)
    eng = ContextParallelEngine(CFG, Adam(1e-2), mesh2(2, 1, "sp"), seed=0)
    losses_a = [straight.train_batch(*batch(s)) for s in range(4)]
    for s in range(2):
        eng.train_batch(*batch(s))
    checkpoint.save(tmp_path, eng, 1)   # step index 1 just finished
    eng2 = ContextParallelEngine(CFG, Adam(1e-2), mesh2(2, 1, "sp"),
                                 seed=0)
    assert checkpoint.restore(eng2, checkpoint.latest(tmp_path)) == 2
    assert eng2._step_count == 2
    for s in range(2, 4):
        np.testing.assert_allclose(eng2.train_batch(*batch(s)),
                                   losses_a[s], rtol=1e-6, atol=1e-7)


def test_generate_never_drops():
    """Decode path passes no key: two samples from the same prompt and
    sampling seed are identical even with cfg.dropout > 0."""
    from shallowspeed_tpu.models.generate import generate

    params = jax.device_put(T.init(CFG, seed=0))
    prompt = np.array([[5, 9, 2, 4]], np.int32)
    a = generate(params, prompt, CFG, max_new=8, seed=1, temperature=1.0)
    b_ = generate(params, prompt, CFG, max_new=8, seed=1, temperature=1.0)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))


# --------------------------------- attention-probability dropout (round 3)


def test_attn_dropout_changes_training_only():
    """cfg.attn_dropout masks attention probabilities during TRAINING
    steps (loss differs from the clean config) while eval stays
    bit-identical to no-dropout (key is None there)."""
    from dataclasses import replace as _replace

    cfg0 = T.TransformerConfig(vocab=32, d_model=16, n_heads=2,
                               n_layers=1, max_seq=16)
    cfgd = _replace(cfg0, attn_dropout=0.5)
    params = T.init(cfg0, seed=1)
    rng = np.random.default_rng(0)
    tok = rng.integers(0, 32, (2, 16)).astype(np.int32)
    tgt = np.roll(tok, -1, axis=1)
    key = jax.random.PRNGKey(7)
    l_clean = float(T.loss(params, tok, tgt, cfg0, dropout_key=key))
    l_drop = float(T.loss(params, tok, tgt, cfgd, dropout_key=key))
    assert abs(l_clean - l_drop) > 1e-4
    # eval (no key): identical to clean
    assert float(T.loss(params, tok, tgt, cfgd)) == pytest.approx(
        l_clean, abs=1e-7)
    # deterministic given the key
    assert float(T.loss(params, tok, tgt, cfgd, dropout_key=key)) \
        == pytest.approx(l_drop, abs=1e-7)


def test_attn_dropout_composes_with_output_dropout():
    from dataclasses import replace as _replace

    cfg = T.TransformerConfig(vocab=32, d_model=16, n_heads=2,
                              n_layers=1, max_seq=16, dropout=0.1,
                              attn_dropout=0.2)
    params = T.init(cfg, seed=1)
    rng = np.random.default_rng(0)
    tok = rng.integers(0, 32, (2, 16)).astype(np.int32)
    l = float(T.loss(params, tok, np.roll(tok, -1, 1), cfg,
                     dropout_key=jax.random.PRNGKey(3)))
    assert np.isfinite(l)


def test_attn_dropout_rejected_on_fused_substrates():
    from dataclasses import replace as _replace

    from shallowspeed_tpu.models.transformer import TransformerConfig
    from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine
    from shallowspeed_tpu.parallel.context import ContextParallelEngine
    from shallowspeed_tpu.optim import SGD
    from jax.sharding import Mesh as _Mesh

    cfg = TransformerConfig(vocab=32, d_model=16, n_heads=2, n_layers=2,
                            max_seq=16, attn_dropout=0.1)
    with pytest.raises(AssertionError, match="attention-probability"):
        PipelineLMEngine(cfg, SGD(0.1), _Mesh(
            np.array(jax.devices()[:2]).reshape(1, 2), ("dp", "pp")))
    with pytest.raises(AssertionError, match="plain XLA attention"):
        ContextParallelEngine(cfg, SGD(0.1), _Mesh(
            np.array(jax.devices()[:2]).reshape(1, 2), ("dp", "sp")))


def test_attn_dropout_context_engine_trains():
    """sp=1 context engine transparently swaps ring -> plain attention
    and trains with the probability mask."""
    from dataclasses import replace as _replace

    from shallowspeed_tpu.models.transformer import TransformerConfig
    from shallowspeed_tpu.parallel.context import ContextParallelEngine
    from shallowspeed_tpu.optim import Adam
    from jax.sharding import Mesh as _Mesh

    cfg = TransformerConfig(vocab=32, d_model=16, n_heads=2, n_layers=1,
                            max_seq=16, attn_dropout=0.2)
    eng = ContextParallelEngine(cfg, Adam(5e-3), _Mesh(
        np.array(jax.devices()[:1]).reshape(1, 1), ("dp", "sp")), seed=0)
    rng = np.random.default_rng(0)
    tok = rng.integers(0, 32, (4, 16)).astype(np.int32)
    tgt = np.roll(tok, -1, axis=1).astype(np.int32)
    losses = [eng.train_batch(tok, tgt) for _ in range(10)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
