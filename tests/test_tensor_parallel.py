"""Tensor-parallel engine tests: Megatron placement must be invisible to the
math — sharded runs equal the serial run through full optimizer steps — and
the parameters must actually be sharded (not silently replicated).
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.optim import SGD, Adam
from shallowspeed_tpu.parallel.context import ContextParallelEngine
from shallowspeed_tpu.parallel.tensor import TensorParallelEngine

CFG = T.TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                          max_seq=64)


def toy_batch(b=4, t=32, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, CFG.vocab, (b, t)).astype(np.int32)
    return tokens, np.roll(tokens, -1, axis=1).astype(np.int32)


def tp_mesh(dp, tp):
    devs = np.array(jax.devices()[: dp * tp]).reshape(dp, tp)
    return Mesh(devs, ("dp", "tp"))


def sp_mesh(dp, sp):
    devs = np.array(jax.devices()[: dp * sp]).reshape(dp, sp)
    return Mesh(devs, ("dp", "sp"))


@pytest.mark.parametrize("dp,tp", [(1, 2), (1, 4), (2, 2), (4, 2)])
def test_tp_step_matches_serial(dp, tp):
    tokens, targets = toy_batch()
    serial = TensorParallelEngine(CFG, SGD(0.1), tp_mesh(1, 1), seed=3)
    eng = TensorParallelEngine(CFG, SGD(0.1), tp_mesh(dp, tp), seed=3)
    for b in range(2):
        tok, tgt = toy_batch(seed=b)
        l0 = serial.train_batch(tok, tgt)
        l1 = eng.train_batch(tok, tgt)
        assert abs(l0 - l1) < 1e-5, (l0, l1)
    for a, b_ in zip(jax.tree_util.tree_leaves(serial.params),
                     jax.tree_util.tree_leaves(eng.params)):
        np.testing.assert_allclose(np.asarray(b_), np.asarray(a),
                                   rtol=1e-4, atol=1e-5)


def test_params_actually_sharded():
    """qkv/up are column-sharded, proj/down row-sharded over tp=4."""
    eng = TensorParallelEngine(CFG, SGD(0.1), tp_mesh(1, 4), seed=0)
    d = CFG.d_model
    blk = eng.params["blocks"][0]
    assert blk["qkv"]["W"].addressable_shards[0].data.shape == (d, 3 * d // 4)
    assert blk["up"]["W"].addressable_shards[0].data.shape == (d, 4 * d // 4)
    assert blk["proj"]["W"].addressable_shards[0].data.shape == (d // 4, d)
    assert blk["down"]["W"].addressable_shards[0].data.shape == (4 * d // 4, d)
    # adam moments inherit the placement
    eng2 = TensorParallelEngine(CFG, Adam(1e-3), tp_mesh(1, 4), seed=0)
    m = eng2.opt_state["m"]["blocks"][0]["qkv"]["W"]
    assert m.addressable_shards[0].data.shape == (d, 3 * d // 4)


def test_tp_matches_context_parallel_engine():
    """Two independent parallelization strategies of the same model agree."""
    tokens, targets = toy_batch(seed=7)
    tp = TensorParallelEngine(CFG, Adam(1e-2), tp_mesh(2, 4), seed=5)
    cp = ContextParallelEngine(CFG, Adam(1e-2), sp_mesh(2, 4), seed=5)
    for _ in range(3):
        lt = tp.train_batch(tokens, targets)
        lc = cp.train_batch(tokens, targets)
        assert abs(lt - lc) < 2e-5, (lt, lc)


def test_tp_checkpoint_roundtrip(tmp_path):
    from shallowspeed_tpu import checkpoint

    eng = TensorParallelEngine(CFG, Adam(1e-3), tp_mesh(2, 2), seed=4)
    tokens, targets = toy_batch(seed=1)
    eng.train_batch(tokens, targets)
    checkpoint.save(tmp_path, eng, epoch=0)

    eng2 = TensorParallelEngine(CFG, Adam(1e-3), tp_mesh(1, 4), seed=99)
    assert checkpoint.restore(eng2, checkpoint.latest(tmp_path)) == 1
    la = eng.train_batch(tokens, targets)
    lb = eng2.train_batch(tokens, targets)
    assert abs(la - lb) < 1e-5
