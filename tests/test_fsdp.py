"""FSDP / ZeRO-3 engine (`parallel/fsdp.py`): fully-sharded params, grads,
and optimizer state over 'dp'.

Correctness oracle: FSDP is the SAME algorithm as replicated data
parallelism — only the placement differs — so its loss trajectory must
match the replicated-DP GSPMD engine step for step (up to float
reassociation from the different collective order).
"""

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.optim import Adam, AdamW, SGD
from shallowspeed_tpu.parallel.fsdp import FSDPEngine, fsdp_spec

CFG = T.TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                          max_seq=32)


def dp_mesh(dp):
    return Mesh(np.array(jax.devices()[:dp]), ("dp",))


def batch(seed=0, b=8, t=32, vocab=64):
    rng = np.random.default_rng(seed)
    tok = rng.integers(0, vocab, (b, t)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1).astype(np.int32)


# ------------------------------------------------------------- placement


def test_fsdp_spec_picks_largest_divisible_dim():
    assert fsdp_spec((64, 32), 4) == P("dp", None)
    assert fsdp_spec((32, 128), 4) == P(None, "dp")
    assert fsdp_spec((6, 128), 4) == P(None, "dp")  # 6 % 4 != 0
    assert fsdp_spec((3,), 4) == P()                # nothing divisible
    assert fsdp_spec((), 4) == P()


def test_params_and_moments_are_sharded():
    eng = FSDPEngine(CFG, Adam(1e-3), dp_mesh(4))
    n_sharded = 0
    for leaf in jax.tree_util.tree_leaves(eng.params):
        spec = leaf.sharding.spec
        if any(e == "dp" for e in spec):
            n_sharded += 1
            # the addressable shard really is 1/dp of the leaf
            shard = leaf.addressable_shards[0].data
            assert shard.size == leaf.size // 4
    assert n_sharded > 0.8 * len(jax.tree_util.tree_leaves(eng.params))
    # Adam moments inherit the placement (ZeRO-3: no replicated state)
    for name in ("m", "v"):
        for leaf, p in zip(jax.tree_util.tree_leaves(eng.opt_state[name]),
                           jax.tree_util.tree_leaves(eng.params)):
            assert leaf.sharding == p.sharding


def test_zero1_flag_rejected():
    with pytest.raises(ValueError, match="superset of ZeRO-1"):
        FSDPEngine(CFG, Adam(1e-3), dp_mesh(2), zero1=True)


def test_mesh_shape_rejected():
    with pytest.raises(AssertionError, match="1-D"):
        FSDPEngine(CFG, Adam(1e-3),
                   Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                        ("dp", "tp")))


# ----------------------------------------------------------- equivalence


def replicated_dp_engine(dp, opt):
    """Replicated-DP oracle: the TP engine with tp=1 is plain GSPMD data
    parallelism with fully replicated parameters."""
    from shallowspeed_tpu.parallel.tensor import TensorParallelEngine

    mesh = Mesh(np.array(jax.devices()[:dp]).reshape(dp, 1), ("dp", "tp"))
    return TensorParallelEngine(CFG, opt, mesh, seed=0)


@pytest.mark.parametrize("opt_cls,lr", [(SGD, 0.1), (Adam, 1e-2)])
def test_fsdp_matches_replicated_dp(opt_cls, lr):
    fsdp = FSDPEngine(CFG, opt_cls(lr), dp_mesh(4), seed=0)
    repl = replicated_dp_engine(4, opt_cls(lr))
    for step in range(5):
        tok, tgt = batch(step)
        lf = fsdp.train_batch(tok, tgt)
        lr_ = repl.train_batch(tok, tgt)
        assert lf == pytest.approx(lr_, rel=2e-4), step
    # trained weights agree leaf by leaf (gather the FSDP shards)
    for a, b in zip(jax.tree_util.tree_leaves(fsdp.params),
                    jax.tree_util.tree_leaves(repl.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-5)


def test_fsdp_dp_count_invariance():
    """dp=2 and dp=8 must train identically (same global batch)."""
    e2 = FSDPEngine(CFG, Adam(1e-2), dp_mesh(2), seed=0)
    e8 = FSDPEngine(CFG, Adam(1e-2), dp_mesh(8), seed=0)
    for step in range(3):
        tok, tgt = batch(step)
        l2 = e2.train_batch(tok, tgt)
        l8 = e8.train_batch(tok, tgt)
        assert l2 == pytest.approx(l8, rel=2e-4), step


# -------------------------------------------------------------- training


def test_fsdp_trains_bf16():
    cfg16 = replace(CFG, compute_dtype=jnp.bfloat16)
    eng = FSDPEngine(cfg16, AdamW(5e-3, weight_decay=0.01, grad_clip=1.0),
                     dp_mesh(4), seed=0)
    tok, tgt = batch(7)
    losses = [eng.train_batch(tok, tgt) for _ in range(25)]
    assert losses[-1] < losses[0] - 0.2, losses[::6]
    for leaf in jax.tree_util.tree_leaves(eng.params):
        assert leaf.dtype == jnp.float32  # master weights


def test_fsdp_checkpoint_roundtrip(tmp_path):
    from shallowspeed_tpu import checkpoint

    eng = FSDPEngine(CFG, Adam(1e-2), dp_mesh(4), seed=0)
    tok, tgt = batch(3)
    for _ in range(3):
        eng.train_batch(tok, tgt)
    checkpoint.save(str(tmp_path), eng, 3)
    eng2 = FSDPEngine(CFG, Adam(1e-2), dp_mesh(4), seed=1)
    # restore returns the resume point (saved step + 1)
    assert checkpoint.restore(eng2, checkpoint.latest(str(tmp_path))) == 4
    # restored state keeps the FSDP placement and the training trajectory
    for a, b in zip(jax.tree_util.tree_leaves(eng2.params),
                    jax.tree_util.tree_leaves(eng.params)):
        assert a.sharding == b.sharding
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0)
    l1 = eng.train_batch(tok, tgt)
    l2 = eng2.train_batch(tok, tgt)
    assert l1 == pytest.approx(l2, rel=1e-5)
