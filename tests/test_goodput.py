"""Goodput ledger + reducer + bench regression gate (telemetry/
goodput.py, regress.py) and the driver/elastic integrations.

The two acceptance pins live here:
- a supervisor kill/restart run whose ledger accounts for >= 95% of
  wall clock, with restart downtime itemized and cross-checked against
  the child processes' own JSONL wall stamps;
- the `--regress` gate passing on the committed BENCH_r01-r05
  trajectory and demonstrably failing on a synthetic regression.
"""

import json
import shutil
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from shallowspeed_tpu.metrics import MetricsLogger, StepRates
from shallowspeed_tpu.telemetry.goodput import (GoodputLedger,
                                                format_report,
                                                run_goodput)

ROOT = Path(__file__).resolve().parents[1]


# ------------------------------------------- windows + ledger == wall


def test_steprates_windows_plus_excluded_ledger_equals_wall():
    """The satellite invariant: because every StepRates.pause also
    stamps the ledger, sum(window seconds) + sum(excluded ledger
    seconds) == elapsed wall clock EXACTLY — the throughput windows
    and the goodput ledger cannot disagree."""
    t = {"now": 100.0}

    def clock():
        return t["now"]

    led = GoodputLedger()  # in-process totals only
    rates = StepRates(tokens_per_step=10, clock=clock, ledger=led)
    win_secs = []

    def log(steps):
        r = rates.log_point(steps)
        win_secs.append(10 * steps / r["tokens_per_sec"])

    t["now"] += 4.0            # 4 s of stepping
    log(4)
    t["now"] += 2.0            # val pause
    rates.pause(2.0, kind="val")
    t["now"] += 3.0            # 3 s of stepping
    log(3)
    t["now"] += 1.5            # checkpoint save
    rates.pause(1.5, kind="ckpt_save")
    t["now"] += 0.5
    log(1)
    wall = t["now"] - 100.0
    assert sum(win_secs) + led.excluded_seconds() == pytest.approx(wall)
    assert led.seconds() == {"val": 2.0, "ckpt_save": 1.5}


def test_ledger_lines_validate_and_accumulate(tmp_path):
    from shallowspeed_tpu.telemetry.schema import validate_file

    log = tmp_path / "m.jsonl"
    led = GoodputLedger(MetricsLogger(log))
    led.note("init", seconds=1.5)
    led.note("recompile", count=2)
    led.note("val", seconds=0.5)
    led.note("val", seconds=0.25)
    assert validate_file(log) == []
    assert led.seconds()["val"] == 0.75
    assert led.counts() == {"recompile": 2}
    kinds = [json.loads(l)["kind"] for l in log.read_text().splitlines()
             if '"ledger"' in l]
    assert kinds == ["init", "recompile", "val", "val"]


# ------------------------------------------------------------ reducer


def _write_jsonl(path, recs):
    path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")


def test_run_goodput_single_run(tmp_path):
    """Synthetic single-process run: 0.1 s/step steady, an itemized
    val pause, and first-window compile excess derived by the
    reducer."""
    log = tmp_path / "m.jsonl"
    recs = [{"event": "run_start", "start_step": 0, "wall": 1000.0},
            {"event": "ledger", "kind": "init", "seconds": 0.4,
             "wall": 1000.4},
            # first step line at 1002.0: 1 step covered, steady rate
            # 0.1 s/step -> compile = 2.0 - 0.4(init) - 0.1 = 1.5
            {"event": "step", "step": 0, "loss": 1.0,
             "tokens_per_sec": 1.0, "wall": 1002.0}]
    w = 1002.0
    for s in range(1, 6):
        w += 0.2  # 2 steps per line at 0.1 s/step
        recs.append({"event": "step", "step": 2 * s, "loss": 1.0,
                     "tokens_per_sec": 1.0, "wall": round(w, 3)})
    recs.insert(6, {"event": "ledger", "kind": "val", "seconds": 0.5,
                    "wall": 1002.75})
    # the val pause really moves wall: shift the lines after it
    for r in recs[7:]:
        r["wall"] = round(r["wall"] + 0.5, 3)
    _write_jsonl(log, recs)
    rep = run_goodput(log)
    assert rep["stanzas"] == 1
    assert rep["per_step_s"] == pytest.approx(0.1, rel=0.05)
    assert rep["losses"]["init"] == pytest.approx(0.4)
    assert rep["losses"]["val"] == pytest.approx(0.5)
    assert rep["losses"]["compile"] == pytest.approx(1.5, abs=0.05)
    assert rep["goodput"] is not None
    assert rep["accounted_frac"] >= 0.99
    assert "wall clock" in format_report(rep)


CHILD = textwrap.dedent(f"""
    import json, sys, time
    sys.path.insert(0, {str(ROOT)!r})
    from pathlib import Path
    from shallowspeed_tpu.metrics import MetricsLogger
    from shallowspeed_tpu.telemetry.goodput import GoodputLedger

    log, state = sys.argv[1], sys.argv[2]
    attempts = Path(state)
    n = int(attempts.read_text()) if attempts.exists() else 0
    attempts.write_text(str(n + 1))
    start_step = 0 if n == 0 else 3   # "checkpoint" at step 3
    m = MetricsLogger(log, start_step=start_step)
    led = GoodputLedger(m)
    t0 = time.time()
    time.sleep(0.05)
    led.note("init", seconds=time.time() - t0)
    for s in range(start_step, 10):
        time.sleep(0.05)
        m.log(event="step", step=s, loss=1.0, tokens_per_sec=100.0)
        if n == 0 and s == 6:
            sys.exit(1)               # crash after logging step 6
    sys.exit(0)
""")


def test_supervisor_kill_restart_ledger_accounts_wall_clock(tmp_path):
    """The elastic-goodput acceptance: a crash-and-resume run's ledger
    accounts for >= 95% of wall clock; the restart-downtime and
    replayed-steps losses match what the child processes' own JSONL
    wall stamps imply."""
    from shallowspeed_tpu.elastic import RestartPolicy, Supervisor

    child = tmp_path / "child.py"
    child.write_text(CHILD)
    log = tmp_path / "metrics.jsonl"
    sup = Supervisor(
        [sys.executable, str(child), str(log), str(tmp_path / "n")],
        policy=RestartPolicy(max_restarts=2, backoff=0.3),
        poll_interval=0.05, ledger_file=str(log), log=lambda *a: None)
    assert sup.run() == 0

    rep = run_goodput(log)
    assert rep["stanzas"] == 2
    assert rep["counts"]["restarts"] == 1
    # child 2 resumed at step 3; child 1 died after step 6 -> steps
    # 3..6 are replayed work
    assert rep["counts"]["replayed_steps"] == 4
    # cross-check against the children's own wall stamps
    recs = [json.loads(l) for l in log.read_text().splitlines()]
    walls = {}
    stanza = -1
    for r in recs:
        if r["event"] == "run_start":
            stanza += 1
            walls[stanza] = {"start": r["wall"], "steps": []}
        elif r["event"] == "step":
            walls[stanza]["steps"].append((r["step"], r["wall"]))
    gap = walls[1]["start"] - walls[0]["steps"][-1][1]
    assert rep["losses"]["restart_downtime"] == pytest.approx(gap,
                                                              abs=1e-6)
    # the supervisor's own stamp covers the same interval (within its
    # poll latency + child-spawn time)
    stamped = [r for r in recs if r["event"] == "ledger"
               and r["kind"] == "restart_downtime"]
    assert len(stamped) == 1 and stamped[0]["attempt"] == 1
    assert 0.3 <= stamped[0]["seconds"] <= gap + 0.1
    # replay loss == replayed steps * the children's own step cadence
    deltas = [b - a for (_, a), (_, b) in
              zip(walls[1]["steps"], walls[1]["steps"][1:])]
    per_step = sorted(deltas)[len(deltas) // 2]
    assert rep["losses"]["replay"] == pytest.approx(4 * per_step,
                                                    rel=0.5)
    # the acceptance bar: >= 95% of wall clock has a name
    assert rep["accounted_frac"] >= 0.95, rep
    assert rep["goodput"] is not None and 0.0 < rep["goodput"] < 1.0


def test_supervisor_autodetects_child_log_file(tmp_path):
    from shallowspeed_tpu.elastic import Supervisor

    sup = Supervisor(["prog", "--log-file", str(tmp_path / "x.jsonl")],
                     log=lambda *a: None)
    assert sup.ledger_file == str(tmp_path / "x.jsonl")
    assert Supervisor(["prog"], log=lambda *a: None).ledger_file is None


# ------------------------------------------------- bench --regress gate


def test_regress_gate_passes_on_committed_trajectory(capsys):
    from shallowspeed_tpu.telemetry.regress import main as rmain

    assert rmain([str(ROOT)]) == 0
    out = capsys.readouterr().out
    assert "regress gate: OK" in out


def test_regress_gate_fails_on_synthetic_regression(tmp_path, capsys):
    from shallowspeed_tpu.telemetry.regress import main as rmain

    rounds = []
    for f in sorted(ROOT.glob("BENCH_r*.json")):
        shutil.copy(f, tmp_path / f.name)
        rounds.append(int(json.loads(f.read_text()).get("n", 0)))
    bad = json.loads((ROOT / "BENCH_r05.json").read_text())
    # the synthetic regression must be the NEWEST round — the gate
    # only judges the last entry, so pin past the committed trajectory
    bad["n"] = max(rounds) + 1
    bad["parsed"]["transformer_mfu"] = 0.40   # ~29% below the median
    (tmp_path / f"BENCH_r{bad['n']:02d}.json").write_text(
        json.dumps(bad))
    assert rmain([str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "transformer_mfu" in out


def test_regress_band_widens_with_recorded_spread():
    from shallowspeed_tpu.telemetry import regress

    entries = [{"n": i, "path": f"r{i}", "parsed":
                {"value": 100.0, "spread": {"tpu": 0.08}}}
               for i in range(1, 4)]
    # 20% drop: beyond the 15% floor but inside 3x the recorded 8%
    entries.append({"n": 4, "path": "r4",
                    "parsed": {"value": 80.0,
                               "spread": {"tpu": 0.08}}})
    probs, _ = regress.check_trajectory(entries)
    assert probs == []
    # without the recorded spread the floor (15%) catches it
    for e in entries:
        e["parsed"].pop("spread")
    probs, _ = regress.check_trajectory(entries)
    assert len(probs) == 1 and "value" in probs[0]


def test_regress_vacuous_on_short_trajectory(tmp_path):
    from shallowspeed_tpu.telemetry.regress import main as rmain

    shutil.copy(ROOT / "BENCH_r01.json", tmp_path / "BENCH_r01.json")
    assert rmain([str(tmp_path)]) == 0


# ------------------------------- driver integration + xprof smoke test


@pytest.mark.parametrize("driver", ["lm"])
def test_driver_goodput_profile_and_decode_lines(tmp_path, driver):
    """ONE tiny train_lm run covering four satellites: the xprof
    --profile-dir capture smoke test (a loadable, NON-EMPTY trace
    artifact — not just a directory that exists), the goodput
    ledger's driver wiring (init/val/ckpt_save stamped, the reducer
    accounts the run), the decode progress line's "generate" metrics
    event, and — with `--profile host+device` on the same run — the
    continuous profiling plane riding the SAME device-capture entry
    point (`profiler.device_trace_ctx`) as --profile-dir, streaming
    schema-v12 profile events next to the spans-level attribution
    fields, all schema-valid."""
    import train_lm

    log = tmp_path / "metrics.jsonl"
    prof = tmp_path / "prof"
    trace = tmp_path / "trace"
    train_lm.train(train_lm.parse_args(
        ["--dp", "1", "--seq-len", "32", "--d-model", "32",
         "--n-layers", "2", "--batch-size", "4", "--steps", "8",
         "--log-every", "2", "--val-every", "4", "--save-every", "4",
         "--save-dir", str(tmp_path / "ck"), "--log-file", str(log),
         "--profile-dir", str(prof), "--telemetry", "spans",
         "--profile", "host+device",
         "--trace-dir", str(trace), "--prefetch", "0",
         "--generate", "8", "--seed", "0"]))
    # xprof smoke, hardened (round 17): an empty directory or a
    # zero-byte artifact used to pass — require a non-empty protobuf
    # (xprof writes *.xplane.pb under plugins/profile/<ts>/)
    pbs = [p for p in prof.rglob("*.pb") if p.stat().st_size > 0]
    assert pbs, (f"no non-empty xprof .pb artifact under {prof}: "
                 f"{[str(p) for p in prof.rglob('*') if p.is_file()]}")
    # schema: the v4 artifact validates end to end
    from shallowspeed_tpu.telemetry.schema import validate_file

    assert validate_file(log) == []
    recs = [json.loads(l) for l in log.read_text().splitlines()]
    kinds = {r["kind"] for r in recs if r["event"] == "ledger"}
    assert {"init", "val", "ckpt_save"} <= kinds, kinds
    steps = [r for r in recs if r["event"] == "step"]
    assert steps and "attrib_unexplained_frac" in steps[-1], steps[-1]
    gen = [r for r in recs if r["event"] == "generate"]
    assert len(gen) == 1 and gen[0]["tokens_per_sec"] > 0
    assert gen[0]["hbm_util"] is None  # CPU: no invented HBM peak
    # the profiling plane ran alongside: schema-v12 snapshots landed,
    # and host+device under an ACTIVE --profile-dir whole-run trace
    # means capture windows would skip their device half (xprof
    # doesn't nest) — the sampler itself must still stream
    profs = [r for r in recs if r["event"] == "profile"]
    assert profs and profs[-1]["samples"] > 0, profs
    # the reducer accounts the run (single process, generous band —
    # the strict >= 0.95 pin is the supervised kill/restart test)
    rep = run_goodput(log)
    assert rep["stanzas"] == 1
    assert rep["accounted_frac"] is not None
    assert rep["accounted_frac"] >= 0.85, rep
    # telemetry.json carries the in-process ledger totals
    summary = json.loads((trace / "telemetry.json").read_text())
    assert summary["goodput_ledger"]["seconds"].get("val", 0) > 0


def test_goodput_prefix_cache_block(tmp_path):
    """Schema-v14 prefix reduction: request lines' hit-blocks /
    skipped-tokens tallies plus the last generate tick's gauges land
    in rep["prefix"], the formatted report prints the hit-rate line,
    and a run without the fields reports prefix=None (the cache-off
    shape is unchanged)."""
    from shallowspeed_tpu.telemetry.goodput import (format_report,
                                                    run_goodput)

    log = tmp_path / "serve.jsonl"
    base = {"event": "request", "ttft_ms": 5.0, "tpot_ms": 1.0,
            "tokens_out": 8}
    _write_jsonl(log, [
        {"event": "run_start", "start_step": 0, "wall": 1000.0},
        dict(base, id="cold", tokens_in=32, prefix_hit_blocks=0,
             prefill_skipped_tokens=0, wall=1000.1),
        dict(base, id="hit", tokens_in=32, prefix_hit_blocks=4,
             prefill_skipped_tokens=31, wall=1000.2),
        dict(base, id="part", tokens_in=48, prefix_hit_blocks=2,
             prefill_skipped_tokens=16, wall=1000.3),
        {"event": "generate", "tokens_per_sec": 100.0,
         "prefix_hit_rate": 0.5, "cold_blocks": 6, "prefix_blocks": 6,
         "wall": 1000.4},
    ])
    pfx = run_goodput(log)["prefix"]
    assert pfx["requests_observed"] == 3
    assert pfx["requests_hit"] == 2
    assert pfx["hit_rate"] == pytest.approx(2 / 3, abs=1e-3)
    assert pfx["hit_blocks"] == 6
    assert pfx["prefill_skipped_tokens"] == 47
    assert pfx["skipped_frac"] == pytest.approx(47 / 112, abs=1e-3)
    assert pfx["cold_blocks"] == 6 and pfx["prefix_blocks"] == 6
    assert "prefix cache: 2/3 request(s) hit" in \
        format_report(run_goodput(log))
    # cache-off runs keep the old shape: no prefix block at all
    off = tmp_path / "off.jsonl"
    _write_jsonl(off, [
        {"event": "run_start", "start_step": 0, "wall": 1000.0},
        dict(base, id="a", tokens_in=16, wall=1000.1),
    ])
    rep = run_goodput(off)
    assert rep["prefix"] is None
    assert "prefix cache" not in format_report(rep)
