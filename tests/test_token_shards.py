"""Streaming tokenized-shard corpus (`data/token_shards.py`).

The L0 contracts: round-trip fidelity, pure-in-(seed, step) batches
(the checkpoint-resume exact-replay property), full per-epoch coverage
under the affine-permutation order, train/val disjointness, and the
driver integration (--data-dir streams what --text loaded whole).
"""

import json

import numpy as np
import pytest

from shallowspeed_tpu.data.token_shards import (TokenShards, ValSplit,
                                                build_shards)


def corpus(n=10_000, vocab=256, seed=0):
    return np.random.default_rng(seed).integers(0, vocab, n)


def test_build_load_roundtrip(tmp_path):
    toks = corpus(5_000)
    build_shards(toks, tmp_path, vocab=256, shard_tokens=1_024)
    ds = TokenShards(tmp_path, seq_len=64)
    assert ds.vocab == 256 and not ds.has_val
    # every stored token equals the source at its shard offset
    idx = json.loads((tmp_path / "index.json").read_text())
    assert sum(idx["shard_tokens"]) == 5_000
    assert len(idx["shard_tokens"]) == 5  # ceil(5000/1024) with tail
    w = ds._window(0)
    np.testing.assert_array_equal(w, toks[:65])


def test_batch_pure_in_seed_and_step(tmp_path):
    """The exact-replay property: a fresh process (new TokenShards
    instance) replays the identical stream from any step — resume
    mid-epoch needs no state beyond the step counter."""
    build_shards(corpus(), tmp_path, vocab=256, shard_tokens=2_048)
    a = TokenShards(tmp_path, seq_len=32)
    run1 = [a.batch(s, 4, seed=7) for s in range(10)]
    b = TokenShards(tmp_path, seq_len=32)  # "restarted process"
    for s in range(5, 10):
        t1, g1 = run1[s]
        t2, g2 = b.batch(s, 4, seed=7)
        np.testing.assert_array_equal(t1, t2)
        np.testing.assert_array_equal(g1, g2)
    # different seed, different stream
    assert not np.array_equal(run1[0][0], b.batch(0, 4, seed=8)[0])


def test_targets_shift_by_one(tmp_path):
    build_shards(corpus(), tmp_path, vocab=256)
    ds = TokenShards(tmp_path, seq_len=16)
    tok, tgt = ds.batch(3, 4, seed=1)
    np.testing.assert_array_equal(tok[:, 1:], tgt[:, :-1])


@pytest.mark.parametrize("n_tokens,shard_tokens", [(4_000, 1_000),
                                                   (3_301, 700)])
def test_perm_order_covers_every_window_once(tmp_path, n_tokens,
                                             shard_tokens):
    """One epoch of the affine-permutation order touches every window
    exactly once (coverage the i.i.d. sampler can't promise), including
    non-divisible shard tails."""
    toks = corpus(n_tokens)
    build_shards(toks, tmp_path, vocab=256, shard_tokens=shard_tokens)
    ds = TokenShards(tmp_path, seq_len=32)
    n = ds.n_windows
    seen = set()
    bsz = 2
    for step in range((n + bsz - 1) // bsz):
        tok, _ = ds.batch(step, bsz, seed=3)
        for row in tok:
            seen.add(row.tobytes())
    assert len(seen) >= n - (bsz - 1)  # epoch 2 may repeat the tail row
    # second epoch uses a different permutation but the same window set
    all_windows = {ds._window(w)[:32].tobytes() for w in range(n)}
    assert seen <= all_windows


def test_val_split_disjoint_and_held_out(tmp_path):
    toks = corpus(8_000)
    build_shards(toks, tmp_path, vocab=256, shard_tokens=2_048,
                 val_fraction=0.2)
    ds = TokenShards(tmp_path, seq_len=32)
    assert ds.has_val
    # val IS the corpus tail; train shards hold only the head
    idx = json.loads((tmp_path / "index.json").read_text())
    assert idx["val_tokens"] == 1_600
    assert sum(idx["shard_tokens"]) == 6_400
    vt, vg = ValSplit(ds).batch(0, 4, seed=2)
    tail = toks[-1_600:]
    # every val row appears in the tail stream
    joined = tail.astype(np.int32).tobytes()
    for row in vt:
        assert row.astype(np.int32).tobytes() in joined
    # determinism
    vt2, _ = ValSplit(ds).batch(0, 4, seed=2)
    np.testing.assert_array_equal(vt, vt2)


def test_large_vocab_uses_uint32(tmp_path):
    toks = np.array([0, 1, 70_000, 2, 3] * 100)
    build_shards(toks, tmp_path, vocab=100_000)
    idx = json.loads((tmp_path / "index.json").read_text())
    assert idx["dtype"] == "uint32"
    ds = TokenShards(tmp_path, seq_len=4)
    tok, _ = ds.batch(0, 2, seed=0)
    assert tok.dtype == np.int32


def test_no_full_window_rejected(tmp_path):
    build_shards(corpus(100), tmp_path, vocab=256)
    with pytest.raises(AssertionError, match="window"):
        TokenShards(tmp_path, seq_len=256)


# ---------------------------------------------------- driver integration


def test_train_lm_streams_from_shards(tmp_path):
    """--data-dir end-to-end: the driver trains off the shard stream
    (vocab from the index), validates from val.bin, and a resumed run
    continues the exact batch stream (same step -> same windows)."""
    from train_lm import make_batch, parse_args, prepare_text

    rng = np.random.default_rng(0)
    text = (tmp_path / "c.txt")
    text.write_bytes(bytes(rng.integers(32, 127, 20_000).tolist()))
    toks = np.frombuffer(text.read_bytes(), np.uint8).astype(np.int32)
    build_shards(toks, tmp_path / "shards", vocab=256,
                 shard_tokens=4_096, val_fraction=0.1)

    args = parse_args(["--data-dir", str(tmp_path / "shards"),
                       "--seq-len", "32", "--batch-size", "4",
                       "--val-every", "5", "--steps", "4"])
    vocab, tok, data, val = prepare_text(args)
    assert vocab == 256 and val is not None
    t1, g1 = make_batch(args, vocab, 7, data)
    t2, g2 = make_batch(args, vocab, 7, data)  # replay
    np.testing.assert_array_equal(t1, t2)
    np.testing.assert_array_equal(t1[:, 1:], g1[:, :-1])
    v1, _ = make_batch(args, vocab, 10**9 + 3, val)
    v2, _ = make_batch(args, vocab, 10**9 + 3, val)
    np.testing.assert_array_equal(v1, v2)
    # train and val windows come from disjoint corpus regions
    head = toks[:18_000].tobytes()
    assert t1[0].tobytes() in head
    assert v1[0].tobytes() in toks[18_000:].tobytes()


def test_single_window_corpus_batches(tmp_path):
    """n_windows == 1 must batch (the trivial permutation), not crash
    in the permutation draw."""
    build_shards(corpus(40), tmp_path, vocab=256)
    ds = TokenShards(tmp_path, seq_len=32)
    assert ds.n_windows == 1
    tok, tgt = ds.batch(0, 3, seed=0)
    assert tok.shape == (3, 32)
    np.testing.assert_array_equal(tok[0], tok[1])  # only one window


def test_driver_rejects_bpe_against_byte_shards(tmp_path):
    from train_lm import parse_args, prepare_text

    build_shards(corpus(5_000), tmp_path / "s", vocab=256)
    args = parse_args(["--data-dir", str(tmp_path / "s"),
                       "--seq-len", "32", "--tokenizer", "bpe"])
    with pytest.raises(SystemExit, match="tokenizer.json"):
        prepare_text(args)


def test_driver_rejects_undersized_val_split(tmp_path):
    from train_lm import parse_args, prepare_text

    build_shards(corpus(5_000), tmp_path / "s", vocab=256,
                 val_fraction=0.004)  # 20 tokens of val
    args = parse_args(["--data-dir", str(tmp_path / "s"),
                       "--seq-len", "32", "--val-every", "5"])
    with pytest.raises(SystemExit, match="val.bin holds"):
        prepare_text(args)
