"""L0 data tests — ports `/root/reference/tests/test_dataset.py` (strided
shard arithmetic, dtype) without its downloaded-file dependency: a session
fixture prepares a small deterministic dataset on disk, and additional tests
pin the equivalence properties the reference only documents (equal μbatches
across DP layouts; batch == concat of its μbatches).
"""

import numpy as np
import pytest

from shallowspeed_tpu.data.dataset import Dataset
from shallowspeed_tpu.data.mnist import synthesize_mnist, prepare_mnist


@pytest.fixture(scope="session")
def data_dir(tmp_path_factory, monkeypatch_session=None):
    d = tmp_path_factory.mktemp("mnist")
    prepare_mnist(d, synthetic=True, n_samples=4000)
    return d


def test_synthetic_generator_shapes():
    x, y = synthesize_mnist(n_samples=256)
    assert x.shape == (256, 784) and y.shape == (256, 10)
    assert x.dtype == np.float32 and y.dtype == np.float32
    np.testing.assert_allclose(y.sum(axis=1), 1.0)
    # deterministic
    x2, _ = synthesize_mnist(n_samples=256)
    np.testing.assert_array_equal(x, x2)


def test_strided_shard_arithmetic(data_dir):
    # Mirrors reference `test_dataset.py:9-18`: rank 1 of 4, check shard
    # length arithmetic and dtype.
    ds = Dataset(data_dir, global_batch_size=128, mubatch_size=16)
    ds.load(DP_rank=1, DP_size=4)
    n_train = 4000 - int(4000 * 0.15)
    full = n_train - (n_train % 128)
    assert len(ds) == full // 4
    assert ds.input_X.dtype == np.float32
    assert ds.input_X.flags["C_CONTIGUOUS"]  # the perf-critical .copy()
    assert ds.get_num_mubatches() == 32 // 16
    assert ds.get_num_batches() == len(ds) // 32


def test_mubatch_equivalence_across_dp(data_dir):
    """Union of all DP ranks' batch samples == the serial batch's samples —
    the equivalence the reference's docstring asks tests for
    (`dataset.py:13`)."""
    serial = Dataset(data_dir, 64, 64).load(0, 1)
    shards = [Dataset(data_dir, 64, 16).load(r, 4) for r in range(4)]
    batch = serial.load_micro_batch_input(0, 0)
    got = np.concatenate([s.load_micro_batch_input(0, 0) for s in shards])
    # strided sharding interleaves; compare as sets of rows via sorting
    np.testing.assert_allclose(
        np.sort(batch.sum(axis=1)), np.sort(got.sum(axis=1)), rtol=1e-6
    )


def test_batch_equals_concat_of_mubatches(data_dir):
    ds = Dataset(data_dir, 128, 16).load(0, 1)
    x, y = ds.load_batch(2)
    mus = [ds.load_micro_batch_input(2, m) for m in range(ds.get_num_mubatches())]
    np.testing.assert_array_equal(x, np.concatenate(mus))
    xs, ys = ds.load_mubatch_stack(2)
    assert xs.shape == (8, 16, 784) and ys.shape == (8, 16, 10)
    np.testing.assert_array_equal(xs.reshape(-1, 784), x)


def test_divisibility_asserts(data_dir):
    with pytest.raises(AssertionError):
        Dataset(data_dir, 128, 48).load(0, 1)  # μbs doesn't divide local bs
    with pytest.raises(AssertionError):
        Dataset(data_dir, 128, 16).load(0, 3)  # DP doesn't divide global bs
