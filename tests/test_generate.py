"""KV-cache decoding (`models/generate.py`).

The load-bearing invariant: cached single-token decoding must reproduce
the batched training forward's logits at every position — cache writes,
position masking, and the f32 score path all have to agree with
`ops/attention.py` for that to hold.
"""

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.models.generate import (
    decode_step, generate, init_kv_cache, prefill)

CFG = T.TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                          max_seq=32)
MOE_CFG = replace(CFG, n_experts=4, moe_top_k=2)


def toks(seed=0, b=2, t=12, vocab=64):
    rng = np.random.default_rng(seed)
    return rng.integers(0, vocab, (b, t)).astype(np.int32)


@pytest.mark.parametrize("cfg", [CFG, MOE_CFG], ids=["dense", "moe"])
def test_cached_decode_matches_batched_forward(cfg):
    """prefill(prompt[:1]) + decode steps over the rest == forward logits
    at every position."""
    params = T.init(cfg, seed=1)
    tokens = toks(0, b=2, t=10)
    ref = np.asarray(T.forward(params, tokens, cfg))       # (B, T, V)

    cache = init_kv_cache(cfg, 2)
    logits, cache = prefill(params, tokens[:, :1], cfg, cache)
    np.testing.assert_allclose(np.asarray(logits), ref[:, 0],
                               rtol=1e-4, atol=1e-5)
    for pos in range(1, tokens.shape[1]):
        logits, cache = decode_step(params, jnp.asarray(tokens[:, pos]),
                                    pos, cache, cfg)
        np.testing.assert_allclose(np.asarray(logits), ref[:, pos],
                                   rtol=1e-4, atol=1e-5, err_msg=str(pos))


def test_prefill_matches_forward_last_position():
    params = T.init(CFG, seed=2)
    tokens = toks(1, b=3, t=7)
    ref = np.asarray(T.forward(params, tokens, CFG))[:, -1]
    logits, _ = prefill(params, tokens, CFG, init_kv_cache(CFG, 3))
    np.testing.assert_allclose(np.asarray(logits), ref, rtol=1e-4,
                               atol=1e-5)


def test_greedy_generation_deterministic():
    params = T.init(CFG, seed=3)
    prompt = toks(2, b=2, t=4)
    a = np.asarray(generate(params, prompt, CFG, 8, temperature=0.0))
    b = np.asarray(generate(params, prompt, CFG, 8, temperature=0.0))
    assert a.shape == (2, 8)
    assert a.dtype == np.int32
    assert (a >= 0).all() and (a < CFG.vocab).all()
    np.testing.assert_array_equal(a, b)


def test_greedy_equals_stepwise_argmax():
    """Greedy generate must equal manually feeding argmax tokens through
    the batched forward — end-to-end decode-vs-forward agreement."""
    params = T.init(CFG, seed=4)
    prompt = toks(3, b=1, t=4)
    out = np.asarray(generate(params, prompt, CFG, 6, temperature=0.0))
    seq = prompt.copy()
    for i in range(6):
        logits = np.asarray(T.forward(params, seq, CFG))[:, -1]
        nxt = logits.argmax(-1).astype(np.int32)
        assert nxt[0] == out[0, i], (i, nxt, out)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)


def test_sampling_seeded_and_varied():
    params = T.init(CFG, seed=5)
    prompt = toks(4, b=2, t=4)
    a = np.asarray(generate(params, prompt, CFG, 16, temperature=1.0,
                            seed=7))
    b = np.asarray(generate(params, prompt, CFG, 16, temperature=1.0,
                            seed=7))
    c = np.asarray(generate(params, prompt, CFG, 16, temperature=1.0,
                            seed=8))
    np.testing.assert_array_equal(a, b)
    assert (a != c).any()  # different seed, different stream


def test_top_k_restricts_support():
    """With top_k=1, sampling must equal greedy regardless of temperature."""
    params = T.init(CFG, seed=6)
    prompt = toks(5, b=2, t=4)
    greedy = np.asarray(generate(params, prompt, CFG, 8, temperature=0.0))
    k1 = np.asarray(generate(params, prompt, CFG, 8, temperature=2.0,
                             top_k=1, seed=3))
    np.testing.assert_array_equal(k1, greedy)


def test_bf16_generation_runs():
    cfg16 = replace(CFG, compute_dtype=jnp.bfloat16)
    params = T.init(CFG, seed=7)
    prompt = toks(6, b=2, t=4)
    out = np.asarray(generate(params, prompt, cfg16, 8, temperature=0.0))
    assert out.shape == (2, 8)
    assert (out >= 0).all() and (out < CFG.vocab).all()


def test_prompt_overflow_rejected():
    params = T.init(CFG, seed=8)
    with pytest.raises(AssertionError, match="max_seq"):
        generate(params, toks(0, b=1, t=30), CFG, 8)


# ------------------------------------------------------- nucleus sampling


def test_top_p_restricts_support():
    """With a peaked distribution and small p only the top token
    survives; with p=0 (off) sampling matches the unfiltered path."""
    from shallowspeed_tpu.models.generate import _sample

    rng = jax.random.PRNGKey(0)
    logits = jnp.log(jnp.asarray(
        [[0.6, 0.25, 0.1, 0.05]], jnp.float32))
    for i in range(8):
        tok = _sample(logits, jax.random.fold_in(rng, i), 1.0, 0,
                      top_p=0.5)
        assert int(tok[0]) == 0, int(tok[0])
    # p=0.7: mass-before test keeps {0.6, 0.25}; token 2/3 never drawn
    seen = {int(_sample(logits, jax.random.fold_in(rng, i), 1.0, 0,
                        top_p=0.7)[0]) for i in range(64)}
    assert seen <= {0, 1}, seen
    off = {int(_sample(logits, jax.random.fold_in(rng, i), 1.0, 0,
                       top_p=0.0)[0]) for i in range(256)}
    assert off == {0, 1, 2, 3}, off


def test_top_p_generate_deterministic():
    params = jax.device_put(T.init(CFG, seed=0))
    prompt = np.array([[3, 1, 4]], np.int32)
    a = generate(params, prompt, CFG, max_new=8, temperature=1.0,
                 top_p=0.9, seed=5)
    b = generate(params, prompt, CFG, max_new=8, temperature=1.0,
                 top_p=0.9, seed=5)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_decode_scan_traces_once(monkeypatch):
    """The decode loop is a lax.scan over a once-traced body — NOT an
    unrolled per-token retrace. Guard: the number of `decode_step` traces
    during a 16-token generation stays far below the token count, and a
    SECOND generation with the same shapes adds zero traces (the jit
    cache holds)."""
    import shallowspeed_tpu.models.generate as G

    calls = {"n": 0}
    real = G.decode_step

    def counting(*a, **k):
        calls["n"] += 1
        return real(*a, **k)

    monkeypatch.setattr(G, "decode_step", counting)
    params = jax.device_put(T.init(CFG, seed=0))
    out = G.generate(params, toks(t=8), CFG, 16, temperature=0.0)
    jax.block_until_ready(out)
    first = calls["n"]
    assert 1 <= first <= 4, (
        f"decode_step traced {first} times for 16 tokens — the scan "
        f"body is being unrolled or retraced per token")
    out2 = G.generate(params, toks(seed=1, t=8), CFG, 16, temperature=0.0)
    jax.block_until_ready(out2)
    assert calls["n"] == first, "same-shape generation retraced the scan"


def test_generate_with_sharded_params():
    """Distributed inference falls out of the design: `generate` is one
    jitted program, so GSPMD propagates a TP/FSDP engine's parameter
    shardings through prefill, the cache, and the decode scan — greedy
    outputs must match the replicated-params decode token for token."""
    from jax.sharding import Mesh

    from shallowspeed_tpu.optim import SGD
    from shallowspeed_tpu.parallel.fsdp import FSDPEngine
    from shallowspeed_tpu.parallel.tensor import TensorParallelEngine

    prompt = toks(seed=3, b=2, t=8)
    ref = np.asarray(generate(jax.device_put(T.init(CFG, seed=0)), prompt,
                              CFG, 8, temperature=0.0))

    tp = TensorParallelEngine(
        CFG, SGD(0.1),
        Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp")),
        seed=0)
    np.testing.assert_array_equal(
        np.asarray(generate(tp.params, prompt, CFG, 8, temperature=0.0)),
        ref)

    fsdp = FSDPEngine(
        CFG, SGD(0.1),
        Mesh(np.array(jax.devices()[:4]).reshape(4), ("dp",)), seed=0)
    np.testing.assert_array_equal(
        np.asarray(generate(fsdp.params, prompt, CFG, 8,
                            temperature=0.0)),
        ref)


# --------------------------------------------- pipelined (pp-sharded) decode


def test_pipeline_generate_token_exact():
    """Decode ON pp-sharded params (no re-gather): the pp-phase
    prefill + ppermute token loop must reproduce the replicated
    `generate` stream token-for-token — greedy AND sampled (same
    key derivation)."""
    import jax as _jax
    from jax.sharding import Mesh as _Mesh

    from shallowspeed_tpu.optim import SGD
    from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine

    cfg = T.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                              n_layers=4, max_seq=48, rope=True,
                              norm="rmsnorm", ffn="swiglu")
    eng = PipelineLMEngine(
        cfg, SGD(0.1),
        _Mesh(np.array(_jax.devices()[:4]).reshape(1, 4), ("dp", "pp")),
        n_mubatches=1, seed=3)
    params = eng.get_canonical_params()
    prompt = toks(5, b=2, t=12, vocab=64)
    for kwargs in ({"temperature": 0.0},
                   {"temperature": 1.0, "top_k": 8, "seed": 7}):
        ref = np.asarray(generate(params, prompt, cfg, 10, **kwargs))
        out = eng.generate(prompt, 10, **kwargs)
        np.testing.assert_array_equal(out, ref, err_msg=str(kwargs))


def test_pipeline_generate_dp_rows():
    """dp>1: batch rows shard over dp and decode independently;
    greedy equals the replicated decode row-for-row."""
    import jax as _jax
    from jax.sharding import Mesh as _Mesh

    from shallowspeed_tpu.optim import SGD
    from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine

    eng = PipelineLMEngine(
        CFG, SGD(0.1),
        _Mesh(np.array(_jax.devices()[:4]).reshape(2, 2), ("dp", "pp")),
        n_mubatches=1, seed=3)
    params = eng.get_canonical_params()
    prompt = toks(9, b=4, t=8)
    ref = np.asarray(generate(params, prompt, CFG, 8, temperature=0.0))
    out = eng.generate(prompt, 8, temperature=0.0)
    np.testing.assert_array_equal(out, ref)


def test_pipeline_generate_dp_sampled_decorrelated():
    """dp>1 sampling (ADVICE r3): each dp shard folds its coordinate
    into the sampling key, so two IDENTICAL prompt rows placed on
    DIFFERENT dp shards must not draw the same gumbel noise stream.
    (With an unfolded key, row r of every shard sampled identically.)"""
    import jax as _jax
    from jax.sharding import Mesh as _Mesh

    from shallowspeed_tpu.optim import SGD
    from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine

    eng = PipelineLMEngine(
        CFG, SGD(0.1),
        _Mesh(np.array(_jax.devices()[:2]).reshape(2, 1), ("dp", "pp")),
        n_mubatches=1, seed=3)
    row = toks(11, b=1, t=8)
    prompt = np.concatenate([row, row], axis=0)  # same row on both shards
    out = eng.generate(prompt, 24, temperature=1.0, seed=5)
    assert not np.array_equal(out[0], out[1]), (
        "dp shards drew correlated sampling noise")
    # greedy remains row-identical (deterministic, key-independent)
    g = eng.generate(prompt, 8, temperature=0.0)
    np.testing.assert_array_equal(g[0], g[1])


def test_pipeline_generate_vpp_token_exact():
    """virtual_pp > 1 decode ON the interleave-permuted pp-sharded
    params (round 5 — the round-4 guard replaced): the pp*vpp-phase
    chain visits chunks in LOGICAL order (stage l = v*pp + d puts
    consecutive stages one hop right), so the stream must equal the
    replicated decode token-for-token — greedy and sampled."""
    import jax as _jax
    from jax.sharding import Mesh as _Mesh

    from shallowspeed_tpu.optim import SGD
    from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine

    cfg = T.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                              n_layers=8, max_seq=48, rope=True,
                              norm="rmsnorm", ffn="swiglu")
    eng = PipelineLMEngine(
        cfg, SGD(0.1),
        _Mesh(np.array(_jax.devices()[:2]).reshape(1, 2), ("dp", "pp")),
        n_mubatches=1, seed=3, virtual_pp=2)
    params = eng.get_canonical_params()
    prompt = toks(5, b=2, t=12, vocab=64)
    for kwargs in ({"temperature": 0.0},
                   {"temperature": 1.0, "top_k": 8, "seed": 7}):
        ref = np.asarray(generate(params, prompt, cfg, 10, **kwargs))
        out = eng.generate(prompt, 10, **kwargs)
        np.testing.assert_array_equal(out, ref, err_msg=str(kwargs))


def test_pipeline_generate_vpp_dp_greedy():
    """vpp x dp decode: rows shard over dp, chunks over pp*vpp phases;
    greedy equals the replicated stream row-for-row."""
    import jax as _jax
    from jax.sharding import Mesh as _Mesh

    from shallowspeed_tpu.optim import SGD
    from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine

    cfg = T.TransformerConfig(vocab=32, d_model=32, n_heads=4,
                              n_layers=8, max_seq=32)
    eng = PipelineLMEngine(
        cfg, SGD(0.1),
        _Mesh(np.array(_jax.devices()[:4]).reshape(2, 2), ("dp", "pp")),
        n_mubatches=1, seed=3, virtual_pp=2)
    params = eng.get_canonical_params()
    prompt = toks(9, b=4, t=8, vocab=32)
    ref = np.asarray(generate(params, prompt, cfg, 8, temperature=0.0))
    out = eng.generate(prompt, 8, temperature=0.0)
    np.testing.assert_array_equal(out, ref)


# --------------------------- prompt bucketing / cache sizing (round 4)


def test_prompt_bucket_no_retrace(monkeypatch):
    """Compile hygiene: prompts of DIFFERENT lengths within one 64-token
    bucket share one executable (the true length is traced, not
    baked) — previously every (prompt_len, max_new, sampler) tuple
    recompiled. Streams must stay exact: the bucketed result equals
    decoding the same prompt under a different same-bucket length
    context, and greedy continuation of a longer prompt that shares a
    prefix diverges only where the prompts do."""
    import shallowspeed_tpu.models.generate as G

    calls = {"n": 0}
    real = G.prefill

    def counting(*a, **k):
        calls["n"] += 1
        return real(*a, **k)

    monkeypatch.setattr(G, "prefill", counting)
    params = jax.device_put(T.init(CFG, seed=0))
    outs = {}
    for tp in (5, 9, 23):  # all in the 64-bucket
        outs[tp] = np.asarray(G.generate(params, toks(seed=2, t=tp),
                                         CFG, 8, temperature=0.0))
        assert outs[tp].shape == (2, 8)
    # <= 1: another test may have warmed the jit cache for this exact
    # (bucket, max_new, sampler) signature already — 0 traces then
    assert calls["n"] <= 1, (
        f"prefill traced {calls['n']} times across same-bucket prompt "
        f"lengths — the bucket is not sharing executables")


def test_bucketed_stream_matches_exact_length():
    """The pad-and-trace path must be a pure compile-strategy change:
    the public (bucketed) generate's tokens equal a direct
    `_generate_padded` call with NO padding (tp_b == tp, cache sized
    tp+max_new) — greedy and sampled."""
    import jax.numpy as _jnp

    from shallowspeed_tpu.models.generate import _generate_padded

    cfg = replace(CFG, max_seq=32)
    params = jax.device_put(T.init(cfg, seed=0))
    tp = 11
    prompt = toks(seed=4, t=tp, vocab=cfg.vocab)
    # public path pads 11 -> bucket capped at max_seq - max_new = 24
    for kwargs in ({"temperature": 0.0},
                   {"temperature": 1.0, "top_k": 8, "seed": 3}):
        out_pub = np.asarray(generate(params, prompt, cfg, 8, **kwargs))
        out_raw = np.asarray(_generate_padded(
            params, jax.numpy.asarray(prompt), _jnp.int32(tp), cfg, 8,
            kwargs.get("temperature", 0.0), kwargs.get("top_k", 0),
            0.0, kwargs.get("seed", 0), cache_len=tp + 8))
        np.testing.assert_array_equal(out_pub, out_raw,
                                      err_msg=str(kwargs))


def test_kv_cache_sized_to_generation():
    """init_kv_cache takes the sized length; generate's cache never
    exceeds bucket + max_new slots even when max_seq is huge."""
    from shallowspeed_tpu.models.generate import (init_kv_cache,
                                                  prompt_bucket_len)

    cfg = replace(CFG, max_seq=4096)
    cache = init_kv_cache(cfg, 2, cache_len=96)
    assert cache[0]["k"].shape[2] == 96  # head-major: slots on axis 2
    assert prompt_bucket_len(5, 32, 4096) == 64
    assert prompt_bucket_len(65, 32, 4096) == 128
    assert prompt_bucket_len(5, 4090, 4096) == 6   # capped by max_seq
    assert prompt_bucket_len(64, 32, 4096) == 64   # exact bucket edge


# ------------------------------------------- int8 KV cache (round 5)


def test_int8_kv_decode_close_to_bf16():
    """Quantized-cache decode logits track the full-precision cache
    within the absmax-int8 error envelope at every position (the cache
    is the ONLY thing that changed)."""
    from shallowspeed_tpu.models.generate import (decode_step,
                                                  init_kv_cache,
                                                  prefill)

    params = T.init(CFG, seed=1)
    tokens = toks(0, b=2, t=10)
    cache_f = init_kv_cache(CFG, 2)
    cache_q = init_kv_cache(CFG, 2, kv_quant="int8")
    lf, cache_f = prefill(params, tokens[:, :1], CFG, cache_f)
    lq, cache_q = prefill(params, tokens[:, :1], CFG, cache_q)
    np.testing.assert_allclose(np.asarray(lq), np.asarray(lf),
                               rtol=0.05, atol=0.05)
    for pos in range(1, tokens.shape[1]):
        lf, cache_f = decode_step(params, jnp.asarray(tokens[:, pos]),
                                  pos, cache_f, CFG)
        lq, cache_q = decode_step(params, jnp.asarray(tokens[:, pos]),
                                  pos, cache_q, CFG)
        np.testing.assert_allclose(np.asarray(lq), np.asarray(lf),
                                   rtol=0.05, atol=0.05,
                                   err_msg=str(pos))


def test_int8_kv_cache_layout_and_memory():
    from shallowspeed_tpu.models.generate import init_kv_cache

    # realistic head_dim (the scale is 4 bytes PER HEAD-ROW, so the
    # ~2x byte saving needs hd >> 4; tiny test dims would hide it)
    cfg = replace(CFG, d_model=256, n_heads=4)  # hd = 64
    cache = init_kv_cache(cfg, 2, cache_len=16, kv_quant="int8")
    blk = cache[0]
    assert blk["k"].dtype == jnp.int8 and blk["v"].dtype == jnp.int8
    assert blk["k_s"].shape == (2, cfg.n_heads, 16, 1)
    q_bytes = sum(np.prod(v.shape) * v.dtype.itemsize
                  for v in blk.values())
    f_bytes = 2 * np.prod((2, 16, cfg.n_heads,
                           cfg.head_dim)) * 2  # k+v bf16
    assert q_bytes < 0.6 * f_bytes, (q_bytes, f_bytes)


def test_int8_kv_generate_runs_and_is_deterministic():
    params = T.init(CFG, seed=3)
    prompt = toks(2, b=2, t=6)
    a = np.asarray(generate(params, prompt, CFG, 8, temperature=0.0,
                            kv_quant="int8"))
    b = np.asarray(generate(params, prompt, CFG, 8, temperature=0.0,
                            kv_quant="int8"))
    np.testing.assert_array_equal(a, b)
    assert (a >= 0).all() and (a < CFG.vocab).all()


def test_flash_prefill_matches_xla_prefill():
    """The long-prompt prefill path (attn_impl='flash' — auto past 2048
    tokens, where XLA's (B,H,Tp,Tp) f32 scores OOM) must produce the
    same logits and the same cache contents as the XLA path."""
    from shallowspeed_tpu.models.generate import init_kv_cache, prefill

    cfg = replace(CFG, max_seq=64)
    params = T.init(cfg, seed=2)
    tokens = toks(1, b=2, t=32)
    lx, cx = prefill(params, tokens, cfg, init_kv_cache(cfg, 2))
    lf, cf = prefill(params, tokens, cfg, init_kv_cache(cfg, 2),
                     attn_impl="flash")
    np.testing.assert_allclose(np.asarray(lf), np.asarray(lx),
                               rtol=1e-4, atol=1e-4)
    for bx, bf in zip(cx, cf):
        np.testing.assert_allclose(np.asarray(bf["k"]),
                                   np.asarray(bx["k"]), rtol=1e-5,
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(bf["v"]),
                                   np.asarray(bx["v"]), rtol=1e-5,
                                   atol=1e-5)


# ------------------------------------- decode HBM roofline (round 9)


@pytest.mark.parametrize("kv_quant", ["", "int8"], ids=["bf16", "int8"])
def test_decode_bytes_per_token_matches_walker_count(kv_quant):
    """The analytic bytes-per-token model behind the decode progress
    line equals the traced decode program's own input-buffer bytes
    (analysis/walker.aval_bytes over the jaxpr invars) — the model
    cannot drift from what the program actually sweeps."""
    from shallowspeed_tpu.analysis.walker import aval_bytes
    from shallowspeed_tpu.models.generate import (
        decode_read_bytes_per_token, decode_write_bytes_per_token)

    cfg = CFG
    b, cache_len = 2, 24
    params = T.cast_params(T.init(cfg, seed=0), cfg.compute_dtype)
    cache = init_kv_cache(cfg, b, cache_len, kv_quant)
    tok = np.zeros((b,), np.int32)

    closed = jax.make_jaxpr(
        lambda p, t, c: decode_step(p, t, 5, c, cfg))(params, tok, cache)
    invar_bytes = sum(aval_bytes(v.aval) for v in closed.jaxpr.invars)
    model = decode_read_bytes_per_token(params, cfg, b, cache_len,
                                        kv_quant)
    assert model == invar_bytes, (model, invar_bytes)
    # writes are the one-token cache update + the logits row —
    # O(1/cache_len) of the read sweep
    w = decode_write_bytes_per_token(cfg, b, kv_quant)
    assert 0 < w < model


def test_decode_report_fields_and_cpu_roofline_none():
    from shallowspeed_tpu.models.generate import decode_report

    params = T.init(CFG, seed=0)
    rep = decode_report(params, CFG, batch=2, cache_len=24,
                        n_tokens=8, seconds=0.5)
    assert rep["tokens_per_sec"] == pytest.approx(2 * 8 / 0.5)
    assert rep["steps_per_sec"] == pytest.approx(16.0)
    assert rep["bytes_per_token"] > 0
    assert rep["hbm_gbps"] == pytest.approx(
        16.0 * rep["bytes_per_token"] / 1e9, abs=1e-4)
    # CPU test mesh: no published HBM peak -> no invented utilization
    assert rep["hbm_peak_gbps"] is None and rep["hbm_util"] is None
