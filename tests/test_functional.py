"""L1 ops contract tests — the porting contract from the reference.

Ports the reference's finite-difference validation strategy
(`/root/reference/tests/test_functional.py`: central differences with EPS,
shape contracts, softmax shift-invariance, MSE values) and strengthens it:
every hand-written gradient is ALSO cross-checked against `jax.vjp` of the
forward function, which is exact to float rounding.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shallowspeed_tpu.ops import functional as F

EPS = 1e-3  # float32 central differences
RNG = np.random.default_rng(0)


def rand(*shape):
    return jnp.asarray(RNG.normal(size=shape).astype(np.float32))


def central_diff(f, x, dout, eps=EPS):
    """Numerical VJP: sum(dout * df/dx_i) for each i, via central differences."""
    x = np.asarray(x, dtype=np.float64)
    dout = np.asarray(dout, dtype=np.float64)
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    for _ in it:
        idx = it.multi_index
        xp, xm = x.copy(), x.copy()
        xp[idx] += eps
        xm[idx] -= eps
        fp = np.asarray(f(jnp.asarray(xp, jnp.float32)), dtype=np.float64)
        fm = np.asarray(f(jnp.asarray(xm, jnp.float32)), dtype=np.float64)
        g[idx] = ((fp - fm) / (2 * eps) * dout).sum()
    return g


# ---------------------------------------------------------------- shapes


def test_shapes():
    x = rand(4, 7)
    w = rand(5, 7)
    b = rand(1, 5)
    assert F.relu(x).shape == x.shape
    assert F.linear(x, w, b).shape == (4, 5)
    dx, dw, db = F.linear_grad(rand(4, 5), x, w)
    assert dx.shape == x.shape and dw.shape == w.shape and db.shape == b.shape
    assert F.softmax(x).shape == x.shape
    assert F.mse_loss(x, x, 4).shape == ()


# ---------------------------------------------------------------- relu


def test_relu_values():
    x = jnp.array([[-1.0, 0.0, 2.5]])
    np.testing.assert_allclose(F.relu(x), [[0.0, 0.0, 2.5]])


def test_relu_grad_matches_fd():
    x = rand(3, 4)
    dout = rand(3, 4)
    got = F.relu_grad(dout, x > 0)
    want = central_diff(F.relu, x, dout)
    np.testing.assert_allclose(got, want, atol=1e-4)


# ---------------------------------------------------------------- linear


def test_linear_grad_matches_fd():
    x, w, b = rand(3, 4), rand(5, 4), rand(1, 5)
    dout = rand(3, 5)
    dx, dw, db = F.linear_grad(dout, x, w)
    np.testing.assert_allclose(
        dx, central_diff(lambda v: F.linear(v, w, b), x, dout), atol=1e-3
    )
    np.testing.assert_allclose(
        dw, central_diff(lambda v: F.linear(x, v, b), w, dout), atol=1e-3
    )
    np.testing.assert_allclose(
        db, central_diff(lambda v: F.linear(x, w, v), b, dout), atol=1e-3
    )


def test_linear_grad_matches_vjp():
    x, w, b = rand(3, 4), rand(5, 4), rand(1, 5)
    dout = rand(3, 5)
    _, vjp = jax.vjp(F.linear, x, w, b)
    vdx, vdw, vdb = vjp(dout)
    dx, dw, db = F.linear_grad(dout, x, w)
    np.testing.assert_allclose(dx, vdx, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(dw, vdw, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(db, vdb, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------- softmax


def test_softmax_rows_sum_to_one():
    p = F.softmax(rand(6, 10))
    np.testing.assert_allclose(p.sum(axis=1), np.ones(6), atol=1e-5)
    assert bool((p >= 0).all())


def test_softmax_shift_invariance():
    # Reference property test (`test_functional.py:116-122`).
    x = rand(4, 9)
    np.testing.assert_allclose(F.softmax(x), F.softmax(x + 100.0), atol=1e-5)


def test_softmax_grad_matches_fd():
    x = rand(3, 5)
    dout = rand(3, 5)
    got = F.softmax_grad(dout, x)
    want = central_diff(F.softmax, x, dout)
    np.testing.assert_allclose(got, want, atol=1e-3)


def test_softmax_grad_matches_vjp():
    x = rand(3, 5)
    dout = rand(3, 5)
    _, vjp = jax.vjp(F.softmax, x)
    (want,) = vjp(dout)
    np.testing.assert_allclose(F.softmax_grad(dout, x), want, rtol=1e-4, atol=1e-6)


# ---------------------------------------------------------------- mse


def test_mse_loss_value():
    pred = jnp.array([[1.0, 2.0], [3.0, 4.0]])
    target = jnp.array([[1.0, 0.0], [0.0, 4.0]])
    # sum of squared errors = 4 + 9 = 13, over batch_size 2
    np.testing.assert_allclose(F.mse_loss(pred, target, 2), 13.0 / 2)


def test_mse_loss_grad_matches_vjp():
    pred, target = rand(4, 3), rand(4, 3)
    got = F.mse_loss_grad(pred, target, 8)
    _, vjp = jax.vjp(lambda p: F.mse_loss(p, target, 8), pred)
    (want,) = vjp(jnp.float32(1.0))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_mse_global_batch_scaling_invariant():
    """Sum of per-microbatch grads (each scaled by GLOBAL bs) equals the
    full-batch grad — the invariant that makes DP+μbatching exact
    (reference `functional.py:43-44` + SURVEY §3.5)."""
    pred, target = rand(8, 3), rand(8, 3)
    full = F.mse_loss_grad(pred, target, 8)
    parts = [F.mse_loss_grad(pred[i : i + 2], target[i : i + 2], 8) for i in range(0, 8, 2)]
    np.testing.assert_allclose(jnp.concatenate(parts), full, rtol=1e-6)


# ---------------------------------------------------------------- jit


@pytest.mark.parametrize("fn_args", [
    (F.relu, (rand(2, 3),)),
    (F.softmax, (rand(2, 3),)),
    (F.linear, (rand(2, 3), rand(4, 3), rand(1, 4))),
])
def test_ops_are_jittable(fn_args):
    fn, args = fn_args
    np.testing.assert_allclose(jax.jit(fn)(*args), fn(*args), rtol=1e-6)


def test_blocked_matmul_matches_xla():
    """The narrow-K Pallas matmul (ops/matmul.py) is exact vs jnp.dot
    in f32 and close in bf16 (f32 accumulator), across odd block
    splits."""
    import jax.numpy as jnp

    from shallowspeed_tpu.ops.matmul import blocked_matmul

    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 128)).astype(np.float32)
    y = rng.normal(size=(128, 384)).astype(np.float32)
    ref = x @ y
    out = blocked_matmul(jnp.asarray(x), jnp.asarray(y),
                         bm=64, bk=32, bn=128, interpret=True)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5,
                               atol=1e-4)
    xb = jnp.asarray(x, jnp.bfloat16)
    yb = jnp.asarray(y, jnp.bfloat16)
    outb = blocked_matmul(xb, yb, bm=128, bk=128, bn=384,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(outb, np.float32), ref,
                               rtol=0.1, atol=0.5)
