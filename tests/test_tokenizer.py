"""Byte-level BPE tokenizer (`data/tokenizer.py`, `--tokenizer bpe`).

Contracts: lossless roundtrip on ANY bytes (base alphabet is all 256
bytes — no <unk>), real compression on repetitive text, deterministic
training, JSON save/load identity, and driver integration (vocab feeds
the model config; sampling decodes through the tokenizer).
"""

import numpy as np
import pytest

from shallowspeed_tpu.data.tokenizer import ByteBPE, train_bpe

CORPUS = ("the quick brown fox jumps over the lazy dog. "
          "the quick brown fox jumps again and again. " * 20)


def test_roundtrip_identity():
    tok = train_bpe(CORPUS, 300)
    ids = tok.encode(CORPUS)
    assert tok.decode(ids) == CORPUS
    # arbitrary bytes (invalid UTF-8 included) survive encode/decode
    blob = bytes(range(256)) * 3
    assert tok.decode_bytes(tok.encode(blob)) == blob


def test_compresses_repetitive_text():
    tok = train_bpe(CORPUS, 500)
    n_bytes = len(CORPUS.encode())
    n_ids = len(tok.encode(CORPUS))
    assert n_ids < 0.5 * n_bytes, (n_ids, n_bytes)
    assert 256 < tok.vocab_size <= 500


def test_merges_never_cross_whitespace():
    tok = train_bpe("aa aa aa aa bb bb bb bb", 300)
    for a, b in tok.merges:
        merged = tok._bytes[a] + tok._bytes[b]
        # a merge may START with the glued-on space but never contain an
        # interior space (chunks end at whitespace boundaries)
        assert b" " not in merged.lstrip(b" "), merged


def test_training_deterministic():
    a = train_bpe(CORPUS, 400)
    b = train_bpe(CORPUS, 400)
    assert a.merges == b.merges


def test_stops_when_nothing_repeats():
    tok = train_bpe("abcdefg", 10_000)
    assert tok.vocab_size < 300  # no pair repeats twice -> early stop


def test_save_load_roundtrip(tmp_path):
    tok = train_bpe(CORPUS, 400)
    tok.save(tmp_path / "tok.json")
    tok2 = ByteBPE.load(tmp_path / "tok.json")
    assert tok2.merges == tok.merges
    ids = tok.encode("the quick brown fox")
    np.testing.assert_array_equal(ids, tok2.encode("the quick brown fox"))


def test_encode_returns_int32():
    tok = train_bpe(CORPUS, 300)
    ids = tok.encode("hello world")
    assert ids.dtype == np.int32


# ------------------------------------------------------ driver integration


def test_driver_trains_and_samples_with_bpe(tmp_path):
    import train_lm

    corpus = tmp_path / "corpus.txt"
    corpus.write_text(CORPUS)
    args = train_lm.parse_args([
        "--text", str(corpus), "--tokenizer", "bpe", "--vocab-size", "400",
        "--steps", "8", "--seq-len", "32", "--d-model", "32",
        "--batch-size", "4", "--log-every", "4", "--prefetch", "0",
        "--save-dir", str(tmp_path / "ck"), "--save-every", "4",
        "--generate", "8", "--prompt", "the quick",
    ])
    loss = train_lm.train(args)
    assert np.isfinite(loss)
    assert (tmp_path / "ck" / "tokenizer.json").exists()

    # sample-only restores the tokenizer (and the vocab it implies)
    args2 = train_lm.parse_args([
        "--tokenizer", "bpe", "--seq-len", "32", "--d-model", "32",
        "--save-dir", str(tmp_path / "ck"), "--sample-only",
        "--prompt", "the quick", "--generate", "8", "--prefetch", "0",
    ])
    assert np.isnan(train_lm.train(args2))


def test_driver_bpe_without_text_rejected():
    import train_lm

    args = train_lm.parse_args(["--tokenizer", "bpe", "--steps", "2"])
    with pytest.raises(SystemExit, match="bpe needs --text"):
        train_lm.train(args)
