"""Numerics observatory (round 18): runtime precision telemetry over
the fp8-e4m3 trainer, shadow-parity gating, and the attribution-gated
rollout contract.

Coverage map:
- the numerics pack rides the ONE compiled fp8 step (zero new
  executables, zero recompiles across steps / shadow sampling /
  fallback — the health-pack contract, `_cache_size() == 1` pins);
- fp8.py typed errors, the `_scales` 1e-12 divide floor, amax-history
  roll + buffer donation;
- `NumericsMonitor`: scale-collapse at the floor, parity-envelope
  verdicts, the warn -> fallback_bf16 -> abort escalation;
- chaos `scale_poison@N`: seeded layer choice, typed error on engines
  without an amax history;
- schema v13: num_* step lines validate (good AND bad), pre-v13 lines
  keep validating;
- attribution prices float8-operand dots at FP8_FLOPS_RATIO (and
  `flops.device_peak_flops` doubles the fp8 peak);
- the --goodput numerics block + `shadow_parity` ledger exclusion;
- the static prover's calibration ranges contain measured RUNTIME
  amax telemetry (the certificate's conditioning holds live);
- bench_fp8: the fp8-on transformer case shrinks attrib_mxu_frac vs
  the bf16 baseline inside the unexplained/parity envelopes, and the
  headline is banded by --regress;
- the end-to-end drill (tier-1): a seeded scale_poison run under
  --health guard detects the collapse at the poisoned step, dumps a
  flight record + profiler capture, falls back to bf16, and finishes
  within the fault-free oracle's loss envelope.
"""

import json
import math
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from shallowspeed_tpu import chaos  # noqa: E402
from shallowspeed_tpu.fp8 import (AMAX_HISTORY, Fp8TrainEngine,  # noqa: E402
                                  init_fp8_mlp)
from shallowspeed_tpu.ops.matmul import E4M3_MAX  # noqa: E402
from shallowspeed_tpu.optim import SGD, MomentumSGD  # noqa: E402
from shallowspeed_tpu.telemetry.anomaly import GuardPolicy  # noqa: E402
from shallowspeed_tpu.telemetry.numerics import (COLLAPSE_FLOOR,  # noqa: E402
                                                 PARITY_LOSS_BUDGET,
                                                 NumericsMonitor)
from shallowspeed_tpu.telemetry.schema import (SCHEMA_VERSION,  # noqa: E402
                                               validate_line)

ROOT = Path(__file__).resolve().parents[1]
SIZES = [12, 16, 10]


@pytest.fixture(autouse=True)
def _no_ambient_plan(monkeypatch):
    for var in (chaos.ENV_SPEC, chaos.ENV_STATE, chaos.ENV_SEED):
        monkeypatch.delenv(var, raising=False)
    chaos.configure(None)
    yield
    chaos.configure(None)


def _batch(seed=0, bs=8):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((bs, SIZES[0])).astype(np.float32)
    y = np.eye(SIZES[-1], dtype=np.float32)[rng.integers(0, SIZES[-1], bs)]
    return x, y


def _engine(**kw):
    return Fp8TrainEngine(SIZES, MomentumSGD(0.05, momentum=0.9), **kw)


# ------------------------------------------------- the compiled step


def test_numerics_pack_rides_one_executable():
    """The zero-new-executables contract: the clamp stats and
    amax/scale telemetry are computed INSIDE the one jitted step —
    N steps, shadow sampling, and the bf16 fallback never grow any
    executable cache past one entry each."""
    eng = _engine()
    for i in range(6):
        eng.train_batch(*_batch(i))
    assert int(eng._step_fn._cache_size()) == 1
    # the oracle and fallback are LAZY: a run that never needs them
    # compiles nothing extra
    assert eng._parity_fn is None and eng._fallback_fn is None
    pack = eng.health_snapshot()
    for key in ("fp8_amax", "fp8_scale", "fp8_overflow", "fp8_underflow"):
        assert key in pack, key
        assert len(pack[key]) == len(SIZES) - 1
        assert all(math.isfinite(v) for v in pack[key])
    # clamp fractions are fractions
    assert all(0.0 <= v <= 1.0 for v in pack["fp8_overflow"])
    assert all(0.0 <= v <= 1.0 for v in pack["fp8_underflow"])

    parity = eng.shadow_parity(*_batch(7))
    assert set(parity) == {"parity_loss_rel", "parity_grad_relmax"}
    eng.fallback_bf16()
    for i in range(3):
        eng.train_batch(*_batch(10 + i))
    assert int(eng._step_fn._cache_size()) == 1
    assert int(eng._parity_fn._cache_size()) == 1
    assert int(eng._fallback_fn._cache_size()) == 1


def test_fallback_keeps_pack_and_state_shape():
    """The bf16 fallback's pack is structurally identical (scales keep
    rolling, clamp fractions are exact zeros — nothing is quantized)."""
    eng = _engine()
    eng.train_batch(*_batch(0))
    before = eng.health_snapshot()
    eng.fallback_bf16()
    eng.train_batch(*_batch(1))
    after = eng.health_snapshot()
    assert set(before) == set(after)
    assert after["fp8_overflow"] == [0.0] * (len(SIZES) - 1)
    assert after["fp8_underflow"] == [0.0] * (len(SIZES) - 1)
    # the history kept rolling under fallback: fresh finite scales
    assert all(s > 0 for s in after["fp8_scale"])


def test_scales_divide_floor_pin():
    """A zeroed amax history must floor the delayed scale at exactly
    1e-12 — never zero (the divide the prover certifies nonzero)."""
    hist = jnp.zeros((2, AMAX_HISTORY), jnp.float32)
    scales = np.asarray(Fp8TrainEngine._scales(hist))
    assert scales.tolist() == pytest.approx([1e-12, 1e-12])
    assert (scales > 0).all()
    hist = hist.at[1, 3].set(448.0)
    scales = np.asarray(Fp8TrainEngine._scales(hist))
    assert scales[0] == pytest.approx(1e-12)
    assert scales[1] == pytest.approx(448.0 / E4M3_MAX)


def test_amax_history_rolls_and_donates():
    """Slot 0 after a step is THIS step's measured absmax (layer 0's is
    the input absmax, exactly computable); older slots shift right; and
    the donated input buffers are actually consumed."""
    eng = _engine()
    x, y = _batch(0)
    old_hist = eng.amax_hist
    marker = eng.amax_hist[0, 0]
    eng.train_batch(x, y)
    hist = np.asarray(eng.amax_hist)
    assert hist[0, 0] == pytest.approx(float(np.max(np.abs(x))), rel=1e-6)
    assert hist[0, 1] == pytest.approx(float(marker))
    # donate_argnums=(0,1,2): the old history buffer was consumed
    assert old_hist.is_deleted()


def test_fp8_typed_errors():
    with pytest.raises(ValueError, match="unsupported precision"):
        Fp8TrainEngine(SIZES, SGD(0.01), precision="int4")
    with pytest.raises(ValueError, match="positive dims"):
        Fp8TrainEngine([12], SGD(0.01))
    with pytest.raises(ValueError, match="positive dims"):
        Fp8TrainEngine([12, 0, 10], SGD(0.01))


# ------------------------------------------------- host-side monitor


def _pack(scales, over=None, amax=None):
    n = len(scales)
    return {"fp8_scale": list(scales),
            "fp8_amax": list(amax or [1.0] * n),
            "fp8_overflow": list(over or [0.0] * n),
            "fp8_underflow": [0.0] * n}


def test_monitor_scale_collapse_and_escalation():
    """Collapse at the floor fires ON ARRIVAL with the guard's
    fallback action; after the fallback is taken the same kind comes
    back as abort (warn -> fall back -> abort)."""
    mon = NumericsMonitor(policy=GuardPolicy.for_mode("guard"))
    out = mon.observe(0, _pack([0.5, 0.5]))
    assert out == []
    out = mon.observe(1, _pack([1e-12, 0.5], over=[0.9, 0.0]))
    assert [v.kind for v in out] == ["scale_collapse"]
    assert out[0].action == "fallback_bf16"
    assert "layer 0" in out[0].detail
    # still collapsed: reported once, not every step
    assert mon.observe(2, _pack([1e-12, 0.5])) == []
    mon.note_fallback()
    # recovers, then collapses AGAIN -> the middle rung is spent
    mon.observe(3, _pack([0.5, 0.5]))
    out = mon.observe(4, _pack([1e-12, 0.5]))
    assert [v.action for v in out] == ["abort"]
    assert mon.step_fields()["num_precision"] == "bf16"


def test_monitor_parity_envelope():
    mon = NumericsMonitor(policy=GuardPolicy.for_mode("guard"))
    ok = mon.note_parity(8, {"parity_loss_rel": 0.01,
                             "parity_grad_relmax": 0.9})
    assert ok == []
    bad = mon.note_parity(16, {"parity_loss_rel": 0.16,
                               "parity_grad_relmax": 1.0})
    assert [v.kind for v in bad] == ["parity_drift"]
    assert bad[0].action == "fallback_bf16"
    fields = mon.step_fields()
    assert fields["num_parity_loss_rel"] == pytest.approx(0.16)
    assert fields["num_shadow_total"] == 2
    assert fields["num_verdicts"] == ["parity_drift"]
    # the verdict window drains
    assert "num_verdicts" not in mon.step_fields()


def test_monitor_oscillation_score():
    """A scale ping-ponging between two values every observation
    scores ~1.0; a constant scale scores 0."""
    mon = NumericsMonitor()
    for i in range(12):
        mon.observe(i, _pack([0.25 if i % 2 else 0.5, 0.5]))
    fields = mon.step_fields()
    assert fields["num_osc"] == pytest.approx(1.0)
    assert fields["num_scale_min"] == pytest.approx(0.25)


# ----------------------------------------------------- chaos fault


def test_chaos_scale_poison_is_seeded_and_once_only(tmp_path):
    plan = chaos.FaultPlan.parse("scale_poison@3", seed=5,
                                 state_dir=tmp_path / "cs")
    chaos.configure(plan)
    eng = _engine()
    eng.train_batch(*_batch(0))
    hist0 = np.asarray(eng.amax_hist).copy()
    chaos.on_step(2, eng)  # not due yet
    assert np.array_equal(np.asarray(eng.amax_hist), hist0)
    chaos.on_step(3, eng)
    hist = np.asarray(eng.amax_hist)
    zeroed = [i for i in range(hist.shape[0]) if (hist[i] == 0.0).all()]
    assert len(zeroed) == 1
    # seeded: the same plan on a fresh engine picks the same layer
    plan2 = chaos.FaultPlan.parse("scale_poison@3", seed=5,
                                  state_dir=tmp_path / "cs2")
    chaos.configure(plan2)
    eng2 = _engine()
    eng2.train_batch(*_batch(0))
    chaos.on_step(3, eng2)
    hist2 = np.asarray(eng2.amax_hist)
    assert [i for i in range(hist2.shape[0])
            if (hist2[i] == 0.0).all()] == zeroed
    # once-only: markers survive, a second pass does not re-fire
    eng3 = _engine()
    chaos.configure(chaos.FaultPlan.parse("scale_poison@3", seed=5,
                                          state_dir=tmp_path / "cs"))
    chaos.on_step(3, eng3)
    assert not np.asarray(eng3.amax_hist == 0.0).all(axis=1).any()


def test_chaos_scale_poison_typed_error_without_history():
    chaos.configure(chaos.FaultPlan.parse("scale_poison@0", seed=1))
    with pytest.raises(RuntimeError, match="amax_hist"):
        chaos.on_step(0, object())


# ------------------------------------------------------- schema v13


def test_schema_v13_step_lines():
    assert SCHEMA_VERSION >= 13
    base = {"event": "step", "step": 4, "loss": 0.5,
            "tokens_per_sec": 100.0, "t": 1.0, "wall": 1.0}
    good = dict(base, num_overflow_max=0.5, num_underflow_max=0.0,
                num_scale_min=1e-12, num_amax_max=3.2, num_drift_z=1.5,
                num_osc=0.0, num_parity_loss_rel=0.01,
                num_parity_grad_relmax=0.9, num_shadow_total=3,
                num_precision="fp8", num_verdicts=["scale_collapse"])
    assert validate_line(good) == []
    # pre-v13 lines (no num_* fields) keep validating
    assert validate_line(base) == []
    bad = dict(base, num_overflow_max="lots")
    assert any("num_overflow_max" in p for p in validate_line(bad))
    bad = dict(base, num_verdicts="scale_collapse")
    assert any("num_verdicts" in p for p in validate_line(bad))


def test_step_fields_from_live_run_validate():
    """The exact dict the driver logs (StepRates merge) passes the
    schema — the contract the committed r18 artifact is gated on."""
    from shallowspeed_tpu.metrics import StepRates

    eng = _engine()
    mon = NumericsMonitor(policy=GuardPolicy.for_mode("guard"))
    rates = StepRates(8, numerics=mon)
    for i in range(3):
        eng.train_batch(*_batch(i))
        mon.observe(i, eng.health_snapshot())
    mon.note_parity(2, eng.shadow_parity(*_batch(2)))
    fields = rates.log_point(3)
    line = {"event": "step", "step": 2, "loss": 0.1,
            **{k: (round(v, 6) if isinstance(v, float) else v)
               for k, v in fields.items()}}
    assert validate_line(line) == [], line
    assert line["num_precision"] == "fp8"
    assert "num_scale_min" in line and "num_parity_loss_rel" in line


# ------------------------------------------------- attribution pricing


def test_attribution_prices_fp8_dots():
    from shallowspeed_tpu.ops.matmul import fp8_dense
    from shallowspeed_tpu.telemetry.attribution import (FP8_FLOPS_RATIO,
                                                        roofline_of_jaxpr,
                                                        roofline_seconds)

    if not hasattr(jnp, "float8_e4m3fn"):
        pytest.skip("no float8 dtype in this jax build")
    x = jnp.ones((16, 32), jnp.float32)
    w = jnp.ones((32, 8), jnp.float32)

    roof = roofline_of_jaxpr(jax.make_jaxpr(
        lambda a, b: fp8_dense(a, b, jnp.float32(0.1)))(x, w))
    fl = roof["flops_global"]
    assert fl >= 2 * 16 * 32 * 8
    assert roof["flops_fp8_global"] == fl  # every dot is quantized here
    plain = roofline_of_jaxpr(jax.make_jaxpr(
        lambda a, b: a @ b)(x, w))
    assert plain["flops_fp8_global"] == 0

    # flop-bound rates: the fp8 subset runs FP8_FLOPS_RATIO x faster
    rates = {"flops": 1e6, "hbm": 1e12, "ici": 1e12}
    quant = roofline_seconds(
        {"flops_global": 1000, "flops_fp8_global": 1000}, rates)
    base = roofline_seconds({"flops_global": 1000}, rates)
    assert quant["mxu_s"] == pytest.approx(
        base["mxu_s"] / FP8_FLOPS_RATIO)


def test_device_peak_flops_fp8_doubles_bf16():
    from shallowspeed_tpu.flops import device_peak_flops

    class _Dev:
        device_kind = "TPU v7"

    bf16 = device_peak_flops(_Dev())
    assert device_peak_flops(_Dev(), dtype="fp8") == bf16 * 2.0
    assert device_peak_flops(_Dev(), dtype="float8_e4m3fn") == bf16 * 2.0


# ------------------------------------------------- goodput reduction


def test_goodput_numerics_block(tmp_path):
    from shallowspeed_tpu.telemetry.goodput import (EXCLUDED_KINDS,
                                                    format_report,
                                                    run_goodput)

    assert "shadow_parity" in EXCLUDED_KINDS
    log = tmp_path / "m.jsonl"
    lines = [
        {"event": "run_start", "schema_version": 13, "t": 0.0,
         "wall": 100.0},
        {"event": "step", "step": 0, "loss": 0.5, "tokens_per_sec": 10.0,
         "num_overflow_max": 0.0, "num_scale_min": 0.002,
         "num_precision": "fp8", "t": 1.0, "wall": 101.0},
        {"event": "ledger", "kind": "shadow_parity", "seconds": 0.5,
         "t": 1.5, "wall": 101.5},
        {"event": "step", "step": 8, "loss": 0.4, "tokens_per_sec": 10.0,
         "num_overflow_max": 0.55, "num_scale_min": 1e-12,
         "num_parity_loss_rel": 0.16, "num_parity_grad_relmax": 1.0,
         "num_shadow_total": 2, "num_precision": "fp8",
         "num_verdicts": ["scale_collapse", "parity_drift"],
         "t": 2.0, "wall": 102.0},
        {"event": "step", "step": 9, "loss": 0.3, "tokens_per_sec": 10.0,
         "num_overflow_max": 0.0, "num_scale_min": 0.002,
         "num_precision": "bf16", "t": 3.0, "wall": 103.0},
    ]
    log.write_text("".join(json.dumps(r) + "\n" for r in lines))
    rep = run_goodput(log)
    num = rep["numerics"]
    assert num["steps_observed"] == 3 and num["steps_fp8"] == 2
    assert num["overflow_max"] == pytest.approx(0.55)
    assert num["scale_min"] == pytest.approx(1e-12)
    assert num["parity_loss_rel_max"] == pytest.approx(0.16)
    assert num["verdicts"] == {"scale_collapse": 1, "parity_drift": 1}
    assert num["fell_back_bf16"] and num["final_precision"] == "bf16"
    assert num["shadow_samples"] == 2
    # shadow-parity seconds land in the excluded/loss buckets
    assert rep["losses"]["shadow_parity"] == pytest.approx(0.5)
    txt = format_report(rep)
    assert "FELL BACK to bf16" in txt and "scale_collapse" in txt


# ------------------------------------- live plane + fleet surfaces


def test_monitor_status_metrics_and_flight_dump(tmp_path):
    from shallowspeed_tpu.telemetry.monitor import Monitor

    mon = Monitor(flight=16, flight_dir=tmp_path)
    mon.note_line({"event": "step", "step": 4, "loss": 0.5,
                   "tokens_per_sec": 10.0, "num_overflow_max": 0.55,
                   "num_scale_min": 1e-12, "num_precision": "fp8",
                   "num_parity_loss_rel": 0.16,
                   "num_verdicts": ["scale_collapse"]})
    st = mon.status()
    assert st["numerics"]["num_scale_min"] == pytest.approx(1e-12)
    assert st["numerics"]["last_verdicts"] == ["scale_collapse"]
    assert "scale_collapse" in st["health"]
    prom = mon.prometheus()
    assert "num_overflow_max 0.55" in prom
    assert "num_precision_fp8 1" in prom
    dumps = list(tmp_path.glob("flightrec_*.json"))
    assert dumps, "a numerics verdict must dump the flight ring"
    rec = json.loads(dumps[0].read_text())
    assert "scale_collapse" in str(rec.get("reason", rec))


def test_fleet_view_carries_numerics(tmp_path):
    from shallowspeed_tpu.telemetry.fleet import (FleetCollector,
                                                  format_fleet_status)

    paths = []
    for name, prec, parity in (("r0", "fp8", 0.01), ("r1", "bf16", 0.2)):
        p = tmp_path / f"{name}.jsonl"
        p.write_text(json.dumps(
            {"event": "step", "step": 3, "loss": 0.5,
             "tokens_per_sec": 10.0, "num_precision": prec,
             "num_parity_loss_rel": parity, "num_overflow_max": 0.1,
             "t": 1.0, "wall": 1.0}) + "\n")
        paths.append(p)
    coll = FleetCollector(paths=paths)
    st = coll.refresh()
    num = st["numerics"]
    assert num["worst_parity_loss_rel"]["replica"] == "r1"
    assert num["worst_parity_loss_rel"]["value"] == pytest.approx(0.2)
    assert num["fell_back_bf16"] == ["r1"]
    txt = format_fleet_status(st)
    assert "numerics:" in txt and "FELL BACK" in txt


# ------------------------------- static certificate vs live telemetry


def test_static_calibration_ranges_contain_runtime_amax():
    """The prover's certificate is conditioned on the probe's measured
    calibration intervals — live runtime amax telemetry from the SAME
    distribution must stay inside them, or the certificate never
    applied to the run (the cross-check the observatory exists for)."""
    from shallowspeed_tpu.analysis.targets import build_fp8_train

    probe = build_fp8_train()
    ranges = {ep.name: ep.ranges for ep in probe.entrypoints}
    lo, hi = ranges["_step"]["amax_hist"]
    eng = _engine(seed=0)
    seen = []
    for i in range(12):
        eng.train_batch(*_batch(i))
        seen.extend(eng.health_snapshot()["fp8_amax"])
    assert seen
    assert all(lo <= a <= hi for a in seen), (lo, hi, seen)
    # and the measured scales stay off the collapse floor
    assert min(eng.health_snapshot()["fp8_scale"]) > COLLAPSE_FLOOR


# ------------------------------------------------ the bench gate


def test_bench_fp8_attribution_gate():
    """The rollout pin: the fp8-on transformer case's attrib_mxu_frac
    sits STRICTLY below the bf16 baseline's, unexplained stays inside
    the 0.10 pin, the one-batch parity is inside the shadow envelope,
    and the headline ratio is banded by --regress."""
    import bench
    from shallowspeed_tpu.telemetry import attribution as attr
    from shallowspeed_tpu.telemetry.regress import METRICS

    for _attempt in range(6):
        out = bench.bench_fp8()
        if "fp8_error" in out:
            pytest.skip(out["fp8_error"])
        cases = out["fp8_attribution"]
        if (cases["bf16"]["attrib_unexplained_frac"] <= 0.10
                and cases["fp8"]["attrib_unexplained_frac"] <= 0.10):
            break
        # shared CI host: step times drift between the fit and frozen
        # windows often enough that one attempt flakes (the same
        # bounded-retry contract as test_attribution)
        time.sleep(0.5)
        attr.recalibrate()
    assert cases["fp8"]["attrib_mxu_frac"] < cases["bf16"]["attrib_mxu_frac"]
    assert out["fp8_mxu_shrink"] > 1.0
    assert cases["fp8"]["fp8_dot_flops"] > 0
    assert cases["bf16"]["fp8_dot_flops"] == 0
    assert cases["bf16"]["attrib_unexplained_frac"] <= 0.10
    assert cases["fp8"]["attrib_unexplained_frac"] <= 0.10
    assert cases["parity_loss_rel"] <= PARITY_LOSS_BUDGET
    band, spread = METRICS["fp8_mxu_shrink"]
    assert 0 < band < 1 and spread is None


def test_transformer_fp8_dense_config():
    from shallowspeed_tpu.models import transformer as tf

    if tf._FP8_DTYPE is None:
        with pytest.raises(ValueError, match="float8_e4m3fn"):
            tf.TransformerConfig(fp8_dense=True)
        return
    cfg = tf.TransformerConfig(vocab=32, d_model=32, n_heads=2,
                               n_layers=1, max_seq=16)
    cfg8 = tf.TransformerConfig(vocab=32, d_model=32, n_heads=2,
                                n_layers=1, max_seq=16, fp8_dense=True)
    params = tf.init(cfg, seed=0)
    rng = np.random.default_rng(0)
    tok = jnp.asarray(rng.integers(0, 32, (2, 16)), jnp.int32)
    tgt = jnp.asarray(rng.integers(0, 32, (2, 16)), jnp.int32)
    l0 = float(tf.loss(params, tok, tgt, cfg))
    l8 = float(tf.loss(params, tok, tgt, cfg8))
    assert math.isfinite(l8)
    assert abs(l8 - l0) / abs(l0) <= PARITY_LOSS_BUDGET
    g = jax.grad(tf.loss)(params, tok, tgt, cfg8)
    assert all(np.isfinite(np.asarray(x)).all()
               for x in jax.tree_util.tree_leaves(g))
    # monkeypatch-free typed-error check: simulate a build without the
    # dtype by the documented gate
    real = tf._FP8_DTYPE
    tf._FP8_DTYPE = None
    try:
        with pytest.raises(ValueError, match="float8_e4m3fn"):
            tf.TransformerConfig(fp8_dense=True)
    finally:
        tf._FP8_DTYPE = real


# ------------------------------------------------ end-to-end drill


def _run_driver(tmp_path, tag, *extra):
    log = tmp_path / f"{tag}.jsonl"
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run(
        [sys.executable, "train.py", "--engine", "fp8", "--epochs", "1",
         "--max-batches", "14", "--shadow-every", "4", "--log-every",
         "4", "--health", "guard", "--flight-recorder", "64",
         "--profile", "host", "--log-file", str(log),
         "--chaos-state", str(tmp_path / f"cs_{tag}"), *extra],
        capture_output=True, text=True, cwd=ROOT, env=env, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    recs = [json.loads(ln) for ln in log.read_text().splitlines()]
    return r, recs, log


def test_fp8_driver_scale_poison_drill(tmp_path):
    """The acceptance drill: poison one layer's amax history mid-run;
    shadow parity + the scale-collapse detector must catch it AT the
    poisoned step, dump a flight record and a profiler capture, fall
    back to bf16, and finish sane (the slow-tier variant below holds
    the run against a live fault-free oracle; here a fixed envelope
    keeps the default tier to ONE subprocess)."""
    r, recs, log = _run_driver(tmp_path, "poison",
                               "--chaos", "scale_poison@6")

    steps = [x for x in recs if x.get("event") == "step"]
    assert steps, recs
    # detection at the poisoned step, on the step line
    hit = [x for x in steps
           if "scale_collapse" in (x.get("num_verdicts") or ())]
    assert [x["step"] for x in hit] == [6], steps
    assert hit[0]["num_scale_min"] == pytest.approx(1e-12)
    assert hit[0]["num_overflow_max"] > 0.1
    # the guard fell back: every later line is bf16 and the fault +
    # fallback are on the ledger
    assert all(x["num_precision"] == "bf16" for x in steps
               if x["step"] >= 6)
    assert any(x.get("event") == "fault"
               and x.get("kind") == "scale_poison" for x in recs)
    assert any(x.get("event") == "ledger"
               and x.get("kind") == "fp8_fallback" for x in recs)
    assert any(x.get("event") == "ledger"
               and x.get("kind") == "shadow_parity" for x in recs)
    # incident artifacts, next to the log file
    assert list(tmp_path.glob("flightrec_*.json"))
    assert list(tmp_path.glob("profcap_*.json"))
    assert "falling back to the bf16" in r.stdout
    # fixed loss envelope: the recovered run keeps LEARNING (measured
    # final val ~0.15 on this config; an un-recovered poisoned run
    # plateaus >1.0 — the live-oracle bound is the slow-tier drill)
    val = [x for x in recs if x.get("event") == "val"][-1]["val_loss"]
    assert val <= 0.5, val
    # schema: the whole artifact validates
    from shallowspeed_tpu.telemetry.schema import validate_file

    assert validate_file(log) == []


@pytest.mark.slow
def test_fp8_drill_within_live_oracle_envelope(tmp_path):
    """Slow-tier completion of the drill above: the guarded poisoned
    run finishes within 1.5x of a LIVE fault-free oracle run's final
    val loss (measured margin ~0.8x — the bf16 master step is simply
    the better trainer on this config)."""
    _, oracle_recs, _ = _run_driver(tmp_path, "oracle")
    _, recs, _ = _run_driver(tmp_path, "poison",
                             "--chaos", "scale_poison@6")
    val = [x for x in recs if x.get("event") == "val"][-1]["val_loss"]
    oval = [x for x in oracle_recs
            if x.get("event") == "val"][-1]["val_loss"]
    assert val <= oval * 1.5, (val, oval)


def test_committed_numerics_artifact_validates():
    """The committed r18 drill artifact stays schema-clean and keeps
    its story: a scale_collapse verdict, the bf16 fallback, shadow
    samples, and the shadow_parity ledger bucket."""
    from shallowspeed_tpu.telemetry.schema import validate_file

    art = ROOT / "docs_runs" / "numerics_r18_metrics.jsonl"
    assert validate_file(art) == []
    recs = [json.loads(ln) for ln in art.read_text().splitlines()]
    steps = [x for x in recs if x.get("event") == "step"]
    assert any("scale_collapse" in (x.get("num_verdicts") or ())
               for x in steps)
    assert steps[-1]["num_precision"] == "bf16"
    assert any(x.get("event") == "ledger"
               and x.get("kind") == "shadow_parity" for x in recs)
