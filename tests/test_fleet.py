"""Fleet observability (round 13): multi-replica collector, straggler
detection, per-request lifecycle tracing.

Acceptance pins:
- fleet quantile parity: a 3-replica scripted serving run's fleet
  /status.json quantiles match the POOLED offline --goodput reduction
  within the recorded rel_err (`test_fleet_quantile_parity_3_replicas`
  — the fleet generalization of the PR-8 live/offline canary);
- a seeded `stall` chaos fault on exactly one of three replicas
  raises a schema-v8 "straggler" event naming that replica, while the
  stalled request's lifecycle timeline reconstructs its phases
  end-to-end (`test_stall_chaos_on_one_replica_names_straggler`);
- lifecycle events validate schema v8 and render as one named track
  per request in the Chrome trace, cross-linked to engine ticks.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from shallowspeed_tpu.telemetry.fleet import (FleetCollector, Replica,
                                              fleet_main,
                                              format_fleet_status)
from shallowspeed_tpu.telemetry.monitor import Monitor, StatusServer
from shallowspeed_tpu.telemetry.report import (percentile,
                                               request_timeline)
from shallowspeed_tpu.telemetry.schema import (validate_file,
                                               validate_line)
from shallowspeed_tpu.telemetry.sketch import MetricSketches

ROOT = Path(__file__).resolve().parents[1]


# ------------------------------------------------------- scripted fixtures


def _write_replica_jsonl(path, replica, ttfts, step_ms=None, wall0=100.0):
    """A minimal schema-valid metrics file for one replica: run_start
    (with the replica label), one request line per ttft, optional
    step lines."""
    wall = wall0
    with open(path, "w") as f:
        f.write(json.dumps({"event": "run_start", "schema_version": 8,
                            "replica": replica, "wall": wall}) + "\n")
        for s, ms in enumerate(step_ms or []):
            wall += ms / 1e3
            f.write(json.dumps({"event": "step", "step": s,
                                "loss": 1.0, "tokens_per_sec": 50.0,
                                "wall": round(wall, 4)}) + "\n")
        for i, t in enumerate(ttfts):
            wall += 0.01
            f.write(json.dumps({"event": "request",
                                "id": f"{replica}-q{i}",
                                "ttft_ms": float(t), "tpot_ms": 3.0,
                                "tokens_in": 4, "tokens_out": 4,
                                "wall": round(wall, 4)}) + "\n")
    return path


def _serve_replica(params, cfg, path, replica, n_req=5, seed=0,
                   chaos_plan=None, **engine_kw):
    """One scripted in-process serving run writing `path`."""
    from shallowspeed_tpu.metrics import MetricsLogger
    from shallowspeed_tpu.serving import ServingEngine

    metrics = MetricsLogger(path, kind="serve", replica=replica)
    eng = ServingEngine(params, cfg, metrics=metrics, log_every=4,
                        chaos_plan=chaos_plan, **engine_kw)
    rng = np.random.default_rng(seed)
    for i in range(n_req):
        eng.submit(rng.integers(0, cfg.vocab, 6 + 2 * i)
                   .astype(np.int32), 4 + i, rid=f"{replica}-q{i}")
    eng.run()
    return eng


@pytest.fixture(scope="module")
def serving_setup():
    """Shared params + a jit warmup run, so replica timing in the
    straggler test reflects steady-state ticks, not which engine paid
    the one-time compile."""
    import jax

    from shallowspeed_tpu.models import transformer as T

    cfg = T.TransformerConfig(vocab=48, d_model=24, n_heads=2,
                              n_layers=2, max_seq=96)
    params = jax.device_put(T.init(cfg, seed=1))
    kw = dict(n_blocks=48, block_size=8, max_slots=2, prefill_chunk=16)
    _serve_replica(params, cfg, None, "warmup", n_req=5, **kw)
    return params, cfg, kw


# ------------------------------------------------------------ collector


def test_fleet_merges_file_replicas_and_labels(tmp_path):
    a = _write_replica_jsonl(tmp_path / "a.jsonl", "alpha",
                             [10, 20, 30, 40])
    b = _write_replica_jsonl(tmp_path / "b.jsonl", "beta",
                             [50, 60, 70, 80])
    fc = FleetCollector(paths=[a, b])
    st = fc.refresh()
    # labels learned from the run_start stamps, not the file names
    assert set(st["replicas"]) == {"alpha", "beta"}
    assert st["fleet"]["alive"] == 2
    merged = st["fleet"]["sketches"]["ttft_ms"]
    assert merged["count"] == 8
    # exact bucket union: the pooled nearest-rank percentile within
    # the sketch's rel_err
    exact = percentile([10, 20, 30, 40, 50, 60, 70, 80], 50)
    assert abs(merged["p50"] - exact) <= st["fleet"]["rel_err"] * exact
    # worst-ttft exemplars name request id AND replica — the one-hop
    # SLO-burn-to-request linkage
    worst = st["worst_ttft"]
    assert worst[0]["replica"] == "beta" and worst[0]["id"] == "beta-q3"
    # per-replica breakdown carries per-metric quantiles
    assert st["replicas"]["alpha"]["quantiles"]["ttft_ms"]["count"] == 4


def test_fleet_url_mode_and_registration(tmp_path):
    mon_a, mon_b = Monitor(label="a", flight=0), Monitor(flight=0)
    for i in range(10):
        mon_a.note_line({"event": "request", "id": f"a{i}",
                         "ttft_ms": 10.0 + i, "tokens_in": 1,
                         "tokens_out": 2, "wall": 100.0 + i})
        mon_b.note_line({"event": "request", "id": f"b{i}",
                         "ttft_ms": 200.0 + i, "tokens_in": 1,
                         "tokens_out": 2, "wall": 100.0 + i})
    srv_a = StatusServer(mon_a, port=0)
    srv_b = StatusServer(mon_b, port=0)
    try:
        fc = FleetCollector(urls=[srv_a.url("/status.json")])
        # replica b self-registers over HTTP, like serve.py
        # --fleet-register does; the fleet endpoint serves the merged
        # view
        fleet_srv = StatusServer(fc, port=0)
        try:
            body = json.dumps({"url": srv_b.url("/status.json"),
                               "name": "b"}).encode()
            resp = json.loads(urllib.request.urlopen(
                urllib.request.Request(
                    fleet_srv.url("/register"), data=body,
                    headers={"Content-Type": "application/json"}),
                timeout=10).read())
            assert resp == {"ok": True, "replicas": 2}
            # re-registration refreshes, never duplicates
            urllib.request.urlopen(urllib.request.Request(
                fleet_srv.url("/register"), data=body,
                headers={"Content-Type": "application/json"}),
                timeout=10).read()
            assert len(fc.replicas) == 2
            fc.refresh()
            st = json.loads(urllib.request.urlopen(
                fleet_srv.url("/status.json"), timeout=10).read())
            assert st["fleet"]["sketches"]["ttft_ms"]["count"] == 20
            assert set(st["replicas"]) == {"a", "b"}
            prom = urllib.request.urlopen(
                fleet_srv.url("/metrics"), timeout=10).read().decode()
            assert 'shallowspeed_ttft_ms{replica="a",quantile="0.95"}' \
                in prom
            assert 'shallowspeed_fleet_up{replica="b"} 1' in prom
        finally:
            fleet_srv.close()
    finally:
        srv_a.close()
        srv_b.close()


def test_fleet_slo_burns_over_merged_stream(tmp_path):
    """The fleet rule fires on the MERGED stream: each replica alone
    is under min_count, together they burn."""
    clock = [1000.0]
    fc = FleetCollector(
        paths=[_write_replica_jsonl(tmp_path / "a.jsonl", "a",
                                    [500.0] * 6),
               _write_replica_jsonl(tmp_path / "b.jsonl", "b",
                                    [600.0] * 6)],
        slos="ttft_p50_ms<100", clock=lambda: clock[0],
        slo_kw=dict(fast_s=10, slow_s=60, min_count=10))
    st = fc.refresh()
    assert st["alerts"] and st["alerts"][0]["state"] == "firing"
    assert fc.events and fc.events[-1]["event"] == "alert"
    # deltas, not cumulative re-feeds: a second refresh with no new
    # lines must not re-count the same observations
    clock[0] += 1
    rule = fc.rules[0]
    before = sum(c for _, _, c in rule._events)
    fc.refresh()
    assert sum(c for _, _, c in rule._events) == before


def test_fleet_straggler_fires_and_resolves(tmp_path):
    """Scripted skew: replica c's ttft p50 is ~6x the fleet median →
    sustained divergence fires a schema-v8 "straggler" naming c, a
    flight dump lands, and recovery resolves it."""
    reps = {"a": [20.0] * 10, "b": [22.0] * 10, "c": [130.0] * 10}
    paths = [_write_replica_jsonl(tmp_path / f"{r}.jsonl", r, v)
             for r, v in reps.items()]
    fc = FleetCollector(paths=paths,
                        straggler_metrics=("ttft_ms",),
                        straggler_patience=2, straggler_min_count=4,
                        flight=8, flight_dir=tmp_path)
    fc.refresh()
    assert not fc.stragglers           # patience: one round is a blip
    st = fc.refresh()
    assert st["stragglers"], st
    s = st["stragglers"][0]
    assert s["replica"] == "c" and s["metric"] == "ttft_ms"
    assert s["state"] == "firing" and s["ratio"] > 2.0
    rec = next(e for e in fc.events if e["event"] == "straggler")
    assert validate_line(rec) == []
    assert fc.flight.dumps, "straggler must dump the flight ring"
    dump = json.loads(Path(fc.flight.dumps[0]).read_text())
    assert dump["reason"] == "straggler:c:ttft_ms"
    # replica-labelled straggler gauge on /metrics
    assert 'shallowspeed_fleet_straggler{replica="c",' \
           'metric="ttft_ms"} 1' in fc.prometheus()
    # recovery: c's distribution comes back to the pack -> resolved
    _write_replica_jsonl(tmp_path / "c.jsonl", "c", [21.0] * 300)
    fc.refresh()
    assert not fc.stragglers
    assert fc.events[-1]["event"] == "straggler"
    assert fc.events[-1]["state"] == "resolved"


def test_fleet_mixed_rel_err_reduces_largest_group(tmp_path):
    # mixed-precision producers reduce to the largest same-rel_err
    # group, like the goodput monitor block
    a = _write_replica_jsonl(tmp_path / "a.jsonl", "a", [10.0] * 4)
    b = _write_replica_jsonl(tmp_path / "b.jsonl", "b", [10.0] * 4)
    fc = FleetCollector(paths=[a, b])
    fc.replicas[1]._mon.sketches = MetricSketches(rel_err=0.05)
    st = fc.refresh()
    assert st["fleet"]["sketches"]["ttft_ms"]["count"] == 4
    assert st["fleet"]["skipped_mixed_rel_err"] == 1


def test_fleet_colliding_replica_names_stay_distinct(tmp_path):
    """Two unlabelled replicas whose files share a basename must not
    collapse into one name-keyed entry: internal state is keyed by
    uid, display names get '#uid' suffixed on collision, and the
    straggler detector still sees every replica."""
    (tmp_path / "runA").mkdir()
    (tmp_path / "runB").mkdir()
    a = tmp_path / "runA" / "metrics.jsonl"
    b = tmp_path / "runB" / "metrics.jsonl"
    # no run_start 'replica' label in either file -> both stems are
    # 'metrics'
    for path, ttfts in ((a, [20.0] * 10), (b, [200.0] * 10)):
        with open(path, "w") as f:
            for i, t in enumerate(ttfts):
                f.write(json.dumps({"event": "request",
                                    "id": f"q{i}", "ttft_ms": t,
                                    "tokens_in": 1, "tokens_out": 1,
                                    "wall": 100.0 + i}) + "\n")
    fc = FleetCollector(paths=[a, b],
                        straggler_metrics=("ttft_ms",),
                        straggler_patience=1, straggler_min_count=4,
                        slos="ttft_p50_ms<100",
                        slo_kw=dict(fast_s=10, slow_s=60,
                                    min_count=5))
    st = fc.refresh()
    assert set(st["replicas"]) == {"metrics", "metrics#1"}
    assert st["fleet"]["sketches"]["ttft_ms"]["count"] == 20
    # straggler detection ran across BOTH replicas (not collapsed)
    assert st["stragglers"] and \
        st["stragglers"][0]["replica"] == "metrics#1"
    assert 'replica="metrics#1"' in fc.prometheus()
    # SLO deltas: exactly 10 bad / 20 total fed once, not corrupted
    # by a shared key
    assert sum(b for _, b, _ in fc.rules[0]._events) == 10
    assert sum(c for _, _, c in fc.rules[0]._events) == 20


def test_fleet_unreachable_endpoint_feeds_availability(tmp_path):
    clock = [500.0]
    fc = FleetCollector(urls=["http://127.0.0.1:9"],  # discard port
                        slos="availability>0.9",
                        clock=lambda: clock[0], timeout=0.2,
                        slo_kw=dict(fast_s=10, slow_s=100,
                                    warn_burn=2.0, critical_burn=50.0))
    st = fc.refresh()                  # baseline: no dt yet
    assert st["fleet"]["alive"] == 0
    assert st["replicas"]["http://127.0.0.1:9"]["error"]
    clock[0] += 30.0
    fc.refresh()            # 30s unreachable -> downtime burns BOTH
    assert fc.rules[0].burn(10, clock[0]) > 2.0   # windows (fires)
    assert fc.active_alerts


def test_fleet_main_once_over_files(tmp_path, capsys):
    _write_replica_jsonl(tmp_path / "a.jsonl", "a", [10.0] * 4)
    rc = fleet_main([str(tmp_path / "a.jsonl")], once=True)
    assert rc == 0
    out = capsys.readouterr().out
    assert "1/1 replicas alive" in out and "ttft_ms" in out
    assert fleet_main([str(tmp_path / "missing.jsonl")],
                      once=True) == 1


# ------------------------------------------------- lifecycle tracing


def test_lifecycle_events_validate_and_reconstruct(tmp_path,
                                                   serving_setup):
    params, cfg, kw = serving_setup
    path = tmp_path / "serve.jsonl"
    eng = _serve_replica(params, cfg, path, "solo", n_req=4, **kw)
    assert validate_file(path) == []
    timelines = request_timeline(path)
    assert set(timelines) == set(eng.results)
    for rid, tl in timelines.items():
        phases = [p["phase"] for p in tl["phases"]]
        assert phases[0] == "submit" and phases[1] == "queued"
        assert "admitted" in phases and "decoding" in phases
        assert phases[-1] == "finished" and tl["complete"]
        # span accounting reconciles: phase times sum to the e2e wall
        assert sum(tl["by_phase_ms"].values()) == pytest.approx(
            tl["e2e_ms"], abs=2.0)
    # a long prompt prefills in multiple chunks, each stamped
    long = request_timeline(path, rid="solo-q3")["solo-q3"]
    chunks = [p for p in long["phases"] if p["phase"] == "prefill"]
    assert len(chunks) >= 1 and chunks[0]["chunk"] == 0


def test_lifecycle_preemption_phases(serving_setup, tmp_path):
    """A pool small enough to force eviction shows the preempted ->
    requeued -> re-prefill arc in the victim's timeline."""
    import jax

    from shallowspeed_tpu.models import transformer as T

    cfg = T.TransformerConfig(vocab=32, d_model=16, n_heads=2,
                              n_layers=1, max_seq=64)
    params = jax.device_put(T.init(cfg, seed=0))
    path = tmp_path / "pre.jsonl"
    eng = _serve_replica(params, cfg, path, "pre", n_req=3, seed=3,
                         n_blocks=8, block_size=4, max_slots=3,
                         prefill_chunk=8)
    assert eng.counters["preempted"] >= 1
    timelines = request_timeline(path)
    victim = next(tl for tl in timelines.values()
                  if "preempted" in [p["phase"] for p in tl["phases"]])
    phases = [p["phase"] for p in victim["phases"]]
    i = phases.index("preempted")
    assert phases[i + 1] == "requeued"
    assert "prefill" in phases[i + 2:], phases  # re-prefills its ctx
    assert phases[-1] == "finished" and victim["complete"]


def test_lifecycle_named_tracks_in_chrome_trace(tmp_path,
                                                serving_setup):
    params, cfg, kw = serving_setup
    from shallowspeed_tpu.telemetry import trace

    tr = trace.configure(trace_dir=tmp_path / "tr", level="steps")
    try:
        _serve_replica(params, cfg, None, "tr", n_req=2, **kw)
        chrome = tr.chrome_trace()["traceEvents"]
    finally:
        trace.configure(level="off")
    names = {e["args"].get("name"): e["tid"] for e in chrome
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert "request tr-q0" in names and "request tr-q1" in names
    tid = names["request tr-q0"]
    spans = [e for e in chrome if e["ph"] == "X" and e["tid"] == tid]
    got = [e["name"] for e in spans]
    # each closed phase is one span on the request's own track,
    # cross-linked to the engine tick counter
    for phase in ("submit", "queued", "admitted", "prefill",
                  "decoding"):
        assert phase in got, (phase, got)
    assert all(e["args"].get("id") == "tr-q0" for e in spans)
    assert any(isinstance(e["args"].get("tick"), int) for e in spans)
    # spans.jsonl validates (ph "M" is schema-v8-legal)
    assert validate_file(tmp_path / "tr" / "spans.jsonl") == []


def test_lifecycle_off_emits_nothing(tmp_path, serving_setup):
    params, cfg, kw = serving_setup
    from shallowspeed_tpu.metrics import MetricsLogger
    from shallowspeed_tpu.serving import ServingEngine

    path = tmp_path / "off.jsonl"
    eng = ServingEngine(params, cfg,
                        metrics=MetricsLogger(path, kind="serve"),
                        lifecycle=False, **kw)
    eng.submit(np.arange(6, dtype=np.int32) % cfg.vocab, 4, rid="x")
    eng.run()
    recs = [json.loads(l) for l in path.read_text().splitlines()]
    assert not any(r.get("event") == "lifecycle" for r in recs)
    assert set(eng.results) == {"x"}     # serving itself unaffected


# ---------------------------------------- acceptance: quantile parity


def test_fleet_quantile_parity_3_replicas(tmp_path, serving_setup):
    """Acceptance: a 3-replica scripted serving run's fleet
    /status.json quantiles match the POOLED offline --goodput
    reduction within the recorded rel_err (the fleet generalization
    of the PR-8 live/offline parity canary)."""
    from shallowspeed_tpu.telemetry.goodput import run_goodput

    params, cfg, kw = serving_setup
    paths = []
    for r in range(3):
        p = tmp_path / f"rep{r}.jsonl"
        paths.append(p)
        _serve_replica(params, cfg, p, f"rep{r}", n_req=4, seed=10 + r,
                       **kw)
    fc = FleetCollector(paths=paths)
    srv = StatusServer(fc, port=0)
    try:
        fc.refresh()
        st = json.loads(urllib.request.urlopen(
            srv.url("/status.json"), timeout=10).read())
    finally:
        srv.close()
    assert st["fleet"]["alive"] == 3
    # pooled offline reduction: one file with all three stanzas
    pooled = tmp_path / "pooled.jsonl"
    pooled.write_text("".join(p.read_text() for p in paths))
    rep = run_goodput(pooled)
    off = rep["requests"]
    assert off["n_requests"] == 12
    rel = st["fleet"]["rel_err"]
    for name in ("ttft_ms", "tpot_ms"):
        for q in (50, 95):
            live = st["fleet"]["sketches"][name][f"p{q}"]
            exact = off[f"{name}_p{q}"]
            # same within_bound contract as the goodput monitor-block
            # parity: sketch vs exact within the recorded rel_err
            # (+1e-3 for the report's ms rounding)
            assert abs(live - exact) <= rel * abs(exact) + 1e-3, (
                name, q, live, exact)
    # the pooled reducer's own merged-monitor cross-check agrees with
    # the per-replica snapshots the engines streamed
    assert rep["monitor"] is None or all(
        v["within_bound"] for v in
        rep["monitor"].get("parity", {}).values())
    # the reducer's schema-v8 lifecycle block accounts every request's
    # phase time across the pooled fleet
    lc = rep["lifecycle"]
    assert lc["requests"] == 12 and lc["complete"] == 12
    assert {"queued", "decoding"} <= set(lc["by_phase_ms"])


# --------------------------- acceptance: stall chaos -> straggler


def test_stall_chaos_on_one_replica_names_straggler(tmp_path,
                                                    serving_setup):
    """Acceptance: a seeded `stall` chaos fault on exactly ONE of
    three replicas raises a "straggler" event naming that replica,
    and the stalled request's lifecycle timeline reconstructs its
    phases end-to-end."""
    from shallowspeed_tpu.chaos import FaultPlan

    params, cfg, kw = serving_setup
    paths = []
    for r in range(3):
        p = tmp_path / f"rep{r}.jsonl"
        paths.append(p)
        plan = (FaultPlan.parse("stall@1:1.0", log_file=str(p))
                if r == 1 else None)
        _serve_replica(params, cfg, p, f"rep{r}", n_req=4, seed=20 + r,
                       chaos_plan=plan, **kw)
    # the fault fired on rep1 and stamped its forensic record there
    recs = [json.loads(l) for l in paths[1].read_text().splitlines()]
    stalls = [r for r in recs if r.get("event") == "fault"
              and r.get("kind") == "stall"]
    assert len(stalls) == 1 and stalls[0]["seconds"] == 1.0
    assert not any(r.get("event") == "fault" for r in
                   (json.loads(l)
                    for p in (paths[0], paths[2])
                    for l in p.read_text().splitlines()))
    fc = FleetCollector(paths=paths,
                        straggler_metrics=("ttft_ms",),
                        straggler_patience=2, straggler_min_count=4,
                        flight=16, flight_dir=tmp_path)
    fc.refresh()
    st = fc.refresh()                 # sustained for `patience` rounds
    assert st["stragglers"], st["replicas"]
    s = st["stragglers"][0]
    assert s["replica"] == "rep1" and s["state"] == "firing"
    assert validate_line(
        next(e for e in fc.events if e["event"] == "straggler")) == []
    # the 1s stall dwarfs the healthy replicas' ttft
    assert s["ratio"] >= 2.0, s
    # end-to-end lifecycle reconstruction of a stalled request: every
    # phase from submit to finished, with the stall's second showing
    # up in the phase the request was in when the engine slept
    timelines = request_timeline(paths[1])
    worst_rid = st["worst_ttft"][0]["id"]
    assert st["worst_ttft"][0]["replica"] == "rep1"
    tl = timelines[worst_rid]
    assert tl["complete"] and tl["e2e_ms"] >= 1000.0
    assert sum(tl["by_phase_ms"].values()) == pytest.approx(
        tl["e2e_ms"], abs=2.0)
    assert max(tl["by_phase_ms"].values()) >= 900.0


# --------------------------- deregistration + poller backoff (round 15)


def test_fleet_deregistration_removes_replica_and_state(tmp_path):
    """POST /deregister (the missing half of /register): the drained
    replica leaves the fleet view entirely — no eternal "unreachable"
    availability burn — and its uid-keyed straggler/SLO state is
    purged so a later replica reusing the name starts clean."""
    reps = {"a": [20.0] * 10, "b": [22.0] * 10, "c": [130.0] * 10}
    fc = FleetCollector(
        paths=[_write_replica_jsonl(tmp_path / f"{r}.jsonl", r, v)
               for r, v in reps.items()],
        slos="ttft_p50_ms<100",
        slo_kw=dict(fast_s=10, slow_s=60, min_count=5),
        straggler_metrics=("ttft_ms",), straggler_patience=1,
        straggler_min_count=4)
    fc.refresh()
    assert fc.stragglers                 # c diverges
    uid_c = next(rep.uid for rep in fc.replicas if rep.name == "c")
    out = fc.deregister_replica({"name": "c"})
    assert out == {"ok": True, "replicas": 2, "removed": "c"}
    assert not fc.stragglers             # uid-keyed state purged
    assert not any(k[0] == uid_c for k in fc._ewma)
    assert not any(k[1] == uid_c for k in fc._slo_prev)
    st = fc.refresh()
    assert set(st["replicas"]) == {"a", "b"}
    with pytest.raises(ValueError, match="no replica"):
        fc.deregister_replica({"name": "ghost"})


def test_fleet_deregister_over_http(tmp_path):
    mon = Monitor(label="x", flight=0)
    for i in range(4):
        mon.note_line({"event": "request", "id": f"x{i}",
                       "ttft_ms": 10.0, "tokens_in": 1,
                       "tokens_out": 1, "wall": 50.0 + i})
    srv_x = StatusServer(mon, port=0)
    fc = FleetCollector(urls=[srv_x.url("/status.json")],
                        labels=["x"])
    fleet_srv = StatusServer(fc, port=0)
    try:
        body = json.dumps({"url": srv_x.url("/status.json")}).encode()
        resp = json.loads(urllib.request.urlopen(
            urllib.request.Request(
                fleet_srv.url("/deregister"), data=body,
                headers={"Content-Type": "application/json"}),
            timeout=10).read())
        assert resp["ok"] and resp["replicas"] == 0
        # deregistering the unknown again is a 400, not a crash
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                fleet_srv.url("/deregister"), data=body,
                headers={"Content-Type": "application/json"}),
                timeout=10)
        assert ei.value.code == 400
    finally:
        fleet_srv.close()
        srv_x.close()


def test_fleet_register_by_name_repoints_respawned_replica():
    """A respawned replica binds a fresh port and re-announces under
    its NAME: registration re-points the existing replica's URL (uid,
    history, straggler state stay attached) instead of duplicating,
    and resets the poller's backoff."""
    fc = FleetCollector(urls=["http://127.0.0.1:9"], labels=["r0"])
    rep = fc.replicas[0]
    rep.fail_streak, rep.next_poll = 3, 1e18      # deep in backoff
    out = fc.register_replica({"url": "http://127.0.0.1:10101",
                               "name": "r0"})
    assert out["replicas"] == 1                   # no duplicate
    assert rep.url == "http://127.0.0.1:10101"
    assert rep.fail_streak == 0 and rep.next_poll == 0.0


def test_fleet_poller_backoff_on_unreachable(monkeypatch):
    """An unreachable endpoint backs off exponentially (with jitter)
    instead of hot re-polling every round: attempts 1, 2 fire, then
    refreshes inside the backoff window cost NO I/O; the window
    doubles per failure (capped), the per-replica breakdown names the
    state, and downtime keeps burning availability on skipped
    rounds."""
    clock = [100.0]
    fc = FleetCollector(urls=["http://127.0.0.1:9"],
                        slos="availability>0.9",
                        clock=lambda: clock[0], timeout=0.1,
                        slo_kw=dict(fast_s=10, slow_s=100))
    rep = fc.replicas[0]
    attempts = []

    def failing_get(endpoint):
        attempts.append(clock[0])
        raise OSError("connection refused")

    monkeypatch.setattr(rep, "_get", failing_get)
    fc.refresh()
    assert len(attempts) == 1 and rep.fail_streak == 1
    b1 = rep.backoff_s
    assert 1.0 <= b1 <= 1.25          # base 1s, jitter <= 25%
    summary = rep.summary()
    assert summary["backoff"]["failures"] == 1
    assert summary["backoff"]["retry_at"] == pytest.approx(
        100.0 + b1, abs=1e-3)      # summary rounds to ms
    clock[0] += b1 / 2
    fc.refresh()                       # inside the window: skipped
    assert len(attempts) == 1
    clock[0] += b1                     # past it: retried, doubles
    fc.refresh()
    assert len(attempts) == 2 and rep.fail_streak == 2
    assert 2.0 <= rep.backoff_s <= 2.5
    # skipped rounds still burn the availability rule
    assert fc.rules[0].burn(10, clock[0]) > 0
    # success resets the stream (swap in a working _get)
    monkeypatch.setattr(
        rep, "_get",
        lambda ep: {"sketches": {}, "rel_err": 0.01}
        if ep == "/sketches.json" else {})
    clock[0] += rep.backoff_s + 0.01
    fc.refresh()
    assert rep.fail_streak == 0 and rep.backoff_s == 0.0
    assert "backoff" not in rep.summary()


# ----------------------------------------------- gang supervisor wiring


def test_gang_supervisor_grows_fleet_collector(tmp_path):
    from shallowspeed_tpu.elastic import (GangSupervisor,
                                          _set_argv_log_file)

    assert _set_argv_log_file(["x", "--log-file", "a.jsonl"], "b")[2] \
        == "b"
    assert _set_argv_log_file(["x", "--log-file=a.jsonl"], "b")[1] \
        == "--log-file=b"
    assert _set_argv_log_file(["x"], "b")[-2:] == ["--log-file", "b"]

    base = str(tmp_path / "gang.jsonl")
    sup = GangSupervisor(["prog", "--log-file", base], n_procs=3,
                         monitor_port=0)
    # per-member files: stanzas never interleave; member 0's file is
    # the supervisor's ledger/poison evidence
    assert sup.member_log_files == [f"{base}.r{i}" for i in range(3)]
    assert sup.ledger_file == f"{base}.r0"
    for i, f in enumerate(sup.member_log_files):
        _write_replica_jsonl(f, f"m{i}", [10.0 + i] * 3)
    fc, srv, tailer = sup._start_monitor()
    try:
        assert isinstance(fc, FleetCollector) and tailer is fc
        st = fc.refresh()
        assert st["fleet"]["sketches"]["ttft_ms"]["count"] == 9
        assert set(st["replicas"]) == {"r0", "r1", "r2"}
    finally:
        fc.stop()
        srv.close()
    # without --log-file there is nothing to aggregate
    sup2 = GangSupervisor(["prog"], n_procs=2, monitor_port=0)
    assert sup2._start_monitor() == (None, None, None)


def test_replica_name_fallback_is_file_stem(tmp_path):
    p = tmp_path / "west-7.jsonl"
    with open(p, "w") as f:
        f.write(json.dumps({"event": "step", "step": 0, "loss": 1.0,
                            "tokens_per_sec": 5.0, "wall": 1.0}) + "\n")
    rep = Replica(None, path=p)
    rep.refresh(0.0)
    assert rep.name == "west-7"


def test_format_fleet_status_renders_stragglers(tmp_path):
    reps = {"a": [20.0] * 10, "b": [22.0] * 10, "c": [130.0] * 10}
    fc = FleetCollector(
        paths=[_write_replica_jsonl(tmp_path / f"{r}.jsonl", r, v)
               for r, v in reps.items()],
        straggler_metrics=("ttft_ms",), straggler_patience=1,
        straggler_min_count=4)
    out = format_fleet_status(fc.refresh())
    assert "3/3 replicas alive" in out
    assert "STRAGGLER c ttft_ms" in out
    assert "worst ttft" in out and "@ c" in out
