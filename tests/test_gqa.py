"""Grouped-query attention (`TransformerConfig.n_kv_heads`).

K/V heads are shared across query groups: the projection splits into
q / kv params, K/V repeat to the full head count just before the
attention op (so every substrate works unchanged), and the decode cache
stores the unrepeated heads — its memory shrinks by the group factor.
"""

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.models.generate import (
    decode_step, generate, init_kv_cache, prefill)
from shallowspeed_tpu.optim import Adam, SGD
from shallowspeed_tpu.parallel.context import ContextParallelEngine
from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine
from shallowspeed_tpu.parallel.tensor import TensorParallelEngine

CFG = T.TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                          max_seq=32, n_kv_heads=2)
MODERN = replace(CFG, rope=True, norm="rmsnorm", ffn="swiglu")


def toks(seed=0, b=4, t=32, vocab=64):
    rng = np.random.default_rng(seed)
    tok = rng.integers(0, vocab, (b, t)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1).astype(np.int32)


# ------------------------------------------------------------- structure


def test_gqa_param_structure():
    params = T.init(CFG, seed=1)
    blk = params["blocks"][0]
    assert "qkv" not in blk and "q" in blk and "kv" in blk
    assert blk["q"]["W"].shape == (32, 32)
    assert blk["kv"]["W"].shape == (32, 2 * 2 * 8)  # 2 kv heads x (k, v)
    # n_kv_heads == n_heads (or 0) keeps the fused projection
    for cfg in (replace(CFG, n_kv_heads=0), replace(CFG, n_kv_heads=4)):
        assert "qkv" in T.init(cfg, seed=1)["blocks"][0]


def test_invalid_group_rejected():
    with pytest.raises(AssertionError, match="divisible by"):
        T.TransformerConfig(n_heads=4, n_kv_heads=3)


def test_cache_stores_unrepeated_heads():
    # head-major slot layout (round 5): (B, Hkv, S, hd)
    cache = init_kv_cache(CFG, batch=2)
    assert cache[0]["k"].shape == (2, 2, CFG.max_seq, CFG.head_dim)


def test_repeat_kv():
    x = jnp.arange(2 * 3 * 2 * 4).reshape(2, 3, 2, 4).astype(jnp.float32)
    r = T.repeat_kv(x, CFG)  # group factor 2
    assert r.shape == (2, 3, 4, 4)
    np.testing.assert_array_equal(np.asarray(r[:, :, 0]),
                                  np.asarray(r[:, :, 1]))


# ---------------------------------------------------------- equivalence


def serial_engine(cfg, opt):
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("dp", "sp"))
    return ContextParallelEngine(cfg, opt, mesh, seed=0)


@pytest.mark.parametrize("attn", ["ring", "ulysses"])
def test_gqa_under_sequence_sharding(attn):
    ser = serial_engine(MODERN, SGD(0.1))
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "sp"))
    eng = ContextParallelEngine(MODERN, SGD(0.1), mesh, seed=0, attn=attn)
    for step in range(3):
        tok, tgt = toks(step)
        assert eng.train_batch(tok, tgt) == pytest.approx(
            ser.train_batch(tok, tgt), rel=3e-4), (step, attn)


def test_gqa_under_tensor_parallel():
    """tp=2 with 4 q heads / 2 kv heads: each shard owns 2 q heads and 1
    kv head; repeat happens per-shard after the column projections."""
    ser = serial_engine(MODERN, SGD(0.1))
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))
    eng = TensorParallelEngine(MODERN, SGD(0.1), mesh, seed=0)
    assert "tp" in eng.params["blocks"][0]["kv"]["W"].sharding.spec
    for step in range(3):
        tok, tgt = toks(step)
        assert eng.train_batch(tok, tgt) == pytest.approx(
            ser.train_batch(tok, tgt), rel=3e-4), step


def test_gqa_under_pipeline_tp():
    ser = serial_engine(MODERN, SGD(0.1))
    devs = np.array(jax.devices()[:4]).reshape(1, 2, 2)
    eng = PipelineLMEngine(MODERN, SGD(0.1), Mesh(devs, ("dp", "pp", "tp")),
                           n_mubatches=2, seed=0)
    for step in range(3):
        tok, tgt = toks(step, b=8)
        assert eng.train_batch(tok, tgt) == pytest.approx(
            ser.train_batch(tok, tgt), rel=3e-4), step


def test_kv_heads_indivisible_by_tp_rejected():
    cfg = replace(CFG, n_kv_heads=1)  # 1 kv head cannot split over tp=2
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(1, 2), ("dp", "tp"))
    with pytest.raises(AssertionError, match="n_kv_heads"):
        TensorParallelEngine(cfg, SGD(0.1), mesh)


# ------------------------------------------------------------- decoding


def test_gqa_cached_decode_matches_forward():
    params = T.init(MODERN, seed=4)
    tokens, _ = toks(1, b=2, t=10)
    ref = np.asarray(T.forward(params, tokens, MODERN))
    cache = init_kv_cache(MODERN, 2)
    logits, cache = prefill(params, tokens[:, :1], MODERN, cache)
    np.testing.assert_allclose(np.asarray(logits), ref[:, 0],
                               rtol=1e-4, atol=1e-5)
    for pos in range(1, tokens.shape[1]):
        logits, cache = decode_step(params, jnp.asarray(tokens[:, pos]),
                                    pos, cache, MODERN)
        np.testing.assert_allclose(np.asarray(logits), ref[:, pos],
                                   rtol=1e-4, atol=1e-5, err_msg=str(pos))


def test_gqa_trains_and_generates():
    cfg = replace(MODERN, compute_dtype=jnp.bfloat16)
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2, 1), ("dp", "sp"))
    eng = ContextParallelEngine(cfg, Adam(5e-3), mesh, seed=0)
    tok, tgt = toks(7)
    losses = [eng.train_batch(tok, tgt) for _ in range(20)]
    assert losses[-1] < losses[0] - 0.15, losses[::5]
    out = np.asarray(generate(eng.params, tok[:1, :4], cfg, 8,
                              temperature=0.0))
    assert out.shape == (1, 8)


def test_gqa_with_moe_engine():
    """Expert-parallel specs must carry the split q/kv keys under GQA."""
    from shallowspeed_tpu.parallel.expert import ExpertParallelEngine

    cfg = replace(CFG, n_experts=4, moe_top_k=2)
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(1, 4), ("dp", "ep"))
    eng = ExpertParallelEngine(cfg, Adam(5e-3), mesh, seed=0)
    tok, tgt = toks(9)
    losses = [eng.train_batch(tok, tgt) for _ in range(15)]
    assert losses[-1] < losses[0] - 0.1, losses[::4]


def test_negative_kv_heads_rejected():
    with pytest.raises(AssertionError, match="non-negative"):
        T.TransformerConfig(n_heads=4, n_kv_heads=-2)
