"""Structured metrics sink (`shallowspeed_tpu/metrics.py`)."""

import json

from shallowspeed_tpu.metrics import MetricsLogger


def read_jsonl(path):
    return [json.loads(line) for line in open(path)]


def test_writes_run_start_and_records(tmp_path):
    p = tmp_path / "sub" / "m.jsonl"  # parent dir is created on demand
    m = MetricsLogger(p, dp=2, seq_len=128)
    m.log(event="step", step=3, loss=1.5)
    m.epoch(epoch=0, accuracy_start=0.1234567, samples=1280,
            epoch_seconds=2.0)
    m.final(accuracy=0.95, total_seconds=10.0)

    rows = read_jsonl(p)
    assert [r["event"] for r in rows] == ["run_start", "step", "epoch",
                                         "final"]
    assert rows[0]["dp"] == 2 and rows[0]["seq_len"] == 128
    assert rows[1]["step"] == 3 and rows[1]["loss"] == 1.5
    assert rows[2]["samples_per_sec"] == 640.0
    assert rows[2]["accuracy_start"] == 0.123457  # rounded to 6 places
    assert rows[3]["accuracy"] == 0.95
    for r in rows:
        assert "t" in r and r["t"] >= 0  # relative wall-clock on every row


def test_append_only_across_loggers(tmp_path):
    p = tmp_path / "m.jsonl"
    MetricsLogger(p).log(event="a")
    MetricsLogger(p).log(event="b")  # resumed run appends, never truncates
    events = [r["event"] for r in read_jsonl(p)]
    assert events == ["run_start", "a", "run_start", "b"]


def test_noop_without_path(tmp_path):
    m = MetricsLogger(None)
    m.log(event="x")
    m.epoch(0, 0.5, 100, 1.0)
    m.final(0.9, 1.0)  # must not raise or write anywhere
    assert list(tmp_path.iterdir()) == []


def test_zero_epoch_seconds_guard(tmp_path):
    p = tmp_path / "m.jsonl"
    m = MetricsLogger(p)
    m.epoch(0, 0.5, 100, 0.0)  # no ZeroDivisionError
    assert read_jsonl(p)[-1]["samples_per_sec"] == 0.0
