"""Structured metrics sink (`shallowspeed_tpu/metrics.py`)."""

import json

import pytest

from shallowspeed_tpu.metrics import MetricsLogger


def read_jsonl(path):
    return [json.loads(line) for line in open(path)]


def test_writes_run_start_and_records(tmp_path):
    p = tmp_path / "sub" / "m.jsonl"  # parent dir is created on demand
    m = MetricsLogger(p, dp=2, seq_len=128)
    m.log(event="step", step=3, loss=1.5)
    m.epoch(epoch=0, accuracy_start=0.1234567, samples=1280,
            epoch_seconds=2.0)
    m.final(accuracy=0.95, total_seconds=10.0)

    rows = read_jsonl(p)
    assert [r["event"] for r in rows] == ["run_start", "step", "epoch",
                                         "final"]
    assert rows[0]["dp"] == 2 and rows[0]["seq_len"] == 128
    assert rows[1]["step"] == 3 and rows[1]["loss"] == 1.5
    assert rows[2]["samples_per_sec"] == 640.0
    assert rows[2]["accuracy_start"] == 0.123457  # rounded to 6 places
    assert rows[3]["accuracy"] == 0.95
    for r in rows:
        assert "t" in r and r["t"] >= 0  # relative wall-clock on every row


def test_append_only_across_loggers(tmp_path):
    p = tmp_path / "m.jsonl"
    MetricsLogger(p).log(event="a")
    MetricsLogger(p).log(event="b")  # resumed run appends, never truncates
    events = [r["event"] for r in read_jsonl(p)]
    assert events == ["run_start", "a", "run_start", "b"]


def test_persistent_handle_survives_external_rotation(tmp_path):
    """Round 16 kept one flushed append handle per logger (reopening
    per line taxed the serving lifecycle stream); the per-line reopen's
    rotation tolerance must survive: after an external mv/unlink, later
    lines land in a fresh file at the path, not the orphaned inode."""
    p = tmp_path / "m.jsonl"
    m = MetricsLogger(p, kind="serve")
    m.log(event="step", step=1)
    (tmp_path / "m.jsonl.1").write_bytes(p.read_bytes())
    p.unlink()          # logrotate-style: old inode moved away
    m.log(event="step", step=2)
    rows = read_jsonl(p)
    assert [r.get("step") for r in rows] == [2]
    m.close()
    rotated = read_jsonl(tmp_path / "m.jsonl.1")
    assert [r.get("step") for r in rotated] == [None, 1]


def test_noop_without_path(tmp_path):
    m = MetricsLogger(None)
    m.log(event="x")
    m.epoch(0, 0.5, 100, 1.0)
    m.final(0.9, 1.0)  # must not raise or write anywhere
    assert list(tmp_path.iterdir()) == []


def test_zero_epoch_seconds_guard(tmp_path):
    p = tmp_path / "m.jsonl"
    m = MetricsLogger(p)
    m.epoch(0, 0.5, 100, 0.0)  # no ZeroDivisionError
    assert read_jsonl(p)[-1]["samples_per_sec"] == 0.0


# ------------------------------------------------ StepRates (round 5)


def test_step_rates_window_vs_cumulative_fake_clock():
    """The round-4 endurance lesson, pinned: a slow first window (think
    compile) must NOT depress later windows' rate — the window rate
    recovers immediately while the cumulative keeps amortizing it."""
    from shallowspeed_tpu.metrics import StepRates

    t = [0.0]
    r = StepRates(tokens_per_step=100, clock=lambda: t[0])
    t[0] = 10.0  # 10s for the first 10 steps (compile-heavy)
    first = r.log_point(10)
    assert first["tokens_per_sec"] == pytest.approx(100.0)
    assert first["tokens_per_sec_cum"] == pytest.approx(100.0)
    t[0] = 11.0  # then 10 steps in 1s (steady state)
    second = r.log_point(10)
    assert second["tokens_per_sec"] == pytest.approx(1000.0)
    # cumulative still dominated by the slow window: 2000 tok / 11 s
    assert second["tokens_per_sec_cum"] == pytest.approx(2000 / 11)


def test_step_rates_pauses_excluded_from_both():
    from shallowspeed_tpu.metrics import StepRates

    t = [0.0]
    r = StepRates(tokens_per_step=10, clock=lambda: t[0])
    t[0] = 1.0
    r.log_point(1)                      # 10 tok in 1s
    r.pause(5.0)                        # a checkpoint save
    t[0] = 7.0                          # 1s of training + the 5s pause
    out = r.log_point(1)
    assert out["tokens_per_sec"] == pytest.approx(10.0)
    assert out["tokens_per_sec_cum"] == pytest.approx(20 / 2.0)


def test_step_rates_window_matches_burst_rate_zero_pause():
    """window == cumulative when every second is training (no pauses,
    uniform speed) — the short-fused-run sanity the VERDICT asked for."""
    from shallowspeed_tpu.metrics import StepRates

    t = [0.0]
    r = StepRates(tokens_per_step=7, clock=lambda: t[0])
    for k in range(1, 5):
        t[0] = float(k)
        out = r.log_point(1)
        assert out["tokens_per_sec"] == pytest.approx(7.0)
        assert out["tokens_per_sec_cum"] == pytest.approx(7.0)
