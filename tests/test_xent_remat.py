"""Chunked cross-entropy + selective-remat-policy equivalence tests.

The round-3 perf work (VERDICT r2 item 1) must not change semantics:
- `cfg.xent_chunk > 0` computes the SAME loss/gradients as the classic
  whole-batch log-softmax (reassociated per chunk — tolerance, not
  bitwise), for every head variant (untied, tied, soft-capped,
  label-smoothed) and any chunk size incl. non-divisors.
- every `cfg.remat_policy` produces bit-identical gradients to the
  non-remat forward (checkpointing changes WHEN values are computed,
  never WHAT).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shallowspeed_tpu.models import transformer as T

BASE = dict(vocab=89, d_model=32, n_heads=2, n_layers=2, max_seq=64,
            rope=True, norm="rmsnorm", ffn="swiglu")


def batch(b=3, t=40, vocab=89, seed=0):
    rng = np.random.default_rng(seed)
    tok = rng.integers(0, vocab, (b, t)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1)


def grads(cfg, params, tok, tgt):
    return jax.grad(lambda p: T.loss(p, tok, tgt, cfg))(params)


def max_leaf_diff(a, b):
    return max(float(jnp.abs(x - y).max()) for x, y in zip(
        jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)))


@pytest.mark.parametrize("chunk", [1, 17, 40, 64, 1000])
def test_chunked_xent_matches_plain(chunk):
    cfg = T.TransformerConfig(**BASE)
    cfgc = T.TransformerConfig(**BASE, xent_chunk=chunk)
    params = T.init(cfg, seed=2)
    tok, tgt = batch()
    l0 = float(T.loss(params, tok, tgt, cfg))
    l1 = float(T.loss(params, tok, tgt, cfgc))
    assert abs(l0 - l1) < 1e-5
    assert max_leaf_diff(grads(cfg, params, tok, tgt),
                         grads(cfgc, params, tok, tgt)) < 1e-4


@pytest.mark.parametrize("extra", [
    {"tie_embeddings": True},
    {"logit_softcap": 5.0},
    {"label_smoothing": 0.1},
    {"tie_embeddings": True, "logit_softcap": 5.0,
     "label_smoothing": 0.05},
])
def test_chunked_xent_head_variants(extra):
    cfg = T.TransformerConfig(**BASE, **extra)
    cfgc = T.TransformerConfig(**BASE, **extra, xent_chunk=13)
    params = T.init(cfg, seed=3)
    tok, tgt = batch(seed=1)
    assert abs(float(T.loss(params, tok, tgt, cfg))
               - float(T.loss(params, tok, tgt, cfgc))) < 1e-5
    assert max_leaf_diff(grads(cfg, params, tok, tgt),
                         grads(cfgc, params, tok, tgt)) < 1e-4


def test_chunked_xent_eval_ignores_smoothing():
    """train=False drops label smoothing in the chunked path too."""
    cfg = T.TransformerConfig(**BASE, label_smoothing=0.2)
    cfgc = T.TransformerConfig(**BASE, label_smoothing=0.2, xent_chunk=16)
    params = T.init(cfg, seed=4)
    tok, tgt = batch(seed=2)
    l0 = float(T.loss(params, tok, tgt, cfg, train=False))
    l1 = float(T.loss(params, tok, tgt, cfgc, train=False))
    ltrain = float(T.loss(params, tok, tgt, cfgc, train=True))
    assert abs(l0 - l1) < 1e-5
    assert abs(l1 - ltrain) > 1e-4  # smoothing actually does something


@pytest.mark.parametrize("policy", ["full", "attn", "dots"])
def test_remat_policy_grads_exact(policy):
    cfg = T.TransformerConfig(**BASE)
    cfgr = T.TransformerConfig(**BASE, remat=True, remat_policy=policy)
    params = T.init(cfg, seed=5)
    tok, tgt = batch(seed=3)
    # remat recomputes the saved-policy residuals in a separately
    # compiled backward region, and XLA is free to fuse/reorder those
    # f32 reductions differently from the stashed-forward program — the
    # replays are mathematically identical but not bitwise (measured
    # 3.6e-7 on this jax/XLA; one ulp at grad scale ~0.3). Assert to
    # float-associativity tolerance, not bit equality.
    assert max_leaf_diff(grads(cfg, params, tok, tgt),
                         grads(cfgr, params, tok, tgt)) < 2e-6


def test_remat_policy_composes_with_chunked_xent():
    cfg = T.TransformerConfig(**BASE)
    cfgrc = T.TransformerConfig(**BASE, remat=True, remat_policy="dots",
                                xent_chunk=32)
    params = T.init(cfg, seed=6)
    tok, tgt = batch(seed=4)
    assert abs(float(T.loss(params, tok, tgt, cfg))
               - float(T.loss(params, tok, tgt, cfgrc))) < 1e-5
    assert max_leaf_diff(grads(cfg, params, tok, tgt),
                         grads(cfgrc, params, tok, tgt)) < 1e-4


def test_d_ff_flows_to_init_forward_and_flops():
    from shallowspeed_tpu.flops import transformer_flops_per_token

    cfg = T.TransformerConfig(**BASE, d_ff=48)
    params = T.init(cfg, seed=7)
    assert params["blocks"][0]["up"]["W"].shape == (32, 48)
    tok, tgt = batch(seed=5)
    assert np.isfinite(float(T.loss(params, tok, tgt, cfg)))
    wide = T.TransformerConfig(**BASE)
    assert (transformer_flops_per_token(cfg, 40)
            < transformer_flops_per_token(wide, 40))

def test_mfu_n_chips_deprecated_and_conflict_raises():
    """ADVICE r4: the deprecated `n_chips` keyword must warn, and a
    conflicting explicit `n_devices` must raise rather than be silently
    overridden."""
    import warnings

    import pytest

    from shallowspeed_tpu.flops import mfu

    cfg = T.TransformerConfig(**BASE)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        out = mfu(1000.0, cfg, seq_len=40, n_chips=4)
    assert any(issubclass(x.category, DeprecationWarning) for x in w)
    assert out["tflops"] > 0
    with pytest.raises(ValueError, match="n_devices"):
        mfu(1000.0, cfg, seq_len=40, n_devices=2, n_chips=4)
    # agreeing values stay accepted (stale call sites passing both)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        mfu(1000.0, cfg, seq_len=40, n_devices=4, n_chips=4)
