"""Sliding-window (local) attention (`cfg.attn_window`, `--attn-window`).

Contracts: window >= seq equals full causal attention exactly; a small
window actually restricts the receptive field; the decode cache applies
the SAME window so cached sampling reproduces the batched forward; and
the windowed model trains through the plain, GSPMD, and pipeline
engines.
"""

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.ops.attention import attention
from shallowspeed_tpu.optim import SGD, Adam
from shallowspeed_tpu.parallel.context import ContextParallelEngine
from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine

CFG = T.TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                          max_seq=32)


def batch(step, b=4, t=32, vocab=64):
    rng = np.random.default_rng([13, step])
    tok = rng.integers(0, vocab, (b, t)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1).astype(np.int32)


def mesh2(dp):
    return Mesh(np.array(jax.devices()[:dp]).reshape(dp, 1), ("dp", "sp"))


# ---------------------------------------------------------------- op level


def test_window_geq_seq_is_full_attention():
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(size=(2, 16, 2, 8)), jnp.float32)
               for _ in range(3))
    full = attention(q, k, v, causal=True)
    for w in (16, 100):
        np.testing.assert_array_equal(
            np.asarray(attention(q, k, v, causal=True, window=w)),
            np.asarray(full))


def test_window_restricts_receptive_field():
    """Perturbing a key OUTSIDE the window must not change the output;
    inside the window it must."""
    rng = np.random.default_rng(1)
    q, k, v = (jnp.asarray(rng.normal(size=(1, 16, 1, 8)), jnp.float32)
               for _ in range(3))
    w = 4
    base = np.asarray(attention(q, k, v, causal=True, window=w))
    v_out = v.at[0, 2].add(100.0)   # position 2: outside window of q=15
    np.testing.assert_array_equal(
        base[0, 15], np.asarray(
            attention(q, k, v_out, causal=True, window=w))[0, 15])
    v_in = v.at[0, 14].add(100.0)   # inside [12, 15]
    assert not np.allclose(
        base[0, 15], np.asarray(
            attention(q, k, v_in, causal=True, window=w))[0, 15])


# ------------------------------------------------------------- model level


def test_windowed_model_differs_and_window_max_matches():
    params = jax.device_put(T.init(CFG, seed=0))
    tok, _ = batch(0, b=2)
    full = np.asarray(T.forward(params, tok, CFG))
    same = np.asarray(T.forward(
        params, tok, replace(CFG, attn_window=CFG.max_seq)))
    np.testing.assert_array_equal(full, same)
    small = np.asarray(T.forward(params, tok, replace(CFG, attn_window=4)))
    assert not np.allclose(full, small)


def test_decode_matches_windowed_forward():
    """The KV-cache decode path applies the same window: teacher-forced
    cached logits equal the batched windowed forward's."""
    from shallowspeed_tpu.models.generate import (decode_step,
                                                  init_kv_cache, prefill)

    cfg = replace(CFG, attn_window=4, rope=True, n_kv_heads=2)
    params = jax.device_put(T.init(cfg, seed=0))
    tok, _ = batch(0, b=1, t=12)
    ref = np.asarray(T.forward(params, tok, cfg))        # (1, 12, V)
    cache = init_kv_cache(cfg, 1)
    logits, cache = prefill(params, tok[:, :6], cfg, cache)
    np.testing.assert_allclose(np.asarray(logits)[0], ref[0, 5],
                               rtol=2e-4, atol=2e-5)
    for i in range(6, 12):
        logits, cache = decode_step(params, jnp.asarray(tok[:, i]), i,
                                    cache, cfg)
        np.testing.assert_allclose(np.asarray(logits)[0], ref[0, i],
                                   rtol=2e-4, atol=2e-5)


# ------------------------------------------------------------ engine level


def test_windowed_training_plain_and_pipeline_agree():
    cfg = replace(CFG, attn_window=8, n_layers=4)
    ref = ContextParallelEngine(cfg, SGD(0.1), mesh2(1), seed=0)
    eng = PipelineLMEngine(
        cfg, SGD(0.1),
        Mesh(np.array(jax.devices()[:4]).reshape(1, 4), ("dp", "pp")),
        n_mubatches=2, seed=0, schedule="1f1b")
    for s in range(3):
        tok, tgt = batch(s, b=8)
        assert eng.train_batch(tok, tgt) == pytest.approx(
            ref.train_batch(tok, tgt), rel=3e-4), s


def test_windowed_trains():
    cfg = replace(CFG, attn_window=8)
    eng = ContextParallelEngine(cfg, Adam(5e-3), mesh2(2), seed=0)
    losses = [eng.train_batch(*batch(s % 4, b=8)) for s in range(20)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses[::5]


def test_window_composes_with_fused_substrates():
    """Windows now compose with EVERY substrate (the round-1 verdict's
    gap): flash (tile-skipping kernel), sequence-sharded ring, and
    ulysses-flash must all train the same windowed model — per-step
    losses match the masked-XLA reference."""
    cfg = replace(CFG, attn_window=8)
    ref = ContextParallelEngine(cfg, SGD(0.1), mesh2(1), seed=0)
    mesh_sp = Mesh(np.array(jax.devices()[:2]).reshape(1, 2), ("dp", "sp"))
    engines = {
        "flash": ContextParallelEngine(cfg, SGD(0.1), mesh2(1), seed=0,
                                       attn="flash"),
        "ring-sp2": ContextParallelEngine(cfg, SGD(0.1), mesh_sp, seed=0),
        "ulysses-flash-sp2": ContextParallelEngine(
            cfg, SGD(0.1), mesh_sp, seed=0, attn="ulysses-flash"),
        "ring-flash-sp2": ContextParallelEngine(
            cfg, SGD(0.1), mesh_sp, seed=0, attn="ring-flash"),
        "pipeline-flash": PipelineLMEngine(
            cfg, SGD(0.1),
            Mesh(np.array(jax.devices()[:2]).reshape(1, 2), ("dp", "pp")),
            n_mubatches=2, seed=0, attn="flash"),
    }
    for s in range(3):
        tok, tgt = batch(s, b=8)
        want = ref.train_batch(tok, tgt)
        for name, eng in engines.items():
            assert eng.train_batch(tok, tgt) == pytest.approx(
                want, rel=3e-4), (name, s)
