"""Compiled 1F1B / PipeDream-Flush schedule (`parallel/pipeline_lm.py`,
`schedule="1f1b"`).

The reference *declares* PipeDream and crashes on selecting it
(`/root/reference/shallowspeed/pipe.py:297-299`); the pipeline VM here
runs 1F1B interpreted (`test_schedules.py`); this file covers the
fully-compiled SPMD form. Oracle: 1F1B reorders microbatch work but
computes the SAME gradient sum as GPipe, so every layout must match the
plain data-parallel engine step for step — the same equivalence bar the
GPipe engine is held to (`test_pipeline_lm.py`).
"""

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.optim import SGD, Adam
from shallowspeed_tpu.parallel.context import ContextParallelEngine
from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine

CFG = T.TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=4,
                          max_seq=32)


def pp_mesh(dp, pp):
    devs = np.array(jax.devices()[: dp * pp]).reshape(dp, pp)
    return Mesh(devs, ("dp", "pp"))


def pp_tp_mesh(dp, pp, tp):
    devs = np.array(jax.devices()[: dp * pp * tp]).reshape(dp, pp, tp)
    return Mesh(devs, ("dp", "pp", "tp"))


def batch(seed=0, b=8, t=32, vocab=64):
    rng = np.random.default_rng(seed)
    tok = rng.integers(0, vocab, (b, t)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1).astype(np.int32)


def ref_engine(opt):
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("dp", "sp"))
    return ContextParallelEngine(CFG, opt, mesh, seed=0)


def test_bad_schedule_rejected():
    with pytest.raises(AssertionError):
        PipelineLMEngine(CFG, SGD(0.1), pp_mesh(1, 2), schedule="gpip")


# ---------------------------------------------------------- equivalence


@pytest.mark.parametrize("dp,pp,n_mu", [(1, 4, 4), (2, 4, 2), (4, 2, 2),
                                        (2, 2, 1), (1, 2, 6)])
def test_1f1b_matches_plain_dp(dp, pp, n_mu):
    """n_mu > pp (the case 1F1B exists for: more microbatches than the
    stash can hold under GPipe) included via (1, 2, 6)."""
    ref = ref_engine(SGD(0.1))
    eng = PipelineLMEngine(CFG, SGD(0.1), pp_mesh(dp, pp),
                           n_mubatches=n_mu, seed=0, schedule="1f1b")
    for step in range(4):
        tok, tgt = batch(step, b=8 if n_mu != 6 else 24)
        lr_ = ref.train_batch(tok, tgt)
        lp = eng.train_batch(tok, tgt)
        assert lp == pytest.approx(lr_, rel=3e-4), (step, dp, pp, n_mu)
    ref_p = ref.get_canonical_params()
    pipe_p = eng.get_canonical_params()
    for a, b in zip(jax.tree_util.tree_leaves(pipe_p),
                    jax.tree_util.tree_leaves(ref_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-5)


def test_1f1b_matches_gpipe_exactly():
    """Same engine class, two schedules: bit-identical data placement, so
    the two trajectories must agree to float reassociation tolerance."""
    g = PipelineLMEngine(CFG, SGD(0.1), pp_mesh(1, 4), n_mubatches=4,
                         seed=0, schedule="gpipe")
    f = PipelineLMEngine(CFG, SGD(0.1), pp_mesh(1, 4), n_mubatches=4,
                         seed=0, schedule="1f1b")
    for step in range(3):
        tok, tgt = batch(step)
        assert f.train_batch(tok, tgt) == pytest.approx(
            g.train_batch(tok, tgt), rel=1e-5), step
    for a, b in zip(jax.tree_util.tree_leaves(f.get_canonical_params()),
                    jax.tree_util.tree_leaves(g.get_canonical_params())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_1f1b_with_adam_and_clip():
    ref = ref_engine(Adam(1e-2, grad_clip=0.5))
    eng = PipelineLMEngine(CFG, Adam(1e-2, grad_clip=0.5), pp_mesh(2, 4),
                           n_mubatches=2, seed=0, schedule="1f1b")
    for step in range(4):
        tok, tgt = batch(step)
        assert eng.train_batch(tok, tgt) == pytest.approx(
            ref.train_batch(tok, tgt), rel=3e-4), step


def test_1f1b_eval_matches():
    ref = ref_engine(SGD(0.1))
    eng = PipelineLMEngine(CFG, SGD(0.1), pp_mesh(2, 4), n_mubatches=2,
                           seed=0, schedule="1f1b")
    tok, tgt = batch(11)
    assert eng.eval_loss(tok, tgt) == pytest.approx(
        ref.eval_loss(tok, tgt), rel=3e-4)


# ----------------------------------------------------- compose features


@pytest.mark.parametrize("dp,pp,tp,n_mu", [(1, 2, 2, 2), (2, 2, 2, 1)])
def test_1f1b_pp_tp_matches_plain_dp(dp, pp, tp, n_mu):
    """Megatron tp inside each 1F1B stage: the explicit psum over 'tp'
    sits inside the cond-gated tick halves — all tp peers of a stage
    share the schedule predicate, so the collective stays uniform."""
    ref = ref_engine(SGD(0.1))
    eng = PipelineLMEngine(CFG, SGD(0.1), pp_tp_mesh(dp, pp, tp),
                           n_mubatches=n_mu, seed=0, schedule="1f1b")
    for step in range(4):
        tok, tgt = batch(step)
        assert eng.train_batch(tok, tgt) == pytest.approx(
            ref.train_batch(tok, tgt), rel=3e-4), (step, dp, pp, tp)
    for a, b in zip(jax.tree_util.tree_leaves(eng.get_canonical_params()),
                    jax.tree_util.tree_leaves(ref.get_canonical_params())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-5)


def test_1f1b_gqa_rope_swiglu_rmsnorm():
    """The modern block stack runs under the hand-built backward (vjp
    recompute must differentiate rope/gqa/swiglu/rmsnorm correctly)."""
    cfg = replace(CFG, n_kv_heads=2, rope=True, norm="rmsnorm",
                  ffn="swiglu")
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("dp", "sp"))
    ref = ContextParallelEngine(cfg, SGD(0.1), mesh, seed=0)
    eng = PipelineLMEngine(cfg, SGD(0.1), pp_mesh(2, 2), n_mubatches=2,
                           seed=0, schedule="1f1b")
    for step in range(3):
        tok, tgt = batch(step)
        assert eng.train_batch(tok, tgt) == pytest.approx(
            ref.train_batch(tok, tgt), rel=3e-4), step


def test_1f1b_bf16_remat_trains():
    cfg = replace(CFG, compute_dtype=jnp.bfloat16, remat=True)
    eng = PipelineLMEngine(cfg, Adam(5e-3), pp_mesh(2, 4), n_mubatches=2,
                           seed=0, schedule="1f1b")
    tok, tgt = batch(7)
    losses = [eng.train_batch(tok, tgt) for _ in range(20)]
    assert losses[-1] < losses[0] - 0.15, losses[::5]
    for leaf in jax.tree_util.tree_leaves(eng.params):
        assert leaf.dtype == jnp.float32


def test_1f1b_checkpoint_roundtrip(tmp_path):
    from shallowspeed_tpu import checkpoint

    eng = PipelineLMEngine(CFG, Adam(1e-2), pp_mesh(1, 4), n_mubatches=2,
                           seed=0, schedule="1f1b")
    tok, tgt = batch(3)
    for _ in range(2):
        eng.train_batch(tok, tgt)
    checkpoint.save(str(tmp_path), eng, 2)
    eng2 = PipelineLMEngine(CFG, Adam(1e-2), pp_mesh(2, 2), n_mubatches=4,
                            seed=1, schedule="gpipe")
    assert checkpoint.restore(eng2, checkpoint.latest(str(tmp_path))) == 3
    assert eng.train_batch(tok, tgt) == pytest.approx(
        eng2.train_batch(tok, tgt), rel=1e-3)
