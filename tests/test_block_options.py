"""Block options: RMSNorm and SwiGLU (`TransformerConfig.norm/.ffn`),
threaded through every engine's placement and the decode path.
"""

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.models.generate import generate
from shallowspeed_tpu.optim import Adam, SGD
from shallowspeed_tpu.parallel.context import ContextParallelEngine
from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine
from shallowspeed_tpu.parallel.tensor import TensorParallelEngine

BASE = T.TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                           max_seq=32)
MODERN = replace(BASE, norm="rmsnorm", ffn="swiglu", rope=True)


def toks(seed=0, b=4, t=32, vocab=64):
    rng = np.random.default_rng(seed)
    tok = rng.integers(0, vocab, (b, t)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1).astype(np.int32)


# ------------------------------------------------------------ unit level


def test_rmsnorm_properties():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, 8, 32)) * 5 + 3, jnp.float32)
    p = {"g": jnp.ones((32,)), "b": jnp.zeros((32,))}
    y = np.asarray(T._rmsnorm(p, x))
    # unit RMS rows, no centering (mean generally nonzero)
    rms = np.sqrt((y ** 2).mean(axis=-1))
    np.testing.assert_allclose(rms, 1.0, rtol=1e-4)
    assert abs(y.mean()) > 0.01  # differs from layernorm's zero mean
    # bias must be inert (kept only for structural stability)
    y2 = np.asarray(T._rmsnorm({"g": p["g"], "b": p["b"] + 7.0}, x))
    np.testing.assert_allclose(y2, y, atol=0)


def test_swiglu_structure_and_forward():
    cfg = replace(BASE, ffn="swiglu")
    params = T.init(cfg, seed=1)
    assert "gate" in params["blocks"][0]
    assert "gate" not in T.init(BASE, seed=1)["blocks"][0]
    tok, tgt = toks(0)
    assert np.isfinite(float(T.loss(params, tok, tgt, cfg)))
    # swiglu output differs from gelu on the same seed
    lg = np.asarray(T.forward(params, tok, cfg))
    lg_gelu = np.asarray(T.forward(T.init(BASE, seed=1), tok, BASE))
    assert not np.allclose(lg, lg_gelu)


def test_moe_ignores_ffn_flag():
    cfg = replace(BASE, ffn="swiglu", n_experts=4)
    params = T.init(cfg, seed=1)
    assert "gate" not in params["blocks"][0]  # moe has its own router gate
    assert "moe" in params["blocks"][0]


# --------------------------------------------------- engine equivalence


def serial_engine(cfg, opt):
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("dp", "sp"))
    return ContextParallelEngine(cfg, opt, mesh, seed=0)


def test_modern_block_under_sequence_sharding():
    ser = serial_engine(MODERN, SGD(0.1))
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "sp"))
    eng = ContextParallelEngine(MODERN, SGD(0.1), mesh, seed=0)
    for step in range(3):
        tok, tgt = toks(step)
        assert eng.train_batch(tok, tgt) == pytest.approx(
            ser.train_batch(tok, tgt), rel=3e-4), step


def test_modern_block_under_tensor_parallel():
    ser = serial_engine(MODERN, SGD(0.1))
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))
    eng = TensorParallelEngine(MODERN, SGD(0.1), mesh, seed=0)
    gate = eng.params["blocks"][0]["gate"]["W"]
    assert "tp" in gate.sharding.spec  # column-parallel gate
    for step in range(3):
        tok, tgt = toks(step)
        assert eng.train_batch(tok, tgt) == pytest.approx(
            ser.train_batch(tok, tgt), rel=3e-4), step


@pytest.mark.parametrize("mesh_shape,axes", [((2, 2), ("dp", "pp")),
                                             ((1, 2, 2), ("dp", "pp", "tp"))])
def test_modern_block_under_pipeline(mesh_shape, axes):
    ser = serial_engine(MODERN, SGD(0.1))
    devs = np.array(jax.devices()[: int(np.prod(mesh_shape))]).reshape(
        mesh_shape)
    eng = PipelineLMEngine(MODERN, SGD(0.1), Mesh(devs, axes),
                           n_mubatches=2, seed=0)
    for step in range(3):
        tok, tgt = toks(step, b=8)
        assert eng.train_batch(tok, tgt) == pytest.approx(
            ser.train_batch(tok, tgt), rel=3e-4), (step, axes)


# ------------------------------------------------------------- decoding


def test_modern_block_cached_decode():
    from shallowspeed_tpu.models.generate import decode_step, init_kv_cache, \
        prefill

    params = T.init(MODERN, seed=4)
    tokens, _ = toks(1, b=2, t=10)
    ref = np.asarray(T.forward(params, tokens, MODERN))
    cache = init_kv_cache(MODERN, 2)
    logits, cache = prefill(params, tokens[:, :1], MODERN, cache)
    np.testing.assert_allclose(np.asarray(logits), ref[:, 0],
                               rtol=1e-4, atol=1e-5)
    for pos in range(1, tokens.shape[1]):
        logits, cache = decode_step(params, jnp.asarray(tokens[:, pos]),
                                    pos, cache, MODERN)
        np.testing.assert_allclose(np.asarray(logits), ref[:, pos],
                                   rtol=1e-4, atol=1e-5, err_msg=str(pos))


def test_modern_block_trains_bf16():
    cfg = replace(MODERN, compute_dtype=jnp.bfloat16)
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2, 1), ("dp", "sp"))
    eng = ContextParallelEngine(cfg, Adam(5e-3), mesh, seed=0)
    tok, tgt = toks(7)
    losses = [eng.train_batch(tok, tgt) for _ in range(20)]
    assert losses[-1] < losses[0] - 0.15, losses[::5]
    out = np.asarray(generate(eng.params, tok[:1, :4], cfg, 8,
                              temperature=0.0))
    assert out.shape == (1, 8)
