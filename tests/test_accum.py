"""Gradient accumulation (`ContextParallelEngine(accum=N)`, `--accum`).

Oracle: the microbatch split is exact for mean-of-equal-means (the same
invariant the reference's microbatching rests on, `functional.py:43-44`),
so accum=N must reproduce the accum=1 trajectory on identical batches —
while running each forward/backward on 1/N of the rows at a time.
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from shallowspeed_tpu.models.transformer import TransformerConfig
from shallowspeed_tpu.optim import SGD, Adam
from shallowspeed_tpu.parallel.context import ContextParallelEngine

CFG = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                        max_seq=32)


def mesh2(dp, sp=1):
    devs = np.array(jax.devices()[: dp * sp]).reshape(dp, sp)
    return Mesh(devs, ("dp", "sp"))


def batch(step, b=8, t=32, vocab=64):
    rng = np.random.default_rng([5, step])
    tok = rng.integers(0, vocab, (b, t)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1).astype(np.int32)


def assert_same(a, b_, n_steps=4, rtol=2e-5):
    for s in range(n_steps):
        tok, tgt = batch(s)
        la, lb = a.train_batch(tok, tgt), b_.train_batch(tok, tgt)
        np.testing.assert_allclose(la, lb, rtol=1e-5, atol=1e-6)
    for x, y in zip(jax.tree_util.tree_leaves(a.params),
                    jax.tree_util.tree_leaves(b_.params)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=rtol, atol=1e-6)


@pytest.mark.parametrize("accum", [2, 4])
def test_accum_matches_single_shot(accum):
    base = ContextParallelEngine(CFG, SGD(0.1), mesh2(1), seed=0)
    acc = ContextParallelEngine(CFG, SGD(0.1), mesh2(1), seed=0,
                                accum=accum)
    assert_same(base, acc)


def test_accum_composes_with_dp_sp():
    base = ContextParallelEngine(CFG, SGD(0.1), mesh2(2, 2), seed=0)
    acc = ContextParallelEngine(CFG, SGD(0.1), mesh2(2, 2), seed=0,
                                accum=2)
    assert_same(base, acc)


def test_accum_composes_with_zero2():
    base = ContextParallelEngine(CFG, SGD(0.1), mesh2(2), seed=0)
    acc = ContextParallelEngine(CFG, SGD(0.1), mesh2(2), seed=0,
                                accum=2, zero2=True)
    assert_same(base, acc)


def test_accum_with_adam_loss_trajectory():
    base = ContextParallelEngine(CFG, Adam(1e-2), mesh2(1), seed=0)
    acc = ContextParallelEngine(CFG, Adam(1e-2), mesh2(1), seed=0,
                                accum=2)
    for s in range(5):
        tok, tgt = batch(s)
        np.testing.assert_allclose(base.train_batch(tok, tgt),
                                   acc.train_batch(tok, tgt), rtol=1e-4)


def test_accum_with_dropout_trains():
    from dataclasses import replace

    cfg = replace(CFG, dropout=0.1)
    eng = ContextParallelEngine(cfg, Adam(5e-3), mesh2(2), seed=0,
                                accum=2)
    losses = [eng.train_batch(*batch(s % 4)) for s in range(30)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses[::10]


def test_indivisible_accum_rejected():
    eng = ContextParallelEngine(CFG, SGD(0.1), mesh2(1), seed=0, accum=3)
    with pytest.raises(AssertionError, match="accum"):
        eng.train_batch(*batch(0))
