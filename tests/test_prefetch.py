"""Async input pipeline (`data/prefetch.py`) + the engines' non-blocking
step (`train_batch_async`).

The invariant that matters: prefetched + async execution must produce
EXACTLY the synchronous loop's results (same batches, same order, same
losses) — the pipeline changes when work happens, never what is computed.
"""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from shallowspeed_tpu.data.prefetch import (
    DevicePrefetcher, prefetch_to_device, sync_every)
from shallowspeed_tpu.models.transformer import TransformerConfig
from shallowspeed_tpu.optim import Adam
from shallowspeed_tpu.parallel.context import ContextParallelEngine
from shallowspeed_tpu.parallel.fsdp import FSDPEngine

CFG = TransformerConfig(vocab=32, d_model=16, n_heads=2, n_layers=1,
                        max_seq=16)


def batches(n, seed0=0):
    for s in range(n):
        rng = np.random.default_rng([seed0, s])
        tok = rng.integers(0, 32, (4, 16)).astype(np.int32)
        yield tok, np.roll(tok, -1, axis=1).astype(np.int32)


# ------------------------------------------------------------- pipeline


def test_prefetcher_preserves_order_and_values():
    got = list(DevicePrefetcher(range(100), lambda x: x * 2, depth=3))
    assert got == [2 * i for i in range(100)]


def test_prefetcher_depth_zero_is_synchronous_map():
    it = prefetch_to_device(range(5), lambda x: x + 1, depth=0)
    assert not isinstance(it, DevicePrefetcher)
    assert list(it) == [1, 2, 3, 4, 5]


def test_prefetcher_propagates_producer_exception():
    def bad(x):
        if x == 3:
            raise ValueError("boom at 3")
        return x

    it = DevicePrefetcher(range(10), bad, depth=2)
    got = []
    with pytest.raises(ValueError, match="boom at 3"):
        for v in it:
            got.append(v)
    assert got == [0, 1, 2]  # everything before the failure was delivered


def test_sync_every():
    assert sync_every(0, 20, 100)
    assert not sync_every(1, 20, 100)
    assert sync_every(40, 20, 100)
    assert sync_every(99, 20, 100)  # final step always syncs


# ------------------------------------------------------- engine parity


def run_sync(eng, n):
    return [eng.train_batch(tok, tgt) for tok, tgt in batches(n)]


def run_prefetched(eng, n, depth=2):
    placed = prefetch_to_device(
        batches(n), lambda b: (eng.place(b[0]), eng.place(b[1])), depth)
    return [float(eng.train_batch_async(tok, tgt)) for tok, tgt in placed]


def ctx_engine():
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "sp"))
    return ContextParallelEngine(CFG, Adam(5e-3), mesh, seed=0)


def fsdp_engine():
    return FSDPEngine(CFG, Adam(5e-3),
                      Mesh(np.array(jax.devices()[:4]), ("dp",)), seed=0)


@pytest.mark.parametrize("make", [ctx_engine, fsdp_engine])
def test_prefetched_training_matches_sync(make):
    a = run_sync(make(), 8)
    b = run_prefetched(make(), 8)
    np.testing.assert_allclose(b, a, rtol=1e-6)


def test_async_loss_is_lazy_then_correct():
    eng = ctx_engine()
    tok, tgt = next(batches(1))
    dev_loss = eng.train_batch_async(tok, tgt)
    assert isinstance(dev_loss, jax.Array)  # not a host float yet
    assert np.isfinite(float(dev_loss))


def test_zero1_engine_async_path():
    """The ZeRO-1 two-program path also runs through train_batch_async."""
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "sp"))
    eng = ContextParallelEngine(CFG, Adam(5e-3), mesh, seed=0, zero1=True)
    losses = run_prefetched(eng, 4)
    ref = run_sync(ContextParallelEngine(CFG, Adam(5e-3), mesh, seed=0,
                                         zero1=True), 4)
    np.testing.assert_allclose(losses, ref, rtol=1e-6)


def test_prefetcher_stays_terminated_after_exhaustion():
    it = DevicePrefetcher(range(3), lambda x: x, depth=2)
    assert list(it) == [0, 1, 2]
    assert list(it) == []          # second iteration: immediate stop
    with pytest.raises(StopIteration):
        next(it)                   # and next() never blocks


def test_prefetcher_stays_terminated_after_error():
    def bad(x):
        raise ValueError("boom")

    it = DevicePrefetcher(range(3), bad, depth=2)
    with pytest.raises(ValueError):
        next(it)
    with pytest.raises(StopIteration):
        next(it)                   # terminated, not deadlocked


def test_close_releases_blocked_producer():
    """Abandoning iteration early + close() must let the producer thread
    exit instead of parking forever on a full queue."""
    it = DevicePrefetcher(range(100), lambda x: x, depth=2)
    assert next(it) == 0
    it.close()
    it._thread.join(timeout=5)
    assert not it._thread.is_alive()
    with pytest.raises(StopIteration):
        next(it)  # closed == terminated


def test_context_manager_closes():
    with DevicePrefetcher(range(50), lambda x: x, depth=2) as it:
        assert next(it) == 0
    assert not it._thread.is_alive()


def test_close_leaves_queue_empty():
    """Even when the producer was parked mid-put, close() must not leave a
    placed batch referenced by the queue."""
    for _ in range(10):  # race-prone path: repeat to catch the window
        it = DevicePrefetcher(range(100), lambda x: x, depth=1)
        next(it)
        it.close()
        assert it._q.empty()
        assert not it._thread.is_alive()
