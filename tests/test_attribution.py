"""Time-attribution waterfall (telemetry/attribution.py).

The acceptance pin: on CPU-scaled analogs of the two bench transformer
configs, the spans-level waterfall BALANCES — the measured fenced step
time is covered by the analytic components within 10%
(`attrib_unexplained_frac <= 0.10`). On calibrated (non-TPU) hosts the
rates are deliberately slow-biased, so the usual failure mode is
over-explanation (unexplained clamps at 0) — under-explanation beyond
10% means the reconciliation machinery itself broke.
"""

import json
import time

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from shallowspeed_tpu import telemetry as tele
from shallowspeed_tpu.models.transformer import TransformerConfig
from shallowspeed_tpu.optim import Adafactor, Adam
from shallowspeed_tpu.parallel.context import ContextParallelEngine
from shallowspeed_tpu.telemetry import attribution as attr

# ------------------------------------------------------- roofline walk


def test_dot_flops_counts_matmul_exactly():
    from shallowspeed_tpu.analysis.walker import dot_flops

    def f(a, b):
        return a @ b

    closed = jax.make_jaxpr(f)(np.zeros((4, 8), np.float32),
                               np.zeros((8, 16), np.float32))
    flops = [dot_flops(e) for e in closed.jaxpr.eqns
             if e.primitive.name == "dot_general"]
    assert flops == [2 * 4 * 16 * 8]


def test_roofline_scan_multiplies_trips_and_skips_collectives():
    def body(c, _):
        return c @ c + 1.0, ()

    def f(a):
        out, _ = jax.lax.scan(body, a, None, length=5)
        return out

    a = np.zeros((8, 8), np.float32)
    roof = attr.roofline_of_jaxpr(jax.make_jaxpr(f)(a))
    # 5 trips of one 8x8x8 matmul, counted in the global bucket
    assert roof["flops_global"] == 5 * 2 * 8 * 8 * 8
    assert roof["flops_shard"] == 0
    assert roof["bytes_global"] > 0  # the scan body's add moves bytes


def test_roofline_shard_map_lands_in_per_device_bucket():
    from shallowspeed_tpu.utils import shard_map as smap

    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("dp",))
    from jax.sharding import PartitionSpec as P

    def local(a):
        return a @ a.T

    def f(a):
        return smap(local, mesh=mesh, in_specs=P("dp"),
                    out_specs=P("dp"))(a)

    a = np.zeros((4, 8), np.float32)  # per-device (2, 8)
    roof = attr.roofline_of_jaxpr(jax.make_jaxpr(f)(a))
    assert roof["flops_shard"] == 2 * 2 * 2 * 8  # per-shard M=N=2, K=8
    assert roof["flops_global"] == 0


# ---------------------------------------------------- waterfall algebra


def test_step_waterfall_math_and_clamp():
    rates = {"flops": 100e9, "hbm": 10e9, "ici": 5e9,
             "source": "table"}
    roof = {"flops_shard": 100e9, "flops_global": 0,
            "bytes_shard": 10e9, "bytes_global": 0}
    # components: 1 s MXU + 1 s HBM + 0.5 s wire + 0.1 bubble + 0.4 host
    out = attr.step_waterfall(
        t_step=10.0, roofline=roof, coll_bytes=2.5e9, exposed_frac=1.0,
        bubble_fraction=0.1, host_gap=4.0, n_devices=1, rates=rates)
    assert out["attrib_compute_frac"] == pytest.approx(0.2)
    assert out["attrib_mxu_frac"] == pytest.approx(0.1)
    assert out["attrib_comm_exposed_frac"] == pytest.approx(0.05)
    assert out["attrib_bubble_frac"] == pytest.approx(0.1)
    assert out["attrib_host_frac"] == pytest.approx(0.4)
    assert out["attrib_unexplained_frac"] == pytest.approx(0.25)
    # hidden collectives cost nothing
    hid = attr.step_waterfall(t_step=10.0, roofline=roof,
                              coll_bytes=2.5e9, exposed_frac=0.0,
                              rates=rates)
    assert hid["attrib_comm_exposed_frac"] == 0.0
    # over-explanation clamps unexplained at 0
    over = attr.step_waterfall(t_step=0.5, roofline=roof, rates=rates)
    assert over["attrib_unexplained_frac"] == 0.0
    assert over["attrib_compute_frac"] == pytest.approx(4.0)


def test_global_bucket_divides_by_fleet():
    rates = {"flops": 100e9, "hbm": 10e9, "ici": 5e9, "source": "table"}
    roof = {"flops_global": 400e9, "bytes_global": 0}
    out = attr.step_waterfall(t_step=1.0, roofline=roof, n_devices=4,
                              rates=rates)
    assert out["attrib_compute_frac"] == pytest.approx(1.0)


def test_device_rates_calibrated_on_cpu():
    rates = attr.device_rates(dtype="f32")
    assert rates["source"] == "calibrated"  # CPU test mesh has no peak
    assert rates["flops"] > 0 and rates["hbm"] > 0 and rates["ici"] > 0


# ------------------------------------- the acceptance pin: it balances

# CPU-scaled analogs of the two bench transformer configs (bench.py
# bench_transformer_mfu): the headline d2048 swiglu+adamw recipe and
# the 1.21B dots-remat + chunked-CE + adafactor recipe, at widths a
# CPU test can compile in seconds. The structure (op mix, remat,
# chunked loss) is what the waterfall must reconcile, not the width.
BENCH_ANALOGS = [
    ("mfu_cfg", TransformerConfig(vocab=64, d_model=64, n_heads=4,
                                  n_layers=2, max_seq=64, ffn="swiglu"),
     Adam(1e-3)),
    ("big_cfg", TransformerConfig(vocab=256, d_model=64, n_heads=4,
                                  n_layers=2, max_seq=64, ffn="swiglu",
                                  remat=True, remat_policy="dots",
                                  xent_chunk=32),
     Adafactor(1e-3)),
]


def _measure_waterfall(cfg, opt, steps=6):
    """Build the engine under a spans-level tracer and return a
    closure measuring one RUN (two back-to-back log windows) with a
    fresh RunTelemetry each call (callers reset the tracer when
    done)."""
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("dp", "sp"))
    tracer = tele.configure(level="spans")
    eng = ContextParallelEngine(cfg, opt, mesh, seed=0)
    rng = np.random.default_rng(0)
    tok = rng.integers(0, cfg.vocab, (8, cfg.max_seq)).astype(np.int32)
    tgt = np.roll(tok, -1, 1).astype(np.int32)
    eng.train_batch_async(tok, tgt)
    jax.block_until_ready(eng.params)

    def run():
        telem = tele.RunTelemetry(eng, tracer, dtype="f32")
        telem.step_fields()  # advance the span mark
        out = []
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(steps):
                eng.train_batch_async(tok, tgt)
            jax.block_until_ready(eng.params)
            out.append(telem.step_fields(
                window_secs=time.perf_counter() - t0,
                steps_in_window=steps))
        return out

    return run


@pytest.mark.parametrize("name,cfg,opt",
                         BENCH_ANALOGS, ids=[a[0] for a in BENCH_ANALOGS])
def test_waterfall_balances_on_bench_analog(name, cfg, opt):
    """The acceptance pin: the calibration windows AND the frozen
    window after them balance within 10% — the THIRD window is the
    real check (the first two fit the scale, the third runs against
    the frozen baseline, which is what the drift alarm relies on)."""
    try:
        run = _measure_waterfall(cfg, opt)
        for _attempt in range(6):
            windows = run()
            if all(w.get("attrib_unexplained_frac", 1.0) <= 0.10
                   for w in windows):
                break
            # retry with fresh probes + a fresh scale: the shared
            # 2-core CI host's step times drift 10-20% on a seconds
            # timescale often enough that one attempt flakes ~1 run
            # in 4 (bench.py extends its rounds for the same reason);
            # the claim under test is that a CLEAN measurement
            # balances, so bounded retries don't weaken it
            time.sleep(0.5)
            attr.recalibrate()
    finally:
        tele.configure(level="off")
    for fields in windows:
        assert "attrib_unexplained_frac" in fields, fields
        assert fields["attrib_t_step_ms"] > 0
        assert "attrib_compute_frac" in fields
        assert fields["attrib_unexplained_frac"] <= 0.10, windows
    # calibrated host: the self-scale freezes at the second fit and
    # rides every later line unchanged
    assert windows[0].get("attrib_rates_source") in ("table",
                                                     "calibrated")
    if windows[0]["attrib_rates_source"] == "calibrated":
        assert windows[1]["attrib_compute_scale"] == \
            windows[2]["attrib_compute_scale"]


def test_waterfall_absent_at_steps_level():
    """Unfenced spans measure dispatch, not compute — no attribution
    fields may ride a steps-level line."""
    cfg = TransformerConfig(vocab=32, d_model=16, n_heads=2, n_layers=1,
                            max_seq=16)
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("dp", "sp"))
    tracer = tele.configure(level="steps")
    try:
        eng = ContextParallelEngine(cfg, Adam(1e-3), mesh, seed=0)
        telem = tele.RunTelemetry(eng, tracer, dtype="f32")
        tok = np.zeros((2, 16), np.int32)
        eng.train_batch_async(tok, tok)
        jax.block_until_ready(eng.params)
        fields = telem.step_fields(window_secs=1.0, steps_in_window=1)
    finally:
        tele.configure(level="off")
    assert not any(k.startswith("attrib_") for k in fields)


# ----------------------------------------------------------- schema v4


def test_schema_v4_attrib_and_ledger_lines_validate():
    from shallowspeed_tpu.telemetry.schema import (SCHEMA_VERSION,
                                                   validate_line)

    assert SCHEMA_VERSION >= 4  # v5 (chaos) extends, never narrows, v4
    step = {"event": "step", "step": 3, "loss": 1.0,
            "tokens_per_sec": 10.0, "wall": 123.4,
            "attrib_compute_frac": 0.7, "attrib_mxu_frac": 0.4,
            "attrib_comm_exposed_frac": 0.01, "attrib_bubble_frac": 0.1,
            "attrib_host_frac": 0.02, "attrib_unexplained_frac": 0.05,
            "attrib_t_step_ms": 12.5, "attrib_rates_source": "table"}
    assert validate_line(step) == []
    bad = dict(step, attrib_unexplained_frac="lots")
    assert validate_line(bad)
    led = {"event": "ledger", "kind": "val", "seconds": 1.25,
           "wall": 123.4, "t": 0.5}
    assert validate_line(led) == []
    assert validate_line({"event": "ledger"})  # kind is required
    assert validate_line({"event": "ledger", "kind": "x",
                          "seconds": "long"})
    gen = {"event": "generate", "tokens_per_sec": 55.0,
           "bytes_per_token": 1024, "hbm_util": None}
    assert validate_line(gen) == []
    # v1-v3 lines (no wall/attrib/ledger) keep validating
    old = {"event": "step", "step": 0, "loss": 2.0,
           "tokens_per_sec": 5.0}
    assert validate_line(old) == []


# the committed-artifact sweep now lives in tests/test_monitor.py as
# ONE parametrized test over docs_runs/*.jsonl (per-file node ids),
# instead of each PR hand-listing its own artifact here.


def test_bench_attribution_fields_are_json_serializable():
    """bench.py's waterfall block must always produce a JSON-clean
    payload (never raises; BENCH_r06 onward carries it)."""
    import bench

    out = bench.bench_attribution()
    json.dumps(out)
    assert "attribution" in out, out
    assert "attrib_unexplained_frac" in out["attribution"]
