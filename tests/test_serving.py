"""Serving runtime (`shallowspeed_tpu/serving/`): paged KV cache +
continuous-batching decode server.

The load-bearing invariants:

- **Stream parity.** Every request served concurrently reproduces its
  solo `generate()` token stream exactly (fixed seeds, greedy AND
  sampled) — paged attention shares `kv_cache.masked_attention` with
  the contiguous path and sampling shares the per-request
  `fold_in(PRNGKey(seed), token_index)` key schedule.
- **Compile hygiene.** Requests join/leave the running batch with ZERO
  new executables after warmup (fixed slot capacity, geometric
  block-table width buckets, donated pools) — the serving analog of
  `test_vm_executables_compile_exactly_once`.
- **Chunked prefill.** A long prompt admitted mid-run never freezes
  in-flight decodes for more than one chunk tick.
- **Allocator soundness.** alloc == free at drain; OOM evicts the
  newest running request (re-queued, stream continues exactly) and
  can never deadlock.
"""

import json
from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.models.generate import generate, init_kv_cache, prefill
from shallowspeed_tpu.serving import (BlockAllocator, OutOfBlocks,
                                      ServingEngine, blocks_for,
                                      init_block_pool,
                                      paged_read_bytes_per_tick,
                                      table_width)

CFG = T.TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                          max_seq=128)


@pytest.fixture(scope="module")
def params():
    return jax.device_put(T.init(CFG, seed=1))


def toks(seed=0, t=12, vocab=64):
    rng = np.random.default_rng(seed)
    return rng.integers(0, vocab, (t,)).astype(np.int32)


def solo(params, prompt, max_new, cfg=CFG, **kw):
    return np.asarray(generate(params, prompt[None, :], cfg, max_new,
                               **kw))[0]


# ------------------------------------------------- allocator + pools


def test_block_allocator_invariants():
    a = BlockAllocator(8)           # block 0 reserved -> 7 usable
    assert a.n_usable == 7 and a.n_free == 7
    got = a.alloc(3)
    assert len(got) == 3 and 0 not in got       # scratch never issued
    assert a.n_free == 4 and a.n_allocated == 3
    with pytest.raises(OutOfBlocks):
        a.alloc(5)                  # all-or-nothing: nothing leaked
    assert a.n_free == 4
    with pytest.raises(ValueError):
        a.free([99])                # not allocated
    a.free(got)
    assert a.n_free == 7 and a.n_allocated == 0   # balanced at drain
    with pytest.raises(ValueError):
        BlockAllocator(1)           # nothing usable past scratch


def test_blocks_for_and_table_width():
    assert blocks_for(0, 16) == 0
    assert blocks_for(1, 16) == 1
    assert blocks_for(16, 16) == 1
    assert blocks_for(17, 16) == 2
    # geometric width buckets: O(log) executables as tables grow
    assert table_width(1, 4) == 4
    assert table_width(4, 4) == 4
    assert table_width(5, 4) == 8
    assert table_width(33, 4) == 64


def test_init_block_pool_shapes_and_errors():
    pools = init_block_pool(CFG, 8, 16)
    assert len(pools) == CFG.n_layers
    assert pools[0]["k"].shape == (8, CFG.kv_heads, 16, CFG.head_dim)
    q = init_block_pool(CFG, 8, 16, kv_quant="int8")
    assert q[0]["k"].dtype == jnp.int8
    assert q[0]["k_s"].shape == (8, CFG.kv_heads, 16, 1)
    with pytest.raises(ValueError, match="kv_quant"):
        init_block_pool(CFG, 8, 16, kv_quant="fp4")
    with pytest.raises(ValueError, match="n_blocks"):
        init_block_pool(CFG, 1, 16)


# ------------------------------------- satellites: typed errors, asarray


def test_init_kv_cache_rejects_unknown_quant_mode():
    """Satellite: the bare `assert kv_quant == "int8"` became a typed
    ValueError naming the supported modes — asserts vanish under
    `python -O`, and this gate guards a production cache layout."""
    with pytest.raises(ValueError, match="int8"):
        init_kv_cache(CFG, 2, kv_quant="fp8")
    assert init_kv_cache(CFG, 1, cache_len=8, kv_quant="int8")


def test_decode_report_rejects_nonpositive_inputs(params):
    from shallowspeed_tpu.models.generate import decode_report

    with pytest.raises(ValueError, match="seconds"):
        decode_report(params, CFG, batch=1, cache_len=8, n_tokens=4,
                      seconds=0.0)
    with pytest.raises(ValueError, match="n_tokens"):
        decode_report(params, CFG, batch=1, cache_len=8, n_tokens=0,
                      seconds=1.0)


def test_generate_converts_prompt_on_no_padding_branch(params):
    """Satellite: `generate` now runs `jnp.asarray` on BOTH branches.
    A prompt whose bucket equals its length (tp == tp_b: the
    no-padding branch) used to pass the caller's raw array straight
    into jit — an int64 host array must normalize identically on both
    branches."""
    # max_seq 128, max_new 104 -> bucket cap = 24 == tp: no padding
    p32 = toks(3, t=24)
    p64 = p32.astype(np.int64)
    a = solo(params, p32, 8, temperature=0.0)
    b = np.asarray(generate(params, p64[None, :], CFG, 8,
                            temperature=0.0))[0]
    np.testing.assert_array_equal(a, b)
    # padded branch, same dtypes
    c = solo(params, toks(3, t=10), 8, temperature=0.0)
    d = np.asarray(generate(params, toks(3, t=10).astype(np.int64)[None],
                            CFG, 8, temperature=0.0))[0]
    np.testing.assert_array_equal(c, d)


# -------------------------------------- paged vs contiguous numerics


def test_prefill_chunk_logits_match_contiguous_prefill(params):
    """The paged prefill's last-position logits match the contiguous
    `prefill`'s to 1e-4 — same cache math read through the gathered
    block table (`kv_cache.masked_attention` is shared)."""
    from shallowspeed_tpu.serving.engine import _prefill_chunk

    prompt = toks(5, t=14)
    ref, _ = prefill(params, jnp.asarray(prompt[None]), CFG,
                     init_kv_cache(CFG, 1, cache_len=32))
    # pool/chunk/width shapes shared with the engine tests below, so
    # this compiles (at most) once per suite run
    pools = init_block_pool(CFG, 32, 8)
    alloc = BlockAllocator(32)
    table = alloc.alloc(blocks_for(14, 8))
    c = 16
    tokens = np.zeros((1, c), np.int32)
    tokens[0, :14] = prompt
    bt = np.zeros((1, table_width(len(table), 4)), np.int32)
    bt[0, :len(table)] = table
    logits, pools = _prefill_chunk(params, pools, tokens, np.int32(0),
                                   np.int32(14), bt, np.int32(0),
                                   np.int32(0), cfg=CFG)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("quant", [False, True], ids=["f32", "int8"])
def test_paged_attention_matches_cached_attention(params, quant):
    """Block-gathered attention == contiguous `_cached_attention` on
    identical cache contents (to fp-reorder noise): the read path's
    only difference is the gather. int8 pools quantize per (row, head,
    position) exactly like the contiguous int8 cache, so the parity
    holds there too — the default-tier int8 canary (the full int8
    stream oracle rides the slow tier)."""
    from shallowspeed_tpu.models.kv_cache import (cache_write,
                                                  cached_attention,
                                                  masked_attention)
    from shallowspeed_tpu.serving.cache import gather_table, write_rows

    rng = np.random.default_rng(0)
    bs, n_pos = 8, 19
    kv_quant = "int8" if quant else ""
    kv = [rng.normal(size=(1, n_pos, CFG.kv_heads,
                           CFG.head_dim)).astype(np.float32)
          for _ in range(2)]
    q = jnp.asarray(rng.normal(
        size=(1, 1, CFG.n_heads, CFG.head_dim)).astype(np.float32))
    cache = init_kv_cache(CFG, 1, cache_len=32, kv_quant=kv_quant)[0]
    cache = cache_write(cache, jnp.asarray(kv[0]), jnp.asarray(kv[1]), 0)
    pool = init_block_pool(CFG, 32, bs, kv_quant=kv_quant)[0]
    table = [3, 1, 5]                      # deliberately out of order
    for pos in range(n_pos):
        pool = write_rows(
            pool, jnp.asarray(kv[0][:, pos]), jnp.asarray(kv[1][:, pos]),
            jnp.asarray([table[pos // bs]]), jnp.asarray([pos % bs]),
            quant=quant)
    bt = jnp.asarray([table + [0]], jnp.int32)       # padded width 4
    pos = n_pos - 1
    ref = cached_attention(q, cache, pos, CFG)
    view = gather_table(pool, bt)
    valid = (jnp.arange(4 * bs) <= pos)[None, None, None, None, :]
    got = masked_attention(q, view, valid, CFG)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


# --------------------------------------------- stream-parity oracle


@pytest.mark.parametrize("kwargs", [
    {"temperature": 0.0},
    {"temperature": 1.0, "seed": 7},
    {"temperature": 0.7, "seed": 3},
], ids=["greedy", "sampled", "temp0.7"])
def test_solo_request_matches_generate(params, kwargs):
    """A request served alone reproduces its `generate()` stream
    token-for-token — the continuous-batching correctness oracle's
    base case, greedy and sampled (same fold_in key schedule)."""
    prompt = toks(11, t=13)
    ref = solo(params, prompt, 10, **kwargs)
    eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                        max_slots=4, prefill_chunk=16)
    eng.submit(prompt, 10, temperature=kwargs.get("temperature", 0.0),
               seed=kwargs.get("seed", 0), rid="q")
    res = eng.run()
    np.testing.assert_array_equal(res["q"], ref)
    assert eng.alloc.n_free == eng.alloc.n_usable


def test_concurrent_mixed_lengths_match_solo_oracles(params):
    """N concurrent requests with different prompt lengths, max_new,
    and samplers — including one submitted MID-RUN (joins the running
    batch) — each reproduce their solo stream exactly."""
    # max_new=10 signatures deliberately match the solo-parity test's
    # compiled generate() oracles (warm jit cache); 12 and 6 are fresh
    reqs = {
        "a": (toks(0, t=5), 10, 0.0, 0),
        "b": (toks(1, t=23), 12, 1.0, 7),
        "c": (toks(2, t=40), 6, 0.0, 0),
        "late": (toks(3, t=17), 10, 1.0, 11),
    }
    oracle = {k: solo(params, p, mn, temperature=tmp, seed=s)
              for k, (p, mn, tmp, s) in reqs.items()}
    eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                        max_slots=4, prefill_chunk=16)
    for k in ("a", "b", "c"):
        p, mn, tmp, s = reqs[k]
        eng.submit(p, mn, temperature=tmp, seed=s, rid=k)
    for _ in range(4):                     # a/b/c already decoding...
        eng.step()
    p, mn, tmp, s = reqs["late"]
    eng.submit(p, mn, temperature=tmp, seed=s, rid="late")  # ...joins
    res = eng.run()
    for k, ref in oracle.items():
        np.testing.assert_array_equal(res[k], ref, err_msg=k)
    assert eng.alloc.n_free == eng.alloc.n_usable


def test_zero_recompiles_across_request_churn(params):
    """After warmup, requests joining and leaving the batch add ZERO
    executables (`fn._cache_size`, the counter the analysis retrace
    rule reads) — occupancy is data, not shape: fixed slot count,
    geometric table-width buckets, fixed prefill chunk."""
    eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                        max_slots=4, prefill_chunk=16)
    # warmup: lengths walking every width bucket the churn uses
    for i, (t, mn) in enumerate([(5, 6), (23, 8), (40, 6)]):
        eng.submit(toks(20 + i, t=t), mn, rid=f"w{i}")
    eng.run()
    warm = eng.executable_counts()
    for i, (t, mn, tmp) in enumerate(
            [(9, 7, 0.0), (31, 5, 1.0), (14, 9, 0.0), (44, 6, 0.0),
             (3, 8, 1.0)]):
        eng.submit(toks(40 + i, t=t), mn, temperature=tmp, rid=f"c{i}")
        eng.step()                  # staggered joins/leaves
    eng.run()
    assert eng.executable_counts() == warm, (
        f"request churn recompiled: {warm} -> "
        f"{eng.executable_counts()}")


def test_chunked_prefill_never_stalls_decode(params):
    """A long prompt admitted mid-run prefills one chunk per engine
    step INTERLEAVED with decode ticks: an in-flight request's stream
    advances every step (tpot bounded at one chunk tick) instead of
    freezing for the whole prefill."""
    eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                        max_slots=4, prefill_chunk=16)
    eng.submit(toks(0, t=6), 40, rid="short")
    while (eng.poll("short")["status"] != "running"
           or len(eng.poll("short")["tokens"]) < 2):
        eng.step()
    eng.submit(toks(1, t=60), 4, rid="long")   # 4 chunks of prefill
    deltas = []
    while eng.poll("long")["status"] != "done":
        before = len(eng.poll("short")["tokens"])
        eng.step()
        deltas.append(len(eng.poll("short")["tokens"]) - before)
    assert min(deltas) >= 1, (
        f"decode stalled during chunked prefill: per-step token "
        f"deltas {deltas}")
    # and the long request still matches its solo oracle
    res = eng.run()
    np.testing.assert_array_equal(
        res["long"], solo(params, toks(1, t=60), 4, temperature=0.0))


def test_oom_evicts_requeues_and_balances(params):
    """Pool pressure: 3 requests whose steady-state footprint exceeds
    the pool force the evict-newest policy — the evicted request
    re-queues, re-prefills prompt + generated, and still reproduces
    its solo stream; the allocator balances at drain and never
    deadlocks."""
    reqs = {k: (toks(50 + i, t=24), 16) for i, k in enumerate("abc")}
    oracle = {k: solo(params, p, mn, temperature=0.0)
              for k, (p, mn) in reqs.items()}
    # 13 usable blocks * 8 = 104 positions < 3 * (24 + 16) = 120
    eng = ServingEngine(params, CFG, n_blocks=14, block_size=8,
                        max_slots=4, prefill_chunk=16)
    for k, (p, mn) in reqs.items():
        eng.submit(p, mn, rid=k)
    res = eng.run()
    for k in reqs:
        np.testing.assert_array_equal(res[k], oracle[k], err_msg=k)
    assert eng.counters["preempted"] >= 1
    assert eng.alloc.n_free == eng.alloc.n_usable
    assert eng.alloc.n_allocated == 0
    rec = {r["id"]: r for r in eng.request_records}
    assert sum(r["preempted"] for r in rec.values()) \
        == eng.counters["preempted"]


def test_submit_rejects_unservable_requests(params):
    eng = ServingEngine(params, CFG, n_blocks=8, block_size=8,
                        max_slots=2)
    with pytest.raises(ValueError, match="max_seq"):
        eng.submit(toks(0, t=100), 64)
    with pytest.raises(ValueError, match="blocks"):
        eng.submit(toks(0, t=60), 40)   # 13 blocks > 7 usable
    eng.submit(toks(0, t=8), 4, rid="ok")
    with pytest.raises(ValueError, match="duplicate"):
        eng.submit(toks(0, t=8), 4, rid="ok")


def test_int8_paged_matches_solo_int8_stream(params):
    """int8 pools quantize per (row, head, position) exactly like the
    contiguous int8 cache, so a greedy paged stream matches the solo
    `generate(kv_quant='int8')` stream."""
    prompt = toks(7, t=18)
    ref = solo(params, prompt, 10, temperature=0.0, kv_quant="int8")
    eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                        max_slots=4, prefill_chunk=16, kv_quant="int8")
    eng.submit(prompt, 10, rid="q")
    np.testing.assert_array_equal(eng.run()["q"], ref)


def test_gqa_rope_swiglu_config_parity(params):
    """The serving tick's per-row rope + GQA pools reproduce the solo
    stream on a modern block config (rope, rmsnorm, swiglu, grouped
    KV heads)."""
    cfg = T.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                              n_kv_heads=2, n_layers=2, max_seq=96,
                              rope=True, norm="rmsnorm", ffn="swiglu")
    p2 = jax.device_put(T.init(cfg, seed=2))
    prompt = toks(9, t=19)
    for kwargs in ({"temperature": 0.0}, {"temperature": 1.0, "seed": 5}):
        ref = solo(p2, prompt, 8, cfg=cfg, **kwargs)
        eng = ServingEngine(p2, cfg, n_blocks=24, block_size=8,
                            max_slots=2, prefill_chunk=16)
        eng.submit(prompt, 8, temperature=kwargs.get("temperature", 0.0),
                   seed=kwargs.get("seed", 0), rid="q")
        np.testing.assert_array_equal(eng.run()["q"], ref,
                                      err_msg=str(kwargs))


# ------------------------------------------- telemetry: schema v6 + SLO


def test_request_events_validate_schema_v6(params, tmp_path):
    from shallowspeed_tpu.metrics import MetricsLogger
    from shallowspeed_tpu.telemetry import schema

    assert schema.SCHEMA_VERSION >= 6
    path = tmp_path / "serve.jsonl"
    eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                        max_slots=4, prefill_chunk=16,
                        metrics=MetricsLogger(path, kind="serve"),
                        log_every=4)
    eng.submit(toks(0, t=9), 8, rid="a")
    eng.submit(toks(1, t=14), 6, temperature=1.0, seed=2, rid="b")
    eng.run()
    assert schema.validate_file(path) == []
    recs = [json.loads(l) for l in path.read_text().splitlines()]
    reqs = [r for r in recs if r.get("event") == "request"]
    assert {r["id"] for r in reqs} == {"a", "b"}
    for r in reqs:
        assert r["ttft_ms"] >= 0 and r["tpot_ms"] >= 0
        assert r["tokens_in"] > 0 and r["tokens_out"] > 0
        assert "queue_depth" in r and "preempted" in r
    gen = [r for r in recs if r.get("event") == "generate"]
    assert gen and all("hbm_gbps" in g and "free_blocks" in g
                       for g in gen)
    # malformed request lines are rejected
    assert schema.validate_line({"event": "request", "id": "x"}) != []
    assert schema.validate_line(
        {"event": "request", "id": "x", "ttft_ms": 1.0, "tokens_in": 1,
         "tokens_out": 1, "queue_depth": "deep"}) != []


def test_goodput_reduces_request_percentiles(params, tmp_path):
    """The `--goodput` reducer reports p50/p95 ttft and tpot from the
    schema-v6 request events, and the formatted report prints them."""
    from shallowspeed_tpu.metrics import MetricsLogger
    from shallowspeed_tpu.telemetry.goodput import (format_report,
                                                    run_goodput)

    path = tmp_path / "serve.jsonl"
    eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                        max_slots=4, prefill_chunk=16,
                        metrics=MetricsLogger(path, kind="serve"))
    for i in range(4):
        eng.submit(toks(i, t=7 + 5 * i), 6, rid=f"r{i}")
    eng.run()
    rep = run_goodput(path)
    req = rep["requests"]
    assert req["n_requests"] == 4
    assert req["ttft_ms_p50"] <= req["ttft_ms_p95"]
    assert req["tpot_ms_p50"] <= req["tpot_ms_p95"]
    assert req["tokens_out"] == 24
    assert "requests 4" in format_report(rep)


def test_request_summary_percentiles():
    from shallowspeed_tpu.telemetry.report import (percentile,
                                                   request_summary)

    assert request_summary([]) is None
    assert percentile([], 50) is None
    recs = [{"ttft_ms": float(i), "tpot_ms": float(10 * i),
             "tokens_in": 2, "tokens_out": 3, "preempted": i % 2}
            for i in range(1, 21)]
    s = request_summary(recs)
    assert s["n_requests"] == 20
    assert s["ttft_ms_p50"] == pytest.approx(10.0, abs=1.0)
    assert s["ttft_ms_p95"] == pytest.approx(19.0, abs=1.0)
    assert s["tpot_ms_p95"] == pytest.approx(190.0, abs=10.0)
    assert s["tokens_out"] == 60 and s["preempted"] == 10
    # single-token generations carry no tpot — summary degrades
    s1 = request_summary([{"ttft_ms": 5.0, "tokens_in": 1,
                           "tokens_out": 1}])
    assert s1["tpot_ms_p50"] is None and s1["ttft_ms_p50"] == 5.0


# ------------------------------------ fast decode path (round 14)
#
# Three composable levers, each gated separately: quantized weight
# storage with fused dequant, the paged Pallas flash-decode kernel,
# and self-drafting speculative decoding. The oracles are the same
# ones PR 7 pinned: solo `generate()` streams and the gather_table
# XLA read path.


def test_quantize_weights_modes_and_errors(params):
    with pytest.raises(ValueError, match="weight_quant"):
        T.quantize_weights(params, "int4")
    assert T.quantize_weights(params, "") is params
    qp = T.quantize_weights(params, "int8")
    assert T.weight_quant_mode(qp) == "int8"
    assert T.weight_quant_mode(params) == ""
    blk = qp["blocks"][0]
    assert blk["qkv"]["Wq"].dtype == jnp.int8
    assert blk["qkv"]["Ws"].dtype == jnp.float32
    assert blk["qkv"]["Ws"].shape == (blk["qkv"]["Wq"].shape[1],)
    assert "W" not in blk["qkv"] and "b" in blk["qkv"]
    # norms and embeddings stay unquantized (O(d) / gathered rows)
    assert "g" in blk["ln1"] and qp["tok_emb"].dtype == params[
        "tok_emb"].dtype
    # idempotent: re-quantizing an already-quantized tree is a no-op
    qp2 = T.quantize_weights(qp, "int8")
    np.testing.assert_array_equal(np.asarray(qp2["blocks"][0]["qkv"][
        "Wq"]), np.asarray(blk["qkv"]["Wq"]))


def test_cast_params_preserves_quantized_storage(params):
    """The mixed-precision boundary must not rewiden quantized
    leaves: Wq stays int8/fp8 (a bf16 cast would be the materialized
    dequant copy the analysis rule flags) and the f32 scales stay f32
    (numerics, not bulk bytes)."""
    qp = T.quantize_weights(params, "int8")
    cast = jax.eval_shape(lambda p: T.cast_params(p, jnp.bfloat16), qp)
    blk = cast["blocks"][0]
    assert blk["qkv"]["Wq"].dtype == jnp.int8
    assert blk["qkv"]["Ws"].dtype == jnp.float32
    assert blk["qkv"]["b"].dtype == jnp.bfloat16   # plain floats cast
    assert cast["tok_emb"].dtype == jnp.bfloat16


def test_dequant_matmul_matches_explicit_dequant():
    """The fused form computes the same number as the materialized
    dequant (per-out-channel scale is constant along K, so scaling
    the accumulator is exact reassociation)."""
    from shallowspeed_tpu.ops.matmul import dequant_matmul

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(5, 16)), jnp.float32)
    w = rng.normal(size=(16, 8)).astype(np.float32)
    ws = (np.abs(w).max(axis=0) / 127.0).astype(np.float32)
    wq = np.clip(np.round(w / ws), -127, 127).astype(np.int8)
    ref = x @ jnp.asarray(wq.astype(np.float32) * ws)
    got = dequant_matmul(x, jnp.asarray(wq), jnp.asarray(ws))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("mode", ["int8", "fp8"])
def test_quantized_weight_serving_matches_solo_stream(params, mode):
    """A request served with quantized weight storage reproduces the
    solo `generate()` stream over the SAME quantized tree — the
    fused-dequant tick is numerics-equal to the contiguous path's
    dequant-dispatching `_dense`, greedy and sampled."""
    if mode == "fp8" and T._FP8_DTYPE is None:
        pytest.skip("no float8_e4m3fn in this jax build")
    qp = jax.device_put(T.quantize_weights(params, mode))
    prompt = toks(13, t=14)
    for kwargs in ({"temperature": 0.0}, {"temperature": 1.0, "seed": 9}):
        ref = np.asarray(generate(qp, prompt[None], CFG, 8,
                                  **kwargs))[0]
        eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                            max_slots=4, prefill_chunk=16,
                            weight_quant=mode)
        eng.submit(prompt, 8, temperature=kwargs.get("temperature", 0.0),
                   seed=kwargs.get("seed", 0), rid="q")
        np.testing.assert_array_equal(eng.run()["q"], ref,
                                      err_msg=f"{mode} {kwargs}")


def test_int8_weight_tick_bytes_beat_bf16_baseline():
    """THE byte-model acceptance gate: the int8-weight decode tick's
    read bytes price at <= 0.55x the bf16 baseline per
    `paged_read_bytes_per_tick`, and the param term is pinned EXACTLY
    against the traced tick's own param invar bytes (walker
    `aval_bytes` over `jax.make_jaxpr` on eval_shape structs — no
    device copies), int8 weights + f32 scales + bf16 embeddings +
    int8 KV mixed in one model."""
    from shallowspeed_tpu.analysis.walker import aval_bytes
    from shallowspeed_tpu.serving.cache import param_read_bytes
    from shallowspeed_tpu.serving.engine import _decode_tick

    cfg = T.TransformerConfig(vocab=64, d_model=128, n_heads=4,
                              n_layers=2, max_seq=64,
                              compute_dtype=jnp.bfloat16)
    params = T.init(cfg, seed=0)
    qp = T.quantize_weights(params, "int8")
    bs, touched, rows = 8, 2, 4
    base = paged_read_bytes_per_tick(params, cfg, touched, bs, rows,
                                     kv_quant="int8")
    fast = paged_read_bytes_per_tick(qp, cfg, touched, bs, rows,
                                     kv_quant="int8")
    assert fast <= 0.55 * base, (fast, base, fast / base)

    # walker pin: trace the tick over the post-cast tree (the dtypes
    # the engine actually serves — `cast_params` inside is then the
    # identity) and compare invar bytes term by term
    cast = jax.eval_shape(
        lambda p: T.cast_params(p, cfg.compute_dtype), qp)
    pools = jax.eval_shape(
        lambda: init_block_pool(cfg, 8, bs, kv_quant="int8"))
    s, w = rows, 4
    i32 = lambda *sh: jax.ShapeDtypeStruct(sh, np.int32)  # noqa: E731
    closed = jax.make_jaxpr(
        lambda p, pl, tok, pos, bt, temp, seeds, idx: _decode_tick(
            p, pl, tok, pos, bt, temp, seeds, idx, cfg=cfg, top_k=0,
            top_p=0.0))(
        cast, pools, i32(s), i32(s), i32(s, w),
        jax.ShapeDtypeStruct((s,), np.float32),
        jax.ShapeDtypeStruct((s,), np.uint32), i32(s))
    n_param = len(jax.tree_util.tree_leaves(cast))
    traced_param_bytes = sum(aval_bytes(v.aval)
                             for v in closed.jaxpr.invars[:n_param])
    assert traced_param_bytes == param_read_bytes(qp, cfg)
    # ...and the per-block KV term equals one traced pool block's bytes
    pool_leaves = jax.tree_util.tree_leaves(pools)
    per_block_traced = sum(aval_bytes(l) for l in pool_leaves) \
        // (cfg.n_layers * 8)
    model_per_block = (fast - param_read_bytes(qp, cfg) - rows * 4) \
        // (cfg.n_layers * touched)
    assert model_per_block == per_block_traced


def test_flash_decode_engine_matches_solo_stream(params):
    """attn_impl='flash' (the paged Pallas kernel, interpret mode on
    CPU) reproduces the gather-path solo stream token-for-token —
    kernel-vs-reference logits sit at ~1e-7, far inside sampling's
    decision boundaries on this model."""
    prompt = toks(17, t=21)
    ref = solo(params, prompt, 10, temperature=0.0)
    eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                        max_slots=4, prefill_chunk=16,
                        attn_impl="flash")
    eng.submit(prompt, 10, rid="q")
    np.testing.assert_array_equal(eng.run()["q"], ref)
    with pytest.raises(ValueError, match="attn_impl"):
        ServingEngine(params, CFG, n_blocks=8, attn_impl="paged")


def test_flash_decode_full_stack_matches_int8_oracle(params):
    """All three levers at once (int8 weights + int8 KV + flash
    kernel) still reproduce the solo oracle over the same quantized
    tree and int8 cache — the levers compose without drift."""
    qp = jax.device_put(T.quantize_weights(params, "int8"))
    prompt = toks(19, t=18)
    ref = np.asarray(generate(qp, prompt[None], CFG, 9,
                              temperature=0.0, kv_quant="int8"))[0]
    eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                        max_slots=4, prefill_chunk=16,
                        weight_quant="int8", kv_quant="int8",
                        attn_impl="flash", spec_k=2)
    eng.submit(prompt, 9, rid="q")
    np.testing.assert_array_equal(eng.run()["q"], ref)


# --------------------------------------------- speculative decoding


def spec_prompt(seed=0, t=18):
    """Self-similar prompt (repeated motif): gives the n-gram
    prompt-lookup proposer something to draft from."""
    rng = np.random.default_rng(seed)
    motif = rng.integers(0, 64, max(2, t // 3)).astype(np.int32)
    return np.concatenate([motif] * (-(-t // len(motif))))[:t]


def test_spec_decode_temp0_stream_identical_and_accepts(params):
    """Temp-0 spec-on streams are token-identical to solo
    `generate()` for EVERY prompt, and on at least one of the probed
    prompts speculation accepts drafts — which must show up as a tick
    count below max_new's one-token-per-tick floor (accepted drafts
    emit extra tokens per tick)."""
    accepted_somewhere = False
    for seed in (0, 5, 9, 23):
        prompt = spec_prompt(seed, t=18)
        ref = solo(params, prompt, 16, temperature=0.0)
        eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                            max_slots=4, prefill_chunk=16, spec_k=3)
        eng.submit(prompt, 16, rid="q")
        res = eng.run()
        np.testing.assert_array_equal(res["q"], ref,
                                      err_msg=f"seed={seed}")
        acc = eng.counters["spec_accepted"]
        assert eng.counters["spec_drafted"] >= acc
        if acc > 0:
            accepted_somewhere = True
            # 16 tokens, 1 sampled at prefill -> 15 ticks unsped
            assert eng.counters["ticks"] < 15
        assert eng.alloc.n_free == eng.alloc.n_usable
    assert accepted_somewhere, (
        "no probed prompt produced an accepted draft — the proposer "
        "or the accept rule broke")


def test_spec_decode_seeded_sampling_parity(params):
    """The oracle-sampler parity pin: under seeded SAMPLING the
    spec-on stream equals the solo stream too — every emitted token
    is the oracle draw `sample(fold_in(PRNGKey(seed), i), logits_i)`
    at its own index (the accept rule re-draws the oracle sample, so
    the output distribution is the oracle sampler's by construction,
    not merely in expectation)."""
    prompt = spec_prompt(7, t=15)
    for seed, temp in ((3, 1.0), (11, 0.7)):
        ref = solo(params, prompt, 12, temperature=temp, seed=seed)
        eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                            max_slots=4, prefill_chunk=16, spec_k=3)
        eng.submit(prompt, 12, temperature=temp, seed=seed, rid="q")
        np.testing.assert_array_equal(eng.run()["q"], ref,
                                      err_msg=f"seed={seed}")


def test_spec_decode_concurrent_and_under_preemption(params):
    """Spec-on continuous batching under pool pressure: concurrent
    requests (drafts competing for free rows) with forced eviction
    still reproduce every solo stream, and the allocator balances at
    drain — draft-grown tables free cleanly."""
    reqs = {k: (spec_prompt(40 + i, t=20), 12)
            for i, k in enumerate("abc")}
    oracle = {k: solo(params, p, mn, temperature=0.0)
              for k, (p, mn) in reqs.items()}
    # tight pool: 13 usable blocks * 8 = 104 positions < 3 * 32
    eng = ServingEngine(params, CFG, n_blocks=14, block_size=8,
                        max_slots=4, prefill_chunk=16, spec_k=3)
    for k, (p, mn) in reqs.items():
        eng.submit(p, mn, rid=k)
    res = eng.run()
    for k in reqs:
        np.testing.assert_array_equal(res[k], oracle[k], err_msg=k)
    assert eng.alloc.n_free == eng.alloc.n_usable
    assert eng.alloc.n_allocated == 0


def test_spec_decode_zero_new_executables(params):
    """Drafts are DATA in rows that already executed empty: after
    spec-off warmup over the same width buckets, turning speculation
    on compiles nothing new."""
    eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                        max_slots=4, prefill_chunk=16)
    eng.submit(spec_prompt(1, t=18), 10, rid="w0")
    eng.run()
    warm = eng.executable_counts()
    eng2 = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                         max_slots=4, prefill_chunk=16, spec_k=3)
    eng2.submit(spec_prompt(2, t=18), 10, rid="s0")
    eng2.submit(spec_prompt(3, t=12), 8, rid="s1")
    eng2.run()
    assert eng2.counters["spec_drafted"] > 0
    assert eng2.executable_counts() == warm, (
        f"speculation recompiled: {warm} -> {eng2.executable_counts()}")


def test_spec_telemetry_schema_v9_and_status_surface(params, tmp_path):
    """Speculation telemetry rides the monitor plane: request lines
    carry the per-request drafted/accepted record, generate lines the
    windowed acceptance rate (all schema-v9-valid), and the monitor
    surfaces spec_accept_rate in /status.json's serving block and
    /metrics."""
    from shallowspeed_tpu.metrics import MetricsLogger
    from shallowspeed_tpu.telemetry import schema
    from shallowspeed_tpu.telemetry.monitor import Monitor

    assert schema.SCHEMA_VERSION >= 9
    path = tmp_path / "spec.jsonl"
    eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                        max_slots=4, prefill_chunk=16, spec_k=3,
                        metrics=MetricsLogger(path, kind="serve"),
                        log_every=2)
    eng.submit(spec_prompt(21, t=18), 12, rid="a")
    eng.run()
    assert schema.validate_file(path) == []
    recs = [json.loads(l) for l in path.read_text().splitlines()]
    req = next(r for r in recs if r.get("event") == "request")
    assert req["spec_drafted"] >= req["spec_accepted"] >= 0
    assert req["spec_drafted"] == eng.counters["spec_drafted"]
    gens = [r for r in recs if r.get("event") == "generate"]
    assert gens and all("spec_accept_rate" in g for g in gens)
    mon = Monitor()
    for r in recs:
        mon.note_line(r)
    srv = mon.status()["serving"]
    assert "spec_accept_rate" in srv
    assert "spec_accept_rate" in mon.prometheus()
    # malformed speculation fields are rejected
    assert schema.validate_line(
        {"event": "request", "id": "x", "ttft_ms": 1.0, "tokens_in": 1,
         "tokens_out": 1, "spec_drafted": "many"}) != []
    assert schema.validate_line(
        {"event": "generate", "tokens_per_sec": 1.0,
         "spec_accept_rate": "high"}) != []


# --------------------------- drain + cross-engine failover (round 15)


def test_engine_drain_typed_rejection_and_completion(params):
    """Graceful drain: accepted work (queued AND running) completes,
    new submits raise the typed EngineDraining (the old post-drain
    behavior was implicit), drain() reports completion, and the
    allocator balances — the replica-side half of the router's
    scale-down path."""
    from shallowspeed_tpu.serving import EngineDraining

    eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                        max_slots=2, prefill_chunk=16)
    oracle = {k: solo(params, toks(70 + i, t=10), 6, temperature=0.0)
              for i, k in enumerate("abc")}
    for i, k in enumerate("abc"):      # c queues behind 2 slots
        eng.submit(toks(70 + i, t=10), 6, rid=k)
    eng.step()
    assert eng.drain() is False        # in-flight work remains
    with pytest.raises(EngineDraining) as ei:
        eng.submit(toks(80, t=8), 4, rid="late")
    assert ei.value.pending == 3
    res = eng.run()
    for k, ref in oracle.items():
        np.testing.assert_array_equal(res[k], ref, err_msg=k)
    assert eng.drain() is True         # idempotent, now complete
    assert eng.alloc.n_free == eng.alloc.n_usable
    # shed-pause is a different mechanism and must still resume;
    # draining is one-way
    assert eng.draining and not eng.admission_paused


@pytest.mark.parametrize("kwargs", [
    {"temperature": 0.0},
    {"temperature": 0.7, "seed": 11},
], ids=["greedy", "sampled"])
def test_failover_continuation_on_fresh_engine_matches_solo(params,
                                                            kwargs):
    """The cross-process failover mechanism at the engine level (the
    fleet drill's in-process canary): decode a request halfway on one
    engine, then re-submit prompt + tokens-so-far on a FRESH engine
    instance (`submit(generated=)` — a different process in the
    drill). The continuation re-prefills and keeps drawing from
    `fold_in(PRNGKey(seed), i)` at the continued indices, so the
    completed stream is token-identical to the solo `generate()`
    oracle."""
    prompt = toks(33, t=14)
    ref = solo(params, prompt, 10, **kwargs)
    eng1 = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                        max_slots=4, prefill_chunk=16)
    eng1.submit(prompt, 10, temperature=kwargs.get("temperature", 0.0),
                seed=kwargs.get("seed", 0), rid="q")
    while len(eng1.poll("q")["tokens"]) < 4:   # mid-decode "death"
        eng1.step()
    prefix = [int(t) for t in eng1.poll("q")["tokens"]]
    assert 4 <= len(prefix) < 10
    np.testing.assert_array_equal(prefix, ref[:len(prefix)])
    eng2 = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                         max_slots=4, prefill_chunk=16)
    eng2.submit(prompt, 10,
                temperature=kwargs.get("temperature", 0.0),
                seed=kwargs.get("seed", 0), rid="q",
                generated=prefix)
    res = eng2.run()
    np.testing.assert_array_equal(res["q"], ref)
    assert eng2.alloc.n_free == eng2.alloc.n_usable
    # a continuation that already has everything is a caller bug
    with pytest.raises(ValueError, match="nothing left"):
        eng2.submit(prompt, 4, rid="full", generated=[1, 2, 3, 4])


# ------------------------------- satellites: rebucket + atomicity


def test_rebucket_ledger_and_log_executable_growth(params, tmp_path):
    """A long-running request crossing geometric table-width buckets
    re-traces the decode tick O(log max_len) times — not O(len) — and
    every crossing stamps a `table_rebucket` ledger event, so
    attribution never books the retrace as unexplained."""
    from shallowspeed_tpu.metrics import MetricsLogger
    from shallowspeed_tpu.telemetry import schema
    from shallowspeed_tpu.serving.engine import _decode_tick

    path = tmp_path / "rebucket.jsonl"
    before = int(_decode_tick._cache_size())
    cfg2 = T.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                               n_layers=2, max_seq=256)
    p2 = jax.device_put(T.init(cfg2, seed=3))
    # block_size 4, bucket 1: a 4 + 60 = 64-position request walks
    # widths 1 -> 2 -> 4 -> 8 -> 16 (forced boundary crossings)
    eng = ServingEngine(p2, cfg2, n_blocks=32, block_size=4,
                        max_slots=2, prefill_chunk=8, table_bucket=1,
                        metrics=MetricsLogger(path, kind="serve"))
    eng.submit(toks(2, t=4), 60, rid="long")
    eng.run()
    grown = int(_decode_tick._cache_size()) - before
    # O(log): 5 distinct widths for 16 blocks, never one-per-block
    assert 1 <= grown <= 6, grown
    assert schema.validate_file(path) == []
    stamps = [json.loads(l) for l in path.read_text().splitlines()
              if '"table_rebucket"' in l]
    assert stamps, "no table_rebucket ledger stamp at the crossing"
    for s in stamps:
        assert s["event"] == "ledger" and s["width"] != s["prev_width"]
    # crossings observed = distinct consecutive width changes >= grown-1
    assert len(stamps) >= grown - 1


def test_alloc_partial_failure_is_atomic():
    """The all-or-nothing claim in `BlockAllocator.alloc`'s docstring,
    pinned: a failing alloc leaves n_free AND the allocated set
    unchanged (no leaked ids), and the free list still serves the
    original capacity afterwards."""
    a = BlockAllocator(6)               # 5 usable
    got = a.alloc(2)
    free_before, alloc_before = a.n_free, a.n_allocated
    for ask in (4, 100):
        with pytest.raises(OutOfBlocks):
            a.alloc(ask)
        assert a.n_free == free_before
        assert a.n_allocated == alloc_before
    rest = a.alloc(3)                   # the full remainder still works
    assert len(set(got) | set(rest)) == 5
    a.free(got + rest)
    assert a.n_free == a.n_usable


def test_write_rows_scratch_sink_isolation():
    """Pad/inactive rows steered to the scratch block never corrupt
    live reads: writes to SCRATCH_BLOCK land (possibly colliding) in
    block 0 only, every other block is bit-unchanged, and a gathered
    table (which by contract never contains block 0) reads back
    exactly what was written before the scratch traffic."""
    from shallowspeed_tpu.serving.cache import (SCRATCH_BLOCK,
                                                gather_table, write_rows)

    cfg = CFG
    bs = 8
    pool = init_block_pool(cfg, 8, bs)[0]
    rng = np.random.default_rng(2)
    k1 = jnp.asarray(rng.normal(size=(1, cfg.kv_heads, cfg.head_dim)),
                     jnp.float32)
    v1 = jnp.asarray(rng.normal(size=(1, cfg.kv_heads, cfg.head_dim)),
                     jnp.float32)
    pool = write_rows(pool, k1, v1, jnp.asarray([3]), jnp.asarray([5]),
                      quant=False)
    live_before = {n: np.asarray(l) for n, l in pool.items()}
    bt = jnp.asarray([[3, 1]], jnp.int32)
    view_before = {n: np.asarray(l)
                   for n, l in gather_table(pool, bt).items()}
    # a burst of scratch writes, including COLLIDING offsets (three
    # rows, same block 0, same offset — the duplicate-scatter winner
    # is unspecified and must not matter)
    ks = jnp.asarray(rng.normal(size=(3, cfg.kv_heads, cfg.head_dim)),
                     jnp.float32)
    vs = jnp.asarray(rng.normal(size=(3, cfg.kv_heads, cfg.head_dim)),
                     jnp.float32)
    pool = write_rows(pool, ks, vs,
                      jnp.full((3,), SCRATCH_BLOCK, jnp.int32),
                      jnp.asarray([0, 0, 4]), quant=False)
    for name, leaf in pool.items():
        np.testing.assert_array_equal(
            np.asarray(leaf)[1:], live_before[name][1:],
            err_msg=f"{name}: scratch write leaked past block 0")
    view_after = gather_table(pool, bt)
    for name in view_before:
        np.testing.assert_array_equal(
            np.asarray(view_after[name]), view_before[name],
            err_msg=f"{name}: gathered read changed after scratch "
                    f"traffic")


def test_paged_read_bytes_per_tick_model(params):
    """The live-blocks HBM model: params once + touched blocks' K/V
    (+ int8 scales) + token ids — the serving generalization of
    `decode_read_bytes_per_token`'s full-cache sweep."""
    from shallowspeed_tpu.analysis.walker import aval_bytes

    cast = jax.eval_shape(
        lambda p: T.cast_params(p, CFG.compute_dtype), params)
    p_bytes = int(sum(aval_bytes(l)
                      for l in jax.tree_util.tree_leaves(cast)))
    bs, touched, rows = 16, 5, 4
    got = paged_read_bytes_per_tick(params, CFG, touched, bs, rows)
    per_block = 2 * CFG.kv_heads * bs * CFG.head_dim * 4  # f32 cache
    assert got == p_bytes + CFG.n_layers * touched * per_block + rows * 4
    q = paged_read_bytes_per_tick(params, CFG, touched, bs, rows,
                                  kv_quant="int8")
    per_block_q = (2 * CFG.kv_heads * bs * CFG.head_dim
                   + 2 * CFG.kv_heads * bs * 4)
    assert q == p_bytes + CFG.n_layers * touched * per_block_q + rows * 4
    assert q < got                      # int8 sweeps fewer bytes


# ------------------------------------ prefix caching (round 19)


def test_block_allocator_double_free_rejected():
    """Satellite: duplicate ids inside ONE free() call used to slip
    through the membership check (each id individually "allocated"),
    corrupting the free list. Now the per-call multiplicity is
    validated against the refcount BEFORE anything mutates."""
    a = BlockAllocator(8)
    got = a.alloc(2)
    with pytest.raises(ValueError, match="over-released"):
        a.free([got[0], got[0]])
    # atomic: the failed call mutated nothing — both ids still live
    assert a.n_allocated == 2 and a.n_free == 5
    a.free(got)
    assert a.n_free == a.n_usable
    # same rule across calls: a second release past refcount 0 raises
    b = a.alloc(1)
    a.free(b)
    with pytest.raises(ValueError):
        a.free(b)


def test_refcount_cold_lru_reclaim_order():
    """Refcounted sharing + the cold list: released-but-indexed
    blocks park on an LRU cold list (oldest reclaimed first, index
    entry dropped at reclaim), acquire() revives them, and a block
    with a live reference is NEVER reclaimed — the pool exhausts with
    OutOfBlocks instead."""
    from shallowspeed_tpu.serving.cache import PrefixIndex

    idx = PrefixIndex(block_size=4)
    a = BlockAllocator(8, index=idx)          # 7 usable
    tokens = np.arange(12, dtype=np.int32)    # 3 aligned blocks
    got = a.alloc(3)
    assert idx.insert(tokens, got) == 3
    a.release(got)          # refcount 0 + indexed -> cold, in order
    assert a.n_cold == 3 and a.n_free == 4 and a.n_live == 0
    assert a.n_free + a.n_live + a.n_cold == a.n_usable
    # a cache hit revives the chain from cold
    assert idx.match(tokens) == got
    a.acquire([got[1]])
    assert a.n_cold == 2 and a.n_live == 1 and a.refcount(got[1]) == 1
    # drain the free list, then force reclaims: OLDEST cold first,
    # and its index entry vanishes with it
    a.alloc(4)
    assert a.alloc(1) == [got[0]] and not idx.has_block(got[0])
    assert a.cold_reclaims == 1
    assert a.alloc(1) == [got[2]]
    assert a.n_cold == 0
    with pytest.raises(OutOfBlocks):
        a.alloc(1)          # got[1] is referenced — never reclaimed
    assert a.refcount(got[1]) == 1
    # releasing more references than held is rejected atomically
    with pytest.raises(ValueError):
        a.release([got[1], got[1]])
    assert a.refcount(got[1]) == 1


def test_prefix_cache_parity_tail_only_and_records(params):
    """The parity gate: cache-hit streams are token-identical to the
    oracle at temperature 0 AND under seeded sampling; a fully-shared
    block-aligned prompt re-prefills only the copied tail block (one
    chunk with prefill_chunk == block_size); request records carry the
    v14 prefix_hit_blocks / prefill_skipped_tokens fields."""
    shared = toks(90, t=32)                   # 4 aligned blocks of 8
    eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                        max_slots=4, prefill_chunk=8, prefix_cache=True)
    ref = solo(params, shared, 6, temperature=0.0)
    eng.submit(shared, 6, rid="cold")
    np.testing.assert_array_equal(eng.run()["cold"], ref)
    cold_chunks = eng.counters["prefill_chunks"]
    assert cold_chunks == 4
    # full-aligned hit under seeded sampling: CoW tail, 1 chunk only
    ref2 = solo(params, shared, 6, temperature=0.8, seed=5)
    eng.submit(shared, 6, temperature=0.8, seed=5, rid="hit")
    np.testing.assert_array_equal(eng.run()["hit"], ref2)
    assert eng.counters["prefill_chunks"] - cold_chunks == 1
    rec = next(r for r in eng.request_records if r["id"] == "hit")
    assert rec["prefix_hit_blocks"] == 4
    assert rec["prefill_skipped_tokens"] == 31    # all but the CoW tok
    # divergent tail: leading 3 blocks hit, the rest prefills fresh
    ext = np.concatenate([shared[:24], toks(91, t=10)])
    ref3 = solo(params, ext, 6, temperature=0.0)
    eng.submit(ext, 6, rid="ext")
    np.testing.assert_array_equal(eng.run()["ext"], ref3)
    rec = next(r for r in eng.request_records if r["id"] == "ext")
    assert rec["prefix_hit_blocks"] == 3
    assert rec["prefill_skipped_tokens"] == 24
    # drain invariant, extended: live zero, free + cold == usable
    assert eng.alloc.n_live == 0
    assert eng.alloc.n_free + eng.alloc.n_cold == eng.alloc.n_usable


def test_prefix_cache_mid_run_join_parity(params):
    """A sharer that joins MID-RUN (while the donor is still
    decoding) must stream the oracle whether it misses (donor not
    finished -> nothing donated yet) or hits a prefix some earlier
    request already sealed."""
    shared = toks(92, t=24)
    refs = {"a": solo(params, shared, 8, temperature=0.0),
            "b": solo(params, shared, 8, temperature=0.9, seed=3),
            "c": solo(params, shared, 8, temperature=0.0)}
    eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                        max_slots=4, prefill_chunk=8, prefix_cache=True)
    eng.submit(shared, 8, rid="a")
    for _ in range(2):                       # a is mid-prefill/decode
        eng.step()
    eng.submit(shared, 8, temperature=0.9, seed=3, rid="b")
    while eng.poll("a")["status"] != "done":
        eng.step()
    eng.submit(shared, 8, rid="c")           # after donation: a hit
    res = eng.run()
    for k, ref in refs.items():
        np.testing.assert_array_equal(res[k], ref, err_msg=k)
    assert eng.counters["prefix_hits"] >= 1  # c at minimum


def test_prefix_cache_cow_leaves_shared_block_bit_unchanged(params):
    """Copy-on-write at the tail: a second request over the SAME
    fully-aligned prompt copies the tail block and rewrites its own
    last token in the copy — every byte of the donor's indexed blocks
    (the shared tail included) is bit-identical afterwards."""
    shared = toks(93, t=16)                  # 2 aligned blocks
    eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                        max_slots=4, prefill_chunk=8, prefix_cache=True)
    eng.submit(shared, 4, rid="a")
    eng.run()
    matched = eng.prefix.match(shared)
    assert len(matched) == 2
    sel = np.asarray(matched, np.int32)
    snap = [{n: np.asarray(leaf[sel]).copy()
             for n, leaf in pool.items()} for pool in eng.pools]
    ref = solo(params, shared, 4, temperature=0.0)
    eng.submit(shared, 4, rid="b")
    np.testing.assert_array_equal(eng.run()["b"], ref)
    for pool, before in zip(eng.pools, snap):
        for n, leaf in pool.items():
            np.testing.assert_array_equal(
                np.asarray(leaf[sel]), before[n],
                err_msg=f"{n}: CoW consumer mutated a shared block")


def test_prefix_cache_oom_evict_requeue_shared(params):
    """Preemption under sharing: a pool too small for the concurrent
    set forces evictions mid-flight; evicted requests drop their
    references, re-probe the index on re-admission, and every stream
    still matches its solo oracle. The allocator balances at drain
    under the extended invariant."""
    shared = toks(94, t=16)

    def mk(i):
        return np.concatenate([shared, toks(100 + i, t=6)])

    oracle = {f"r{i}": solo(params, mk(i), 12, temperature=0.0)
              for i in range(3)}
    # 9 usable blocks * 8 = 72 positions < 3 * blocks_for(33) * 8
    eng = ServingEngine(params, CFG, n_blocks=10, block_size=8,
                        max_slots=4, prefill_chunk=8, prefix_cache=True)
    for i in range(3):
        eng.submit(mk(i), 12, rid=f"r{i}")
    res = eng.run()
    for k, ref in oracle.items():
        np.testing.assert_array_equal(res[k], ref, err_msg=k)
    assert eng.counters["preempted"] >= 1, "pool never pressured"
    assert eng.alloc.n_live == 0
    assert eng.alloc.n_free + eng.alloc.n_cold == eng.alloc.n_usable


def test_prefix_cache_zero_new_executables(params):
    """The hit path (prefix map-in + CoW copy + short tail prefill)
    is DATA through programs that already executed cold: after a
    prefix-OFF warmup over the same shapes, serving hits with the
    cache on compiles nothing new."""
    eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                        max_slots=4, prefill_chunk=8)
    eng.submit(toks(95, t=16), 6, rid="w")
    eng.run()
    warm = eng.executable_counts()
    eng2 = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                         max_slots=4, prefill_chunk=8,
                         prefix_cache=True)
    eng2.submit(toks(95, t=16), 6, rid="a")
    eng2.run()
    eng2.submit(toks(95, t=16), 6, rid="b")   # full-aligned CoW hit
    eng2.run()
    assert eng2.counters["prefix_hits"] == 1
    assert eng2.executable_counts() == warm, (
        f"prefix caching recompiled: {warm} -> "
        f"{eng2.executable_counts()}")


def test_prefix_telemetry_schema_v14_and_status_surface(params,
                                                        tmp_path):
    """Prefix-cache telemetry rides the monitor plane: request lines
    carry prefix_hit_blocks / prefill_skipped_tokens, generate lines
    the windowed prefix_hit_rate + cold/indexed gauges (all schema-
    v14-valid), and the monitor surfaces them in /status.json's
    serving block and /metrics."""
    from shallowspeed_tpu.metrics import MetricsLogger
    from shallowspeed_tpu.telemetry import schema
    from shallowspeed_tpu.telemetry.monitor import Monitor

    assert schema.SCHEMA_VERSION >= 14
    path = tmp_path / "prefix.jsonl"
    eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                        max_slots=4, prefill_chunk=8,
                        prefix_cache=True,
                        metrics=MetricsLogger(path, kind="serve"),
                        log_every=2)
    shared = toks(96, t=16)
    eng.submit(shared, 6, rid="a")
    eng.run()
    eng.submit(shared, 6, rid="b")
    eng.run()
    assert schema.validate_file(path) == []
    recs = [json.loads(l) for l in path.read_text().splitlines()]
    hit = next(r for r in recs if r.get("event") == "request"
               and r.get("id") == "b")
    assert hit["prefix_hit_blocks"] == 2
    assert hit["prefill_skipped_tokens"] == 15
    gens = [r for r in recs if r.get("event") == "generate"]
    assert gens and all("prefix_hit_rate" in g and "cold_blocks" in g
                        and "prefix_blocks" in g for g in gens)
    # the prefill_cached lifecycle phase stamps the hit at admission
    lcs = [r for r in recs if r.get("event") == "lifecycle"
           and r.get("phase") == "prefill_cached"]
    assert lcs and lcs[0]["blocks"] == 2 and lcs[0]["tokens"] == 15
    mon = Monitor()
    for r in recs:
        mon.note_line(r)
    srv = mon.status()["serving"]
    assert "prefix_hit_rate" in srv and "cold_blocks" in srv
    prom = mon.prometheus()
    assert "prefix_hit_rate" in prom and "prefix_blocks" in prom
    # malformed prefix fields are rejected
    assert schema.validate_line(
        {"event": "request", "id": "x", "ttft_ms": 1.0, "tokens_in": 1,
         "tokens_out": 1, "prefix_hit_blocks": "many"}) != []
    assert schema.validate_line(
        {"event": "generate", "tokens_per_sec": 1.0,
         "prefix_hit_rate": "high"}) != []
