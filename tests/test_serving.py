"""Serving runtime (`shallowspeed_tpu/serving/`): paged KV cache +
continuous-batching decode server.

The load-bearing invariants:

- **Stream parity.** Every request served concurrently reproduces its
  solo `generate()` token stream exactly (fixed seeds, greedy AND
  sampled) — paged attention shares `kv_cache.masked_attention` with
  the contiguous path and sampling shares the per-request
  `fold_in(PRNGKey(seed), token_index)` key schedule.
- **Compile hygiene.** Requests join/leave the running batch with ZERO
  new executables after warmup (fixed slot capacity, geometric
  block-table width buckets, donated pools) — the serving analog of
  `test_vm_executables_compile_exactly_once`.
- **Chunked prefill.** A long prompt admitted mid-run never freezes
  in-flight decodes for more than one chunk tick.
- **Allocator soundness.** alloc == free at drain; OOM evicts the
  newest running request (re-queued, stream continues exactly) and
  can never deadlock.
"""

import json
from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.models.generate import generate, init_kv_cache, prefill
from shallowspeed_tpu.serving import (BlockAllocator, OutOfBlocks,
                                      ServingEngine, blocks_for,
                                      init_block_pool,
                                      paged_read_bytes_per_tick,
                                      table_width)

CFG = T.TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                          max_seq=128)


@pytest.fixture(scope="module")
def params():
    return jax.device_put(T.init(CFG, seed=1))


def toks(seed=0, t=12, vocab=64):
    rng = np.random.default_rng(seed)
    return rng.integers(0, vocab, (t,)).astype(np.int32)


def solo(params, prompt, max_new, cfg=CFG, **kw):
    return np.asarray(generate(params, prompt[None, :], cfg, max_new,
                               **kw))[0]


# ------------------------------------------------- allocator + pools


def test_block_allocator_invariants():
    a = BlockAllocator(8)           # block 0 reserved -> 7 usable
    assert a.n_usable == 7 and a.n_free == 7
    got = a.alloc(3)
    assert len(got) == 3 and 0 not in got       # scratch never issued
    assert a.n_free == 4 and a.n_allocated == 3
    with pytest.raises(OutOfBlocks):
        a.alloc(5)                  # all-or-nothing: nothing leaked
    assert a.n_free == 4
    with pytest.raises(ValueError):
        a.free([99])                # not allocated
    a.free(got)
    assert a.n_free == 7 and a.n_allocated == 0   # balanced at drain
    with pytest.raises(ValueError):
        BlockAllocator(1)           # nothing usable past scratch


def test_blocks_for_and_table_width():
    assert blocks_for(0, 16) == 0
    assert blocks_for(1, 16) == 1
    assert blocks_for(16, 16) == 1
    assert blocks_for(17, 16) == 2
    # geometric width buckets: O(log) executables as tables grow
    assert table_width(1, 4) == 4
    assert table_width(4, 4) == 4
    assert table_width(5, 4) == 8
    assert table_width(33, 4) == 64


def test_init_block_pool_shapes_and_errors():
    pools = init_block_pool(CFG, 8, 16)
    assert len(pools) == CFG.n_layers
    assert pools[0]["k"].shape == (8, CFG.kv_heads, 16, CFG.head_dim)
    q = init_block_pool(CFG, 8, 16, kv_quant="int8")
    assert q[0]["k"].dtype == jnp.int8
    assert q[0]["k_s"].shape == (8, CFG.kv_heads, 16, 1)
    with pytest.raises(ValueError, match="kv_quant"):
        init_block_pool(CFG, 8, 16, kv_quant="fp4")
    with pytest.raises(ValueError, match="n_blocks"):
        init_block_pool(CFG, 1, 16)


# ------------------------------------- satellites: typed errors, asarray


def test_init_kv_cache_rejects_unknown_quant_mode():
    """Satellite: the bare `assert kv_quant == "int8"` became a typed
    ValueError naming the supported modes — asserts vanish under
    `python -O`, and this gate guards a production cache layout."""
    with pytest.raises(ValueError, match="int8"):
        init_kv_cache(CFG, 2, kv_quant="fp8")
    assert init_kv_cache(CFG, 1, cache_len=8, kv_quant="int8")


def test_decode_report_rejects_nonpositive_inputs(params):
    from shallowspeed_tpu.models.generate import decode_report

    with pytest.raises(ValueError, match="seconds"):
        decode_report(params, CFG, batch=1, cache_len=8, n_tokens=4,
                      seconds=0.0)
    with pytest.raises(ValueError, match="n_tokens"):
        decode_report(params, CFG, batch=1, cache_len=8, n_tokens=0,
                      seconds=1.0)


def test_generate_converts_prompt_on_no_padding_branch(params):
    """Satellite: `generate` now runs `jnp.asarray` on BOTH branches.
    A prompt whose bucket equals its length (tp == tp_b: the
    no-padding branch) used to pass the caller's raw array straight
    into jit — an int64 host array must normalize identically on both
    branches."""
    # max_seq 128, max_new 104 -> bucket cap = 24 == tp: no padding
    p32 = toks(3, t=24)
    p64 = p32.astype(np.int64)
    a = solo(params, p32, 8, temperature=0.0)
    b = np.asarray(generate(params, p64[None, :], CFG, 8,
                            temperature=0.0))[0]
    np.testing.assert_array_equal(a, b)
    # padded branch, same dtypes
    c = solo(params, toks(3, t=10), 8, temperature=0.0)
    d = np.asarray(generate(params, toks(3, t=10).astype(np.int64)[None],
                            CFG, 8, temperature=0.0))[0]
    np.testing.assert_array_equal(c, d)


# -------------------------------------- paged vs contiguous numerics


def test_prefill_chunk_logits_match_contiguous_prefill(params):
    """The paged prefill's last-position logits match the contiguous
    `prefill`'s to 1e-4 — same cache math read through the gathered
    block table (`kv_cache.masked_attention` is shared)."""
    from shallowspeed_tpu.serving.engine import _prefill_chunk

    prompt = toks(5, t=14)
    ref, _ = prefill(params, jnp.asarray(prompt[None]), CFG,
                     init_kv_cache(CFG, 1, cache_len=32))
    # pool/chunk/width shapes shared with the engine tests below, so
    # this compiles (at most) once per suite run
    pools = init_block_pool(CFG, 32, 8)
    alloc = BlockAllocator(32)
    table = alloc.alloc(blocks_for(14, 8))
    c = 16
    tokens = np.zeros((1, c), np.int32)
    tokens[0, :14] = prompt
    bt = np.zeros((1, table_width(len(table), 4)), np.int32)
    bt[0, :len(table)] = table
    logits, pools = _prefill_chunk(params, pools, tokens, np.int32(0),
                                   np.int32(14), bt, cfg=CFG)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("quant", [False, True], ids=["f32", "int8"])
def test_paged_attention_matches_cached_attention(params, quant):
    """Block-gathered attention == contiguous `_cached_attention` on
    identical cache contents (to fp-reorder noise): the read path's
    only difference is the gather. int8 pools quantize per (row, head,
    position) exactly like the contiguous int8 cache, so the parity
    holds there too — the default-tier int8 canary (the full int8
    stream oracle rides the slow tier)."""
    from shallowspeed_tpu.models.kv_cache import (cache_write,
                                                  cached_attention,
                                                  masked_attention)
    from shallowspeed_tpu.serving.cache import gather_table, write_rows

    rng = np.random.default_rng(0)
    bs, n_pos = 8, 19
    kv_quant = "int8" if quant else ""
    kv = [rng.normal(size=(1, n_pos, CFG.kv_heads,
                           CFG.head_dim)).astype(np.float32)
          for _ in range(2)]
    q = jnp.asarray(rng.normal(
        size=(1, 1, CFG.n_heads, CFG.head_dim)).astype(np.float32))
    cache = init_kv_cache(CFG, 1, cache_len=32, kv_quant=kv_quant)[0]
    cache = cache_write(cache, jnp.asarray(kv[0]), jnp.asarray(kv[1]), 0)
    pool = init_block_pool(CFG, 32, bs, kv_quant=kv_quant)[0]
    table = [3, 1, 5]                      # deliberately out of order
    for pos in range(n_pos):
        pool = write_rows(
            pool, jnp.asarray(kv[0][:, pos]), jnp.asarray(kv[1][:, pos]),
            jnp.asarray([table[pos // bs]]), jnp.asarray([pos % bs]),
            quant=quant)
    bt = jnp.asarray([table + [0]], jnp.int32)       # padded width 4
    pos = n_pos - 1
    ref = cached_attention(q, cache, pos, CFG)
    view = gather_table(pool, bt)
    valid = (jnp.arange(4 * bs) <= pos)[None, None, None, None, :]
    got = masked_attention(q, view, valid, CFG)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


# --------------------------------------------- stream-parity oracle


@pytest.mark.parametrize("kwargs", [
    {"temperature": 0.0},
    {"temperature": 1.0, "seed": 7},
    {"temperature": 0.7, "seed": 3},
], ids=["greedy", "sampled", "temp0.7"])
def test_solo_request_matches_generate(params, kwargs):
    """A request served alone reproduces its `generate()` stream
    token-for-token — the continuous-batching correctness oracle's
    base case, greedy and sampled (same fold_in key schedule)."""
    prompt = toks(11, t=13)
    ref = solo(params, prompt, 10, **kwargs)
    eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                        max_slots=4, prefill_chunk=16)
    eng.submit(prompt, 10, temperature=kwargs.get("temperature", 0.0),
               seed=kwargs.get("seed", 0), rid="q")
    res = eng.run()
    np.testing.assert_array_equal(res["q"], ref)
    assert eng.alloc.n_free == eng.alloc.n_usable


def test_concurrent_mixed_lengths_match_solo_oracles(params):
    """N concurrent requests with different prompt lengths, max_new,
    and samplers — including one submitted MID-RUN (joins the running
    batch) — each reproduce their solo stream exactly."""
    # max_new=10 signatures deliberately match the solo-parity test's
    # compiled generate() oracles (warm jit cache); 12 and 6 are fresh
    reqs = {
        "a": (toks(0, t=5), 10, 0.0, 0),
        "b": (toks(1, t=23), 12, 1.0, 7),
        "c": (toks(2, t=40), 6, 0.0, 0),
        "late": (toks(3, t=17), 10, 1.0, 11),
    }
    oracle = {k: solo(params, p, mn, temperature=tmp, seed=s)
              for k, (p, mn, tmp, s) in reqs.items()}
    eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                        max_slots=4, prefill_chunk=16)
    for k in ("a", "b", "c"):
        p, mn, tmp, s = reqs[k]
        eng.submit(p, mn, temperature=tmp, seed=s, rid=k)
    for _ in range(4):                     # a/b/c already decoding...
        eng.step()
    p, mn, tmp, s = reqs["late"]
    eng.submit(p, mn, temperature=tmp, seed=s, rid="late")  # ...joins
    res = eng.run()
    for k, ref in oracle.items():
        np.testing.assert_array_equal(res[k], ref, err_msg=k)
    assert eng.alloc.n_free == eng.alloc.n_usable


def test_zero_recompiles_across_request_churn(params):
    """After warmup, requests joining and leaving the batch add ZERO
    executables (`fn._cache_size`, the counter the analysis retrace
    rule reads) — occupancy is data, not shape: fixed slot count,
    geometric table-width buckets, fixed prefill chunk."""
    eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                        max_slots=4, prefill_chunk=16)
    # warmup: lengths walking every width bucket the churn uses
    for i, (t, mn) in enumerate([(5, 6), (23, 8), (40, 6)]):
        eng.submit(toks(20 + i, t=t), mn, rid=f"w{i}")
    eng.run()
    warm = eng.executable_counts()
    for i, (t, mn, tmp) in enumerate(
            [(9, 7, 0.0), (31, 5, 1.0), (14, 9, 0.0), (44, 6, 0.0),
             (3, 8, 1.0)]):
        eng.submit(toks(40 + i, t=t), mn, temperature=tmp, rid=f"c{i}")
        eng.step()                  # staggered joins/leaves
    eng.run()
    assert eng.executable_counts() == warm, (
        f"request churn recompiled: {warm} -> "
        f"{eng.executable_counts()}")


def test_chunked_prefill_never_stalls_decode(params):
    """A long prompt admitted mid-run prefills one chunk per engine
    step INTERLEAVED with decode ticks: an in-flight request's stream
    advances every step (tpot bounded at one chunk tick) instead of
    freezing for the whole prefill."""
    eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                        max_slots=4, prefill_chunk=16)
    eng.submit(toks(0, t=6), 40, rid="short")
    while (eng.poll("short")["status"] != "running"
           or len(eng.poll("short")["tokens"]) < 2):
        eng.step()
    eng.submit(toks(1, t=60), 4, rid="long")   # 4 chunks of prefill
    deltas = []
    while eng.poll("long")["status"] != "done":
        before = len(eng.poll("short")["tokens"])
        eng.step()
        deltas.append(len(eng.poll("short")["tokens"]) - before)
    assert min(deltas) >= 1, (
        f"decode stalled during chunked prefill: per-step token "
        f"deltas {deltas}")
    # and the long request still matches its solo oracle
    res = eng.run()
    np.testing.assert_array_equal(
        res["long"], solo(params, toks(1, t=60), 4, temperature=0.0))


def test_oom_evicts_requeues_and_balances(params):
    """Pool pressure: 3 requests whose steady-state footprint exceeds
    the pool force the evict-newest policy — the evicted request
    re-queues, re-prefills prompt + generated, and still reproduces
    its solo stream; the allocator balances at drain and never
    deadlocks."""
    reqs = {k: (toks(50 + i, t=24), 16) for i, k in enumerate("abc")}
    oracle = {k: solo(params, p, mn, temperature=0.0)
              for k, (p, mn) in reqs.items()}
    # 13 usable blocks * 8 = 104 positions < 3 * (24 + 16) = 120
    eng = ServingEngine(params, CFG, n_blocks=14, block_size=8,
                        max_slots=4, prefill_chunk=16)
    for k, (p, mn) in reqs.items():
        eng.submit(p, mn, rid=k)
    res = eng.run()
    for k in reqs:
        np.testing.assert_array_equal(res[k], oracle[k], err_msg=k)
    assert eng.counters["preempted"] >= 1
    assert eng.alloc.n_free == eng.alloc.n_usable
    assert eng.alloc.n_allocated == 0
    rec = {r["id"]: r for r in eng.request_records}
    assert sum(r["preempted"] for r in rec.values()) \
        == eng.counters["preempted"]


def test_submit_rejects_unservable_requests(params):
    eng = ServingEngine(params, CFG, n_blocks=8, block_size=8,
                        max_slots=2)
    with pytest.raises(ValueError, match="max_seq"):
        eng.submit(toks(0, t=100), 64)
    with pytest.raises(ValueError, match="blocks"):
        eng.submit(toks(0, t=60), 40)   # 13 blocks > 7 usable
    eng.submit(toks(0, t=8), 4, rid="ok")
    with pytest.raises(ValueError, match="duplicate"):
        eng.submit(toks(0, t=8), 4, rid="ok")


def test_int8_paged_matches_solo_int8_stream(params):
    """int8 pools quantize per (row, head, position) exactly like the
    contiguous int8 cache, so a greedy paged stream matches the solo
    `generate(kv_quant='int8')` stream."""
    prompt = toks(7, t=18)
    ref = solo(params, prompt, 10, temperature=0.0, kv_quant="int8")
    eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                        max_slots=4, prefill_chunk=16, kv_quant="int8")
    eng.submit(prompt, 10, rid="q")
    np.testing.assert_array_equal(eng.run()["q"], ref)


def test_gqa_rope_swiglu_config_parity(params):
    """The serving tick's per-row rope + GQA pools reproduce the solo
    stream on a modern block config (rope, rmsnorm, swiglu, grouped
    KV heads)."""
    cfg = T.TransformerConfig(vocab=64, d_model=32, n_heads=4,
                              n_kv_heads=2, n_layers=2, max_seq=96,
                              rope=True, norm="rmsnorm", ffn="swiglu")
    p2 = jax.device_put(T.init(cfg, seed=2))
    prompt = toks(9, t=19)
    for kwargs in ({"temperature": 0.0}, {"temperature": 1.0, "seed": 5}):
        ref = solo(p2, prompt, 8, cfg=cfg, **kwargs)
        eng = ServingEngine(p2, cfg, n_blocks=24, block_size=8,
                            max_slots=2, prefill_chunk=16)
        eng.submit(prompt, 8, temperature=kwargs.get("temperature", 0.0),
                   seed=kwargs.get("seed", 0), rid="q")
        np.testing.assert_array_equal(eng.run()["q"], ref,
                                      err_msg=str(kwargs))


# ------------------------------------------- telemetry: schema v6 + SLO


def test_request_events_validate_schema_v6(params, tmp_path):
    from shallowspeed_tpu.metrics import MetricsLogger
    from shallowspeed_tpu.telemetry import schema

    assert schema.SCHEMA_VERSION >= 6
    path = tmp_path / "serve.jsonl"
    eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                        max_slots=4, prefill_chunk=16,
                        metrics=MetricsLogger(path, kind="serve"),
                        log_every=4)
    eng.submit(toks(0, t=9), 8, rid="a")
    eng.submit(toks(1, t=14), 6, temperature=1.0, seed=2, rid="b")
    eng.run()
    assert schema.validate_file(path) == []
    recs = [json.loads(l) for l in path.read_text().splitlines()]
    reqs = [r for r in recs if r.get("event") == "request"]
    assert {r["id"] for r in reqs} == {"a", "b"}
    for r in reqs:
        assert r["ttft_ms"] >= 0 and r["tpot_ms"] >= 0
        assert r["tokens_in"] > 0 and r["tokens_out"] > 0
        assert "queue_depth" in r and "preempted" in r
    gen = [r for r in recs if r.get("event") == "generate"]
    assert gen and all("hbm_gbps" in g and "free_blocks" in g
                       for g in gen)
    # malformed request lines are rejected
    assert schema.validate_line({"event": "request", "id": "x"}) != []
    assert schema.validate_line(
        {"event": "request", "id": "x", "ttft_ms": 1.0, "tokens_in": 1,
         "tokens_out": 1, "queue_depth": "deep"}) != []


def test_goodput_reduces_request_percentiles(params, tmp_path):
    """The `--goodput` reducer reports p50/p95 ttft and tpot from the
    schema-v6 request events, and the formatted report prints them."""
    from shallowspeed_tpu.metrics import MetricsLogger
    from shallowspeed_tpu.telemetry.goodput import (format_report,
                                                    run_goodput)

    path = tmp_path / "serve.jsonl"
    eng = ServingEngine(params, CFG, n_blocks=32, block_size=8,
                        max_slots=4, prefill_chunk=16,
                        metrics=MetricsLogger(path, kind="serve"))
    for i in range(4):
        eng.submit(toks(i, t=7 + 5 * i), 6, rid=f"r{i}")
    eng.run()
    rep = run_goodput(path)
    req = rep["requests"]
    assert req["n_requests"] == 4
    assert req["ttft_ms_p50"] <= req["ttft_ms_p95"]
    assert req["tpot_ms_p50"] <= req["tpot_ms_p95"]
    assert req["tokens_out"] == 24
    assert "requests 4" in format_report(rep)


def test_request_summary_percentiles():
    from shallowspeed_tpu.telemetry.report import (percentile,
                                                   request_summary)

    assert request_summary([]) is None
    assert percentile([], 50) is None
    recs = [{"ttft_ms": float(i), "tpot_ms": float(10 * i),
             "tokens_in": 2, "tokens_out": 3, "preempted": i % 2}
            for i in range(1, 21)]
    s = request_summary(recs)
    assert s["n_requests"] == 20
    assert s["ttft_ms_p50"] == pytest.approx(10.0, abs=1.0)
    assert s["ttft_ms_p95"] == pytest.approx(19.0, abs=1.0)
    assert s["tpot_ms_p95"] == pytest.approx(190.0, abs=10.0)
    assert s["tokens_out"] == 60 and s["preempted"] == 10
    # single-token generations carry no tpot — summary degrades
    s1 = request_summary([{"ttft_ms": 5.0, "tokens_in": 1,
                           "tokens_out": 1}])
    assert s1["tpot_ms_p50"] is None and s1["ttft_ms_p50"] == 5.0


def test_paged_read_bytes_per_tick_model(params):
    """The live-blocks HBM model: params once + touched blocks' K/V
    (+ int8 scales) + token ids — the serving generalization of
    `decode_read_bytes_per_token`'s full-cache sweep."""
    from shallowspeed_tpu.analysis.walker import aval_bytes

    cast = jax.eval_shape(
        lambda p: T.cast_params(p, CFG.compute_dtype), params)
    p_bytes = int(sum(aval_bytes(l)
                      for l in jax.tree_util.tree_leaves(cast)))
    bs, touched, rows = 16, 5, 4
    got = paged_read_bytes_per_tick(params, CFG, touched, bs, rows)
    per_block = 2 * CFG.kv_heads * bs * CFG.head_dim * 4  # f32 cache
    assert got == p_bytes + CFG.n_layers * touched * per_block + rows * 4
    q = paged_read_bytes_per_tick(params, CFG, touched, bs, rows,
                                  kv_quant="int8")
    per_block_q = (2 * CFG.kv_heads * bs * CFG.head_dim
                   + 2 * CFG.kv_heads * bs * 4)
    assert q == p_bytes + CFG.n_layers * touched * per_block_q + rows * 4
    assert q < got                      # int8 sweeps fewer bytes
