"""Public API surface (`shallowspeed_tpu/__init__.py` lazy exports)."""

import shallowspeed_tpu as st


def test_every_export_resolves():
    for name in st.__all__:
        assert getattr(st, name) is not None, name


def test_function_vs_module_exports():
    from shallowspeed_tpu.models.generate import generate as gen_fn
    from shallowspeed_tpu.optim import Adam

    assert st.generate is gen_fn          # function, not the module
    assert st.Adam is Adam
    assert st.checkpoint.__name__ == "shallowspeed_tpu.checkpoint"


def test_unknown_attribute_raises():
    import pytest

    with pytest.raises(AttributeError, match="no attribute 'nope'"):
        st.nope
