"""Transformer LM + context-parallel engine tests.

Equivalence strategy as everywhere in this framework: the sharded run must
match the serial run (reference's own check,
`scripts/DDP_PyTorch_MNIST.py:159-167`) — here dp x sp tiles vs a
single-device full-attention run, through a full optimizer step.
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.optim import SGD, Adam
from shallowspeed_tpu.parallel.context import ContextParallelEngine

CFG = T.TransformerConfig(vocab=64, d_model=32, n_heads=2, n_layers=2,
                          max_seq=64)


def toy_batch(b=4, t=32, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, CFG.vocab, (b, t)).astype(np.int32)
    # next-token targets of a repeat-previous task: learnable quickly
    targets = np.roll(tokens, -1, axis=1).astype(np.int32)
    return tokens, targets


def make_mesh(dp, sp):
    devs = np.array(jax.devices()[: dp * sp]).reshape(dp, sp)
    return Mesh(devs, ("dp", "sp"))


def test_forward_shapes_and_loss_finite():
    params = T.init(CFG, seed=1)
    tokens, targets = toy_batch()
    logits = T.forward(params, tokens, CFG)
    assert logits.shape == (4, 32, CFG.vocab)
    loss = T.loss(params, tokens, targets, CFG)
    assert np.isfinite(float(loss))
    # untrained loss ~ log(vocab)
    assert abs(float(loss) - np.log(CFG.vocab)) < 1.0


@pytest.mark.parametrize("dp,sp", [(1, 1), (2, 1), (1, 4), (2, 4)])
def test_context_parallel_step_matches_serial(dp, sp):
    """One full train step on a (dp, sp) mesh equals the single-device step."""
    tokens, targets = toy_batch()

    serial = ContextParallelEngine(CFG, SGD(0.1), make_mesh(1, 1), seed=3)
    l0 = serial.train_batch(tokens, targets)

    eng = ContextParallelEngine(CFG, SGD(0.1), make_mesh(dp, sp), seed=3)
    l1 = eng.train_batch(tokens, targets)

    assert abs(l0 - l1) < 1e-5
    flat_a = jax.tree_util.tree_leaves(serial.params)
    flat_b = jax.tree_util.tree_leaves(eng.params)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-5)


def test_context_parallel_training_learns():
    """Loss decreases substantially on the toy next-token task under dp=2, sp=4."""
    eng = ContextParallelEngine(CFG, Adam(1e-2), make_mesh(2, 4), seed=0)
    tokens, targets = toy_batch(seed=5)
    first = eng.eval_loss(tokens, targets)
    for _ in range(30):
        eng.train_batch(tokens, targets)
    last = eng.eval_loss(tokens, targets)
    assert last < first * 0.5, (first, last)


def test_flash_engine_matches_ring_engine():
    """attn='flash' (Pallas kernel) trains identically to attn='ring'."""
    tokens, targets = toy_batch()
    ring = ContextParallelEngine(CFG, SGD(0.1), make_mesh(2, 1), seed=3)
    flash = ContextParallelEngine(CFG, SGD(0.1), make_mesh(2, 1), seed=3,
                                  attn="flash")
    for b in range(2):
        tok, tgt = toy_batch(seed=b)
        lr = ring.train_batch(tok, tgt)
        lf = flash.train_batch(tok, tgt)
        assert abs(lr - lf) < 1e-5, (lr, lf)


def test_ulysses_engine_matches_ring_engine():
    """attn='ulysses' (all-to-all SP) trains identically to attn='ring'
    on a sequence-sharded (dp=2, sp=2) mesh."""
    ring = ContextParallelEngine(CFG, SGD(0.1), make_mesh(2, 2), seed=3)
    uly = ContextParallelEngine(CFG, SGD(0.1), make_mesh(2, 2), seed=3,
                                attn="ulysses")
    for b in range(2):
        tok, tgt = toy_batch(seed=b)
        lr = ring.train_batch(tok, tgt)
        lu = uly.train_batch(tok, tgt)
        assert abs(lr - lu) < 1e-5, (lr, lu)
    flat_r = jax.tree_util.tree_leaves(ring.params)
    flat_u = jax.tree_util.tree_leaves(uly.params)
    for a, b in zip(flat_r, flat_u):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-5)


def test_ring_flash_engine_matches_ring_engine():
    """attn='ring-flash' (the fused kernel as the ring's local compute,
    round 2) trains identically to attn='ring' on a sequence-sharded
    (dp=2, sp=2) mesh — including an sp that does NOT divide n_heads,
    where ulysses cannot go."""
    ring = ContextParallelEngine(CFG, SGD(0.1), make_mesh(2, 2), seed=3)
    rf = ContextParallelEngine(CFG, SGD(0.1), make_mesh(2, 2), seed=3,
                               attn="ring-flash")
    for b in range(2):
        tok, tgt = toy_batch(seed=b)
        lr = ring.train_batch(tok, tgt)
        lf = rf.train_batch(tok, tgt)
        assert abs(lr - lf) < 1e-5, (lr, lf)
    for a, b in zip(jax.tree_util.tree_leaves(ring.params),
                    jax.tree_util.tree_leaves(rf.params)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-5)


def test_logits_match_full_attention_reference():
    """Sharded inference logits == direct full-attention forward."""
    eng = ContextParallelEngine(CFG, SGD(0.1), make_mesh(2, 4), seed=9)
    tokens, _ = toy_batch(seed=2)
    got = np.asarray(eng.logits(tokens))
    params_host = jax.device_get(eng.params)
    want = np.asarray(T.forward(params_host, tokens, CFG))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_checkpoint_roundtrip_context_engine(tmp_path):
    from shallowspeed_tpu import checkpoint

    eng = ContextParallelEngine(CFG, Adam(1e-3), make_mesh(2, 4), seed=4)
    tokens, targets = toy_batch(seed=1)
    eng.train_batch(tokens, targets)
    checkpoint.save(tmp_path, eng, epoch=0)

    eng2 = ContextParallelEngine(CFG, Adam(1e-3), make_mesh(1, 2), seed=99)
    assert checkpoint.restore(eng2, checkpoint.latest(tmp_path)) == 1
    # continued training matches bit-for-bit modulo topology reassociation
    la = eng.train_batch(tokens, targets)
    lb = eng2.train_batch(tokens, targets)
    assert abs(la - lb) < 1e-5
