"""Mixed-precision (bf16 compute, f32 master weights) numerics.

TPU-first capability beyond the reference (whose NumPy compute is f32-only,
`/root/reference/shallowspeed/functional.py`): `TransformerConfig.
compute_dtype=bfloat16` casts params/activations at the forward boundary
while layernorm stats, attention softmax, the MoE router, and the loss
log-softmax stay float32, and gradients/optimizer state remain float32.
"""

from dataclasses import replace
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.ops.attention import attention
from shallowspeed_tpu.optim import Adam
from shallowspeed_tpu.parallel.context import ContextParallelEngine

CFG32 = T.TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                            max_seq=32)
CFG16 = replace(CFG32, compute_dtype=jnp.bfloat16)


def batch(seed=0, b=4, t=32, vocab=64):
    rng = np.random.default_rng(seed)
    tok = rng.integers(0, vocab, (b, t)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1).astype(np.int32)


def test_bf16_forward_close_to_f32():
    params = T.init(CFG32, seed=1)
    tok, _ = batch()
    lg32 = np.asarray(T.forward(params, tok, CFG32))
    lg16 = np.asarray(T.forward(params, tok, CFG16)).astype(np.float32)
    assert lg16.dtype == np.float32  # cast back for comparison
    # bf16 has ~3 decimal digits; logits are O(1)
    np.testing.assert_allclose(lg16, lg32, atol=0.15, rtol=0.1)


def test_bf16_logits_dtype():
    params = T.init(CFG16, seed=1)
    tok, _ = batch()
    assert T.forward(params, tok, CFG16).dtype == jnp.bfloat16


def test_bf16_grads_are_f32_master():
    """Gradients must arrive in the master-weight dtype (f32): the transpose
    of the boundary cast converts bf16 activations' grads back."""
    params = T.init(CFG16, seed=1)
    tok, tgt = batch()
    grads = jax.grad(T.loss)(params, tok, tgt, CFG16)
    for leaf in jax.tree_util.tree_leaves(grads):
        assert leaf.dtype == jnp.float32
    gn = sum(float(jnp.abs(l).sum()) for l in jax.tree_util.tree_leaves(grads))
    assert np.isfinite(gn) and gn > 0


def test_bf16_loss_close_to_f32():
    params = T.init(CFG32, seed=2)
    tok, tgt = batch(1)
    l32 = float(T.loss(params, tok, tgt, CFG32))
    l16 = float(T.loss(params, tok, tgt, CFG16))
    assert l16 == pytest.approx(l32, rel=0.02)


def test_layernorm_f32_stats_under_bf16():
    """Large-offset activations: bf16 mean/var would catastrophically cancel;
    f32 stats keep the normalized output accurate."""
    rng = np.random.default_rng(0)
    x = (rng.normal(size=(2, 8, 32)) + 300.0).astype(jnp.bfloat16)
    p = {"g": jnp.ones((32,), jnp.bfloat16), "b": jnp.zeros((32,), jnp.bfloat16)}
    y = np.asarray(T._layernorm(p, x)).astype(np.float32)
    ref = np.asarray(T._layernorm(
        {"g": jnp.ones((32,)), "b": jnp.zeros((32,))},
        jnp.asarray(x, jnp.float32)))
    assert y.dtype == np.float32
    np.testing.assert_allclose(y, ref, atol=0.1)
    assert abs(y.mean()) < 0.05  # normalized: mean ~ 0 despite the offset


def test_attention_bf16_close_to_f32():
    rng = np.random.default_rng(3)
    q, k, v = (rng.normal(size=(2, 16, 4, 8)).astype(np.float32)
               for _ in range(3))
    o32 = np.asarray(attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)))
    o16 = np.asarray(attention(jnp.asarray(q, jnp.bfloat16),
                               jnp.asarray(k, jnp.bfloat16),
                               jnp.asarray(v, jnp.bfloat16))).astype(np.float32)
    np.testing.assert_allclose(o16, o32, atol=0.03, rtol=0.05)


@pytest.mark.parametrize("attn", ["ring", "ulysses", "ulysses-flash"])
def test_bf16_engine_trains(attn):
    """End-to-end: (dp=2, sp=2) mesh, bf16 compute — loss decreases and the
    master params/opt state stay f32."""
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:4]).reshape(2, 2)
    eng = ContextParallelEngine(CFG16, Adam(5e-3), Mesh(devs, ("dp", "sp")),
                                seed=0, attn=attn)
    tok, tgt = batch(7, b=4, t=32)
    losses = [eng.train_batch(tok, tgt) for _ in range(25)]
    assert losses[-1] < losses[0] - 0.2, losses[::6]
    for leaf in jax.tree_util.tree_leaves(eng.params):
        assert leaf.dtype == jnp.float32


def test_bf16_moe_router_stays_f32():
    """Gate logits must accumulate in f32 under bf16 compute (bf16 logits
    can flip top-k routing); verified by routing equality with f32."""
    from shallowspeed_tpu.ops.moe import moe_ffn

    rng = np.random.default_rng(5)
    d, e = 32, 4
    p32 = {"gate": rng.normal(0, 1, (d, e)).astype(np.float32),
           "wi": rng.normal(0, 0.1, (e, d, 4 * d)).astype(np.float32),
           "bi": np.zeros((e, 4 * d), np.float32),
           "wo": rng.normal(0, 0.1, (e, 4 * d, d)).astype(np.float32),
           "bo": np.zeros((e, d), np.float32)}
    x32 = jnp.asarray(rng.normal(0, 1, (2, 16, d)), jnp.float32)
    p16 = jax.tree_util.tree_map(lambda a: jnp.asarray(a, jnp.bfloat16), p32)
    y32, aux32, _z32, _s32 = moe_ffn(p32, x32, 2, 2.0)
    y16, aux16, _z16, _s16 = moe_ffn(p16, x32.astype(jnp.bfloat16),
                                     2, 2.0)
    assert float(aux16) == pytest.approx(float(aux32), rel=0.05)
    np.testing.assert_allclose(np.asarray(y16, np.float32), np.asarray(y32),
                               atol=0.06, rtol=0.1)


def test_bf16_moe_engine_trains():
    from jax.sharding import Mesh

    from shallowspeed_tpu.parallel.expert import ExpertParallelEngine

    cfg = replace(CFG16, n_experts=4, moe_top_k=2)
    devs = np.array(jax.devices()[:4]).reshape(1, 4)
    eng = ExpertParallelEngine(cfg, Adam(5e-3), Mesh(devs, ("dp", "ep")),
                               seed=0)
    tok, tgt = batch(9, b=4, t=32)
    losses = [eng.train_batch(tok, tgt) for _ in range(25)]
    assert losses[-1] < losses[0] - 0.15, losses[::6]


def test_remat_grads_match_exactly():
    """jax.checkpoint recomputes the SAME ops, so gradients must match the
    stored-activation backward to float tolerance."""
    cfg_r = replace(CFG32, remat=True)
    params = T.init(CFG32, seed=4)
    tok, tgt = batch(2)
    g0 = jax.grad(T.loss)(params, tok, tgt, CFG32)
    g1 = jax.grad(T.loss)(params, tok, tgt, cfg_r)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-8)


def test_remat_trains_with_engines():
    from jax.sharding import Mesh

    cfg = replace(CFG16, remat=True)
    devs = np.array(jax.devices()[:4]).reshape(2, 2)
    eng = ContextParallelEngine(cfg, Adam(5e-3), Mesh(devs, ("dp", "sp")),
                                seed=0)
    tok, tgt = batch(7)
    losses = [eng.train_batch(tok, tgt) for _ in range(20)]
    assert losses[-1] < losses[0] - 0.15, losses[::5]
