"""Rotary position embeddings (`transformer.rope_rotate`, cfg.rope).

The defining property: attention scores depend only on RELATIVE position
— rotating q at i and k at j gives the same dot product as i+s and j+s.
That is also exactly why RoPE composes with sequence sharding: each
device rotates its local block by its global positions, and the
ring/all-to-all moves already-rotated K.
"""

from dataclasses import replace
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.models.generate import decode_step, generate, \
    init_kv_cache, prefill
from shallowspeed_tpu.optim import Adam, SGD
from shallowspeed_tpu.parallel.context import ContextParallelEngine
from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine

CFG = T.TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                          max_seq=64, rope=True)


def toks(seed=0, b=4, t=32, vocab=64):
    rng = np.random.default_rng(seed)
    tok = rng.integers(0, vocab, (b, t)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1).astype(np.int32)


# ------------------------------------------------------------ properties


def test_rope_relative_phase_invariance():
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(1, 8, 2, 16)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 8, 2, 16)), jnp.float32)
    pos = jnp.arange(8)

    def scores(shift):
        qr = T.rope_rotate(q, pos + shift)
        kr = T.rope_rotate(k, pos + shift)
        return np.asarray(jnp.einsum("bqhd,bkhd->bhqk", qr, kr))

    np.testing.assert_allclose(scores(0), scores(17), rtol=1e-4, atol=1e-4)


def test_rope_preserves_norm():
    """Rotation is orthogonal: vector norms are unchanged."""
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(2, 8, 2, 16)), jnp.float32)
    r = T.rope_rotate(x, jnp.arange(8) + 100)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(r), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1), rtol=1e-5)


def test_rope_scalar_position_matches_vector():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(2, 1, 2, 16)), jnp.float32)
    a = np.asarray(T.rope_rotate(x, 5))
    b = np.asarray(T.rope_rotate(x, jnp.arange(5, 6)))
    np.testing.assert_allclose(a, b, atol=0)


def test_rope_skips_learned_pos_emb():
    """With rope on, pos_emb must not influence the logits."""
    params = T.init(CFG, seed=3)
    tok, _ = toks(0)
    base = np.asarray(T.forward(params, tok, CFG))
    params2 = dict(params, pos_emb=params["pos_emb"] + 100.0)
    np.testing.assert_allclose(
        np.asarray(T.forward(params2, tok, CFG)), base, atol=0)


# ------------------------------------------- sharded-engine equivalence


def serial_engine(opt):
    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1), ("dp", "sp"))
    return ContextParallelEngine(CFG, opt, mesh, seed=0)


@pytest.mark.parametrize("attn", ["ring", "ulysses", "ulysses-flash"])
def test_rope_under_sequence_sharding(attn):
    """sp=4 with rope must match the serial run: each device rotates by
    its GLOBAL positions (pos_offset), so the moving K is pre-rotated."""
    ser = serial_engine(SGD(0.1))
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(1, 4), ("dp", "sp"))
    eng = ContextParallelEngine(CFG, SGD(0.1), mesh, seed=0, attn=attn)
    for step in range(3):
        tok, tgt = toks(step)
        assert eng.train_batch(tok, tgt) == pytest.approx(
            ser.train_batch(tok, tgt), rel=3e-4), (step, attn)


def test_rope_under_pipeline():
    ser = serial_engine(SGD(0.1))
    cfg = CFG
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "pp"))
    eng = PipelineLMEngine(cfg, SGD(0.1), mesh, n_mubatches=2, seed=0)
    for step in range(3):
        tok, tgt = toks(step, b=8)
        assert eng.train_batch(tok, tgt) == pytest.approx(
            ser.train_batch(tok, tgt), rel=3e-4), step


# ------------------------------------------------------------- decoding


def test_rope_cached_decode_matches_forward():
    params = T.init(CFG, seed=4)
    tokens, _ = toks(1, b=2, t=10)
    ref = np.asarray(T.forward(params, tokens, CFG))
    cache = init_kv_cache(CFG, 2)
    logits, cache = prefill(params, tokens[:, :1], CFG, cache)
    np.testing.assert_allclose(np.asarray(logits), ref[:, 0],
                               rtol=1e-4, atol=1e-5)
    for pos in range(1, tokens.shape[1]):
        logits, cache = decode_step(params, jnp.asarray(tokens[:, pos]),
                                    pos, cache, CFG)
        np.testing.assert_allclose(np.asarray(logits), ref[:, pos],
                                   rtol=1e-4, atol=1e-5, err_msg=str(pos))


def test_rope_generation_runs():
    params = T.init(CFG, seed=5)
    prompt, _ = toks(2, b=2, t=4)
    out = np.asarray(generate(params, prompt, CFG, 8, temperature=0.0))
    assert out.shape == (2, 8)
    assert (out >= 0).all() and (out < CFG.vocab).all()


def test_rope_trains():
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2, 1), ("dp", "sp"))
    cfg = replace(CFG, compute_dtype=jnp.bfloat16)
    eng = ContextParallelEngine(cfg, Adam(5e-3), mesh, seed=0)
    tok, tgt = toks(7)
    losses = [eng.train_batch(tok, tgt) for _ in range(20)]
    assert losses[-1] < losses[0] - 0.15, losses[::5]
