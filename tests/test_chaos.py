"""Chaos-hardened recovery (`shallowspeed_tpu/chaos.py` + the
checkpoint-integrity and failure-class-supervision layers it forces).

Coverage map:
- FaultPlan: DSL/JSON parsing, determinism, env propagation, once-only
  firing across "restarts" (state-dir markers).
- Checkpoint integrity: SHA-256 manifest write/verify, typed
  CheckpointError (never a raw BadZipFile), quarantine + fall-back to
  the newest verified checkpoint, retention that never deletes the
  last verified one, legacy (pre-manifest) checkpoints still restore.
- Save-atomicity torture: a child process SIGKILLed at seeded offsets
  inside save (sync AND async) — `latest()` must only ever return a
  manifest-verified checkpoint.
- Injected faults: ENOSPC mid-save leaves `latest()` untouched;
  post-hoc corruption is caught and quarantined; the NaN poison hits
  one seeded leaf; the stall stamps data-loader seconds.
- Goodput reducer: per-failure-class MTTR, availability, fault tally.
- End-to-end: a fast deterministic canary (supervised run under
  kill + corrupt + stall matches the fault-free oracle's final loss
  exactly) in tier-1, and the full multi-fault acceptance run
  (kill-in-save, corruption, NaN storm, data stall, heartbeat-freeze
  hang) marked `slow`.
"""

import json
import os
import subprocess
import sys
import textwrap
import time
from pathlib import Path

import numpy as np
import pytest

from shallowspeed_tpu import chaos, checkpoint
from shallowspeed_tpu.chaos import Fault, FaultPlan
from shallowspeed_tpu.engine import FusedDPEngine
from shallowspeed_tpu.models.mlp import MLPStage
from shallowspeed_tpu.optim import SGD
from shallowspeed_tpu.parallel.mesh import make_mesh

ROOT = Path(__file__).resolve().parents[1]


@pytest.fixture(autouse=True)
def _no_ambient_plan(monkeypatch):
    """Chaos must never leak between tests (or in from the env)."""
    for var in (chaos.ENV_SPEC, chaos.ENV_STATE, chaos.ENV_SEED):
        monkeypatch.delenv(var, raising=False)
    chaos.configure(None)
    yield
    chaos.configure(None)


# ------------------------------------------------------------ fault plan


def test_plan_parse_roundtrip():
    p = FaultPlan.parse("kill@9,stall@5:0.5,corrupt@2:truncate,nan@3")
    assert [f.kind for f in p.faults] == ["kill", "stall", "corrupt",
                                          "nan"]
    assert p.faults[1].arg == 0.5 and p.faults[2].arg == "truncate"
    # the spec round-trips (what the supervisor exports to children)
    assert FaultPlan.parse(p.to_spec()).to_spec() == p.to_spec()


def test_plan_parse_json_and_file(tmp_path):
    obj = {"seed": 7, "faults": [{"kind": "kill", "at": 2},
                                 {"kind": "stall", "at": 1,
                                  "arg": 0.25}]}
    p = FaultPlan.parse(json.dumps(obj))
    assert p.seed == 7 and p.faults[0].id == "kill@2"
    f = tmp_path / "plan.json"
    f.write_text(json.dumps(obj))
    assert FaultPlan.parse(str(f)).to_spec() == p.to_spec()


def test_plan_rejects_garbage():
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultPlan.parse("explode@3")
    with pytest.raises(ValueError, match="not an integer"):
        FaultPlan.parse("kill@soon")
    with pytest.raises(ValueError, match="1-based save"):
        FaultPlan.parse("enospc@0")
    with pytest.raises(ValueError, match="corrupt mode"):
        Fault("corrupt", 1, "scramble")
    with pytest.raises(ValueError, match="empty"):
        FaultPlan.parse("  ,  ")


def test_env_export_and_setup_roundtrip(tmp_path, monkeypatch):
    p = FaultPlan.parse("kill@4", seed=3, state_dir=tmp_path / "cs")
    for k, v in p.export_env({}).items():
        if k.startswith("SHALLOWSPEED_CHAOS"):
            monkeypatch.setenv(k, v)
    got = chaos.setup()  # no flag: adopt the supervisor's env
    assert got is not None and got.to_spec() == "kill@4"
    assert got.seed == 3 and got.state_dir == tmp_path / "cs"
    # the --chaos flag wins over the env
    flag = chaos.setup("stall@1:0.1", state_dir=tmp_path / "cs2")
    assert flag.to_spec() == "stall@1:0.1"


def test_faults_fire_once_across_restarts(tmp_path):
    """The once-only contract every replay-equals-oracle claim rests
    on: a fired fault's marker survives into a fresh plan object (a
    restarted child) and suppresses re-firing."""
    state = tmp_path / "cs"
    p1 = FaultPlan.parse("stall@2:0.05", state_dir=state)
    t0 = time.monotonic()
    p1.on_data_load(2)
    assert time.monotonic() - t0 >= 0.05  # slept
    p2 = FaultPlan.parse("stall@2:0.05", state_dir=state)  # "restart"
    assert p2.fired(p2.faults[0])
    t0 = time.monotonic()
    p2.on_data_load(2)
    assert time.monotonic() - t0 < 0.04  # marker suppressed the sleep


def test_fault_stamp_validates_as_schema_v5(tmp_path):
    from shallowspeed_tpu.telemetry.schema import (SCHEMA_VERSION,
                                                   validate_file)

    assert SCHEMA_VERSION >= 5
    log = tmp_path / "m.jsonl"
    p = FaultPlan.parse("stall@1:0.01,freeze@2", log_file=log)
    p.on_data_load(1)
    p.on_step(2)
    assert p.heartbeat_frozen()
    assert validate_file(log) == []
    kinds = [json.loads(l)["kind"] for l in log.read_text().splitlines()]
    assert kinds == ["stall", "freeze"]


def test_nan_poison_hits_one_seeded_leaf():
    class Eng:
        params = {"a": np.ones(3, np.float32),
                  "b": np.ones(4, np.float32),
                  "c": np.ones(5, np.float32)}

    def poisoned(seed):
        eng = Eng()
        eng.params = {k: np.array(v) for k, v in Eng.params.items()}
        FaultPlan.parse("nan@1", seed=seed).on_step(1, eng)
        return sorted(k for k, v in eng.params.items()
                      if not np.all(np.isfinite(v)))

    first = poisoned(0)
    assert len(first) == 1           # exactly one leaf poisoned
    assert poisoned(0) == first      # seeded: same leaf every time
    seeds = {tuple(poisoned(s)) for s in range(8)}
    assert len(seeds) > 1            # the seed really picks the leaf


# ------------------------------------------------- checkpoint integrity


SIZES = [784, 16, 15, 10]


def small_engine():
    return FusedDPEngine(MLPStage(SIZES, 0, 1, batch_size=8), SGD(0.5),
                         make_mesh(1, 1))


def test_save_writes_manifest_and_verify_passes(tmp_path):
    eng = small_engine()
    ck = checkpoint.save(tmp_path, eng, epoch=0)
    man = json.loads((ck / "manifest.json").read_text())
    assert set(man["files"]) == {"params.npz", "opt.npz"}
    assert all(len(rec["sha256"]) == 64 for rec in man["files"].values())
    checkpoint.verify(ck)  # does not raise
    assert checkpoint.is_verified(ck)


@pytest.mark.parametrize("damage", ["bitflip", "truncate", "delete"])
def test_verify_catches_each_corruption_mode(tmp_path, damage):
    eng = small_engine()
    ck = checkpoint.save(tmp_path, eng, epoch=0)
    target = ck / "params.npz"
    if damage == "bitflip":
        raw = bytearray(target.read_bytes())
        raw[len(raw) // 2] ^= 0x10
        target.write_bytes(bytes(raw))
    elif damage == "truncate":
        target.write_bytes(target.read_bytes()[:100])
    else:
        target.unlink()
    with pytest.raises(checkpoint.CheckpointError) as ei:
        checkpoint.verify(ck)
    assert ei.value.path == target


def test_restore_raises_typed_error_never_bad_zipfile(tmp_path):
    """A truncated npz must surface as CheckpointError carrying the
    path — not zipfile.BadZipFile leaking out of np.load."""
    eng = small_engine()
    ck = checkpoint.save(tmp_path, eng, epoch=0)
    (ck / "manifest.json").unlink()  # legacy dir: no manifest to catch it
    (ck / "params.npz").write_bytes(b"PK\x03\x04 not a real zip")
    with pytest.raises(checkpoint.CheckpointError) as ei:
        checkpoint.restore(small_engine(), ck)
    assert ei.value.path is not None
    assert "params.npz" in str(ei.value.path)


def test_latest_quarantines_and_falls_back(tmp_path):
    eng = small_engine()
    checkpoint.save(tmp_path, eng, epoch=1)
    ck2 = checkpoint.save(tmp_path, eng, epoch=2)
    raw = bytearray((ck2 / "opt.npz").read_bytes())
    raw[-10] ^= 1
    (ck2 / "opt.npz").write_bytes(bytes(raw))
    with pytest.warns(UserWarning, match="quarantined"):
        got = checkpoint.latest(tmp_path)
    assert got.name == "ckpt_1"                  # fell back
    assert (tmp_path / "ckpt_2.corrupt").exists()  # quarantined
    assert not (tmp_path / "ckpt_2").exists()


def test_restore_latest_quarantine_loop(tmp_path):
    """restore_latest: corrupt newest + intact older -> the older one
    is installed and the corrupt one quarantined; all corrupt -> (0,
    None, [...]) so --auto-resume can fall back to a fresh start."""
    eng = small_engine()
    checkpoint.save(tmp_path, eng, epoch=0)
    ck1 = checkpoint.save(tmp_path, eng, epoch=1)
    (ck1 / "params.npz").write_bytes(b"garbage")
    with pytest.warns(UserWarning, match="quarantined"):
        nxt, path, quarantined = checkpoint.restore_latest(
            small_engine(), tmp_path)
    assert nxt == 1 and path.name == "ckpt_0"
    assert len(quarantined) == 1

    ck0 = tmp_path / "ckpt_0"
    (ck0 / "opt.npz").write_bytes(b"also garbage")
    with pytest.warns(UserWarning, match="quarantined"):
        nxt, path, quarantined = checkpoint.restore_latest(
            small_engine(), tmp_path)
    assert (nxt, path) == (0, None) and len(quarantined) == 1


def test_legacy_checkpoint_without_manifest_still_restores(tmp_path):
    eng = small_engine()
    ck = checkpoint.save(tmp_path, eng, epoch=3)
    (ck / "manifest.json").unlink()  # a pre-round-10 checkpoint
    assert checkpoint.latest(tmp_path) == ck
    assert checkpoint.restore(small_engine(), ck) == 4


def test_prune_never_deletes_last_verified(tmp_path):
    """Retention vs corruption: keep=2 would normally drop ckpt_1, but
    when both newer checkpoints are corrupt it is the only restorable
    state and must survive the rotation."""
    eng = small_engine()
    for e in (1, 2, 3):
        checkpoint.save(tmp_path, eng, epoch=e)
    for e in (2, 3):
        p = tmp_path / f"ckpt_{e}" / "params.npz"
        raw = bytearray(p.read_bytes())
        raw[50] ^= 1
        p.write_bytes(bytes(raw))
    checkpoint.prune(tmp_path, keep=2)
    names = {p.name for p in tmp_path.iterdir()}
    assert {"ckpt_1", "ckpt_2", "ckpt_3"} <= names  # ckpt_1 survived
    with pytest.warns(UserWarning, match="quarantined"):
        assert checkpoint.latest(tmp_path).name == "ckpt_1"


# ----------------------------------------------------- injected faults


def test_enospc_fault_leaves_latest_untouched(tmp_path):
    eng = small_engine()
    checkpoint.save(tmp_path / "ck", eng, epoch=0)
    chaos.configure(FaultPlan.parse("enospc@2"))
    checkpoint.save(tmp_path / "ck", eng, epoch=1)  # save #1: clean
    with pytest.raises(OSError, match="ENOSPC|space"):
        checkpoint.save(tmp_path / "ck", eng, epoch=2)  # save #2 dies
    assert checkpoint.latest(tmp_path / "ck").name == "ckpt_1"
    checkpoint.save(tmp_path / "ck", eng, epoch=3)  # fired once only
    assert checkpoint.latest(tmp_path / "ck").name == "ckpt_3"


def test_corrupt_fault_is_caught_at_restore(tmp_path):
    chaos.configure(FaultPlan.parse("corrupt@2"))
    eng = small_engine()
    checkpoint.save(tmp_path, eng, epoch=0)
    checkpoint.save(tmp_path, eng, epoch=1)  # save #2: corrupted post-hoc
    with pytest.warns(UserWarning, match="quarantined"):
        got = checkpoint.latest(tmp_path)
    assert got.name == "ckpt_0"
    assert (tmp_path / "ckpt_1.corrupt").exists()


# ------------------------------------------- save-atomicity torture test


TORTURE_CHILD = textwrap.dedent(f"""
    import sys
    sys.path.insert(0, {str(ROOT)!r})
    import numpy as np
    from shallowspeed_tpu import chaos, checkpoint

    ckpt_dir, state_dir, seed, use_async = sys.argv[1:5]

    class Eng:  # minimal engine surface the save path needs
        opt_state = {{"m": np.arange(64, dtype=np.float32)}}
        def get_canonical_params(self):
            return [{{"W": np.full((64, 64), 0.5, np.float32),
                      "b": np.zeros(64, np.float32)}}]

    chaos.configure(chaos.FaultPlan.parse(
        "kill_in_save@2", seed=int(seed), state_dir=state_dir))
    eng = Eng()
    if use_async == "1":
        saver = checkpoint.AsyncSaver()
        for epoch in range(4):   # save #2 dies on the WRITER thread
            saver.save(ckpt_dir, eng, epoch)
        saver.close()
    else:
        for epoch in range(4):   # save #2 dies on the main thread
            checkpoint.save(ckpt_dir, eng, epoch)
""")


@pytest.mark.parametrize("use_async", ["0", "1"])
def test_torture_sigkill_inside_save_window(tmp_path, use_async):
    """The save-atomicity acceptance: children SIGKILL themselves at
    SEEDED offsets inside the save window (between npz writes, before
    the rename, after it — sync and async paths both); whatever state
    that leaves on disk, `latest()` must only ever return a
    manifest-verified checkpoint, and a later save must recover."""
    child = tmp_path / "child.py"
    child.write_text(TORTURE_CHILD)
    for seed in range(4):  # sweep the seeded kill offsets
        ck = tmp_path / f"ck_{use_async}_{seed}"
        r = subprocess.run(
            [sys.executable, str(child), str(ck),
             str(tmp_path / f"cs_{use_async}_{seed}"), str(seed),
             use_async],
            capture_output=True, text=True, timeout=120)
        assert r.returncode != 0, (seed, r.stdout, r.stderr)  # was killed
        got = checkpoint.latest(ck)
        if got is not None:
            checkpoint.verify(got)  # never an unverified survivor
        # epoch 0's save completed before the fault armed on save #2
        assert got is not None and got.name in ("ckpt_0", "ckpt_1"), got
        # a respawned "child" (fresh process state, same marker dir)
        # saves cleanly over the wreckage
        checkpoint.save(ck, _torture_engine(), epoch=9)
        assert checkpoint.latest(ck).name == "ckpt_9"


def _torture_engine():
    class Eng:
        opt_state = {"m": np.arange(64, dtype=np.float32)}

        def get_canonical_params(self):
            return [{"W": np.full((64, 64), 0.5, np.float32),
                     "b": np.zeros(64, np.float32)}]

    return Eng()


# ---------------------------------------------------- goodput MTTR/fault


def test_goodput_reports_mttr_availability_and_faults(tmp_path):
    from shallowspeed_tpu.telemetry.goodput import (format_report,
                                                    run_goodput)

    log = tmp_path / "m.jsonl"
    recs = [{"event": "run_start", "start_step": 0, "wall": 100.0},
            {"event": "step", "step": 0, "loss": 1.0,
             "tokens_per_sec": 1.0, "wall": 101.0, "t": 1.0},
            {"event": "fault", "kind": "kill", "fault_id": "kill@1",
             "wall": 101.5},
            {"event": "ledger", "kind": "restart_downtime",
             "seconds": 2.0, "fail_class": "crash", "wall": 103.4},
            {"event": "run_start", "start_step": 0, "wall": 103.5},
            {"event": "step", "step": 0, "loss": 1.0,
             "tokens_per_sec": 1.0, "wall": 104.5, "t": 1.0},
            {"event": "step", "step": 4, "loss": 1.0,
             "tokens_per_sec": 1.0, "wall": 105.5, "t": 2.0},
            {"event": "ledger", "kind": "restart_downtime",
             "seconds": 1.0, "fail_class": "hang", "wall": 120.0},
            {"event": "ledger", "kind": "poison_step_abort", "step": 4,
             "fail_class": "crash", "wall": 121.0}]
    log.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    rep = run_goodput(log)
    assert rep["mttr"]["crash"]["count"] == 1
    assert rep["mttr"]["crash"]["mttr_s"] == pytest.approx(2.0)
    assert rep["mttr"]["crash"]["poison_step_abort"] == 1
    assert rep["mttr"]["hang"]["mttr_s"] == pytest.approx(1.0)
    assert rep["faults"] == {"kill": 1}
    assert rep["availability"] is not None and rep["availability"] < 1.0
    text = format_report(rep)
    assert "mttr[crash" in text and "injected faults" in text
    assert "availability" in text

    from shallowspeed_tpu.telemetry.schema import validate_file

    assert validate_file(log) == []


# -------------------------------------------------- e2e chaos canary


LM_BASE = ["--platform", "cpu", "--seq-len", "32", "--d-model", "32",
           "--n-layers", "1", "--batch-size", "4", "--steps", "14",
           "--log-every", "2", "--prefetch", "0", "--save-every", "4"]


def _final_loss(log_path, step):
    recs = [json.loads(l) for l in Path(log_path).read_text().splitlines()]
    steps = [r for r in recs if r.get("event") == "step"
             and r["step"] == step]
    assert steps, f"no step-{step} line in {log_path}"
    return steps[-1]["loss"]


def test_chaos_canary_supervised_run_matches_oracle(tmp_path):
    """The fast deterministic chaos acceptance (tier-1): a supervised
    train_lm run under kill@9 + corrupt@2 + stall@5 must (a) finish all
    steps with the EXACT final loss of a fault-free oracle — replay
    from the last verified checkpoint is trajectory-preserving because
    data/dropout are step-seeded — (b) quarantine the corrupted
    checkpoint rather than restore it, and (c) account the wall clock
    with a per-class MTTR in the goodput report."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    oracle_log = tmp_path / "oracle.jsonl"
    r = subprocess.run(
        [sys.executable, "train_lm.py", *LM_BASE,
         "--save-dir", str(tmp_path / "oracle_ck"),
         "--log-file", str(oracle_log)],
        capture_output=True, text=True, cwd=ROOT, env=env, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr

    log = tmp_path / "chaos.jsonl"
    r = subprocess.run(
        [sys.executable, "-m", "shallowspeed_tpu.elastic",
         "--max-restarts", "4", "--backoff", "0.3",
         "--term-grace", "3",
         "--chaos", "kill@9,corrupt@2,stall@5:0.3",
         "--chaos-state", str(tmp_path / "cs"), "--",
         sys.executable, "train_lm.py", *LM_BASE,
         "--save-dir", str(tmp_path / "ck"), "--auto-resume",
         "--log-file", str(log)],
        capture_output=True, text=True, cwd=ROOT, env=env, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr

    # (a) trajectory-preserving recovery: exact final-loss match
    assert _final_loss(log, 13) == _final_loss(oracle_log, 13)
    # (b) the corrupted checkpoint was quarantined, never restored
    corrupt = [p.name for p in (tmp_path / "ck").iterdir()
               if ".corrupt" in p.name]
    assert corrupt, "corruption fault fired but nothing was quarantined"
    resumed = [l for l in r.stdout.splitlines() if "resumed from" in l]
    assert resumed and not any(".corrupt" in l for l in resumed)
    # every fault in the plan fired exactly once, stamped schema-v5
    from shallowspeed_tpu.telemetry.schema import validate_file

    assert validate_file(log) == []
    recs = [json.loads(l) for l in log.read_text().splitlines()]
    fault_kinds = sorted(r_["kind"] for r_ in recs
                         if r_.get("event") == "fault")
    assert fault_kinds == ["corrupt", "kill", "stall"]
    # (c) goodput: the run decomposes with MTTR per class
    from shallowspeed_tpu.telemetry.goodput import run_goodput

    rep = run_goodput(log)
    assert rep["counts"]["restarts"] >= 1
    assert rep["mttr"].get("crash", {}).get("count", 0) >= 1
    assert rep["faults"] == {"corrupt": 1, "kill": 1, "stall": 1}
    assert rep["losses"].get("data_stall", 0) > 0  # the stall was named
    assert rep["accounted_frac"] >= 0.95, rep


# -------------------------------------- full acceptance suite (slow)


@pytest.mark.slow
def test_chaos_acceptance_multi_fault_plan(tmp_path):
    """ISSUE-7 acceptance: under a seeded plan injecting kill-mid-save,
    post-hoc corruption, a NaN storm, a data stall, and a heartbeat
    freeze (hang), the supervised run completes training with the
    fault-free oracle's final loss at the same step count, restores
    only verified checkpoints, and `--goodput` attributes >= 95% of
    wall clock with per-fault-class MTTR reported."""
    base = ["--platform", "cpu", "--seq-len", "32", "--d-model", "32",
            "--n-layers", "1", "--batch-size", "4", "--steps", "24",
            "--log-every", "2", "--prefetch", "0", "--save-every", "4",
            "--health", "monitor"]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    oracle_log = tmp_path / "oracle.jsonl"
    r = subprocess.run(
        [sys.executable, "train_lm.py", *base,
         "--save-dir", str(tmp_path / "oracle_ck"),
         "--log-file", str(oracle_log)],
        capture_output=True, text=True, cwd=ROOT, env=env, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr

    # stall@4 exercises ledger accounting; kill_in_save@3 dies inside
    # save #3's write window; corrupt@4 poisons a later checkpoint
    # post-hoc; nan@13 poisons a param leaf (numeric storm -> labeled
    # exit -> restart); freeze@17 stops heartbeats and stall@19:45
    # wedges the loader long enough for the supervisor's staleness
    # clock to hang-kill the child (SIGTERM-first, so the ledger tail
    # survives). The 30 s hang timeout must exceed worst-case jax
    # child startup on a loaded host (a slow spawn must not be
    # mistaken for a hang), and the stall must exceed the timeout.
    log = tmp_path / "chaos.jsonl"
    r = subprocess.run(
        [sys.executable, "-m", "shallowspeed_tpu.elastic",
         "--max-restarts", "6", "--backoff", "0.3",
         "--hang-timeout", "30", "--term-grace", "5",
         "--chaos",
         "stall@4:0.4,kill_in_save@3,corrupt@4,nan@13,freeze@17,"
         "stall@19:45",
         "--chaos-state", str(tmp_path / "cs"), "--",
         sys.executable, "train_lm.py", *base,
         "--save-dir", str(tmp_path / "ck"), "--auto-resume",
         "--log-file", str(log)],
        capture_output=True, text=True, cwd=ROOT, env=env, timeout=900)
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]

    # completes all steps at the oracle's exact trajectory
    assert _final_loss(log, 23) == _final_loss(oracle_log, 23)
    # zero unverified restores: every 'resumed from' target was the
    # verified survivor, never the corrupted path the fault stamped
    recs = [json.loads(l) for l in log.read_text().splitlines()]
    faults = [r_ for r_ in recs if r_.get("event") == "fault"]
    corrupted = [r_["path"] for r_ in faults
                 if r_["kind"] == "corrupt"]
    # on failure, show what DID fire plus the supervisor log tail —
    # a timing flake must name itself, not just count to zero
    assert len(corrupted) == 1, (faults, r.stdout[-3000:])
    corrupt_dir = str(Path(corrupted[0]).parent)
    resumed = [l for l in r.stdout.splitlines() if "resumed from" in l]
    assert resumed
    assert not any(corrupt_dir + " " in l or l.endswith(corrupt_dir)
                   for l in resumed), (corrupt_dir, resumed)
    # every planned fault kind fired
    fault_kinds = {r_["kind"] for r_ in faults}
    assert fault_kinds == {"stall", "kill_in_save", "corrupt", "nan",
                           "freeze"}, (faults, r.stdout[-3000:])
    # the supervisor saw multiple failure classes; the ledger carries
    # per-class MTTR and >= 95% of wall clock has a name
    from shallowspeed_tpu.telemetry.goodput import run_goodput

    rep = run_goodput(log)
    assert rep["counts"]["restarts"] >= 3
    assert "crash" in rep["mttr"] and "hang" in rep["mttr"], rep["mttr"]
    assert rep["accounted_frac"] >= 0.95, rep
    assert rep["availability"] is not None
    from shallowspeed_tpu.telemetry.schema import validate_file

    assert validate_file(log) == []
