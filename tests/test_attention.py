"""Ring attention correctness: the sequence-sharded ring must match full
attention (forward AND gradients) on a real multi-device mesh — the test
strategy the reference applies to its parallelism (equivalence against the
serial run, `scripts/DDP_PyTorch_MNIST.py:159-167`) applied to context
parallelism.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

# the version-compat wrapper (check_rep=False on pre-VMA jax): the
# legacy replication rewriter has no rule for pallas_call, so the
# flash-substrate cases below would otherwise raise NotImplementedError
# — the engines run all their shard_maps through this same wrapper
from shallowspeed_tpu.utils import shard_map

from shallowspeed_tpu.ops.attention import (attention, ring_attention,
                                            ulysses_attention)

B, T, H, D = 2, 32, 4, 16


def naive_attention(q, k, v, causal):
    """O(T^2) numpy reference, independent of the jnp implementation."""
    b, t, h, d = q.shape
    out = np.zeros_like(q, dtype=np.float64)
    for bi in range(b):
        for hi in range(h):
            s = (q[bi, :, hi].astype(np.float64)
                 @ k[bi, :, hi].astype(np.float64).T) / np.sqrt(d)
            if causal:
                s = np.where(np.tril(np.ones((t, t), bool)), s, -np.inf)
            p = np.exp(s - s.max(axis=-1, keepdims=True))
            p /= p.sum(axis=-1, keepdims=True)
            out[bi, :, hi] = p @ v[bi, :, hi].astype(np.float64)
    return out.astype(q.dtype)


@pytest.fixture
def qkv():
    rng = np.random.default_rng(7)
    mk = lambda: rng.normal(size=(B, T, H, D)).astype(np.float32)
    return mk(), mk(), mk()


def ring_on_mesh(q, k, v, sp, causal):
    mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))
    spec = P(None, "sp")
    fn = shard_map(
        partial(ring_attention, axis_name="sp", causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return np.asarray(jax.jit(fn)(q, k, v))


@pytest.mark.parametrize("causal", [True, False])
def test_full_attention_matches_naive(qkv, causal):
    q, k, v = qkv
    got = np.asarray(attention(q, k, v, causal=causal))
    np.testing.assert_allclose(got, naive_attention(q, k, v, causal),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("sp", [1, 2, 4, 8])
@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_full(qkv, sp, causal):
    q, k, v = qkv
    want = np.asarray(attention(q, k, v, causal=causal))
    got = ring_on_mesh(q, k, v, sp, causal)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_ring_gradients_match_full(qkv):
    """jax.grad straight through the ring (scan + ppermute) must equal the
    full-attention gradient — the property context-parallel training rests on."""
    q, k, v = qkv
    sp = 4
    mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))
    spec = P(None, "sp")

    def full_loss(q, k, v):
        return (attention(q, k, v, causal=True) ** 2).sum()

    @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
             out_specs=P())
    def ring_loss(q, k, v):
        o = ring_attention(q, k, v, axis_name="sp", causal=True)
        return jax.lax.psum((o.astype(jnp.float32) ** 2).sum(), "sp")

    g_full = jax.grad(full_loss, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    for gf, gr in zip(g_full, g_ring):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=5e-4, atol=5e-5)


def ulysses_on_mesh(q, k, v, sp, causal, use_flash=False):
    mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))
    spec = P(None, "sp")
    fn = shard_map(
        partial(ulysses_attention, axis_name="sp", causal=causal,
                use_flash=use_flash),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return np.asarray(jax.jit(fn)(q, k, v))


@pytest.mark.parametrize("sp", [1, 2, 4])
@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_full(qkv, sp, causal):
    """All-to-all sequence parallelism must equal full attention (H=4, so
    sp in {1,2,4} covers heads-per-device in {4,2,1})."""
    q, k, v = qkv
    want = np.asarray(attention(q, k, v, causal=causal))
    got = ulysses_on_mesh(q, k, v, sp, causal)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_ulysses_gradients_match_full(qkv):
    """jax.grad straight through the two all-to-alls must equal the
    full-attention gradient."""
    q, k, v = qkv
    sp = 4
    mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))
    spec = P(None, "sp")

    def full_loss(q, k, v):
        return (attention(q, k, v, causal=True) ** 2).sum()

    @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
             out_specs=P())
    def uly_loss(q, k, v):
        o = ulysses_attention(q, k, v, axis_name="sp", causal=True)
        return jax.lax.psum((o.astype(jnp.float32) ** 2).sum(), "sp")

    g_full = jax.grad(full_loss, argnums=(0, 1, 2))(q, k, v)
    g_uly = jax.jit(jax.grad(uly_loss, argnums=(0, 1, 2)))(q, k, v)
    for gf, gu in zip(g_full, g_uly):
        np.testing.assert_allclose(np.asarray(gu), np.asarray(gf),
                                   rtol=5e-4, atol=5e-5)


def test_ulysses_rejects_indivisible_heads(qkv):
    """H=4 over sp=8 cannot shard heads; the op must refuse loudly."""
    q, k, v = qkv
    with pytest.raises(Exception, match="divisible"):
        ulysses_on_mesh(q, k, v, sp=8, causal=True)


def test_ring_long_sequence_small_blocks():
    """Long-context shape: T >> block size; every device holds T/sp tokens."""
    rng = np.random.default_rng(3)
    t = 256
    q, k, v = (rng.normal(size=(1, t, 2, 8)).astype(np.float32)
               for _ in range(3))
    want = np.asarray(attention(q, k, v, causal=True))
    got = ring_on_mesh(q, k, v, sp=8, causal=True)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("sp", [1, 2, 4])
def test_ulysses_flash_matches_full(qkv, sp):
    """All-to-all sequence parallelism with the Pallas flash kernel as the
    local attention must equal plain full attention."""
    q, k, v = qkv
    want = np.asarray(attention(q, k, v, causal=True))
    got = ulysses_on_mesh(q, k, v, sp, True, use_flash=True)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_ulysses_flash_gradients_match_full(qkv):
    """The flash kernel's custom VJP composes with the all-to-all
    transposes: gradients must equal the full-attention gradients."""
    q, k, v = qkv
    sp = 4
    mesh = Mesh(np.array(jax.devices()[:sp]), ("sp",))
    spec = P(None, "sp")

    def full_loss(q, k, v):
        return (attention(q, k, v, causal=True) ** 2).sum()

    @partial(shard_map, mesh=mesh, in_specs=(spec, spec, spec),
             out_specs=P())
    def uf_loss(q, k, v):
        o = ulysses_attention(q, k, v, axis_name="sp", causal=True,
                              use_flash=True)
        return jax.lax.psum((o.astype(jnp.float32) ** 2).sum(), "sp")

    want = jax.grad(full_loss, argnums=(0, 1, 2))(q, k, v)
    got = jax.grad(uf_loss, argnums=(0, 1, 2))(q, k, v)
    for w, g in zip(want, got):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=5e-4, atol=5e-5)
