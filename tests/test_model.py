"""L2 model tests — ports the reference's serial partitioning tests
(`/root/reference/tests/test_layers.py`) and strengthens them with
full-model-vs-jax.grad and partitioned-vs-unpartitioned equivalence checks
(possible because init is deterministic and dims-keyed, `layers.py:104-112`).
"""

import jax
import jax.numpy as jnp
import numpy as np

from shallowspeed_tpu.models.mlp import (
    MLPStage,
    accumulate_grads,
    init_stage_params,
    stage_layer_sizes,
    zero_grads_like,
)
from shallowspeed_tpu.ops import functional as F

SIZES = [784, 128, 127, 126, 125, 124, 123, 10]  # reference `train.py:98`
RNG = np.random.default_rng(1)


def rand(*shape):
    return jnp.asarray(RNG.normal(size=shape).astype(np.float32))


def onehot_batch(n, classes=10):
    t = np.zeros((n, classes), np.float32)
    t[np.arange(n), RNG.integers(0, classes, n)] = 1.0
    return jnp.asarray(t)


# ------------------------------------------------------- partitioning


def test_stage_layer_sizes_overlap():
    # 8 sizes over 4 stages -> stage_size 2, one-dim overlap
    # (reference `layers.py:242-250`).
    assert stage_layer_sizes(SIZES, 0, 4) == [784, 128, 127]
    assert stage_layer_sizes(SIZES, 1, 4) == [127, 126, 125]
    assert stage_layer_sizes(SIZES, 2, 4) == [125, 124, 123]
    assert stage_layer_sizes(SIZES, 3, 4) == [123, 10]


def test_stage_structure_first_last():
    # Mirrors `test_layers.py:52-70`: layer counts and in/out dims per stage.
    first = MLPStage(SIZES, 0, 4, batch_size=128)
    last = MLPStage(SIZES, 3, 4, batch_size=128)
    assert first.n_linears == 2 and last.n_linears == 1
    assert first.in_dim == 784 and first.out_dim == 127
    assert last.in_dim == 123 and last.out_dim == 10
    assert not first.is_last_stage and last.is_last_stage


def test_init_deterministic_and_partition_independent():
    # Same dims -> same weights, regardless of partitioning
    # (`layers.py:104-106` "same initial weights no matter if distributed").
    whole = init_stage_params(SIZES, 0, 1)
    parts = [init_stage_params(SIZES, s, 4) for s in range(4)]
    flat = [layer for p in parts for layer in p]
    assert len(whole) == len(flat) == 7
    for a, b in zip(whole, flat):
        np.testing.assert_array_equal(a["W"], b["W"])
        np.testing.assert_array_equal(a["b"], b["b"])
    w = np.asarray(whole[0]["W"])
    assert w.dtype == np.float32
    assert abs(w.std() - 1 / np.sqrt(784)) < 0.005  # scaled-normal init


# ------------------------------------------------------- forward/backward


def test_forward_shapes_and_softmax_head():
    stage = MLPStage(SIZES, 0, 1, batch_size=32)
    params = stage.init()
    x = rand(32, 784)
    out, stash = stage.forward(params, x)
    assert out.shape == (32, 10)
    np.testing.assert_allclose(out.sum(axis=1), np.ones(32), atol=1e-5)
    assert len(stash) == 7 + 1  # 7 linears + softmax/loss head


def test_backward_accumulation_and_zero():
    # Grad accumulation across microbatches + zero_grad
    # (`test_layers.py:7-49`, `layers.py:135-136,59-61`).
    stage = MLPStage(SIZES, 0, 1, batch_size=8)
    params = stage.init()
    acc = zero_grads_like(params)
    for mu in range(2):
        x, t = rand(4, 784), onehot_batch(4)
        _, stash = stage.forward(params, x)
        _, grads = stage.backward(params, stash, t)
        acc = accumulate_grads(acc, grads)
    for layer in acc:
        assert float(jnp.abs(layer["W"]).sum()) > 0
        assert layer["W"].dtype == jnp.float32
    zeroed = zero_grads_like(acc)
    for layer in zeroed:
        assert float(jnp.abs(layer["W"]).sum()) == 0


def test_manual_backward_matches_jax_grad():
    """The hand-written stage backward equals jax.grad of the loss — the
    strongest possible autograd contract (not present in the reference)."""
    stage = MLPStage(SIZES, 0, 1, batch_size=16)
    params = stage.init()
    x, t = rand(16, 784), onehot_batch(16)

    _, stash = stage.forward(params, x)
    _, manual = stage.backward(params, stash, t)

    auto = jax.grad(lambda p: stage.loss(p, x, t))(params)
    for m, a in zip(manual, auto):
        np.testing.assert_allclose(m["W"], a["W"], rtol=2e-3, atol=2e-6)
        np.testing.assert_allclose(m["b"], a["b"], rtol=2e-3, atol=2e-6)


def test_pipelined_stages_equal_monolithic():
    """Chaining 4 stage forwards/backwards == the 1-stage model, exactly.
    This is the parallelism-equivalence property the deterministic init is
    load-bearing for (SURVEY §2 row 11)."""
    bs = 8
    mono = MLPStage(SIZES, 0, 1, batch_size=bs)
    mono_p = mono.init()
    stages = [MLPStage(SIZES, s, 4, batch_size=bs) for s in range(4)]
    stage_ps = [s.init() for s in stages]

    x, t = rand(bs, 784), onehot_batch(bs)

    mono_out, mono_stash = mono.forward(mono_p, x)
    h = x
    stashes = []
    for s, p in zip(stages, stage_ps):
        h, st = s.forward(p, h)
        stashes.append(st)
    np.testing.assert_allclose(h, mono_out, rtol=1e-6)

    _, mono_grads = mono.backward(mono_p, mono_stash, t)
    dout = t
    pp_grads = []
    for s, p, st in zip(reversed(stages), reversed(stage_ps), reversed(stashes)):
        dout, g = s.backward(p, st, dout)
        pp_grads = g + pp_grads
    for m, g in zip(mono_grads, pp_grads):
        np.testing.assert_allclose(m["W"], g["W"], rtol=1e-5, atol=1e-7)


def test_infer_mode_no_stash_needed():
    stage = MLPStage(SIZES, 0, 1, batch_size=4)
    out = stage.infer(stage.init(), rand(4, 784))
    assert out.shape == (4, 10)


def test_stage_fns_jit():
    stage = MLPStage(SIZES, 3, 4, batch_size=8)
    params = stage.init()
    x, t = rand(8, 123), onehot_batch(8)
    fwd = jax.jit(stage.forward)
    out, stash = fwd(params, x)
    bwd = jax.jit(stage.backward)
    dx, grads = bwd(params, stash, t)
    ref_out, ref_stash = stage.forward(params, x)
    ref_dx, _ = stage.backward(params, ref_stash, t)
    np.testing.assert_allclose(out, ref_out, rtol=1e-6)
    np.testing.assert_allclose(dx, ref_dx, rtol=1e-5, atol=1e-7)
