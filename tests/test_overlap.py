"""Comm/compute interleaving (`shallowspeed_tpu/parallel/overlap.py`).

Four layers of pinning:

- **Bucket plans**: every leaf in exactly one bucket, bucket payloads
  at most the target (single oversized leaves excepted) — pure-function
  unit tests.
- **Oracle parity**: the bucketed/overlapped reduction must train
  bit-for-bit-close to the bulk-psum oracle on every engine family —
  fused dp, dp x pp SPMD pipeline (both hop modes), FSDP, and the
  context engine (dense / zero1 / zero2, with gradient accumulation so
  the peeled-microbatch path runs).
- **Program shape**: one executable per entrypoint (no new entrypoints,
  no recompiles), and the dataflow exposure (`collective_exposure`)
  strictly lower with overlap on than with the bulk reduction — the
  acceptance measure telemetry stamps on step lines as
  `exposed_comm_frac` (schema v3).
- **Health interaction**: the spec-driven health pack (PR 3) stays
  oracle-correct when grads arrive pre-reduced per bucket.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from shallowspeed_tpu.engine import FusedDPEngine
from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.models.mlp import MLPStage
from shallowspeed_tpu.optim import SGD, Adam
from shallowspeed_tpu.parallel.context import ContextParallelEngine
from shallowspeed_tpu.parallel.fsdp import FSDPEngine
from shallowspeed_tpu.parallel.mesh import make_mesh
from shallowspeed_tpu.parallel.overlap import (OverlapConfig,
                                               bucket_signature,
                                               collective_exposure,
                                               from_flags, leaf_bytes,
                                               plan_buckets,
                                               plan_param_buckets,
                                               registered)

TOL = 2e-5  # worst-leaf relmax vs the bulk oracle (float reassociation)


def relmax(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    out = 0.0
    for x, y in zip(la, lb):
        x = np.asarray(x, np.float32)
        y = np.asarray(y, np.float32)
        out = max(out, float(np.abs(x - y).max()
                             / max(1e-8, float(np.abs(y).max()))))
    return out


def sds_of(tree):
    return jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(np.shape(l), np.asarray(l).dtype)
        if not hasattr(l, "dtype")
        else jax.ShapeDtypeStruct(l.shape, l.dtype), tree)


# -------------------------------------------------------- bucket plans


def test_plan_every_leaf_in_exactly_one_bucket():
    leaves = [np.zeros(s, np.float32) for s in
              [(64, 64), (64,), (128, 32), (8,), (1000,), (3,)]]
    plan = plan_buckets(leaves, bucket_bytes=8 << 10)
    seen = [i for b in plan for i in b]
    assert sorted(seen) == list(range(len(leaves)))
    assert all(len(set(b)) == len(b) for b in plan)


def test_plan_respects_byte_target():
    leaves = [np.zeros((50,), np.float32) for _ in range(20)]  # 200 B each
    plan = plan_buckets(leaves, bucket_bytes=1000)
    for b in plan:
        assert sum(leaf_bytes(leaves[i]) for i in b) <= 1000
    assert len(plan) == 4  # 5 x 200 B per bucket


def test_plan_oversized_leaf_gets_own_bucket():
    leaves = [np.zeros((10,), np.float32),
              np.zeros((10_000,), np.float32),
              np.zeros((10,), np.float32)]
    plan = plan_buckets(leaves, bucket_bytes=1000)
    assert [len(b) for b in plan] == [1, 1, 1]


def test_plan_preserves_given_order():
    leaves = [np.zeros((100,), np.float32) for _ in range(4)]
    plan = plan_buckets(leaves, bucket_bytes=800)  # 2 leaves per bucket
    assert plan == [[0, 1], [2, 3]]


def test_param_plan_is_backward_finalization_ordered():
    params = {"a": np.zeros((100,), np.float32),
              "b": np.zeros((100,), np.float32),
              "c": np.zeros((100,), np.float32)}
    plan, leaves, _ = plan_param_buckets(params, bucket_bytes=800)
    # reversed flatten order, contiguous: the LAST leaves bucket first
    assert plan[0] == [2, 1] and plan[-1] == [0]
    assert len(leaves) == 3


def test_from_flags():
    assert from_flags("off", 4.0) is None
    cfg = from_flags("on", 2.0)
    assert cfg.bucket_mb == 2.0 and cfg.bucket_bytes == 2 << 20


# --------------------------------------------------- fused dp engine

SIZES = [784, 128, 127, 126, 125, 124, 123, 10]


class _DS:
    def __init__(self, seed, n_mu, mubs, d_in=784, d_out=10):
        self.seed, self.n_mu, self.mubs = seed, n_mu, mubs
        self.d_in, self.d_out = d_in, d_out

    def load_mubatch_stack(self, batch_id):
        rng = np.random.default_rng([self.seed, batch_id])
        x = rng.standard_normal(
            (self.n_mu, self.mubs, self.d_in)).astype(np.float32)
        y = np.eye(self.d_out, dtype=np.float32)[
            rng.integers(0, self.d_out, (self.n_mu, self.mubs))]
        return x, y


def fused_pair(n_mu=4, dp=2, health="off"):
    gbs = 32
    mubs = gbs // dp // n_mu

    def build(ov):
        return FusedDPEngine(MLPStage(SIZES, 0, 1, batch_size=gbs),
                             SGD(0.1), make_mesh(dp, 1), health=health,
                             overlap=ov)

    ds = [_DS(r, n_mu, mubs) for r in range(dp)]
    return build(None), build(OverlapConfig(bucket_mb=0.25)), ds


def test_fused_dp_overlap_matches_bulk_oracle():
    e_off, e_on, ds = fused_pair()
    for b in range(3):
        e_off.train_batch(b, ds)
        e_on.train_batch(b, ds)
    assert relmax(e_on.params, e_off.params) <= TOL


def test_fused_dp_overlap_single_microbatch():
    # n_mu=1: the peeled microbatch IS the whole batch (empty scan head)
    e_off, e_on, ds = fused_pair(n_mu=1)
    for b in range(2):
        e_off.train_batch(b, ds)
        e_on.train_batch(b, ds)
    assert relmax(e_on.params, e_off.params) <= TOL


def test_fused_dp_compile_count_pinned():
    _, e_on, ds = fused_pair()
    for b in range(3):
        e_on.train_batch(b, ds)
    assert e_on._step._cache_size() == 1  # no recompiles, no extra eps


def test_fused_dp_exposure_strictly_lower_with_overlap():
    e_off, e_on, ds = fused_pair()
    e_off.train_batch(0, ds)
    e_on.train_batch(0, ds)
    dp, n_mu, mubs = 2, 4, 4
    xs = jax.ShapeDtypeStruct((dp, n_mu, mubs, 784), np.float32)
    ys = jax.ShapeDtypeStruct((dp, n_mu, mubs, 10), np.float32)

    def exposure(e):
        closed = jax.make_jaxpr(e._step)(
            sds_of(e.params), sds_of(e.opt_state), xs, ys)
        return collective_exposure(closed, axes=("dp",))

    off, on = exposure(e_off), exposure(e_on)
    assert off["exposed_comm_frac"] == 1.0  # post-scan bulk: a barrier
    assert on["exposed_comm_frac"] < off["exposed_comm_frac"]
    # equal wire bytes: bucketing moves the reduction, it does not
    # duplicate it
    assert on["total_bytes"] == off["total_bytes"]
    assert on["n_collectives"] < off["n_collectives"]  # per-bucket binds


def test_fused_dp_overlap_registered():
    _, e_on, _ = fused_pair()
    info = registered(e_on._step)
    assert info is not None and info["axis"] == "dp"
    assert len(info["buckets"]) >= 2  # 0.25 MiB buckets over ~0.9 MiB
    total = sum(len(b) for b in info["buckets"])
    assert total == 2 * (len(SIZES) - 1)  # every W and b leaf covered


def test_fused_dp_run_fusion_with_overlap():
    e_off, e_on, ds = fused_pair()
    staged_off = e_off.stage_epoch(ds, 3)
    staged_on = e_on.stage_epoch(ds, 3)
    e_off.train_run(staged_off, 2)
    e_on.train_run(staged_on, 2)
    assert relmax(e_on.params, e_off.params) <= TOL


# ----------------------------------------------- spmd pipeline engine


def spmd_pair(double_buffer, dp=2, pp=2):
    from shallowspeed_tpu.parallel.spmd_pipeline import SPMDPipelineEngine

    sizes = [12, 14, 13, 10]
    gbs, n_mu = 16, 2
    mubs = gbs // dp // n_mu

    def build(ov):
        return SPMDPipelineEngine(sizes, SGD(0.1), make_mesh(dp, pp),
                                  n_mu, mubs, gbs, overlap=ov)

    ds = [_DS(r, n_mu, mubs, sizes[0], sizes[-1]) for r in range(dp)]
    return (build(None),
            build(OverlapConfig(bucket_mb=0.001,
                                double_buffer_hops=double_buffer)), ds)


@pytest.mark.parametrize("double_buffer", [False, True])
def test_spmd_pipeline_overlap_matches_bulk_oracle(double_buffer):
    e_off, e_on, ds = spmd_pair(double_buffer)
    for b in range(3):
        e_off.train_batch(b, ds)
        e_on.train_batch(b, ds)
    assert relmax(e_on.params, e_off.params) <= TOL
    assert e_on._step_fn._cache_size() == 1
    # inference unaffected by the hop restructure
    x = np.random.default_rng(0).standard_normal((8, 12)).astype(np.float32)
    assert relmax(e_on.infer(x), e_off.infer(x)) <= TOL


def test_spmd_pipeline_epoch_fusion_with_overlap():
    e_off, e_on, ds = spmd_pair(True)
    e_off.train_epoch(e_off.stage_epoch(ds, 3))
    e_on.train_epoch(e_on.stage_epoch(ds, 3))
    assert relmax(e_on.params, e_off.params) <= TOL


def test_spmd_pipeline_exposure_and_schedule_info():
    e_off, e_on, _ = spmd_pair(True)
    assert e_on.schedule_info()["hop_double_buffer"] is True
    assert e_off.schedule_info()["hop_double_buffer"] is False
    wmax = 14
    xs = jax.ShapeDtypeStruct((2, 2, 4, wmax), np.float32)
    ys = jax.ShapeDtypeStruct((2, 2, 4, 10), np.float32)

    def exposure(e):
        closed = jax.make_jaxpr(e._step_fn)(
            sds_of(e.params), sds_of(e.opt_state), xs, ys)
        return collective_exposure(closed, axes=("dp",))

    off, on = exposure(e_off), exposure(e_on)
    assert on["exposed_comm_frac"] < off["exposed_comm_frac"] == 1.0


# -------------------------------------------------- context engine

CFG = T.TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                          max_seq=32)


def lm_batch(seed, b=8, t=32):
    rng = np.random.default_rng(seed)
    tok = rng.integers(0, 64, (b, t)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1).astype(np.int32)


def ctx_mesh(dp, sp=1):
    return Mesh(np.array(jax.devices()[:dp * sp]).reshape(dp, sp),
                ("dp", "sp"))


def ctx_pair(health="off", **kw):
    def build(ov):
        return ContextParallelEngine(CFG, Adam(1e-3), ctx_mesh(2),
                                     health=health, overlap=ov, **kw)

    return build(None), build(OverlapConfig(bucket_mb=0.02))


@pytest.mark.parametrize("kw", [dict(accum=2), dict(zero1=True, accum=2),
                                dict(zero2=True, accum=2)],
                         ids=["dense", "zero1", "zero2"])
def test_context_overlap_matches_bulk_oracle(kw):
    e_off, e_on = ctx_pair(**kw)
    for s in range(3):
        tok, tgt = lm_batch(s)
        l_off = e_off.train_batch(tok, tgt)
        l_on = e_on.train_batch(tok, tgt)
    assert abs(l_on - l_off) <= TOL * max(1.0, abs(l_off))
    assert relmax(e_on.get_canonical_params(),
                  e_off.get_canonical_params()) <= TOL
    fn = e_on._step_fn or e_on._loss_grads_fn
    assert fn._cache_size() == 1


def test_context_overlap_accum_exposure_strictly_lower():
    e_off, e_on = ctx_pair(accum=2)
    tok, tgt = lm_batch(0)

    def exposure(e):
        args = (e.params, e.opt_state, e._place(tok), e._place(tgt),
                np.uint32(0))
        closed = jax.make_jaxpr(e._step_fn)(*sds_of(args))
        return collective_exposure(closed, axes=("dp",))

    off, on = exposure(e_off), exposure(e_on)
    # the accumulation scan is one dataflow node: every bulk psum after
    # it is a barrier; the peeled+tagged program reduces in-backward
    assert off["exposed_comm_frac"] == 1.0
    assert on["exposed_comm_frac"] < off["exposed_comm_frac"]
    assert on["total_bytes"] == off["total_bytes"]


def test_context_zero2_overlap_keeps_grad_sharding():
    # the scatter tags must hand the sharded update the SAME 1/dp
    # grad layout as the bulk reduce-scatter path
    e_off, e_on = ctx_pair(zero2=True, accum=2)
    for e in (e_off, e_on):
        tok, tgt = lm_batch(0)
        e.train_batch(tok, tgt)
    for a, b in zip(jax.tree_util.tree_leaves(e_on.opt_state),
                    jax.tree_util.tree_leaves(e_off.opt_state)):
        assert getattr(a, "sharding", None) == getattr(b, "sharding",
                                                       None)


# ------------------------------------------------------ fsdp engine


def fsdp_pair(health="off"):
    def build(ov):
        return FSDPEngine(CFG, Adam(1e-3),
                          Mesh(np.array(jax.devices()[:4]), ("dp",)),
                          health=health, overlap=ov)

    return build(None), build(OverlapConfig(bucket_mb=0.01))


def test_fsdp_overlap_matches_gspmd_oracle():
    e_off, e_on = fsdp_pair()
    for s in range(3):
        tok, tgt = lm_batch(s)
        l_off = e_off.train_batch(tok, tgt)
        l_on = e_on.train_batch(tok, tgt)
    assert abs(l_on - l_off) <= TOL * max(1.0, abs(l_off))
    assert relmax(jax.device_get(e_on.params),
                  jax.device_get(e_off.params)) <= TOL
    assert e_on._step_fn._cache_size() == 1


def test_fsdp_overlap_preserves_placements():
    e_off, e_on = fsdp_pair()
    tok, tgt = lm_batch(0)
    e_on.train_batch(tok, tgt)
    for a, b in zip(jax.tree_util.tree_leaves(e_on.params),
                    jax.tree_util.tree_leaves(e_off.params)):
        assert a.sharding == b.sharding


def test_fsdp_overlap_gathers_and_scatters_in_program():
    _, e_on = fsdp_pair()
    tok = jax.ShapeDtypeStruct((8, 32), np.int32)
    closed = jax.make_jaxpr(e_on._step_fn)(
        sds_of(e_on.params), sds_of(e_on.opt_state), tok, tok,
        jax.ShapeDtypeStruct((), np.uint32))
    expo = collective_exposure(closed, axes=("dp",))
    # explicit collectives exist (the GSPMD step has none at jaxpr
    # level) and nearly all of them have independent compute to hide
    # under — gather of layer i+1 under layer i, scatter of layer i
    # under the backward of layer i-1
    assert expo["n_collectives"] > 10
    assert expo["n_overlapped"] >= 0.8 * expo["n_collectives"]


def test_fsdp_overlap_rejects_adafactor():
    from shallowspeed_tpu.optim import Adafactor

    with pytest.raises(ValueError, match="Adafactor"):
        FSDPEngine(CFG, Adafactor(1e-3),
                   Mesh(np.array(jax.devices()[:4]), ("dp",)),
                   overlap=OverlapConfig())


def test_gspmd_engines_reject_explicit_overlap():
    from shallowspeed_tpu.parallel.tensor import TensorParallelEngine

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))
    with pytest.raises(ValueError, match="GSPMD-partitioned"):
        TensorParallelEngine(CFG, Adam(1e-3), mesh,
                             overlap=OverlapConfig())


# ------------------------------------------------- health interaction


def test_health_pack_oracle_correct_with_bucketed_grads():
    """PR-3 satellite pin: the spec-driven health reductions stay
    oracle-correct when grads arrive pre-reduced per bucket instead of
    via the bulk psum."""
    e_off, e_on = ctx_pair(health="monitor", zero2=True, accum=2)
    tok, tgt = lm_batch(0)
    e_off.train_batch(tok, tgt)
    e_on.train_batch(tok, tgt)
    h_off, h_on = e_off.health_snapshot(), e_on.health_snapshot()
    for k in ("grad_norm", "param_norm", "update_ratio"):
        assert abs(h_on[k] - h_off[k]) <= 1e-4 * max(1.0, abs(h_off[k]))
    assert h_on["nonfinite"] == h_off["nonfinite"] == 0


def test_health_guard_skips_identically_with_overlap():
    # a poisoned batch must skip bit-identically whether the nonfinite
    # sentinel saw bulk-reduced or bucket-reduced grads
    gbs, n_mu, dp = 32, 4, 2
    mubs = gbs // dp // n_mu
    eng = FusedDPEngine(MLPStage(SIZES, 0, 1, batch_size=gbs),
                        SGD(0.1), make_mesh(dp, 1), health="guard",
                        overlap=OverlapConfig(bucket_mb=0.25))
    ds = [_DS(r, n_mu, mubs) for r in range(dp)]
    eng.train_batch(0, ds)
    before = jax.device_get(eng.params)

    class _PoisonDS(_DS):
        def load_mubatch_stack(self, batch_id):
            x, y = super().load_mubatch_stack(batch_id)
            x[0, 0, 0] = np.nan
            return x, y

    eng.train_batch(1, [_PoisonDS(r, n_mu, mubs) for r in range(dp)])
    after = jax.device_get(eng.params)
    for a, b in zip(jax.tree_util.tree_leaves(before),
                    jax.tree_util.tree_leaves(after)):
        np.testing.assert_array_equal(a, b)
    assert eng.health_snapshot()["skipped_total"] == 1


def test_fsdp_health_pack_with_overlap():
    e_off, e_on = fsdp_pair(health="monitor")
    tok, tgt = lm_batch(0)
    e_off.train_batch(tok, tgt)
    e_on.train_batch(tok, tgt)
    h_off, h_on = e_off.health_snapshot(), e_on.health_snapshot()
    for k in ("grad_norm", "param_norm"):
        assert abs(h_on[k] - h_off[k]) <= 1e-4 * max(1.0, abs(h_off[k]))


# -------------------------------------------------- telemetry surface


def test_step_lines_carry_exposed_comm_frac():
    from shallowspeed_tpu import telemetry as tele

    e_off, e_on, ds = fused_pair()
    tracer = tele.configure(level="steps")
    try:
        telem_on = tele.RunTelemetry(e_on, tracer)
        telem_off = tele.RunTelemetry(e_off, tracer)
        e_on.train_batch(0, ds)
        e_off.train_batch(0, ds)
        f_on = telem_on.step_fields()
        f_off = telem_off.step_fields()
    finally:
        tele.configure(level="off")
    assert f_on["overlap"] is True and f_off["overlap"] is False
    assert f_on["exposed_comm_frac"] < f_off["exposed_comm_frac"]
    assert f_on["overlap_ratio"] > f_off["overlap_ratio"]


def test_schema_v3_accepts_old_and_new_step_lines():
    from shallowspeed_tpu.telemetry.schema import (SCHEMA_VERSION,
                                                   validate_line)

    assert SCHEMA_VERSION >= 3
    v1 = {"event": "step", "step": 1, "loss": 2.0,
          "tokens_per_sec": 10.0, "coll_gbps": 0.5}
    v2 = dict(v1, health_grad_norm=0.1, health_nonfinite=0)
    v3 = dict(v2, exposed_comm_frac=0.25, overlap_ratio=0.75,
              overlap=True)
    assert validate_line(v1) == []
    assert validate_line(v2) == []
    assert validate_line(v3) == []
    assert validate_line(dict(v3, exposed_comm_frac="high"))
    assert validate_line(dict(v3, overlap="yes"))


def test_bucket_signature_is_shape_dtype_multiset():
    a = [np.zeros((4, 4), np.float32), np.zeros((2,), np.float32)]
    assert bucket_signature(a) == bucket_signature(a[::-1])
    assert bucket_signature(a) != bucket_signature(a[:1])
